"""Driver benchmark: GPT pretraining step throughput on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} (plus an
MFU estimate and step time as extra keys) on stdout. Staged progress goes
to stderr so a watcher can tell WHERE a run is stuck:

    [bench] stage=probe attempt=1 ...
    [bench] stage=backend_up device_kind=...
    [bench] stage=compiling / compiled / measuring / done

Hardening (round 3, after a wedged tunnel blacked out round 2's signal):
  * backend availability is probed in a SUBPROCESS first, with 3
    retry attempts of growing budget (120/240/300s + backoff; worst-case
    ~11.5 min before giving up). A hung/unavailable tunnel produces a
    fail-fast JSON error record (value 0, "error" key) instead of an
    indefinite hang. Killing an init-phase probe child is safe; the
    parent never touches the TPU until a probe succeeds.
  * a watchdog thread enforces per-stage deadlines in the main process
    (backend 240s, compile 900s, measure 600s). On expiry it emits the
    JSON error record and exits, so the driver always gets a parseable
    line.
  * the baseline record stores device_kind; a different chip class next
    round is flagged ("chip_mismatch") instead of silently shifting the
    ratio.

Metric: GPT-125M-class causal-LM training tokens/sec/chip — the
single-chip proxy for BASELINE.json's "GPT tokens/sec/chip" target (the
reference publishes no absolute numbers, BASELINE.json "published": {};
vs_baseline is reported against the first recorded value of this same
benchmark, BENCH_baseline.json, 58693 tok/s from round 1).

The whole step (forward, loss, backward, AdamW update, bf16 compute with
fp32 master weights) is one donated XLA program (jit.TrainStep).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
# PADDLE_TPU_BENCH_MODEL selects the config: "gpt125m" (default, the
# driver's tracked metric) or "gpt1.3b" (north-star-scale single-chip run,
# VERDICT r3 item 5 — HBM/remat behavior differs qualitatively from 125M)
_MODEL_SEL = os.environ.get("PADDLE_TPU_BENCH_MODEL", "gpt125m")
if _MODEL_SEL not in ("gpt125m", "gpt1.3b"):
    sys.stderr.write("[bench] unknown PADDLE_TPU_BENCH_MODEL=%r "
                     "(expected gpt125m | gpt1.3b)\n" % _MODEL_SEL)
    sys.exit(2)
_METRIC = ("gpt1p3b_train_tokens_per_sec_chip" if _MODEL_SEL == "gpt1.3b"
           else "gpt125m_train_tokens_per_sec_chip")

# bf16 peak FLOP/s per chip by device_kind substring (public specs)
_PEAK = (("v5 lite", 197e12), ("v5e", 197e12), ("v6 lite", 918e12),
         ("v6e", 918e12), ("v5p", 459e12), ("v5", 459e12), ("v4", 275e12))


def _peak_flops(kind: str) -> float:
    k = kind.lower()
    for sub, peak in _PEAK:
        if sub in k:
            return peak
    return 197e12  # conservative default (v5e-class)


def _log(msg: str) -> None:
    sys.stderr.write("[bench] %s\n" % msg)
    sys.stderr.flush()


def _int_env(name: str, default: int) -> int:
    """Guarded env parse: a typo'd value must never abort the bench
    before it prints its JSON line (the driver contract)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        _log("bad %s, using default %d" % (name, default))
        return default


def _fail(stage: str, detail: str, code: int = 1) -> None:
    """Emit a parseable error record on stdout and exit immediately.

    The record stays honest (value 0) but carries the last LANDED
    measurement of this same metric when one exists in tpu_results/ —
    a wedged-tunnel round end then still points the reader at the real
    number instead of leaving only a failure marker."""
    rec = {
        "metric": _METRIC, "value": 0, "unit": "tokens/s/chip",
        "vs_baseline": 0,
        "error": "%s: %s" % (stage, detail.strip()[-400:]),
    }
    # Only the PLAIN config may claim the landed record — a variant run
    # (fused-CE / pure-bf16 / dots-remat / scan-off A/Bs, a sweep run,
    # or a pallas-required run) must not pass off the baseline config's
    # number as its own measurement (ADVICE round 5: ANY non-default
    # bench env disqualifies the failure record from carrying
    # last_landed).
    variant = bool(
        os.environ.get("PADDLE_TPU_BENCH_PURE_BF16", "0") != "0"
        or os.environ.get("PADDLE_TPU_BENCH_REMAT_POLICY", "full") != "full"
        or os.environ.get("PADDLE_TPU_BENCH_SCAN", "1") == "0"
        or os.environ.get("PADDLE_TPU_BENCH_SWEEP", "") != ""
        or os.environ.get("PADDLE_TPU_REQUIRE_PALLAS", "0") != "0"
        or (_MODEL_SEL == "gpt125m"
            and os.environ.get("PADDLE_TPU_BENCH_FUSED_CE", "0") != "0")
        or (_MODEL_SEL == "gpt1.3b"
            and os.environ.get("PADDLE_TPU_BENCH_FUSED_CE", "2048")
            != "2048"))
    landed = os.path.join(
        _HERE, "tpu_results",
        "bench_1p3b.json" if _MODEL_SEL == "gpt1.3b" else "bench_125m.json")
    try:
        with open(landed) as f:
            prev = json.load(f)
        if (not variant and isinstance(prev, dict) and prev.get("value")
                and "error" not in prev):
            rec["last_landed"] = {k: prev[k] for k in
                                  ("value", "vs_baseline", "mfu_pct",
                                   "device_kind") if k in prev}
    except (OSError, ValueError):
        pass
    sys.stdout.write(json.dumps(rec) + "\n")
    sys.stdout.flush()
    os._exit(code)


_PROBE_SRC = (
    "import jax, sys\n"
    "d = jax.devices()\n"
    "p = getattr(d[0], 'platform', '')\n"
    "if p == 'cpu':\n"  # silent CPU fallback is NOT a live accelerator
    "    sys.stderr.write('probe resolved to CPU backend, not a TPU')\n"
    "    sys.exit(3)\n"
    "sys.stdout.write(getattr(d[0], 'device_kind', 'unknown'))\n"
)


def _probe_backend() -> str:
    """Check the TPU backend is reachable from a throwaway subprocess.

    Returns device_kind. Three attempts with growing budgets (120/240/
    300s — healthy device init is seconds, but a cold tunnel's first
    contact has been observed over a minute). Killing the probe child is
    safe: it never runs a TPU step, only backend init.

    The inter-attempt backoff is the resilience layer's shared schedule
    (paddle_tpu.distributed.resilience.RetryPolicy — the same semantics
    tools/tpu_watch2.sh mirrors). resilience.py is loaded DIRECTLY off
    disk, never via `import paddle_tpu...`: a package import would run
    paddle_tpu/__init__ (jax init, multi-host formation) in the probe
    PARENT before any probe succeeded — exactly the in-process hang
    this function exists to avoid. A local fallback keeps the probe
    alive even if the module is broken (the probe must be able to
    report THAT failure too).
    """
    try:
        import importlib.util as _ilu
        spec = _ilu.spec_from_file_location(
            "_bench_resilience", os.path.join(
                _HERE, "paddle_tpu", "distributed", "resilience.py"))
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        delays = mod.RetryPolicy(max_attempts=3, base_delay=10.0,
                                 multiplier=2.0, max_delay=60.0,
                                 jitter=0.0).schedule()
    except Exception:
        delays = (10.0, 20.0)
    last = ""
    budgets = (120, 240, 300)
    for attempt, budget in enumerate(budgets, 1):
        _log("stage=probe attempt=%d budget=%ds" % (attempt, budget))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC], capture_output=True,
                text=True, timeout=budget)
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip()
            last = (r.stderr or "")[-400:] or "rc=%d" % r.returncode
        except subprocess.TimeoutExpired:
            last = "probe subprocess hung >%ds (tunnel wedged?)" % budget
        _log("stage=probe attempt=%d failed: %s" % (attempt, last[-160:]))
        if attempt < len(budgets):
            time.sleep(delays[attempt - 1])
    _fail("backend_unavailable", last)
    raise AssertionError  # unreachable


class _Watchdog:
    """Per-stage deadline enforcement; emits error JSON on expiry."""

    def __init__(self):
        self._deadline = time.monotonic() + 240
        self._stage = "backend_init"
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def stage(self, name: str, budget_s: float) -> None:
        self._stage = name
        self._deadline = time.monotonic() + budget_s
        _log("stage=%s budget=%ds" % (name, budget_s))

    def disarm(self) -> None:
        self._deadline = float("inf")

    def _run(self):
        while True:
            time.sleep(5)
            if time.monotonic() > self._deadline:
                _fail("watchdog_timeout",
                      "stage '%s' exceeded its budget" % self._stage, 4)


def main():
    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    lock = None
    if not on_cpu:
        # Single-flight: only one process may touch the tunnel at a
        # time (tools/_single_flight.py). Waiting out a long-running
        # holder is strictly cheaper than overlapping with (or killing)
        # a remote compile — overlap wedges the tunnel for hours.
        sys.path.insert(0, os.path.join(_HERE, "tools"))
        from _single_flight import BusyTimeout, maybe_acquire
        # The 125M driver metric must outlast a suite-held lock: a 1.3B
        # remote compile legitimately holds it up to 3600s (+ measure).
        # Waiting ~90 min beats reporting tpu_busy for the round.
        os.environ.setdefault("PADDLE_TPU_LOCK_WAIT", "5400")
        try:
            lock = maybe_acquire("bench:%s" % _MODEL_SEL, log=_log)
        except BusyTimeout as e:
            _fail("tpu_busy", str(e))
        # (_fail's os._exit skips maybe_acquire's atexit release: the
        # kernel drops the flock when the process's fds close, so that
        # path still releases the lock)
        lock.stage("probe")
        kind = _probe_backend()
        _log("stage=probe_ok device_kind=%s" % kind)

    dog = _Watchdog()
    if lock is not None:
        # keep the lock's stage note in sync with the watchdog stages so
        # a waiter can see where this run is without touching the tunnel
        _orig_stage = dog.stage

        def _stage(name, budget_s, _orig=_orig_stage, _lock=lock):
            _lock.stage(name)
            _orig(name, budget_s)
        dog.stage = _stage
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F  # noqa: F401 (warm import)
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown")
    _log("stage=backend_up device_kind=%s" % kind)

    # single-chip friendly config (bf16 params)
    multi_precision = True
    seq, batch = 1024, 8
    if on_cpu:  # keep the CPU smoke run quick
        seq, batch = 128, 2
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=seq,
                        recompute=_MODEL_SEL == "gpt1.3b")
    elif _MODEL_SEL == "gpt1.3b":
        # 1.3B on one v5e chip (16 GiB HBM): bf16 Adam (no f32 master —
        # master+moments alone would be 15.6 GiB) + per-block remat.
        # scan_layers stacks the 24 blocks into one lax.scan so the HLO is
        # depth-independent — the unrolled 24-layer whole-step program
        # exceeded a 25-min compile budget through the remote-compile
        # tunnel (round 4); PADDLE_TPU_BENCH_SCAN=0 opts back out.
        seq, batch = 2048, 4
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=seq, recompute=True,
                        scan_layers=os.environ.get(
                            "PADDLE_TPU_BENCH_SCAN", "1") != "0",
                        fused_loss_chunk=_int_env(
                            "PADDLE_TPU_BENCH_FUSED_CE", 2048),
                        recompute_policy=os.environ.get(
                            "PADDLE_TPU_BENCH_REMAT_POLICY", "full"))
        multi_precision = False
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=seq,
                        fused_loss_chunk=_int_env(
                            "PADDLE_TPU_BENCH_FUSED_CE", 0))
        # A/B lever (PADDLE_TPU_BENCH_PURE_BF16=1): drop the f32 master
        # copy (moments stay f32) — trims the HBM-bound optimizer
        # update from ~16B to ~12B per param per step, worth ~1% of
        # the 125M step if the MFU profile confirms the update slice.
        # Extra record only; the driver metric keeps
        # multi_precision=True.
        if _int_env("PADDLE_TPU_BENCH_PURE_BF16", 0):
            multi_precision = False

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 multi_precision=multi_precision,
                                 parameters=model.parameters())
    step = TrainStep(model, model.make_loss_fn(), opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))

    # warmup (compile + 2 steady steps). First axon compile of the full
    # donated step is 1-3 min; cached recompiles are seconds.
    # Budget override for slow remote-compile paths. The 1.3B default is
    # deliberately generous: aborting bench.py mid-remote-compile WEDGES
    # the axon tunnel for every later client (observed round 4 — the
    # 1500s kill at 04:29 made the whole rest of the suite UNAVAILABLE),
    # so for non-driver configs waiting out a slow compile is strictly
    # cheaper than killing it. The driver metric (125M, ~3 min measured)
    # keeps the tight budget.
    dog.stage("compiling",
              _int_env("PADDLE_TPU_BENCH_COMPILE_BUDGET",
                       3600 if _MODEL_SEL == "gpt1.3b" else 900))
    loss = step(ids, ids)
    float(loss)
    dog.stage("warmup", 120)
    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    dog.stage("measuring", 600)
    iters = 5 if on_cpu else 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    float(loss)  # sync
    dt = time.perf_counter() - t0
    dog.disarm()
    _log("stage=measured ms_per_step=%.1f" % (dt / iters * 1e3))

    tokens_per_sec = batch * seq * iters / dt
    # capture the DRIVER-geometry dispatch now — the sweep below re-traces
    # at other batches and would overwrite the module-global record
    attention_backend = F.last_attention_dispatch().get("backend")

    # optional batch sweep (PADDLE_TPU_BENCH_SWEEP="16,32"): measure the
    # same step at other batch sizes to find the throughput-optimal
    # configuration on this chip; reported as an extra, never as the
    # driver metric (whose geometry must stay comparable across rounds).
    # Extras-only means extras-only: a sweep failure (OOM at 4x batch,
    # typo'd env var) must not take down the already-measured record.
    sweep = {}
    sweep_batches = []
    for s in os.environ.get("PADDLE_TPU_BENCH_SWEEP", "").split(","):
        if not s.strip():
            continue
        try:
            sweep_batches.append(int(s))
        except ValueError:
            _log("sweep: skipping unparseable batch %r" % s)
    # the watchdog stays DISARMED here: its expiry path is os._exit,
    # which would discard the record no try/except can save — and the
    # main metric is already measured, so a hung sweep only costs time
    for b2 in sweep_batches:
        try:
            ids2 = paddle.to_tensor(rng.randint(
                0, cfg.vocab_size, (b2, seq)).astype("int64"))
            _log("stage=sweep_compile b=%d" % b2)
            loss = step(ids2, ids2)
            float(loss)
            for _ in range(2):
                loss = step(ids2, ids2)
            float(loss)
            _log("stage=sweep_measure b=%d" % b2)
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = step(ids2, ids2)
            float(loss)
            dt2 = time.perf_counter() - t0
            sweep[str(b2)] = round(b2 * seq * iters / dt2, 2)
            _log("stage=sweep b=%d tok/s=%.0f" % (b2, sweep[str(b2)]))
        except Exception as e:  # noqa: BLE001 — record, keep the run alive
            sweep[str(b2)] = "error: %s" % str(e)[:120]
            _log("stage=sweep b=%d FAILED: %s" % (b2, str(e)[:160]))

    # optional fused-loop A/B (PADDLE_TPU_BENCH_SCAN_STEPS=K, PR 4):
    # the SAME donated step program dispatched as K-step scanned windows
    # (TrainStep.scan_steps) instead of per-step calls — extras-only,
    # the driver metric keeps per-step dispatch so its geometry stays
    # comparable across rounds. tools/bench_train_loop.py is the
    # dedicated dispatch-overhead bench; this lever shows the effect at
    # bench geometry. Watchdog stays disarmed (extras contract above).
    scan_extra = {}
    scan_k = _int_env("PADDLE_TPU_BENCH_SCAN_STEPS", 0)
    if scan_k > 1:
        try:
            sb = np.stack([np.asarray(ids.value)] * scan_k)
            _log("stage=scan_compile k=%d" % scan_k)
            step.scan_steps(scan_k, sb, sb)          # compile + warm
            n_win = max(1, iters // 2)
            t0 = time.perf_counter()
            for _ in range(n_win):
                last = step.scan_steps(scan_k, sb, sb)
            np.asarray(last.value)                    # terminal sync
            dt_scan = time.perf_counter() - t0
            scan_extra = {
                "scan_steps_k": scan_k,
                "scan_tokens_per_sec": round(
                    batch * seq * scan_k * n_win / dt_scan, 2),
            }
            _log("stage=scan_steps k=%d tok/s=%s"
                 % (scan_k, scan_extra["scan_tokens_per_sec"]))
        except Exception as e:  # noqa: BLE001 — extras-only
            scan_extra = {"scan_steps_error": str(e)[:120]}
            _log("stage=scan_steps FAILED: %s" % str(e)[:160])

    # MFU estimate: 6N per token (fwd+bwd matmuls) + attention
    # 12*L*H*S (PaLM appendix B accounting, causal halved)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size \
        * seq
    peak = _peak_flops(kind)
    mfu = tokens_per_sec * flops_per_token / peak

    if on_cpu:
        # CPU smoke config is not comparable to the chip benchmark
        print(json.dumps({
            "metric": _METRIC,
            "value": round(tokens_per_sec, 2),
            "unit": "tokens/s/chip",
            "vs_baseline": 1.0,
            **scan_extra,
        }))
        return 0

    prev_path = os.path.join(
        _HERE, "BENCH_baseline.json" if _MODEL_SEL == "gpt125m"
        else "BENCH_baseline_gpt1p3b.json")
    vs, base_kind, mismatch = 1.0, None, False
    if os.path.exists(prev_path):
        # Never overwrite an existing baseline — a parse error must not
        # destroy the round-1 anchor (vs_baseline would silently reset).
        try:
            with open(prev_path) as f:
                prev = json.load(f)
            if prev.get("value"):
                vs = tokens_per_sec / float(prev["value"])
            base_kind = prev.get("device_kind")
            if base_kind is None:
                # round-1 record predates the device_kind field. It was
                # measured on the v5e axon tunnel (PALLAS_AXON_TPU_GEN at
                # the time), so only backfill when the current chip is
                # v5e-class too — backfilling a DIFFERENT current kind
                # would mask exactly the mismatch this field exists to
                # flag. Temp-file + replace so a failure can't truncate.
                if "v5" in kind.lower():
                    prev["device_kind"] = base_kind = kind
                    tmp = prev_path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(prev, f)
                    os.replace(tmp, prev_path)
                else:
                    base_kind = "unknown (v5e-era record)"
            mismatch = base_kind != kind
        except (OSError, ValueError) as e:
            _log("baseline record unreadable (%s); reporting vs_baseline=1"
                 % e)
    else:
        # first run establishes the baseline
        try:
            with open(prev_path, "w") as f:
                json.dump({"metric": _METRIC, "value": tokens_per_sec,
                           "device_kind": kind}, f)
        except OSError:
            pass

    rec = {
        "metric": _METRIC,
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 4),
        "mfu_pct": round(100 * mfu, 1),
        "ms_per_step": round(dt / iters * 1e3, 1),
        "params": n_params,
        "device_kind": kind,
        # which attention kernel the model actually traced — proof the
        # Pallas path fired at the bench geometry (VERDICT r2 weak #3);
        # captured BEFORE the sweep re-traced at other batches
        "attention_backend": attention_backend,
    }
    if sweep:
        rec["batch_sweep_tok_s"] = sweep
    if scan_extra:
        rec.update(scan_extra)
    if mismatch:
        rec["chip_mismatch"] = True
        rec["baseline_device_kind"] = base_kind
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
