"""Driver benchmark: GPT pretraining step throughput on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} (plus an
MFU estimate and step time as extra keys).

Metric: GPT-125M-class causal-LM training tokens/sec/chip — the single-chip
proxy for BASELINE.json's "GPT tokens/sec/chip" target (the reference
publishes no absolute numbers, BASELINE.json "published": {}; vs_baseline
is reported against the first recorded value of this same benchmark,
BENCH_baseline.json, 58693 tok/s from round 1).

The whole step (forward, loss, backward, AdamW update, bf16 compute with
fp32 master weights) is one donated XLA program (jit.TrainStep). Batch 8
was the measured optimum of the {8,16,32,64} sweep in round 2 (larger
batches lose ~3% to activation pressure at seq 1024 on 16G HBM).
"""
import json
import os
import sys
import time

import numpy as np

# bf16 peak FLOP/s per chip by device_kind substring (public specs)
_PEAK = (("v5 lite", 197e12), ("v5e", 197e12), ("v6 lite", 918e12),
         ("v6e", 918e12), ("v5p", 459e12), ("v5", 459e12), ("v4", 275e12))


def _peak_flops(kind: str) -> float:
    k = kind.lower()
    for sub, peak in _PEAK:
        if sub in k:
            return peak
    return 197e12  # conservative default (v5e-class)


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F  # noqa: F401 (warm import)
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    # single-chip friendly config (125M-class, bf16 params)
    seq, batch = 1024, 8
    on_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if on_cpu:  # keep the CPU smoke run quick
        seq, batch = 128, 2
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=seq)
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=seq)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                                 parameters=model.parameters())
    step = TrainStep(model, GPTForCausalLM.loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))

    # warmup (compile + 2 steady steps)
    for _ in range(3):
        loss = step(ids, ids)
    float(loss)

    iters = 5 if on_cpu else 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    float(loss)  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt

    # MFU estimate: 6N per token (fwd+bwd matmuls) + attention
    # 12*L*H*S (PaLM appendix B accounting, causal halved)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size \
        * seq
    peak = _peak_flops(getattr(jax.devices()[0], "device_kind", ""))
    mfu = tokens_per_sec * flops_per_token / peak

    if on_cpu:
        # CPU smoke config is not comparable to the chip benchmark
        print(json.dumps({
            "metric": "gpt125m_train_tokens_per_sec_chip",
            "value": round(tokens_per_sec, 2),
            "unit": "tokens/s/chip",
            "vs_baseline": 1.0,
        }))
        return

    prev_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_baseline.json")
    vs = 1.0
    try:
        with open(prev_path) as f:
            prev = json.load(f)
        if prev.get("value"):
            vs = tokens_per_sec / float(prev["value"])
    except (OSError, ValueError):
        # first run establishes the baseline
        try:
            with open(prev_path, "w") as f:
                json.dump({"metric": "gpt125m_train_tokens_per_sec_chip",
                           "value": tokens_per_sec}, f)
        except OSError:
            pass

    print(json.dumps({
        "metric": "gpt125m_train_tokens_per_sec_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 4),
        "mfu_pct": round(100 * mfu, 1),
        "ms_per_step": round(dt / iters * 1e3, 1),
        "params": n_params,
    }))


if __name__ == "__main__":
    sys.exit(main())
