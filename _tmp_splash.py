import time, functools
import jax, jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.splash_attention import (
    splash_attention_kernel as sk, splash_attention_mask as sm)

B,S,H,D = 8,1024,12,64
key = jax.random.PRNGKey(0)
ks = jax.random.split(key,3)
q = jax.random.normal(ks[0],(B,H,S,D),jnp.bfloat16)
k = jax.random.normal(ks[1],(B,H,S,D),jnp.bfloat16)
v = jax.random.normal(ks[2],(B,H,S,D),jnp.bfloat16)
scale = 1.0/D**0.5

mask = sm.MultiHeadMask([sm.CausalMask((S,S)) for _ in range(H)])
kernel = sk.make_splash_mha(mask=mask, head_shards=1, q_seq_shards=1)
kernel_b = jax.vmap(kernel)

def splash_loss(q,k,v):
    o = kernel_b(q*scale, k, v)
    return o.astype(jnp.float32).sum()

def xla_loss(q,k,v):
    qt,kt,vt = [jnp.swapaxes(x,1,2) for x in (q,k,v)]
    return jax.nn.dot_product_attention(qt,kt,vt,is_causal=True,scale=scale).astype(jnp.float32).sum()

# numeric check vs xla
o_s = jax.jit(lambda q,k,v: kernel_b(q*scale,k,v))(q,k,v)
qt,kt,vt = [jnp.swapaxes(x,1,2) for x in (q,k,v)]
o_x = jnp.swapaxes(jax.nn.dot_product_attention(qt,kt,vt,is_causal=True,scale=scale),1,2)
print("splash vs xla fwd max diff:", float(jnp.abs(o_s.astype(jnp.float32)-o_x.astype(jnp.float32)).max()))

def bench(fn,*args,iters=100):
    o=fn(*args); jax.block_until_ready(o)
    t0=time.perf_counter()
    for _ in range(iters): o=fn(*args)
    jax.block_until_ready(o)
    return (time.perf_counter()-t0)/iters*1e6

sg = jax.jit(jax.grad(splash_loss, argnums=(0,1,2)))
xg = jax.jit(jax.grad(xla_loss, argnums=(0,1,2)))
print("splash f+b %8.1f us" % bench(sg,q,k,v))
print("xla    f+b %8.1f us" % bench(xg,q,k,v))
