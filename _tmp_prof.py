import sys, time, glob, gzip, json, os
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM
import jax

batch, seq = 8, 1024
cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                num_heads=12, max_seq_len=seq)
paddle.seed(0)
model = GPTForCausalLM(cfg); model.bfloat16()
opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                             parameters=model.parameters())
step = TrainStep(model, GPTForCausalLM.loss_fn, opt)
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))
for _ in range(3): loss = step(ids, ids)
float(loss)
with jax.profiler.trace("/tmp/jaxtrace"):
    for _ in range(3): loss = step(ids, ids)
    float(loss)
print("trace done")
