import time, jax, jax.numpy as jnp
from paddle_tpu.nn.functional.loss import _fused_softmax_ce
N,V = 8184, 50304
key = jax.random.PRNGKey(0)
lg = jax.random.normal(key,(N,V),jnp.bfloat16)
idx = jax.random.randint(jax.random.PRNGKey(1),(N,),0,V)
t0=time.perf_counter()
f = jax.jit(jax.grad(lambda lg: _fused_softmax_ce(lg, idx).mean()))
g = f(lg); jax.block_until_ready(g)
print("CE fwd+bwd compile+run", time.perf_counter()-t0, "s")
t0=time.perf_counter()
for _ in range(10): g=f(lg)
jax.block_until_ready(g)
print("CE f+b steady %.2f ms" % ((time.perf_counter()-t0)/10*1e3))
