"""LR scheduler numerics — closed-form checks for the schedulers the
main optimizer suite does not cover (reference:
python/paddle/optimizer/lr.py; test pattern:
test_lr_scheduler.py's python-reference comparison)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.optimizer import lr


def _series(sched, n=8, **step_kw):
    out = []
    for _ in range(n):
        out.append(float(sched()))
        sched.step(**step_kw)
    return out


class TestClosedForms:
    def test_natural_exp(self):
        s = lr.NaturalExpDecay(learning_rate=0.5, gamma=0.1)
        got = _series(s, 5)
        want = [0.5 * math.exp(-0.1 * k) for k in range(5)]
        np.testing.assert_allclose(got, want, rtol=1e-12)

    def test_inverse_time(self):
        s = lr.InverseTimeDecay(learning_rate=0.5, gamma=0.5)
        np.testing.assert_allclose(
            _series(s, 4), [0.5 / (1 + 0.5 * k) for k in range(4)])

    def test_polynomial_clip_and_cycle(self):
        s = lr.PolynomialDecay(learning_rate=1.0, decay_steps=4,
                               end_lr=0.1, power=2.0)
        got = _series(s, 7)
        want = [(1.0 - 0.1) * (1 - min(k, 4) / 4.0) ** 2 + 0.1
                for k in range(7)]
        np.testing.assert_allclose(got, want)
        # cycle=True keeps decaying against a growing horizon
        c = lr.PolynomialDecay(learning_rate=1.0, decay_steps=4,
                               end_lr=0.1, power=1.0, cycle=True)
        got = _series(c, 7)
        assert got[5] > got[3] * 0.0 and got[5] != got[4]  # no flatline
        assert min(got) >= 0.1 - 1e-12

    def test_multistep(self):
        s = lr.MultiStepDecay(learning_rate=1.0, milestones=[2, 4],
                              gamma=0.1)
        np.testing.assert_allclose(
            _series(s, 6), [1.0, 1.0, 0.1, 0.1, 0.01, 0.01])

    def test_lambda(self):
        s = lr.LambdaDecay(learning_rate=0.5,
                           lr_lambda=lambda e: 0.9 ** e)
        np.testing.assert_allclose(
            _series(s, 4), [0.5 * 0.9 ** k for k in range(4)])

    def test_multiplicative(self):
        s = lr.MultiplicativeDecay(learning_rate=0.5,
                                   lr_lambda=lambda e: 0.5)
        # multiplies the RUNNING lr each step (unlike LambdaDecay)
        np.testing.assert_allclose(
            _series(s, 4), [0.5, 0.25, 0.125, 0.0625])

    def test_cosine_warm_restarts(self):
        s = lr.CosineAnnealingWarmRestarts(learning_rate=1.0, T_0=4,
                                           T_mult=1, eta_min=0.0)
        got = _series(s, 9)
        # restarts at t=4 and t=8: back to base_lr
        assert got[0] == pytest.approx(1.0)
        assert got[4] == pytest.approx(1.0)
        assert got[8] == pytest.approx(1.0)
        want2 = (1 + math.cos(math.pi * 2 / 4)) / 2
        assert got[2] == pytest.approx(want2)
        # T_mult=2 doubles the second period: restart lands at 4+8=12
        s2 = lr.CosineAnnealingWarmRestarts(learning_rate=1.0, T_0=4,
                                            T_mult=2, eta_min=0.0)
        got2 = _series(s2, 13)
        assert got2[12] == pytest.approx(1.0)
        assert got2[8] == pytest.approx((1 + math.cos(math.pi * 4 / 8)) / 2)

    def test_linear_lr(self):
        s = lr.LinearLR(learning_rate=1.0, total_steps=4,
                        start_factor=0.25, end_factor=1.0)
        np.testing.assert_allclose(
            _series(s, 6),
            [0.25, 0.25 + 0.75 / 4, 0.25 + 2 * 0.75 / 4,
             0.25 + 3 * 0.75 / 4, 1.0, 1.0])

    def test_one_cycle(self):
        s = lr.OneCycleLR(max_learning_rate=1.0, total_steps=10,
                          divide_factor=4.0, end_learning_rate=0.01,
                          phase_pct=0.3)
        got = _series(s, 10)
        assert got[0] == pytest.approx(0.25)        # max/divide_factor
        peak = max(got)
        assert peak == pytest.approx(1.0)           # reaches max_lr
        assert got[-1] < 0.1                        # anneals toward end
        assert np.argmax(got) <= 3                  # warmup is ~30%

    def test_cyclic_modes(self):
        s = lr.CyclicLR(base_learning_rate=0.1, max_learning_rate=1.1,
                        step_size_up=2, step_size_down=2)
        got = _series(s, 9)
        np.testing.assert_allclose(
            got, [0.1, 0.6, 1.1, 0.6, 0.1, 0.6, 1.1, 0.6, 0.1])
        # triangular2 halves the amplitude each cycle
        s2 = lr.CyclicLR(base_learning_rate=0.1, max_learning_rate=1.1,
                         step_size_up=2, step_size_down=2,
                         mode="triangular2")
        got2 = _series(s2, 7)
        assert got2[2] == pytest.approx(1.1)
        assert got2[6] == pytest.approx(0.1 + (1.1 - 0.1) * 0.5)


class TestStateDict:
    @pytest.mark.parametrize("mk", [
        lambda: lr.NaturalExpDecay(0.5, 0.1),
        lambda: lr.PolynomialDecay(1.0, 4, cycle=True),
        lambda: lr.CosineAnnealingWarmRestarts(1.0, 4, T_mult=2),
        lambda: lr.OneCycleLR(1.0, 10),
        lambda: lr.CyclicLR(0.1, 1.1, 2),
        lambda: lr.MultiplicativeDecay(0.5, lambda e: 0.5),
    ])
    def test_roundtrip_resumes_series(self, mk):
        a = mk()
        for _ in range(3):
            a.step()
        state = a.state_dict()
        b = mk()
        b.set_state_dict(state)
        for _ in range(4):
            assert float(a()) == pytest.approx(float(b()))
            a.step()
            b.step()

    def test_scheduler_drives_optimizer(self):
        sched = lr.MultiStepDecay(learning_rate=0.5, milestones=[1],
                                  gamma=0.1)
        p = paddle.create_parameter([3], "float32")
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[p])
        before = p.numpy().copy()
        (p * paddle.to_tensor(np.ones(3, np.float32))).sum().backward()
        opt.step()
        d1 = before - p.numpy()           # lr 0.5 step
        sched.step()
        opt.clear_grad()
        before = p.numpy().copy()
        (p * paddle.to_tensor(np.ones(3, np.float32))).sum().backward()
        opt.step()
        d2 = before - p.numpy()           # lr 0.05 step
        np.testing.assert_allclose(d2, d1 * 0.1, rtol=1e-5)
