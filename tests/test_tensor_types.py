"""TensorArray / SelectedRows / StringTensor (phi/core aux tensor types,
SURVEY §2.1)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestTensorArray:
    def test_write_read_length(self):
        arr = paddle.create_array("float32")
        x0 = paddle.to_tensor(np.ones(2, np.float32))
        paddle.array_write(x0, 0, arr)
        paddle.array_write(x0 * 2, paddle.to_tensor(np.int64(1)), arr)
        assert paddle.array_length(arr) == 2
        np.testing.assert_allclose(paddle.array_read(arr, 1).numpy(), 2.0)

    def test_sparse_write_pads(self):
        arr = paddle.create_array("float32")
        paddle.array_write(paddle.to_tensor(np.ones(2, np.float32)), 3,
                           arr)
        assert paddle.array_length(arr) == 4
        assert arr[0] is None

    def test_initialized_list_type_check(self):
        with pytest.raises(TypeError, match="should be Tensor"):
            paddle.create_array("float32", [1, 2, 3])

    def test_stack_concat_grad(self):
        xs = [paddle.to_tensor(np.full((3,), i, np.float32))
              for i in range(4)]
        for x in xs:
            x.stop_gradient = False
        arr = paddle.TensorArray(initialized_list=xs)
        s = arr.stack()
        assert s.shape == [4, 3]
        c = arr.concat()
        assert c.shape == [12]
        s.sum().backward()
        np.testing.assert_allclose(xs[0].grad.numpy(), 1.0)


class TestSelectedRows:
    def test_roundtrip(self):
        dense = paddle.to_tensor(
            np.arange(12).reshape(4, 3).astype(np.float32))
        sr = paddle.SelectedRows.from_dense(dense, [1, 2])
        assert sr.height == 4 and sr.rows == [1, 2]
        out = sr.to_dense().numpy()
        np.testing.assert_allclose(out[1:3], dense.numpy()[1:3])
        assert out[0].sum() == 0 and out[3].sum() == 0

    def test_duplicate_rows_accumulate(self):
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import Tensor
        sr = paddle.SelectedRows([0, 0], 2,
                                 Tensor(jnp.ones((2, 2))))
        np.testing.assert_allclose(sr.to_dense().numpy()[0], 2.0)


class TestStringTensor:
    def test_basic(self):
        st = paddle.StringTensor(["Alpha", "beta"])
        assert st.shape == [2]
        assert st[0] == "Alpha"
        assert st.upper().numpy()[1] == "BETA"
        assert len(st) == 2
