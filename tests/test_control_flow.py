"""Control-flow combinators: cond / while_loop / case / switch_case / Assert.

Parity targets: python/paddle/static/nn/control_flow.py (cond :873,
while_loop :401, case :564, switch_case :697, Assert :43) and the
dy2static data-dependent control-flow tests
(python/paddle/fluid/tests/unittests/dygraph_to_static/test_ifelse.py,
test_loop.py). Eager path = one branch on the tape; traced path =
lax.cond / lax.while_loop / lax.switch inside the XLA program.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn

from op_test import OpTest


class TestCondEager:
    def test_picks_branch(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = snn.cond(x.sum() < 5.0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        out = snn.cond(x.sum() > 5.0, lambda: x * 2, lambda: x - 1)
        np.testing.assert_allclose(out.numpy(), [0.0, 1.0])

    def test_python_bool_pred_and_none_branch(self):
        x = paddle.to_tensor(3.0)
        assert snn.cond(True, lambda: x + 1).numpy() == 4.0
        assert snn.cond(False, lambda: x + 1) is None

    def test_nested_structure(self):
        x = paddle.to_tensor(2.0)
        a, (b, c) = snn.cond(x < 3.0,
                             lambda: (x, (x + 1, x + 2)),
                             lambda: (x * 0, (x, x)))
        assert (a.numpy(), b.numpy(), c.numpy()) == (2.0, 3.0, 4.0)

    def test_grad_through_both_branches(self):
        # grad check through the TRUE branch
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        out = snn.cond(x.sum() < 5.0, lambda: (x * x).sum(),
                       lambda: (3 * x).sum())
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])
        # grad check through the FALSE branch
        y = paddle.to_tensor(np.array([4.0, 4.0], np.float32),
                             stop_gradient=False)
        out = snn.cond(y.sum() < 5.0, lambda: (y * y).sum(),
                       lambda: (3 * y).sum())
        out.backward()
        np.testing.assert_allclose(y.grad.numpy(), [3.0, 3.0])


class TestCondOpTest(OpTest):
    """OpTest-style finite-difference grad check across both branches."""

    def _run(self, x):
        return snn.cond(x.sum() < 0.0,
                        lambda: paddle.tanh(x) * 2.0,
                        lambda: x * x + x)

    def test_true_branch(self):
        self.inputs = {"x": -np.abs(
            np.random.RandomState(0).randn(3, 4).astype(np.float32)) - 0.1}
        self.op = self._run
        self.ref = lambda x: np.tanh(x) * 2.0
        self.check_output()
        self.check_grad(wrt=["x"])

    def test_false_branch(self):
        self.inputs = {"x": np.abs(
            np.random.RandomState(1).randn(3, 4).astype(np.float32)) + 0.1}
        self.op = self._run
        self.ref = lambda x: x * x + x
        self.check_output()
        self.check_grad(wrt=["x"])


class TestCondTraced:
    def test_lax_cond_in_to_static(self):
        @paddle.jit.to_static
        def f(x):
            return snn.cond(x.sum() < 5.0, lambda: x * 2, lambda: x - 1)

        lo = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        hi = paddle.to_tensor(np.array([4.0, 4.0], np.float32))
        np.testing.assert_allclose(f(lo).numpy(), [2.0, 4.0])
        # same compiled program, other branch at run time
        np.testing.assert_allclose(f(hi).numpy(), [3.0, 3.0])

    def test_grad_through_traced_cond(self):
        lin = paddle.nn.Linear(4, 4)
        layer = paddle.jit.to_static(lin)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))

        @paddle.jit.to_static
        def head(h):
            return snn.cond(h.sum() > 0.0,
                            lambda: (h * h).sum(), lambda: h.sum())

        out = head(layer(x))
        out.backward()
        assert lin.weight.grad is not None
        assert np.isfinite(lin.weight.grad.numpy()).all()

    def test_structure_mismatch_raises(self):
        @paddle.jit.to_static
        def f(x):
            return snn.cond(x.sum() < 5.0,
                            lambda: (x, x), lambda: x)

        with pytest.raises(TypeError, match="true_fn and false_fn"):
            f(paddle.to_tensor(np.ones(2, np.float32)))


class TestWhileLoop:
    def test_eager_unrolled_with_grad(self):
        x = paddle.to_tensor(np.array(1.0, np.float32), stop_gradient=False)
        i = paddle.to_tensor(np.array(0, np.int32))
        i_out, s_out = snn.while_loop(
            lambda i, s: i < 3, lambda i, s: [i + 1, s * 2.0], [i, x])
        assert int(i_out.numpy()) == 3
        assert float(s_out.numpy()) == 8.0
        s_out.backward()
        np.testing.assert_allclose(x.grad.numpy(), 8.0)  # d(8x)/dx

    def test_traced_data_dependent_trip_count(self):
        # dy2static parity: a loop whose trip count depends on tensor data
        @paddle.jit.to_static
        def grow(s):
            [out] = snn.while_loop(lambda v: v.sum() < 100.0,
                                   lambda v: [v * 2.0], [s])
            return out

        r = grow(paddle.to_tensor(np.array([1.0, 1.0], np.float32)))
        np.testing.assert_allclose(r.numpy(), [64.0, 64.0])
        # different data, different trip count, same compiled program
        r2 = grow(paddle.to_tensor(np.array([30.0, 30.0], np.float32)))
        np.testing.assert_allclose(r2.numpy(), [60.0, 60.0])

    def test_validation(self):
        with pytest.raises(TypeError):
            snn.while_loop(1, lambda: None, [paddle.to_tensor(0.0)])
        with pytest.raises(ValueError):
            snn.while_loop(lambda: True, lambda: None, [])


class TestCaseSwitch:
    def _mk(self):
        return paddle.to_tensor(np.array([1.0, 2.0], np.float32))

    def test_case_eager(self):
        x = self._mk()
        out = snn.case([(x.sum() > 10.0, lambda: x * 0),
                        (x.sum() > 1.0, lambda: x * 10)],
                       default=lambda: x)
        np.testing.assert_allclose(out.numpy(), [10.0, 20.0])
        # no pred true and no default -> last fn is the default (reference)
        out = snn.case([(x.sum() > 10.0, lambda: x * 0),
                        (x.sum() > 20.0, lambda: x + 1)])
        np.testing.assert_allclose(out.numpy(), [2.0, 3.0])

    def test_case_traced(self):
        @paddle.jit.to_static
        def f(x):
            return snn.case([(x.sum() > 10.0, lambda: x * 0),
                             (x.sum() > 1.0, lambda: x * 10)],
                            default=lambda: x)

        np.testing.assert_allclose(f(self._mk()).numpy(), [10.0, 20.0])
        big = paddle.to_tensor(np.array([6.0, 6.0], np.float32))
        np.testing.assert_allclose(f(big).numpy(), [0.0, 0.0])

    def test_switch_case_eager(self):
        x = self._mk()
        fns = [lambda: x + 1, lambda: x + 2, lambda: x + 3]
        idx = paddle.to_tensor(np.array(1, np.int32))
        np.testing.assert_allclose(
            snn.switch_case(idx, fns).numpy(), [3.0, 4.0])
        # out-of-range index -> default (= max-key fn when default=None)
        oob = paddle.to_tensor(np.array(7, np.int32))
        np.testing.assert_allclose(
            snn.switch_case(oob, fns).numpy(), [4.0, 5.0])
        # (key, fn) pairs + explicit default
        np.testing.assert_allclose(
            snn.switch_case(oob, [(5, lambda: x)],
                            default=lambda: x * 0).numpy(), [0.0, 0.0])

    def test_switch_case_traced(self):
        @paddle.jit.to_static
        def f(idx, x):
            return snn.switch_case(
                idx, [lambda: x + 1, lambda: x * 2], default=lambda: x * 0)

        x = self._mk()
        np.testing.assert_allclose(
            f(paddle.to_tensor(np.array(0, np.int32)), x).numpy(), [2.0, 3.0])
        np.testing.assert_allclose(
            f(paddle.to_tensor(np.array(1, np.int32)), x).numpy(), [2.0, 4.0])
        np.testing.assert_allclose(
            f(paddle.to_tensor(np.array(9, np.int32)), x).numpy(), [0.0, 0.0])


class TestAssertAndHook:
    def test_assert_eager(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        snn.Assert(x.sum() > 0.0)  # passes silently
        with pytest.raises(ValueError, match="Assert failed"):
            snn.Assert(x.sum() < 0.0, data=[x])

    def test_python_if_in_to_static_names_combinators(self):
        @paddle.jit.to_static
        def f(x):
            if x.sum() > 0:  # data-dependent python branch: must be loud
                return x * 2
            return x

        with pytest.raises(RuntimeError, match="static.nn.cond"):
            f(paddle.to_tensor(np.ones(2, np.float32)))
