"""static.nn layer functions (reference: python/paddle/static/nn/common.py).

Each function instantiates the matching nn Layer, registered by name in
a build registry (the role Program parameters play in the reference) —
named calls reuse their layer, so static-style build code trains.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


@pytest.fixture(autouse=True)
def fresh_registry():
    snn.reset_build_registry()
    yield
    snn.reset_build_registry()


def _x(shape, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype(np.float32))


class TestShapes:
    def test_core_layers(self):
        x4 = _x((4, 3, 8, 8))
        flat = _x((4, 16), 1)
        assert snn.fc(flat, 8, activation="relu").shape == [4, 8]
        ids = paddle.to_tensor(np.arange(8).reshape(4, 2).astype(np.int64))
        assert snn.embedding(ids, (32, 5)).shape == [4, 2, 5]
        assert snn.batch_norm(x4).shape == [4, 3, 8, 8]
        assert snn.layer_norm(flat).shape == [4, 16]
        assert snn.group_norm(x4, groups=3).shape == [4, 3, 8, 8]
        assert snn.instance_norm(x4).shape == [4, 3, 8, 8]
        assert snn.data_norm(flat).shape == [4, 16]
        assert snn.conv2d(x4, 6, 3, act="relu").shape == [4, 6, 6, 6]
        assert snn.conv2d_transpose(x4, 6, filter_size=3).shape == \
            [4, 6, 10, 10]
        # output_size derives the filter (reference semantics)
        assert snn.conv2d_transpose(x4, 6, output_size=10).shape == \
            [4, 6, 10, 10]
        assert snn.prelu(x4, mode="channel").shape == [4, 3, 8, 8]
        y = _x((4, 10), 3)
        assert snn.bilinear_tensor_product(flat, y, 6).shape == [4, 6]
        w = _x((8, 6), 4)
        assert snn.spectral_norm(w, dim=0).shape == [8, 6]

    def test_conv3d_family(self):
        x5 = _x((2, 3, 4, 8, 8), 2)
        assert snn.conv3d(x5, 4, 3).shape == [2, 4, 2, 6, 6]
        assert snn.conv3d_transpose(x5, 4, filter_size=3).shape == \
            [2, 4, 6, 10, 10]

    def test_row_conv_numerics(self):
        seq = _x((4, 10, 16), 5)
        out = snn.row_conv(seq, 2)
        wv = [v for k, v in snn.build_registry().items()
              if k.startswith("row_conv")][0]
        wnp = np.asarray(wv.value)
        xp = np.pad(seq.numpy(), ((0, 0), (0, 2), (0, 0)))
        want = sum(xp[:, k:k + 10] * wnp[k] for k in range(3))
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5, atol=1e-5)


class TestBuildSemantics:
    def test_named_calls_reuse_and_train(self):
        """A static-style build function called per step must reuse its
        parameters — training through the registry works."""
        X = np.random.RandomState(7).randn(64, 8).astype(np.float32)
        yv = (X.sum(1) > 0).astype(np.int64)

        def net(x):
            h = snn.fc(x, 16, activation="relu", name="l1")
            return snn.fc(h, 2, name="l2")

        _ = net(paddle.to_tensor(X))  # build
        params = [p for l in snn.build_registry().values()
                  for p in (l.parameters() if hasattr(l, "parameters")
                            else [l])]
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=params)
        loss_fn = paddle.nn.CrossEntropyLoss()
        for _ in range(25):
            loss = loss_fn(net(paddle.to_tensor(X)),
                           paddle.to_tensor(yv))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < 0.4, float(loss)

    def test_data_norm_updates_stats_in_train_only(self):
        x = _x((8, 4), 9)
        out1 = snn.data_norm(x, name="dn")
        dn = snn.build_registry()["data_norm/dn"]
        before = np.asarray(dn.batch_size.value).copy()
        snn.data_norm(x, name="dn")
        assert (np.asarray(dn.batch_size.value) > before).all()
        dn.eval()
        frozen = np.asarray(dn.batch_size.value).copy()
        snn.data_norm(x, name="dn")
        np.testing.assert_array_equal(np.asarray(dn.batch_size.value),
                                      frozen)

    def test_lod_and_ps_stubs_raise(self):
        with pytest.raises(NotImplementedError, match="LoD"):
            snn.sequence_pool(None)
        with pytest.raises(NotImplementedError, match="parameter-server"):
            snn.sparse_embedding()
        with pytest.raises(NotImplementedError):
            snn.nce()
        with pytest.raises(NotImplementedError, match="nn.RNN"):
            snn.StaticRNN()
