"""Optimizer tests.

Mirrors the reference test style (test/legacy_test/test_adam_op.py etc.):
each optimizer's fused update is checked against a plain numpy
re-implementation of the same rule, plus convergence + state_dict tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _make_param(shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    p = paddle.Parameter(rng.randn(*shape).astype(np.float32))
    g = rng.randn(*shape).astype(np.float32)
    p._grad = paddle.to_tensor(g).value
    return p, g


def _run_steps(opt_cls, np_rule, steps=3, **kw):
    rng = np.random.RandomState(0)
    p0 = rng.randn(4, 3).astype(np.float32)
    p = paddle.Parameter(p0.copy())
    opt = opt_cls(learning_rate=0.1, parameters=[p], **kw)
    ref_p = p0.copy()
    state = {}
    for t in range(1, steps + 1):
        g = rng.randn(4, 3).astype(np.float32)
        p._grad = paddle.to_tensor(g).value
        opt.step()
        ref_p, state = np_rule(ref_p, g, state, 0.1, t)
    # fp32 on-device vs float64 numpy scalar math → ~1e-4 relative
    np.testing.assert_allclose(p.numpy(), ref_p, rtol=5e-4, atol=5e-5)


def test_sgd():
    def rule(p, g, s, lr, t):
        return p - lr * g, s
    _run_steps(optimizer.SGD, rule)


def test_momentum():
    def rule(p, g, s, lr, t):
        v = s.get("v", np.zeros_like(p))
        v = 0.9 * v + g
        return p - lr * v, {"v": v}
    _run_steps(optimizer.Momentum, rule, momentum=0.9)


def test_momentum_nesterov():
    def rule(p, g, s, lr, t):
        v = s.get("v", np.zeros_like(p))
        v = 0.9 * v + g
        return p - lr * (g + 0.9 * v), {"v": v}
    _run_steps(optimizer.Momentum, rule, momentum=0.9, use_nesterov=True)


def test_adam():
    def rule(p, g, s, lr, t):
        m = s.get("m", np.zeros_like(p))
        v = s.get("v", np.zeros_like(p))
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        lr_t = lr * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        return p - lr_t * m / (np.sqrt(v) + 1e-8), {"m": m, "v": v}
    _run_steps(optimizer.Adam, rule)


def test_adamw_decoupled_decay():
    wd = 0.01

    def rule(p, g, s, lr, t):
        p = p * (1 - lr * wd)
        m = s.get("m", np.zeros_like(p))
        v = s.get("v", np.zeros_like(p))
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        lr_t = lr * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        return p - lr_t * m / (np.sqrt(v) + 1e-8), {"m": m, "v": v}
    _run_steps(optimizer.AdamW, rule, weight_decay=wd)


def test_adagrad():
    def rule(p, g, s, lr, t):
        acc = s.get("acc", np.zeros_like(p)) + g * g
        return p - lr * g / (np.sqrt(acc) + 1e-6), {"acc": acc}
    _run_steps(optimizer.Adagrad, rule)


def test_rmsprop():
    def rule(p, g, s, lr, t):
        ms = s.get("ms", np.zeros_like(p))
        mom = s.get("mom", np.zeros_like(p))
        ms = 0.95 * ms + 0.05 * g * g
        mom = 0.0 * mom + lr * g / np.sqrt(ms + 1e-6)
        return p - mom, {"ms": ms, "mom": mom}
    _run_steps(optimizer.RMSProp, rule)


def test_adamax():
    def rule(p, g, s, lr, t):
        m = s.get("m", np.zeros_like(p))
        u = s.get("u", np.zeros_like(p))
        m = 0.9 * m + 0.1 * g
        u = np.maximum(0.999 * u, np.abs(g))
        return p - (lr / (1 - 0.9 ** t)) * m / (u + 1e-8), {"m": m, "u": u}
    _run_steps(optimizer.Adamax, rule)


def test_adadelta():
    def rule(p, g, s, lr, t):
        rho, eps = 0.95, 1e-6
        sq = s.get("sq", np.zeros_like(p))
        du = s.get("du", np.zeros_like(p))
        sq = rho * sq + (1 - rho) * g * g
        upd = g * np.sqrt(du + eps) / np.sqrt(sq + eps)
        du = rho * du + (1 - rho) * upd * upd
        return p - lr * upd, {"sq": sq, "du": du}
    _run_steps(optimizer.Adadelta, rule)


def test_coupled_weight_decay():
    wd = 0.1

    def rule(p, g, s, lr, t):
        return p - lr * (g + wd * p), s
    _run_steps(optimizer.SGD, rule, weight_decay=wd)


def test_lamb_runs_and_converges():
    p = paddle.Parameter(np.ones((8,), np.float32) * 5)
    opt = optimizer.Lamb(learning_rate=0.1, parameters=[p],
                         lamb_weight_decay=0.0)
    for _ in range(50):
        # grad of 0.5*||p||^2
        p._grad = p.value
        opt.step()
    assert np.abs(p.numpy()).max() < 5.0


def test_training_convergence_linear_regression():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    w_true = np.array([[1.], [-2.], [3.], [0.5]], np.float32)
    y = x @ w_true

    lin = nn.Linear(4, 1)
    opt = optimizer.Adam(learning_rate=0.1, parameters=lin.parameters())
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    for _ in range(200):
        loss = ((lin(xt) - yt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(lin.weight.numpy(), w_true, atol=0.05)


def test_grad_clip_global_norm():
    p, g = _make_param()
    clip = nn.ClipGradByGlobalNorm(clip_norm=0.001)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
    before = p.numpy().copy()
    opt.step()
    delta = np.linalg.norm(p.numpy() - before)
    assert delta <= 0.001 + 1e-5


def test_state_dict_roundtrip():
    p, g = _make_param()
    opt = optimizer.Adam(learning_rate=0.01, parameters=[p])
    opt.step()
    sd = opt.state_dict()

    p2 = paddle.Parameter(p.numpy())
    p2.name = p.name
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    m1 = opt._accumulators["moment1"][p.name]
    m2 = opt2._accumulators["moment1"][p.name]
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2))


def test_multi_precision_master_weights():
    rng = np.random.RandomState(0)
    p = paddle.Parameter(rng.randn(16).astype(np.float32))
    p.value = p.value.astype("bfloat16")
    opt = optimizer.Adam(learning_rate=1e-3, parameters=[p],
                         multi_precision=True)
    p._grad = paddle.to_tensor(rng.randn(16).astype(np.float32)).value
    opt.step()
    assert "master" in opt._accumulators
    master = opt._accumulators["master"][p.name]
    assert str(master.dtype) == "float32"
    assert str(p.value.dtype) == "bfloat16"


def test_lr_scheduler_feeds_optimizer():
    p, _ = _make_param()
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    assert opt.get_lr() == pytest.approx(0.1)
    sched.step()
    sched.step()
    assert opt.get_lr() == pytest.approx(0.05)


def test_schedulers_values():
    lr = optimizer.lr
    s = lr.PiecewiseDecay(boundaries=[2, 4], values=[1.0, 0.5, 0.1])
    vals = []
    for _ in range(5):
        vals.append(s())
        s.step()
    assert vals == [1.0, 1.0, 0.5, 0.5, 0.1]

    s = lr.ExponentialDecay(learning_rate=1.0, gamma=0.5)
    s.step()
    assert s() == pytest.approx(0.5)

    s = lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    assert s() == pytest.approx(1.0)
    s.step(10)
    assert s() == pytest.approx(0.0, abs=1e-6)

    s = lr.LinearWarmup(learning_rate=1.0, warmup_steps=4, start_lr=0.0,
                        end_lr=1.0)
    assert s() == pytest.approx(0.0)
    s.step()
    assert s() == pytest.approx(0.25)
    s.step(4)
    assert s() == pytest.approx(1.0)

    s = lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
    s.step(5)
    expect = (512 ** -0.5) * min(5 ** -0.5, 5 * 10 ** -1.5)
    assert s() == pytest.approx(expect)

    s = lr.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.5)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    s.step(metrics=1.0)
    assert s() == pytest.approx(0.5)


def test_minimize_api():
    p = paddle.Parameter(np.ones((3,), np.float32))
    opt = optimizer.SGD(learning_rate=0.5, parameters=[p])
    loss = (paddle.Tensor(p.value, stop_gradient=True) * 0).sum()  # dummy
    x = p * p
    l2 = x.sum()
    opt.minimize(l2)
    np.testing.assert_allclose(p.numpy(), 1 - 0.5 * 2, rtol=1e-6)


def test_adamw_apply_decay_param_fun():
    rng = np.random.RandomState(0)
    v = rng.randn(4).astype(np.float32)
    g = np.zeros(4, np.float32)  # zero grad isolates the decay term
    p_decay = paddle.Parameter(v.copy())
    p_skip = paddle.Parameter(v.copy())
    names = {p_decay.name}
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                          parameters=[p_decay, p_skip],
                          apply_decay_param_fun=lambda n: n in names)
    p_decay._grad = paddle.to_tensor(g).value
    p_skip._grad = paddle.to_tensor(g).value
    opt.step()
    np.testing.assert_allclose(p_decay.numpy(), v * (1 - 0.1 * 0.5), rtol=1e-6)
    np.testing.assert_allclose(p_skip.numpy(), v, rtol=1e-6)


def test_adamw_lr_ratio():
    v = np.ones(4, np.float32)
    g = np.ones(4, np.float32)
    p_full = paddle.Parameter(v.copy())
    p_tenth = paddle.Parameter(v.copy())
    tenth_id = id(p_tenth)
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.0,
                          parameters=[p_full, p_tenth],
                          lr_ratio=lambda p: 0.1 if id(p) == tenth_id else 1.0)
    p_full._grad = paddle.to_tensor(g).value
    p_tenth._grad = paddle.to_tensor(g).value
    opt.step()
    d_full = 1.0 - p_full.numpy()[0]
    d_tenth = 1.0 - p_tenth.numpy()[0]
    np.testing.assert_allclose(d_tenth, d_full * 0.1, rtol=1e-4)


def test_lamb_exclude_from_weight_decay():
    v = np.ones(4, np.float32) * 2
    p_in = paddle.Parameter(v.copy())
    p_out = paddle.Parameter(v.copy())
    out_id = id(p_out)
    opt = optimizer.Lamb(learning_rate=0.1, lamb_weight_decay=0.5,
                         parameters=[p_in, p_out],
                         exclude_from_weight_decay_fn=lambda p: id(p) == out_id)
    z = np.zeros(4, np.float32)
    p_in._grad = paddle.to_tensor(z).value
    p_out._grad = paddle.to_tensor(z).value
    opt.step()
    # excluded param sees zero update (zero grad, no decay); included decays
    np.testing.assert_allclose(p_out.numpy(), v, rtol=1e-6)
    assert p_in.numpy()[0] < 2.0


def test_per_param_regularizer_overrides():
    v = np.ones(4, np.float32)
    g = np.zeros(4, np.float32)
    p = paddle.Parameter(v.copy())
    p.regularizer = optimizer.L2Decay(0.5)
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p], weight_decay=0.0)
    p._grad = paddle.to_tensor(g).value
    opt.step()
    # coupled decay: p -= lr * coeff * p
    np.testing.assert_allclose(p.numpy(), v - 0.1 * 0.5 * v, rtol=1e-6)


def test_state_dict_prefix_names_no_collision():
    pa = paddle.Parameter(np.ones(2, np.float32))
    pb = paddle.Parameter(np.ones(3, np.float32))
    pa.name, pb.name = "w", "w_ho"
    opt = optimizer.Adam(learning_rate=0.01, parameters=[pa, pb])
    pa._grad = paddle.to_tensor(np.ones(2, np.float32)).value
    pb._grad = paddle.to_tensor(np.ones(3, np.float32)).value
    opt.step()
    sd = opt.state_dict()

    qa = paddle.Parameter(np.ones(2, np.float32))
    qb = paddle.Parameter(np.ones(3, np.float32))
    qa.name, qb.name = "w", "w_ho"
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=[qa, qb])
    opt2.set_state_dict(sd)
    assert opt2._accumulators["moment1"]["w"].shape == (2,)
    assert opt2._accumulators["moment1"]["w_ho"].shape == (3,)


def test_functional_apply_gradients_named_tree():
    params = {"linear.weight": np.ones((2, 2), np.float32),
              "norm.bias": np.ones((2,), np.float32)}
    grads = {k: np.zeros_like(v) for k, v in params.items()}
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                          parameters=[paddle.Parameter(np.zeros(1))],
                          apply_decay_param_fun=lambda n: "bias" not in n)
    state = opt.init(params)
    new_p, _ = opt.apply_gradients(params, grads, state)
    np.testing.assert_allclose(np.asarray(new_p["norm.bias"]),
                               params["norm.bias"], rtol=1e-6)
    assert np.asarray(new_p["linear.weight"])[0, 0] < 1.0


def test_reduce_on_plateau_cooldown_suppresses():
    s = optimizer.lr.ReduceOnPlateau(learning_rate=1.0, patience=0,
                                     factor=0.5, cooldown=3)
    s.step(metrics=1.0)   # best=1.0
    s.step(metrics=2.0)   # bad -> reduce, cooldown starts
    assert s() == pytest.approx(0.5)
    s.step(metrics=2.0)   # cooling down: no further reduce
    s.step(metrics=2.0)
    assert s() == pytest.approx(0.5)
