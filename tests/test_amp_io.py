"""AMP + io tests (reference style: test_amp_*.py, test_paddle_save_load)."""
import os
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_auto_cast_o1_white_black():
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    m = nn.Linear(8, 8)
    with paddle.amp.auto_cast(level="O1"):
        y = m(x)
        assert str(y.dtype) == "bfloat16"
        s = paddle.nn.functional.softmax(y)
        # blacklisted op computes in fp32
        assert str(s.dtype) == "float32"
    y2 = m(x)
    assert str(y2.dtype) == "float32"


def test_auto_cast_o2():
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    with paddle.amp.auto_cast(level="O2"):
        y = x + x   # even non-white ops cast under O2
        assert str(y.dtype) == "bfloat16"


def test_grad_scaler_skips_on_inf():
    m = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                   decr_every_n_nan_or_inf=1)
    w0 = m.weight.numpy().copy()
    x = paddle.to_tensor(np.full((2, 4), np.inf, "float32"))
    loss = paddle.mean(m(x))
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    # inf grads -> step skipped, scale halved
    np.testing.assert_array_equal(m.weight.numpy(), w0)
    assert scaler.get_loss_scaling() == 4.0

    m.clear_gradients()
    x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
    loss = paddle.mean(m(x))
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    assert not np.allclose(m.weight.numpy(), w0)


def test_grad_scaler_unscales_correctly():
    m = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=m.parameters())
    x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
    # unscaled reference grad
    loss = paddle.mean(m(x))
    loss.backward()
    ref = m.weight.grad.numpy().copy()
    m.clear_gradients()
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    scaled = scaler.scale(paddle.mean(m(x)))
    scaled.backward()
    scaler.unscale_(opt)
    np.testing.assert_allclose(m.weight.grad.numpy(), ref, rtol=1e-5)


def test_save_load_state_dict(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    p = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), p)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict(paddle.load(p))
    x = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_save_load_optimizer_state(tmp_path):
    m = nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters())
    x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
    paddle.mean(m(x)).backward()
    opt.step()
    p = str(tmp_path / "opt.pdopt")
    paddle.save(opt.state_dict(), p)
    state = paddle.load(p)
    opt2 = paddle.optimizer.Adam(learning_rate=0.01,
                                 parameters=m.parameters())
    opt2.set_state_dict(state)
    assert opt2.state_dict()["@step"] == opt.state_dict()["@step"]


def test_load_return_numpy(tmp_path):
    p = str(tmp_path / "t.pdtensor")
    t = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    paddle.save({"t": t}, p)
    out = paddle.load(p, return_numpy=True)
    assert isinstance(out["t"], np.ndarray)
    np.testing.assert_array_equal(out["t"], t.numpy())


def test_auto_cast_decorator_keeps_custom_lists():
    @paddle.amp.auto_cast(custom_white_list=["softmax"], level="O1")
    def f(x):
        return F.softmax(x)

    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    out = f(x)
    # softmax moved to the white list -> computed in bf16
    assert str(out.dtype) == "bfloat16"


def test_reference_format_pdparams_loads(tmp_path):
    """A reference-produced .pdparams (plain pickled {name: ndarray})
    must load and apply without conversion (MIGRATING.md contract)."""
    import pickle
    import paddle_tpu.nn as nn
    ref = {"0.weight": np.random.RandomState(0).randn(4, 8).astype("float32"),
           "0.bias": np.zeros(8, "float32")}
    path = tmp_path / "refmt.pdparams"
    with open(path, "wb") as f:
        pickle.dump(ref, f, protocol=2)
    state = paddle.load(str(path))
    m = nn.Sequential(nn.Linear(4, 8))
    m.set_state_dict(state)
    np.testing.assert_allclose(m.state_dict()["0.weight"].numpy(),
                               ref["0.weight"])
