"""Multi-replica serving tier tests (inference/router.py, ISSUE 7).

The live tier fixture is EXPENSIVE on this 1-core host (two replica
subprocesses, cold XLA compiles shared through the executable store),
so it is module-scoped and every integration test rides the same two
replicas. Deterministic routing/autoscaler decisions are unit-tested
against fake replicas — the live tests cover the chaos paths: injected
forward faults, kill -9 mid-traffic, and the store-warm rolling
restart (ZERO successor compiles, counter-asserted via /healthz).
"""
import io
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.distributed.resilience import FaultInjector
from paddle_tpu.inference.router import (Replica, ReplicaSpec, Router,
                                         single_device_child_env)

MODEL = {"kind": "gpt", "vocab_size": 128, "hidden_size": 32,
         "num_layers": 1, "num_heads": 2, "max_seq_len": 64}
ENGINE = {"slots": 2, "max_len": 48, "cache_dtype": "float32",
          "prefill_buckets": [8], "tick_tokens": 2}

# replica children are single-device serving processes: drop the test
# harness's 8-virtual-device flag, keep cpu
_child_env = single_device_child_env


@pytest.fixture(scope="module")
def tier(tmp_path_factory):
    store = str(tmp_path_factory.mktemp("tier_store"))
    spec = ReplicaSpec(MODEL, ENGINE, warmup=True, drain_s=10.0, seed=0,
                       env=_child_env())
    router = Router(spec, replicas=2, poll_s=0.25, deadline_s=60.0,
                    exec_store_dir=store)
    router.start()
    assert router.wait_ready(2, timeout=240), router.replicas()
    yield router
    router.stop()


def _gen(router, ids, n=6, timeout=90):
    req = urllib.request.Request(
        f"http://{router.host}:{router.port}/generate",
        json.dumps({"input_ids": ids, "max_new_tokens": n}).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---------------------------------------------------------------------------
# deterministic routing decisions (fake replicas, no processes)
# ---------------------------------------------------------------------------

class _FakeProc:
    pid = 0

    def __init__(self, alive=True):
        self._alive = alive

    def poll(self):
        return None if self._alive else 1


def _fake_replica(name, state="ready", inflight=0, queued=0,
                  ejected_for=0.0, draining=False, alive=True):
    r = Replica(name, _FakeProc(alive), f"/nonexistent/{name}.port",
                f"/nonexistent/{name}.log", "127.0.0.1")
    r.port = 1
    r.state = state
    r.inflight = inflight
    r.draining = draining
    r.health = {"engine": {"queued": queued, "active": 0}}
    if ejected_for:
        r.ejected_until = time.monotonic() + ejected_for
    return r


@pytest.fixture()
def bare_router(tmp_path):
    """A Router that never spawned anything — for decision-logic tests
    (its HTTP socket binds but no thread serves it)."""
    spec = ReplicaSpec(MODEL, ENGINE, env=_child_env())
    r = Router(spec, replicas=2, min_replicas=1, max_replicas=3,
               poll_s=0.1, workdir=str(tmp_path), scale_cycles=2,
               scale_cooldown_s=0.0)
    yield r
    r.httpd.server_close()


def test_pick_skips_warming_ejected_draining_dead(bare_router):
    ready = _fake_replica("ready1")
    skips = [_fake_replica("warm1", state="warming"),
             _fake_replica("eject1", ejected_for=30.0),
             _fake_replica("drain1", draining=True),
             _fake_replica("unready1", state="unready"),
             _fake_replica("unreach1", state="unreachable"),
             _fake_replica("dead1", alive=False)]
    bare_router._replicas = skips + [ready]
    for _ in range(5):
        assert bare_router._pick(set()) is ready
    # exclusion honored even when it leaves nothing
    assert bare_router._pick({"ready1"}) is None


def test_pick_prefers_least_loaded(bare_router):
    a = _fake_replica("a", inflight=2)
    b = _fake_replica("b", inflight=0, queued=1)
    c = _fake_replica("c", inflight=0, queued=4)
    bare_router._replicas = [a, b, c]
    assert bare_router._pick(set()) is b
    b.inflight = 5
    assert bare_router._pick(set()) is c


def test_circuit_breaker_ejects_after_streak(bare_router):
    rep = _fake_replica("r")
    bare_router._replicas = [rep]
    for _ in range(bare_router.breaker_threshold - 1):
        bare_router._note_failure(rep)
    assert bare_router._pick(set()) is rep          # still under streak
    bare_router._note_failure(rep)
    assert rep.ejected_until > time.monotonic()     # ejected
    assert bare_router._pick(set()) is None
    assert bare_router.stats_counters["ejections"] == 1
    rep.ejected_until = 0.0                          # cooldown lapsed
    assert bare_router._pick(set()) is rep


def test_autoscale_up_on_sustained_queue_and_down_on_idle(bare_router):
    spawned, retired = [], []
    bare_router._spawn_replica = lambda: spawned.append(1)
    bare_router._terminate = \
        lambda rep, drain_timeout=0.0: retired.append(rep.name)
    busy = [_fake_replica("a", queued=3), _fake_replica("b", queued=2)]
    bare_router._replicas = list(busy)
    bare_router._autoscale()                 # streak 1 of scale_cycles=2
    assert not spawned
    bare_router._autoscale()                 # sustained pressure: scale up
    assert len(spawned) == 1
    assert bare_router.stats_counters["scale_ups"] == 1
    # idle: scale down to min_replicas, newest first, drained
    for r in busy:
        r.health = {"engine": {"queued": 0, "active": 0}}
    busy[1].spawned_at = busy[0].spawned_at + 1
    bare_router._autoscale()
    bare_router._autoscale()
    time.sleep(0.1)                          # retire runs on a thread
    assert retired == ["b"]
    assert bare_router.stats_counters["scale_downs"] == 1


def test_autoscale_respects_cooldown(bare_router):
    bare_router.scale_cooldown_s = 3600.0
    bare_router._last_scale = time.monotonic()
    spawned = []
    bare_router._spawn_replica = lambda: spawned.append(1)
    bare_router._replicas = [_fake_replica("a", queued=9)]
    for _ in range(5):
        bare_router._autoscale()
    assert not spawned


# ---------------------------------------------------------------------------
# live tier (module fixture): identity, chaos, rolling restart
# ---------------------------------------------------------------------------

@pytest.mark.timeout(280)
def test_tier_healthz_and_identity_vs_direct_engine(tier):
    code, body, _ = _gen(tier, [1, 2, 3, 4], n=8)
    assert code == 200, body
    assert body["served_by"] in {r["name"] for r in tier.replicas()}
    # the replica's generation accounting rides the response body
    # through the router UNCHANGED (ISSUE 13 satellite): no eos here,
    # so every requested token was actually generated. Speculative
    # engines add tokens_drafted/tokens_accepted the same way
    # (tests/test_speculative.py covers those fields end-to-end).
    assert body["tokens_generated"] == 8

    # tier healthz names every replica with occupancy detail
    with urllib.request.urlopen(
            f"http://{tier.host}:{tier.port}/healthz", timeout=10) as r:
        h = json.loads(r.read())
    assert h["ready_replicas"] == 2 and h["tier"]
    assert all("queued" in rep and "state" in rep
               for rep in h["replicas"])

    # greedy tokens through the tier == a direct in-process engine call
    # over the same seed/spec (the engine's token-identity oracle
    # composed through the fleet)
    from paddle_tpu.framework import random as _rng
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(
        **{k: v for k, v in MODEL.items() if k != "kind"}))
    with ContinuousBatchingEngine(
            model, **{**ENGINE,
                      "prefill_buckets": tuple(ENGINE["prefill_buckets"])}
            ) as eng:
        direct = eng.generate([1, 2, 3, 4], max_new_tokens=8).tolist()
    assert body["tokens"] == direct


def test_routing_skips_ejected_replica_live(tier):
    reps = tier._replicas
    assert len(reps) == 2
    victim, survivor = reps[0], reps[1]
    victim.ejected_until = time.monotonic() + 30.0
    try:
        for _ in range(3):
            code, body, _ = _gen(tier, [5, 6], n=4)
            assert code == 200, body
            assert body["served_by"] == survivor.name
    finally:
        victim.ejected_until = 0.0


def test_circuit_breaker_recovery_reenters_rotation(tier):
    """ISSUE 15 satellite: the breaker's RECOVERY half. A streak-
    ejected replica must re-enter the rotation once its
    PADDLE_TPU_TIER_EJECT_S cooldown lapses — routable again with NO
    reset or respawn — and serve output token-identical to the
    pre-ejection tier (only ejection was covered until now)."""
    code, oracle, _ = _gen(tier, [11, 3, 5], n=5)
    assert code == 200, oracle
    reps = tier._replicas
    victim = next(r for r in reps if r.name == oracle["served_by"])
    other = next(r for r in reps if r is not victim)
    ejections = tier.stats_counters["ejections"]
    # trip the REAL breaker (streak of io-class failures)
    for _ in range(tier.breaker_threshold):
        tier._note_failure(victim)
    assert tier.stats_counters["ejections"] == ejections + 1
    assert victim.ejected_until > time.monotonic()
    assert not victim.routable(time.monotonic())
    try:
        # during the cooldown every request lands on the other replica
        code, body, _ = _gen(tier, [11, 3, 5], n=5)
        assert code == 200 and body["served_by"] == other.name
        assert body["tokens"] == oracle["tokens"]
        # shorten the breaker's own window rather than sleeping the
        # full eject_s — the LAPSE semantics are what is under test
        victim.ejected_until = time.monotonic() + 0.3
        time.sleep(0.35)
        assert victim.routable(time.monotonic())    # re-entered
        # force the next pick to the recovered replica and prove it
        # serves token-identical output (no reset happened: same
        # process, same warm engine, same greedy tokens)
        other.ejected_until = time.monotonic() + 30.0
        code, body, _ = _gen(tier, [11, 3, 5], n=5)
        assert code == 200, body
        assert body["served_by"] == victim.name
        assert body["tokens"] == oracle["tokens"]
    finally:
        other.ejected_until = 0.0
        victim.ejected_until = 0.0
        victim.failure_streak = 0


def test_retry_on_different_replica_after_injected_fault(tier):
    before = tier.stats_counters["retries"]
    with FaultInjector({"router_forward": 1}):
        code, body, _ = _gen(tier, [7, 8, 9], n=4)
    assert code == 200, body       # the retry landed elsewhere
    assert tier.stats_counters["retries"] >= before + 1


@pytest.mark.timeout(280)
def test_kill9_mid_traffic_clean_outcomes_then_recovery(tier):
    """kill -9 a replica under concurrent traffic: every request ends
    in engine tokens (200, possibly via a different-replica retry) or
    a clean retryable 503 — zero resets, zero hangs — and the tier
    respawns back to full strength."""
    respawns_before = tier.stats_counters["respawns"]
    results, errors = [], []

    def client(i):
        try:
            results.append(_gen(tier, [1 + i, 2, 3], n=24, timeout=90))
        except Exception as e:   # noqa: BLE001 — a reset/hang is a FAIL
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    victim_pid = tier.replicas()[0]["pid"]
    os.kill(victim_pid, signal.SIGKILL)
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors                      # no resets, no hangs
    assert len(results) == 6
    for code, body, _ in results:
        if code == 200:
            assert len(body["tokens"]) == 3 + 24
        else:                                      # clean retryable 503
            assert code == 503, body
            assert float(body["retry_after_s"]) > 0, body
    # recovery: the control loop respawns the dead replica
    assert tier.wait_ready(2, timeout=120), tier.replicas()
    assert tier.stats_counters["respawns"] >= respawns_before + 1
    code, body, _ = _gen(tier, [1, 2], n=4)
    assert code == 200, body


@pytest.mark.timeout(280)
def test_rolling_restart_store_warm_zero_compiles(tier):
    """Rolling restart under traffic: every replica is replaced, the
    successors AOT-warm from the shared executable store and reach
    ready with ZERO XLA compiles (counter-asserted via /healthz), and
    greedy tokens are unchanged across the restart."""
    code, before_body, _ = _gen(tier, [4, 4, 4], n=6)
    assert code == 200
    pids_before = {r["pid"] for r in tier.replicas()}

    stop_traffic = threading.Event()
    mismatches = []

    def traffic():
        while not stop_traffic.is_set():
            c, b, _ = _gen(tier, [4, 4, 4], n=6)
            if c == 200 and b["tokens"] != before_body["tokens"]:
                mismatches.append(b["tokens"])
            time.sleep(0.05)

    t = threading.Thread(target=traffic)
    t.start()
    try:
        res = tier.rolling_restart(ready_timeout=180)
    finally:
        stop_traffic.set()
        t.join(timeout=60)
    assert res["ok"], res
    assert len(res["replaced"]) == 2
    assert not mismatches          # token-identical across the restart

    live = [r for r in tier.replicas() if not r["draining"]]
    assert {r["pid"] for r in live}.isdisjoint(pids_before)
    for r in live:
        with urllib.request.urlopen(
                f"http://{tier.host}:{r['port']}/healthz",
                timeout=10) as resp:
            h = json.loads(resp.read())
        assert h["compilation"]["xla_compiles"] == 0, (r["name"], h)

    code, after_body, _ = _gen(tier, [4, 4, 4], n=6)
    assert code == 200 and after_body["tokens"] == before_body["tokens"]


def test_tier_truthful_503_when_no_replica_admits(tier):
    """Both replicas ejected: the tier answers a truthful retryable
    503 with Retry-After instead of hanging or guessing."""
    reps = list(tier._replicas)
    saved = [(r, r.ejected_until) for r in reps]
    for r in reps:
        r.ejected_until = time.monotonic() + 30.0
    try:
        code, body, hdr = _gen(tier, [1], n=2, timeout=30)
        assert code == 503, body
        assert body["error"] == "no_replica_ready"
        assert float(body["retry_after_s"]) > 0
        assert int(hdr["Retry-After"]) >= 1
    finally:
        for r, prev in saved:
            r.ejected_until = prev
    assert tier.wait_ready(2, timeout=30)
    code, _, _ = _gen(tier, [1], n=2)
    assert code == 200


# ---------------------------------------------------------------------------
# crash-loop governance: escalating respawn backoff + give-up (ISSUE 11)
# ---------------------------------------------------------------------------

def test_respawn_governor_escalates_then_gives_up():
    from paddle_tpu.distributed.resilience import RetryPolicy
    from paddle_tpu.inference.router import RespawnGovernor
    now = [100.0]
    gov = RespawnGovernor(
        budget=3, window_s=10.0,
        policy=RetryPolicy(max_attempts=8, base_delay=1.0,
                           multiplier=2.0, max_delay=8.0, jitter=0.0),
        clock=lambda: now[0])
    # deaths at startup escalate on the deterministic schedule
    assert gov.note_death(0.5, became_ready=False) == 101.0
    assert gov.note_death(0.5, became_ready=False) == 102.0
    assert gov.note_death(0.5, became_ready=False) == 104.0
    # budget burned: the respawn is abandoned (None = give up)
    assert gov.note_death(0.5, became_ready=False) is None
    assert gov.note_death(0.5, became_ready=False) is None
    # a replica surviving past the window clears the streak
    gov.note_stable()
    assert gov.note_death(0.5, became_ready=False) == 101.0


def test_respawn_governor_slow_death_resets_streak():
    from paddle_tpu.inference.router import RespawnGovernor
    gov = RespawnGovernor(budget=2, window_s=5.0, clock=lambda: 50.0)
    gov.note_death(0.1, became_ready=False)
    gov.note_death(0.1, became_ready=False)
    assert gov.streak == 2
    # a replica that became ready AND outlived the window is a normal
    # death (rolling hardware, OOM after hours): immediate respawn
    assert gov.note_death(3600.0, became_ready=True) == 50.0
    assert gov.streak == 0


def test_respawn_governor_never_ready_counts_fast_even_if_old():
    from paddle_tpu.inference.router import RespawnGovernor
    gov = RespawnGovernor(budget=1, window_s=5.0, clock=lambda: 0.0)
    # wedged-at-startup replica killed by the unreachable path after
    # minutes: it never served, so it still extends the crash streak
    gov.note_death(600.0, became_ready=False)
    assert gov.streak == 1


def test_crash_loops_surfaced_in_stats_and_healthz(bare_router):
    assert "crash_loops" in bare_router.stats_counters
    body = bare_router.stats()
    assert body["stats"]["crash_loops"] == 0


# ---------------------------------------------------------------------------
# work-conserving recovery verdicts (scripted attempts, no processes)
# ISSUE 15 hardening: the coordinator's reap/relaunch logic is pure
# decision-making over attempt outcomes — drive it with scripted
# stand-ins for _StreamAttempt instead of live replicas.
# ---------------------------------------------------------------------------

def test_retry_after_hint_malformed_degrades_to_none():
    """A replica's retry_after_s hint flows into RetryPolicy.sleep and
    send_json arithmetic — a malformed value (anything answering on
    the replica's port can send one) must degrade to None, never
    crash the forward path."""
    from paddle_tpu.inference.router import _retry_after_hint
    assert _retry_after_hint({"retry_after_s": 2.5}) == 2.5
    assert _retry_after_hint({"retry_after_s": "3"}) == 3.0
    assert _retry_after_hint({}) is None
    assert _retry_after_hint({"retry_after_s": "soon"}) is None
    assert _retry_after_hint({"retry_after_s": None}) is None
    assert _retry_after_hint({"retry_after_s": [1]}) is None


def test_hedge_budget_caps_concurrent_backups(bare_router):
    """Tier-wide hedge budget: at most hedge_frac of the live
    journaled requests (floor 1) may run a backup at once — a
    saturated tier where every queued request looks silent must not
    hedge itself into double load."""
    r = bare_router
    r.hedge_frac = 0.25
    r._journaled = 20
    grabbed = 0
    while r._reserve_hedge():
        grabbed += 1
        assert grabbed <= 5, "cap must be frac * journaled"
    assert grabbed == 5
    r._release_hedge()
    assert r._reserve_hedge()       # a freed slot is reusable
    # floor: a lone straggler always clears the budget
    r2 = bare_router
    r2._hedges_live = 0
    r2._journaled = 1
    assert r2._reserve_hedge()
    assert not r2._reserve_hedge()


def _scripted_attempts(script):
    """A _StreamAttempt stand-in running ``script[seq]`` in the
    attempt thread (coordinator-visible attrs mirrored exactly). A
    behavior that raises books an io-failure so the coordinator
    terminates instead of waiting out the deadline."""
    import threading as _threading

    class _Scripted(_threading.Thread):
        made = []

        def __init__(self, router, rep, st, base, deadline_at,
                     is_hedge, seq):
            super().__init__(daemon=True)
            self.router, self.rep, self.j = router, rep, st
            self.base, self.is_hedge = int(base), bool(is_hedge)
            self.rid = f"scripted.{seq}"
            self.status = "running"
            self.reaped = False
            self.kind = None
            self.reason = ""
            self.code = 0
            self.body = None
            self.retry_after = None
            self.done_body = None
            self.streamed = True
            self.got = 0
            self._behave = script[min(seq, len(script) - 1)]
            _Scripted.made.append(self)

        def run(self):
            try:
                self._behave(self)
            except Exception as e:   # noqa: BLE001 — surface to the
                self.kind = "io"     # coordinator as a failure
                self.reason = f"scripted: {e}"
                self.status = "failed"
            with self.j.cond:
                self.j.cond.notify_all()

        def cancel(self):
            pass
    return _Scripted


def _finish(a, prompt, full_new):
    """Terminal behavior: extend the journal past ``a.base`` and land
    the done body in the replica's own frame (residual prompt)."""
    a.j.extend(a.base, full_new[a.base:], a.rep.name)
    a.got = len(full_new) - a.base
    a.done_body = {"tokens": list(prompt) + list(full_new),
                   "prompt_len": len(prompt) + a.base,
                   "new_tokens": len(full_new) - a.base,
                   "tokens_generated": len(full_new) - a.base,
                   # the replica echoes the ATTEMPT's derived id —
                   # the coordinator must restore the client's
                   "request_id": a.rid}
    a.status = "done"


def test_coordinator_keeps_relaunching_until_a_replica_returns(
        bare_router, monkeypatch):
    """A journaled request whose replica died while NO other replica
    is routable must keep retrying launch() and resume the moment the
    respawn is pickable — not idle to the deadline (the relaunch
    intent persists across poll iterations)."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0                  # hedging off: deterministic seqs
    prompt, full = [1, 2, 3], [11, 12, 13, 14]
    rep = _fake_replica("fr")
    picks = {"n": 0}

    def pick(exclude):
        picks["n"] += 1
        # launch 1 lands; then the tier is replica-less for 5 picks
        # (the dead primary reaped, the respawn still warming); then
        # the respawn is routable again
        return None if 2 <= picks["n"] <= 6 else rep

    monkeypatch.setattr(r, "_pick", pick)

    def die_with_progress(a):
        a.j.extend(0, full[:2], a.rep.name)
        a.kind, a.reason = "io", "stream truncated"
        a.status = "failed"

    def resume(a):
        assert a.base == 2, "resume must seed the journaled prefix"
        _finish(a, prompt, full)

    cls = _scripted_attempts([die_with_progress, resume])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    t0 = time.monotonic()
    code, body, _ = r._forward_recovering(prompt, 4, None, 0, 8.0,
                                          "rid-gap", t0)
    assert code == 200, body
    assert body["tokens"] == prompt + full
    assert body["prompt_len"] == len(prompt)
    assert body["tokens_generated"] == 4
    assert body["request_id"] == "rid-gap", \
        "winner path must restore the client's request id"
    assert body["recovered"] == 1
    assert picks["n"] >= 7, "launch() must keep retrying the pick"
    assert time.monotonic() - t0 < 6.0, "must beat the deadline"


def test_token_mismatch_falls_back_to_from_scratch_rerun(
        bare_router, monkeypatch):
    """A resumed attempt that mismatches the journal must relaunch
    from scratch (journal VERIFIES, not seeds) — retrying the resume
    at the same base would mismatch forever and fail the request."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0
    prompt, full = [7, 8], [21, 22, 23]
    rep = _fake_replica("fr")
    monkeypatch.setattr(r, "_pick", lambda exclude: rep)

    def mismatch_after_progress(a):
        a.j.extend(0, full[:2], a.rep.name)
        a.kind, a.reason = "mismatch", "token mismatch vs journal"
        a.status = "failed"

    def rerun(a):
        assert a.base == 0, "mismatch must force a from-scratch rerun"
        _finish(a, prompt, full)

    cls = _scripted_attempts([mismatch_after_progress, rerun])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    code, body, _ = r._forward_recovering(prompt, 3, None, 0, 8.0,
                                          "rid-mm", time.monotonic())
    assert code == 200, body
    assert body["tokens"] == prompt + full
    assert r.stats_counters["resume_fallbacks"] >= 1


def test_sampling_tier_never_seeds_a_resume(bare_router, monkeypatch):
    """do_sample engines roll tok0 from the raw key at admit but
    fold_in(key, pos) mid-decode, so a seeded resume re-rolls
    different tokens — a sampling tier's relaunches must all run from
    scratch (verify-only journal) from the start."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0
    r.spec.engine["do_sample"] = True
    prompt, full = [4, 5], [31, 32]
    rep = _fake_replica("fr")
    monkeypatch.setattr(r, "_pick", lambda exclude: rep)

    def die_with_progress(a):
        assert a.base == 0
        a.j.extend(0, full[:1], a.rep.name)
        a.kind, a.reason = "io", "stream truncated"
        a.status = "failed"

    def rerun(a):
        assert a.base == 0, "sampling tier must never seed a resume"
        _finish(a, prompt, full)

    cls = _scripted_attempts([die_with_progress, rerun])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    try:
        code, body, _ = r._forward_recovering(prompt, 2, None, 0, 8.0,
                                              "rid-samp",
                                              time.monotonic())
    finally:
        r.spec.engine.pop("do_sample", None)
    assert code == 200, body
    assert body["tokens"] == prompt + full


# ---------------------------------------------------------------------------
# streaming-first QoS front (ISSUE 16): weighted-fair admission with
# truthful per-class degradation, the client NDJSON relay over the
# journal, TTFT hedging, and prefix-affinity _pick
# ---------------------------------------------------------------------------

def test_qos_dispatch_strict_priority_then_class_order():
    """Strict-priority dispatch: with the tier saturated, queued
    waiters drain interactive -> standard -> batch regardless of
    arrival order."""
    from paddle_tpu.inference.router import _QosScheduler
    s = _QosScheduler(capacity=1, queue_limit=8, starvation_s=60.0)
    assert s.try_acquire("seed", "standard", 5.0) == ("admitted", None)
    order, threads = [], []

    def client(tenant, qcls):
        state, _ = s.try_acquire(tenant, qcls, 30.0)
        assert state == "admitted"
        order.append(tenant)
        s.release(tenant, qcls, tokens=0)

    for tenant, qcls in [("tb", "batch"), ("ts", "standard"),
                         ("ti", "interactive")]:
        th = threading.Thread(target=client, args=(tenant, qcls))
        th.start()
        threads.append(th)
        deadline = time.monotonic() + 5.0
        while (s.snapshot()["waiting"] < len(threads)
               and time.monotonic() < deadline):
            time.sleep(0.01)
    s.release("seed", "standard", tokens=0)      # cascade the queue
    for th in threads:
        th.join(timeout=10)
    assert order == ["ti", "ts", "tb"]


def test_qos_token_charge_prefers_the_lighter_tenant():
    """Weighted-fair inside one class: the tenant that burned fewer
    journal-accounted tokens dispatches first even when the heavy
    tenant enqueued earlier (charge beats FIFO across tenants)."""
    from paddle_tpu.inference.router import _QosScheduler
    s = _QosScheduler(capacity=1, queue_limit=8, starvation_s=60.0)
    # hog burned 1000 tokens at weight 2 -> charge 500
    assert s.try_acquire("hog", "standard", 1.0)[0] == "admitted"
    s.release("hog", "standard", tokens=1000)
    assert s.try_acquire("seed", "standard", 1.0)[0] == "admitted"
    order, threads = [], []

    def client(tenant):
        state, _ = s.try_acquire(tenant, "standard", 30.0)
        assert state == "admitted"
        order.append(tenant)
        s.release(tenant, "standard", tokens=0)

    for tenant in ["hog", "sipper"]:             # hog enqueues FIRST
        th = threading.Thread(target=client, args=(tenant,))
        th.start()
        threads.append(th)
        deadline = time.monotonic() + 5.0
        while (s.snapshot()["waiting"] < len(threads)
               and time.monotonic() < deadline):
            time.sleep(0.01)
    s.release("seed", "standard", tokens=0)
    for th in threads:
        th.join(timeout=10)
    assert order == ["sipper", "hog"]


def test_qos_starvation_aging_overrides_class_policy():
    """A batch waiter older than starvation_s is served before a
    fresher interactive one — no class is starvable forever."""
    from paddle_tpu.inference.router import _QosScheduler
    now = [0.0]
    s = _QosScheduler(capacity=1, queue_limit=8, starvation_s=5.0,
                      clock=lambda: now[0])
    assert s.try_acquire("seed", "standard", 1.0)[0] == "admitted"
    order, threads = [], []

    def client(tenant, qcls):
        state, _ = s.try_acquire(tenant, qcls, 9999.0)
        assert state == "admitted"
        order.append(tenant)
        s.release(tenant, qcls, tokens=0)

    th = threading.Thread(target=client, args=("old-batch", "batch"))
    th.start()
    threads.append(th)
    deadline = time.monotonic() + 5.0
    while s.snapshot()["waiting"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    now[0] += 6.0                                # batch waiter ages out
    th = threading.Thread(target=client, args=("fresh-i", "interactive"))
    th.start()
    threads.append(th)
    deadline = time.monotonic() + 5.0
    while s.snapshot()["waiting"] < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    s.release("seed", "standard", tokens=0)
    for th in threads:
        th.join(timeout=10)
    assert order == ["old-batch", "fresh-i"]


def test_qos_retry_after_tracks_observed_drain_rate():
    """Honest Retry-After: sheds answer (work ahead at this priority
    + 1) / the drain-rate EWMA — per class, never a blanket constant
    (a higher class sees LESS work ahead, so a smaller hint)."""
    from paddle_tpu.inference.router import _QosScheduler
    now = [0.0]
    s = _QosScheduler(capacity=1, queue_limit=1, starvation_s=60.0,
                      clock=lambda: now[0])
    for _ in range(3):                           # teach a 2/s drain
        assert s.try_acquire("t", "standard", 1.0)[0] == "admitted"
        now[0] += 0.5
        s.release("t", "standard", tokens=4)
    assert s.snapshot()["drain_per_s"] == pytest.approx(2.0)
    assert s.try_acquire("t", "standard", 1.0)[0] == "admitted"
    done = threading.Event()

    def blocked_batch():
        s.try_acquire("b1", "batch", 9999.0)
        s.release("b1", "batch", tokens=0)
        done.set()

    th = threading.Thread(target=blocked_batch)
    th.start()
    deadline = time.monotonic() + 5.0
    while s.snapshot()["waiting"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    # batch queue (cap queue_limit * weight = 1) is full: shed with
    # ahead = 1 inflight + 1 same-priority waiter -> (2+1)/2 = 1.5s
    state, ra = s.try_acquire("b2", "batch", 5.0)
    assert state == "shed"
    assert ra == pytest.approx(1.5)
    # an interactive request queues (its class has room) and, burning
    # a zero budget, times out with a SMALLER hint: only the inflight
    # request is ahead of priority 0 -> (1+1)/2 = 1.0s
    state, ra_i = s.try_acquire("i1", "interactive", 0.0)
    assert state == "timeout"
    assert ra_i == pytest.approx(1.0)
    assert ra_i < ra
    s.release("t", "standard", tokens=0)
    assert done.wait(timeout=10)
    th.join(timeout=10)


def test_pick_prefix_affinity_blends_overlap_with_load(bare_router):
    from paddle_tpu.inference.paging import chain_hashes
    r = bare_router
    prompt = list(range(12))
    hashes = chain_hashes(prompt, 4)             # 3 complete pages
    assert len(hashes) == 3
    warm = _fake_replica("warm", inflight=1)
    warm.prefix_fps = frozenset(hashes)
    cold = _fake_replica("cold", inflight=0)
    r._replicas = [cold, warm]
    # load-only (no hashes): least-loaded wins
    assert r._pick(set()) is cold
    # affinity blend: 3 cached pages x 0.5 outweigh one inflight
    assert r._pick(set(), hashes) is warm
    # overlap is the longest chain PREFIX: holding only a later hash
    # (parent missing) scores zero
    broken = _fake_replica("broken", inflight=0)
    broken.prefix_fps = frozenset(hashes[1:])
    r._replicas = [broken, warm]
    assert r._pick(set(), hashes) is warm
    # affinity off: back to pure load
    r.affinity_w = 0.0
    r._replicas = [cold, warm]
    assert r._pick(set(), hashes) is cold


class _FakeStreamHandler:
    """Just enough of BaseHTTPRequestHandler for _ClientRelay."""

    def __init__(self, wfile=None):
        self.wfile = wfile if wfile is not None else io.BytesIO()
        self.status = None
        self.sent_headers = {}
        self.close_connection = False

    def send_response(self, code):
        self.status = code

    def send_header(self, k, v):
        self.sent_headers[k] = v

    def end_headers(self):
        pass


class _ExplodingFile:
    """A client that hung up: every write raises."""

    def write(self, b):
        raise BrokenPipeError("client went away")

    def flush(self):
        pass


def test_stream_failover_splice_byte_exact(bare_router, monkeypatch):
    """Mid-stream failover through the client relay: the primary dies
    after streaming 3 tokens, the resume carries on from the journal
    frontier — the client's NDJSON holds every token exactly once
    (zero loss, zero duplicates) plus one terminal done body."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0
    r.ttft_hedge_s = 0.0
    prompt, full = [1, 2, 3], [41, 42, 43, 44, 45]
    rep = _fake_replica("fr")
    monkeypatch.setattr(r, "_pick", lambda exclude: rep)

    def die_with_progress(a):
        a.j.extend(0, full[:3], a.rep.name)
        time.sleep(0.1)       # let the relay drain the first block
        a.kind, a.reason = "io", "stream truncated"
        a.status = "failed"

    def resume(a):
        assert a.base == 3, "resume must splice AT the journal frontier"
        _finish(a, prompt, full)

    cls = _scripted_attempts([die_with_progress, resume])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    h = _FakeStreamHandler()
    relay = router_mod._ClientRelay(h, "rid-stream")
    code, body, _ = r._forward_recovering(prompt, 5, None, 0, 8.0,
                                          "rid-stream",
                                          time.monotonic(), relay=relay)
    assert code == 200, body
    assert h.status == 200
    assert h.sent_headers["Content-Type"] == "application/x-ndjson"
    lines = [json.loads(ln) for ln in h.wfile.getvalue().splitlines()]
    streamed = [t for ln in lines if "t" in ln for t in ln["t"]]
    assert streamed == full     # byte-exact splice across the failover
    dones = [ln for ln in lines if "done" in ln]
    assert len(dones) == 1 and "done" in lines[-1]
    assert dones[0]["done"]["tokens"] == prompt + full
    assert dones[0]["done"]["request_id"] == "rid-stream"
    assert dones[0]["done"]["recovered"] == 1
    assert dones[0]["done"]["tokens_generated"] == 5


def test_stream_error_reaches_client_as_err_record(bare_router,
                                                   monkeypatch):
    """A mid-stream terminal failure must land on the NDJSON stream as
    a truthful err record (code + retry hint), never a bare EOF."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0
    r.ttft_hedge_s = 0.0
    prompt, full = [5, 5], [71, 72, 73, 74]

    def die_then_nothing(a):
        a.j.extend(0, full[:2], a.rep.name)
        a.kind, a.reason = "io", "stream truncated"
        a.status = "failed"

    rep = _fake_replica("fr")
    picks = {"n": 0}

    def pick(exclude):
        picks["n"] += 1
        return rep if picks["n"] == 1 else None   # no replica to resume

    monkeypatch.setattr(r, "_pick", pick)
    cls = _scripted_attempts([die_then_nothing])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    h = _FakeStreamHandler()
    relay = router_mod._ClientRelay(h, "rid-err")
    code, body, ra = r._forward_recovering(prompt, 4, None, 0, 2.0,
                                           "rid-err",
                                           time.monotonic(), relay=relay)
    assert code == 503
    lines = [json.loads(ln) for ln in h.wfile.getvalue().splitlines()]
    assert [t for ln in lines if "t" in ln for t in ln["t"]] == full[:2]
    err = lines[-1]["err"]
    assert err["code"] == 503
    assert err["retry_after_s"] == ra


def test_stream_client_disconnect_cancels_all_attempts(bare_router,
                                                       monkeypatch):
    """Client hangs up mid-stream: the coordinator cancels every live
    attempt (slot retired on the owning replica), books the disconnect,
    and accounts the tokens the journal actually produced."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0
    r.ttft_hedge_s = 0.0
    prompt, full = [9, 9], [51, 52, 53, 54]

    def progress_then_linger(a):
        a.j.extend(0, full[:2], a.rep.name)
        # stays "running": only the disconnect can end this request

    rep = _fake_replica("fr")
    monkeypatch.setattr(r, "_pick", lambda exclude: rep)
    cls = _scripted_attempts([progress_then_linger])
    cancelled = []
    cls.cancel = lambda self: cancelled.append(self.rid)
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    h = _FakeStreamHandler(wfile=_ExplodingFile())
    relay = router_mod._ClientRelay(h, "rid-gone")
    before = r.stats_counters["client_disconnects"]
    code, body, ra = r._forward_recovering(prompt, 4, None, 0, 8.0,
                                           "rid-gone",
                                           time.monotonic(), relay=relay)
    assert code == 499
    assert body["error"] == "client_disconnected"
    assert body["tokens_generated"] == 2
    assert r.stats_counters["client_disconnects"] == before + 1
    deadline = time.monotonic() + 5.0
    while not cancelled and time.monotonic() < deadline:
        time.sleep(0.01)
    assert cancelled, "live attempt must be cancelled on disconnect"


def test_stream_refusals_stay_plain_json(bare_router):
    """A stream request the journal cannot serve is refused BEFORE any
    NDJSON head is written — plain JSON 400/503, protocol intact."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    h = _FakeStreamHandler()
    relay = router_mod._ClientRelay(h, None)
    payload = json.dumps({"prompt": "opaque", "stream": True}).encode()
    code, body, _ = r.forward_generate(payload, deadline_s=2.0,
                                       relay=relay)
    assert code == 400 and body["error"] == "stream_requires_token_ids"
    assert not relay.started_http and h.status is None
    r.recovery = False           # journaling off on this tier
    h2 = _FakeStreamHandler()
    relay2 = router_mod._ClientRelay(h2, None)
    code, body, ra = r.forward_generate(payload, deadline_s=2.0,
                                        relay=relay2)
    assert code == 503 and body["error"] == "stream_unavailable"
    assert ra is not None and not relay2.started_http


def test_forward_generate_qos_gate_sheds_per_class(bare_router):
    """The QoS gate on the real forward path: overload sheds the LOW
    class with a per-class 429 + Retry-After while the high class
    keeps its queue spot; admitted requests release their slot."""
    from paddle_tpu.inference.router import _QosScheduler
    r = bare_router
    r.qos = _QosScheduler(capacity=1, queue_limit=1, starvation_s=60.0)
    assert r.qos.try_acquire("seed", "standard", 1.0)[0] == "admitted"

    def pay(tenant, qcls):
        return json.dumps({"input_ids": [1], "max_new_tokens": 1,
                           "tenant": tenant, "qos_class": qcls}).encode()

    results = []
    th = threading.Thread(target=lambda: results.append(
        r.forward_generate(pay("t1", "batch"), deadline_s=30.0)))
    th.start()
    deadline = time.monotonic() + 5.0
    while (r.qos.snapshot()["waiting"] < 1
           and time.monotonic() < deadline):
        time.sleep(0.01)
    # batch queue full -> truthful per-class 429
    code, body, ra = r.forward_generate(pay("t2", "batch"),
                                        deadline_s=5.0)
    assert code == 429 and body["error"] == "qos_shed"
    assert body["qos_class"] == "batch" and body["tenant"] == "t2"
    assert ra is not None and ra > 0
    assert r.stats_counters["qos_shed"] >= 1
    # interactive still has queue room: it QUEUES (timing out against
    # its own zero budget with the deadline face), never a 429
    code, body, ra_i = r.forward_generate(pay("t3", "interactive"),
                                          deadline_s=0.0)
    assert code == 503 and body["error"] == "deadline_exceeded"
    assert body["qos_class"] == "interactive"
    # release the seed slot: the queued batch request dispatches (no
    # replicas on a bare router -> clean 503) and RELEASES its slot
    r.qos.release("seed", "standard", tokens=0)
    th.join(timeout=15)
    assert results and results[0][0] == 503
    snap = r.qos.snapshot()
    assert snap["inflight"] == 0 and snap["waiting"] == 0
    assert r.stats_counters["qos_admitted"] >= 1


def test_ttft_budget_derivation(bare_router):
    r = bare_router
    r.ttft_hedge_s = 0
    assert r._ttft_budget() is None              # explicit 0 disables
    r.ttft_hedge_s = 1.5
    assert r._ttft_budget() == 1.5               # explicit wins
    r.ttft_hedge_s = -1.0
    b = r._ttft_budget()                         # cold-tier default
    assert b is not None and 0 < b <= max(2.0, r.deadline_s / 4.0)


def test_ttft_hedge_fires_on_admission_stall(bare_router, monkeypatch):
    """An admission stall (no FIRST token past the TTFT budget) hedges
    onto a second replica under the tier-wide budget — today's decode
    hedge only watches requests that already produced a token."""
    from paddle_tpu.inference import router as router_mod
    r = bare_router
    r.hedge_s = 0.0              # decode-stall hedge off
    r.ttft_hedge_s = 0.15        # tiny explicit TTFT budget
    prompt, full = [2, 2], [61, 62]
    reps = [_fake_replica("p"), _fake_replica("h")]

    def pick(exclude, prompt_hashes=None):
        for rep in reps:
            if rep.name not in exclude:
                return rep
        return None

    monkeypatch.setattr(r, "_pick", pick)

    def wedged_prefill(a):
        time.sleep(1.0)          # never produces a token

    def hedged(a):
        assert a.is_hedge and a.base == 0
        _finish(a, prompt, full)

    cls = _scripted_attempts([wedged_prefill, hedged])
    monkeypatch.setattr(router_mod, "_StreamAttempt", cls)
    t0 = time.monotonic()
    code, body, _ = r._forward_recovering(prompt, 2, None, 0, 8.0,
                                          "rid-ttft", t0)
    assert code == 200, body
    assert body["tokens"] == prompt + full
    assert body.get("hedged") is True
    assert r.stats_counters["ttft_hedges"] == 1
    assert r.stats_counters["hedge_wins"] >= 1
    assert time.monotonic() - t0 < 4.0, "hedge must beat the deadline"
