"""Multi-replica serving tier tests (inference/router.py, ISSUE 7).

The live tier fixture is EXPENSIVE on this 1-core host (two replica
subprocesses, cold XLA compiles shared through the executable store),
so it is module-scoped and every integration test rides the same two
replicas. Deterministic routing/autoscaler decisions are unit-tested
against fake replicas — the live tests cover the chaos paths: injected
forward faults, kill -9 mid-traffic, and the store-warm rolling
restart (ZERO successor compiles, counter-asserted via /healthz).
"""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.distributed.resilience import FaultInjector
from paddle_tpu.inference.router import (Replica, ReplicaSpec, Router,
                                         single_device_child_env)

MODEL = {"kind": "gpt", "vocab_size": 128, "hidden_size": 32,
         "num_layers": 1, "num_heads": 2, "max_seq_len": 64}
ENGINE = {"slots": 2, "max_len": 48, "cache_dtype": "float32",
          "prefill_buckets": [8], "tick_tokens": 2}

# replica children are single-device serving processes: drop the test
# harness's 8-virtual-device flag, keep cpu
_child_env = single_device_child_env


@pytest.fixture(scope="module")
def tier(tmp_path_factory):
    store = str(tmp_path_factory.mktemp("tier_store"))
    spec = ReplicaSpec(MODEL, ENGINE, warmup=True, drain_s=10.0, seed=0,
                       env=_child_env())
    router = Router(spec, replicas=2, poll_s=0.25, deadline_s=60.0,
                    exec_store_dir=store)
    router.start()
    assert router.wait_ready(2, timeout=240), router.replicas()
    yield router
    router.stop()


def _gen(router, ids, n=6, timeout=90):
    req = urllib.request.Request(
        f"http://{router.host}:{router.port}/generate",
        json.dumps({"input_ids": ids, "max_new_tokens": n}).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---------------------------------------------------------------------------
# deterministic routing decisions (fake replicas, no processes)
# ---------------------------------------------------------------------------

class _FakeProc:
    pid = 0

    def __init__(self, alive=True):
        self._alive = alive

    def poll(self):
        return None if self._alive else 1


def _fake_replica(name, state="ready", inflight=0, queued=0,
                  ejected_for=0.0, draining=False, alive=True):
    r = Replica(name, _FakeProc(alive), f"/nonexistent/{name}.port",
                f"/nonexistent/{name}.log", "127.0.0.1")
    r.port = 1
    r.state = state
    r.inflight = inflight
    r.draining = draining
    r.health = {"engine": {"queued": queued, "active": 0}}
    if ejected_for:
        r.ejected_until = time.monotonic() + ejected_for
    return r


@pytest.fixture()
def bare_router(tmp_path):
    """A Router that never spawned anything — for decision-logic tests
    (its HTTP socket binds but no thread serves it)."""
    spec = ReplicaSpec(MODEL, ENGINE, env=_child_env())
    r = Router(spec, replicas=2, min_replicas=1, max_replicas=3,
               poll_s=0.1, workdir=str(tmp_path), scale_cycles=2,
               scale_cooldown_s=0.0)
    yield r
    r.httpd.server_close()


def test_pick_skips_warming_ejected_draining_dead(bare_router):
    ready = _fake_replica("ready1")
    skips = [_fake_replica("warm1", state="warming"),
             _fake_replica("eject1", ejected_for=30.0),
             _fake_replica("drain1", draining=True),
             _fake_replica("unready1", state="unready"),
             _fake_replica("unreach1", state="unreachable"),
             _fake_replica("dead1", alive=False)]
    bare_router._replicas = skips + [ready]
    for _ in range(5):
        assert bare_router._pick(set()) is ready
    # exclusion honored even when it leaves nothing
    assert bare_router._pick({"ready1"}) is None


def test_pick_prefers_least_loaded(bare_router):
    a = _fake_replica("a", inflight=2)
    b = _fake_replica("b", inflight=0, queued=1)
    c = _fake_replica("c", inflight=0, queued=4)
    bare_router._replicas = [a, b, c]
    assert bare_router._pick(set()) is b
    b.inflight = 5
    assert bare_router._pick(set()) is c


def test_circuit_breaker_ejects_after_streak(bare_router):
    rep = _fake_replica("r")
    bare_router._replicas = [rep]
    for _ in range(bare_router.breaker_threshold - 1):
        bare_router._note_failure(rep)
    assert bare_router._pick(set()) is rep          # still under streak
    bare_router._note_failure(rep)
    assert rep.ejected_until > time.monotonic()     # ejected
    assert bare_router._pick(set()) is None
    assert bare_router.stats_counters["ejections"] == 1
    rep.ejected_until = 0.0                          # cooldown lapsed
    assert bare_router._pick(set()) is rep


def test_autoscale_up_on_sustained_queue_and_down_on_idle(bare_router):
    spawned, retired = [], []
    bare_router._spawn_replica = lambda: spawned.append(1)
    bare_router._terminate = \
        lambda rep, drain_timeout=0.0: retired.append(rep.name)
    busy = [_fake_replica("a", queued=3), _fake_replica("b", queued=2)]
    bare_router._replicas = list(busy)
    bare_router._autoscale()                 # streak 1 of scale_cycles=2
    assert not spawned
    bare_router._autoscale()                 # sustained pressure: scale up
    assert len(spawned) == 1
    assert bare_router.stats_counters["scale_ups"] == 1
    # idle: scale down to min_replicas, newest first, drained
    for r in busy:
        r.health = {"engine": {"queued": 0, "active": 0}}
    busy[1].spawned_at = busy[0].spawned_at + 1
    bare_router._autoscale()
    bare_router._autoscale()
    time.sleep(0.1)                          # retire runs on a thread
    assert retired == ["b"]
    assert bare_router.stats_counters["scale_downs"] == 1


def test_autoscale_respects_cooldown(bare_router):
    bare_router.scale_cooldown_s = 3600.0
    bare_router._last_scale = time.monotonic()
    spawned = []
    bare_router._spawn_replica = lambda: spawned.append(1)
    bare_router._replicas = [_fake_replica("a", queued=9)]
    for _ in range(5):
        bare_router._autoscale()
    assert not spawned


# ---------------------------------------------------------------------------
# live tier (module fixture): identity, chaos, rolling restart
# ---------------------------------------------------------------------------

@pytest.mark.timeout(280)
def test_tier_healthz_and_identity_vs_direct_engine(tier):
    code, body, _ = _gen(tier, [1, 2, 3, 4], n=8)
    assert code == 200, body
    assert body["served_by"] in {r["name"] for r in tier.replicas()}
    # the replica's generation accounting rides the response body
    # through the router UNCHANGED (ISSUE 13 satellite): no eos here,
    # so every requested token was actually generated. Speculative
    # engines add tokens_drafted/tokens_accepted the same way
    # (tests/test_speculative.py covers those fields end-to-end).
    assert body["tokens_generated"] == 8

    # tier healthz names every replica with occupancy detail
    with urllib.request.urlopen(
            f"http://{tier.host}:{tier.port}/healthz", timeout=10) as r:
        h = json.loads(r.read())
    assert h["ready_replicas"] == 2 and h["tier"]
    assert all("queued" in rep and "state" in rep
               for rep in h["replicas"])

    # greedy tokens through the tier == a direct in-process engine call
    # over the same seed/spec (the engine's token-identity oracle
    # composed through the fleet)
    from paddle_tpu.framework import random as _rng
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(
        **{k: v for k, v in MODEL.items() if k != "kind"}))
    with ContinuousBatchingEngine(
            model, **{**ENGINE,
                      "prefill_buckets": tuple(ENGINE["prefill_buckets"])}
            ) as eng:
        direct = eng.generate([1, 2, 3, 4], max_new_tokens=8).tolist()
    assert body["tokens"] == direct


def test_routing_skips_ejected_replica_live(tier):
    reps = tier._replicas
    assert len(reps) == 2
    victim, survivor = reps[0], reps[1]
    victim.ejected_until = time.monotonic() + 30.0
    try:
        for _ in range(3):
            code, body, _ = _gen(tier, [5, 6], n=4)
            assert code == 200, body
            assert body["served_by"] == survivor.name
    finally:
        victim.ejected_until = 0.0


def test_retry_on_different_replica_after_injected_fault(tier):
    before = tier.stats_counters["retries"]
    with FaultInjector({"router_forward": 1}):
        code, body, _ = _gen(tier, [7, 8, 9], n=4)
    assert code == 200, body       # the retry landed elsewhere
    assert tier.stats_counters["retries"] >= before + 1


@pytest.mark.timeout(280)
def test_kill9_mid_traffic_clean_outcomes_then_recovery(tier):
    """kill -9 a replica under concurrent traffic: every request ends
    in engine tokens (200, possibly via a different-replica retry) or
    a clean retryable 503 — zero resets, zero hangs — and the tier
    respawns back to full strength."""
    respawns_before = tier.stats_counters["respawns"]
    results, errors = [], []

    def client(i):
        try:
            results.append(_gen(tier, [1 + i, 2, 3], n=24, timeout=90))
        except Exception as e:   # noqa: BLE001 — a reset/hang is a FAIL
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    victim_pid = tier.replicas()[0]["pid"]
    os.kill(victim_pid, signal.SIGKILL)
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors                      # no resets, no hangs
    assert len(results) == 6
    for code, body, _ in results:
        if code == 200:
            assert len(body["tokens"]) == 3 + 24
        else:                                      # clean retryable 503
            assert code == 503, body
            assert float(body["retry_after_s"]) > 0, body
    # recovery: the control loop respawns the dead replica
    assert tier.wait_ready(2, timeout=120), tier.replicas()
    assert tier.stats_counters["respawns"] >= respawns_before + 1
    code, body, _ = _gen(tier, [1, 2], n=4)
    assert code == 200, body


@pytest.mark.timeout(280)
def test_rolling_restart_store_warm_zero_compiles(tier):
    """Rolling restart under traffic: every replica is replaced, the
    successors AOT-warm from the shared executable store and reach
    ready with ZERO XLA compiles (counter-asserted via /healthz), and
    greedy tokens are unchanged across the restart."""
    code, before_body, _ = _gen(tier, [4, 4, 4], n=6)
    assert code == 200
    pids_before = {r["pid"] for r in tier.replicas()}

    stop_traffic = threading.Event()
    mismatches = []

    def traffic():
        while not stop_traffic.is_set():
            c, b, _ = _gen(tier, [4, 4, 4], n=6)
            if c == 200 and b["tokens"] != before_body["tokens"]:
                mismatches.append(b["tokens"])
            time.sleep(0.05)

    t = threading.Thread(target=traffic)
    t.start()
    try:
        res = tier.rolling_restart(ready_timeout=180)
    finally:
        stop_traffic.set()
        t.join(timeout=60)
    assert res["ok"], res
    assert len(res["replaced"]) == 2
    assert not mismatches          # token-identical across the restart

    live = [r for r in tier.replicas() if not r["draining"]]
    assert {r["pid"] for r in live}.isdisjoint(pids_before)
    for r in live:
        with urllib.request.urlopen(
                f"http://{tier.host}:{r['port']}/healthz",
                timeout=10) as resp:
            h = json.loads(resp.read())
        assert h["compilation"]["xla_compiles"] == 0, (r["name"], h)

    code, after_body, _ = _gen(tier, [4, 4, 4], n=6)
    assert code == 200 and after_body["tokens"] == before_body["tokens"]


def test_tier_truthful_503_when_no_replica_admits(tier):
    """Both replicas ejected: the tier answers a truthful retryable
    503 with Retry-After instead of hanging or guessing."""
    reps = list(tier._replicas)
    saved = [(r, r.ejected_until) for r in reps]
    for r in reps:
        r.ejected_until = time.monotonic() + 30.0
    try:
        code, body, hdr = _gen(tier, [1], n=2, timeout=30)
        assert code == 503, body
        assert body["error"] == "no_replica_ready"
        assert float(body["retry_after_s"]) > 0
        assert int(hdr["Retry-After"]) >= 1
    finally:
        for r, prev in saved:
            r.ejected_until = prev
    assert tier.wait_ready(2, timeout=30)
    code, _, _ = _gen(tier, [1], n=2)
    assert code == 200


# ---------------------------------------------------------------------------
# crash-loop governance: escalating respawn backoff + give-up (ISSUE 11)
# ---------------------------------------------------------------------------

def test_respawn_governor_escalates_then_gives_up():
    from paddle_tpu.distributed.resilience import RetryPolicy
    from paddle_tpu.inference.router import RespawnGovernor
    now = [100.0]
    gov = RespawnGovernor(
        budget=3, window_s=10.0,
        policy=RetryPolicy(max_attempts=8, base_delay=1.0,
                           multiplier=2.0, max_delay=8.0, jitter=0.0),
        clock=lambda: now[0])
    # deaths at startup escalate on the deterministic schedule
    assert gov.note_death(0.5, became_ready=False) == 101.0
    assert gov.note_death(0.5, became_ready=False) == 102.0
    assert gov.note_death(0.5, became_ready=False) == 104.0
    # budget burned: the respawn is abandoned (None = give up)
    assert gov.note_death(0.5, became_ready=False) is None
    assert gov.note_death(0.5, became_ready=False) is None
    # a replica surviving past the window clears the streak
    gov.note_stable()
    assert gov.note_death(0.5, became_ready=False) == 101.0


def test_respawn_governor_slow_death_resets_streak():
    from paddle_tpu.inference.router import RespawnGovernor
    gov = RespawnGovernor(budget=2, window_s=5.0, clock=lambda: 50.0)
    gov.note_death(0.1, became_ready=False)
    gov.note_death(0.1, became_ready=False)
    assert gov.streak == 2
    # a replica that became ready AND outlived the window is a normal
    # death (rolling hardware, OOM after hours): immediate respawn
    assert gov.note_death(3600.0, became_ready=True) == 50.0
    assert gov.streak == 0


def test_respawn_governor_never_ready_counts_fast_even_if_old():
    from paddle_tpu.inference.router import RespawnGovernor
    gov = RespawnGovernor(budget=1, window_s=5.0, clock=lambda: 0.0)
    # wedged-at-startup replica killed by the unreachable path after
    # minutes: it never served, so it still extends the crash streak
    gov.note_death(600.0, became_ready=False)
    assert gov.streak == 1


def test_crash_loops_surfaced_in_stats_and_healthz(bare_router):
    assert "crash_loops" in bare_router.stats_counters
    body = bare_router.stats()
    assert body["stats"]["crash_loops"] == 0
