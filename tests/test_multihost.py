"""Multi-host formation tests: real 2-process jax.distributed world with
cross-process eager collectives (SURVEY.md §5.8 — the role the reference's
NCCL rendezvous + ProcessGroupNCCL play; reference test pattern:
TestDistBase spawning real trainer processes, test_dist_base.py:943)."""
import os
import socket
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent("""
    import sys
    import numpy as np
    rank = int(sys.argv[1]); port = sys.argv[2]
    import os
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(rank)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env({"dp": 2})   # forms the 2-process world
    import jax
    assert jax.process_count() == 2, jax.process_count()
    assert env.world_size == 2 and env.rank == rank

    # all_reduce: each rank contributes rank+1 -> every rank sees 3
    t = paddle.to_tensor(np.full((4,), rank + 1.0, np.float32))
    out = dist.all_reduce(t)
    np.testing.assert_allclose(out.numpy(), 3.0)

    # mean + max modes
    m = dist.all_reduce(paddle.to_tensor(np.float32(rank)),
                        op=dist.ReduceOp.MAX)
    assert float(m.numpy()) == 1.0, m

    # all_gather: both slices visible on every process
    got = dist.all_gather(None, paddle.to_tensor(
        np.full((2,), float(rank), np.float32)))
    vals = [float(g.numpy()[0]) for g in got]
    assert vals == [0.0, 1.0], vals

    # broadcast from rank 1
    b = dist.broadcast(paddle.to_tensor(
        np.full((3,), float(rank * 10), np.float32)), src=1)
    np.testing.assert_allclose(b.numpy(), 10.0)

    # object broadcast: 3 fixed collectives carry pickled payloads
    objs = [{"k": 41}, "hello", list(range(rank + 1))] if rank == 0 \
        else [None, None, None]
    dist.broadcast_object_list(objs, src=0)
    assert objs[0] == {"k": 41} and objs[1] == "hello" and objs[2] == [0]
    outs = []
    dist.scatter_object_list(outs, [f"obj{r}" for r in range(2)], src=0)
    assert outs == [f"obj{rank}"], outs

    # real cross-process barrier
    dist.barrier()

    # reduce: only dst rank sees the reduction
    r = dist.reduce(paddle.to_tensor(
        np.full((2,), rank + 1.0, np.float32)), dst=1)
    want_r = 3.0 if rank == 1 else rank + 1.0
    np.testing.assert_allclose(r.numpy(), want_r)

    # reduce_scatter: my K-block of the summed [N*K] vector
    rs = dist.reduce_scatter(
        None, paddle.to_tensor(
            np.arange(4, dtype=np.float32) + 10 * rank))
    # rank contributions: [0,1,2,3] and [10,11,12,13] -> sum [10,12,14,16]
    np.testing.assert_allclose(
        rs.numpy(), [10.0, 12.0] if rank == 0 else [14.0, 16.0])

    # alltoall_single: chunk j of my vector goes to rank j
    a2a = dist.alltoall_single(None, paddle.to_tensor(
        np.array([rank * 10, rank * 10 + 1], np.float32)))
    np.testing.assert_allclose(
        a2a.numpy(), [0.0, 10.0] if rank == 0 else [1.0, 11.0])

    # scatter: SPMD same-list convention; rank i gets list[i]
    sc = dist.scatter(None, [paddle.to_tensor(
        np.full((2,), float(i * 100), np.float32)) for i in range(2)])
    np.testing.assert_allclose(sc.numpy(), rank * 100.0)

    # alltoall (list form): my chunk j goes to rank j
    outs = dist.alltoall(None, [paddle.to_tensor(
        np.full((3,), float(rank * 10 + j), np.float32))
        for j in range(2)])
    got = [float(o.numpy()[0]) for o in outs]
    assert got == [0.0 + rank, 10.0 + rank], got

    # all_gather_object: real cross-process python objects
    objs = []
    dist.all_gather_object(objs, {"rank": rank, "tag": "x" * (rank + 1)})
    assert [o["rank"] for o in objs] == [0, 1], objs
    assert objs[1]["tag"] == "xx"

    # quantized all-reduce rides the same multi-process adapters
    from paddle_tpu.distributed.quantized import quantized_all_reduce
    qx = np.linspace(-1, 1, 512).astype(np.float32) * (rank + 1)
    q = quantized_all_reduce(paddle.to_tensor(qx.copy()))
    exact = np.linspace(-1, 1, 512) * 3.0
    rel = np.abs(q.numpy() - exact).max() / np.abs(exact).max()
    assert rel < 0.02, rel

    print("MULTIHOST_OK", rank)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER_P2P = textwrap.dedent("""
    import sys
    import numpy as np
    rank = int(sys.argv[1]); port = sys.argv[2]
    import os
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(rank)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env({"dp": 2})

    # blocking round-trip: 0 -> 1 then 1 -> 0
    # (reference contract: communication/send.py + recv.py)
    if rank == 0:
        dist.send(paddle.to_tensor(np.arange(6, dtype=np.float32)), dst=1)
        back = paddle.to_tensor(np.zeros(6, np.float32))
        dist.recv(back, src=1)
        np.testing.assert_allclose(back.numpy(), np.arange(6) * 2.0)
    else:
        buf = paddle.to_tensor(np.zeros(6, np.float32))
        dist.recv(buf, src=0)
        np.testing.assert_allclose(buf.numpy(), np.arange(6))
        dist.send(paddle.to_tensor(buf.numpy() * 2.0), dst=0)

    # async isend/irecv with Work handles
    if rank == 0:
        w = dist.isend(paddle.to_tensor(np.full((3,), 7.0, np.float32)),
                       dst=1)
        w.wait()
    else:
        buf = paddle.to_tensor(np.zeros(3, np.float32))
        w = dist.irecv(buf, src=0)
        w.wait()
        assert w.is_completed()
        np.testing.assert_allclose(buf.numpy(), 7.0)

    # pp-style microbatch exchange via batch_isend_irecv: each step rank0
    # feeds activations forward, rank1 returns grads (both directions in
    # one batch; reference batch_isend_irecv.py:27)
    for mb in range(3):
        if rank == 0:
            acts = paddle.to_tensor(
                np.full((2, 4), float(mb), np.float32))
            gbuf = paddle.to_tensor(np.zeros((2, 4), np.float32))
            ops = [dist.P2POp(dist.isend, acts, 1),
                   dist.P2POp(dist.irecv, gbuf, 1)]
            for w in dist.batch_isend_irecv(ops): w.wait()
            np.testing.assert_allclose(gbuf.numpy(), mb * 10.0)
        else:
            abuf = paddle.to_tensor(np.zeros((2, 4), np.float32))
            ops = [dist.P2POp(dist.irecv, abuf, 0)]
            for w in dist.batch_isend_irecv(ops): w.wait()
            np.testing.assert_allclose(abuf.numpy(), float(mb))
            grads = paddle.to_tensor(abuf.numpy() * 10.0)
            for w in dist.batch_isend_irecv(
                    [dist.P2POp(dist.isend, grads, 0)]): w.wait()

    # uneven alltoall_single (global_scatter semantics): rank0 sends
    # sizes [1,3], rank1 sends [2,4]
    if rank == 0:
        xin = np.array([0, 100, 101, 102], np.float32)
        got = dist.alltoall_single(None, paddle.to_tensor(xin),
                                   in_split_sizes=[1, 3],
                                   out_split_sizes=[1, 2])
        np.testing.assert_allclose(got.numpy(), [0, 10, 11])
    else:
        xin = np.array([10, 11, 110, 111, 112, 113], np.float32)
        got = dist.alltoall_single(None, paddle.to_tensor(xin),
                                   in_split_sizes=[2, 4],
                                   out_split_sizes=[3, 4])
        np.testing.assert_allclose(
            got.numpy(), [100, 101, 102, 110, 111, 112, 113])

    dist.barrier()
    print("P2P_OK", rank)
""")


_WORKER_MULTIDEV = textwrap.dedent("""
    import sys
    import numpy as np
    rank = int(sys.argv[1]); port = sys.argv[2]
    import os
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(rank)

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    # 2 processes x 4 local devices = dp axis of 8 (the real pod shape:
    # one process drives several chips); contribution = 4 rows
    env = dist.init_parallel_env({"dp": 8})
    import jax
    assert jax.device_count() == 8, jax.device_count()
    local = np.arange(4, dtype=np.float32) + rank * 4   # rows 0-3 / 4-7
    out = dist.all_reduce(paddle.to_tensor(local[:, None]))
    # sum over all 8 rows of [0..7] broadcast to every row
    np.testing.assert_allclose(out.numpy(), 28.0)
    assert out.numpy().shape == (4, 1)
    dist.barrier()
    # object gather under L=4 local device-ranks
    objs = []
    dist.all_gather_object(objs, ("proc", rank))
    assert len(objs) == 8 and objs.count(("proc", 0)) == 4, objs
    print("MULTIDEV_OK", rank)
""")


def _run_pair(worker, tag, devices_per_proc):
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                        f"{devices_per_proc}")
    env.pop("_PADDLE_TPU_TEST_REEXEC", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker, str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)))
        for r in range(2)]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"{tag} {r}" in out


def test_two_process_world_collectives():
    _run_pair(_WORKER, "MULTIHOST_OK", devices_per_proc=1)


def test_two_process_p2p_send_recv():
    _run_pair(_WORKER_P2P, "P2P_OK", devices_per_proc=1)


def test_two_process_multidevice_rows():
    _run_pair(_WORKER_MULTIDEV, "MULTIDEV_OK", devices_per_proc=4)
