"""int8 KV cache for decode (cache_dtype="int8" in generate()).

Reference role: fused_multi_transformer_op.cu serves int8 CacheKV
(paddle/fluid/operators/fused/). TPU-native: values stored int8 with one
dynamic scale per (batch, position, head) row, quantized on write and
dequantized at use inside the same jitted decode step — half the cache
HBM vs bf16, quarter vs f32.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _prompt():
    return paddle.to_tensor(
        np.random.RandomState(5).randint(0, 256, (2, 12)).astype("int64"))


@pytest.mark.parametrize("name,M,tiny,kw", [
    ("gpt", GPTForCausalLM, gpt_tiny, {}),
    ("gpt-scan", GPTForCausalLM, gpt_tiny, {"scan_layers": True}),
    ("llama-gqa", LlamaForCausalLM, llama_tiny, {}),
    ("llama-scan", LlamaForCausalLM, llama_tiny, {"scan_layers": True}),
])
def test_greedy_matches_f32_cache(name, M, tiny, kw):
    paddle.seed(0)
    m = M(tiny(**kw))
    prompt = _prompt()
    out_f32 = m.generate(prompt, max_new_tokens=8, do_sample=False,
                         cache_dtype="float32")
    out_i8 = m.generate(prompt, max_new_tokens=8, do_sample=False,
                        cache_dtype="int8")
    agree = float((np.asarray(out_f32) == np.asarray(out_i8)).mean())
    assert agree >= 0.9, (name, agree)


def test_cache_layout_and_memory():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    caches = m.new_cache(2, 16, "int8")
    k0, v0 = caches[0]
    assert k0["data"].dtype == np.int8 and k0["scale"].dtype == np.float32
    assert k0["data"].shape == (2, 16, 4, 16)
    assert k0["scale"].shape == (2, 16, 4)
    # int8 data + f32 row scales ≈ 1/3.6 the bytes of an f32 cache
    f32 = m.new_cache(2, 16, "float32")[0][0]
    i8_bytes = k0["data"].nbytes + k0["scale"].nbytes
    assert i8_bytes < 0.4 * f32.nbytes

    # scan layout: stacked leaves with leading L
    ms = GPTForCausalLM(gpt_tiny(scan_layers=True))
    kst, vst = ms.new_cache(2, 16, "int8")
    assert kst["data"].shape == (4, 2, 16, 4, 16)
    assert kst["scale"].shape == (4, 2, 16, 4)


def test_quantization_noise_bounded():
    from paddle_tpu.nn.functional.flash_attention import (_cache_read,
                                                          _cache_write,
                                                          quantized_kv_cache)
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    rows = jnp.asarray(rng.randn(2, 8, 4, 16).astype("float32"))
    cache = quantized_kv_cache(2, 8, 4, 16)
    cache = _cache_write(cache, rows, jnp.int32(0))
    back = _cache_read(cache)
    rel = float(jnp.max(jnp.abs(back - rows)) / jnp.max(jnp.abs(rows)))
    assert rel < 0.01, rel  # |err| <= scale/2 = amax/254 per row
