"""End-to-end "book" tests (reference test/book/ pattern: train a few
iterations on a classic task, assert convergence) + hapi callback
coverage."""
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi import Model
from paddle_tpu.io.dataloader import Dataset


class TestFitALine:
    """Reference: test/book/test_fit_a_line.py — linear regression on
    UCIHousing-format data."""

    def test_fit_a_line(self, tmp_path):
        rng = np.random.RandomState(0)
        w_true = rng.randn(13).astype(np.float32)
        X = rng.randn(200, 13).astype(np.float32)
        y = X @ w_true + 0.01 * rng.randn(200).astype(np.float32)
        raw = np.concatenate([X, y[:, None]], 1)
        path = str(tmp_path / "housing.data")
        np.savetxt(path, raw)

        from paddle_tpu.text.datasets import UCIHousing
        train = UCIHousing(data_file=path, mode="train")
        test = UCIHousing(data_file=path, mode="test")

        paddle.seed(0)
        net = nn.Linear(13, 1)
        model = Model(net)
        model.prepare(paddle.optimizer.Adam(learning_rate=0.3,
                                            parameters=net.parameters()),
                      nn.MSELoss())
        # UCIHousing normalizes features into a small range, so the
        # effective weights are large — the classic book test just needs
        # enough steps at a healthy LR
        model.fit(train, epochs=60, batch_size=32, verbose=0)
        logs = model.evaluate(test, batch_size=32, verbose=0)
        assert logs["loss"] < 1.0, logs


class TestCallbacks:
    def _ds(self, n=64):
        rng = np.random.RandomState(1)
        X = rng.randn(n, 8).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return n

        return DS()

    def test_model_checkpoint(self, tmp_path):
        paddle.seed(1)
        net = nn.Sequential(nn.Linear(8, 2))
        model = Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
                      nn.CrossEntropyLoss())
        ckpt = paddle.callbacks.ModelCheckpoint(
            save_freq=1, save_dir=str(tmp_path))
        model.fit(self._ds(), epochs=2, batch_size=16, verbose=0,
                  callbacks=[ckpt])
        files = os.listdir(tmp_path)
        assert any(f.startswith("final") for f in files), files
        assert any(f.startswith("0") or f.startswith("1")
                   for f in files), files

    def test_reduce_lr_on_plateau(self):
        paddle.seed(2)
        net = nn.Sequential(nn.Linear(8, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        model = Model(net)
        model.prepare(opt, nn.CrossEntropyLoss())
        cb = paddle.callbacks.ReduceLROnPlateau(
            monitor="loss", factor=0.5, patience=1, verbose=0)
        cb.set_model(model)
        cb.on_train_begin()
        cb.on_eval_end({"loss": 1.0})   # sets best
        cb.on_eval_end({"loss": 1.0})   # patience hit -> 0.05
        assert abs(float(opt.get_lr()) - 0.05) < 1e-8
        cb.on_eval_end({"loss": 0.5})   # improvement resets wait
        cb.on_eval_end({"loss": 0.5})   # patience hit -> 0.025
        assert abs(float(opt.get_lr()) - 0.025) < 1e-8
        # epoch-end fallback ignores epochs where eval ran
        cb.on_epoch_end(9, {"loss": 0.1, "eval_loss": 0.5})
        assert abs(float(opt.get_lr()) - 0.025) < 1e-8
        # a second fit resets plateau state
        cb.wait = 7
        cb.on_train_begin()
        assert cb.wait == 0
        assert not np.isfinite(cb.best)

    def test_visualdl_writes_scalars(self, tmp_path):
        import json
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 2))
        model = Model(net)
        model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                           parameters=net.parameters()),
                      nn.CrossEntropyLoss())
        vdl = paddle.callbacks.VisualDL(log_dir=str(tmp_path))
        model.fit(self._ds(), epochs=2, batch_size=16, verbose=0,
                  callbacks=[vdl])
        lines = open(tmp_path / "scalars.jsonl").read().splitlines()
        assert len(lines) >= 2
        rec = json.loads(lines[0])
        assert rec["tag"] == "train" and "loss" in rec

    def test_summary_function(self):
        out = paddle.summary(nn.Sequential(nn.Linear(4, 3),
                                           nn.Linear(3, 2)), (1, 4))
        assert out["total_params"] == 4 * 3 + 3 + 3 * 2 + 2
