"""Sparse COO/CSR op numerics vs dense references (reference:
test/legacy_test/test_sparse_* suite pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sparse

RNG = np.random.RandomState(9)


def _coo(dense):
    idx = np.stack(np.nonzero(dense))
    vals = dense[tuple(idx)]
    return sparse.sparse_coo_tensor(
        paddle.to_tensor(idx.astype("int64")),
        paddle.to_tensor(vals.astype("float32")), shape=list(dense.shape))


def _dense_with_zeros(shape, density=0.4):
    d = RNG.randn(*shape).astype("float32")
    d[RNG.rand(*shape) > density] = 0.0
    return d


class TestSparseOps:
    def test_coo_roundtrip_and_coalesce(self):
        d = _dense_with_zeros((4, 5))
        s = _coo(d)
        np.testing.assert_allclose(s.to_dense().numpy(), d, rtol=1e-6)
        # duplicate entries must sum on coalesce
        idx = np.array([[0, 0, 1], [2, 2, 3]], np.int64)
        vals = np.array([1.0, 2.0, 5.0], np.float32)
        dup = sparse.sparse_coo_tensor(paddle.to_tensor(idx),
                                       paddle.to_tensor(vals),
                                       shape=[2, 4])
        c = sparse.coalesce(dup)
        dd = c.to_dense().numpy()
        assert dd[0, 2] == 3.0 and dd[1, 3] == 5.0

    def test_csr_roundtrip(self):
        d = _dense_with_zeros((3, 6))
        crows = [0]
        cols, vals = [], []
        for r in range(3):
            nz = np.nonzero(d[r])[0]
            cols += nz.tolist()
            vals += d[r, nz].tolist()
            crows.append(len(cols))
        s = sparse.sparse_csr_tensor(
            paddle.to_tensor(np.asarray(crows, np.int64)),
            paddle.to_tensor(np.asarray(cols, np.int64)),
            paddle.to_tensor(np.asarray(vals, np.float32)), shape=[3, 6])
        np.testing.assert_allclose(s.to_dense().numpy(), d, rtol=1e-6)

    def test_elementwise_and_unary(self):
        a = _dense_with_zeros((4, 4))
        b = _dense_with_zeros((4, 4))
        np.testing.assert_allclose(
            sparse.add(_coo(a), _coo(b)).to_dense().numpy(), a + b,
            rtol=1e-5)
        np.testing.assert_allclose(
            sparse.subtract(_coo(a), _coo(b)).to_dense().numpy(), a - b,
            rtol=1e-5)
        np.testing.assert_allclose(
            sparse.multiply(_coo(a), _coo(b)).to_dense().numpy(), a * b,
            rtol=1e-5)
        np.testing.assert_allclose(
            sparse.relu(_coo(a)).to_dense().numpy(), np.maximum(a, 0),
            rtol=1e-6)
        np.testing.assert_allclose(
            sparse.pow(_coo(a), 2).to_dense().numpy(), a ** 2, rtol=1e-5)

    def test_matmul_mv_addmm(self):
        a = _dense_with_zeros((3, 4))
        dense = RNG.randn(4, 2).astype("float32")
        np.testing.assert_allclose(
            sparse.matmul(_coo(a), paddle.to_tensor(dense)).numpy(),
            a @ dense, rtol=1e-5)
        v = RNG.randn(4).astype("float32")
        np.testing.assert_allclose(
            sparse.mv(_coo(a), paddle.to_tensor(v)).numpy(), a @ v,
            rtol=1e-5)
        inp = RNG.randn(3, 2).astype("float32")
        np.testing.assert_allclose(
            sparse.addmm(paddle.to_tensor(inp), _coo(a),
                         paddle.to_tensor(dense), beta=0.5,
                         alpha=2.0).numpy(),
            0.5 * inp + 2.0 * (a @ dense), rtol=1e-5)

    def test_masked_matmul(self):
        a = RNG.randn(3, 4).astype("float32")
        b = RNG.randn(4, 3).astype("float32")
        mask_d = _dense_with_zeros((3, 3))
        mask = _coo(mask_d)
        got = sparse.masked_matmul(paddle.to_tensor(a),
                                   paddle.to_tensor(b), mask)
        ref = np.where(mask_d != 0, a @ b, 0.0)
        np.testing.assert_allclose(got.to_dense().numpy(), ref,
                                   rtol=1e-5)

    def test_reshape_transpose_cast(self):
        d = _dense_with_zeros((2, 6))
        np.testing.assert_allclose(
            sparse.reshape(_coo(d), [3, 4]).to_dense().numpy(),
            d.reshape(3, 4), rtol=1e-6)
        np.testing.assert_allclose(
            sparse.transpose(_coo(d), [1, 0]).to_dense().numpy(), d.T,
            rtol=1e-6)
        c = sparse.cast(_coo(d), value_dtype="float64")
        assert sparse.is_sparse(c)
        np.testing.assert_allclose(
            np.asarray(c.to_dense().numpy(), np.float32), d, rtol=1e-6)

    def test_to_sparse_and_shape_utils(self):
        d = _dense_with_zeros((3, 3))
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        assert sparse.is_sparse(s)
        np.testing.assert_allclose(s.to_dense().numpy(), d, rtol=1e-6)
        assert sparse.is_same_shape(s, _coo(d))
