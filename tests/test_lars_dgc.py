"""LARS / DGC optimizer tests (reference roles:
meta_optimizers/lars_optimizer.py, dgc_optimizer.py and their ops)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.strategy import DistributedStrategy
from paddle_tpu.optimizer import DGCMomentum, Lars, Momentum


def _model_and_data(seed=0):
    paddle.seed(seed)
    m = nn.Linear(8, 4)
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
    y = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
    return m, x, y


def _train(m, opt, x, y, steps=5):
    import paddle_tpu.nn.functional as F
    losses = []
    for _ in range(steps):
        loss = F.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_lars_rule_matches_numpy():
    m, x, y = _model_and_data(1)
    opt = Lars(learning_rate=0.1, momentum=0.9, lars_coeff=0.001,
               lars_weight_decay=0.0005, parameters=m.parameters())
    w0 = m.weight.numpy().astype(np.float64)
    import paddle_tpu.nn.functional as F
    loss = F.mse_loss(m(x), y)
    loss.backward()
    g = np.asarray(m.weight._grad).astype(np.float64)
    opt.step()
    # numpy re-derivation of one LARS step (v0 = 0)
    wd, coeff, lr = 0.0005, 0.001, 0.1
    p_n, g_n = np.linalg.norm(w0), np.linalg.norm(g)
    local_lr = lr * coeff * p_n / (g_n + wd * p_n)
    v = local_lr * (g + wd * w0)
    np.testing.assert_allclose(m.weight.numpy(), w0 - v, rtol=1e-5,
                               atol=1e-6)


def test_lars_exclude_list():
    m, x, y = _model_and_data(2)
    m.weight.name = "linear_0.w_0"
    m.bias.name = "linear_0.bias"
    opt = Lars(learning_rate=0.1, parameters=m.parameters(),
               exclude_from_weight_decay=["bias"])
    # bias gets wd=0; weight keeps lars_weight_decay
    assert opt._param_meta(m.bias).wd == 0.0
    assert opt._param_meta(m.weight).wd == 0.0005


def test_lars_converges():
    # LARS pairs with large base lr: local_lr = lr * coeff * ||p||/||g||
    m, x, y = _model_and_data(3)
    losses = _train(m, Lars(learning_rate=20.0, momentum=0.9,
                            lars_coeff=0.01,
                            parameters=m.parameters()), x, y, steps=40)
    assert losses[-1] < losses[0], losses


def test_dgc_before_rampup_is_momentum():
    m1, x, y = _model_and_data(4)
    m2, _, _ = _model_and_data(4)
    o1 = DGCMomentum(learning_rate=0.05, momentum=0.9,
                     parameters=m1.parameters(), rampup_begin_step=1000)
    o2 = Momentum(learning_rate=0.05, momentum=0.9,
                  parameters=m2.parameters())
    l1 = _train(m1, o1, x, y, steps=5)
    l2 = _train(m2, o2, x, y, steps=5)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_dgc_sparse_phase_updates_and_residual():
    m, x, y = _model_and_data(5)
    opt = DGCMomentum(learning_rate=0.05, momentum=0.9,
                      parameters=m.parameters(), rampup_begin_step=0,
                      sparsity=[0.75])
    w0 = m.weight.numpy().copy()
    import paddle_tpu.nn.functional as F
    loss = F.mse_loss(m(x), y)
    loss.backward()
    opt.step()
    delta = m.weight.numpy() - w0
    # with 75% sparsity only ~25% of entries move on the first step
    moved = (np.abs(delta) > 0).sum()
    assert 0 < moved <= int(np.ceil(delta.size * 0.25)) + 1, moved
    # residual holds the unsent mass
    resid = opt._accumulators["residual"][m.weight.name]
    assert float(np.abs(np.asarray(resid)).sum()) > 0


def test_dgc_converges():
    m, x, y = _model_and_data(6)
    losses = _train(m, DGCMomentum(learning_rate=0.1, momentum=0.9,
                                   parameters=m.parameters(),
                                   sparsity=[0.5]), x, y, steps=30)
    assert losses[-1] < losses[0] * 0.5, losses


def test_dgc_momentum_factor_masking():
    """dgc_op semantics: velocity is zeroed at coordinates that were sent."""
    m, x, y = _model_and_data(8)
    opt = DGCMomentum(learning_rate=0.05, momentum=0.9,
                      parameters=m.parameters(), rampup_begin_step=0,
                      sparsity=[0.75])
    import paddle_tpu.nn.functional as F
    loss = F.mse_loss(m(x), y)
    loss.backward()
    w0 = m.weight.numpy().copy()
    opt.step()
    moved = np.abs(m.weight.numpy() - w0) > 0
    vel = np.asarray(opt._accumulators["velocity"][m.weight.name])
    assert (vel[moved] == 0).all()        # sent coords: velocity cleared
    assert (np.abs(vel[~moved]) > 0).any()  # unsent keep momentum history


def test_dgc_sparsity_ramp():
    opt = DGCMomentum(learning_rate=0.1, momentum=0.9,
                      parameters=nn.Linear(2, 2).parameters(),
                      rampup_begin_step=0, rampup_step=9,
                      sparsity=[0.3, 0.6, 0.9])
    import jax.numpy as jnp
    got = [float(opt._sparsity_at(jnp.int32(t))) for t in (1, 2, 3, 4, 7,
                                                           100)]
    assert got[0] == pytest.approx(0.3)      # first segment
    assert got[3] == pytest.approx(0.6)      # t=4 -> seg 1
    assert got[4] == pytest.approx(0.9)      # t=7 -> seg 2
    assert got[5] == pytest.approx(0.9)      # clamped after ramp


def test_fleet_strategy_preserves_momentum_config():
    m, _, _ = _model_and_data(9)
    from paddle_tpu.optimizer import L2Decay
    strat = DistributedStrategy()
    strat.dgc = True
    opt = fleet.distributed_optimizer(
        Momentum(learning_rate=0.1, momentum=0.8, use_nesterov=True,
                 weight_decay=L2Decay(1e-4), parameters=m.parameters()),
        strat)
    assert isinstance(opt, DGCMomentum)
    assert opt._momentum == 0.8 and opt._nesterov
    assert opt._wd_coeff == pytest.approx(1e-4)


def test_fleet_strategy_swaps_optimizer():
    m, _, _ = _model_and_data(7)
    strat = DistributedStrategy()
    strat.lars = True
    strat.lars_configs = {"lars_coeff": 0.002}
    opt = fleet.distributed_optimizer(
        Momentum(learning_rate=0.1, parameters=m.parameters()), strat)
    assert isinstance(opt, Lars) and opt._coeff == 0.002

    strat2 = DistributedStrategy()
    strat2.dgc = True
    opt2 = fleet.distributed_optimizer(
        Momentum(learning_rate=0.1, parameters=m.parameters()), strat2)
    assert isinstance(opt2, DGCMomentum)

    with pytest.raises(ValueError, match="Momentum"):
        fleet.distributed_optimizer(
            paddle.optimizer.Adam(learning_rate=0.1,
                                  parameters=m.parameters()), strat)