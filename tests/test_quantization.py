"""Quantization framework: QAT fake-quant training + PTQ calibration
(reference test pattern: test/quantization/test_quant.py family)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (PTQ, QAT, QuantConfig,
                                     fake_quant_dequant)
from paddle_tpu.quantization.observers import AbsmaxObserver
from paddle_tpu.quantization.quanters import (
    FakeQuanterChannelWiseAbsMaxObserver, FakeQuanterWithAbsMaxObserver)


def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _lenet():
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    return LeNet()


class TestFakeQuant:
    def test_qdq_rounds_to_grid(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
        scale = paddle.to_tensor(np.float32(1.0))
        out = fake_quant_dequant(x, scale, bit_length=8).numpy()
        # every output is k/127 for integer k
        k = out * 127
        np.testing.assert_allclose(k, np.round(k), atol=1e-4)

    def test_ste_gradient_identity(self):
        x = paddle.to_tensor(np.array([0.3, -0.7, 0.9], np.float32))
        x.stop_gradient = False
        out = fake_quant_dequant(
            x, paddle.to_tensor(np.float32(1.0)))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 1.0, atol=1e-6)

    def test_channelwise(self):
        rng = np.random.RandomState(0)
        w = paddle.to_tensor(rng.randn(4, 3).astype(np.float32) *
                             np.array([[1], [10], [100], [1000]],
                                      np.float32))
        scales = paddle.to_tensor(
            np.abs(w.numpy()).max(1).astype(np.float32))
        out = fake_quant_dequant(w, scales, channel_axis=0).numpy()
        # each row's error bounded by its own scale / 254
        err = np.abs(out - w.numpy()).max(1)
        assert (err <= scales.numpy() / 254 + 1e-6).all()


class TestQAT:
    def test_quantize_replaces_layers(self):
        q = QuantConfig(
            activation=FakeQuanterWithAbsMaxObserver(moving_rate=0.9),
            weight=FakeQuanterWithAbsMaxObserver(moving_rate=0.9))
        model = _mlp()
        qat = QAT(q)
        qmodel = qat.quantize(model)
        names = [type(m).__name__ for m in qmodel.children()]
        assert names.count("QuantedLinear") == 2
        # original model untouched (inplace=False)
        assert [type(m).__name__ for m in model.children()].count(
            "Linear") == 2

    def test_qat_trains_close_to_fp32(self):
        rng = np.random.RandomState(1)
        X = rng.randn(128, 8).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        xs, ys = paddle.to_tensor(X), paddle.to_tensor(y)
        loss_fn = nn.CrossEntropyLoss()

        def train(model):
            opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                        parameters=model.parameters())
            for _ in range(40):
                loss = loss_fn(model(xs), ys)
                loss.backward()
                opt.step()
                opt.clear_grad()
            return float(loss)

        fp32 = _mlp()
        l32 = train(fp32)
        q = QuantConfig(
            activation=FakeQuanterWithAbsMaxObserver(),
            weight=FakeQuanterChannelWiseAbsMaxObserver(quant_axis=1))
        qmodel = QAT(q).quantize(_mlp())
        lq = train(qmodel)
        assert lq < l32 + 0.1, (l32, lq)

    def test_qat_lenet_forward_and_convert(self):
        q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                        weight=FakeQuanterWithAbsMaxObserver())
        qat = QAT(q)
        net = _lenet()
        qnet = qat.quantize(net)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 1, 28, 28).astype(
                np.float32))
        out_q = qnet(x)
        assert out_q.shape == [2, 10]
        # convert strips quanters back to plain layers
        plain = qat.convert(qnet)
        out_p = plain(x)
        assert out_p.shape == [2, 10]
        kinds = [type(m).__name__ for m in plain.features.children()]
        assert "QuantedConv2D" not in kinds

    def test_qat_requires_training_mode(self):
        import pytest
        q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                        weight=FakeQuanterWithAbsMaxObserver())
        model = _mlp()
        model.eval()
        with pytest.raises(AssertionError):
            QAT(q).quantize(model)


class TestPTQ:
    def test_calibrate_and_convert(self):
        rng = np.random.RandomState(2)
        model = _mlp()
        model.eval()
        x = paddle.to_tensor(rng.randn(64, 8).astype(np.float32))
        ref = model(x).numpy()

        q = QuantConfig(activation=AbsmaxObserver(), weight=None)
        ptq = PTQ(q)
        cal = ptq.quantize(model)
        for _ in range(4):
            cal(x)
        conv, scales = ptq.convert(cal)
        # scales exported for both linears (activation + weight)
        act_keys = [k for k in scales if k.endswith("activation")]
        w_keys = [k for k in scales if k.endswith("weight")]
        assert len(act_keys) == 2 and len(w_keys) == 2
        assert scales[act_keys[0]] > 0
        out = conv(x).numpy()
        # int8 quantization error is small relative to output range
        denom = np.abs(ref).max()
        assert np.abs(out - ref).max() / denom < 0.1

    def test_observer_sees_running_max(self):
        q = QuantConfig(activation=AbsmaxObserver(), weight=None)
        ptq = PTQ(q)
        model = _mlp()
        model.eval()
        cal = ptq.quantize(model)
        a = np.zeros((4, 8), np.float32)
        a[0, 0] = 3.0
        cal(paddle.to_tensor(a))
        b = np.zeros((4, 8), np.float32)
        b[0, 0] = 7.0
        cal(paddle.to_tensor(b))
        _, scales = ptq.convert(cal)
        first_act = [v for k, v in scales.items()
                     if k.endswith("activation")][0]
        np.testing.assert_allclose(first_act, 7.0, rtol=1e-5)
