"""tpuprof measured runtime profiling (ISSUE 14).

Fixture-driven (ZERO compiles): the chrome-trace parser over a
checked-in device-plane trace, the measured<->modeled join against the
mlp_fused HLO fixture, the CPU degrade contract, and the dispatch-
ratchet/anchor gate semantics. Plus one LIVE smoke: a tiny registry
program profiled end-to-end (report names its kernels, the gate
round-trips --update-baseline) and the efficiency gauges the same
issue wires into the engine tick and the fit loop.

Registered in tools/ci.py --quick.
"""
import json
import os

import numpy as np
import pytest

from paddle_tpu.analysis import runtime_profile as rp
from paddle_tpu.analysis.findings import (PROF_ANCHOR, PROF_BUDGET,
                                          STALE_PROF_PROGRAM)
from paddle_tpu.analysis.hlo_cost import collect_kernels, \
    parse_hlo_module

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HLO_FIXTURES = os.path.join(ROOT, "tests", "fixtures", "hlo")
TRACE_FIXTURE = os.path.join(ROOT, "tests", "fixtures", "trace",
                             "mlp_device.trace.json")


def _fixture_events():
    with open(TRACE_FIXTURE) as fh:
        return json.load(fh)["traceEvents"]


def _mlp_kernels():
    with open(os.path.join(HLO_FIXTURES, "mlp_fused.txt")) as fh:
        return collect_kernels(parse_hlo_module(fh.read()))


# ---------------------------------------------------------------------------
# parser (zero compiles)
# ---------------------------------------------------------------------------

def test_device_op_times_aggregates_xla_ops_lane_only():
    prof = rp.device_op_times(_fixture_events())
    assert prof.had_device
    # two dispatches summed per op; the 5000us "Steps"-lane span and
    # the host events must NOT land in per_op
    assert prof.per_op["dot.14"] == pytest.approx(620.0)
    assert prof.per_op["broadcast_multiply_fusion"] == \
        pytest.approx(220.0)
    assert prof.per_op["copy.99"] == pytest.approx(80.0)
    assert "train_step_like_whole_step" not in prof.per_op
    assert "TfrtCpuExecutable::Execute" not in prof.per_op
    assert prof.op_category["dot.14"] == "matmul"
    assert prof.host_dispatch_events == 2


def test_load_trace_events_reads_gz_and_plain(tmp_path):
    import gzip
    events = _fixture_events()
    d = tmp_path / "plugins" / "profile" / "x"
    d.mkdir(parents=True)
    with open(TRACE_FIXTURE) as fh:
        doc = fh.read()
    (d / "a.trace.json").write_text(doc)
    with gzip.open(d / "b.trace.json.gz", "wt") as fh:
        fh.write(doc)
    loaded = rp.load_trace_events(str(tmp_path))
    assert len(loaded) == 2 * len(events)


def test_host_only_trace_degrades():
    host_only = [e for e in _fixture_events() if e.get("pid") == 701]
    prof = rp.device_op_times(host_only)
    assert not prof.had_device
    assert prof.per_op == {}
    assert prof.host_dispatch_events == 2


# ---------------------------------------------------------------------------
# measured <-> modeled join (zero compiles)
# ---------------------------------------------------------------------------

def test_join_against_mlp_fixture():
    prof = rp.device_op_times(_fixture_events())
    join = rp.join_measured_modeled(prof.per_op, _mlp_kernels(),
                                    chip="v5lite", dispatches=2)
    assert join["available"]
    rows = {r["name"]: r for r in join["rows"]}
    # both modeled kernels joined, per-dispatch times
    assert rows["dot.14"]["measured_us"] == pytest.approx(310.0)
    assert rows["broadcast_multiply_fusion"]["measured_us"] == \
        pytest.approx(110.0)
    assert rows["dot.14"]["matmul_flops"] > 0
    assert rows["dot.14"]["measured_vs_roofline"] > 1.0
    # copy.99 is measured but unmodeled: time-weighted join rate is
    # (620 + 220) / 920 and the leftover is named
    assert join["join_rate_time_weighted"] == pytest.approx(840 / 920,
                                                            abs=1e-3)
    assert join["unjoined_top"][0]["name"] == "copy.99"
    assert join["unjoined_us"] == pytest.approx(40.0)


def test_time_weighted_histogram_and_matmul_share():
    prof = rp.device_op_times(_fixture_events())
    join = rp.join_measured_modeled(prof.per_op, _mlp_kernels(),
                                    chip="v5lite", dispatches=2)
    hist = rp.time_weighted_histogram(join)
    assert hist["dot"] == pytest.approx(310.0)
    assert hist["loop"] == pytest.approx(110.0)
    assert hist["unattributed"] == pytest.approx(40.0)
    # histogram sums to the measured total (the honesty property)
    assert sum(hist.values()) == pytest.approx(
        join["measured_total_us"])
    share = rp.matmul_time_share(join)
    assert share == pytest.approx(310.0 / 460.0, abs=1e-3)


def test_time_weighted_chains_reranks_by_seconds():
    from paddle_tpu.analysis.hlo_cost import KernelCost

    def k(name, wr):
        return KernelCost(name=name, opcode="add", klass="unfused",
                          flops=1.0, matmul_flops=0.0, bytes_read=wr,
                          bytes_written=wr, trip=1, path="",
                          operands=())
    # chain A is bytes-heavy, chain B is where the measured time is
    chains = [
        {"kernels": ["a.1", "a.2"], "kernel_count": 2, "ops": [],
         "path": "", "trip": 1, "intermediate_bytes": 10_000_000,
         "savable_bytes": 20_000_000},
        {"kernels": ["b.1", "b.2"], "kernel_count": 2, "ops": [],
         "path": "", "trip": 1, "intermediate_bytes": 1_000,
         "savable_bytes": 2_000},
    ]
    join = {"rows": [
        {"name": "a.1", "measured_us": 1.0},
        {"name": "a.2", "measured_us": 1.0},
        {"name": "b.1", "measured_us": 500.0},
        {"name": "b.2", "measured_us": 400.0},
    ]}
    out = rp.time_weighted_chains(join, chains)
    assert [c["kernels"][0] for c in out] == ["b.1", "a.1"]
    assert out[0]["measured_us"] == pytest.approx(900.0)
    # a chain with no measured time is dropped, not ranked at zero
    chains.append({"kernels": ["c.1", "c.2"], "kernel_count": 2,
                   "ops": [], "path": "", "trip": 1,
                   "intermediate_bytes": 5, "savable_bytes": 10})
    assert all(c["kernels"][0] != "c.1"
               for c in rp.time_weighted_chains(join, chains))


def test_runtime_report_device_and_degraded_paths():
    with open(os.path.join(HLO_FIXTURES, "mlp_fused.txt")) as fh:
        hlo = fh.read()
    rep = rp.runtime_report("mlp", hlo_text=hlo,
                            events=_fixture_events(),
                            dispatch_s=[0.01, 0.012, 0.011],
                            dispatches_profiled=2, chip="v5lite")
    assert rep["had_device_plane"]
    assert rep["dispatch"]["median_ms"] == pytest.approx(11.0)
    assert rep["matmul_time_share"] is not None
    assert rep["measured_vs_roofline"] > 0
    assert "dot.14" in rep["modeled"]["top_kernels"]
    # degraded: host-only events — wall time kept, join marked
    # unavailable with a reason, anchors get nothing to latch onto
    host_only = [e for e in _fixture_events() if e.get("pid") == 701]
    deg = rp.runtime_report("mlp", hlo_text=hlo, events=host_only,
                            dispatch_s=[0.01], chip="v5lite")
    assert not deg["had_device_plane"]
    assert deg["join"]["available"] is False
    assert "device plane" in deg["join"]["reason"]
    assert deg["matmul_time_share"] is None
    assert deg["measured_vs_roofline"] is None
    assert deg["dispatch"]["median_ms"] == pytest.approx(10.0)
    assert deg["modeled"]["top_kernels"]  # still names its kernels


# ---------------------------------------------------------------------------
# baseline gate semantics (zero compiles)
# ---------------------------------------------------------------------------

def _report(median_ms=10.0, matmul_share=0.7, vs_roofline=5.0,
            device=True):
    rep = {"dispatch": {"median_ms": median_ms, "n": 3},
           "had_device_plane": device,
           "matmul_time_share": matmul_share if device else None,
           "measured_vs_roofline": vs_roofline if device else None,
           "join": ({"available": True} if device else
                    {"available": False, "reason": "no device plane"})}
    return rep


def test_gate_budget_tolerance_band():
    base = {"budgets": {"p": {"dispatch_ms": 10.0}}, "anchors": {},
            "tolerance": 2.0}
    ok, _ = rp.check_profile_baseline({"p": _report(19.0)}, base, ["p"])
    assert ok == []
    bad, _ = rp.check_profile_baseline({"p": _report(21.0)}, base,
                                       ["p"])
    assert [f.code for f in bad] == [PROF_BUDGET]
    assert bad[0].site == "dispatch_ms"


def test_gate_unbaselined_stale_and_require_all():
    base = {"budgets": {"gone": {"dispatch_ms": 5.0},
                        "quiet": {"dispatch_ms": 5.0}},
            "anchors": {}}
    fs, _ = rp.check_profile_baseline({"new": _report()}, base,
                                      ["new", "quiet"],
                                      require_all=True)
    codes = {(f.code, f.program) for f in fs}
    assert (STALE_PROF_PROGRAM, "gone") in codes
    assert (PROF_BUDGET, "new") in codes          # unbaselined
    assert (PROF_BUDGET, "quiet") in codes        # live, not measured


def test_gate_anchors_fire_and_skip():
    base = {"budgets": {}, "anchors": {
        "train_step": {"kind": "matmul_time_share_floor",
                       "min_share": 0.5},
        "gpt_decode": {"kind": "measured_vs_roofline",
                       "max_ratio": 10.0}}}
    live = ["train_step", "gpt_decode"]
    # holding
    ok, skipped = rp.check_profile_baseline(
        {"train_step": _report(matmul_share=0.7),
         "gpt_decode": _report(vs_roofline=8.0)}, base, live)
    assert [f for f in ok if f.code == PROF_ANCHOR] == []
    assert skipped == []
    # broken: both must-hold anchors fire
    bad, _ = rp.check_profile_baseline(
        {"train_step": _report(matmul_share=0.3),
         "gpt_decode": _report(vs_roofline=40.0)}, base, live)
    assert sorted(f.site for f in bad if f.code == PROF_ANCHOR) == \
        ["matmul_time_share_floor", "measured_vs_roofline"]
    # degraded (CPU): anchors SKIP with reasons — never silently pass,
    # never spuriously fail
    none, skipped = rp.check_profile_baseline(
        {"train_step": _report(device=False),
         "gpt_decode": _report(device=False)}, base, live)
    assert [f for f in none if f.code == PROF_ANCHOR] == []
    assert {s["program"] for s in skipped} == set(live)
    # a typo'd kind must fail loudly, not disable the invariant
    typo = {"budgets": {}, "anchors": {
        "train_step": {"kind": "matmul_share_floor"}}}
    fs, _ = rp.check_profile_baseline({"train_step": _report()}, typo,
                                      ["train_step"])
    assert [f.site for f in fs if f.code == PROF_ANCHOR] == \
        ["unknown-kind"]


def test_update_baseline_preserves_anchors_and_tolerance():
    base = {"budgets": {"p": {"dispatch_ms": 99.0}},
            "anchors": {"p": {"kind": "measured_vs_roofline",
                              "max_ratio": 3.0}},
            "tolerance": 1.7, "notes": {"p": "why"}}
    new = rp.updated_profile_baseline(base, {"p": _report(12.0)})
    assert new["budgets"]["p"]["dispatch_ms"] == pytest.approx(12.0)
    assert new["anchors"] == base["anchors"]
    assert new["tolerance"] == 1.7
    assert new["notes"] == {"p": "why"}


def test_committed_baseline_parses_and_names_live_programs():
    """tools/tpuprof_baseline.json must stay loadable, carry both
    must-hold anchors, and name only programs the registry still has
    (the stale check runs against the committed file without building
    anything)."""
    path = os.path.join(ROOT, "tools", "tpuprof_baseline.json")
    base = rp.load_profile_baseline(path)
    kinds = {a["kind"] for a in base.get("anchors", {}).values()}
    assert {"matmul_time_share_floor", "measured_vs_roofline"} <= kinds
    from paddle_tpu.compilation import registry
    live = registry.names(tag="manifest")
    stale, _ = rp.check_profile_baseline({}, base, live)
    assert [f for f in stale if f.code == STALE_PROF_PROGRAM] == []


# ---------------------------------------------------------------------------
# live smoke: one tiny registry program end-to-end + the gauges
# ---------------------------------------------------------------------------

@pytest.mark.timeout(280)
def test_live_tpuprof_cli_profiles_and_roundtrips_baseline(tmp_path):
    """Profile ONE tiny registry program end-to-end through the REAL
    CLI, in a SUBPROCESS: the report names its kernels and carries
    real dispatch medians, `--update-baseline` writes a baseline the
    same report re-gates clean, and the terminal line satisfies the
    _have_result contract. Subprocess on purpose — a jax.profiler
    session permanently slows every later XLA compile in its process
    ~1.5x (measured 2026-08-04), which an in-suite session would tax
    the whole tier-1 tail with."""
    import subprocess
    import sys
    base = tmp_path / "tpuprof_baseline.json"
    art = tmp_path / "report.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "tpuprof.py"),
         "--programs", "llama_decode",
         "--baseline", str(base), "--update-baseline",
         "--json", str(art),
         "--rounds", "1", "--inner", "2", "--profile-dispatches", "1"],
        capture_output=True, text=True, timeout=240, cwd=ROOT, env=env)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    term = json.loads(r.stdout.strip().splitlines()[-1])
    assert term["gate"] == "pass"
    rep = json.load(open(art))["reports"]["llama_decode"]
    assert rep["dispatch"]["median_ms"] > 0
    assert rep["modeled"]["kernel_count"] > 0
    assert rep["modeled"]["top_kernels"]
    if not rep["had_device_plane"]:      # CPU backend: the degrade path
        assert rep["join"]["available"] is False
        assert "device plane" in rep["join"]["reason"]
    # the written baseline re-gates the same report clean (in-process,
    # zero compiles)
    loaded = rp.load_profile_baseline(str(base))
    assert loaded["budgets"]["llama_decode"]["dispatch_ms"] > 0
    fs, _ = rp.check_profile_baseline({"llama_decode": rep}, loaded,
                                      ["llama_decode"],
                                      require_all=True)
    assert fs == []


def test_engine_tick_model_eff_gauge_and_stats():
    """The live serving half of ISSUE 14: a ticking engine exports
    ptpu_engine_tick_model_eff (modeled bytes / measured tick time as
    a bandwidth fraction) and mirrors it in stats() — the same value
    serve.py surfaces under /healthz engine.tick_model_eff."""
    from paddle_tpu import obs
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.framework import random as _rng
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=128, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=64))
    eng = ContinuousBatchingEngine(model, slots=2, max_len=32,
                                   cache_dtype="float32",
                                   tick_tokens=2,
                                   prefill_buckets=(8,))
    try:
        eng.generate(np.zeros(4, np.int64), max_new_tokens=4)
        st = eng.stats()
        assert st["tick_model_eff"] > 0
        g = obs.metrics.registry.get("ptpu_engine_tick_model_eff")
        assert g is not None and g.value() == pytest.approx(
            eng.last_tick_model_eff)
    finally:
        eng.stop()


def test_fit_exports_train_mfu_gauges():
    """The live training half: one tiny fit exports ptpu_train_mfu +
    ptpu_train_step_seconds through the shared obs/efficiency.py
    formula (param count x 6 x tokens over measured seconds)."""
    import paddle_tpu as paddle
    from paddle_tpu import obs
    from paddle_tpu.hapi import Model
    from paddle_tpu.obs import efficiency as eff
    paddle.seed(0)
    net = paddle.nn.Linear(8, 4)
    m = Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.1, parameters=net.parameters()),
        loss=lambda out, y: ((out - y) ** 2).mean())
    xs = np.random.RandomState(0).rand(8, 8).astype("float32")
    ys = np.zeros((8, 4), np.float32)
    from paddle_tpu.io.dataloader import DataLoader, TensorDataset
    loader = DataLoader(TensorDataset([xs, ys]), batch_size=4)
    m.fit(loader, epochs=1, verbose=0)
    g_mfu = obs.metrics.registry.get(eff.MFU_GAUGE)
    g_sec = obs.metrics.registry.get(eff.STEP_SECONDS_GAUGE)
    assert g_mfu is not None and g_mfu.value() > 0
    assert g_sec is not None and g_sec.value() > 0
    # the gauge is the shared formula, not a third derivation:
    # batch 4 x 36 params (8x4 + 4) -> 6 * N * B tokens at the
    # recorded seconds reproduces the same order of magnitude
    assert g_mfu.value() == pytest.approx(
        eff.mfu(eff.train_step_flops(36, 4), g_sec.value()), rel=0.5)
