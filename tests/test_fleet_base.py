"""fleet base surface tests: Fleet facade, role makers, UtilBase,
MultiSlot data generators (reference: base/role_maker.py,
base/util_factory.py, data_generator/data_generator.py)."""
import io

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet as fleet_mod
from paddle_tpu.distributed.fleet import (DataGenerator, Fleet,
                                          MultiSlotDataGenerator,
                                          MultiSlotStringDataGenerator,
                                          PaddleCloudRoleMaker, Role,
                                          UserDefinedRoleMaker, UtilBase)


def test_fleet_object_mirrors_module():
    f = Fleet()
    f.init(is_collective=True)
    assert f.worker_num() == fleet_mod.worker_num()
    assert f.worker_index() == fleet_mod.worker_index()
    assert f.is_first_worker() == fleet_mod.is_first_worker()
    assert f.is_worker() and not f.is_server()
    f.init_worker()    # PS lifecycle: no-ops on the collective path
    f.stop_worker()
    m = nn.Linear(4, 2)
    assert f.distributed_model(m) is not None
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=m.parameters())
    assert f.distributed_optimizer(opt) is opt


def test_role_makers():
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker() and not rm.is_server()
    assert rm.worker_index() >= 0 and rm.worker_num() >= 1

    u = UserDefinedRoleMaker(current_id=3, worker_num=8, role=Role.WORKER,
                             worker_endpoints=[f"h{i}:90" for i in
                                               range(8)])
    assert u.worker_index() == 3
    assert u.worker_num() == 8
    assert not u.is_first_worker()
    assert len(u._get_trainer_endpoints()) == 8


def test_util_file_shard():
    files = [f"part-{i:03d}" for i in range(10)]
    shards = []
    for idx in range(3):
        util = UtilBase(UserDefinedRoleMaker(current_id=idx, worker_num=3))
        shards.append(util.get_file_shard(files))
    # 10 files over 3 workers: 4/3/3, disjoint, order-preserving
    assert [len(s) for s in shards] == [4, 3, 3]
    assert sum(shards, []) == files
    with pytest.raises(TypeError):
        UtilBase().get_file_shard("not-a-list")


def test_util_single_world_collectives():
    util = UtilBase(UserDefinedRoleMaker(current_id=0, worker_num=1))
    out = util.all_reduce(np.arange(4.0))
    np.testing.assert_allclose(out, np.arange(4.0))
    assert len(util.all_gather(np.ones(2))) == 1
    util.barrier()   # no-op, must not hang


class _WordsGen(MultiSlotStringDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            w, label = line.strip().split("\t")
            yield [("words", w.split()), ("label", [label])]
        return local_iter


def test_multislot_string_generator():
    gen = _WordsGen()
    gen.set_batch(2)
    buf = io.StringIO()
    gen._stream(["1926 08 17\t1\n", "5 6\t0\n"], out=buf)
    lines = buf.getvalue().splitlines()
    assert lines == ["3 1926 08 17 1 1", "2 5 6 1 0"]


class _NumGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            yield [("ids", [1, 2, 3]), ("score", [0.5])]
        return local_iter


def test_multislot_numeric_generator_tracks_dtype():
    gen = _NumGen()
    buf = io.StringIO()
    gen._stream(["x"], out=buf)
    assert buf.getvalue() == "3 1 2 3 1 0.5\n"
    assert gen._proto_info == [("ids", "uint64"), ("score", "float")]


def test_base_generator_requires_overrides():
    g = DataGenerator()
    with pytest.raises(NotImplementedError):
        g.generate_sample("x")
    with pytest.raises(NotImplementedError):
        g._gen_str([("a", [1])])


def test_local_fs(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS, HDFSClient
    fs = LocalFS()
    root = str(tmp_path)
    fs.mkdirs(root + "/a/b")
    fs.touch(root + "/a/x.txt")
    with pytest.raises(FileExistsError):
        fs.touch(root + "/a/x.txt", exist_ok=False)
    assert fs.is_dir(root + "/a/b") and fs.is_file(root + "/a/x.txt")
    dirs, files = fs.ls_dir(root + "/a")
    assert dirs == ["b"] and files == ["x.txt"]
    assert fs.list_dirs(root + "/a") == ["b"]
    fs.mv(root + "/a/x.txt", root + "/a/y.txt")
    assert fs.is_exist(root + "/a/y.txt")
    with pytest.raises(FileNotFoundError):
        fs.mv(root + "/nope", root + "/z", test_exists=True)
    fs.delete(root + "/a")
    assert not fs.is_exist(root + "/a")
    assert not fs.need_upload_download()
    # hadoop-less HDFSClient raises an actionable error lazily
    h = HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(RuntimeError, match="hadoop"):
        h.is_exist("/x")
