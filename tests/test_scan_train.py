"""Fused K-step training loop (PR 4): scan_steps bitwise identity,
lazy losses, double-buffered prefetch, watchdog scaling, and the
2-programs-per-drifting-epoch trace-counter guarantee.

The identity tests are BITWISE (np.array_equal, not allclose): the
scanned window reuses the per-step program's fwd/bwd closure verbatim,
and at these geometries the trajectories match to the last ulp — drift
HERE means the fused path changed training semantics (counter/LR/RNG
cadence or update math). NB the bitwise property is geometry-pinned,
not universal: identical jaxprs can still compile to differently-
vectorized reductions inside a scan body (observed: last-ulp CE-loss
drift at batch 32, 16->64->2 on CPU from identical params+data), which
is why these tests pin exact shapes rather than sampling.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import TrainStep


def _net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 16))


def _opt(m, sched=False):
    lr = paddle.optimizer.lr.StepDecay(learning_rate=0.05, step_size=2,
                                       gamma=0.5) if sched else 0.05
    return paddle.optimizer.AdamW(learning_rate=lr,
                                  parameters=m.parameters())


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8, 16).astype("float32"),
            rng.randn(n, 8, 16).astype("float32"))


def _params_bitwise(a, b):
    return all(np.array_equal(np.asarray(a.params[n]),
                              np.asarray(b.params[n])) for n in a.params)


def _loss(o, y):
    return F.mse_loss(o, y)


# ---------------------------------------------------------------------------
# scanned-vs-sequential identity
# ---------------------------------------------------------------------------

def test_scan_matches_sequential_bitwise_with_trailing_window():
    """10 steps, K=4 (K does not divide 10): two fused windows + two
    per-step trailing calls must be bitwise the 10-step sequential run
    — losses AND parameters."""
    xs, ys = _data(10)

    paddle.seed(123)
    m1 = _net()
    s1 = TrainStep(m1, _loss, _opt(m1))
    seq = [float(s1(xs[i], ys[i])) for i in range(10)]

    paddle.seed(123)
    m2 = _net()
    s2 = TrainStep(m2, _loss, _opt(m2))
    fused = []
    for w in range(2):
        win = s2.scan_steps(4, xs[w * 4:(w + 1) * 4], ys[w * 4:(w + 1) * 4])
        assert tuple(win.shape) == (4,)
        fused.extend(np.asarray(win.value).tolist())
    for i in (8, 9):
        fused.append(float(s2(xs[i], ys[i])))

    assert np.array_equal(np.asarray(seq), np.asarray(fused))
    assert _params_bitwise(s1, s2)
    assert s2.step_count == 10 and s2.update_count == 10


def test_scan_accumulation_and_lr_schedule_bitwise():
    """Gradient merge (accumulate_steps=2) + a per-update LR schedule:
    the in-window lax.cond cadence, the host-precomputed lr vector, and
    a trailing UNFLUSHED micro-step + flush must all be bitwise the
    sequential run's."""
    xs, ys = _data(9, seed=3)

    paddle.seed(11)
    m1 = _net()
    s1 = TrainStep(m1, _loss, _opt(m1, sched=True), accumulate_steps=2)
    seq = [float(s1(xs[i], ys[i])) for i in range(9)]
    s1.flush_accumulation()

    paddle.seed(11)
    m2 = _net()
    s2 = TrainStep(m2, _loss, _opt(m2, sched=True), accumulate_steps=2)
    fused = []
    for w in range(2):
        win = s2.scan_steps(4, xs[w * 4:(w + 1) * 4], ys[w * 4:(w + 1) * 4])
        fused.extend(np.asarray(win.value).tolist())
    fused.append(float(s2(xs[8], ys[8])))   # trailing micro-step
    s2.flush_accumulation()

    assert np.array_equal(np.asarray(seq), np.asarray(fused))
    assert _params_bitwise(s1, s2)
    assert s1.update_count == s2.update_count == 5
    # LR schedules advanced identically
    assert float(s1.optimizer.get_lr()) == float(s2.optimizer.get_lr())


def test_parallel_scan_matches_sequential_bitwise():
    """ParallelTrainStep.scan_steps under dp8 / ZeRO-2: the GSPMD
    program inside the scan must reproduce the per-step trajectory."""
    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype("float32")

    paddle.seed(5)
    m1 = _net()
    p1 = dist.ParallelTrainStep(m1, _loss, _opt(m1), zero_stage=2)
    seq = [float(p1(x, x)) for _ in range(8)]

    paddle.seed(5)
    m2 = _net()
    p2 = dist.ParallelTrainStep(m2, _loss, _opt(m2), zero_stage=2)
    stacked = np.stack([x] * 4)
    fused = []
    for _ in range(2):
        fused.extend(np.asarray(
            p2.scan_steps(4, stacked, stacked).value).tolist())

    assert np.array_equal(np.asarray(seq), np.asarray(fused))
    assert _params_bitwise(p1, p2)


def test_scan_steps_rejects_bad_window():
    m = _net()
    s = TrainStep(m, _loss, _opt(m))
    xs, ys = _data(4)
    with pytest.raises(ValueError):
        s.scan_steps(0, xs, ys)
    with pytest.raises(ValueError):
        s.scan_steps(3, xs, ys)    # leading dim 4 != K=3


def test_parallel_scan_check_nan_inf_wiring():
    """FLAGS_check_nan_inf armed: a finite window passes through (the
    check takes the raw stacked-loss array, not the Tensor wrapper) and
    a diverged window raises at the window boundary."""
    from paddle_tpu.framework import flags as fw_flags
    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype("float32")
    paddle.seed(5)
    m = _net()
    p = dist.ParallelTrainStep(m, _loss, _opt(m), zero_stage=2)
    stacked = np.stack([x] * 4)
    fw_flags.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        losses = p.scan_steps(4, stacked, stacked)   # finite: no raise
        assert np.isfinite(np.asarray(losses.value)).all()
        bad = stacked.copy()
        bad[1] = np.nan
        with pytest.raises(FloatingPointError):
            p.scan_steps(4, bad, bad)
    finally:
        fw_flags.set_flags({"FLAGS_check_nan_inf": 0})


# ---------------------------------------------------------------------------
# watchdog: deadline scales to the window, NaN storm from stacked losses
# ---------------------------------------------------------------------------

def test_watchdog_deadline_scales_to_window():
    from paddle_tpu.distributed.resilience import StepTimeout, StepWatchdog
    dog = StepWatchdog(deadline=0.15)

    def slow_window():
        time.sleep(0.4)
        return [0.5]

    # one per-step budget: hangs
    with pytest.raises(StepTimeout):
        dog.run(slow_window)
    # the K-step window gets K budgets: passes
    assert dog.run(slow_window, deadline_scale=4) == [0.5]
    dog.close()


def test_watchdog_nan_storm_from_stacked_losses():
    from paddle_tpu.distributed.resilience import NanInfStorm, StepWatchdog
    dog = StepWatchdog(deadline=None, nan_limit=3)
    # a storm INSIDE one stacked window fires
    with pytest.raises(NanInfStorm):
        dog.run(lambda: paddle.to_tensor(
            np.array([1.0, np.nan, np.nan, np.nan], np.float32)))
    # ...and the consecutive streak spans window boundaries
    dog2 = StepWatchdog(deadline=None, nan_limit=3)
    dog2.run(lambda: paddle.to_tensor(
        np.array([1.0, 2.0, np.nan, np.nan], np.float32)))
    with pytest.raises(NanInfStorm):
        dog2.run(lambda: paddle.to_tensor(
            np.array([np.nan, 1.0], np.float32)))
    # a finite step in between resets the streak
    dog3 = StepWatchdog(deadline=None, nan_limit=3)
    dog3.run(lambda: paddle.to_tensor(
        np.array([np.nan, 1.0, np.nan, np.nan], np.float32)))
    dog3.run(lambda: [0.25])
    dog3.close()


# ---------------------------------------------------------------------------
# prefetch pipeline
# ---------------------------------------------------------------------------

def _ds(n):
    from paddle_tpu.io.dataloader import Dataset

    class DS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype("float32")
            self.y = rng.randn(n, 4).astype("float32")

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    return DS()


def test_prefetch_to_device_windows_and_tail():
    import jax
    from paddle_tpu.io.dataloader import DataLoader, prefetch_to_device
    ds = _ds(60)   # 8 batches of 8 except a 4-sample trailer
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    wins = list(prefetch_to_device(loader, 3, depth=2))
    # 7 full-size batches -> 2 windows of 3 + tail [1 batch of 8] ...
    # the size-4 trailer can't stack with the size-8 prefix
    assert [w.full for w in wins] == [True, True, False, False]
    full = wins[0]
    assert isinstance(full.data[0], jax.Array)
    assert full.data[0].shape == (3, 8, 8)
    assert len(wins[2]) == 1 and len(wins[3]) == 1
    # order is preserved: rows of window 0 are batches 0..2
    row0 = next(iter(full.rows()))
    assert np.array_equal(np.asarray(row0[0]), ds.x[:8])

    # loader errors propagate to the consumer
    class Boom:
        def __iter__(self):
            yield (np.zeros((2, 4), np.float32),)
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(prefetch_to_device(Boom(), 4))


# ---------------------------------------------------------------------------
# hapi driver: lazy losses, callback alignment, 2-program epochs
# ---------------------------------------------------------------------------

class _Rec:
    """Records (step, loss-object) per batch without coercing."""

    def __init__(self):
        self.steps, self.losses = [], []

    def make(self):
        from paddle_tpu.hapi.callbacks import Callback
        rec = self

        class CB(Callback):
            def on_train_batch_end(self, step, logs=None):
                rec.steps.append(step)
                rec.losses.append(logs["loss"])

        return CB()


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    from paddle_tpu.hapi import Model
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  loss=_loss)
    return model


def test_fit_fused_matches_per_step_and_log_alignment():
    """Model.fit(scan_steps=4) over a drifting-length epoch: callbacks
    see the same step indices, the same (bitwise) losses as the
    per-step loop, losses arrive LAZY, and exactly 2 programs compile
    (scanned window + trailing per-step)."""
    from paddle_tpu.hapi.lazy import LazyLoss
    r1, r2 = _Rec(), _Rec()
    m1 = _model()
    m1.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
           callbacks=[r1.make()], scan_steps=1)
    m2 = _model()
    m2.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
           callbacks=[r2.make()], scan_steps=4)

    assert r1.steps == r2.steps == list(range(10))
    assert all(isinstance(v, LazyLoss) for v in r2.losses)
    # lazy losses format like floats (ProgBarLogger's log_freq path)
    assert f"{r2.losses[0]:.4f}" == f"{float(r2.losses[0]):.4f}"
    l1 = np.asarray([float(v) for v in r1.losses])
    l2 = np.asarray([float(v) for v in r2.losses])
    assert np.array_equal(l1, l2)
    assert _params_bitwise(m1._train_step, m2._train_step)

    # trace counter: the drifting-length epoch (2 windows of 4 + 2
    # trailing) compiled exactly TWO programs; a second epoch adds none
    assert m2._train_step._trace_count == 2
    m2.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
           scan_steps=4)
    assert m2._train_step._trace_count == 2


def test_fit_fused_respects_num_iters_and_accumulation():
    """num_iters capping mid-window falls back to per-step rows;
    accumulate_grad_batches>1 keeps its update cadence through fused
    windows."""
    r = _Rec()
    m = _model()
    m.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
          callbacks=[r.make()], scan_steps=4, num_iters=6)
    assert r.steps == list(range(6))
    assert m._train_step.step_count == 6

    m2 = _model()
    m2.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
           scan_steps=4, accumulate_grad_batches=2)
    assert m2._train_step.accumulate_steps == 2
    assert m2._train_step.update_count == 5    # 10 batches / k=2

    # bitwise vs the per-step accumulation loop
    m3 = _model()
    m3.fit(_ds(80), batch_size=8, epochs=1, shuffle=False, verbose=0,
           scan_steps=1, accumulate_grad_batches=2)
    assert _params_bitwise(m2._train_step, m3._train_step)


def test_train_batch_lazy_and_sync_counter():
    """train_batch keeps its [scalar] contract but defers the
    device->host sync to the read; the sync counter sees exactly one
    fetch per window."""
    from paddle_tpu.framework import syncs
    from paddle_tpu.hapi.lazy import LazyLoss
    m = _model()
    x = np.random.RandomState(0).randn(8, 8).astype("float32")
    y = np.random.RandomState(1).randn(8, 4).astype("float32")
    m.train_batch([paddle.to_tensor(x)], [paddle.to_tensor(y)])  # compile
    before = syncs.sync_count()
    (loss,) = m.train_batch([paddle.to_tensor(x)], [paddle.to_tensor(y)])
    assert isinstance(loss, LazyLoss)
    assert syncs.sync_count() == before          # dispatch only, no sync
    v1 = float(loss)
    assert syncs.sync_count() == before + 1      # the read is the sync
    v2 = float(loss)
    assert v1 == v2
    assert syncs.sync_count() == before + 1      # cached thereafter


def test_evaluate_batches_the_loss_fetch():
    """evaluate() syncs ONCE for all per-batch losses instead of once
    per batch."""
    from paddle_tpu.framework import syncs
    m = _model()
    m.fit(_ds(16), batch_size=8, epochs=1, verbose=0)   # warm infer path
    m.evaluate(_ds(40), batch_size=8, verbose=0)        # warm eval prog
    before = syncs.sync_count()
    logs = m.evaluate(_ds(40), batch_size=8, verbose=0)
    assert np.isfinite(logs["loss"])
    assert syncs.sync_count() - before == 1


def test_fit_fused_under_watchdog_nan_storm(tmp_path, monkeypatch):
    """A NaN-poisoned dataset under the armed watchdog raises
    NanInfStorm out of the FUSED loop (stacked-loss scan) and leaves
    the checkpoint-on-failure artifact."""
    from paddle_tpu.distributed.resilience import NanInfStorm
    monkeypatch.setenv("PADDLE_TPU_STEP_TIMEOUT", "60")
    from paddle_tpu.io.dataloader import Dataset

    class BadDS(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            x = np.full((8,), np.nan, np.float32)
            return x, np.zeros((4,), np.float32)

    m = _model()
    with pytest.raises(NanInfStorm):
        m.fit(BadDS(), batch_size=8, epochs=1, verbose=0, scan_steps=4,
              save_dir=str(tmp_path))
    assert (tmp_path / "on_failure.pdparams").exists()
