"""DataLoader/Dataset tests (reference style: test_dataloader_*.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset,
                           DistributedBatchSampler, IterableDataset,
                           TensorDataset, random_split)


class SquareDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        x = np.full((3,), i, dtype="float32")
        return x, np.int64(i % 2)

    def __len__(self):
        return self.n


class CountStream(IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.full((2,), i, dtype="float32")


def test_map_dataset_loader():
    ds = SquareDataset(10)
    loader = DataLoader(ds, batch_size=4, shuffle=False, drop_last=False)
    batches = list(loader)
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape == [4, 3] and y.shape == [4]
    np.testing.assert_array_equal(x.numpy()[:, 0], [0, 1, 2, 3])


def test_loader_workers_match_serial():
    ds = SquareDataset(23)
    serial = [x.numpy() for x, _ in DataLoader(ds, batch_size=5)]
    threaded = [x.numpy() for x, _ in DataLoader(ds, batch_size=5,
                                                 num_workers=3)]
    assert len(serial) == len(threaded)
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a, b)


def test_iterable_dataset():
    loader = DataLoader(CountStream(7), batch_size=3, drop_last=True)
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0].shape == [3, 2]


def test_batch_sampler_drop_last():
    ds = SquareDataset(10)
    bs = BatchSampler(ds, batch_size=4, drop_last=True)
    assert len(bs) == 2
    assert all(len(b) == 4 for b in bs)


def test_distributed_batch_sampler_covers_all():
    ds = SquareDataset(11)
    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                    rank=rank)
        for batch in s:
            seen.extend(batch)
    # padded to multiple of 4: every index appears at least once
    assert set(range(11)) <= set(seen)
    # each rank sees the same number of batches
    lens = [len(list(DistributedBatchSampler(ds, batch_size=2,
                                             num_replicas=4, rank=r)))
            for r in range(4)]
    assert len(set(lens)) == 1


def test_tensor_dataset_and_split():
    xs = paddle.to_tensor(np.random.randn(10, 4).astype("float32"))
    ys = paddle.to_tensor(np.arange(10, dtype="int64"))
    ds = TensorDataset([xs, ys])
    assert len(ds) == 10
    a, b = random_split(ds, [7, 3])
    assert len(a) == 7 and len(b) == 3


class TestMultiprocessWorkers:
    """worker_mode='process': forked fetch + numpy collate in children
    (reference dataloader_iter.py multiprocess path)."""

    def _ds(self, n=32):
        import numpy as np
        from paddle_tpu.io.dataloader import Dataset

        class DS(Dataset):
            def __getitem__(self, i):
                return (np.full((3,), i, np.float32),
                        np.asarray([i % 2], np.int64))

            def __len__(self):
                return n

        return DS()

    def test_order_and_content(self):
        import numpy as np
        from paddle_tpu.io.dataloader import DataLoader
        dl = DataLoader(self._ds(), batch_size=4, num_workers=2,
                        worker_mode="process")
        batches = list(dl)
        assert len(batches) == 8
        for bi, (x, y) in enumerate(batches):
            np.testing.assert_allclose(x.numpy()[:, 0],
                                       np.arange(bi * 4, bi * 4 + 4))

    def test_worker_error_propagates(self):
        import pytest
        from paddle_tpu.io.dataloader import DataLoader, Dataset

        class Bad(Dataset):
            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom at 5")
                import numpy as np
                return np.zeros(2, np.float32)

            def __len__(self):
                return 8

        dl = DataLoader(Bad(), batch_size=2, num_workers=2,
                        worker_mode="process")
        with pytest.raises(RuntimeError, match="boom at 5"):
            list(dl)

    def test_invalid_mode_raises(self):
        import pytest
        from paddle_tpu.io.dataloader import DataLoader
        with pytest.raises(ValueError, match="worker_mode"):
            DataLoader(self._ds(), batch_size=2, worker_mode="fiber")
