"""Topology-elastic checkpoints (ISSUE 12).

Every train-state checkpoint carries a layout manifest (mesh shape +
axis names, ZeRO stage, per-leaf sharding specs, scan K, device count)
and restores onto a DIFFERENT topology — mesh reshape, 8->4->8 virtual
devices, ZeRO-2<->3, changed fused-window K — via the streaming
reshard path (canonical-layout assembly + re-placement, ~one leaf of
peak host memory). A truncated/bit-flipped shard raises
CheckpointCorrupt NAMING the offending leaf; the supervisor falls back
to the previous verified entry; a reshard killed mid-stream leaves the
checkpoint untouched and costs one restart-budget strike.
"""
import glob
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import resilience as resil
from paddle_tpu.distributed.resilience import (CheckpointCorrupt,
                                               FaultInjected,
                                               FaultInjector)
from paddle_tpu.hapi import Model
from paddle_tpu.io.dataloader import DataLoader

FAST_BACKOFF = resil.RetryPolicy(max_attempts=16, base_delay=0.0,
                                 jitter=0.0)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _build_step(degrees, zero_stage):
    dist.set_mesh(None)
    dist.init_mesh(degrees)
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=net.parameters())
    return dist.ParallelTrainStep(net, lambda o, y: F.mse_loss(o, y),
                                  opt, zero_stage=zero_stage)


def _batch(seed=5):
    rng = np.random.RandomState(seed)
    return (paddle.to_tensor(rng.randn(8, 8).astype("float32")),
            paddle.to_tensor(rng.randn(8, 8).astype("float32")))


def _state_bitwise(a, b):
    import jax
    for n in a.params:
        if not np.array_equal(np.asarray(a.params[n]),
                              np.asarray(b.params[n])):
            return False
    la = jax.tree_util.tree_leaves(a.opt_state)
    lb = jax.tree_util.tree_leaves(b.opt_state)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _truncate_shards(path):
    """Corrupt the committed checkpoint's DATA (marker + layout kept):
    the top-level OCDBT value files hold the array bytes."""
    files = glob.glob(os.path.join(path, "d", "*"))
    assert files, "no data files found to corrupt"
    for f in files:
        with open(f, "r+b") as fh:
            fh.truncate(max(1, os.path.getsize(f) // 3))


# ---------------------------------------------------------------------------
# layout manifest
# ---------------------------------------------------------------------------

def test_layout_manifest_rides_the_commit(tmp_path):
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    a(x, y)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path, scan_steps=4)
    lay = dist.read_layout(path)
    assert lay["mesh"] == {"axes": ["dp", "sharding"], "shape": [4, 2]}
    assert lay["zero_stage"] == 3 and lay["scan_steps"] == 4
    assert lay["device_count"] == 8
    # every leaf is booked with spec/shape/dtype
    assert "params/0.weight" in lay["leaves"]
    w = lay["leaves"]["params/0.weight"]
    assert w["shape"] == [8, 16] and w["dtype"] == "float32"
    assert isinstance(w["spec"], list)          # mesh-sharded leaf
    assert lay["leaves"]["meta/step_count"]["spec"] == "host"
    # the manifest is INSIDE the committed dir (rides the atomic publish)
    assert os.path.exists(os.path.join(path, ckpt.LAYOUT_NAME))
    # no changes against itself; info-only change for a different K
    live = resil.train_state_layout(a, scan_steps=4)
    assert ckpt.layout_changes(lay, live) == []
    live1 = resil.train_state_layout(a, scan_steps=1)
    assert ckpt.layout_changes(lay, live1) == ["scan_steps: 4 -> 1"]


# ---------------------------------------------------------------------------
# reshard-on-restore: mesh / device count / ZeRO stage
# ---------------------------------------------------------------------------

def test_zero3_8_to_4_to_8_roundtrip_bitwise(tmp_path):
    """The satellite coverage item: ZeRO-3 state saved on 8 virtual
    devices restores BITWISE onto the 4-device slice, trains nothing,
    saves, and restores BITWISE back onto 8 — params, sharded optimizer
    slots, counters, and RNG all round-trip through two reshards."""
    import jax
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    for _ in range(3):
        a(x, y)
    rng_before = np.asarray(jax.random.key_data(
        paddle.framework.random.get_rng_state()))
    p8 = str(tmp_path / "ck8")
    dist.save_train_state(a, p8)

    b = _build_step({"dp": 2, "sharding": 2}, 3)   # 4-device slice
    events = []
    dist.restore_train_state(b, p8,
                             on_reshard=lambda s, l, c: events.append(c))
    assert len(events) == 1          # the reshard path actually ran
    assert any(c.startswith("device_count: 8 -> 4")
               for c in events[0])
    assert _state_bitwise(a, b)
    assert b.step_count == 3 and b.update_count == 3
    w = list(b.params.values())[0]
    assert dict(w.sharding.mesh.shape) == {"dp": 2, "sharding": 2}
    assert np.array_equal(
        np.asarray(jax.random.key_data(
            paddle.framework.random.get_rng_state())), rng_before)

    p4 = str(tmp_path / "ck4")
    dist.save_train_state(b, p4)
    assert dist.read_layout(p4)["device_count"] == 4

    c = _build_step({"dp": 4, "sharding": 2}, 3)   # grow back to 8
    dist.restore_train_state(c, p4,
                             on_reshard=lambda s, l, ch: events.append(ch))
    assert len(events) == 2
    assert _state_bitwise(a, c)
    assert c.step_count == 3 and c.update_count == 3


def test_zero_stage_change_restores_bitwise(tmp_path):
    """ZeRO-2 <-> ZeRO-3: same state tree, different placements — the
    reshard path re-places, values identical."""
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    for _ in range(2):
        a(x, y)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path)
    b = _build_step({"dp": 4, "sharding": 2}, 2)
    events = []
    dist.restore_train_state(b, path,
                             on_reshard=lambda s, l, c: events.append(c))
    assert len(events) == 1
    assert any(c == "zero_stage: 3 -> 2" for c in events[0])
    assert _state_bitwise(a, b)
    # and the resumed trajectory continues (stage change is a layout
    # change only — the math is topology-independent on this geometry)
    la = float(a(x, y))
    lb = float(b(x, y))
    np.testing.assert_allclose(lb, la, rtol=1e-5)


def test_dp_only_reshard_is_bitwise_and_exact_restore_is_fast_path(
        tmp_path):
    x, y = _batch()
    a = _build_step({"dp": 8}, 2)
    for _ in range(2):
        a(x, y)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path)
    # same topology: the fast path (no reshard event)
    b = _build_step({"dp": 8}, 2)
    events = []
    dist.restore_train_state(b, path,
                             on_reshard=lambda *args: events.append(args))
    assert events == []
    assert _state_bitwise(a, b)
    # dp-only shrink: 8 -> 4 devices, bitwise state
    c = _build_step({"dp": 4}, 2)
    dist.restore_train_state(c, path,
                             on_reshard=lambda *args: events.append(args))
    assert len(events) == 1
    assert _state_bitwise(a, c)


# ---------------------------------------------------------------------------
# corrupt-shard diagnostics + killed reshard
# ---------------------------------------------------------------------------

def test_corrupt_shard_raises_named_checkpoint_corrupt(tmp_path):
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    a(x, y)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path)
    _truncate_shards(path)
    ckpt.verify_checkpoint(path)     # marker intact: "otherwise committed"
    b = _build_step({"dp": 4, "sharding": 2}, 3)
    with pytest.raises(CheckpointCorrupt) as ei:
        dist.restore_train_state(b, path)
    # the error NAMES a leaf path, not an opaque unpickle/reshape error
    assert "leaf" in str(ei.value) and "/" in str(ei.value)
    # the reshard path reports corruption identically
    c = _build_step({"dp": 2, "sharding": 2}, 3)
    with pytest.raises(CheckpointCorrupt) as ei2:
        dist.restore_train_state(c, path)
    assert "leaf" in str(ei2.value)


def test_killed_reshard_leaves_checkpoint_untouched(tmp_path):
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    a(x, y)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path)
    snap = sorted(
        (os.path.relpath(p, path), os.path.getsize(p))
        for p in glob.glob(os.path.join(path, "**"), recursive=True)
        if os.path.isfile(p))
    b = _build_step({"dp": 2, "sharding": 2}, 3)
    with FaultInjector({"ckpt_reshard": 1}):
        with pytest.raises(FaultInjected):
            dist.restore_train_state(b, path)
    after = sorted(
        (os.path.relpath(p, path), os.path.getsize(p))
        for p in glob.glob(os.path.join(path, "**"), recursive=True)
        if os.path.isfile(p))
    assert snap == after
    dist.restore_train_state(b, path)      # next attempt succeeds
    assert _state_bitwise(a, b)


# ---------------------------------------------------------------------------
# supervisor: elastic resume policy
# ---------------------------------------------------------------------------

class _Rows:
    def __init__(self, xs, ys):
        self.xs, self.ys = xs, ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]


def _elastic_trainer(degrees, zero_stage=3, epochs=2):
    dist.set_mesh(None)
    dist.init_mesh(degrees)
    paddle.seed(11)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    model = Model(net)
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    model.prepare(optimizer=opt, loss=lambda o, y: F.mse_loss(o, y),
                  parallel={"zero_stage": zero_stage})
    rng = np.random.RandomState(5)
    xs = rng.randn(48, 8).astype("float32")
    ys = rng.randn(48, 8).astype("float32")
    loader = DataLoader(_Rows(xs, ys), batch_size=8, shuffle=False)
    return model, loader, {"epochs": epochs, "verbose": 0}


def _sup(model, loader, d, kw, **policy):
    from paddle_tpu.distributed.supervisor import TrainSupervisor
    policy.setdefault("ckpt_every", 4)
    policy.setdefault("max_to_keep", 3)
    return TrainSupervisor(model, loader, directory=str(d),
                           fit_kwargs=kw, backoff=FAST_BACKOFF, **policy)


def test_supervisor_elastic_resume_reshards_and_records(tmp_path):
    """Preempt an 8-device ZeRO-3 supervised run; a fresh supervisor on
    the SAME dir with a 4-device trainer reshards instead of crashing,
    completes, and the event is visible (manifest incident + counter +
    per-entry topology stamps)."""
    from paddle_tpu.distributed.supervisor import load_manifest
    d = tmp_path / "job"
    model, loader, kw = _elastic_trainer({"dp": 4, "sharding": 2})
    with FaultInjector({"preempt_signal": 1}):
        r = _sup(model, loader, d, kw).run()
    assert r.outcome == "preempted"

    model2, loader2, kw2 = _elastic_trainer({"dp": 2, "sharding": 2})
    r2 = _sup(model2, loader2, d, kw2).run()
    assert r2.outcome == "completed" and r2.final_step == 12
    assert r2.reshards == 1
    m = load_manifest(str(d))
    reshards = [i for i in m["incidents"] if i["kind"] == "reshard"]
    assert len(reshards) == 1
    assert reshards[0]["from"] == "dp4xsharding2"
    assert reshards[0]["to"] == "dp2xsharding2"
    assert int(m["reshards"]) == 1
    # satellite bugfix: every entry records the topology that wrote it
    topo_of = {e["name"]: e["topology"] for e in m["checkpoints"]}
    assert all(t and t.get("mesh") for t in topo_of.values())
    assert topo_of[m["last_good"]]["mesh"]["shape"] == [2, 2]


def test_supervisor_falls_back_past_corrupt_entry(tmp_path):
    """The corrupt-shard satellite end to end: the NEWEST checkpoint's
    shard data is truncated post-commit; resume discards it (incident
    recorded, marker stripped) and restores the previous verified
    entry, then completes."""
    from paddle_tpu.distributed.supervisor import load_manifest
    d = tmp_path / "job"
    model, loader, kw = _elastic_trainer({"dp": 4, "sharding": 2})
    with FaultInjector({"preempt_signal": 1}):
        r = _sup(model, loader, d, kw, ckpt_every=2).run()
    assert r.outcome == "preempted"
    steps = [s for s, _ in ckpt.list_checkpoints(str(d))]
    assert len(steps) >= 2
    newest = ckpt.latest_checkpoint(str(d))
    _truncate_shards(newest)

    model2, loader2, kw2 = _elastic_trainer({"dp": 4, "sharding": 2})
    r2 = _sup(model2, loader2, d, kw2, ckpt_every=2).run()
    assert r2.outcome == "completed" and r2.final_step == 12
    m = load_manifest(str(d))
    corrupt = [i for i in m["incidents"]
               if i["kind"] == "restore_corrupt"]
    assert corrupt and corrupt[0]["name"] == os.path.basename(newest)
    assert "leaf" in corrupt[0]["error"]
    # the corrupt entry lost its marker: out of every enumeration
    assert os.path.basename(newest) not in {
        os.path.basename(p) for _s, p in ckpt.list_checkpoints(str(d))} \
        or ckpt._committed(newest)  # unless re-published at that step


def test_supervisor_falls_back_after_persistent_restore_failure(
        tmp_path):
    """A non-corrupt restore failure on the newest entry is retried
    ONCE (one strike), then the next-older verified entry restores —
    the budget is never burned in place while an older checkpoint
    would heal the run."""
    from paddle_tpu.distributed.supervisor import load_manifest
    d = tmp_path / "job"
    model, loader, kw = _elastic_trainer({"dp": 4, "sharding": 2})
    with FaultInjector({"preempt_signal": 1}):
        r = _sup(model, loader, d, kw, ckpt_every=2).run()
    assert r.outcome == "preempted"
    assert len(ckpt.list_checkpoints(str(d))) >= 2

    # both attempts on the NEWEST entry die mid-reshard; the fall-back
    # restore of the older entry (third fire left unarmed) succeeds
    model2, loader2, kw2 = _elastic_trainer({"dp": 2, "sharding": 2})
    with FaultInjector({"ckpt_reshard": 2}):
        r2 = _sup(model2, loader2, d, kw2, ckpt_every=2).run()
    assert r2.outcome == "completed" and r2.final_step == 12
    assert r2.restarts >= 2              # retry + fall_back strikes
    m = load_manifest(str(d))
    actions = [i["action"] for i in m["incidents"]
               if i["kind"] == "restore_failed"]
    assert actions[:2] == ["retry", "fall_back"]
    names = [i["name"] for i in m["incidents"]
             if i["kind"] == "restore_failed"]
    assert names[0] == names[1]          # same (newest) entry twice
    # fall-back never DISCARDS the failing entry (that is the corrupt
    # path's move): no restore_corrupt incident, no stripped marker —
    # the entry simply stops being the resume target (later retention
    # GC may still prune it like any other superseded checkpoint)
    assert not any(i["kind"] == "restore_corrupt"
                   for i in m["incidents"])


def test_supervisor_resume_with_changed_scan_steps(tmp_path):
    """Resume with a different fused-window K (fused<->per-step): no
    reshard (state is identical), the run completes at the same final
    step, and the loss trajectory CONTINUES the unfaulted one — the
    bounded-drift gate (fused windows are bitwise-equal to sequential
    at tier-1 tested geometries; allclose pins the contract here)."""
    from paddle_tpu.distributed.supervisor import load_manifest
    d = tmp_path / "job"
    model, loader, kw = _elastic_trainer({"dp": 4, "sharding": 2})
    kw["scan_steps"] = 3
    with FaultInjector({"preempt_signal": 1}):
        r = _sup(model, loader, d, kw).run()
    assert r.outcome == "preempted"
    lay = dist.read_layout(ckpt.latest_checkpoint(str(d)))
    assert lay["scan_steps"] == 3

    model2, loader2, kw2 = _elastic_trainer({"dp": 4, "sharding": 2})
    kw2["scan_steps"] = 1
    r2 = _sup(model2, loader2, d, kw2).run()
    assert r2.outcome == "completed" and r2.final_step == 12
    assert r2.reshards == 0          # K change alone moves no shards
    m = load_manifest(str(d))
    assert not any(i["kind"] == "reshard" for i in m["incidents"])

    # trajectory gate: the fused->per-step chain ends where a clean
    # uninterrupted per-step run ends
    ref_model, ref_loader, ref_kw = _elastic_trainer(
        {"dp": 4, "sharding": 2})
    ref_model.fit(ref_loader, **ref_kw)
    final = _final_tree_of(d)
    for n, ref in ref_model._train_step.params.items():
        np.testing.assert_allclose(
            np.asarray(final["params"][n]), np.asarray(ref),
            rtol=1e-6, atol=1e-7)


def _final_tree_of(d):
    path = ckpt.latest_checkpoint(str(d))
    assert path is not None
    return ckpt.load_state_dict(path)


def test_retention_handles_mixed_topology_entries(tmp_path):
    """latest_checkpoint / gc_checkpoints over a directory whose
    entries were saved from DIFFERENT topologies: enumeration is
    layout-blind, GC never touches the last verified entry."""
    x, y = _batch()
    a = _build_step({"dp": 4, "sharding": 2}, 3)
    a(x, y)
    dist.save_train_state(a, str(tmp_path / "ckpt-2"))
    b = _build_step({"dp": 2, "sharding": 2}, 2)
    dist.restore_train_state(b, str(tmp_path / "ckpt-2"))
    b(x, y)
    dist.save_train_state(b, str(tmp_path / "ckpt-4"))
    c = _build_step({"dp": 8}, 1)
    dist.restore_train_state(c, str(tmp_path / "ckpt-4"))
    c(x, y)
    dist.save_train_state(c, str(tmp_path / "ckpt-6"))

    assert [s for s, _ in ckpt.list_checkpoints(str(tmp_path))] == \
        [2, 4, 6]
    assert ckpt.latest_checkpoint(str(tmp_path)).endswith("ckpt-6")
    layouts = [dist.read_layout(p)["mesh"]
               for _s, p in ckpt.list_checkpoints(str(tmp_path))]
    assert len({str(m) for m in layouts}) == 3   # three topologies
    deleted = ckpt.gc_checkpoints(str(tmp_path), max_to_keep=1)
    assert {os.path.basename(p) for p in deleted} == {"ckpt-2",
                                                      "ckpt-4"}
    # the last verified (newest) entry survives whatever its topology
    assert ckpt.latest_checkpoint(str(tmp_path)).endswith("ckpt-6")
    d = _build_step({"dp": 4, "sharding": 2}, 3)
    dist.restore_train_state(d, str(tmp_path / "ckpt-6"))
    assert _state_bitwise(c, d)


# ---------------------------------------------------------------------------
# hapi parallel engine under fit
# ---------------------------------------------------------------------------

def test_model_prepare_parallel_trains_on_mesh(tmp_path):
    """Model.prepare(parallel=...) routes fit through
    ParallelTrainStep; skip_windows works on the hybrid engine too."""
    from paddle_tpu.distributed.parallel_step import ParallelTrainStep
    model, loader, kw = _elastic_trainer({"dp": 4, "sharding": 2},
                                         epochs=1)
    model.fit(loader, **kw)
    step = model._train_step
    assert isinstance(step, ParallelTrainStep)
    assert step.zero_stage == 3 and step.step_count == 6
    w = step.params["0.weight"]
    assert dict(w.sharding.mesh.shape) == {"dp": 4, "sharding": 2}

    # skip_windows advances counters without training (TrainStep parity)
    model2, loader2, kw2 = _elastic_trainer({"dp": 4, "sharding": 2},
                                            epochs=1)
    model2.fit(loader2, skip_windows=[(2, 4)], **kw2)
    assert model2._train_step.step_count == 6
