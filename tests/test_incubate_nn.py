"""incubate.nn fused-layer tests (reference: incubate/nn over the fused
CUDA ops §2.4). Numeric checks compose the same math from unfused pieces."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.nn import (FusedBiasDropoutResidualLayerNorm,
                                    FusedDropoutAdd, FusedEcMoe,
                                    FusedFeedForward, FusedLinear,
                                    FusedMultiHeadAttention,
                                    FusedMultiTransformer,
                                    FusedTransformerEncoderLayer)


def _x(shape, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


def _np_ln(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


def _np_attention_block(m, x):
    """Re-derive FusedMultiHeadAttention's sub-block in numpy."""
    E = x.shape[-1]
    nh = m.num_heads
    hd = m.head_dim
    qkv = x @ m.qkv.weight.numpy() + m.qkv.bias.numpy()
    B, S, _ = x.shape
    q = qkv[..., :E].reshape(B, S, nh, hd)
    k = qkv[..., E:2 * E].reshape(B, S, nh, hd)
    v = qkv[..., 2 * E:].reshape(B, S, nh, hd)
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, E)
    return ctx @ m.out_proj.weight.numpy() + m.out_proj.bias.numpy()


@pytest.mark.parametrize("pre_ln", [True, False])
def test_fused_mha_matches_manual(pre_ln):
    paddle.seed(50)
    m = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                attn_dropout_rate=0.0,
                                normalize_before=pre_ln)
    m.eval()
    xt = _x((2, 8, 32), 1)
    x = xt.numpy()
    got = m(xt).numpy()
    w, b = m.ln.weight.numpy(), m.ln.bias.numpy()
    if pre_ln:
        want = x + _np_attention_block(m, _np_ln(x, w, b))
    else:
        want = _np_ln(x + _np_attention_block(m, x), w, b)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_fused_ffn_matches_manual(pre_ln):
    paddle.seed(51)
    m = FusedFeedForward(16, 64, dropout_rate=0.0, activation="relu",
                         normalize_before=pre_ln)
    m.eval()
    xt = _x((2, 6, 16), 2)
    x = xt.numpy()

    def ffn(h):
        h1 = np.maximum(h @ m.fc1.weight.numpy() + m.fc1.bias.numpy(), 0)
        return h1 @ m.fc2.weight.numpy() + m.fc2.bias.numpy()

    w, b = m.ln.weight.numpy(), m.ln.bias.numpy()
    want = (x + ffn(_np_ln(x, w, b))) if pre_ln \
        else _np_ln(x + ffn(x), w, b)
    np.testing.assert_allclose(m(xt).numpy(), want, rtol=2e-4, atol=2e-4)


def test_encoder_layer_runs_and_trains():
    paddle.seed(52)
    m = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
    xt = _x((2, 8, 32), 3)
    out = m(xt)
    assert out.shape == [2, 8, 32]
    loss = paddle.mean(out * out)
    loss.backward()
    g = m.fused_attn.qkv.weight._grad
    assert g is not None and float((np.asarray(g) ** 2).sum()) > 0


def test_fused_multi_transformer_cachekv_decode():
    """Incremental CacheKV decode must equal the full causal forward —
    the fused_multi_transformer_op contract."""
    paddle.seed(53)
    m = FusedMultiTransformer(32, 4, 64, num_layers=2)
    m.eval()
    xt = _x((1, 6, 32), 4)
    full = m(xt).numpy()

    caches = m.new_cache(1, 6)
    import jax.numpy as jnp
    outs = []
    for t in range(6):
        step = paddle.to_tensor(xt.numpy()[:, t:t + 1])
        y, caches = m(step, caches, jnp.int32(t))
        outs.append(y.numpy())
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-4)


def test_bias_dropout_residual_ln():
    paddle.seed(54)
    m = FusedBiasDropoutResidualLayerNorm(16, dropout_rate=0.0)
    m.eval()
    x, r = _x((2, 5, 16), 5), _x((2, 5, 16), 6)
    got = m(x, r).numpy()
    want = _np_ln(r.numpy() + x.numpy() + m.bias.numpy(),
                  m.ln.weight.numpy(), m.ln.bias.numpy())
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_dropout_add_and_fused_linear():
    m = FusedDropoutAdd(p=0.0)
    m.eval()
    x, y = _x((3, 4), 7), _x((3, 4), 8)
    np.testing.assert_allclose(m(x, y).numpy(), x.numpy() + y.numpy(),
                               rtol=1e-6)
    lin = FusedLinear(4, 8)
    assert lin(_x((3, 4), 9)).shape == [3, 8]


def test_fused_ec_moe():
    paddle.seed(55)
    B, S, H, E = 2, 8, 16, 4
    m = FusedEcMoe(H, 32, E, capacity_factor=2.0)
    x = _x((B, S, H), 10)
    gates = _x((B, S, E), 11)
    out = m(x, gates)
    assert out.shape == [B, S, H]
    # expert choice: each expert processes exactly k = S*cap/E tokens;
    # with cap=2, E=4, S=8 -> k=4 -> 16 expert-token slots over 8 tokens
    loss = paddle.mean(out * out)
    loss.backward()
    for p in (m.w1, m.w2):
        assert float((np.asarray(p._grad) ** 2).sum()) > 0
    # gate gradient flows too (differentiable routing weights)
    # capacity_factor=E/S edge: k=1
    m2 = FusedEcMoe(H, 32, E, capacity_factor=E / S)
    assert m2(x, gates).shape == [B, S, H]
