"""paddle.signal stft/istft tests (reference: python/paddle/signal.py) —
round-trip reconstruction + numpy reference comparisons."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.signal import istft, stft


def _sig(n=512, seed=0):
    rng = np.random.RandomState(seed)
    t = np.arange(n) / n
    return (np.sin(2 * np.pi * 13 * t) + 0.5 * rng.randn(n)).astype(
        "float32")


def test_stft_matches_numpy():
    x = _sig()
    n_fft, hop = 64, 16
    win = np.hanning(n_fft).astype("float32")
    got = stft(paddle.to_tensor(x), n_fft, hop_length=hop,
               window=paddle.to_tensor(win), center=False).numpy()
    n_frames = 1 + (len(x) - n_fft) // hop
    want = np.stack([np.fft.rfft(x[i * hop:i * hop + n_fft] * win)
                     for i in range(n_frames)], axis=1)
    assert got.shape == (n_fft // 2 + 1, n_frames)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_round_trip_reconstruction():
    x = _sig(1024)
    n_fft, hop = 128, 32
    win = paddle.to_tensor(np.hanning(n_fft).astype("float32"))
    spec = stft(paddle.to_tensor(x), n_fft, hop_length=hop, window=win)
    rec = istft(spec, n_fft, hop_length=hop, window=win,
                length=len(x)).numpy()
    np.testing.assert_allclose(rec, x, rtol=1e-3, atol=1e-4)


def test_round_trip_normalized_twosided():
    x = _sig(512, seed=3)
    n_fft, hop = 64, 16
    win = paddle.to_tensor(np.hanning(n_fft).astype("float32"))
    spec = stft(paddle.to_tensor(x), n_fft, hop_length=hop, window=win,
                normalized=True, onesided=False)
    assert spec.shape == [n_fft, 1 + len(x) // hop]
    rec = istft(spec, n_fft, hop_length=hop, window=win, normalized=True,
                onesided=False, length=len(x)).numpy()
    np.testing.assert_allclose(rec, x, rtol=1e-3, atol=1e-4)


def test_batched_and_grad():
    xs = np.stack([_sig(256, s) for s in range(3)])
    win = paddle.to_tensor(np.hanning(64).astype("float32"))
    xt = paddle.to_tensor(xs)
    xt.stop_gradient = False
    spec = stft(xt, 64, hop_length=32, window=win)
    assert spec.shape == [3, 33, 1 + 256 // 32]
    # gradient flows through |stft|^2
    import paddle_tpu.tensor as T
    power = T.mean(T.abs(spec) ** 2)
    power.backward()
    g = np.asarray(xt._grad)
    assert g.shape == xs.shape and np.abs(g).sum() > 0


def test_validation():
    x = paddle.to_tensor(_sig(128))
    with pytest.raises(ValueError, match="win_length"):
        stft(x, 64, win_length=100)
    with pytest.raises(ValueError, match="window length"):
        stft(x, 64, window=paddle.to_tensor(np.ones(10, "float32")))


def test_nola_violation_rejected():
    spec = stft(paddle.to_tensor(_sig(512)), 64, hop_length=16,
                window=paddle.to_tensor(np.hanning(64).astype("float32")))
    with pytest.raises(ValueError, match="NOLA"):
        istft(spec, 64, hop_length=64,
              window=paddle.to_tensor(np.hanning(64).astype("float32")))
    with pytest.raises(ValueError, match="win_length"):
        stft(paddle.to_tensor(_sig(128)), 64, win_length=0)
