"""GPTScannedBlocks (cfg.scan_layers): the depth-independent-compile
decoder stack.

Reference role: no analog — the reference's executor dispatches per-op
per-layer at runtime (SURVEY.md §3.3), so its "compile time" doesn't
grow with depth; under XLA the unrolled stack does, and scan-over-layers
is the TPU-native answer (flax nn.scan idiom). Parity obligations here
are internal: identical math to the unrolled stack, trainable under the
donated TrainStep, loud errors for the unsupported combinations.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny


def _ids(batch=2, seq=64, vocab=256):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(
        rng.randint(0, vocab, (batch, seq)).astype("int64"))


def _scanned_pair(**scan_cfg_kw):
    """(unrolled, scanned) GPT models with identical parameters;
    scan_cfg_kw adds config fields to the scanned model only."""
    paddle.seed(0)
    m_u = GPTForCausalLM(gpt_tiny())
    paddle.seed(1)  # different init seed: copy must erase the difference
    m_s = GPTForCausalLM(gpt_tiny(scan_layers=True, **scan_cfg_kw))
    m_s.gpt.blocks.load_from_blocks(m_u.gpt.blocks)
    sd_u = dict(m_u.named_parameters())
    for n, p in m_s.named_parameters():
        if not n.startswith("gpt.blocks."):
            p.value = sd_u[n].value
    return m_u, m_s


class TestScanLayersParity:
    def test_forward_matches_unrolled(self):
        m_u, m_s = _scanned_pair()
        ids = _ids()
        out_u, out_s = m_u(ids), m_s(ids)
        np.testing.assert_allclose(np.asarray(out_u.value),
                                   np.asarray(out_s.value),
                                   rtol=0, atol=1e-5)

    def test_eager_backward_matches_unrolled(self):
        # the scan is one tape op (tape.apply over jax.vjp) — per-layer
        # grads must equal the unrolled model's
        m_u, m_s = _scanned_pair()
        ids = _ids()
        GPTForCausalLM.loss_fn(m_u(ids), ids).backward()
        GPTForCausalLM.loss_fn(m_s(ids), ids).backward()
        sd_u = dict(m_u.named_parameters())
        sd_s = dict(m_s.named_parameters())
        g_stack = sd_s["gpt.blocks.attn__qkv__weight"].grad
        assert g_stack is not None
        for i in range(m_u.cfg.num_layers):
            g_i = sd_u[f"gpt.block_{i}.attn.qkv.weight"].grad
            np.testing.assert_allclose(np.asarray(g_i),
                                       np.asarray(g_stack[i]),
                                       rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sd_u["gpt.embeddings.word_embeddings.weight"].grad),
            np.asarray(sd_s["gpt.embeddings.word_embeddings.weight"].grad),
            rtol=1e-4, atol=1e-6)

    def test_recompute_policy_dots_matches_full(self):
        # "dots" saves matmul outputs instead of recomputing everything —
        # gradients must be identical either way
        ids = _ids(seq=32)
        gs = {}
        for pol in ("full", "dots"):
            paddle.seed(0)
            m = GPTForCausalLM(gpt_tiny(scan_layers=True, recompute=True,
                                        recompute_policy=pol))
            m.train()
            GPTForCausalLM.loss_fn(m(ids), ids).backward()
            gs[pol] = np.asarray(dict(m.named_parameters())
                                 ["gpt.blocks.attn__qkv__weight"].grad)
        np.testing.assert_allclose(gs["full"], gs["dots"], atol=1e-6)

    def test_bad_recompute_policy_raises(self):
        with pytest.raises(ValueError, match="recompute policy"):
            GPTForCausalLM(gpt_tiny(scan_layers=True,
                                    recompute_policy="bogus"))

    def test_jit_save_load_roundtrip(self, tmp_path):
        # scanned models must export (lax.scan -> StableHLO) and serve
        import paddle_tpu.jit as jit
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny(scan_layers=True))
        m.eval()
        ids = _ids(seq=16)
        ref = np.asarray(m(ids).value)
        prefix = str(tmp_path / "gpt_scan")
        jit.save(m, prefix, input_spec=[ids])
        loaded = jit.load(prefix)
        out = loaded(ids)
        out = out[0] if isinstance(out, (list, tuple)) else out
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_recompute_matches(self):
        paddle.seed(0)
        m_plain = GPTForCausalLM(gpt_tiny(scan_layers=True))
        paddle.seed(0)
        m_rc = GPTForCausalLM(gpt_tiny(scan_layers=True, recompute=True))
        ids = _ids()
        m_rc.train(), m_plain.train()
        GPTForCausalLM.loss_fn(m_plain(ids), ids).backward()
        GPTForCausalLM.loss_fn(m_rc(ids), ids).backward()
        for (n, p), (_, q) in zip(m_plain.named_parameters(),
                                  m_rc.named_parameters()):
            if p.grad is not None:
                np.testing.assert_allclose(np.asarray(p.grad),
                                           np.asarray(q.grad),
                                           rtol=1e-5, atol=1e-6,
                                           err_msg=n)


class TestScanLayersTraining:
    def test_trainstep_bf16_converges(self):
        # the exact 1.3B bench recipe at tiny scale: bf16 params, plain
        # Adam, per-block remat, scanned stack, donated whole-step program
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny(scan_layers=True, recompute=True))
        m.bfloat16()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     multi_precision=False,
                                     parameters=m.parameters())
        step = TrainStep(m, GPTForCausalLM.loss_fn, opt)
        ids = _ids()
        losses = [float(step(ids, ids)) for _ in range(6)]
        assert losses[-1] < losses[0] - 0.2, losses

    def test_param_count_matches_unrolled(self):
        m_u, m_s = _scanned_pair()
        n_u = sum(int(np.prod(p.shape)) for _, p in m_u.named_parameters())
        n_s = sum(int(np.prod(p.shape)) for _, p in m_s.named_parameters())
        assert n_u == n_s


class TestFusedLoss:
    def test_trajectory_matches_plain(self):
        # cfg.fused_loss_chunk changes only the loss composition, not
        # param creation — same seed must give the IDENTICAL trajectory
        import functools
        ids = _ids()
        traj = {}
        for tag, kw, lf in (
            ("plain", {}, GPTForCausalLM.loss_fn),
            ("fused", {"fused_loss_chunk": 32},
             functools.partial(GPTForCausalLM.fused_loss_fn,
                               chunk_size=32)),
        ):
            paddle.seed(0)
            m = GPTForCausalLM(gpt_tiny(**kw))
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters())
            step = TrainStep(m, lf, opt)
            traj[tag] = [float(step(ids, ids)) for _ in range(4)]
        np.testing.assert_allclose(traj["plain"], traj["fused"],
                                   rtol=1e-5)

    def test_functional_parity_with_ignore_index(self):
        import paddle_tpu.nn.functional as F
        import paddle_tpu.tensor as T
        rng = np.random.RandomState(0)
        N, H, V = 70, 16, 37  # non-multiple of chunk -> padding path
        x = paddle.to_tensor(rng.randn(N, H).astype("float32"))
        w = paddle.to_tensor(rng.randn(V, H).astype("float32"))
        lbl = rng.randint(0, V, (N,))
        lbl[::7] = -100
        lt = paddle.to_tensor(lbl.astype("int64"))
        x.stop_gradient = False
        w.stop_gradient = False
        loss_f = F.fused_linear_cross_entropy(x, w, lt, chunk_size=16)
        loss_f.backward()
        gx, gw = np.asarray(x.grad), np.asarray(w.grad)
        x.clear_grad(), w.clear_grad()
        logits = paddle.matmul(x, T.transpose(w, [1, 0]))
        loss_r = F.cross_entropy(logits, lt, ignore_index=-100)
        loss_r.backward()
        assert abs(float(loss_f) - float(loss_r)) < 1e-5
        np.testing.assert_allclose(gx, np.asarray(x.grad), atol=1e-6)
        np.testing.assert_allclose(gw, np.asarray(w.grad), atol=1e-6)

    def test_square_weight_raises(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(np.zeros((4, 8), "float32"))
        w = paddle.to_tensor(np.eye(8, dtype="float32"))
        lbl = paddle.to_tensor(np.zeros((4,), "int64"))
        with pytest.raises(ValueError, match="ambiguous"):
            F.fused_linear_cross_entropy(x, w, lbl)


class TestScanLayersDistributed:
    def test_dp_mp_step_matches_unrolled(self):
        # the stacked leaves carry (None,)+inner sharding annotations —
        # prove they are correct by training the scanned model under the
        # hybrid engine on the virtual mesh and matching the unrolled
        # model's loss trajectory exactly
        import paddle_tpu.distributed as dist
        dist.init_mesh({"dp": 2, "mp": 2})
        try:
            m_u, m_s = _scanned_pair()
            sd = dict(m_s.named_parameters())
            assert sd["gpt.blocks.attn__qkv__weight"].sharding_axes == \
                (None, None, "mp")
            assert sd["gpt.blocks.mlp__fc_out__weight"].sharding_axes == \
                (None, "mp", None)
            ids = _ids(batch=4)
            losses = {}
            for tag, m in (("unrolled", m_u), ("scanned", m_s)):
                opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                             parameters=m.parameters())
                step = dist.ParallelTrainStep(
                    m, GPTForCausalLM.loss_fn, opt)
                losses[tag] = [float(step(ids, ids)) for _ in range(3)]
            np.testing.assert_allclose(losses["unrolled"],
                                       losses["scanned"],
                                       rtol=2e-4)
            assert losses["scanned"][-1] < losses["scanned"][0]
        finally:
            dist.set_mesh(None)


class TestLlamaScanLayers:
    """ScannedStack generalizes: GQA + RoPE blocks (LlamaBlock) through
    the same scan, incl. stacked-cache decode."""

    def test_train_and_decode(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny(scan_layers=True, recompute=True,
                                        fused_loss_chunk=32))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = TrainStep(m, m.make_loss_fn(), opt)
        ids = _ids(seq=48)
        losses = [float(step(ids, ids)) for _ in range(4)]
        assert losses[-1] < losses[0], losses

    def test_decode_matches_unrolled(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
        paddle.seed(0)
        m_u = LlamaForCausalLM(llama_tiny())
        m_s = LlamaForCausalLM(llama_tiny(scan_layers=True))
        m_s.llama.blocks.load_from_blocks(m_u.llama.blocks)
        sd_u = dict(m_u.named_parameters())
        for n, p in m_s.named_parameters():
            if not n.startswith("llama.blocks."):
                p.value = sd_u[n].value
        prompt = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 256, (2, 9)).astype(
                "int64"))
        out_u = m_u.generate(prompt, max_new_tokens=6, do_sample=False,
                             cache_dtype="float32")
        out_s = m_s.generate(prompt, max_new_tokens=6, do_sample=False,
                             cache_dtype="float32")
        np.testing.assert_array_equal(np.asarray(out_u),
                                      np.asarray(out_s))


class TestScanSequenceParallel:
    def test_scan_with_ring_attention_trains(self):
        # ring attention's shard_map runs INSIDE the scan body under the
        # sp axis — the full long-context composition. Ring attention is
        # exact, so the trajectory must MATCH the same scanned model
        # trained without sp, and the ring dispatch must actually fire.
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.sequence_parallel import \
            last_ring_dispatch
        ids = _ids(batch=4)
        traj = {}
        try:
            for tag, degrees in (("no_sp", {"dp": 8}),
                                 ("sp", {"sp": 2, "mp": 2, "dp": 2})):
                dist.set_mesh(None)
                dist.init_mesh(degrees)
                paddle.seed(0)
                m = GPTForCausalLM(gpt_tiny(scan_layers=True))
                opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                             parameters=m.parameters())
                step = dist.ParallelTrainStep(
                    m, GPTForCausalLM.loss_fn, opt)
                traj[tag] = [float(step(ids, ids)) for _ in range(3)]
            assert last_ring_dispatch(), \
                "ring attention never dispatched under the sp mesh"
            np.testing.assert_allclose(traj["no_sp"], traj["sp"],
                                       rtol=2e-4)
        finally:
            dist.set_mesh(None)


class TestFusedScanDistributed:
    def test_dp_mp_fused_scan_matches_plain(self):
        # the full composition: scanned TP blocks + fused CE over the
        # vocab-sharded tied weight, under the hybrid engine — GSPMD must
        # insert the cross-shard collectives for the chunked logsumexp
        import paddle_tpu.distributed as dist
        dist.init_mesh({"dp": 2, "mp": 2})
        try:
            ids = _ids(batch=4, seq=48)
            m_plain, m_fused = _scanned_pair(fused_loss_chunk=32)
            traj = {}
            for tag, m in (("plain", m_plain), ("fused+scan", m_fused)):
                opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                             parameters=m.parameters())
                step = dist.ParallelTrainStep(m, m.make_loss_fn(), opt)
                traj[tag] = [float(step(ids, ids)) for _ in range(3)]
            np.testing.assert_allclose(traj["plain"], traj["fused+scan"],
                                       rtol=2e-4)
        finally:
            dist.set_mesh(None)


class TestBertScanLayers:
    """ScannedStack with a layer-invariant extra arg (the additive
    attention mask) — the encoder-family wiring."""

    def test_masked_forward_matches_unrolled(self):
        from paddle_tpu.models.bert import BertModel, bert_tiny
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 512, (3, 24)).astype(
            "int64"))
        mask_np = np.ones((3, 24), "int64")
        mask_np[:, 18:] = 0
        mask = paddle.to_tensor(mask_np)
        paddle.seed(0)
        m_u = BertModel(bert_tiny())
        m_s = BertModel(bert_tiny(scan_layers=True))
        m_s.layers.load_from_blocks(m_u.layers)
        sd = dict(m_u.named_parameters())
        for n, p in m_s.named_parameters():
            if not n.startswith("layers."):
                p.value = sd[n].value
        seq_u, pool_u = m_u(ids, attention_mask=mask)
        seq_s, pool_s = m_s(ids, attention_mask=mask)
        np.testing.assert_allclose(np.asarray(seq_u.value),
                                   np.asarray(seq_s.value), atol=1e-5)
        np.testing.assert_allclose(np.asarray(pool_u.value),
                                   np.asarray(pool_s.value), atol=1e-5)

    def test_finetune_trains_through_mask(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.models.bert import (BertForSequenceClassification,
                                            bert_tiny)
        rng = np.random.RandomState(1)
        ids = paddle.to_tensor(rng.randint(0, 512, (3, 24)).astype(
            "int64"))
        mask_np = np.ones((3, 24), "int64")
        mask_np[:, 20:] = 0  # real padding: grads flow past -1e30 masks
        mask = paddle.to_tensor(mask_np)
        y = paddle.to_tensor(rng.randint(0, 3, (3,)).astype("int64"))
        paddle.seed(1)
        clf = BertForSequenceClassification(bert_tiny(scan_layers=True),
                                            num_classes=3)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=clf.parameters())
        losses = []
        for _ in range(4):
            loss = F.cross_entropy(clf(ids, attention_mask=mask), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_dropout_raises(self):
        # bert_base keeps the real default dropout=0.1
        from paddle_tpu.models.bert import BertModel, bert_base
        with pytest.raises(NotImplementedError, match="dropout"):
            BertModel(bert_base(scan_layers=True))


class TestMoEScan:
    """MoE blocks through the scan: per-layer aux losses ride the scan
    outputs and are re-reported once to the outer scope."""

    def test_aux_loss_matches_unrolled(self):
        from paddle_tpu.framework.aux_loss import aux_loss_scope, total
        paddle.seed(0)
        m_u = GPTForCausalLM(gpt_tiny(use_moe=True, moe_experts=4))
        m_s = GPTForCausalLM(gpt_tiny(use_moe=True, moe_experts=4,
                                      scan_layers=True))
        m_s.gpt.blocks.load_from_blocks(m_u.gpt.blocks)
        sd = dict(m_u.named_parameters())
        for n, p in m_s.named_parameters():
            if not n.startswith("gpt.blocks."):
                p.value = sd[n].value
        ids = _ids(seq=32)
        with aux_loss_scope() as b_u:
            out_u = m_u(ids)
        with aux_loss_scope() as b_s:
            out_s = m_s(ids)
        np.testing.assert_allclose(np.asarray(out_u.value),
                                   np.asarray(out_s.value), atol=1e-5)
        assert float(total(b_u)) > 0
        np.testing.assert_allclose(float(total(b_u)), float(total(b_s)),
                                   rtol=1e-6)

    def test_moe_scan_remat_trains(self):
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny(use_moe=True, moe_experts=4,
                                    scan_layers=True, recompute=True))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
        step = TrainStep(m, GPTForCausalLM.loss_fn, opt)
        ids = _ids(seq=32)
        losses = [float(step(ids, ids)) for _ in range(4)]
        assert losses[-1] < losses[0], losses


class TestScanLayersGuards:

    def test_dropout_raises(self):
        with pytest.raises(NotImplementedError, match="dropout"):
            GPTForCausalLM(gpt_tiny(scan_layers=True, dropout=0.1))

    def test_greedy_decode_matches_unrolled(self):
        # stacked-cache decode: same params -> same greedy continuation
        m_u, m_s = _scanned_pair()
        prompt = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 256, (2, 12)).astype(
                "int64"))
        out_u = m_u.generate(prompt, max_new_tokens=8, do_sample=False,
                             cache_dtype="float32")
        out_s = m_s.generate(prompt, max_new_tokens=8, do_sample=False,
                             cache_dtype="float32")
        np.testing.assert_array_equal(np.asarray(out_u),
                                      np.asarray(out_s))
