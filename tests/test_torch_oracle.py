"""Independent-oracle numerics: the math long tail vs torch-CPU.

The OpTest suites validate against numpy references written alongside
the implementations; this file cross-checks the trickier special
functions and reductions against torch (bundled CPU build) — an oracle
nobody in this repo wrote. Reference role: the cross-framework
consistency tests in the reference's unittests (which compare against
scipy/np golden values); semantics parity target is the phi kernels'.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def _x(shape=(3, 7), seed=0, positive=False, lo=-3.0, hi=3.0):
    rng = np.random.RandomState(seed)
    v = rng.uniform(lo, hi, size=shape).astype(np.float32)
    if positive:
        v = np.abs(v) + 0.1
    return v


UNARY = [
    ("erf", {}, dict()),
    ("erfinv", dict(lo=-0.95, hi=0.95), dict()),
    ("lgamma", dict(positive=True), dict()),
    ("digamma", dict(positive=True), dict()),
    ("cumprod", {}, dict(paddle_kw={"dim": 1}, torch_kw={"dim": 1})),
    ("logcumsumexp", {}, dict(paddle_kw={"axis": 1}, torch_kw={"dim": 1})),
    ("logsumexp", {}, dict(paddle_kw={"axis": 1}, torch_kw={"dim": 1})),
    ("diff", {}, dict(paddle_kw={"axis": 1}, torch_kw={"dim": 1})),
]


@pytest.mark.parametrize("name,gen,kws", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_matches_torch(name, gen, kws):
    v = _x(**gen)
    got = getattr(paddle, name)(paddle.to_tensor(v),
                                **kws.get("paddle_kw", {})).numpy()
    want = getattr(torch, name)(torch.from_numpy(v),
                                **kws.get("torch_kw", {})).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


BINARY = ["logaddexp", "heaviside", "fmax", "fmin", "nextafter"]


@pytest.mark.parametrize("name", BINARY)
def test_binary_matches_torch(name):
    a, b = _x(seed=1), _x(seed=2)
    if name == "heaviside":
        a[0, 0] = 0.0  # exercise the at-zero branch
    got = getattr(paddle, name)(paddle.to_tensor(a),
                                paddle.to_tensor(b)).numpy()
    want = getattr(torch, name)(torch.from_numpy(a),
                                torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


class TestReductionsMatchTorch:
    def test_median_even_and_odd(self):
        for n in (7, 8):  # odd + even tails differ between frameworks
            v = _x((3, n), seed=n)
            got = paddle.median(paddle.to_tensor(v), axis=1).numpy()
            want = np.median(v, axis=1).astype(np.float32)
            # paddle's median averages the two middle values (numpy
            # semantics), unlike torch's lower-median
            np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_nanmedian(self):
        v = _x((4, 9), seed=3)
        v[v > 2.0] = np.nan
        got = paddle.nanmedian(paddle.to_tensor(v), axis=1).numpy()
        want = np.nanmedian(v, axis=1).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_quantile_interpolations(self):
        v = _x((5, 11), seed=4)
        for q in (0.25, 0.5, 0.9):
            got = paddle.quantile(paddle.to_tensor(v), q, axis=1).numpy()
            want = torch.quantile(torch.from_numpy(v), q, dim=1).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_kthvalue_and_mode(self):
        v = _x((4, 9), seed=5)
        gv, gi = paddle.kthvalue(paddle.to_tensor(v), 3, axis=1)
        tv, ti = torch.kthvalue(torch.from_numpy(v), 3, dim=1)
        np.testing.assert_allclose(gv.numpy(), tv.numpy())
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        iv = np.random.RandomState(6).randint(0, 3, (4, 15)).astype(
            np.float32)
        gv, _ = paddle.mode(paddle.to_tensor(iv), axis=1)
        tv, _ = torch.mode(torch.from_numpy(iv), dim=1)
        np.testing.assert_allclose(gv.numpy(), tv.numpy())

    def test_bucketize_searchsorted(self):
        edges = np.array([-1.0, 0.0, 1.0, 2.0], np.float32)
        v = _x((3, 6), seed=7)
        for right in (False, True):
            got = paddle.bucketize(paddle.to_tensor(v),
                                   paddle.to_tensor(edges),
                                   right=right).numpy()
            want = torch.bucketize(torch.from_numpy(v),
                                   torch.from_numpy(edges),
                                   right=right).numpy()
            np.testing.assert_array_equal(got, want)
        sv = np.sort(_x((8,), seed=8))
        got = paddle.searchsorted(paddle.to_tensor(sv),
                                  paddle.to_tensor(v)).numpy()
        want = torch.searchsorted(torch.from_numpy(sv),
                                  torch.from_numpy(v)).numpy()
        np.testing.assert_array_equal(got, want)


class TestLinalgMatchesTorch:
    def test_slogdet_solve_pinv(self):
        rng = np.random.RandomState(9)
        A = rng.randn(4, 4).astype(np.float32) + 4 * np.eye(
            4, dtype=np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        sign, logdet = paddle.linalg.slogdet(paddle.to_tensor(A))
        tsign, tlog = torch.linalg.slogdet(torch.from_numpy(A))
        np.testing.assert_allclose(float(sign.numpy()), float(tsign))
        np.testing.assert_allclose(float(logdet.numpy()), float(tlog),
                                   rtol=1e-5)
        got = paddle.linalg.solve(paddle.to_tensor(A),
                                  paddle.to_tensor(b)).numpy()
        want = torch.linalg.solve(torch.from_numpy(A),
                                  torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        M = rng.randn(5, 3).astype(np.float32)
        got = paddle.linalg.pinv(paddle.to_tensor(M)).numpy()
        want = torch.linalg.pinv(torch.from_numpy(M)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


class TestEinsumManipulationMatchTorch:
    def test_einsum_patterns(self):
        a = _x((3, 4), 30)
        b = _x((4, 5), 31)
        c = _x((2, 3, 4), 32)
        d = _x((2, 4, 6), 33)
        cases = [
            ("ij,jk->ik", (a, b)),
            ("bij,bjk->bik", (c, d)),
            ("ij->ji", (a,)),
            ("ij->", (a,)),
            ("bij->bi", (c,)),
            ("ij,ij->", (a, _x((3, 4), 34))),
        ]
        for eq, ops in cases:
            got = paddle.einsum(eq, *[paddle.to_tensor(o) for o in ops])
            want = torch.einsum(eq, *[torch.from_numpy(o) for o in ops])
            np.testing.assert_allclose(np.asarray(got.numpy()),
                                       want.numpy(), rtol=1e-4, atol=1e-5,
                                       err_msg=eq)

    def test_sort_topk_stability_and_values(self):
        v = _x((4, 9), 35)
        gv, gi = paddle.topk(paddle.to_tensor(v), 3, axis=1)
        tv, ti = torch.topk(torch.from_numpy(v), 3, dim=1)
        np.testing.assert_allclose(gv.numpy(), tv.numpy())
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        gs = paddle.sort(paddle.to_tensor(v), axis=1, descending=True)
        ts, _ = torch.sort(torch.from_numpy(v), dim=1, descending=True)
        np.testing.assert_allclose(gs.numpy(), ts.numpy())

    def test_cummax_roll_rot90(self):
        v = _x((3, 6), 36)
        gv, gi = paddle.cummax(paddle.to_tensor(v), axis=1)
        tv, ti = torch.cummax(torch.from_numpy(v), dim=1)
        np.testing.assert_allclose(gv.numpy(), tv.numpy())
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        gv, gi = paddle.cummin(paddle.to_tensor(v), axis=0)
        tv, ti = torch.cummin(torch.from_numpy(v), dim=0)
        np.testing.assert_allclose(gv.numpy(), tv.numpy())
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        # tie semantics: the LATEST index wins (torch contract)
        t = np.array([[1.0, 1.0, 0.5, 1.0]], np.float32)
        _, gi = paddle.cummax(paddle.to_tensor(t), axis=1)
        _, ti = torch.cummax(torch.from_numpy(t), dim=1)
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        # NaN propagates like torch (values and indices)
        nt = np.array([1.0, np.nan, 0.5, 2.0], np.float32)
        gv, gi = paddle.cummax(paddle.to_tensor(nt), axis=0)
        tv, ti = torch.cummax(torch.from_numpy(nt), dim=0)
        np.testing.assert_allclose(gv.numpy(), tv.numpy(), equal_nan=True)
        np.testing.assert_array_equal(gi.numpy(), ti.numpy())
        np.testing.assert_allclose(
            paddle.roll(paddle.to_tensor(v), 2, axis=1).numpy(),
            torch.roll(torch.from_numpy(v), 2, dims=1).numpy())
        m = _x((3, 4), 37)
        np.testing.assert_allclose(
            paddle.rot90(paddle.to_tensor(m), 1, [0, 1]).numpy(),
            torch.rot90(torch.from_numpy(m), 1, [0, 1]).numpy())

    def test_repeat_interleave_tile_takealong(self):
        v = _x((2, 3), 38)
        np.testing.assert_allclose(
            paddle.repeat_interleave(paddle.to_tensor(v), 2,
                                     axis=1).numpy(),
            torch.repeat_interleave(torch.from_numpy(v), 2, dim=1).numpy())
        np.testing.assert_allclose(
            paddle.tile(paddle.to_tensor(v), [2, 2]).numpy(),
            torch.tile(torch.from_numpy(v), (2, 2)).numpy())
        idx = np.random.RandomState(39).randint(0, 3, (2, 5)).astype(
            np.int64)
        np.testing.assert_allclose(
            paddle.take_along_axis(paddle.to_tensor(v),
                                   paddle.to_tensor(idx), 1).numpy(),
            torch.take_along_dim(torch.from_numpy(v),
                                 torch.from_numpy(idx), 1).numpy())
