"""tpulint static analyzer (paddle_tpu/analysis): every hazard class
must be detected with its exact finding code, the baseline gate must
ratchet, and the real engine decode program must stay clean (the PR-2
scatter-free + donated-cache regime, now machine-locked).

Registered in tools/ci.py --quick. No test here executes a compiled
program — analysis is trace/lower only.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import (
    diff_against_baseline, lint_file, lint_program, lint_quarantine,
    load_baseline, recompile_report)
from paddle_tpu.analysis.findings import (
    BAKED_RNG_KEY, DTYPE_PROMOTION, HOST_CALLBACK, JIT_IN_CALL,
    NUMPY_IN_TRACE, RECOMPILE_DIM, RECOMPILE_STRUCTURE, SCATTER_OP,
    STALE_QUARANTINE, TRACED_ATTR_MUTATION, UNDONATED_BUFFER, Finding,
    count_findings)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# program linter: one synthetic program per hazard class, exact codes
# ---------------------------------------------------------------------------

def test_dtype_promotion_detected():
    def f(x):
        return x.astype(jnp.float32) * 2          # bf16 -> f32 widening

    fs = lint_program("p", f, (jnp.ones(256, jnp.bfloat16),))
    promo = [f_ for f_ in fs if f_.code == DTYPE_PROMOTION]
    assert len(promo) == 1 and promo[0].site == "bfloat16->float32"
    # scalar / tiny converts don't fire (promotion_min_elems)
    fs2 = lint_program("p2", f, (jnp.ones(4, jnp.bfloat16),))
    assert DTYPE_PROMOTION not in _codes(fs2)


def test_scatter_detected_including_nested_scan():
    def f(cache, idx, v):
        def body(c, i):
            return c.at[idx].set(v), i
        out, _ = jax.lax.scan(body, cache, jnp.arange(3))
        return out

    fs = lint_program("p", f, (jnp.zeros((8, 8)), jnp.int32(1),
                               jnp.ones(8)))
    sc = [f_ for f_ in fs if f_.code == SCATTER_OP]
    assert sc and sc[0].site == "scatter"


def test_host_callback_detected():
    def f(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape,
                                                          x.dtype), x)

    fs = lint_program("p", f, (jnp.ones(8),))
    assert HOST_CALLBACK in _codes(fs)


def test_baked_rng_key_detected_and_threaded_key_clean():
    baked = jax.random.PRNGKey(7)

    def bad(x):
        return x + jax.random.normal(baked, x.shape)

    def good(x, key):
        return x + jax.random.normal(key, x.shape)

    assert BAKED_RNG_KEY in _codes(lint_program("b", bad, (jnp.ones(8),)))
    assert BAKED_RNG_KEY not in _codes(
        lint_program("g", good, (jnp.ones(8), jax.random.PRNGKey(0))))


def test_undonated_buffer_detected_and_donation_clears_it():
    def f(cache, x):
        return cache + x, x.sum()

    cache = jnp.zeros((64, 256), jnp.float32)    # 64 KiB >= threshold
    x = jnp.ones((64, 256), jnp.float32)
    fs = lint_program("p", jax.jit(f), (cache, x))
    assert UNDONATED_BUFFER in _codes(fs)
    fs2 = lint_program("p", jax.jit(f, donate_argnums=(0, 1)), (cache, x))
    assert UNDONATED_BUFFER not in _codes(fs2)


# ---------------------------------------------------------------------------
# recompile-hazard analyzer
# ---------------------------------------------------------------------------

def test_recompile_dim_exact():
    specs = [(np.zeros((1, p), np.int64), np.zeros((4,), np.float32))
             for p in (7, 9, 13)]
    fs = recompile_report("gen", specs)
    assert len(fs) == 1 and fs[0].code == RECOMPILE_DIM
    assert fs[0].site == "arg0"
    assert fs[0].data["varying_dims"] == [1]
    assert fs[0].data["distinct_programs"] == 3


def test_recompile_stable_specs_clean_and_structure_drift():
    stable = [(np.zeros((1, 8)),)] * 3
    assert recompile_report("gen", stable) == []
    drift = [({"a": np.zeros(3)},), ({"a": np.zeros(3),
                                      "b": np.zeros(3)},)]
    fs = recompile_report("gen", drift)
    assert [f.code for f in fs] == [RECOMPILE_STRUCTURE]


def test_recompile_dtype_drift_flagged():
    fs = recompile_report("gen", [(np.zeros(8, np.float32),),
                                  (np.zeros(8, np.float64),)])
    assert fs and fs[0].code == RECOMPILE_DIM
    assert "dtype varies" in fs[0].message


# ---------------------------------------------------------------------------
# codebase (AST) lint
# ---------------------------------------------------------------------------

_SNIPPET = '''
import jax
import numpy as np
from paddle_tpu.nn import Layer


def hot(x):
    return jax.jit(lambda v: v * 2)(x)            # retrace per call


class Gate(Layer):
    def forward(self, x):
        stats = np.asarray(x)                     # concretizes tracer
        self._last = x * 2                        # tracer on the layer
        self._ok = x.sum()   # tpulint: disable=traced-attr-mutation
        self.training = True                      # constant: trace-safe
        return x


class HostSide:                                   # not a Layer: exempt
    def forward(self, x):
        self.cache = np.asarray(x)
        return x
'''


def test_codebase_lint_synthetic(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(_SNIPPET)
    fs = lint_file(str(p), str(tmp_path))
    by_code = {}
    for f in fs:
        by_code.setdefault(f.code, []).append(f)
    assert [f.site for f in by_code[JIT_IN_CALL]] == ["hot"]
    assert [f.site for f in by_code[TRACED_ATTR_MUTATION]] == \
        ["Gate.forward._last"]          # _ok disabled, constants exempt
    assert [f.site for f in by_code[NUMPY_IN_TRACE]] == \
        ["Gate.forward.np.asarray"]     # HostSide is not layer-like


def test_jit_no_donation_on_hot_wrapper_files(tmp_path):
    """HOT_JIT_FILES membership is by repo-relative path: the same
    knob-less jax.jit is info-flagged inside jit/training.py and silent
    elsewhere."""
    from paddle_tpu.analysis.findings import JIT_NO_DONATION
    hot = tmp_path / "paddle_tpu" / "jit" / "training.py"
    hot.parent.mkdir(parents=True)
    hot.write_text("import jax\n\ndef build(f):\n    return jax.jit(f)\n")
    fs = lint_file(str(hot), str(tmp_path))
    assert [f.code for f in fs] == [JIT_NO_DONATION]
    cold = tmp_path / "paddle_tpu" / "other.py"
    cold.write_text("import jax\n\ndef build(f):\n    return jax.jit(f)\n")
    assert lint_file(str(cold), str(tmp_path)) == []


def test_quarantine_machine_check(tmp_path):
    q = tmp_path / "flaky_quarantine.txt"
    q.write_text(
        "# comment\n"
        "tests/test_analysis.py::test_quarantine_machine_check\n"
        "tests/no_such_file.py::test_gone\n"
        "name_that_matches_no_test\n")
    fs = lint_quarantine(ROOT, quarantine_path=str(q))
    stale = sorted(f.site for f in fs)
    assert all(f.code == STALE_QUARANTINE for f in fs)
    assert stale == ["name_that_matches_no_test",
                     "tests/no_such_file.py::test_gone"]


def test_quarantine_class_based_nodeids_and_substrings_resolve(tmp_path):
    """Class-based nodeids (path::TestCls::test_fn) and Test-class -k
    substrings are valid quarantine entries and must not read as stale
    (ci.py's own _quarantine() accepts them; the policies must agree)."""
    q = tmp_path / "q.txt"
    q.write_text(
        "tests/test_analysis.py::TestGateAnchors::test_anchor_is_"
        "segment_bounded\n"
        "TestGateAnchors\n"
        "flash_kernel\n")     # -k also matches MODULE names (whole-file)
    assert lint_quarantine(ROOT, quarantine_path=str(q)) == []


def test_run_manifest_rejects_unknown_program_names():
    from paddle_tpu.analysis import run_manifest
    with pytest.raises(ValueError, match="unknown manifest program"):
        run_manifest(["gpt_deocde"])      # typo must not silently pass


def test_repo_quarantine_entries_all_resolve():
    """The checked-in registry must be clean — known failures stay
    tracked, not rotted (satellite: machine-checked annotations)."""
    assert lint_quarantine(ROOT) == []


# ---------------------------------------------------------------------------
# baseline gate semantics
# ---------------------------------------------------------------------------

def _mk(code, program, site, sev="warn", count=1):
    return Finding(code, sev, program, site, "m",
                   {"count": count} if count != 1 else {})


def test_gate_ratchets_on_counts_and_weights():
    base = {"counts": {"scatter-op::p::scatter": 2}}
    ok = [_mk("scatter-op", "p", "scatter", count=2)]
    assert diff_against_baseline(ok, base) == []
    worse = [_mk("scatter-op", "p", "scatter", count=3)]
    new = diff_against_baseline(worse, base)
    assert len(new) == 1 and "exceeds baseline" in new[0]["reason"]
    # info inventories are count-pinned too: a gather/collective count
    # regression gates exactly like a warn (the documented contract)
    info = [_mk("gather-op", "p", "gather", sev="info", count=3)]
    assert diff_against_baseline(
        info, {"counts": {"gather-op::p::gather": 3}}) == []
    assert diff_against_baseline(
        info, {"counts": {"gather-op::p::gather": 2}})


class TestGateAnchors:
    def test_anchor_beats_counts(self):
        base = {"counts": {"scatter-op::p::scatter": 5},
                "must_stay_clean": ["scatter-op::p"]}
        new = diff_against_baseline([_mk("scatter-op", "p", "scatter")],
                                    base)
        assert len(new) == 1 and "must_stay_clean" in new[0]["reason"]

    def test_anchor_is_segment_bounded(self):
        """Anchor 'x::train_step' must not capture a future program
        named 'train_step_acc' (prefix match is '::'-bounded)."""
        base = {"counts": {"scatter-op::train_step_acc::scatter": 1},
                "must_stay_clean": ["scatter-op::train_step"]}
        ok = [_mk("scatter-op", "train_step_acc", "scatter")]
        assert diff_against_baseline(ok, base) == []
        hit = [_mk("scatter-op", "train_step", "scatter")]
        assert diff_against_baseline(hit, base)


def test_count_findings_weights_op_counts():
    counts = count_findings([_mk("scatter-op", "p", "scatter", count=2),
                             _mk("scatter-op", "p", "scatter")])
    assert counts == {"scatter-op::p::scatter": 3}


# ---------------------------------------------------------------------------
# the acceptance demonstration: a seeded hazard fails the CHECKED-IN
# baseline, and the real engine decode program stays clean
# ---------------------------------------------------------------------------

def test_seeded_scatter_cache_write_fails_checked_in_baseline():
    """Reintroducing a scatter cache write into the decode program (the
    exact PR-2 hazard) must fail the CI gate against the committed
    baseline — the must_stay_clean anchor fires even if counts were
    bumped."""
    def bad_decode(cache, tok, pos):
        # the regression tpulint exists to catch: per-row scatter write
        return cache.at[jnp.arange(cache.shape[0]), pos].set(
            tok.astype(cache.dtype))

    cache = jnp.zeros((4, 64, 8), jnp.float32)
    fs = lint_program(
        "gpt_decode", jax.jit(bad_decode, donate_argnums=(0,)),
        (cache, jnp.zeros((4, 8), jnp.int32), jnp.zeros(4, jnp.int32)))
    base = load_baseline(os.path.join(ROOT, "tools",
                                      "tpulint_baseline.json"))
    new = diff_against_baseline(fs, base)
    assert any(n["code"] == SCATTER_OP and n["program"] == "gpt_decode"
               for n in new), new


def test_real_engine_decode_program_is_clean():
    """The engine's batched decode program: no scatter (one-hot masked
    cache writes), KV cache donated, no baked keys, no host callbacks —
    the donation satellite + PR-2 write regime, asserted on the REAL
    program via the same manifest builder the CLI uses."""
    # the builders moved to compilation/sites.py when the registry
    # became the one program table (PR 5) — build through it, exactly
    # as the CLI's manifest does
    from paddle_tpu.compilation import registry
    r = registry.build("gpt_decode")
    try:
        fs = lint_program("gpt_decode", r.fn, r.args)
    finally:
        if r.cleanup is not None:
            r.cleanup()
    codes = _codes(fs)
    assert SCATTER_OP not in codes
    assert UNDONATED_BUFFER not in codes      # cache donation wired
    assert BAKED_RNG_KEY not in codes
    assert HOST_CALLBACK not in codes
    # and the committed baseline accepts the program as-is
    base = load_baseline(os.path.join(ROOT, "tools",
                                      "tpulint_baseline.json"))
    assert diff_against_baseline(fs, base) == []


def test_tpulint_cli_codebase_only_gate_passes(capsys, monkeypatch):
    """The CLI contract tpu_suite2.sh relies on: last stdout line is a
    good JSON record (tools/_have_result.py), gate passes on HEAD.
    Run in-process (runpy) — a subprocess would pay a cold paddle_tpu
    import (~10 s) for nothing on the 1-core tier-1 budget."""
    import runpy
    monkeypatch.setattr(sys, "argv", ["tpulint.py", "--codebase-only"])
    with pytest.raises(SystemExit) as exc:
        runpy.run_path(os.path.join(ROOT, "tools", "tpulint.py"),
                       run_name="__main__")
    assert exc.value.code == 0
    rec = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["gate"] == "pass" and "error" not in rec


# ---------------------------------------------------------------------------
# tpucost (analysis/hlo_cost.py + analysis/fusion.py): the HLO parsers
# run over CHECKED-IN fixtures — zero compiles — so the cost pass is
# exercised even where compile is skipped; the live registry pass and
# the decode anchor ride one shared module-scoped inventory below
# ---------------------------------------------------------------------------

FIXTURES = os.path.join(ROOT, "tests", "fixtures", "hlo")


def _fixture(name):
    with open(os.path.join(FIXTURES, name)) as fh:
        return fh.read()


def test_hlo_parser_fusion_and_dot_flops():
    """mlp_fused.txt: dot [8,64]x[64,128] + one kLoop fusion. The dot's
    FLOPs are exact (2*M*N*K); the fusion counts its internal
    elementwise ops at full shape and pays HBM only at its boundary
    (operands + root output — fused producers are free)."""
    from paddle_tpu.analysis import program_cost
    inv = program_cost(_fixture("mlp_fused.txt"), name="mlp")
    assert inv["matmul_flops"] == 2 * 8 * 128 * 64
    assert inv["fusion_histogram"] == {"dot": 1, "loop": 1}
    assert inv["kernel_count"] == 2
    assert inv["flops"] > inv["matmul_flops"]      # + fused elementwise
    # reads: dot streams w + x; the fusion re-reads the dot's output
    # from HBM plus the bias — nothing INSIDE the fusion pays traffic
    w_x = (64 * 128 + 8 * 64) * 4
    fus_r = (8 * 128 + 128) * 4
    assert inv["bytes_read"] == w_x + fus_r
    assert inv["bytes_written"] == 2 * 8 * 128 * 4
    assert inv["bound"] == "bandwidth"
    assert inv["roofline_seconds"] > 0


def test_hlo_parser_while_trip_count_multiplies():
    """scan_loop.txt: lax.scan(length=5) lowers to a while whose
    condition compares against constant 5 — every body kernel is
    counted 5x (the decode tick / fused train window accounting)."""
    from paddle_tpu.analysis import collect_kernels, parse_hlo_module
    m = parse_hlo_module(_fixture("scan_loop.txt"))
    ks = collect_kernels(m)
    body = [k for k in ks if k.path and k.opcode == "fusion"]
    assert len(body) == 1 and body[0].trip == 5
    # 3 arithmetic ops x 128*128 elems x 5 trips
    assert body[0].flops == 3 * 128 * 128 * 5
    assert body[0].bytes_read == 128 * 128 * 4 * 5


def test_hlo_parser_collective_replica_groups():
    """collectives.txt: a 4-wide psum all-reduce. The inventory counts
    the replica group, so per-chip bytes are 2(n-1)/n of the result —
    the ZeRO-2 byte-accuracy fix (satellite: count groups)."""
    from paddle_tpu.analysis import (collective_inventory_from_hlo,
                                     program_cost)
    txt = _fixture("collectives.txt")
    inv = collective_inventory_from_hlo(txt)
    assert set(inv) == {"all-reduce"}
    rec = inv["all-reduce"]
    assert rec["count"] == 1 and rec["group_size"] == 4
    assert rec["result_bytes"] == 2 * 512 * 4
    assert rec["bytes"] == int(2 * 512 * 4 * 2 * 3 / 4)   # 2(n-1)/n
    cost = program_cost(txt, name="psum")
    assert cost["fusion_histogram"].get("collective") == 1


def test_hlo_parser_unfused_chain_ranked():
    """unfused_chain.txt (synthetic): add -> tanh -> multiply left as
    three separate kernels behind a dot. The fusion report names the
    chain and ranks its intermediate HBM traffic; the dot is not part
    of the elementwise chain."""
    from paddle_tpu.analysis import program_cost
    inv = program_cost(_fixture("unfused_chain.txt"), name="chain")
    assert inv["fusion_histogram"] == {"dot": 1, "unfused": 3}
    top = inv["top_unfused"]
    assert len(top) == 1
    chain = top[0]
    assert chain["kernels"] == ["add.4", "multiply.6", "tanh.5"]
    # exactly the two distinct intermediates (add.4, tanh.5) cross HBM
    # — add.4 fans out to BOTH consumers but is written once
    assert chain["intermediate_bytes"] == 2 * 256 * 256 * 4
    assert chain["savable_bytes"] == 2 * chain["intermediate_bytes"]


def test_collective_empty_replica_groups_means_all_devices():
    """`replica_groups={}` is HLO for ONE all-replica group — the
    inventory must scale by the module's partition count, not read it
    as a degenerate single-device group (which would zero the bytes)."""
    from paddle_tpu.analysis import collective_inventory_from_hlo
    # a real-size entry_computation_layout pushes num_partitions
    # thousands of chars into the header line — the whole first line
    # must be searched, not a fixed byte window
    layout = ", ".join("f32[128,128]{1,0}" for _ in range(200))
    txt = (f"HloModule m, entry_computation_layout={{({layout})->"
           "f32[2,512]{1,0}}, num_partitions=8\n"
           "  %ar = f32[2,512]{1,0} all-reduce(f32[2,512]{1,0} %p), "
           "replica_groups={}, to_apply=%add\n")
    assert txt.index("num_partitions") > 2048
    rec = collective_inventory_from_hlo(txt)["all-reduce"]
    assert rec["group_size"] == 8
    assert rec["bytes"] == int(2 * 512 * 4 * 2 * 7 / 8)   # 2(n-1)/n


def test_collective_permute_bytes_are_per_hop():
    """collective-permute uses source_target_pairs, not replica groups
    — its transferred bytes are the result bytes (one hop), never
    zeroed by the degenerate group size."""
    from paddle_tpu.analysis import collective_inventory_from_hlo
    line = ("  %cp = f32[128,8]{1,0} collective-permute("
            "f32[128,8]{1,0} %x), channel_id=1, "
            "source_target_pairs={{0,1},{1,2},{2,3},{3,0}}\n")
    inv = collective_inventory_from_hlo(line)
    assert inv["collective-permute"]["bytes"] == 128 * 8 * 4


# ---------------------------------------------------------------------------
# tpucost baseline-gate semantics (pure; no compiles)
# ---------------------------------------------------------------------------

def _inv(hbm=1000, kernels=10, share=0.8):
    return {"hbm_bytes": hbm, "kernel_count": kernels,
            "matmul_flop_share": share}


def test_cost_budgets_ratchet():
    from paddle_tpu.analysis import check_cost_baseline
    from paddle_tpu.analysis.findings import COST_BUDGET
    base = {"budgets": {"p": {"hbm_bytes": 1000, "kernel_count": 10,
                              "matmul_flop_share_min": 0.8}}}
    assert check_cost_baseline({"p": _inv()}, base, ["p"]) == []
    worse = check_cost_baseline({"p": _inv(hbm=1001)}, base, ["p"])
    assert [f.code for f in worse] == [COST_BUDGET]
    assert worse[0].site == "hbm_bytes"
    worse = check_cost_baseline({"p": _inv(kernels=11)}, base, ["p"])
    assert worse and worse[0].site == "kernel_count"
    worse = check_cost_baseline({"p": _inv(share=0.79)}, base, ["p"])
    assert worse and worse[0].site == "matmul_flop_share"
    # improvements pass (and --update-baseline locks them in)
    assert check_cost_baseline(
        {"p": _inv(hbm=900, kernels=9, share=0.9)}, base, ["p"]) == []


def test_cost_gate_flags_unbaselined_program():
    """A newly registered program with no pinned budget fails the gate
    — registry completeness is enforced in BOTH directions."""
    from paddle_tpu.analysis import check_cost_baseline
    new = check_cost_baseline({"fresh": _inv()},
                              {"budgets": {}}, ["fresh"])
    assert len(new) == 1 and new[0].site == "unbaselined"


def test_cost_gate_stale_program_detected():
    """A baseline budget or anchor naming a program the registry no
    longer has fails loudly — the registry-rename rot check (the
    stale-quarantine analogue for cost baselines)."""
    from paddle_tpu.analysis import check_cost_baseline
    from paddle_tpu.analysis.findings import STALE_COST_PROGRAM
    base = {"budgets": {"gone": {"hbm_bytes": 1}},
            "anchors": {"also_gone": {"kind": "matmul_share_floor",
                                      "min_share": 0.5}}}
    new = check_cost_baseline({}, base, ["live_prog"])
    assert sorted(f.program for f in new) == ["also_gone", "gone"]
    assert all(f.code == STALE_COST_PROGRAM for f in new)


def test_cost_anchor_decode_hbm_and_share_floor():
    from paddle_tpu.analysis import (analytic_decode_hbm_bytes,
                                     check_cost_baseline)
    from paddle_tpu.analysis.findings import COST_ANCHOR
    geom = {"tick_tokens": 4, "param_bytes": 1000,
            "kv_cache_bytes": 100}
    bound = analytic_decode_hbm_bytes(geom)
    assert bound == 4 * (1000 + 7 * 100)
    base = {"budgets": {"d": {"hbm_bytes": 10 * bound,
                              "kernel_count": 99,
                              "matmul_flop_share_min": 0.0}},
            "anchors": {"d": {"kind": "decode_hbm", "max_ratio": 1.15}}}
    ok = check_cost_baseline({"d": _inv(hbm=int(bound * 1.1))}, base,
                             ["d"], {"d": geom})
    assert ok == []
    bad = check_cost_baseline({"d": _inv(hbm=int(bound * 1.2))}, base,
                              ["d"], {"d": geom})
    assert [f.code for f in bad] == [COST_ANCHOR]
    floor = {"budgets": {"t": {"hbm_bytes": 10, "kernel_count": 1,
                               "matmul_flop_share_min": 0.0}},
             "anchors": {"t": {"kind": "matmul_share_floor",
                               "min_share": 0.85}}}
    assert check_cost_baseline({"t": _inv(hbm=1, kernels=1,
                                          share=0.86)},
                               floor, ["t"]) == []
    assert check_cost_baseline({"t": _inv(hbm=1, kernels=1,
                                          share=0.84)},
                               floor, ["t"])


def test_cost_gate_unknown_anchor_kind_fails_loudly():
    """A typo in a hand-edited anchor must not silently DISABLE the
    invariant — unknown kinds are violations, not no-ops."""
    from paddle_tpu.analysis import check_cost_baseline
    base = {"budgets": {"p": {"hbm_bytes": 10, "kernel_count": 99,
                              "matmul_flop_share_min": 0.0}},
            "anchors": {"p": {"kind": "decode-hbm"}}}     # typo'd kind
    new = check_cost_baseline({"p": _inv(hbm=1)}, base, ["p"])
    assert len(new) == 1 and new[0].site == "unknown-kind"


def test_cost_gate_full_run_requires_every_baselined_program():
    """require_all (a full run): a live baselined program missing from
    the inventories is a violation — a silently skipped site must not
    read as its anchors passing. Partial (--programs) runs still skip
    absent programs."""
    from paddle_tpu.analysis import check_cost_baseline
    base = {"budgets": {"p": {"hbm_bytes": 10, "kernel_count": 99,
                              "matmul_flop_share_min": 0.0}}}
    assert check_cost_baseline({}, base, ["p"]) == []     # partial
    new = check_cost_baseline({}, base, ["p"], require_all=True)
    assert len(new) == 1 and new[0].site == "not-measured"


def test_updated_cost_baseline_preserves_anchors():
    from paddle_tpu.analysis import updated_cost_baseline
    base = {"anchors": {"p": {"kind": "decode_hbm", "max_ratio": 1.15}},
            "notes": {"p": "why"}, "budgets": {}}
    new = updated_cost_baseline(
        base, {"p": {"hbm_bytes": 5, "kernel_count": 2,
                     "matmul_flop_share": 0.51239}})
    assert new["anchors"] == base["anchors"]
    assert new["notes"] == {"p": "why"}
    assert new["budgets"]["p"] == {"hbm_bytes": 5, "kernel_count": 2,
                                   "matmul_flop_share_min": 0.5123}


# ---------------------------------------------------------------------------
# live registry pass: every registered program gets a cost record, the
# committed baseline accepts HEAD, and the decode-tick HBM anchor holds
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_inventories():
    """One shared cost pass over the full registry (compiles every
    program once — the warm persistent cache makes repeat runs cheap;
    tools/tpucost.py's collect_inventories is the SAME code path the
    CLI gates on)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tpucost_cli", os.path.join(ROOT, "tools", "tpucost.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    sys.path.insert(0, ROOT)
    return mod.collect_inventories()


@pytest.mark.timeout(600)
def test_every_registered_program_gets_a_cost_record(live_inventories):
    """Registry completeness: a program registered with the manifest
    tag is cost-inventoried BY DEFAULT (same contract as lint/warmup
    coverage — one table serves every consumer)."""
    from paddle_tpu.compilation import registry
    invs, geoms, skipped = live_inventories
    assert skipped == {}        # conftest provides 8 virtual devices
    assert sorted(invs) == sorted(registry.names(tag="manifest"))
    for name, inv in invs.items():
        assert inv["flops"] > 0, name
        assert inv["hbm_bytes"] > 0, name
        assert inv["kernel_count"] > 0, name
        assert 0.0 <= inv["matmul_flop_share"] <= 1.0, name
        assert inv["roofline_seconds"] > 0, name
        assert isinstance(inv["fusion_histogram"], dict), name
        assert isinstance(inv["top_unfused"], list), name


def test_decode_tick_hbm_anchor_holds(live_inventories):
    """The acceptance anchor: the engine decode tick's modeled HBM
    bytes stay within 1.15x of the analytic KV-cache + weight bound
    (7 cache passes per micro-step under the current masked-write
    regime — analysis/hlo_cost.analytic_decode_hbm_bytes). An eighth
    pass appearing (unfused activation chain, dropped fusion) breaks
    this, and CI with it."""
    from paddle_tpu.analysis import analytic_decode_hbm_bytes
    invs, geoms, _ = live_inventories
    bound = analytic_decode_hbm_bytes(geoms["gpt_decode"])
    ratio = invs["gpt_decode"]["hbm_bytes"] / bound
    assert ratio <= 1.15, (invs["gpt_decode"]["hbm_bytes"], bound)
    # and the bound is honest: the model carries MORE traffic than the
    # weights+cache floor, not less (an undercounting parser would
    # silently hollow the anchor out)
    assert ratio > 0.9


def test_committed_cost_baseline_accepts_head(live_inventories):
    """tools/tpucost_baseline.json gates green against HEAD — the same
    check ci.py --quick/--full append after the tests."""
    from paddle_tpu.analysis import (check_cost_baseline,
                                     load_cost_baseline)
    from paddle_tpu.compilation import registry
    invs, geoms, _ = live_inventories
    base = load_cost_baseline(
        os.path.join(ROOT, "tools", "tpucost_baseline.json"))
    assert check_cost_baseline(invs, base,
                               registry.names(tag="manifest"),
                               geoms) == []
