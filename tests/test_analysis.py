"""tpulint static analyzer (paddle_tpu/analysis): every hazard class
must be detected with its exact finding code, the baseline gate must
ratchet, and the real engine decode program must stay clean (the PR-2
scatter-free + donated-cache regime, now machine-locked).

Registered in tools/ci.py --quick. No test here executes a compiled
program — analysis is trace/lower only.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.analysis import (
    diff_against_baseline, lint_file, lint_program, lint_quarantine,
    load_baseline, recompile_report)
from paddle_tpu.analysis.findings import (
    BAKED_RNG_KEY, DTYPE_PROMOTION, HOST_CALLBACK, JIT_IN_CALL,
    NUMPY_IN_TRACE, RECOMPILE_DIM, RECOMPILE_STRUCTURE, SCATTER_OP,
    STALE_QUARANTINE, TRACED_ATTR_MUTATION, UNDONATED_BUFFER, Finding,
    count_findings)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# program linter: one synthetic program per hazard class, exact codes
# ---------------------------------------------------------------------------

def test_dtype_promotion_detected():
    def f(x):
        return x.astype(jnp.float32) * 2          # bf16 -> f32 widening

    fs = lint_program("p", f, (jnp.ones(256, jnp.bfloat16),))
    promo = [f_ for f_ in fs if f_.code == DTYPE_PROMOTION]
    assert len(promo) == 1 and promo[0].site == "bfloat16->float32"
    # scalar / tiny converts don't fire (promotion_min_elems)
    fs2 = lint_program("p2", f, (jnp.ones(4, jnp.bfloat16),))
    assert DTYPE_PROMOTION not in _codes(fs2)


def test_scatter_detected_including_nested_scan():
    def f(cache, idx, v):
        def body(c, i):
            return c.at[idx].set(v), i
        out, _ = jax.lax.scan(body, cache, jnp.arange(3))
        return out

    fs = lint_program("p", f, (jnp.zeros((8, 8)), jnp.int32(1),
                               jnp.ones(8)))
    sc = [f_ for f_ in fs if f_.code == SCATTER_OP]
    assert sc and sc[0].site == "scatter"


def test_host_callback_detected():
    def f(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape,
                                                          x.dtype), x)

    fs = lint_program("p", f, (jnp.ones(8),))
    assert HOST_CALLBACK in _codes(fs)


def test_baked_rng_key_detected_and_threaded_key_clean():
    baked = jax.random.PRNGKey(7)

    def bad(x):
        return x + jax.random.normal(baked, x.shape)

    def good(x, key):
        return x + jax.random.normal(key, x.shape)

    assert BAKED_RNG_KEY in _codes(lint_program("b", bad, (jnp.ones(8),)))
    assert BAKED_RNG_KEY not in _codes(
        lint_program("g", good, (jnp.ones(8), jax.random.PRNGKey(0))))


def test_undonated_buffer_detected_and_donation_clears_it():
    def f(cache, x):
        return cache + x, x.sum()

    cache = jnp.zeros((64, 256), jnp.float32)    # 64 KiB >= threshold
    x = jnp.ones((64, 256), jnp.float32)
    fs = lint_program("p", jax.jit(f), (cache, x))
    assert UNDONATED_BUFFER in _codes(fs)
    fs2 = lint_program("p", jax.jit(f, donate_argnums=(0, 1)), (cache, x))
    assert UNDONATED_BUFFER not in _codes(fs2)


# ---------------------------------------------------------------------------
# recompile-hazard analyzer
# ---------------------------------------------------------------------------

def test_recompile_dim_exact():
    specs = [(np.zeros((1, p), np.int64), np.zeros((4,), np.float32))
             for p in (7, 9, 13)]
    fs = recompile_report("gen", specs)
    assert len(fs) == 1 and fs[0].code == RECOMPILE_DIM
    assert fs[0].site == "arg0"
    assert fs[0].data["varying_dims"] == [1]
    assert fs[0].data["distinct_programs"] == 3


def test_recompile_stable_specs_clean_and_structure_drift():
    stable = [(np.zeros((1, 8)),)] * 3
    assert recompile_report("gen", stable) == []
    drift = [({"a": np.zeros(3)},), ({"a": np.zeros(3),
                                      "b": np.zeros(3)},)]
    fs = recompile_report("gen", drift)
    assert [f.code for f in fs] == [RECOMPILE_STRUCTURE]


def test_recompile_dtype_drift_flagged():
    fs = recompile_report("gen", [(np.zeros(8, np.float32),),
                                  (np.zeros(8, np.float64),)])
    assert fs and fs[0].code == RECOMPILE_DIM
    assert "dtype varies" in fs[0].message


# ---------------------------------------------------------------------------
# codebase (AST) lint
# ---------------------------------------------------------------------------

_SNIPPET = '''
import jax
import numpy as np
from paddle_tpu.nn import Layer


def hot(x):
    return jax.jit(lambda v: v * 2)(x)            # retrace per call


class Gate(Layer):
    def forward(self, x):
        stats = np.asarray(x)                     # concretizes tracer
        self._last = x * 2                        # tracer on the layer
        self._ok = x.sum()   # tpulint: disable=traced-attr-mutation
        self.training = True                      # constant: trace-safe
        return x


class HostSide:                                   # not a Layer: exempt
    def forward(self, x):
        self.cache = np.asarray(x)
        return x
'''


def test_codebase_lint_synthetic(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(_SNIPPET)
    fs = lint_file(str(p), str(tmp_path))
    by_code = {}
    for f in fs:
        by_code.setdefault(f.code, []).append(f)
    assert [f.site for f in by_code[JIT_IN_CALL]] == ["hot"]
    assert [f.site for f in by_code[TRACED_ATTR_MUTATION]] == \
        ["Gate.forward._last"]          # _ok disabled, constants exempt
    assert [f.site for f in by_code[NUMPY_IN_TRACE]] == \
        ["Gate.forward.np.asarray"]     # HostSide is not layer-like


def test_jit_no_donation_on_hot_wrapper_files(tmp_path):
    """HOT_JIT_FILES membership is by repo-relative path: the same
    knob-less jax.jit is info-flagged inside jit/training.py and silent
    elsewhere."""
    from paddle_tpu.analysis.findings import JIT_NO_DONATION
    hot = tmp_path / "paddle_tpu" / "jit" / "training.py"
    hot.parent.mkdir(parents=True)
    hot.write_text("import jax\n\ndef build(f):\n    return jax.jit(f)\n")
    fs = lint_file(str(hot), str(tmp_path))
    assert [f.code for f in fs] == [JIT_NO_DONATION]
    cold = tmp_path / "paddle_tpu" / "other.py"
    cold.write_text("import jax\n\ndef build(f):\n    return jax.jit(f)\n")
    assert lint_file(str(cold), str(tmp_path)) == []


def test_quarantine_machine_check(tmp_path):
    q = tmp_path / "flaky_quarantine.txt"
    q.write_text(
        "# comment\n"
        "tests/test_analysis.py::test_quarantine_machine_check\n"
        "tests/no_such_file.py::test_gone\n"
        "name_that_matches_no_test\n")
    fs = lint_quarantine(ROOT, quarantine_path=str(q))
    stale = sorted(f.site for f in fs)
    assert all(f.code == STALE_QUARANTINE for f in fs)
    assert stale == ["name_that_matches_no_test",
                     "tests/no_such_file.py::test_gone"]


def test_quarantine_class_based_nodeids_and_substrings_resolve(tmp_path):
    """Class-based nodeids (path::TestCls::test_fn) and Test-class -k
    substrings are valid quarantine entries and must not read as stale
    (ci.py's own _quarantine() accepts them; the policies must agree)."""
    q = tmp_path / "q.txt"
    q.write_text(
        "tests/test_analysis.py::TestGateAnchors::test_anchor_is_"
        "segment_bounded\n"
        "TestGateAnchors\n"
        "flash_kernel\n")     # -k also matches MODULE names (whole-file)
    assert lint_quarantine(ROOT, quarantine_path=str(q)) == []


def test_run_manifest_rejects_unknown_program_names():
    from paddle_tpu.analysis import run_manifest
    with pytest.raises(ValueError, match="unknown manifest program"):
        run_manifest(["gpt_deocde"])      # typo must not silently pass


def test_repo_quarantine_entries_all_resolve():
    """The checked-in registry must be clean — known failures stay
    tracked, not rotted (satellite: machine-checked annotations)."""
    assert lint_quarantine(ROOT) == []


# ---------------------------------------------------------------------------
# baseline gate semantics
# ---------------------------------------------------------------------------

def _mk(code, program, site, sev="warn", count=1):
    return Finding(code, sev, program, site, "m",
                   {"count": count} if count != 1 else {})


def test_gate_ratchets_on_counts_and_weights():
    base = {"counts": {"scatter-op::p::scatter": 2}}
    ok = [_mk("scatter-op", "p", "scatter", count=2)]
    assert diff_against_baseline(ok, base) == []
    worse = [_mk("scatter-op", "p", "scatter", count=3)]
    new = diff_against_baseline(worse, base)
    assert len(new) == 1 and "exceeds baseline" in new[0]["reason"]
    # info inventories are count-pinned too: a gather/collective count
    # regression gates exactly like a warn (the documented contract)
    info = [_mk("gather-op", "p", "gather", sev="info", count=3)]
    assert diff_against_baseline(
        info, {"counts": {"gather-op::p::gather": 3}}) == []
    assert diff_against_baseline(
        info, {"counts": {"gather-op::p::gather": 2}})


class TestGateAnchors:
    def test_anchor_beats_counts(self):
        base = {"counts": {"scatter-op::p::scatter": 5},
                "must_stay_clean": ["scatter-op::p"]}
        new = diff_against_baseline([_mk("scatter-op", "p", "scatter")],
                                    base)
        assert len(new) == 1 and "must_stay_clean" in new[0]["reason"]

    def test_anchor_is_segment_bounded(self):
        """Anchor 'x::train_step' must not capture a future program
        named 'train_step_acc' (prefix match is '::'-bounded)."""
        base = {"counts": {"scatter-op::train_step_acc::scatter": 1},
                "must_stay_clean": ["scatter-op::train_step"]}
        ok = [_mk("scatter-op", "train_step_acc", "scatter")]
        assert diff_against_baseline(ok, base) == []
        hit = [_mk("scatter-op", "train_step", "scatter")]
        assert diff_against_baseline(hit, base)


def test_count_findings_weights_op_counts():
    counts = count_findings([_mk("scatter-op", "p", "scatter", count=2),
                             _mk("scatter-op", "p", "scatter")])
    assert counts == {"scatter-op::p::scatter": 3}


# ---------------------------------------------------------------------------
# the acceptance demonstration: a seeded hazard fails the CHECKED-IN
# baseline, and the real engine decode program stays clean
# ---------------------------------------------------------------------------

def test_seeded_scatter_cache_write_fails_checked_in_baseline():
    """Reintroducing a scatter cache write into the decode program (the
    exact PR-2 hazard) must fail the CI gate against the committed
    baseline — the must_stay_clean anchor fires even if counts were
    bumped."""
    def bad_decode(cache, tok, pos):
        # the regression tpulint exists to catch: per-row scatter write
        return cache.at[jnp.arange(cache.shape[0]), pos].set(
            tok.astype(cache.dtype))

    cache = jnp.zeros((4, 64, 8), jnp.float32)
    fs = lint_program(
        "gpt_decode", jax.jit(bad_decode, donate_argnums=(0,)),
        (cache, jnp.zeros((4, 8), jnp.int32), jnp.zeros(4, jnp.int32)))
    base = load_baseline(os.path.join(ROOT, "tools",
                                      "tpulint_baseline.json"))
    new = diff_against_baseline(fs, base)
    assert any(n["code"] == SCATTER_OP and n["program"] == "gpt_decode"
               for n in new), new


def test_real_engine_decode_program_is_clean():
    """The engine's batched decode program: no scatter (one-hot masked
    cache writes), KV cache donated, no baked keys, no host callbacks —
    the donation satellite + PR-2 write regime, asserted on the REAL
    program via the same manifest builder the CLI uses."""
    from paddle_tpu.analysis.manifest import _build_gpt_decode
    prog, args, cleanup = _build_gpt_decode()
    try:
        fs = lint_program("gpt_decode", prog, args)
    finally:
        cleanup()
    codes = _codes(fs)
    assert SCATTER_OP not in codes
    assert UNDONATED_BUFFER not in codes      # cache donation wired
    assert BAKED_RNG_KEY not in codes
    assert HOST_CALLBACK not in codes
    # and the committed baseline accepts the program as-is
    base = load_baseline(os.path.join(ROOT, "tools",
                                      "tpulint_baseline.json"))
    assert diff_against_baseline(fs, base) == []


def test_tpulint_cli_codebase_only_gate_passes(capsys, monkeypatch):
    """The CLI contract tpu_suite2.sh relies on: last stdout line is a
    good JSON record (tools/_have_result.py), gate passes on HEAD.
    Run in-process (runpy) — a subprocess would pay a cold paddle_tpu
    import (~10 s) for nothing on the 1-core tier-1 budget."""
    import runpy
    monkeypatch.setattr(sys, "argv", ["tpulint.py", "--codebase-only"])
    with pytest.raises(SystemExit) as exc:
        runpy.run_path(os.path.join(ROOT, "tools", "tpulint.py"),
                       run_name="__main__")
    assert exc.value.code == 0
    rec = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["gate"] == "pass" and "error" not in rec
