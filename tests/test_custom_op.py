"""Custom-op extension paths (VERDICT missing item 12): Python/Pallas
registration (framework/custom_op) and out-of-tree C++ via the C-ABI
(utils/cpp_extension, reference custom_operator.cc / phi capi)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import custom_op


class TestRegisteredOp:
    def test_register_and_dispatch(self):
        import jax.numpy as jnp

        @custom_op.register("cube_plus_one")
        def cube_plus_one(x):
            return x ** 3 + 1

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = paddle.ops.cube_plus_one(x)
        np.testing.assert_allclose(out.numpy(), [2.0, 9.0])
        # autodiff through the registered forward (no custom vjp)
        x.stop_gradient = False
        paddle.ops.cube_plus_one(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 3 * x.numpy() ** 2)

    def test_custom_vjp(self):
        import jax.numpy as jnp

        def bwd(res, g):
            (x,) = res
            return (jnp.full_like(x, 7.0) * g,)  # deliberately wrong math

        @custom_op.register("odd_grad", backward=bwd)
        def odd_grad(x):
            return 2.0 * x

        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        paddle.ops.odd_grad(x).sum().backward()
        # custom vjp wins over the analytic d(2x)/dx = 2
        np.testing.assert_allclose(x.grad.numpy(), 7.0)

    def test_get_op_unknown_raises(self):
        with pytest.raises(KeyError, match="not registered"):
            custom_op.get_op("no_such_op")


_SRC = textwrap.dedent("""
    extern "C" void axpy2(const float* const* ins,
                          const long long* const* shapes,
                          const int* ndims, int n_ins, float* out) {
      long long n = 1;
      for (int d = 0; d < ndims[0]; ++d) n *= shapes[0][d];
      for (long long i = 0; i < n; ++i)
        out[i] = 2.0f * ins[0][i] + ins[1][i];
    }
    extern "C" void axpy2_grad(const float* const* ins,
                               const long long* const* shapes,
                               const int* ndims, int n_ins,
                               float* const* grad_outs) {
      long long n = 1;
      for (int d = 0; d < ndims[0]; ++d) n *= shapes[0][d];
      const float* ct = ins[n_ins - 1];
      for (long long i = 0; i < n; ++i) {
        grad_outs[0][i] = 2.0f * ct[i];
        grad_outs[1][i] = ct[i];
      }
    }
    extern "C" void sum_all(const float* const* ins,
                            const long long* const* shapes,
                            const int* ndims, int n_ins, float* out) {
      long long n = 1;
      for (int d = 0; d < ndims[0]; ++d) n *= shapes[0][d];
      out[0] = 0.0f;
      for (long long i = 0; i < n; ++i) out[0] += ins[0][i];
    }
""")


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils import cpp_extension
    d = tmp_path_factory.mktemp("ext")
    src = d / "ops.cc"
    src.write_text(_SRC)
    return cpp_extension.load("testext", [str(src)])


class TestCppExtension:
    def test_forward(self, ext):
        rng = np.random.RandomState(0)
        a = paddle.to_tensor(rng.randn(4, 5).astype(np.float32))
        b = paddle.to_tensor(rng.randn(4, 5).astype(np.float32))
        out = ext.axpy2(a, b)
        np.testing.assert_allclose(out.numpy(), 2 * a.numpy() + b.numpy(),
                                   rtol=1e-6)

    def test_gradient_via_c_abi(self, ext):
        a = paddle.to_tensor(np.ones((3,), np.float32))
        b = paddle.to_tensor(np.ones((3,), np.float32))
        a.stop_gradient = False
        b.stop_gradient = False
        ext.axpy2(a, b).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), 2.0)
        np.testing.assert_allclose(b.grad.numpy(), 1.0)

    def test_custom_out_shape(self, ext):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32))
        out = ext.call("sum_all", x, out_shape=(1,))
        np.testing.assert_allclose(out.numpy(), [15.0])

    def test_works_under_jit(self, ext):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(u, v):
            t = ext.axpy2(paddle.to_tensor(u), paddle.to_tensor(v))
            return t.value + 1

        u = jnp.ones((2, 2))
        v = jnp.ones((2, 2))
        np.testing.assert_allclose(np.asarray(f(u, v)), 4.0)

    def test_build_cache_reused(self, ext, tmp_path):
        from paddle_tpu.utils import cpp_extension
        src = tmp_path / "ops2.cc"
        src.write_text(_SRC)
        e2 = cpp_extension.load("testext", [str(src)])
        # same content hash → the exact same cached artifact
        assert e2._path == ext._path

    def test_ops_importable_module(self):
        import importlib
        mod = importlib.import_module("paddle_tpu.ops")
        import paddle_tpu
        assert mod is paddle_tpu.ops

    def test_kwargs_with_custom_vjp(self):
        import jax.numpy as jnp

        def bwd(res, g):
            (x,) = res
            return (jnp.zeros_like(x) + 5.0 * g,)

        @custom_op.register("scaled_tanh", backward=bwd)
        def scaled_tanh(x, scale=1.0):
            return jnp.tanh(x) * scale

        x = paddle.to_tensor(np.zeros(2, np.float32))
        x.stop_gradient = False
        out = paddle.ops.scaled_tanh(x, scale=3.0)
        np.testing.assert_allclose(out.numpy(), 0.0)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 5.0)

    def test_missing_symbol_raises(self, ext):
        x = paddle.to_tensor(np.ones(2, np.float32))
        with pytest.raises(AttributeError, match="no symbol"):
            ext.call("nope", x)

    def test_compile_error_surfaces(self, tmp_path):
        from paddle_tpu.utils import cpp_extension
        bad = tmp_path / "bad.cc"
        bad.write_text("this is not C++")
        with pytest.raises(RuntimeError, match="build failed"):
            cpp_extension.load("badext", [str(bad)])


class TestUtils:
    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        assert a != b and a.startswith("fc")

    def test_deprecated_warns(self):
        from paddle_tpu.utils import deprecated

        @deprecated(update_to="new_fn", since="2.0")
        def old_fn():
            return 42

        with pytest.warns(DeprecationWarning):
            assert old_fn() == 42
