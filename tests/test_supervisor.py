"""Self-healing training supervisor (distributed/supervisor.py).

The loop PR 1's primitives never closed: NaN storms / wedged steps /
finite loss spikes roll back to the last verified checkpoint and
resume (bitwise where nothing was skipped), SIGTERM preemption grace-
checkpoints and exits with the requeue code, a fresh run() on the same
directory auto-resumes flaglessly, retention GC prunes without ever
touching the last verified checkpoint, and subprocess mode respawns a
kill -9'd trainer under a bounded crash-loop budget.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import resilience as resil
from paddle_tpu.distributed.checkpoint import (gc_checkpoints,
                                               latest_checkpoint,
                                               list_checkpoints)
from paddle_tpu.distributed.resilience import FaultInjected, FaultInjector
from paddle_tpu.distributed.supervisor import (REQUEUE_EXIT_CODE,
                                               SupervisorGaveUp,
                                               TrainSupervisor,
                                               load_manifest)
from paddle_tpu.hapi import Model
from paddle_tpu.io.dataloader import DataLoader

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FACTORY_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "_supervisor_factories.py")

FAST_BACKOFF = resil.RetryPolicy(max_attempts=16, base_delay=0.0,
                                 jitter=0.0)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

class _Rows:
    def __init__(self, xs, ys):
        self.xs, self.ys = xs, ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]


def _make_model(seed=0):
    paddle.seed(seed)
    net = nn.Linear(4, 4)
    m = Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    m.prepare(optimizer=opt, loss=lambda o, y: F.mse_loss(o, y))
    return m


def _make_loader(n=16, bs=4, seed=0, poison_at=None):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 4).astype("float32")
    ys = rng.randn(n, 4).astype("float32")
    if poison_at is not None:
        # one batch of absurd labels -> a FINITE loss spike (the case
        # the NaN scan can never catch)
        lo = poison_at * bs
        ys[lo:lo + bs] = 1e6
    return DataLoader(_Rows(xs, ys), batch_size=bs, shuffle=False)


def _sup(model, loader, d, **kw):
    kw.setdefault("fit_kwargs", {"epochs": 3, "verbose": 0})
    kw.setdefault("ckpt_every", 4)
    kw.setdefault("max_to_keep", 2)
    kw.setdefault("backoff", FAST_BACKOFF)
    return TrainSupervisor(model, loader, directory=str(d), **kw)


def _final_tree(d):
    path = latest_checkpoint(str(d))
    assert path is not None
    return path, ckpt.load_state_dict(path)


def _trees_bitwise(a, b):
    import jax
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


@pytest.fixture(scope="module")
def unfaulted(tmp_path_factory):
    """One unfaulted supervised run — the bitwise comparison object
    every recovery test measures against."""
    d = tmp_path_factory.mktemp("unfaulted")
    r = _sup(_make_model(), _make_loader(), d).run()
    assert r.outcome == "completed" and r.final_step == 12
    _, tree = _final_tree(d)
    return tree


# ---------------------------------------------------------------------------
# retention / latest_checkpoint / GC
# ---------------------------------------------------------------------------

def _mk_committed(root, step):
    p = os.path.join(str(root), f"ckpt-{step}")
    os.makedirs(p)
    with open(os.path.join(p, ckpt._COMMIT_MARKER), "w") as f:
        f.write("committed\n")
    return p


def test_latest_skips_uncommitted_and_corrupt(tmp_path):
    for s in (1, 2, 5):
        _mk_committed(tmp_path, s)
    os.makedirs(tmp_path / "ckpt-6.tmp")          # killed mid-write
    os.makedirs(tmp_path / "ckpt-7")              # corrupt: no marker
    os.makedirs(tmp_path / "ckpt-junk")           # not ours
    assert latest_checkpoint(str(tmp_path)).endswith("ckpt-5")
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [1, 2, 5]


def test_latest_finishes_interrupted_publish(tmp_path):
    _mk_committed(tmp_path, 3)
    # a save killed between marker write and publish: committed .tmp
    p = _mk_committed(tmp_path, 9)
    os.rename(p, p + ".tmp")
    assert latest_checkpoint(str(tmp_path)).endswith("ckpt-9")
    assert os.path.isdir(tmp_path / "ckpt-9")


def test_gc_retention_never_deletes_last_verified_mid_publish(tmp_path):
    for s in (1, 2, 3, 4, 5):
        _mk_committed(tmp_path, s)
    # a NEW save is mid-publish right now: its tmp must be invisible —
    # neither deleted nor counted against the quota
    os.makedirs(tmp_path / "ckpt-6.tmp")
    deleted = gc_checkpoints(str(tmp_path), max_to_keep=2,
                             keep=[str(tmp_path / "ckpt-1")])
    names = {os.path.basename(p) for p in deleted}
    assert names == {"ckpt-2", "ckpt-3"}
    assert os.path.isdir(tmp_path / "ckpt-5")    # newest: last verified
    assert os.path.isdir(tmp_path / "ckpt-4")
    assert os.path.isdir(tmp_path / "ckpt-1")    # protected via keep
    assert os.path.isdir(tmp_path / "ckpt-6.tmp")  # mid-publish: untouched
    # max_to_keep clamps to >= 1: the sole survivor is never collected
    assert gc_checkpoints(str(tmp_path), max_to_keep=0,
                          keep=[str(tmp_path / "ckpt-1")]) != []
    assert os.path.isdir(tmp_path / "ckpt-5")


def test_gc_sweeps_markerless_strays(tmp_path):
    _mk_committed(tmp_path, 4)
    os.makedirs(tmp_path / "ckpt-2")             # killed mid-GC earlier
    deleted = gc_checkpoints(str(tmp_path), max_to_keep=3)
    assert {os.path.basename(p) for p in deleted} == {"ckpt-2"}
    assert os.path.isdir(tmp_path / "ckpt-4")


def test_ckpt_gc_fault_site_fires_before_deleting(tmp_path):
    for s in (1, 2, 3):
        _mk_committed(tmp_path, s)
    with FaultInjector({"ckpt_gc": 1}):
        with pytest.raises(FaultInjected):
            gc_checkpoints(str(tmp_path), max_to_keep=1)
    # nothing was deleted: the fault fires before any removal
    assert [s for s, _ in list_checkpoints(str(tmp_path))] == [1, 2, 3]


def test_manifest_entries_record_topology(tmp_path):
    """ISSUE 12 satellite bugfix: every checkpoint entry carries the
    topology that produced it (single-device here — the mesh case is
    tests/test_elastic_checkpoint.py's), and the result surface counts
    reshards (zero on a topology-stable run)."""
    d = tmp_path / "job"
    r = _sup(_make_model(), _make_loader(), d).run()
    assert r.outcome == "completed" and r.reshards == 0
    m = load_manifest(str(d))
    assert m["checkpoints"]
    for e in m["checkpoints"]:
        topo = e.get("topology")
        assert topo is not None
        assert topo["mesh"] is None and topo["device_count"] == 1
        assert topo["scan_steps"] == 1
    # the checkpoint itself is stamped with a layout manifest
    lay = ckpt.read_layout(latest_checkpoint(str(d)))
    assert lay is not None and lay["mesh"] is None
    assert "params/weight" in lay["leaves"]


def test_supervised_run_prunes_to_policy(tmp_path, unfaulted):
    d = tmp_path / "job"
    r = _sup(_make_model(), _make_loader(), d, max_to_keep=2).run()
    assert r.outcome == "completed"
    steps = [s for s, _ in list_checkpoints(str(d))]
    assert len(steps) <= 3          # max_to_keep newest + keep-best
    assert steps[-1] == 12          # the final state is a checkpoint
    m = load_manifest(str(d))
    assert m["done"] and m["final_step"] == 12
    assert {e["name"] for e in m["checkpoints"]} == \
        {f"ckpt-{s}" for s in steps}


# ---------------------------------------------------------------------------
# rollback on divergence
# ---------------------------------------------------------------------------

def test_nan_storm_rollback_resumes_bitwise(tmp_path, unfaulted):
    d = tmp_path / "job"
    sup = _sup(_make_model(), _make_loader(), d, nan_limit=3)
    with FaultInjector({"train_step_nan": 3}):
        r = sup.run()
    assert r.outcome == "completed" and r.rollbacks == 1
    _, tree = _final_tree(d)
    assert _trees_bitwise(tree["params"], unfaulted["params"])
    assert _trees_bitwise(tree["opt"], unfaulted["opt"])
    assert int(tree["meta"]["step_count"]) == 12
    m = load_manifest(str(d))
    kinds = [i["kind"] for i in m["incidents"]]
    assert kinds == ["nan_storm"] and m["skipped_windows"] == []


def test_wedged_step_rollback_resumes_bitwise(tmp_path, unfaulted):
    d = tmp_path / "job"
    sup = _sup(_make_model(), _make_loader(), d, step_timeout=1.0)
    with FaultInjector({"step_hang": 1}, wedge_s=5.0):
        r = sup.run()
    assert r.outcome == "completed" and r.rollbacks == 1
    _, tree = _final_tree(d)
    assert _trees_bitwise(tree["params"], unfaulted["params"])
    m = load_manifest(str(d))
    assert [i["kind"] for i in m["incidents"]] == ["hang"]


def test_loss_spike_rollback_restores_bitwise_state_then_skips(tmp_path):
    """The escalation ladder end to end: a FINITE poison batch spikes
    the loss at step 6 -> rollback to ckpt-4 (bitwise) -> retry hits
    the same spike -> the window [4, 6) is skipped -> completion. The
    faulted run's final state must be bitwise the state of a clean run
    told to skip the same window — only possible if every rollback
    restored params/opt/RNG exactly."""
    poisoned = lambda: _make_loader(n=48, poison_at=5)  # noqa: E731
    d = tmp_path / "job"
    sup = _sup(_make_model(), poisoned(), d,
               fit_kwargs={"epochs": 1, "verbose": 0},
               spike_window=8, spike_z=6.0, spike_min_points=4,
               retries_per_window=1)
    r = sup.run()
    assert r.outcome == "completed"
    assert r.rollbacks == 2          # retry once, then skip
    assert r.skipped_steps == 2
    m = load_manifest(str(d))
    assert m["skipped_windows"] == [[4, 6]]
    actions = [i["action"] for i in m["incidents"]]
    assert actions == ["retry", "skip_window"]
    assert all(i["kind"] == "loss_spike" for i in m["incidents"])

    # clean reference: same data, the same window skipped a priori
    ref = _make_model()
    ref.fit(poisoned(), epochs=1, verbose=0, skip_windows=[(4, 6)])
    _, tree = _final_tree(d)
    ref_params = ref._train_step.params
    assert _trees_bitwise(tree["params"], ref_params)
    assert int(tree["meta"]["step_count"]) == 12


def test_restart_budget_exhausts_loudly(tmp_path):
    d = tmp_path / "job"
    sup = _sup(_make_model(), _make_loader(n=48, poison_at=5), d,
               fit_kwargs={"epochs": 1, "verbose": 0},
               spike_window=8, spike_z=6.0, spike_min_points=4,
               restart_budget=0)
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert "budget" in str(ei.value)
    m = load_manifest(str(d))
    assert m["outcome"] == "gave_up"
    assert m["incidents"][-1]["action"] == "give_up"


# ---------------------------------------------------------------------------
# preemption grace + flagless auto-resume
# ---------------------------------------------------------------------------

def test_injected_preemption_checkpoints_and_requeues(tmp_path, unfaulted):
    d = tmp_path / "job"
    sup = _sup(_make_model(), _make_loader(), d)
    with FaultInjector({"preempt_signal": 1}):
        r = sup.run()
    assert r.outcome == "preempted"
    assert r.exit_code == REQUEUE_EXIT_CODE == 75
    # the grace checkpoint landed at the preemption step
    m = load_manifest(str(d))
    assert m["outcome"] == "preempted" and m["preemptions"] == 1
    assert latest_checkpoint(str(d)) is not None

    # flagless auto-resume: a FRESH supervisor+model on the same dir
    r2 = _sup(_make_model(), _make_loader(), d).run()
    assert r2.outcome == "completed" and r2.final_step == 12
    _, tree = _final_tree(d)
    assert _trees_bitwise(tree["params"], unfaulted["params"])
    assert _trees_bitwise(tree["opt"], unfaulted["opt"])


def test_resume_of_completed_run_trains_nothing(tmp_path):
    d = tmp_path / "job"
    assert _sup(_make_model(), _make_loader(), d).run().final_step == 12
    t0 = _final_tree(d)[1]
    r = _sup(_make_model(), _make_loader(), d).run()
    assert r.outcome == "completed" and r.final_step == 12
    assert _trees_bitwise(_final_tree(d)[1]["params"], t0["params"])


# ---------------------------------------------------------------------------
# subprocess mode: real SIGTERM + kill -9 crash isolation
# ---------------------------------------------------------------------------

def _child_env(extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.update(extra or {})
    return env


def _child_argv(d, policy=None):
    spec = {"factory": f"{FACTORY_FILE}:make_trainer",
            "policy": dict({"ckpt_every": 5, "max_to_keep": 3},
                           **(policy or {}))}
    return [sys.executable, "-m", "paddle_tpu.distributed.supervisor",
            "--child", "--dir", str(d), "--spec", json.dumps(spec)]


@pytest.fixture(scope="module")
def factory_unfaulted(tmp_path_factory):
    """The factory trainer run unfaulted IN-PROCESS (identical to what
    an unfaulted child computes — same seed, same data)."""
    from paddle_tpu.distributed.supervisor import _load_factory
    model, loader, kw = _load_factory(f"{FACTORY_FILE}:make_trainer")()
    d = tmp_path_factory.mktemp("factory_unfaulted")
    r = TrainSupervisor(model, loader, directory=str(d), fit_kwargs=kw,
                        ckpt_every=5, max_to_keep=3,
                        backoff=FAST_BACKOFF).run()
    assert r.outcome == "completed" and r.final_step == 24
    return _final_tree(d)[1]


def _wait_for_checkpoint(d, min_step, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if any(s >= min_step for s, _ in list_checkpoints(str(d))):
            return True
        time.sleep(0.1)
    return False


def test_sigterm_grace_requeue_exit_and_flagless_resume(
        tmp_path, factory_unfaulted):
    d = tmp_path / "job"
    argv = _child_argv(d)
    env = _child_env({"PTPU_TEST_STEP_SLEEP": "0.2"})
    proc = subprocess.Popen(argv, env=env, cwd=ROOT,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        assert _wait_for_checkpoint(d, 5), "no checkpoint before signal"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == REQUEUE_EXIT_CODE      # the distinct requeue code
    m = load_manifest(str(d))
    assert m["outcome"] == "preempted" and m["preemptions"] == 1
    preempt_step = m["incidents"][-1]["step"]
    assert 5 <= preempt_step < 24
    # the grace checkpoint is AT the preemption step: zero lost work
    assert latest_checkpoint(str(d)).endswith(f"ckpt-{preempt_step}")

    # requeue: the SAME command line, no flags — auto-resumes and
    # finishes bitwise-identical to the unfaulted run
    rc2 = subprocess.run(_child_argv(d), env=_child_env(), cwd=ROOT,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT,
                         timeout=120).returncode
    assert rc2 == 0
    m2 = load_manifest(str(d))
    assert m2["done"] and m2["final_step"] == 24
    tree = _final_tree(d)[1]
    assert _trees_bitwise(tree["params"], factory_unfaulted["params"])
    assert _trees_bitwise(tree["opt"], factory_unfaulted["opt"])


def test_subprocess_kill9_respawn_matches_unfaulted(
        tmp_path, factory_unfaulted):
    d = tmp_path / "job"
    sup = TrainSupervisor(
        factory=f"{FACTORY_FILE}:make_trainer", directory=str(d),
        subprocess_mode=True, ckpt_every=5, max_to_keep=3,
        restart_budget=3, backoff=FAST_BACKOFF,
        child_env={"JAX_PLATFORMS": "cpu",
                   "PYTHONPATH": _child_env()["PYTHONPATH"],
                   "PTPU_TEST_STEP_SLEEP": "0.2"})
    box = {}

    def run():
        try:
            box["result"] = sup.run()
        except BaseException as e:   # surface in the test thread
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert _wait_for_checkpoint(d, 5), "no checkpoint before kill"
    pid = sup.child_pid
    assert pid is not None
    os.kill(pid, signal.SIGKILL)               # kill -9 the trainer
    t.join(timeout=180)
    assert not t.is_alive(), "supervisor did not finish after respawn"
    assert "error" not in box, box.get("error")
    r = box["result"]
    assert r.outcome == "completed" and r.respawns >= 1
    m = load_manifest(str(d))
    assert m["final_step"] == 24
    assert any(i["kind"] == "trainer_crash" for i in m["incidents"])
    tree = _final_tree(d)[1]
    assert _trees_bitwise(tree["params"], factory_unfaulted["params"])
    assert _trees_bitwise(tree["opt"], factory_unfaulted["opt"])
    assert _trees_bitwise(tree["meta"]["rng_key_data"],
                          factory_unfaulted["meta"]["rng_key_data"])


def test_subprocess_crash_loop_budget_exhausts_loudly(tmp_path):
    d = tmp_path / "job"
    sup = TrainSupervisor(
        factory=f"{FACTORY_FILE}:make_crashing_trainer",
        directory=str(d), subprocess_mode=True, restart_budget=0,
        backoff=FAST_BACKOFF,
        child_env={"JAX_PLATFORMS": "cpu",
                   "PYTHONPATH": _child_env()["PYTHONPATH"]})
    with pytest.raises(SupervisorGaveUp) as ei:
        sup.run()
    assert "crash-loop" in str(ei.value)
    m = load_manifest(str(d))
    assert m["outcome"] == "gave_up"
    assert any(i["kind"] == "trainer_crash" for i in m["incidents"])


def test_rollback_survives_torn_manifest(tmp_path, unfaulted):
    """The state on disk outranks the book about it: losing the
    manifest between runs must not turn a restorable rollback into a
    give-up."""
    d = tmp_path / "job"
    with FaultInjector({"preempt_signal": 1}):
        _sup(_make_model(), _make_loader(), d).run()
    os.unlink(os.path.join(str(d), "supervisor_manifest.json"))
    sup = _sup(_make_model(), _make_loader(), d, nan_limit=3)
    with FaultInjector({"train_step_nan": 3}):
        r = sup.run()
    assert r.outcome == "completed" and r.rollbacks == 1
    assert _trees_bitwise(_final_tree(d)[1]["params"],
                          unfaulted["params"])


def test_subprocess_fit_kwargs_ride_the_spec(tmp_path):
    # non-serializable fit_kwargs fail LOUDLY at construction (they
    # would otherwise be silently dropped on the way to the child)
    with pytest.raises(ValueError, match="JSON-serializable"):
        TrainSupervisor(factory="mod:fn", directory=str(tmp_path),
                        subprocess_mode=True,
                        fit_kwargs={"callbacks": [object()]})
    # serializable ones land in the child spec verbatim
    sup = TrainSupervisor(factory="mod:fn", directory=str(tmp_path),
                          subprocess_mode=True,
                          fit_kwargs={"epochs": 5})
    assert sup.fit_kwargs == {"epochs": 5}


def test_preempted_parent_forwards_and_never_respawns(tmp_path):
    """A parent under preemption must forward ONE TERM and propagate
    the requeue — never respawn (a fresh child would eat the forwarded
    TERM mid-import and read as a crash loop), and never report a
    teardown signal death as a trainer crash."""
    d = tmp_path / "job"
    sup = TrainSupervisor(
        factory=f"{FACTORY_FILE}:make_trainer", directory=str(d),
        subprocess_mode=True, ckpt_every=5, restart_budget=3,
        backoff=FAST_BACKOFF,
        child_env={"JAX_PLATFORMS": "cpu",
                   "PYTHONPATH": _child_env()["PYTHONPATH"],
                   "PTPU_TEST_STEP_SLEEP": "0.2"})
    box = {}

    def run():
        try:
            box["result"] = sup.run()
        except BaseException as e:
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert _wait_for_checkpoint(d, 5), "no checkpoint before preempt"
    sup._note_preempt("test_preempt")    # what the SIGTERM handler does
    t.join(timeout=120)
    assert not t.is_alive() and "error" not in box, box.get("error")
    r = box["result"]
    assert r.outcome == "preempted" and r.exit_code == REQUEUE_EXIT_CODE
    assert r.respawns == 0
    m = load_manifest(str(d))
    assert m["preemptions"] >= 1
    assert not any(i["kind"] == "trainer_crash" for i in m["incidents"])
