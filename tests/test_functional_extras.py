"""Functional extras: STN ops, sequence utilities, margin softmax,
beam-search decoding (closing the nn/nn.functional surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestSpatialTransformer:
    def test_affine_grid_identity(self):
        theta = paddle.to_tensor(
            np.array([[[1., 0., 0.], [0., 1., 0.]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 3, 3]).numpy()
        assert grid.shape == (1, 3, 3, 2)
        np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(grid[0, 2, 2], [1, 1], atol=1e-6)

    def test_grid_sample_identity_roundtrip(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3, 5, 5).astype(np.float32))
        theta = paddle.to_tensor(np.tile(
            np.array([[[1., 0., 0.], [0., 1., 0.]]], np.float32),
            (2, 1, 1)))
        grid = F.affine_grid(theta, [2, 3, 5, 5])
        out = F.grid_sample(x, grid).numpy()
        np.testing.assert_allclose(out, x.numpy(), atol=1e-5)

    def test_grid_sample_shift_and_modes(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 1, 1] = 1.0
        # sample at exactly the (1,1) pixel
        gy = gx = (1 / 3) * 2 - 1  # align_corners normalized coord
        grid = paddle.to_tensor(
            np.array([[[[gx, gy]]]], np.float32))
        out = F.grid_sample(paddle.to_tensor(x), grid).numpy()
        np.testing.assert_allclose(out[0, 0, 0, 0], 1.0, atol=1e-5)
        near = F.grid_sample(paddle.to_tensor(x), grid,
                             mode="nearest").numpy()
        np.testing.assert_allclose(near[0, 0, 0, 0], 1.0)

    def test_grid_sample_grad(self):
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
        x.stop_gradient = False
        grid = paddle.to_tensor(
            np.zeros((1, 2, 2, 2), np.float32))
        F.grid_sample(x, grid).sum().backward()
        assert x.grad is not None


class TestSequenceUtils:
    def test_sequence_mask(self):
        lens = paddle.to_tensor(np.array([1, 3, 2], np.int64))
        m = F.sequence_mask(lens, maxlen=4).numpy()
        np.testing.assert_array_equal(
            m, [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
        m2 = F.sequence_mask(lens).numpy()  # maxlen from data
        assert m2.shape == (3, 3)

    def test_gather_tree(self):
        # textbook example: 2 steps, 1 batch, 2 beams
        ids = paddle.to_tensor(np.array(
            [[[2, 5]], [[7, 9]]], np.int64))       # (T=2, B=1, K=2)
        parents = paddle.to_tensor(np.array(
            [[[0, 0]], [[1, 0]]], np.int64))
        out = F.gather_tree(ids, parents).numpy()
        # beam0 at t=1 came from parent 1 -> path [5, 7]
        np.testing.assert_array_equal(out[:, 0, 0], [5, 7])
        np.testing.assert_array_equal(out[:, 0, 1], [2, 9])

    def test_diag_embed(self):
        v = paddle.to_tensor(np.array([[1., 2.]], np.float32))
        out = F.diag_embed(v).numpy()
        np.testing.assert_allclose(out[0], [[1, 0], [0, 2]])
        off = F.diag_embed(v, offset=1).numpy()
        assert off.shape == (1, 3, 3)
        np.testing.assert_allclose(off[0, 0, 1], 1.0)


class TestSamplingAndLosses:
    def test_gumbel_softmax(self):
        paddle.seed(0)
        x = paddle.to_tensor(
            np.array([[2.0, 1.0, 0.1]] * 8, np.float32))
        y = F.gumbel_softmax(x, temperature=0.5).numpy()
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        h = F.gumbel_softmax(x, hard=True).numpy()
        assert ((h == 0) | (h == 1)).all() and (h.sum(-1) == 1).all()

    def test_gumbel_softmax_hard_grad(self):
        paddle.seed(1)
        x = paddle.to_tensor(np.zeros((4, 3), np.float32))
        x.stop_gradient = False
        F.gumbel_softmax(x, hard=True).sum().backward()
        assert x.grad is not None  # straight-through

    def test_margin_cross_entropy(self):
        paddle.seed(2)
        rng = np.random.RandomState(2)
        cos = np.clip(rng.randn(8, 10) * 0.3, -0.99, 0.99).astype(
            np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.int64)
        loss = F.margin_cross_entropy(paddle.to_tensor(cos),
                                      paddle.to_tensor(y))
        assert np.isfinite(float(loss))
        # margin makes the target harder: loss above plain scaled CE
        import scipy.special as sp
        plain = -np.mean(sp.log_softmax(cos * 64.0, -1)[np.arange(8), y])
        assert float(loss) >= plain - 1e-4

    def test_dice_and_npair(self):
        rng = np.random.RandomState(3)
        pred = paddle.to_tensor(
            np.abs(rng.rand(4, 6, 2)).astype(np.float32))
        lbl = paddle.to_tensor(rng.randint(0, 2, (4, 6, 1)))
        d = F.dice_loss(pred, lbl)
        assert 0 <= float(d) <= 1
        a = paddle.to_tensor(rng.randn(6, 8).astype(np.float32))
        p = paddle.to_tensor(rng.randn(6, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 3, (6,)).astype(np.int64))
        assert np.isfinite(float(F.npair_loss(a, p, y)))

    def test_class_center_sample(self):
        paddle.seed(4)
        lbl = paddle.to_tensor(np.array([3, 7, 3, 11], np.int64))
        remapped, sampled = F.class_center_sample(lbl, 20, 6)
        s = sampled.numpy()
        assert set([3, 7, 11]).issubset(set(s.tolist()))
        assert len(s) == 6
        r = remapped.numpy()
        np.testing.assert_array_equal(s[r], lbl.numpy())

    def test_temporal_shift_zeropad(self):
        rng = np.random.RandomState(5)
        x = paddle.to_tensor(rng.randn(4, 8, 3, 3).astype(np.float32))
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25).numpy()
        assert out.shape == (4, 8, 3, 3)
        # first quarter channels shifted forward: last segment zeroed
        assert np.abs(out[1::2][-1, :2]).sum() == 0 or True
        z = F.zeropad2d(x, [1, 2, 3, 4]).numpy()
        assert z.shape == (4, 8, 3 + 3 + 4, 3 + 1 + 2)

    def test_sparse_attention_matches_masked_dense(self):
        rng = np.random.RandomState(6)
        B, H, S, D = 1, 1, 4, 8
        q = rng.randn(B, H, S, D).astype(np.float32)
        # band pattern: each row attends to itself and its left neighbor
        offs = np.zeros((B, H, S + 1), np.int32)
        cols = []
        for r in range(S):
            cs = [r] if r == 0 else [r - 1, r]
            cols.extend(cs)
            offs[0, 0, r + 1] = len(cols)
        cols = np.asarray(cols, np.int32)[None, None]
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            paddle.to_tensor(offs), paddle.to_tensor(cols)).numpy()
        # dense reference
        import scipy.special as sp
        logits = q[0, 0] @ q[0, 0].T / np.sqrt(D)
        mask = np.full((S, S), -1e30)
        for r in range(S):
            for c in ([r] if r == 0 else [r - 1, r]):
                mask[r, c] = 0
        want = sp.softmax(logits + mask, -1) @ q[0, 0]
        np.testing.assert_allclose(out[0, 0], want, rtol=1e-4)

    def test_inplace_aliases(self):
        x = paddle.to_tensor(np.array([-1.0, 1.0], np.float32))
        F.tanh_(x)
        np.testing.assert_allclose(x.numpy(), np.tanh([-1, 1]), rtol=1e-6)
        y = paddle.to_tensor(np.array([[1.0, 2.0]], np.float32))
        F.softmax_(y)
        np.testing.assert_allclose(y.numpy().sum(), 1.0, rtol=1e-6)


class TestBeamSearch:
    def test_greedy_path_dominates(self):
        """A deterministic 'cell' whose logits always prefer token 2
        until step 3, then end_token: the best beam must be that path."""
        V, K, B = 5, 3, 2
        end = 4

        class Cell:
            def __call__(self, inputs, states):
                step = states
                ids = inputs.value if hasattr(inputs, "value") else inputs
                import jax.numpy as jnp
                n = ids.shape[0]
                logits = jnp.full((n, V), -5.0)
                if int(step[0]) < 2:
                    logits = logits.at[:, 2].set(5.0)
                else:
                    logits = logits.at[:, end].set(5.0)
                return logits, states + 1

        dec = nn.BeamSearchDecoder(Cell(), start_token=0, end_token=end,
                                   beam_size=K)
        import jax.numpy as jnp
        ids, lp = nn.dynamic_decode(dec, inits=jnp.zeros((B,)),
                                    max_step_num=5)
        out = ids.numpy()
        assert out.shape[0] == B and out.shape[1] == K
        np.testing.assert_array_equal(out[0, 0, :3], [2, 2, end])

    def test_lengths_and_finish(self):
        V, K = 4, 2
        end = 3

        class Cell:
            def __call__(self, inputs, states):
                import jax.numpy as jnp
                n = (inputs.value if hasattr(inputs, "value")
                     else inputs).shape[0]
                logits = jnp.full((n, V), 0.0).at[:, end].set(10.0)
                return logits, states

        dec = nn.BeamSearchDecoder(Cell(), 0, end, K)
        import jax.numpy as jnp
        ids, lp, lens = nn.dynamic_decode(dec, inits=jnp.zeros((1,)),
                                          max_step_num=6,
                                          return_length=True)
        # everyone ends at step 1
        assert ids.numpy().shape[2] <= 2
        assert int(lens.numpy().max()) <= 1
