"""ONNX export: wire-format round-trip + numeric execution check.

The exporter (paddle_tpu/onnx/export.py) emits ModelProto bytes with a
self-contained protobuf writer; these tests parse the bytes back with
the independent reader in _proto.py and EXECUTE the graph with a small
numpy interpreter of ONNX-13 semantics, comparing against the Layer's
own output — so the check covers wire format, graph topology, and op
semantics. Reference contract: python/paddle/onnx/export.py.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.onnx import OnnxExportError, export
from paddle_tpu.onnx import _proto as P
from paddle_tpu.static import InputSpec

_erf = np.vectorize(math.erf)


def _run_onnx(model_bytes: bytes, feeds: dict) -> list:
    m = P.parse_model(model_bytes)
    g = m["graph"]
    env = dict(g["initializers"])
    env.update(feeds)

    for node in g["nodes"]:
        i = [env[n] for n in node["inputs"]]
        a = node["attrs"]
        op = node["op_type"]
        if op == "MatMul":
            out = i[0] @ i[1]
        elif op == "Add":
            out = i[0] + i[1]
        elif op == "Sub":
            out = i[0] - i[1]
        elif op == "Mul":
            out = i[0] * i[1]
        elif op == "Div":
            out = i[0] / i[1]
        elif op == "Max":
            out = np.maximum(i[0], i[1])
        elif op == "Min":
            out = np.minimum(i[0], i[1])
        elif op == "Pow":
            out = i[0] ** i[1]
        elif op == "Neg":
            out = -i[0]
        elif op == "Exp":
            out = np.exp(i[0])
        elif op == "Log":
            out = np.log(i[0])
        elif op == "Tanh":
            out = np.tanh(i[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Erf":
            out = _erf(i[0]).astype(i[0].dtype)
        elif op == "Sqrt":
            out = np.sqrt(i[0])
        elif op == "Reciprocal":
            out = 1.0 / i[0]
        elif op == "Identity":
            out = i[0]
        elif op == "Cast":
            out = i[0].astype(P.ONNX_TO_NP[a["to"]])
        elif op == "Transpose":
            out = np.transpose(i[0], a["perm"])
        elif op == "Reshape":
            out = i[0].reshape([int(d) for d in i[1]])
        elif op == "Expand":
            out = np.broadcast_to(i[0], [int(d) for d in i[1]])
        elif op == "Where":
            out = np.where(i[0], i[1], i[2])
        elif op == "Greater":
            out = i[0] > i[1]
        elif op == "Less":
            out = i[0] < i[1]
        elif op == "GreaterOrEqual":
            out = i[0] >= i[1]
        elif op == "LessOrEqual":
            out = i[0] <= i[1]
        elif op == "Equal":
            out = i[0] == i[1]
        elif op == "And":
            out = np.logical_and(i[0], i[1])
        elif op == "ReduceSum":
            out = np.sum(i[0], axis=tuple(int(d) for d in i[1]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "ReduceMax":
            out = np.max(i[0], axis=tuple(a["axes"]),
                         keepdims=bool(a.get("keepdims", 1)))
        elif op == "Concat":
            out = np.concatenate(i, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (np.asarray(v, np.int64)
                                         for v in i[1:5])
            sl = [slice(None)] * i[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[ax] = slice(int(s), int(e), int(st))
            out = i[0][tuple(sl)]
        elif op in ("MaxPool", "AveragePool"):
            import jax.lax as lax
            ks = a["kernel_shape"]
            pads = a.get("pads", [0] * (2 * len(ks)))
            n = len(ks)
            window = (1, 1) + tuple(ks)
            # ONNX default stride is 1, not kernel_shape
            strides = (1, 1) + tuple(a.get("strides", [1] * n))
            dil = (1, 1) + tuple(a.get("dilations", [1] * n))
            padcfg = [(0, 0), (0, 0)] + list(zip(pads[:n], pads[n:]))
            x = i[0].astype(np.float32)
            if op == "MaxPool":
                out = np.asarray(lax.reduce_window(
                    x, -np.inf, lax.max, window, strides, padcfg,
                    window_dilation=dil))
            else:
                s = np.asarray(lax.reduce_window(
                    x, 0.0, lax.add, window, strides, padcfg))
                out = s / np.prod(ks)
        elif op == "Conv":
            import jax.lax as lax
            pads = a["pads"]
            n = len(pads) // 2
            out = np.asarray(lax.conv_general_dilated(
                i[0].astype(np.float32), i[1].astype(np.float32),
                window_strides=a["strides"],
                padding=list(zip(pads[:n], pads[n:])),
                rhs_dilation=a["dilations"],
                feature_group_count=a.get("group", 1)))
        else:
            raise AssertionError(f"numpy executor: unhandled op {op}")
        env[node["outputs"][0]] = np.asarray(out)

    return [env[o["name"]] for o in g["outputs"]]


def _check_export(layer, specs, feeds, rtol=2e-5, atol=2e-5,
                  out_dir="."):
    # export under the test's tmp_path, never the repo root (.gitignore
    # guards _tmp_* as a second line of defense against strays)
    path = export(layer, str(out_dir) + "/_tmp_onnx_model",
                  input_spec=specs)
    with open(path, "rb") as f:
        data = f.read()
    m = P.parse_model(data)
    assert m["opset"] == 13
    assert m["graph"]["nodes"], "graph has no nodes"
    got = _run_onnx(data, feeds)
    want = layer(*[paddle.to_tensor(v) for v in feeds.values()])
    wants = want if isinstance(want, (list, tuple)) else [want]
    for gv, wv in zip(got, wants):
        np.testing.assert_allclose(gv, wv.numpy(), rtol=rtol, atol=atol)
    return m


class TestOnnxExport:
    def test_mlp_gelu(self, tmp_path):
        paddle.seed(0)
        layer = nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                              nn.Linear(32, 4))
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        m = _check_export(layer, [InputSpec([8, 16], "float32", "x")],
                          {"x": x}, out_dir=tmp_path)
        ops = {n["op_type"] for n in m["graph"]["nodes"]}
        assert "MatMul" in ops
        # weights became initializers, input stayed a graph input
        assert len(m["graph"]["inputs"]) == 1
        assert m["graph"]["inputs"][0]["name"] == "x"
        assert len(m["graph"]["initializers"]) >= 4

    def test_layernorm_softmax(self, tmp_path):
        paddle.seed(1)
        layer = nn.Sequential(nn.Linear(10, 10), nn.LayerNorm(10),
                              nn.Softmax())
        x = np.random.RandomState(1).randn(4, 10).astype(np.float32)
        _check_export(layer, [InputSpec([4, 10], "float32", "x")], {"x": x},
                      out_dir=tmp_path)

    def test_conv_relu(self, tmp_path):
        paddle.seed(2)
        layer = nn.Sequential(nn.Conv2D(3, 6, 3, padding=1), nn.ReLU())
        x = np.random.RandomState(2).randn(2, 3, 8, 8).astype(np.float32)
        m = _check_export(layer, [InputSpec([2, 3, 8, 8], "float32", "img")],
                          {"img": x}, rtol=1e-4, atol=1e-4,
                          out_dir=tmp_path)
        conv = [n for n in m["graph"]["nodes"] if n["op_type"] == "Conv"]
        assert conv and conv[0]["attrs"]["pads"] == [1, 1, 1, 1]

    def test_cnn_with_pooling(self, tmp_path):
        paddle.seed(3)
        layer = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1), nn.ReLU(),
                              nn.MaxPool2D(2), nn.AvgPool2D(2))
        x = np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32)
        m = _check_export(layer, [InputSpec([2, 3, 8, 8], "float32", "img")],
                          {"img": x}, rtol=1e-4, atol=1e-4,
                          out_dir=tmp_path)
        ops = [n["op_type"] for n in m["graph"]["nodes"]]
        assert "MaxPool" in ops and "AveragePool" in ops

    def test_unmapped_primitive_raises_with_guidance(self, tmp_path):
        class Sorter(nn.Layer):
            def forward(self, x):
                return paddle.sort(x, axis=-1)

        with pytest.raises(OnnxExportError, match="jit.save"):
            export(nn.Sequential(Sorter()), str(tmp_path / "_tmp_onnx_bad"),
                   input_spec=[InputSpec([4, 8], "float32")])

    def test_varint_negative_roundtrip(self):
        # negative attr ints (e.g. axis=-1) must survive the wire format
        b = P.attribute("axis", -1)
        name, val = P.parse_attribute(b)
        assert (name, val) == ("axis", -1)


class TestOnnxZoo:
    def test_shufflenet_exports(self, tmp_path):
        import paddle_tpu.vision.models as M
        m = M.shufflenet_v2_x0_25()
        m.eval()
        p = export(m, str(tmp_path / "sn"),
                   input_spec=[InputSpec([1, 3, 64, 64], "float32")])
        g = P.parse_model(open(p, "rb").read())["graph"]
        ops = {n["op_type"] for n in g["nodes"]}
        assert {"Conv", "Concat", "Slice", "Transpose"} <= ops

    @pytest.mark.slow
    def test_zoo_families_export(self, tmp_path):
        """One representative per CNN family exports and parses
        (LeNet/AlexNet/VGG/SqueezeNet/MobileNetV2/ResNet/DenseNet were
        all verified by hand; CI keeps the three cheapest)."""
        import paddle_tpu.vision.models as M
        for name, mk, shape in (
                ("lenet", lambda: M.LeNet(), [1, 1, 28, 28]),
                ("squeezenet", lambda: M.squeezenet1_1(), [1, 3, 64, 64]),
                ("resnet18", lambda: M.resnet18(), [1, 3, 64, 64])):
            m = mk()
            m.eval()
            p = export(m, str(tmp_path / name), input_spec=[
                InputSpec(shape, "float32")])
            parsed = P.parse_model(open(p, "rb").read())
            assert parsed["graph"]["nodes"], name
