"""Independent-oracle nn.functional checks vs torch-CPU.

Convolution/pooling/interpolation/loss semantics are where frameworks
classically diverge (padding conventions, align_corners, ceil_mode,
reduction defaults) — each case here pins ours to torch's output on the
same inputs. Parity target: the phi kernels the reference dispatches
to, whose contracts match torch for this op set.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _x(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def _close(got, want, rtol=2e-5, atol=2e-5):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=rtol, atol=atol)


class TestConvPool:
    @pytest.mark.parametrize("stride,pad,dil,groups", [
        (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 2)])
    def test_conv2d(self, stride, pad, dil, groups):
        x = _x((2, 4, 9, 9))
        w = _x((6, 4 // groups, 3, 3), 1)
        b = _x((6,), 2)
        got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                       paddle.to_tensor(b), stride=stride, padding=pad,
                       dilation=dil, groups=groups).numpy()
        want = tF.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                         torch.from_numpy(b), stride=stride, padding=pad,
                         dilation=dil, groups=groups).numpy()
        _close(got, want, rtol=1e-4, atol=1e-4)

    def test_conv1d_conv3d(self):
        x1, w1 = _x((2, 3, 11)), _x((5, 3, 3), 1)
        _close(F.conv1d(paddle.to_tensor(x1), paddle.to_tensor(w1),
                        padding=1).numpy(),
               tF.conv1d(torch.from_numpy(x1), torch.from_numpy(w1),
                         padding=1).numpy(), rtol=1e-4, atol=1e-4)
        x3, w3 = _x((1, 2, 5, 6, 7)), _x((4, 2, 2, 2, 2), 1)
        _close(F.conv3d(paddle.to_tensor(x3), paddle.to_tensor(w3),
                        stride=2).numpy(),
               tF.conv3d(torch.from_numpy(x3), torch.from_numpy(w3),
                         stride=2).numpy(), rtol=1e-4, atol=1e-4)

    def test_pooling_semantics(self):
        x = _x((2, 3, 7, 7))
        _close(F.max_pool2d(paddle.to_tensor(x), 3, stride=2).numpy(),
               tF.max_pool2d(torch.from_numpy(x), 3, stride=2).numpy())
        # exclusive-vs-inclusive padding counting is the classic trap:
        # paddle's default exclusive=True == torch count_include_pad=False
        got = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1)
        want = tF.avg_pool2d(torch.from_numpy(x), 3, stride=2, padding=1,
                             count_include_pad=False)
        _close(got.numpy(), want.numpy())
        _close(F.adaptive_avg_pool2d(paddle.to_tensor(x), 3).numpy(),
               tF.adaptive_avg_pool2d(torch.from_numpy(x), 3).numpy())

    def test_unfold(self):
        x = _x((2, 3, 8, 8))
        got = F.unfold(paddle.to_tensor(x), 3, strides=2,
                       paddings=1).numpy()
        want = tF.unfold(torch.from_numpy(x), 3, stride=2,
                         padding=1).numpy()
        _close(got, want)


class TestInterpolate:
    @pytest.mark.parametrize("mode,align", [
        ("nearest", None), ("bilinear", False), ("bilinear", True),
        ("bicubic", False), ("bicubic", True)])
    def test_upsample_2x(self, mode, align):
        x = _x((1, 2, 5, 5))
        kw = {} if align is None else {"align_corners": align}
        got = F.interpolate(paddle.to_tensor(x), scale_factor=2.0,
                            mode=mode, **kw).numpy()
        want = tF.interpolate(torch.from_numpy(x), scale_factor=2.0,
                              mode=mode, **kw).numpy()
        _close(got, want, rtol=1e-4, atol=1e-4)

    def test_bicubic_align_corners_size_one(self):
        x = _x((1, 2, 5, 5))
        got = F.interpolate(paddle.to_tensor(x), size=[1, 1],
                            mode="bicubic", align_corners=True).numpy()
        want = tF.interpolate(torch.from_numpy(x), size=[1, 1],
                              mode="bicubic", align_corners=True).numpy()
        _close(got, want, rtol=1e-5, atol=1e-5)

    def test_grid_sample(self):
        x = _x((1, 2, 6, 6))
        g = np.random.RandomState(1).uniform(
            -1, 1, (1, 4, 4, 2)).astype(np.float32)
        for align in (False, True):
            got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g),
                                align_corners=align).numpy()
            want = tF.grid_sample(torch.from_numpy(x),
                                  torch.from_numpy(g),
                                  align_corners=align).numpy()
            _close(got, want, rtol=1e-4, atol=1e-4)


class TestLosses:
    def test_nll_bce_kldiv(self):
        logits = _x((6, 5))
        logp = tF.log_softmax(torch.from_numpy(logits), -1).numpy()
        tgt = np.random.RandomState(2).randint(0, 5, 6).astype(np.int64)
        _close(F.nll_loss(paddle.to_tensor(logp),
                          paddle.to_tensor(tgt)).numpy(),
               tF.nll_loss(torch.from_numpy(logp),
                           torch.from_numpy(tgt)).numpy())
        p = 1 / (1 + np.exp(-_x((4, 3), 3)))
        y = (np.random.RandomState(4).rand(4, 3) > 0.5).astype(np.float32)
        _close(F.binary_cross_entropy(paddle.to_tensor(p),
                                      paddle.to_tensor(y)).numpy(),
               tF.binary_cross_entropy(torch.from_numpy(p),
                                       torch.from_numpy(y)).numpy(),
               rtol=1e-4)
        q = tF.log_softmax(torch.from_numpy(_x((4, 7), 5)), -1)
        r = tF.softmax(torch.from_numpy(_x((4, 7), 6)), -1)
        _close(F.kl_div(paddle.to_tensor(q.numpy()),
                        paddle.to_tensor(r.numpy()),
                        reduction="mean").numpy(),
               tF.kl_div(q, r, reduction="mean").numpy(), rtol=1e-4)

    def test_smooth_l1_matches_huber_delta(self):
        a, b = _x((5, 4), 7), _x((5, 4), 8)
        got = F.smooth_l1_loss(paddle.to_tensor(a), paddle.to_tensor(b),
                               delta=1.0).numpy()
        want = tF.smooth_l1_loss(torch.from_numpy(a),
                                 torch.from_numpy(b)).numpy()
        _close(got, want, rtol=1e-5)

    def test_ctc_loss(self):
        T, B, C, S = 12, 2, 6, 4
        logits = _x((T, B, C), 9)
        logp = tF.log_softmax(torch.from_numpy(logits), -1)
        tgt = np.random.RandomState(10).randint(1, C, (B, S)).astype(
            np.int32)
        ilen = np.array([T, T - 2], np.int64)
        tlen = np.array([S, S - 1], np.int64)
        want = tF.ctc_loss(logp, torch.from_numpy(tgt.astype(np.int64)),
                           torch.from_numpy(ilen), torch.from_numpy(tlen),
                           blank=0, reduction="mean",
                           zero_infinity=False).numpy()
        got = F.ctc_loss(paddle.to_tensor(logp.numpy()),
                         paddle.to_tensor(tgt),
                         paddle.to_tensor(ilen.astype(np.int64)),
                         paddle.to_tensor(tlen.astype(np.int64)),
                         blank=0, reduction="mean").numpy()
        _close(got, want, rtol=1e-4, atol=1e-4)


class TestActivations:
    @pytest.mark.parametrize("name,kw,tname", [
        ("gelu", {}, "gelu"), ("silu", {}, "silu"), ("mish", {}, "mish"),
        ("hardswish", {}, "hardswish"), ("softplus", {}, "softplus"),
        ("elu", {}, "elu"), ("celu", {}, "celu"),
        ("log_softmax", {"axis": -1}, "log_softmax")])
    def test_matches(self, name, kw, tname):
        x = _x((4, 9), 11)
        got = getattr(F, name)(paddle.to_tensor(x), **kw).numpy()
        tkw = {"dim": -1} if name == "log_softmax" else {}
        want = getattr(tF, tname)(torch.from_numpy(x), **tkw).numpy()
        _close(got, want, rtol=1e-5, atol=1e-5)

    def test_gelu_tanh_approx(self):
        x = _x((4, 9), 12)
        got = F.gelu(paddle.to_tensor(x), approximate=True).numpy()
        want = tF.gelu(torch.from_numpy(x), approximate="tanh").numpy()
        _close(got, want, rtol=1e-5, atol=1e-5)

    def test_glu_pixel_shuffle(self):
        x = _x((4, 8), 13)
        _close(F.glu(paddle.to_tensor(x), axis=-1).numpy(),
               tF.glu(torch.from_numpy(x), dim=-1).numpy())
        y = _x((1, 8, 3, 3), 14)
        _close(F.pixel_shuffle(paddle.to_tensor(y), 2).numpy(),
               tF.pixel_shuffle(torch.from_numpy(y), 2).numpy())


def _copy_rnn_weights(ours, theirs):
    """Copy torch layer-0 RNN weights into ours by suffix match."""
    tsd = dict(theirs.named_parameters())
    mapped = 0
    for k, p in dict(ours.named_parameters()).items():
        for suffix, t_name in (("weight_ih", "weight_ih_l0"),
                               ("weight_hh", "weight_hh_l0"),
                               ("bias_ih", "bias_ih_l0"),
                               ("bias_hh", "bias_hh_l0")):
            if k.endswith(suffix):
                p.set_value(tsd[t_name].detach().numpy())
                mapped += 1
    assert mapped == 4, mapped


class TestRNNFamilyMatchesTorch:
    """Gate order and bias conventions are the classic RNN divergence:
    paddle and torch both use i,f,g,o (LSTM) and r,z,n (GRU with
    separate bias_hh inside the candidate gate). Weights are copied
    from torch into ours and outputs compared step-exactly."""

    def test_lstm_forward_matches(self):
        import paddle_tpu.nn as nn
        T, B, I, H = 5, 3, 4, 6
        ours = nn.LSTM(I, H)
        theirs = torch.nn.LSTM(I, H, batch_first=True)
        _copy_rnn_weights(ours, theirs)
        x = _x((B, T, I), 21)
        got, (h, c) = ours(paddle.to_tensor(x))
        want, (th, tc) = theirs(torch.from_numpy(x))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)
        _close(h.numpy(), th.detach().numpy(), rtol=1e-4, atol=1e-5)
        _close(c.numpy(), tc.detach().numpy(), rtol=1e-4, atol=1e-5)
        # list-form [h0, c0] initial state == tuple form (reference API)
        o1, _ = ours(paddle.to_tensor(x), (h, c))
        o2, _ = ours(paddle.to_tensor(x), [h, c])
        _close(o1.numpy(), o2.numpy())

    def test_gru_forward_matches(self):
        import paddle_tpu.nn as nn
        T, B, I, H = 4, 2, 3, 5
        ours = nn.GRU(I, H)
        theirs = torch.nn.GRU(I, H, batch_first=True)
        _copy_rnn_weights(ours, theirs)
        x = _x((B, T, I), 22)
        got, h = ours(paddle.to_tensor(x))
        want, th = theirs(torch.from_numpy(x))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)
        _close(h.numpy(), th.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_simple_rnn_matches(self):
        import paddle_tpu.nn as nn
        T, B, I, H = 4, 2, 3, 5
        ours = nn.SimpleRNN(I, H)
        theirs = torch.nn.RNN(I, H, batch_first=True, nonlinearity="tanh")
        _copy_rnn_weights(ours, theirs)
        x = _x((B, T, I), 23)
        got, h = ours(paddle.to_tensor(x))
        want, th = theirs(torch.from_numpy(x))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_sequence_length_masks_padding(self):
        import paddle_tpu.nn as nn
        T, B, I, H = 6, 2, 3, 4
        gru = nn.GRU(I, H)
        x = _x((B, T, I), 24)
        sl = np.array([6, 3], np.int64)
        out, h = gru(paddle.to_tensor(x),
                     sequence_length=paddle.to_tensor(sl))
        # padded timesteps of row 1 are zeroed; final h equals step-3 h
        assert np.abs(out.numpy()[1, 3:]).max() == 0.0
        out_cut, h_cut = gru(paddle.to_tensor(x[1:2, :3]))
        _close(h.numpy()[0, 1], h_cut.numpy()[0, 0], rtol=1e-5, atol=1e-6)


class TestTransformerMatchesTorch:
    """MHA + encoder layer vs torch with copied weights. torch packs
    q/k/v rows into in_proj_weight [3E, E] (out, in layout); paddle uses
    separate [E, E] (in, out) projections — rows split + transpose."""

    def _copy_mha(self, ours, theirs, E):
        ipw = theirs.in_proj_weight.detach().numpy()    # [3E, E]
        ipb = theirs.in_proj_bias.detach().numpy()      # [3E]
        ps = dict(ours.named_parameters())
        for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
            ps[f"{name}.weight"].set_value(ipw[i * E:(i + 1) * E].T.copy())
            ps[f"{name}.bias"].set_value(ipb[i * E:(i + 1) * E].copy())
        ps["out_proj.weight"].set_value(
            theirs.out_proj.weight.detach().numpy().T.copy())
        ps["out_proj.bias"].set_value(
            theirs.out_proj.bias.detach().numpy().copy())

    def test_multi_head_attention(self):
        import paddle_tpu.nn as nn
        B, S, E, H = 2, 5, 8, 2
        ours = nn.MultiHeadAttention(E, H)
        theirs = torch.nn.MultiheadAttention(E, H, batch_first=True)
        self._copy_mha(ours, theirs, E)
        x = _x((B, S, E), 31)
        got = ours(paddle.to_tensor(x))
        want, _ = theirs(torch.from_numpy(x), torch.from_numpy(x),
                         torch.from_numpy(x))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_mha_with_causal_mask(self):
        import paddle_tpu.nn as nn
        B, S, E, H = 2, 4, 8, 2
        ours = nn.MultiHeadAttention(E, H)
        theirs = torch.nn.MultiheadAttention(E, H, batch_first=True)
        self._copy_mha(ours, theirs, E)
        x = _x((B, S, E), 32)
        causal_bool = np.triu(np.ones((S, S), bool), 1)   # True = masked
        # paddle mask convention: additive float mask (0 keep, -inf drop)
        add_mask = np.where(causal_bool, -1e9, 0.0).astype(np.float32)
        got = ours(paddle.to_tensor(x),
                   attn_mask=paddle.to_tensor(add_mask))
        want, _ = theirs(torch.from_numpy(x), torch.from_numpy(x),
                         torch.from_numpy(x),
                         attn_mask=torch.from_numpy(causal_bool))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_encoder_layer(self):
        import paddle_tpu.nn as nn
        B, S, E, H, FF = 2, 5, 8, 2, 16
        ours = nn.TransformerEncoderLayer(E, H, FF, dropout=0.0,
                                          activation="relu")
        theirs = torch.nn.TransformerEncoderLayer(
            E, H, FF, dropout=0.0, activation="relu", batch_first=True)
        ours.eval()
        theirs.eval()
        self._copy_mha(ours.self_attn, theirs.self_attn, E)
        ps = dict(ours.named_parameters())
        for o_name, t_param in (
                ("linear1.weight", theirs.linear1.weight.T),
                ("linear1.bias", theirs.linear1.bias),
                ("linear2.weight", theirs.linear2.weight.T),
                ("linear2.bias", theirs.linear2.bias),
                ("norm1.weight", theirs.norm1.weight),
                ("norm1.bias", theirs.norm1.bias),
                ("norm2.weight", theirs.norm2.weight),
                ("norm2.bias", theirs.norm2.bias)):
            ps[o_name].set_value(t_param.detach().numpy().copy())
        x = _x((B, S, E), 33)
        got = ours(paddle.to_tensor(x))
        want = theirs(torch.from_numpy(x))
        _close(got.numpy(), want.detach().numpy(), rtol=1e-4, atol=1e-5)
