"""Optimizer trajectories vs torch.optim — an oracle nobody here wrote.

Step-exact comparison: identical initial weights and data, each
framework computes its OWN gradients (so the test also pins the
Linear+activation fwd/bwd), then N optimizer steps; parameters must
track torch's to float32 tolerance at every step.

Covered where the reference's semantics coincide with torch's (the
phi kernels implement the same update rules): SGD, Momentum (paddle
Momentum == torch SGD(momentum, dampening=0)), Adam (bias-corrected),
AdamW (decoupled decay), Adagrad. RMSProp is deliberately absent —
the reference puts eps INSIDE the sqrt (rmsprop kernel), torch outside;
its numerics are pinned by tests/test_optimizer.py instead.
Reference role: the dist_optimizer/optimizer unittests' golden-value
checks.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _data(seed=0, n=16, din=6, dout=3):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, din).astype(np.float32),
            rng.randn(n, dout).astype(np.float32))


def _paddle_net(seed=7):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 3))


def _torch_net_from(pnet):
    tnet = torch.nn.Sequential(torch.nn.Linear(6, 8), torch.nn.Tanh(),
                               torch.nn.Linear(8, 3))
    with torch.no_grad():
        for t, p in zip((tnet[0], tnet[2]), (pnet[0], pnet[2])):
            # paddle Linear weight is [in, out]; torch is [out, in]
            # (.copy() — from_numpy on the transposed view warns)
            t.weight.copy_(torch.from_numpy(p.weight.numpy().T.copy()))
            t.bias.copy_(torch.from_numpy(p.bias.numpy()))
    return tnet


def _run_paddle(pnet, opt, X, Y, steps):
    traj = []
    loss_fn = nn.MSELoss()
    for _ in range(steps):
        loss = loss_fn(pnet(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        traj.append(np.concatenate(
            [p.numpy().ravel() for p in pnet.parameters()]))
    return traj


def _run_torch(tnet, topt, X, Y, steps, post_backward=None):
    traj = []
    loss_fn = torch.nn.MSELoss()
    for _ in range(steps):
        topt.zero_grad()
        loss = loss_fn(tnet(torch.from_numpy(X)), torch.from_numpy(Y))
        loss.backward()
        if post_backward is not None:   # e.g. grad clipping
            post_backward(tnet)
        topt.step()
        # flatten in paddle's parameter order (weightT, bias per layer)
        flat = []
        for t in (tnet[0], tnet[2]):
            flat.append(t.weight.detach().numpy().T.ravel())
            flat.append(t.bias.detach().numpy().ravel())
        traj.append(np.concatenate(flat))
    return traj


CASES = [
    ("sgd",
     lambda ps: paddle.optimizer.SGD(learning_rate=0.05, parameters=ps),
     lambda ts: torch.optim.SGD(ts, lr=0.05)),
    ("momentum",
     lambda ps: paddle.optimizer.Momentum(learning_rate=0.05,
                                          momentum=0.9, parameters=ps),
     lambda ts: torch.optim.SGD(ts, lr=0.05, momentum=0.9, dampening=0)),
    ("adam",
     lambda ps: paddle.optimizer.Adam(learning_rate=0.01, parameters=ps),
     lambda ts: torch.optim.Adam(ts, lr=0.01)),
    ("adamw",
     lambda ps: paddle.optimizer.AdamW(learning_rate=0.01,
                                       weight_decay=0.05, parameters=ps),
     lambda ts: torch.optim.AdamW(ts, lr=0.01, weight_decay=0.05)),
    ("adagrad",
     lambda ps: paddle.optimizer.Adagrad(learning_rate=0.05,
                                         parameters=ps),
     lambda ts: torch.optim.Adagrad(ts, lr=0.05, eps=1e-6)),
]


@pytest.mark.parametrize("name,mk_p,mk_t", CASES,
                         ids=[c[0] for c in CASES])
def test_trajectory_matches_torch(name, mk_p, mk_t):
    X, Y = _data()
    pnet = _paddle_net()
    tnet = _torch_net_from(pnet)
    steps = 10
    pt = _run_paddle(pnet, mk_p(pnet.parameters()), X, Y, steps)
    tt = _run_torch(tnet, mk_t(tnet.parameters()), X, Y, steps)
    for s, (a, b) in enumerate(zip(pt, tt)):
        np.testing.assert_allclose(
            a, b, rtol=2e-4, atol=2e-5,
            err_msg=f"{name}: parameters diverged at step {s}")


SCHED = [
    ("step", lambda: paddle.optimizer.lr.StepDecay(0.1, step_size=5,
                                                   gamma=0.5),
     lambda o: torch.optim.lr_scheduler.StepLR(o, step_size=5,
                                               gamma=0.5)),
    ("multistep", lambda: paddle.optimizer.lr.MultiStepDecay(
        0.1, milestones=[3, 7, 15], gamma=0.3),
     lambda o: torch.optim.lr_scheduler.MultiStepLR(
        o, milestones=[3, 7, 15], gamma=0.3)),
    ("exponential", lambda: paddle.optimizer.lr.ExponentialDecay(
        0.1, gamma=0.9),
     lambda o: torch.optim.lr_scheduler.ExponentialLR(o, gamma=0.9)),
    ("cosine", lambda: paddle.optimizer.lr.CosineAnnealingDecay(
        0.1, T_max=10, eta_min=0.01),
     lambda o: torch.optim.lr_scheduler.CosineAnnealingLR(
        o, T_max=10, eta_min=0.01)),
]


@pytest.mark.parametrize("name,mk_p,mk_t", SCHED,
                         ids=[s[0] for s in SCHED])
def test_lr_schedule_matches_torch(name, mk_p, mk_t):
    """Scheduler LR sequences over 20 epochs vs torch's (same rule
    families; the reference's lr.py semantics coincide here)."""
    sched = mk_p()
    dummy = torch.nn.Parameter(torch.zeros(1))
    topt = torch.optim.SGD([dummy], lr=0.1)
    tsched = mk_t(topt)
    ours, theirs = [], []
    for _ in range(20):
        ours.append(float(sched()))
        theirs.append(topt.param_groups[0]["lr"])
        sched.step()
        topt.step()        # silence the torch "step order" warning
        tsched.step()
    np.testing.assert_allclose(ours, theirs, rtol=1e-6,
                               err_msg=name)


def test_global_norm_clip_matches_torch():
    """ClipGradByGlobalNorm trajectory vs torch clip_grad_norm_ + SGD
    (same rule: scale all grads by c/max(c, ||g||_global))."""
    X, Y = _data(seed=4)
    pnet = _paddle_net()
    tnet = _torch_net_from(pnet)
    clip = paddle.nn.ClipGradByGlobalNorm(clip_norm=0.1)
    popt = paddle.optimizer.SGD(learning_rate=0.5,
                                parameters=pnet.parameters(),
                                grad_clip=clip)
    topt = torch.optim.SGD(tnet.parameters(), lr=0.5)
    pt = _run_paddle(pnet, popt, X, Y, 8)
    traj = _run_torch(
        tnet, topt, X, Y, 8,
        post_backward=lambda net: torch.nn.utils.clip_grad_norm_(
            net.parameters(), 0.1))
    for s, (a, b) in enumerate(zip(pt, traj)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5,
                                   err_msg=f"clip diverged at step {s}")
