"""PP-YOLOE detector tests — BASELINE.json config 5 (serving path).

Checks: forward shapes across levels, DFL decode geometry (uniform logits
=> centered boxes of expectation reg_max/2 * stride), gradient flow,
postprocess NMS output structure, and the serving export (jit.save ->
inference predictor parity), the AnalysisPredictor-role e2e.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.models import PPYOLOE, ppyoloe_s


def _tiny(num_classes=4):
    # minimal real PPYOLOE (width/depth mults below s) for test speed
    return PPYOLOE(num_classes=num_classes, width_mult=0.25,
                   depth_mult=0.33)


@pytest.mark.slow
def test_forward_shapes():
    paddle.seed(31)
    m = _tiny()
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 3, 64, 64).astype("float32"))
    scores, boxes = m(x)
    # strides 8/16/32 over 64x64 input -> 8*8 + 4*4 + 2*2 = 84 anchors
    assert scores.shape == [2, 84, 4]
    assert boxes.shape == [2, 84, 4]
    s = scores.numpy()
    assert (s >= 0).all() and (s <= 1).all()


def test_dfl_decode_geometry():
    """Zero reg logits => uniform DFL => ltrb = reg_max/2 bins * stride."""
    paddle.seed(32)
    m = _tiny()
    m.eval()
    # force the last reg conv of every level to zero
    for conv in m.head.reg_preds:
        conv.weight.set_value(np.zeros(conv.weight.shape, np.float32))
        conv.bias.set_value(np.zeros(conv.bias.shape, np.float32))
    x = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    _, boxes = m(x)
    b = boxes.numpy()[0]
    rm = m.head.reg_max
    # first 64 anchors are stride 8: first anchor center (4, 4)
    exp = rm / 2.0 * 8.0
    np.testing.assert_allclose(b[0], [4 - exp, 4 - exp, 4 + exp, 4 + exp],
                               rtol=1e-5, atol=1e-4)


@pytest.mark.slow
def test_gradient_flow():
    paddle.seed(33)
    m = _tiny(num_classes=2)
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(1, 3, 64, 64).astype("float32"))
    scores, boxes = m(x)
    loss = paddle.mean(scores) + paddle.mean(boxes) * 1e-3
    loss.backward()
    g = m.backbone.stem[0].conv.weight._grad
    assert g is not None and float((np.asarray(g) ** 2).sum()) > 0


def test_postprocess_structure():
    paddle.seed(34)
    m = _tiny()
    m.eval()
    x = paddle.to_tensor(np.random.RandomState(2)
                         .randn(2, 3, 64, 64).astype("float32"))
    scores, boxes = m(x)
    dets = m.postprocess(scores, boxes, score_threshold=0.0,
                         iou_threshold=0.6, max_dets=10)
    assert len(dets) == 2
    for d in dets:
        k = d["boxes"].shape[0]
        assert d["scores"].shape == (k,) and d["labels"].shape == (k,)
        assert k <= 10 * m.num_classes


def test_serving_export_parity(tmp_path):
    """Config 5 shape: save the compiled program, reload through the
    inference predictor, compare against eager forward."""
    paddle.seed(35)
    m = _tiny()
    m.eval()
    x_np = np.random.RandomState(3).randn(1, 3, 64, 64).astype("float32")
    scores, boxes = m(paddle.to_tensor(x_np))

    path = os.path.join(str(tmp_path), "ppyoloe")
    paddle.jit.save(m, path,
                    input_spec=[paddle.jit.InputSpec([1, 3, 64, 64],
                                                     "float32")])

    from paddle_tpu.inference import Config, create_predictor
    cfg = Config(path + ".pdmodel")
    pred = create_predictor(cfg)
    inp = pred.get_input_handle(pred.get_input_names()[0])
    inp.copy_from_cpu(x_np)
    pred.run()
    outs = [pred.get_output_handle(n).copy_to_cpu()
            for n in pred.get_output_names()]
    got_scores, got_boxes = outs
    np.testing.assert_allclose(got_scores, scores.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got_boxes, boxes.numpy(),
                               rtol=1e-4, atol=1e-3)
