"""Pallas blockwise flash kernel (kernels/flash_block.py) + fused ring path.

Runs in interpret mode on the CPU mesh; the same code compiles on TPU.
Reference semantics: paddle/phi/kernels/gpu/flash_attn_kernel.cu (fused
attention with LSE residuals) — numerics checked against plain softmax
attention, like the reference's test_flash_attention.py does vs
scaled_dot_product_attention.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.sequence_parallel import (_fused_geometry_ok,
                                                      last_ring_dispatch)
from paddle_tpu.kernels.flash_block import (flash_attention_lse,
                                            flash_block_attention,
                                            merge_lse_blocks)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _ref(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        S = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -jnp.inf)
    lse = jax.nn.logsumexp(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v), lse


def _rand(*shape, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype("float32"))


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_forward_and_lse(causal):
    B, H, S, D = 2, 3, 256, 64
    q, k, v = (_rand(B, H, S, D, seed=i) for i in range(3))
    out, lse = flash_attention_lse(q, k, v, causal=causal, interpret=True)
    ro, rl = _ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rl), atol=2e-5)


def test_kernel_grads_including_lse_cotangent(causal=True):
    B, H, S, D = 1, 2, 256, 64
    q, k, v = (_rand(B, H, S, D, seed=i) for i in range(3))
    co = _rand(B, H, S, D, seed=7)
    cl = _rand(B, H, S, seed=8)

    def loss_kern(q, k, v):
        o, l = flash_attention_lse(q, k, v, causal=causal, interpret=True)
        return (o * co).sum() + (l * cl).sum()

    def loss_ref(q, k, v):
        o, l = _ref(q, k, v, causal)
        return (o * co).sum() + (l * cl).sum()

    gk = jax.grad(loss_kern, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_block_offsets_match_sliced_full_attention():
    """Global-position causal masking: merging per-block kernel calls with
    offsets must equal full causal attention (the ring schedule)."""
    B, H, S, D, sl = 1, 2, 512, 64, 128
    q, k, v = (_rand(B, H, S, D, seed=i) for i in range(3))
    ro, _ = _ref(q, k, v, True)
    scale = 1.0 / np.sqrt(D)
    for qi in range(S // sl):
        qs = q[:, :, qi * sl:(qi + 1) * sl]
        acc = jnp.zeros((B, H, sl, D), jnp.float32)
        lse = jnp.full((B, H, sl), -jnp.inf, jnp.float32)
        for ki in range(S // sl):
            o_i, l_i = flash_block_attention(
                qs, k[:, :, ki * sl:(ki + 1) * sl],
                v[:, :, ki * sl:(ki + 1) * sl],
                float(qi * sl), float(ki * sl), True, scale, 128, 128,
                True)
            acc, lse = merge_lse_blocks(acc, lse, o_i, l_i)
        np.testing.assert_allclose(
            np.asarray(acc), np.asarray(ro[:, :, qi * sl:(qi + 1) * sl]),
            atol=2e-5)


def test_attention_dispatch_gate_at_bench_geometry():
    """The GPT-125M bench geometry (seq 1024, head_dim 64, no dropout)
    must pass the Pallas gate; dispatch decisions must be observable."""
    from paddle_tpu.nn.functional.flash_attention import (
        _pallas_geometry_ok, last_attention_dispatch)
    assert _pallas_geometry_ok(1024, 64, 0.0)
    assert _pallas_geometry_ok(2048, 128, 0.0)
    assert not _pallas_geometry_ok(100, 64, 0.0)    # seq doesn't tile
    assert not _pallas_geometry_ok(1024, 192, 0.0)  # bad head_dim
    assert not _pallas_geometry_ok(1024, 64, 0.1)   # dropout
    # on CPU the runtime dispatch records the xla fallback with a reason
    import paddle_tpu.nn.functional as F
    q = paddle.to_tensor(np.zeros((1, 128, 2, 64), "float32"))
    F.flash_attention(q, q, q)[0]
    d = last_attention_dispatch()
    assert d["backend"] == "xla" and "TPU" in d["reason"]


def test_require_pallas_flag_raises(monkeypatch):
    import importlib

    import paddle_tpu.nn.functional as F
    fa_mod = importlib.import_module(
        "paddle_tpu.nn.functional.flash_attention")
    monkeypatch.setenv("PADDLE_TPU_REQUIRE_PALLAS", "1")
    monkeypatch.setattr(fa_mod, "_on_tpu", lambda: True)
    q = paddle.to_tensor(np.zeros((1, 100, 2, 64), "float32"))
    with pytest.raises(RuntimeError, match="REQUIRE_PALLAS"):
        F.flash_attention(q, q, q)


def test_geometry_gate():
    assert _fused_geometry_ok(128, 64)
    assert _fused_geometry_ok(512, 128)
    assert _fused_geometry_ok(256, 256)
    assert not _fused_geometry_ok(100, 64)   # sl doesn't tile
    assert not _fused_geometry_ok(128, 192)  # head_dim >128, not %128


@pytest.mark.parametrize("causal", [False, True])
def test_fused_ring_matches_plain(causal):
    """sp=4 ring at a 128-tiling geometry must take the Pallas path and
    match single-device attention (this is the dispatch regression test:
    it FAILS if the fused kernel stops being selected)."""
    dist.init_mesh({"sp": 4})
    B, S, H, D = 1, 512, 2, 64
    rng = np.random.RandomState(3)
    q, k, v = (rng.randn(B, S, H, D).astype("float32") for _ in range(3))
    out = dist.ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                              paddle.to_tensor(v), causal=causal)
    disp = last_ring_dispatch()
    assert disp["path"] == "pallas", disp
    # reference in (B,S,H,D) layout
    qh, kh, vh = (jnp.swapaxes(jnp.asarray(a), 1, 2) for a in (q, k, v))
    ro, _ = _ref(qh, kh, vh, causal)
    np.testing.assert_allclose(out.numpy(),
                               np.asarray(jnp.swapaxes(ro, 1, 2)),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_ulysses_matches_plain(causal):
    """Ulysses with the full-sequence geometry tiling 128 runs its local
    attention in the fused kernel; outputs must match plain attention."""
    dist.init_mesh({"sp": 4})
    B, S, H, D = 1, 512, 4, 64
    rng = np.random.RandomState(5)
    q, k, v = (rng.randn(B, S, H, D).astype("float32") for _ in range(3))
    out = dist.ulysses_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), causal=causal)
    qh, kh, vh = (jnp.swapaxes(jnp.asarray(a), 1, 2) for a in (q, k, v))
    ro, _ = _ref(qh, kh, vh, causal)
    np.testing.assert_allclose(out.numpy(),
                               np.asarray(jnp.swapaxes(ro, 1, 2)),
                               rtol=2e-4, atol=2e-5)


def test_fused_ring_backward_matches_plain():
    dist.init_mesh({"sp": 4})
    B, S, H, D = 1, 512, 2, 64
    rng = np.random.RandomState(4)
    qn, kn, vn = (rng.randn(B, S, H, D).astype("float32")
                  for _ in range(3))
    q = paddle.to_tensor(qn, stop_gradient=False)
    k = paddle.to_tensor(kn, stop_gradient=False)
    v = paddle.to_tensor(vn, stop_gradient=False)
    out = dist.ring_attention(q, k, v, causal=True)
    assert last_ring_dispatch()["path"] == "pallas"
    paddle.mean(out).backward()

    # reference grads via jax on the unsharded computation
    def loss(qv, kv, vv):
        o, _ = _ref(jnp.swapaxes(qv, 1, 2), jnp.swapaxes(kv, 1, 2),
                    jnp.swapaxes(vv, 1, 2), True)
        return jnp.mean(jnp.swapaxes(o, 1, 2))

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn))
    np.testing.assert_allclose(q.grad.numpy(), np.asarray(gq), atol=1e-5)
    np.testing.assert_allclose(k.grad.numpy(), np.asarray(gk), atol=1e-5)
    np.testing.assert_allclose(v.grad.numpy(), np.asarray(gv), atol=1e-5)
