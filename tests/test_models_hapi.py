"""Model zoo + hapi tests (reference style: test/book e2e smoke tests —
train a few iters, assert the loss drops; hapi test_model.py fit/eval)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.models import (GPTForCausalLM, GPTPipelineForCausalLM,
                               gpt_tiny)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def test_gpt_forward_shapes():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 16)).astype("int64"))
    out = m(ids)
    assert out.shape == [2, 16, 256]


def test_gpt_trains_single_device():
    paddle.seed(0)
    cfg = gpt_tiny()
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    step = paddle.jit.TrainStep(m, GPTForCausalLM.loss_fn, opt)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (4, 32)).astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_gpt_hybrid_mesh_training():
    dist.init_mesh({"dp": 2, "mp": 2, "sp": 2})
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    step = dist.ParallelTrainStep(m, GPTForCausalLM.loss_fn, opt,
                                  zero_stage=1)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (4, 32)).astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert "mp" in str(
        step.params["gpt.block_0.attn.qkv.weight"].sharding.spec)


def test_gpt_pipeline_variant():
    dist.init_mesh({"pp": 4, "dp": 2})
    paddle.seed(0)
    cfg = gpt_tiny()
    m = GPTPipelineForCausalLM(cfg, num_stages=4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    step = dist.ParallelTrainStep(m, GPTForCausalLM.loss_fn, opt)
    ids = paddle.to_tensor(
        np.random.randint(0, 256, (8, 32)).astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(5)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_resnet_forward_and_train():
    paddle.seed(0)
    m = paddle.vision.models.resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.randn(2, 3, 32, 32).astype("float32"))
    assert m(x).shape == [2, 10]
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=m.parameters())
    ce = nn.CrossEntropyLoss()
    step = paddle.jit.TrainStep(m, lambda o, y: ce(o, y), opt)
    y = paddle.to_tensor(np.random.randint(0, 10, (2,)).astype("int64"))
    losses = [float(step(x, y)) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_hapi_model_fit_eval_predict(tmp_path):
    from paddle_tpu.io import TensorDataset

    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    w = rng.randn(8).astype("float32")
    Y = (X @ w > 0).astype("int64")
    ds = TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])

    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.05,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    model.fit(ds, epochs=6, batch_size=16, verbose=0, shuffle=False)
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["acc"] > 0.9, logs
    outs = model.predict(ds, batch_size=16, stack_outputs=True)
    assert outs[0].shape == [64, 2]

    path = str(tmp_path / "ckpt")
    model.save(path)
    net2 = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    m2 = paddle.Model(net2)
    m2.prepare(loss=nn.CrossEntropyLoss(),
               metrics=paddle.metric.Accuracy())
    m2.load(path)
    logs2 = m2.evaluate(ds, batch_size=16, verbose=0)
    np.testing.assert_allclose(logs2["acc"], logs["acc"])


def test_metric_accuracy_topk():
    acc = paddle.metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    label = np.array([1, 2])
    acc.update(*acc.compute(pred, label))
    top1, top2 = acc.accumulate()
    assert top1 == 0.5 and top2 == 0.5


@pytest.mark.slow
def test_graft_entry_contracts():
    import __graft_entry__ as g
    import jax

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 64, 256)
    dist.set_mesh(None)
    g.dryrun_multichip(8)
