"""HTTP predictor-server tests (serving north star: model served
end-to-end; reference role: DistModel service / embedded predictor)."""
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference.serve import PredictorServer


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path_factory.mktemp("serve") / "model")
    paddle.jit.save(m, path,
                    input_spec=[paddle.jit.InputSpec([None, 8])])
    srv = PredictorServer(path + ".pdmodel", port=0).start()
    yield srv, m
    srv.stop()


def _req(srv, path, payload=None):
    url = f"http://{srv.host}:{srv.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_and_metadata(server):
    srv, _ = server
    code, body = _req(srv, "/health")
    assert code == 200 and body["status"] == "ok"
    code, meta = _req(srv, "/metadata")
    assert code == 200
    assert len(meta["inputs"]) == 1 and len(meta["outputs"]) == 1


def test_predict_matches_eager(server):
    srv, m = server
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    _, meta = _req(srv, "/metadata")
    code, body = _req(srv, "/predict", {
        "inputs": {meta["inputs"][0]: {"data": x.tolist(),
                                       "dtype": "float32"}}})
    assert code == 200, body
    out = body["outputs"][meta["outputs"][0]]
    got = np.asarray(out["data"], dtype=out["dtype"])
    want = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert out["shape"] == [3, 4]


def test_predict_error_paths(server):
    srv, _ = server
    code, body = _req(srv, "/predict", {"inputs": {"nope": [[1.0]]}})
    assert code == 400 and "unknown" in body["error"]
    code, body = _req(srv, "/predict", {"bad": 1})
    assert code == 400
    code, body = _req(srv, "/nothing")
    assert code == 404


@pytest.mark.slow
def test_serving_latency_bench_smoke():
    """The north-star serving benchmark (tools/bench_serving.py,
    BASELINE config 5) runs end-to-end at toy scale and emits a sane
    record: encoder p50 through the Predictor path + KV-cache decode."""
    import json
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # single-device serving: drop the test harness's 8-device flag
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "bench_serving.py"),
         "--smoke"], capture_output=True, text=True, timeout=420, env=env,
        cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "ernie3_serving_latency"
    assert 0 < rec["p50_ms"] <= rec["p99_ms"]
    assert rec["decode_ms_per_token"] > 0
