"""HTTP predictor-server tests (serving north star: model served
end-to-end; reference role: DistModel service / embedded predictor)."""
import json
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference.serve import PredictorServer


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path_factory.mktemp("serve") / "model")
    paddle.jit.save(m, path,
                    input_spec=[paddle.jit.InputSpec([None, 8])])
    srv = PredictorServer(path + ".pdmodel", port=0).start()
    yield srv, m
    srv.stop()


def _req(srv, path, payload=None):
    code, body, _ = _req_h(srv, path, payload)
    return code, body


def _req_h(srv, path, payload=None):
    """Like _req but also returns the response headers (Retry-After)."""
    url = f"http://{srv.host}:{srv.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_health_and_metadata(server):
    srv, _ = server
    code, body = _req(srv, "/health")
    assert code == 200 and body["status"] == "ok"
    code, meta = _req(srv, "/metadata")
    assert code == 200
    assert len(meta["inputs"]) == 1 and len(meta["outputs"]) == 1


def test_predict_matches_eager(server):
    srv, m = server
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    _, meta = _req(srv, "/metadata")
    code, body = _req(srv, "/predict", {
        "inputs": {meta["inputs"][0]: {"data": x.tolist(),
                                       "dtype": "float32"}}})
    assert code == 200, body
    out = body["outputs"][meta["outputs"][0]]
    got = np.asarray(out["data"], dtype=out["dtype"])
    want = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert out["shape"] == [3, 4]


def test_predict_error_paths(server):
    srv, _ = server
    code, body = _req(srv, "/predict", {"inputs": {"nope": [[1.0]]}})
    assert code == 400 and "unknown" in body["error"]
    code, body = _req(srv, "/predict", {"bad": 1})
    assert code == 400
    code, body = _req(srv, "/nothing")
    assert code == 404


# ---------------------------------------------------------------------------
# Retry-After contract: every 503 names its reason AND carries a
# Retry-After header + retry_after_s body field — the router tier and
# external clients back off on the server's word, never by guessing.
# ---------------------------------------------------------------------------

def _assert_retry_after(code, body, headers, reason):
    assert code == 503, body
    assert body["error"].split(":")[0] == reason, body
    assert float(body["retry_after_s"]) > 0, body
    assert int(headers["Retry-After"]) >= 1, headers


@pytest.fixture()
def saved_model_path(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path,
                    input_spec=[paddle.jit.InputSpec([None, 8])])
    return path + ".pdmodel"


def test_503_overloaded_carries_retry_after(saved_model_path):
    srv = PredictorServer(saved_model_path, port=0, max_queue=0).start()
    try:
        code, body, hdr = _req_h(srv, "/predict", {"inputs": {"x": [[1.0]]}})
        _assert_retry_after(code, body, hdr, "overloaded")
    finally:
        srv.stop()


def test_503_deadline_and_backend_carry_retry_after(saved_model_path):
    from paddle_tpu.distributed.resilience import FaultInjector
    srv = PredictorServer(saved_model_path, port=0,
                          deadline_s=0.3).start()
    try:
        _, meta = _req(srv, "/metadata")
        x = np.zeros((1, 8), "float32")
        payload = {"inputs": {meta["inputs"][0]: {"data": x.tolist(),
                                                  "dtype": "float32"}}}
        with FaultInjector({"serve_hang": 1}, wedge_s=1.0):
            code, body, hdr = _req_h(srv, "/predict", payload)
        _assert_retry_after(code, body, hdr, "deadline_exceeded")
        # the abandoned worker is still inside its 1 s wedge and holds
        # its depth slot; wait for it to clear so the next request is
        # admitted and reaches the injected backend fault
        deadline = time.monotonic() + 10
        while srv.inflight() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        with FaultInjector({"serve_backend": 1}):
            code, body, hdr = _req_h(srv, "/predict", payload)
        _assert_retry_after(code, body, hdr, "backend_unavailable")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# engine-backed server: warming 503, drain semantics, graceful stop
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_server():
    from paddle_tpu.framework import random as _rng
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=96, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=128))
    eng = ContinuousBatchingEngine(model, slots=2, max_len=96,
                                   cache_dtype="float32", tick_tokens=2,
                                   prefill_buckets=(8,))
    srv = PredictorServer(engine=eng, port=0).start()
    yield srv
    srv.stop()
    eng.stop()


def test_503_warming_carries_retry_after(engine_server):
    srv = engine_server
    srv._warm_state = "warming"     # white-box: deterministic warming
    try:
        code, body, hdr = _req_h(srv, "/generate",
                                 {"input_ids": [1], "max_new_tokens": 2})
        _assert_retry_after(code, body, hdr, "warming_up")
        code, body, hdr = _req_h(srv, "/healthz")
        assert code == 503 and body["status"] == "warming"
        assert int(hdr["Retry-After"]) >= 1
    finally:
        srv._warm_state = "ready"


def test_stop_drain_completes_inflight_and_sheds_new(engine_server):
    """The drain regression (ISSUE 7 satellite): an in-flight
    /generate completes across stop(drain_s=...) while new admissions
    get a 503 "draining" — the serve.py:443 fast-stop abandonment is
    now opt-in (drain_s=0), not the only behavior."""
    import threading
    from paddle_tpu.framework import random as _rng
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=96, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=128))
    eng = ContinuousBatchingEngine(model, slots=2, max_len=96,
                                   cache_dtype="float32", tick_tokens=2,
                                   prefill_buckets=(8,))
    srv = PredictorServer(engine=eng, port=0).start()
    results = {}

    def long_request():
        # max_new=60 at tick_tokens=2 is ~30 ticks (plus the first
        # request's compile): reliably in flight when stop() begins
        results["long"] = _req_h(srv, "/generate",
                                 {"input_ids": [3, 1, 4],
                                  "max_new_tokens": 60})

    t = threading.Thread(target=long_request)
    t.start()
    deadline = time.monotonic() + 30
    while srv._resp_inflight < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert srv._resp_inflight >= 1, "long request never became in-flight"

    stopper = threading.Thread(target=srv.stop, kwargs={"drain_s": 60.0})
    stopper.start()
    while not srv._draining and stopper.is_alive():
        time.sleep(0.005)
    # new admission during the drain: clean 503 "draining" + Retry-After
    code, body, hdr = _req_h(srv, "/generate",
                             {"input_ids": [1], "max_new_tokens": 2})
    _assert_retry_after(code, body, hdr, "draining")
    # /healthz tells the router why this replica left the rotation
    code, body, _ = _req_h(srv, "/healthz")
    assert code == 503 and body["status"] == "draining"

    t.join(timeout=90)
    stopper.join(timeout=90)
    assert not t.is_alive() and not stopper.is_alive()
    code, body, _ = results["long"]
    assert code == 200, body
    assert len(body["tokens"]) == 3 + 60     # completed, not abandoned
    eng.stop()


def test_fast_stop_default_unchanged(engine_server):
    """drain_s=0 (the default) must keep today's behavior: stop()
    returns promptly even with nothing special done about in-flight
    work (the wedged-backend shutdown guarantee)."""
    srv = PredictorServer(engine=engine_server.engine, port=0).start()
    t0 = time.monotonic()
    srv.stop()
    assert time.monotonic() - t0 < 10.0


# ---------------------------------------------------------------------------
# incremental /generate + /cancel + /admin/inject (ISSUE 15)
# ---------------------------------------------------------------------------

def test_generate_stream_ndjson_matches_single_shot(engine_server):
    """"stream": true turns /generate into NDJSON read-until-close:
    {"t": [...]} per emitted block then one terminal {"done": body} —
    the concatenated token events ARE the generated suffix, and the
    terminal body is identical to the single-shot response (the
    contract the router's token journal rides)."""
    srv = engine_server
    payload = {"input_ids": [3, 1, 4, 1, 5], "max_new_tokens": 8}
    _, oneshot, _ = _req_h(srv, "/generate", payload)
    url = f"http://{srv.host}:{srv.port}/generate"
    req = urllib.request.Request(
        url, json.dumps(dict(payload, stream=True)).encode(),
        {"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        for raw in r:
            raw = raw.strip()
            if raw:
                events.append(json.loads(raw))
    assert "done" in events[-1]
    streamed = [t for ev in events[:-1] for t in ev["t"]]
    body = events[-1]["done"]
    assert streamed == body["tokens"][5:5 + body["tokens_generated"]]
    # the terminal body matches the single-shot contract bitwise
    # (request_id differs per request; everything token-shaped equal)
    for k in ("tokens", "prompt_len", "new_tokens", "tokens_generated"):
        assert body[k] == oneshot[k]


def test_cancel_endpoint_mid_decode_409_with_partial(engine_server):
    """POST /cancel retires an admitted request at the next tick
    boundary; its own waiter gets 409 "cancelled" WITH the partial
    result (tokens_generated + partial_tokens) — work surfaced, not
    discarded."""
    import threading
    from paddle_tpu.distributed import resilience as resil
    srv = engine_server
    # warm the decode program first so the wedge below can't be
    # mistaken for compile time
    code, _, _ = _req_h(srv, "/generate",
                        {"input_ids": [2, 7], "max_new_tokens": 2})
    assert code == 200
    rid = "cancel-me-http"
    result = {}

    def waiter():
        url = f"http://{srv.host}:{srv.port}/generate"
        req = urllib.request.Request(
            url, json.dumps({"input_ids": [2, 7, 1, 8],
                             "max_new_tokens": 80}).encode(),
            {"Content-Type": "application/json",
             "X-PTPU-Request-Id": rid})
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                result["resp"] = (r.status, json.loads(r.read()))
        except urllib.error.HTTPError as e:
            result["resp"] = (e.code, json.loads(e.read()))

    # wedge ONE decode tick (replica_stall, the straggler site): the
    # request is guaranteed mid-decode — admitted, first token out,
    # loop asleep — when the cancel lands, however loaded the host is
    resil.arm_fault("replica_stall", 1, wedge_s=1.5)
    t = threading.Thread(target=waiter)
    t.start()
    # wait until the request is admitted and producing tokens
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = srv.engine.stats()
        if st["active"] >= 1:
            break
        time.sleep(0.01)
    code, body = _req(srv, "/cancel", {"request_id": rid})
    assert code == 200 and body["cancelled"] is True, body
    t.join(timeout=90)
    code, body = result["resp"]
    assert code == 409, body
    assert body["error"] == "cancelled"
    assert body["request_id"] == rid
    assert body["tokens_generated"] == len(body["partial_tokens"])
    # a second cancel of the resolved id is a truthful no-op
    code, body = _req(srv, "/cancel", {"request_id": rid})
    assert code == 200 and body["cancelled"] is False
    # /cancel without a request id is a 400
    code, body = _req(srv, "/cancel", {})
    assert code == 400


def test_admin_inject_gated_and_validated(engine_server, monkeypatch):
    """/admin/inject is the chaos bench's way to wedge a LIVE replica
    (replica_stall). It must be locked behind PADDLE_TPU_CHAOS_ADMIN
    (403 otherwise) and reject unknown sites (400) so a typo'd chaos
    script can't silently arm nothing."""
    srv = engine_server
    monkeypatch.delenv("PADDLE_TPU_CHAOS_ADMIN", raising=False)
    code, body = _req(srv, "/admin/inject",
                      {"site": "replica_stall", "count": 1})
    assert code == 403 and "chaos admin" in body["error"]
    monkeypatch.setenv("PADDLE_TPU_CHAOS_ADMIN", "1")
    code, body = _req(srv, "/admin/inject",
                      {"site": "replica_stal", "count": 1})
    assert code == 400 and "unknown fault-injection" in body["error"]
    # armed for real: the next decode tick sleeps the configured wedge
    code, body = _req(srv, "/admin/inject",
                      {"site": "replica_stall", "count": 1,
                       "wedge_s": 0.3})
    assert code == 200 and body["armed"] == "replica_stall"
    t0 = time.monotonic()
    code, body = _req(srv, "/generate",
                      {"input_ids": [5, 3], "max_new_tokens": 2})
    assert code == 200, body
    assert time.monotonic() - t0 >= 0.3     # the wedge really fired


def test_stream_disconnect_frees_slot_and_pages_fps_exported():
    """ISSUE 16: a streaming client that vanishes mid-generation must
    propagate to REAL cancellation on the replica — slot retired at
    the next tick, KV pages decref'd back to the pool (leak-free,
    counter-asserted) — and the paged engine's /healthz carries the
    prefix-trie fingerprints the router's affinity _pick intersects
    with incoming prompts."""
    from paddle_tpu.framework import random as _rng
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.inference.paging import chain_hashes
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    _rng.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=96, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=128))
    eng = ContinuousBatchingEngine(model, slots=2, max_len=96,
                                   cache_dtype="float32", tick_tokens=2,
                                   prefill_buckets=(8,), paged=True,
                                   page_size=8)
    srv = PredictorServer(engine=eng, port=0).start()
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]       # one complete page
        cancelled0 = eng.stats()["cancelled"]
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/generate",
            json.dumps({"input_ids": prompt, "max_new_tokens": 80,
                        "stream": True}).encode(),
            {"Content-Type": "application/json"})
        r = urllib.request.urlopen(req, timeout=60)
        assert r.status == 200
        first = json.loads(r.readline())
        assert first.get("t"), "no first token block"
        r.close()                # the client vanishes mid-stream
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            st = eng.stats()
            if st["cancelled"] > cancelled0 and st["active"] == 0:
                break
            time.sleep(0.05)
        st = eng.stats()
        assert st["cancelled"] == cancelled0 + 1, st
        assert st["active"] == 0                 # slot retired
        # leak-free: only trie-cached prefix pages stay referenced
        assert st["pages_used"] == st["pages_cached_prefix"]
        eng._allocator.check()
        # a later same-prefix request still serves normally...
        code, body, _ = _req_h(srv, "/generate",
                               {"input_ids": prompt,
                                "max_new_tokens": 4})
        assert code == 200, body
        # ...and /healthz exports the cross-process trie fingerprints:
        # the prompt's chain hashes are a subset, so a router hashing
        # this prompt scores the overlap without shipping token ids
        code, body, _ = _req_h(srv, "/healthz")
        assert code == 200
        fps = set(body["engine"]["prefix_fingerprints"])
        assert set(chain_hashes(prompt, 8)) <= fps
    finally:
        srv.stop()
        eng.stop()


@pytest.mark.slow
def test_serving_latency_bench_smoke():
    """The north-star serving benchmark (tools/bench_serving.py,
    BASELINE config 5) runs end-to-end at toy scale and emits a sane
    record: encoder p50 through the Predictor path + KV-cache decode."""
    import json
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # single-device serving: drop the test harness's 8-device flag
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "bench_serving.py"),
         "--smoke"], capture_output=True, text=True, timeout=420, env=env,
        cwd=root)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "ernie3_serving_latency"
    assert 0 < rec["p50_ms"] <= rec["p99_ms"]
    assert rec["decode_ms_per_token"] > 0
