"""nn layer tests (parity patterns: reference unittests for nn layers)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def setup_module(m):
    paddle.seed(2024)


def test_linear_grads_match_manual():
    lin = nn.Linear(4, 3)
    x = paddle.randn([5, 4])
    y = lin(x)
    loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(lin.bias.grad.numpy(), np.full(3, 5.0),
                               rtol=1e-5)
    np.testing.assert_allclose(lin.weight.grad.numpy(),
                               np.tile(x.numpy().sum(0)[:, None], (1, 3)),
                               rtol=1e-5)


def test_conv2d_matches_numpy():
    conv = nn.Conv2D(1, 1, 3, padding=0, bias_attr=False)
    w = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    conv.weight.set_value(w)
    x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
    out = conv(paddle.to_tensor(x))
    # direct correlation
    ref = np.zeros((3, 3), dtype=np.float32)
    for i in range(3):
        for j in range(3):
            ref[i, j] = (x[0, 0, i:i + 3, j:j + 3] * w[0, 0]).sum()
    np.testing.assert_allclose(out.numpy()[0, 0], ref, rtol=1e-5)


def test_conv2d_groups_and_stride():
    conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
    out = conv(paddle.randn([2, 4, 8, 8]))
    assert out.shape == [2, 8, 4, 4]


def test_conv_transpose_shape():
    deconv = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
    out = deconv(paddle.randn([1, 4, 5, 5]))
    assert out.shape == [1, 2, 9, 9]


def test_batchnorm_stats_update():
    bn = nn.BatchNorm1D(4, momentum=0.5, data_format="NCL")
    x = paddle.randn([8, 4, 6]) * 3 + 1
    bn(x)
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    m = bn._mean.numpy().copy()
    bn(x)
    np.testing.assert_array_equal(bn._mean.numpy(), m)  # frozen in eval


def test_layernorm_normalizes():
    ln = nn.LayerNorm(16)
    x = paddle.randn([4, 16]) * 5 + 3
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(-1), 1, atol=2e-2)


def test_groupnorm_instance_rms():
    gn = nn.GroupNorm(2, 4)
    assert gn(paddle.randn([2, 4, 5, 5])).shape == [2, 4, 5, 5]
    inorm = nn.InstanceNorm2D(4)
    assert inorm(paddle.randn([2, 4, 5, 5])).shape == [2, 4, 5, 5]
    rms = nn.RMSNorm(8)
    y = rms(paddle.randn([3, 8]))
    assert y.shape == [3, 8]


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor(np.array([[0, 1], [2, 0]]))
    out = emb(idx)
    np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))
    np.testing.assert_allclose(out.numpy()[1, 1], np.zeros(4))
    out.sum().backward()
    assert emb.weight.grad is not None


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    y = d(x)
    kept = float((y.numpy() != 0).mean())
    assert 0.3 < kept < 0.7
    np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0)  # upscale
    d.eval()
    np.testing.assert_array_equal(d(x).numpy(), x.numpy())


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2)
    np.testing.assert_allclose(mp(x).numpy()[0, 0], [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2)
    np.testing.assert_allclose(ap(x).numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    aap = nn.AdaptiveAvgPool2D(1)
    np.testing.assert_allclose(aap(x).numpy()[0, 0], [[7.5]])


def test_mha_self_attention_shapes_and_cache():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 6, 16])
    out = mha(q)
    assert out.shape == [2, 6, 16]
    # causal mask via bool mask
    mask = paddle.tril(paddle.ones([6, 6], dtype="bool"))
    out2 = mha(q, attn_mask=paddle.reshape(mask, [1, 1, 6, 6]))
    assert out2.shape == [2, 6, 16]
    # incremental cache decode
    cache = mha.gen_cache(q)
    step = paddle.randn([2, 1, 16])
    o, cache = mha(step, step, step, None, cache)
    assert o.shape == [2, 1, 16]
    assert cache.k.shape[1] == 1
    o, cache = mha(step, step, step, None, cache)
    assert cache.k.shape[1] == 2


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 4, 16])
    out = model(src, tgt)
    assert out.shape == [2, 4, 16]
    out.mean().backward()
    grads = [p.grad is not None for p in model.parameters()]
    assert all(grads)


def test_rnn_variants():
    for cls, states in [(nn.SimpleRNN, 1), (nn.GRU, 1), (nn.LSTM, 2)]:
        rnn = cls(4, 8, num_layers=1)
        out, st = rnn(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 8]
    birnn = nn.LSTM(4, 8, direction="bidirectional")
    out, _ = birnn(paddle.randn([2, 5, 4]))
    assert out.shape == [2, 5, 16]


def test_lstm_grad_flows():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    x.stop_gradient = False
    out, _ = lstm(x)
    out.mean().backward()
    assert x.grad is not None
    for p in lstm.parameters():
        assert p.grad is not None


def test_losses():
    logits = paddle.randn([8, 5])
    labels = paddle.to_tensor(np.random.randint(0, 5, (8,)))
    ce = nn.CrossEntropyLoss()
    l = ce(logits, labels)
    assert l.shape == []
    # soft label
    soft = paddle.nn.functional.softmax(paddle.randn([8, 5]))
    l2 = F.cross_entropy(logits, soft, soft_label=True)
    # ignore index
    labels2 = labels.clone()
    labels2[0] = -100
    l3 = F.cross_entropy(logits, labels2)
    assert np.isfinite(float(l3))
    # mse/l1/bce
    a, b = paddle.randn([4]), paddle.randn([4])
    np.testing.assert_allclose(float(F.mse_loss(a, b)),
                               ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
    p = paddle.nn.functional.sigmoid(paddle.randn([4]))
    t = paddle.to_tensor(np.array([0., 1., 1., 0.], dtype=np.float32))
    bce = F.binary_cross_entropy(p, t)
    bcel = F.binary_cross_entropy_with_logits(paddle.randn([4]), t)
    assert np.isfinite(float(bce)) and np.isfinite(float(bcel))
    kl = F.kl_div(paddle.nn.functional.log_softmax(paddle.randn([3, 4])),
                  paddle.nn.functional.softmax(paddle.randn([3, 4])))
    assert np.isfinite(float(kl))


def test_clip_grad_by_global_norm():
    lin = nn.Linear(4, 4)
    x = paddle.randn([2, 4]) * 100
    lin(x).sum().backward()
    clip = nn.ClipGradByGlobalNorm(1.0)
    pg = [(p, p.grad) for p in lin.parameters()]
    clipped = clip(pg)
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in clipped))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)


def test_containers():
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4 and len(list(ll.parameters())) == 8
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    ld["b"] = nn.Linear(2, 2)
    assert "a" in ld and len(ld) == 2
    pl = nn.ParameterList([paddle.Parameter(paddle.randn([2]).value)
                           for _ in range(2)])
    assert len(list(pl.parameters())) == 2


def test_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h = lin.register_forward_post_hook(
        lambda layer, inp, out: calls.append(1))
    lin(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    lin(paddle.randn([1, 2]))
    assert calls == [1]


def test_sdpa_matches_reference():
    b, s, h, d = 2, 8, 2, 4
    q = paddle.randn([b, s, h, d])
    k = paddle.randn([b, s, h, d])
    v = paddle.randn([b, s, h, d])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    # numpy reference
    qn, kn, vn = (t.numpy().transpose(0, 2, 1, 3) for t in (q, k, v))
    logits = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), dtype=bool))
    logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = (p @ vn).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)


def test_flash_attention_api():
    q = paddle.randn([2, 16, 2, 8])
    out, _ = F.flash_attention(q, q, q, causal=True)
    assert out.shape == [2, 16, 2, 8]
