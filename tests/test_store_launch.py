"""Native TCPStore + launcher tests (reference: test_tcp_store.cc,
test_launch_coverage.py)."""
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.distributed.launch import ElasticManager, launch
from paddle_tpu.distributed.store import TCPStore, build_native_store


def test_native_store_builds():
    assert build_native_store() is not None


def test_store_set_get_add_wait():
    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    c = TCPStore("127.0.0.1", master.port, timeout=5)
    c.set("k", b"v1")
    assert master.get("k") == b"v1"
    assert c.add("n", 2) == 2
    assert master.add("n", 40) == 42

    def later():
        time.sleep(0.2)
        master.set("slow", b"done")

    t = threading.Thread(target=later)
    t.start()
    assert c.get("slow") == b"done"
    t.join(timeout=5)
    c.close()
    master.close()


def test_store_timeout():
    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    c = TCPStore("127.0.0.1", master.port, timeout=0.3)
    with pytest.raises(TimeoutError):
        c.get("missing")
    c.close()
    master.close()


def test_store_barrier_two_clients():
    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=10)
    results = []

    def participant():
        c = TCPStore("127.0.0.1", master.port, timeout=10)
        c.barrier("b0", 2)
        results.append(1)
        c.close()

    ts = [threading.Thread(target=participant) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert results == [1, 1]
    master.close()


def test_launch_spawns_with_envs(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        print(os.environ["PADDLE_TRAINER_ID"],
              os.environ["PADDLE_TRAINERS_NUM"],
              os.environ["JAX_PROCESS_ID"])
    """))
    log_dir = str(tmp_path / "logs")
    ret = launch(str(script), [], nnodes=1, node_rank=0,
                 master="127.0.0.1:0" if False else "127.0.0.1:38211",
                 nproc_per_node=2, log_dir=log_dir)
    assert ret == 0
    logs = sorted(os.listdir(log_dir))
    assert logs == ["rank_0.log", "rank_1.log"]
    body0 = open(os.path.join(log_dir, "rank_0.log")).read()
    assert body0.strip().startswith("0 2 0")


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text("import sys; sys.exit(3)\n")
    ret = launch(str(script), [], nnodes=1, node_rank=0,
                 master="127.0.0.1:38212", nproc_per_node=1)
    assert ret == 3


def test_elastic_manager_membership():
    master = TCPStore("127.0.0.1", 0, is_master=True, timeout=5)
    a = ElasticManager(master, "node_a", np_range=(1, 2)).register()
    b_store = TCPStore("127.0.0.1", master.port, timeout=5)
    b = ElasticManager(b_store, "node_b", np_range=(1, 2)).register()
    assert set(a.alive_nodes(["node_a", "node_b"])) == {"node_a", "node_b"}
    assert a.match(["node_a", "node_b"])
    b.exit()
    assert a.alive_nodes(["node_a", "node_b"]) == ["node_a"]
    a.exit()
    b_store.close()
    master.close()
