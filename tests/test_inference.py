"""Serving-path tests (reference: test_analysis_predictor / inference api
tests): save -> Config -> create_predictor -> zero-copy IO -> run."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor


@pytest.fixture
def saved_model(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "serve" / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([None, 8])])
    x = np.random.randn(4, 8).astype("float32")
    return path, x, m(paddle.to_tensor(x)).numpy()


def test_predictor_zero_copy_roundtrip(saved_model):
    path, x, ref = saved_model
    cfg = Config(path + ".pdmodel")
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    assert len(names) == 1
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(out.copy_to_cpu(), ref, rtol=1e-5,
                               atol=1e-6)
    # dynamic batch via symbolic export
    x2 = np.random.randn(9, 8).astype("float32")
    h.copy_from_cpu(x2)
    pred.run()
    assert pred.get_output_handle("out0").copy_to_cpu().shape == (9, 4)


def test_predictor_run_with_inputs_list(saved_model):
    path, x, ref = saved_model
    pred = create_predictor(Config(path))
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


def test_static_save_load_inference_model(tmp_path):
    import paddle_tpu.static as static

    paddle.seed(1)
    m = nn.Linear(4, 2)
    m.eval()
    prefix = str(tmp_path / "static_model")
    static.save_inference_model(prefix, m,
                                [static.InputSpec([None, 4])])
    prog = static.load_inference_model(prefix)
    exe = static.Executor()
    x = np.random.randn(3, 4).astype("float32")
    (out,) = exe.run(prog, feed={"x": x})
    np.testing.assert_allclose(out, m(paddle.to_tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_static_data_and_program_guard():
    import paddle_tpu.static as static
    spec = static.data("img", [None, 3, 32, 32], "float32")
    assert spec.shape == [None, 3, 32, 32]
    with static.program_guard(static.default_main_program()):
        pass
