"""Serving-path tests (reference: test_analysis_predictor / inference api
tests): save -> Config -> create_predictor -> zero-copy IO -> run."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor


@pytest.fixture
def saved_model(tmp_path):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "serve" / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([None, 8])])
    x = np.random.randn(4, 8).astype("float32")
    return path, x, m(paddle.to_tensor(x)).numpy()


def test_predictor_zero_copy_roundtrip(saved_model):
    path, x, ref = saved_model
    cfg = Config(path + ".pdmodel")
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    assert len(names) == 1
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(out.copy_to_cpu(), ref, rtol=1e-5,
                               atol=1e-6)
    # dynamic batch via symbolic export
    x2 = np.random.randn(9, 8).astype("float32")
    h.copy_from_cpu(x2)
    pred.run()
    assert pred.get_output_handle("out0").copy_to_cpu().shape == (9, 4)


def test_predictor_run_with_inputs_list(saved_model):
    path, x, ref = saved_model
    pred = create_predictor(Config(path))
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


def test_static_save_load_inference_model(tmp_path):
    import paddle_tpu.static as static

    paddle.seed(1)
    m = nn.Linear(4, 2)
    m.eval()
    prefix = str(tmp_path / "static_model")
    static.save_inference_model(prefix, m,
                                [static.InputSpec([None, 4])])
    prog = static.load_inference_model(prefix)
    exe = static.Executor()
    x = np.random.randn(3, 4).astype("float32")
    (out,) = exe.run(prog, feed={"x": x})
    np.testing.assert_allclose(out, m(paddle.to_tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_static_data_and_program_guard():
    import paddle_tpu.static as static
    spec = static.data("img", [None, 3, 32, 32], "float32")
    assert spec.shape == [None, 3, 32, 32]
    with static.program_guard(static.default_main_program()):
        pass


# ---------------------------------------------------------------------------
# graceful degradation (resilience subsystem): the serving front-end
# must answer 503 — never hang — when the backend is unavailable or a
# request exceeds its deadline, and /healthz must report readiness.
# ---------------------------------------------------------------------------

import json
import time
import urllib.error
import urllib.request

from paddle_tpu.distributed.resilience import FaultInjector
from paddle_tpu.inference.serve import PredictorServer


@pytest.fixture
def resilient_server(saved_model):
    path, x, ref = saved_model
    srv = PredictorServer(path + ".pdmodel", port=0, deadline_s=0.6,
                          max_queue=2).start()
    yield srv, x
    srv.stop()


def _req(srv, path, payload=None, timeout=30):
    url = f"http://{srv.host}:{srv.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _predict_payload(srv, x):
    name = srv.predictor.get_input_names()[0]
    return {"inputs": {name: {"data": x.tolist(), "dtype": "float32"}}}


def test_healthz_reports_ready(resilient_server):
    srv, _ = resilient_server
    code, body = _req(srv, "/healthz")
    assert code == 200
    assert body["status"] == "ready"
    assert body["max_queue"] == 2 and body["failure_streak"] == 0


def test_deadline_exceeded_returns_503_not_a_hang(resilient_server):
    srv, x = resilient_server
    payload = _predict_payload(srv, x)
    with FaultInjector({"serve_hang": 1}, wedge_s=1.5):
        t0 = time.monotonic()
        code, body = _req(srv, "/predict", payload)
        took = time.monotonic() - t0
    assert code == 503, body
    assert body["error"] == "deadline_exceeded"
    assert took < 1.4, f"client waited {took:.2f}s — that is a hang"
    time.sleep(1.2)  # let the wedged worker drain
    code, body = _req(srv, "/predict", payload)
    assert code == 200, body  # server recovered


def test_backend_unavailable_returns_503_and_healthz_degrades(
        resilient_server):
    srv, x = resilient_server
    payload = _predict_payload(srv, x)
    with FaultInjector({"serve_backend": 3}):
        for _ in range(3):
            code, body = _req(srv, "/predict", payload)
            assert code == 503, body
            assert "backend_unavailable" in body["error"]
    code, body = _req(srv, "/healthz")
    assert code == 503 and body["status"] == "unready"
    assert "consecutive" in body["reason"]
    # one healthy predict clears the streak and readiness returns
    code, _ = _req(srv, "/predict", payload)
    assert code == 200
    code, body = _req(srv, "/healthz")
    assert code == 200 and body["status"] == "ready"
