"""Paged KV cache + shared-prefix reuse (ISSUE 9).

Host-side units (inference/paging.py — no jax, no model):
- PageAllocator: all-or-nothing alloc, refcount sharing, exact free,
  invariant survival under randomized admit/retire churn;
- PrefixTrie: longest-chain match, first-writer-wins insert, LRU leaf
  eviction that never touches a page a live slot references.

Engine level (the serving guarantees):
- greedy output TOKEN-IDENTICAL to the slot-cache engine's oracle
  (sequential generate()) across staggered mixed-length traffic —
  float32 AND int8 pools, shared-prefix admissions included;
- prefix-cache hits SKIP prefill: a fully cached prompt re-prefills
  exactly ONE token (copy-on-write tail page), a partial hit only its
  un-cached suffix;
- ZERO recompiles under (prompt-len, max-new, prefix-depth, page
  placement) drift — the engine trace counters must not move;
- no page leak: after every request retires, only trie-cached prefix
  pages remain referenced, and evicting the trie empties the pool;
- the queue sheds 503 `cache_exhausted` (typed + HTTP, Retry-After
  carried) when the PAGE POOL, not slot count, is the binding
  constraint.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.engine import (CacheExhausted,
                                         ContinuousBatchingEngine,
                                         EngineOverloaded)
from paddle_tpu.inference.paging import (PageAllocator, PrefixTrie,
                                         pages_needed)
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


# ---------------------------------------------------------------------------
# host-side units
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_refcount():
    a = PageAllocator(4)
    got = a.alloc(3)
    assert sorted(got) == [0, 1, 2] and a.free_pages == 1
    assert a.alloc(2) is None        # all-or-nothing: pool untouched
    assert a.free_pages == 1
    a.incref([got[0]])               # shared with a second owner
    assert a.decref([got[0]]) == 0   # still held
    assert a.decref(got) == 3        # now everything frees
    assert a.free_pages == 4
    a.check()
    with pytest.raises(AssertionError):
        a.decref([0])                # double-free is loud


def test_allocator_churn_no_leak():
    rng = np.random.RandomState(0)
    a = PageAllocator(16)
    held = []
    for _ in range(500):
        if held and rng.rand() < 0.45:
            a.decref(held.pop(rng.randint(len(held))))
        else:
            got = a.alloc(int(rng.randint(1, 5)))
            if got is not None:
                held.append(got)
        a.check()
    for pages in held:
        a.decref(pages)
    a.check()
    assert a.free_pages == 16


def test_trie_match_insert_evict():
    a = PageAllocator(8)
    t = PrefixTrie(a)
    k1, k2 = tuple(range(4)), tuple(range(4, 8))
    p = a.alloc(2)
    t.insert([k1, k2], p)            # trie now co-owns both pages
    assert t.match([k1, k2]) == p
    assert t.match([k1, (9, 9, 9, 9)]) == p[:1]
    assert t.match([(7, 7, 7, 7)]) == []
    a.decref(p)                      # slot retires; trie refs remain
    assert a.used_pages == 2
    # eviction respects live references: pin the head page
    a.incref([p[0]])
    assert t.evict(2) == 1           # only the (leaf) tail page frees
    assert a.refcount(p[0]) == 2 and a.free_pages == 7
    a.decref([p[0]])
    assert t.evict(1) == 1           # now the head drains too
    assert a.free_pages == 8
    a.check()


def test_trie_first_writer_wins_and_lru_order():
    a = PageAllocator(8)
    t = PrefixTrie(a)
    key = ((1, 2),)
    pg1 = a.alloc(1)
    t.insert([key[0]], pg1)
    pg2 = a.alloc(1)
    t.insert([key[0]], pg2)          # duplicate key: no-op
    assert t.match([key[0]]) == pg1 and t.pages_cached == 1
    a.decref(pg1), a.decref(pg2)
    assert a.free_pages == 7         # pg2 freed, pg1 trie-held
    # LRU: older unmatched chain evicts before the freshly matched one
    other = a.alloc(1)
    t.insert([(3, 4)], other)
    a.decref(other)
    t.match([key[0]])                # refresh pg1
    assert t.evict(1) == 1
    assert a.refcount(pg1[0]) == 1 and a.refcount(other[0]) == 0


def test_pages_needed():
    assert pages_needed(1, 16) == 1
    assert pages_needed(16, 16) == 1
    assert pages_needed(17, 16) == 2


def test_trie_fingerprints_match_prompt_chain_hashes():
    """ISSUE 16: the trie's crc32-chained fingerprints and a prompt's
    chain_hashes agree EXACTLY on cached prefixes — the cross-process
    identity the router's affinity _pick intersects (Python hash() is
    per-process salted; crc32 is not)."""
    from paddle_tpu.inference.paging import chain_hashes
    a = PageAllocator(8)
    t = PrefixTrie(a)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    keys = [tuple(prompt[0:4]), tuple(prompt[4:8])]
    pages = a.alloc(2)
    t.insert(keys, pages)
    fps = set(t.fingerprints())
    assert len(fps) == 2
    assert set(chain_hashes(prompt, 4)) <= fps
    # incomplete tail pages never hash; degenerate page sizes are safe
    assert chain_hashes(prompt[:7], 4) == chain_hashes(prompt, 4)[:1]
    assert chain_hashes([], 4) == []
    assert chain_hashes(prompt, 0) == []
    # a DIFFERENT second page forks the chain: shared first hash,
    # distinct second (parent folds in, so position matters)
    other = chain_hashes([1, 2, 3, 4, 9, 9, 9, 9], 4)
    mine = chain_hashes(prompt, 4)
    assert other[0] == mine[0] and other[1] != mine[1]
    # the walk is bounded: limit caps the exported set
    assert len(t.fingerprints(limit=1)) == 1


# ---------------------------------------------------------------------------
# engine level
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def paged_engine(model):
    eng = ContinuousBatchingEngine(
        model, slots=4, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True,
        page_size=8)
    yield eng
    eng.stop()


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        0, 250, (n,)).astype("int64")


def test_paged_greedy_identity_staggered_mixed_lengths(model,
                                                       paged_engine):
    """Mixed-length staggered traffic through the paged engine is
    token-identical to sequential generate() — the gathered page view,
    live-masked page writes and suffix admission are pure cache
    plumbing, never a numerics change."""
    eng = paged_engine
    shapes = [(5, 6), (8, 9), (12, 4), (3, 12), (16, 8)] * 2
    prompts = [_prompt(i, p) for i, (p, _) in enumerate(shapes)]
    futs = []
    for (p, n), ids in zip(shapes, prompts):
        futs.append(eng.submit(ids, max_new_tokens=n))
        time.sleep(0.01)          # arrivals land across tick boundaries
    outs = [f.result(timeout=300) for f in futs]
    for (p, n), ids, got in zip(shapes, prompts, outs):
        want = model.generate(ids[None], max_new_tokens=n,
                              cache_dtype="float32")[0]
        np.testing.assert_array_equal(got, want)
    st = eng.stats()
    assert st["paged"] and st["pages_used"] >= 0


def test_paged_identity_with_eos(model, paged_engine):
    ids = _prompt(0, 6)
    first = model.generate(ids[None], max_new_tokens=1,
                           cache_dtype="float32")[0, -1]
    eos = int(first)
    want = model.generate(ids[None], max_new_tokens=10,
                          eos_token_id=eos, cache_dtype="float32")[0]
    got = paged_engine.generate(ids, max_new_tokens=10,
                                eos_token_id=eos, timeout=300)
    np.testing.assert_array_equal(got, want)


def test_prefix_hit_skips_prefill_and_stays_identical(model,
                                                      paged_engine):
    """Shared-prefix admissions: a fully cached prompt re-prefills
    exactly ONE token (COW tail page), a partial hit only its suffix —
    and every output stays token-identical to the oracle."""
    eng = paged_engine
    # P=16 aligned to page_size=8: two complete, shareable pages
    p16 = _prompt(50, 16)
    before = eng.stats()
    a = eng.generate(p16, max_new_tokens=6, timeout=300)
    mid = eng.stats()
    b = eng.generate(p16, max_new_tokens=6, timeout=300)
    after = eng.stats()
    want = model.generate(p16[None], max_new_tokens=6,
                          cache_dtype="float32")[0]
    np.testing.assert_array_equal(a, want)
    np.testing.assert_array_equal(b, want)
    # first admission prefilled the whole prompt, second only 1 token
    assert mid["prefill_tokens"] - before["prefill_tokens"] == 16
    assert after["prefill_tokens"] - mid["prefill_tokens"] == 1
    assert after["prefix_hits"] == mid["prefix_hits"] + 1
    assert after["prefix_tokens_saved"] - mid["prefix_tokens_saved"] \
        == 15
    # partial hit: shared 8-token head (one page), fresh tail
    tail = np.concatenate([p16[:8], _prompt(51, 5)])
    want_t = model.generate(tail[None], max_new_tokens=5,
                            cache_dtype="float32")[0]
    got_t = eng.generate(tail, max_new_tokens=5, timeout=300)
    np.testing.assert_array_equal(got_t, want_t)
    st = eng.stats()
    assert st["prefix_hits"] == after["prefix_hits"] + 1
    assert st["prefill_tokens"] - after["prefill_tokens"] == 5


def test_paged_program_count_constant_under_drift(model, paged_engine):
    """Prompt-length, max-new, prefix-depth AND page-placement drift
    all ride the same compiled programs: the trace counters inside the
    jitted bodies must not move after warmup."""
    eng = paged_engine
    for p in (4, 12):
        eng.generate(_prompt(p, p), max_new_tokens=3, timeout=300)
    warm = eng.compiled_program_count
    pairs = [(p, n) for p in range(3, 12) for n in (2, 3)]
    futs = [eng.submit(_prompt(i, p), max_new_tokens=n)
            for i, (p, n) in enumerate(pairs)]
    # plus prefix-hit and COW admissions (different code paths)
    shared = _prompt(50, 16)
    futs.append(eng.submit(shared, max_new_tokens=3))
    futs.append(eng.submit(np.concatenate([shared[:8], _prompt(52, 3)]),
                           max_new_tokens=3))
    for f in futs:
        f.result(timeout=300)
    assert eng.compiled_program_count == warm, \
        "paged engine recompiled under drift"


def test_paged_int8_identity_and_slot_reuse(model):
    """int8 page pools: identity vs sequential int8 generate, across
    slot reuse (a retired request's pages, scales included, can never
    leak — freshly admitted tokens overwrite before any masked read)."""
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="int8",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8)
    try:
        for seed, (p, n) in enumerate([(12, 8), (5, 6), (16, 8),
                                       (9, 10)]):
            ids = _prompt(seed, p)
            want = model.generate(ids[None], max_new_tokens=n,
                                  cache_dtype="int8")[0]
            got = eng.generate(ids, max_new_tokens=n, timeout=300)
            np.testing.assert_array_equal(got, want)
        # prefix reuse under int8 (quantized pages shared bit-exactly)
        ids = _prompt(99, 16)
        want = model.generate(ids[None], max_new_tokens=6,
                              cache_dtype="int8")[0]
        for _ in range(2):
            got = eng.generate(ids, max_new_tokens=6, timeout=300)
            np.testing.assert_array_equal(got, want)
        assert eng.stats()["prefix_hits"] >= 1
    finally:
        eng.stop()


def test_no_page_leak_after_retire_under_churn(model):
    """Randomized admit/retire churn: once every request resolves, the
    only referenced pages are the trie's cached prefixes, and draining
    the trie returns the pool to fully free."""
    eng = ContinuousBatchingEngine(
        model, slots=3, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8,
        max_queue=64)
    rng = np.random.RandomState(3)
    try:
        shared = _prompt(77, 8)
        futs = []
        for i in range(16):
            if rng.rand() < 0.4:    # prefix-sharing mix
                ids = np.concatenate([shared,
                                      _prompt(100 + i,
                                              int(rng.randint(1, 6)))])
            else:
                ids = _prompt(200 + i, int(rng.randint(3, 17)))
            futs.append(eng.submit(
                ids, max_new_tokens=int(rng.randint(2, 8))))
        for f in futs:
            f.result(timeout=300)
        # engine idle: only trie references remain
        deadline = time.time() + 30
        while eng.stats()["active"] and time.time() < deadline:
            time.sleep(0.02)
        st = eng.stats()
        assert st["active"] == 0
        assert st["pages_used"] == st["pages_cached_prefix"]
        eng._allocator.check()
        # drop the prefix cache: the pool must drain to fully free
        eng._trie.evict_all()
        assert eng._allocator.used_pages == 0
        eng._allocator.check()
    finally:
        eng.stop()


def test_cancel_mid_decode_reclaims_pages_leak_free(model,
                                                    paged_engine):
    """ISSUE 15: cancelling a paged request mid-decode retires its
    slot at the next tick boundary AND decrefs its pages — after the
    cancel the pool holds only trie-cached prefix pages (the hedge
    loser's leak-free guarantee, the same invariant the chaos bench
    counter-asserts tier-wide). Rides the warm module engine: cancel
    must add zero compiles."""
    import threading
    from paddle_tpu.inference.engine import RequestCancelled
    eng = paged_engine
    shared = _prompt(50, 8)          # one shared (trie-cached) page
    ids = np.concatenate([shared, _prompt(51, 4)])
    progressed = threading.Event()
    seen = []

    def cb(toks):
        seen.extend(toks)
        if len(seen) >= 4:
            progressed.set()

    # a sibling holding the shared prefix keeps the trie page hot
    sib = eng.submit(np.concatenate([shared, _prompt(52, 3)]),
                     max_new_tokens=4)
    fut = eng.submit(ids, max_new_tokens=40, request_id="victim",
                     progress_cb=cb)
    assert progressed.wait(timeout=300), "no token progress"
    assert eng.cancel("victim") is True
    with pytest.raises(RequestCancelled):
        fut.result(timeout=60)
    assert fut._ptpu_gen_info["tokens_generated"] >= 4
    sib.result(timeout=300)
    deadline = time.time() + 60
    while eng.stats()["active"] and time.time() < deadline:
        time.sleep(0.02)
    st = eng.stats()
    assert st["active"] == 0
    # the cancelled request's pages are GONE from the pool — only
    # trie-held prefix pages remain referenced, and the allocator's
    # refcount invariants hold
    assert st["pages_used"] == st["pages_cached_prefix"]
    eng._allocator.check()
    # and the engine still serves token-identically afterwards
    got = eng.generate(ids, max_new_tokens=6, timeout=300)
    want = model.generate(ids[None], max_new_tokens=6,
                          cache_dtype="float32")[0]
    np.testing.assert_array_equal(got, want)


def test_spec_churn_never_touches_shared_pages_and_leak_free(model):
    """ISSUE 13 satellite: randomized draft/verify churn over shared-
    prefix slots. The verify block writes (and the rejected-token
    garbage it leaves behind) land ONLY in a slot's private pages —
    every speculative write position is >= prompt_len while shared
    prefix pages hold complete PROMPT pages — so the trie's refcount>1
    pages must end the churn BITWISE unchanged, and the allocator ends
    leak-free. int8 pools (data AND quantization scales compared) so
    the quantized paged path is churned too; spot requests assert
    identity vs the int8 generate() oracle."""
    eng = ContinuousBatchingEngine(
        model, slots=3, max_len=64, cache_dtype="int8",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8,
        max_queue=64, num_pages=48, speculative="ngram", spec_k=4)
    rng = np.random.RandomState(9)
    try:
        # a shared 8-token prefix = one complete shareable page; the
        # repeated 4-token pattern inside it makes the n-gram drafter
        # fire (accepted AND rejected verify positions both occur)
        pat = rng.randint(0, 250, (4,)).astype("int64")
        shared = np.concatenate([pat, pat])
        # seed the trie, then snapshot the shared pages' physical
        # contents while a second holder keeps them refcount > 1
        f0 = eng.submit(np.concatenate([shared, pat[:2]]),
                        max_new_tokens=4)
        f0.result(timeout=300)
        trie_pages = []
        stack = [eng._trie.root]
        while stack:
            node = stack.pop()
            if node is not eng._trie.root:
                trie_pages.append(node.page)
            stack.extend(node.children.values())
        assert trie_pages, "no shared pages cached"

        def page_bytes(pages):
            out = []
            for kc, vc in eng._caches:
                for half in (kc, vc):
                    out.append(np.asarray(half["pages"])[pages].copy())
                    if "scale" in half:
                        out.append(
                            np.asarray(half["scale"])[pages].copy())
            return out

        before = page_bytes(trie_pages)
        futs, spot = [], []
        for i in range(18):
            if rng.rand() < 0.6:     # shared-prefix + repetitive tail
                ids = np.concatenate(
                    [shared, pat[:int(rng.randint(1, 4))]])
            else:                    # fresh random traffic
                ids = rng.randint(0, 250,
                                  (int(rng.randint(3, 17)),)) \
                    .astype("int64")
            n = int(rng.randint(2, 10))
            futs.append(eng.submit(ids, max_new_tokens=n))
            if i == 0:               # one identity spot-check vs the
                spot.append((ids, n, futs[-1]))   # int8 oracle
        for f in futs:
            f.result(timeout=300)
        for ids, n, f in spot:
            want = model.generate(ids[None], max_new_tokens=n,
                                  cache_dtype="int8")[0]
            np.testing.assert_array_equal(f.result(), want)
        st = eng.stats()
        assert st["spec_ticks"] > 0, "churn never took a verify tick"
        assert st["tokens_rejected"] > 0, \
            "churn never exercised rejection rollback"
        assert st["prefix_hits"] >= 1
        # shared pages bitwise untouched by all that draft/verify churn
        after = page_bytes(trie_pages)
        for b, a in zip(before, after):
            np.testing.assert_array_equal(b, a)
        # leak-free: engine idle -> only trie references remain; drain
        # the trie -> pool fully free
        deadline = time.time() + 30
        while eng.stats()["active"] and time.time() < deadline:
            time.sleep(0.02)
        assert eng.stats()["active"] == 0
        eng._allocator.check()
        assert eng.stats()["pages_used"] \
            == eng.stats()["pages_cached_prefix"]
        eng._trie.evict_all()
        assert eng._allocator.used_pages == 0
        eng._allocator.check()
    finally:
        eng.stop()


def test_submit_validation_paged(model):
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=32, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8,
        num_pages=4)
    try:
        # the per-request view-length check rejects outright what could
        # never fit (and, via the constructor's num_pages >=
        # pages_per_slot invariant, anything passing it CAN fit once
        # pages free up)
        with pytest.raises(ValueError):
            eng.submit(_prompt(0, 16), max_new_tokens=20)
        with pytest.raises(ValueError):
            eng.submit(_prompt(0, 16), max_new_tokens=16)
        # a max-size request is statically admissible: it queues
        fut = eng.submit(_prompt(0, 16), max_new_tokens=12)
        fut.result(timeout=300)
    finally:
        eng.stop()


def test_cache_exhausted_shed_typed_and_http(model):
    """When the page pool (not slots) is what blocks admission, the
    queue sheds CacheExhausted -> HTTP 503 `cache_exhausted` with
    Retry-After; requests already queued still complete."""
    from paddle_tpu.inference.serve import PredictorServer
    eng = ContinuousBatchingEngine(
        model, slots=4, max_len=32, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8,
        num_pages=4, max_queue=2, prefix_cache=False)
    srv = PredictorServer(engine=eng, port=0).start()
    try:
        # each request needs 3 of the 4 pages: one runs, rest queue.
        # Back-to-back submits can transiently saturate the 2-deep
        # queue before the engine thread pops the head (GIL timing on
        # this 1-core host) — that shed is the OTHER kind; retry it.
        futs = []
        for i in range(3):
            for _ in range(500):
                try:
                    futs.append(eng.submit(_prompt(i, 8),
                                           max_new_tokens=12))
                    break
                except EngineOverloaded:
                    time.sleep(0.01)
        assert len(futs) == 3
        seen = None
        for _ in range(500):
            try:
                futs.append(eng.submit(_prompt(9, 8),
                                       max_new_tokens=12))
                time.sleep(0.01)
            except CacheExhausted as e:
                seen = e
                break
            except EngineOverloaded:
                time.sleep(0.01)
        assert seen is not None, "pool-bound shed never surfaced"
        assert seen.reason == "cache_exhausted"
        assert seen.free_pages < 3 and seen.num_pages == 4
        # HTTP face: same truthful reason + Retry-After header
        url = f"http://{srv.host}:{srv.port}/generate"
        data = json.dumps({"input_ids": _prompt(10, 8).tolist(),
                           "max_new_tokens": 12}).encode()
        code, body, headers = None, None, {}
        for _ in range(500):
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    time.sleep(0.01)   # admitted: pressure not yet on
            except urllib.error.HTTPError as e:
                code, body = e.code, json.loads(e.read())
                headers = dict(e.headers)
                if body.get("error") == "cache_exhausted":
                    break
        assert code == 503 and body["error"] == "cache_exhausted", body
        assert "Retry-After" in headers
        assert body["retry_after_s"] > 0
        assert body["free_pages"] < 3 and body["num_pages"] == 4
        for f in futs:
            f.result(timeout=300)
    finally:
        srv.stop()
        eng.stop()


def test_healthz_and_metrics_report_page_pool(model):
    """/healthz page-pool fields + the obs registry gauges/counters —
    ONE engine serves both faces (they are second views of the same
    record sites, and each extra engine costs a cold compile set)."""
    from paddle_tpu import obs
    from paddle_tpu.inference.serve import PredictorServer
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8)
    srv = PredictorServer(engine=eng, port=0).start()
    try:
        eng.generate(_prompt(1, 16), max_new_tokens=4, timeout=300)
        eng.generate(_prompt(1, 16), max_new_tokens=4, timeout=300)
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/healthz",
                timeout=60) as r:
            body = json.loads(r.read())
        e = body["engine"]
        assert e["paged"] is True
        assert e["pages_total"] == eng.num_pages
        assert e["pages_free"] + e["pages_used"] == e["pages_total"]
        assert e["prefix_hits"] >= 1
        assert 0.0 <= e["prefix_hit_rate"] <= 1.0
        assert 0.0 <= e["page_utilization"] <= 1.0
        if obs.enabled():
            reg = obs.metrics.registry
            free = reg.get("ptpu_engine_pages_free")
            used = reg.get("ptpu_engine_pages_used")
            hits = reg.get("ptpu_engine_prefix_hits_total")
            misses = reg.get("ptpu_engine_prefix_misses_total")
            assert free is not None and used is not None
            assert free.value() + used.value() == eng.num_pages
            assert hits is not None and hits.value() >= 1
            assert misses is not None and misses.value() >= 1
    finally:
        srv.stop()
        eng.stop()


def test_llama_paged_identity_gqa():
    """The paged cache works for any cache-threaded model: LLaMA-tiny
    exercises GQA pools (nkv < nh broadcast at use) and RoPE per-row
    offsets over the gathered page view."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(11)
    lm = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=176,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128))
    lm.eval()
    eng = ContinuousBatchingEngine(
        lm, slots=2, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, paged=True, page_size=8)
    try:
        for seed, (p, n) in enumerate([(9, 6), (16, 5)]):
            ids = _prompt(seed, p)
            want = lm.generate(ids[None], max_new_tokens=n,
                               cache_dtype="float32")[0]
            got = eng.generate(ids, max_new_tokens=n, timeout=300)
            np.testing.assert_array_equal(got, want)
        # prefix reuse across the GQA pool
        ids = _prompt(42, 16)
        want = lm.generate(ids[None], max_new_tokens=4,
                           cache_dtype="float32")[0]
        for _ in range(2):
            np.testing.assert_array_equal(
                eng.generate(ids, max_new_tokens=4, timeout=300), want)
        assert eng.stats()["prefix_hits"] >= 1
    finally:
        eng.stop()


def test_paged_scan_layers_builds_stacked_pools():
    """Since PR 20 the scanned stack serves paged: per-layer pools
    stack under a leading L axis and the shared block table broadcasts
    onto it inside the engine (token identity vs the unrolled model is
    asserted in tests/test_tp_engine.py). The old NotImplementedError
    rejection is gone — construction must yield the stacked shape."""
    paddle.seed(5)
    m = GPTForCausalLM(gpt_tiny(scan_layers=True))
    k, v = m.new_paged_cache(8, 16, "float32")
    L = m.cfg.num_layers
    assert k["pages"].ndim == 5 and k["pages"].shape[:2] == (L, 8)
    assert v["pages"].shape == k["pages"].shape
