"""Distribution + sparse + sharded-checkpoint tests (reference:
test_distribution_*.py numeric checks vs scipy-derived closed forms,
test_sparse_*.py, dist ckpt converter tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distribution import (Beta, Categorical, Dirichlet,
                                     ExpTransform, Gamma, Independent,
                                     Laplace, LogNormal, Normal,
                                     TransformedDistribution, Uniform,
                                     kl_divergence, register_kl)


def test_normal_moments_and_logprob():
    n = Normal(1.0, 2.0)
    assert float(n.mean) == 1.0 and float(n.variance) == 4.0
    # N(1,2) at x=1: log(1/(2*sqrt(2pi)))
    lp = float(n.log_prob(paddle.to_tensor(1.0)))
    np.testing.assert_allclose(lp, -np.log(2 * np.sqrt(2 * np.pi)),
                               rtol=1e-5)
    paddle.seed(0)
    s = n.sample([20000])
    np.testing.assert_allclose(s.numpy().mean(), 1.0, atol=0.06)
    np.testing.assert_allclose(s.numpy().std(), 2.0, atol=0.06)


def test_normal_rsample_differentiable():
    loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
    n = Normal(loc, 1.0)
    paddle.seed(0)
    s = n.rsample([64])
    paddle.mean(s).backward()
    np.testing.assert_allclose(loc.grad.numpy(), 1.0, rtol=1e-5)


def test_kl_normal_closed_form():
    p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
    kl = float(kl_divergence(p, q))
    expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, expect, rtol=1e-5)
    # sanity: KL(p, p) == 0
    np.testing.assert_allclose(float(kl_divergence(p, p)), 0.0, atol=1e-7)


def test_categorical_entropy_and_kl():
    # reference split semantics: entropy/KL softmax the weights
    # (categorical.py:258/:214) while probs/log_prob sum-normalize
    # (categorical.py:116) — both halves asserted
    logits = np.log(np.array([0.2, 0.3, 0.5], "float32"))
    c = Categorical(logits=logits)
    ent = float(c.entropy())
    expect = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
    np.testing.assert_allclose(ent, expect, rtol=1e-5)
    c2 = Categorical(probs=np.array([1 / 3] * 3, "float32"))
    assert float(kl_divergence(c, c2)) > 0
    w = Categorical(logits=np.array([2.0, 3.0, 5.0], "float32"))
    np.testing.assert_allclose(w.probs().numpy(), [0.2, 0.3, 0.5],
                               rtol=1e-6)
    np.testing.assert_allclose(
        w.log_prob(paddle.to_tensor(np.array([2], np.int64))).numpy(),
        [np.log(0.5)], rtol=1e-5)


def test_beta_dirichlet_gamma_laplace():
    b = Beta(2.0, 3.0)
    np.testing.assert_allclose(float(b.mean), 0.4, rtol=1e-6)
    d = Dirichlet(np.array([1.0, 2.0, 3.0], "float32"))
    np.testing.assert_allclose(d.mean.numpy(), [1 / 6, 2 / 6, 3 / 6],
                               rtol=1e-5)
    g = Gamma(2.0, 4.0)
    np.testing.assert_allclose(float(g.mean), 0.5, rtol=1e-6)
    l = Laplace(0.0, 1.0)
    lp = float(l.log_prob(paddle.to_tensor(0.0)))
    np.testing.assert_allclose(lp, -np.log(2.0), rtol=1e-5)
    assert float(kl_divergence(l, Laplace(0.0, 1.0))) == pytest.approx(
        0.0, abs=1e-6)


def test_lognormal_and_transformed_agree():
    paddle.seed(0)
    ln = LogNormal(0.3, 0.4)
    td = TransformedDistribution(Normal(0.3, 0.4), [ExpTransform()])
    x = paddle.to_tensor(np.array([0.5, 1.0, 2.0], "float32"))
    np.testing.assert_allclose(ln.log_prob(x).numpy(),
                               td.log_prob(x).numpy(), rtol=1e-5)


def test_independent_sums_event_dims():
    base = Normal(np.zeros((4, 3), "float32"), np.ones((4, 3), "float32"))
    ind = Independent(base, 1)
    assert ind.batch_shape == (4,) and ind.event_shape == (3,)
    x = paddle.to_tensor(np.zeros((4, 3), "float32"))
    np.testing.assert_allclose(ind.log_prob(x).numpy(),
                               base.log_prob(x).numpy().sum(-1), rtol=1e-6)


def test_register_kl_custom():
    class MyDist(Normal):
        pass

    @register_kl(MyDist, MyDist)
    def _kl_my(p, q):
        return paddle.to_tensor(42.0)

    assert float(kl_divergence(MyDist(0.0, 1.0), MyDist(0.0, 1.0))) == 42.0
    with pytest.raises(NotImplementedError):
        kl_divergence(Uniform(0, 1), Normal(0.0, 1.0))


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------

def test_sparse_coo_roundtrip():
    import paddle_tpu.sparse as sparse

    idx = np.array([[0, 1, 2], [1, 2, 0]], "int64")
    vals = np.array([1.0, 2.0, 3.0], "float32")
    s = sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
    assert s.nnz() == 3 and s.shape == [3, 3]
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), "float32")
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_array_equal(dense, expect)
    # back to sparse
    s2 = sparse.to_sparse_coo(paddle.to_tensor(expect))
    np.testing.assert_array_equal(s2.to_dense().numpy(), expect)


def test_sparse_csr_and_ops():
    import paddle_tpu.sparse as sparse

    crows = np.array([0, 1, 3, 3], "int64")
    cols = np.array([1, 0, 2], "int64")
    vals = np.array([4.0, -1.0, 2.0], "float32")
    s = sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
    np.testing.assert_array_equal(s.crows().numpy(), crows)
    np.testing.assert_array_equal(s.cols().numpy(), cols)
    dense = s.to_dense().numpy()
    assert dense[0, 1] == 4.0 and dense[1, 0] == -1.0 and dense[1, 2] == 2.0

    r = sparse.relu(s)
    assert r.to_dense().numpy().min() >= 0

    y = np.random.randn(3, 2).astype("float32")
    out = sparse.matmul(s, y)
    np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5)


def test_sparse_masked_matmul():
    import paddle_tpu.sparse as sparse

    rng = np.random.RandomState(0)
    a = rng.randn(4, 5).astype("float32")
    b = rng.randn(5, 4).astype("float32")
    mask = sparse.to_sparse_coo(paddle.to_tensor(
        (rng.rand(4, 4) > 0.5).astype("float32")))
    out = sparse.masked_matmul(a, b, mask)
    dense = a @ b
    got = out.to_dense().numpy()
    mask_np = mask.to_dense().numpy() != 0
    np.testing.assert_allclose(got[mask_np], dense[mask_np], rtol=1e-5)
    assert (got[~mask_np] == 0).all()


# ---------------------------------------------------------------------------
# sharded checkpoint with re-shard on load
# ---------------------------------------------------------------------------

def test_sharded_checkpoint_reshard(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    dist.set_mesh(None)
    mesh8 = dist.init_mesh({"dp": 8})
    x = jax.device_put(np.arange(64, dtype=np.float32).reshape(8, 8),
                       NamedSharding(mesh8, P("dp")))
    state = {"w": x, "step": np.int64(7)}
    path = str(tmp_path / "ckpt")
    dist.save_state_dict(state, path)

    # restore onto a DIFFERENT topology: dp2 x mp4, sharded on dim 1
    dist.set_mesh(None)
    mesh24 = dist.init_mesh({"dp": 2, "mp": 4})
    target = {"w": jax.device_put(np.zeros((8, 8), np.float32),
                                  NamedSharding(mesh24, P(None, "mp"))),
              "step": np.int64(0)}
    restored = dist.load_state_dict(path, target=target)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(x))
    assert "mp" in str(restored["w"].sharding.spec)
    assert int(restored["step"]) == 7
    dist.set_mesh(None)


def test_probs_param_and_beta_rsample_differentiable():
    from paddle_tpu.distribution import Bernoulli, Beta

    p = paddle.to_tensor(np.float32(0.3), stop_gradient=False)
    b = Bernoulli(probs=p)
    b.log_prob(paddle.to_tensor(1.0)).backward()
    np.testing.assert_allclose(p.grad.numpy(), 1.0 / 0.3, rtol=1e-4)

    a = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    paddle.seed(0)
    s = Beta(a, 3.0).rsample([16])
    paddle.mean(s).backward()
    assert a.grad is not None and np.isfinite(a.grad.numpy()).all()


def test_geometric_mean_matches_samples():
    from paddle_tpu.distribution import Geometric

    g = Geometric(0.5)
    paddle.seed(0)
    s = g.sample([40000])
    np.testing.assert_allclose(s.numpy().mean(), float(g.mean), atol=0.05)
