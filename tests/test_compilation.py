"""Program registry, AOT warmup & persistent executable store.

The subsystem's contracts (paddle_tpu/compilation/):

- the ProgramRegistry is the ONE table of named program sites — the
  tpulint manifest must enumerate exactly the registry (plus its two
  static reports), so a newly registered program is lint-covered by
  default;
- warmup is idempotent: a second pass over a store-warm directory
  compiles ZERO programs (counter-asserted via the jax.monitoring-fed
  compile counters, not inferred from timings);
- the executable store invalidates explicitly: any key-component
  mismatch (jax version, signature hash, donation) is a miss, corrupt
  entries self-evict;
- a warming PredictorServer truthfully reports warming->ready on
  /healthz and sheds /generate with the 503 contract until its engine
  is compiled;
- Model.fit(warm_start=True) loads a geometry-identical second
  process's train step straight from the store.
"""
import json
import pickle
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.compilation import (BuildResult, counters, log,
                                    registry, warmup)
from paddle_tpu.compilation.registry import (abstract_signature,
                                             signature_hash)
from paddle_tpu.compilation.store import (AotProgram, ExecutableStore,
                                          aot_compile)


@pytest.fixture
def store(tmp_path):
    return ExecutableStore(root=str(tmp_path / "exec"), enabled=True)


def _tiny_jit(scale=2.0):
    import jax

    @jax.jit
    def f(x):
        return x * scale + 1.0

    return f, (np.ones(8, np.float32),)


# ---------------------------------------------------------------------------
# registry <-> tpulint manifest completeness
# ---------------------------------------------------------------------------

class TestRegistry:
    CANONICAL = ["gpt_decode", "llama_prefill", "train_step",
                 "train_step_scan", "parallel_train_step", "gpt_admit",
                 "llama_decode"]

    def test_canonical_sites_registered(self):
        names = registry.names()
        for name in self.CANONICAL:
            assert name in names, f"{name} missing from the registry"

    def test_manifest_is_the_registry(self):
        """tpulint lints exactly the registry's manifest-tagged sites
        (plus the two static recompile reports) — no private rebuild
        list anywhere. A program registered at runtime is covered by
        default."""
        from paddle_tpu.analysis.manifest import (STATIC_REPORTS,
                                                  default_manifest,
                                                  manifest_names)
        assert (set(manifest_names())
                == set(registry.names(tag="manifest"))
                | set(STATIC_REPORTS))
        assert ([s.name for s in default_manifest()]
                == registry.names(tag="manifest"))
        reg = registry.register("t_late_prog",
                                lambda: (_ for _ in ()).throw(
                                    AssertionError("never built")),
                                tags=("manifest",), replace=True)
        try:
            assert reg.name in manifest_names()
        finally:
            registry.unregister("t_late_prog")
        assert "t_late_prog" not in manifest_names()

    def test_duplicate_name_rejected(self):
        registry.register("t_dup", lambda: None, replace=True)
        try:
            with pytest.raises(ValueError, match="already registered"):
                registry.register("t_dup", lambda: None)
        finally:
            registry.unregister("t_dup")

    def test_unknown_name_lists_known(self):
        with pytest.raises(KeyError, match="no registered program"):
            registry.get("t_no_such_program")
        with pytest.raises(ValueError, match="unknown program"):
            warmup(["t_no_such_program"])

    def test_signature_identity(self):
        a = (np.ones((2, 3), np.float32),)
        same = (np.zeros((2, 3), np.float32),)   # values don't matter
        other_shape = (np.ones((3, 2), np.float32),)
        other_dtype = (np.ones((2, 3), np.int32),)
        other_tree = ((np.ones((2, 3), np.float32),),)
        assert abstract_signature(a) == abstract_signature(same)
        assert len({abstract_signature(x) for x in
                    (a, other_shape, other_dtype, other_tree)}) == 4
        # trace-time constants not visible in any aval split the key
        assert signature_hash(a, "cfg-A") != signature_hash(a, "cfg-B")


# ---------------------------------------------------------------------------
# executable store: roundtrip, invalidation, eviction
# ---------------------------------------------------------------------------

class TestStore:
    def test_compile_then_store_hit_zero_compiles(self, store):
        fn, args = _tiny_jit()
        rec1, rec2 = {}, {}
        aot_compile("t_round", fn, args, store=store, log_record=rec1)
        assert rec1["source"] == "compiled"
        assert len(store.entries()) == 1
        fn2, _ = _tiny_jit()      # fresh jit wrapper, same program
        with counters.CompileTracker() as trk:
            aot = aot_compile("t_round", fn2, args, store=store,
                              log_record=rec2)
        assert rec2["source"] == "store"
        assert trk.xla_compiles == 0
        np.testing.assert_allclose(np.asarray(aot(*args)),
                                   np.ones(8) * 2 + 1)

    def test_signature_mismatch_is_a_miss(self, store):
        fn, args = _tiny_jit()
        aot_compile("t_sig", fn, args, store=store)
        other = (np.ones(16, np.float32),)
        assert store.load("t_sig", signature_hash(other), ()) is None
        # same args, different baked config: also a miss
        assert store.load("t_sig", signature_hash(args, "other-cfg"),
                          ()) is None

    def test_different_program_same_avals_is_a_miss(self, store):
        """The key digests the lowered StableHLO, not just the arg
        signature: two different computations over IDENTICAL argument
        avals (same-geometry models with different activations, a loss
        with different baked smoothing) must never alias each other's
        stored executables."""
        fn_a, args = _tiny_jit(scale=2.0)
        aot_compile("t_prog", fn_a, args, store=store)
        fn_b, _ = _tiny_jit(scale=3.0)   # same avals, new baked const
        rec = {}
        aot = aot_compile("t_prog", fn_b, args, store=store,
                          log_record=rec)
        assert rec["source"] != "store"
        np.testing.assert_allclose(np.asarray(aot(*args)),
                                   np.ones(8) * 3 + 1)
        assert len(store.entries()) == 2   # both keys live side by side

    def test_jax_version_mismatch_is_a_miss(self, store):
        fn, args = _tiny_jit()
        aot_compile("t_ver", fn, args, store=store)
        (entry,) = store.entries()
        sig = entry.signature_hash
        with open(entry.path, "rb") as fh:
            header = pickle.load(fh)        # header frame
            rest = fh.read()                # payload frame, untouched
        header["jax_version"] = "0.0.1-stale"
        with open(entry.path, "wb") as fh:
            pickle.dump(header, fh)
            fh.write(rest)
        assert store.load("t_ver", sig, entry.donation) is None
        # ... and stale-only eviction reaps exactly it
        assert store.evict(stale_only=True) == 1
        assert store.entries() == []

    def test_corrupt_entry_self_evicts(self, store):
        fn, args = _tiny_jit()
        aot_compile("t_torn", fn, args, store=store)
        (entry,) = store.entries()
        with open(entry.path, "wb") as fh:
            fh.write(b"torn write, not a pickle")
        assert store.load("t_torn", entry.signature_hash,
                          entry.donation) is None
        assert store.entries() == []    # evicted on touch

    def test_evict_by_name(self, store):
        fn, args = _tiny_jit()
        aot_compile("t_keep", fn, args, store=store)
        aot_compile("t_drop", fn, args, store=store)
        assert store.evict(names=["t_drop"]) == 1
        assert [e.name for e in store.entries()] == ["t_keep"]

    def test_disabled_store_degrades_to_plain_compile(self, tmp_path):
        off = ExecutableStore(root=str(tmp_path / "off"), enabled=False)
        fn, args = _tiny_jit()
        rec = {}
        aot = aot_compile("t_off", fn, args, store=off, log_record=rec)
        assert rec["source"] == "compiled-unstored"
        assert off.entries() == []
        np.testing.assert_allclose(np.asarray(aot(*args)),
                                   np.ones(8) * 2 + 1)

    def test_aot_program_falls_back_on_shape_drift(self, store):
        fn, args = _tiny_jit()
        aot = aot_compile("t_drift", fn, args, store=store)
        assert isinstance(aot, AotProgram)
        drifted = (np.ones(5, np.float32),)
        np.testing.assert_allclose(np.asarray(aot(*drifted)),
                                   np.ones(5) * 2 + 1)
        assert aot._use_fallback      # sticks to the lazy wrapper now
        np.testing.assert_allclose(np.asarray(aot(*args)),
                                   np.ones(8) * 2 + 1)


# ---------------------------------------------------------------------------
# warmup engine: idempotence, counter-asserted
# ---------------------------------------------------------------------------

class TestWarmup:
    @pytest.fixture
    def tiny_program(self):
        def build():
            import jax

            @jax.jit
            def g(x):
                return (x @ x.T).sum()

            return BuildResult(g, (np.ones((4, 4), np.float32),))

        registry.register("t_warm", build, tags=("test",), replace=True)
        yield "t_warm"
        registry.unregister("t_warm")

    def test_warmup_idempotent_second_pass_compiles_zero(
            self, tiny_program, store):
        r1 = warmup([tiny_program], store=store)
        assert r1.ok and r1.compiled == 1 and r1.from_store == 0
        r2 = warmup([tiny_program], store=store)
        assert r2.ok and r2.compiled == 0 and r2.from_store == 1
        # the claim, on the counters: the warm pass never entered
        # XLA's compiler (a store deserialize fires no compile event)
        assert r2["xla_compiles"] == 0
        assert r2["programs"][0]["compile_s"] == 0.0

    def test_warmup_report_records_failures_not_raises(self, store):
        def bad_build():
            raise RuntimeError("builder exploded")

        registry.register("t_bad", bad_build, tags=("test",),
                          replace=True)
        try:
            rep = warmup(["t_bad"], store=store)
        finally:
            registry.unregister("t_bad")
        assert not rep.ok
        (rec,) = rep["programs"]
        assert rec["source"] == "error"
        assert "builder exploded" in rec["error"]

    def test_min_devices_skip(self, store):
        def never():
            raise AssertionError("must not build")

        registry.register("t_big", never, min_devices=10 ** 6,
                          replace=True)
        try:
            rep = warmup(["t_big"], store=store)
        finally:
            registry.unregister("t_big")
        assert rep.ok                       # a skip is not a failure
        assert rep["programs"][0]["source"] == "skipped"

    def test_compile_log_records_and_summary(self, store):
        log.reset()
        fn, args = _tiny_jit()
        aot_compile("t_logged", fn, args, store=store,
                    log_record=log.record({"name": "t_logged_pre"}))
        rec = {}
        aot_compile("t_logged", fn, args, store=store, log_record=rec)
        log.record(rec)
        s = log.summary()
        assert s["programs"] == len(log.records()) >= 2
        assert s["by_source"].get("store", 0) >= 1
        assert s["xla_compiles"] == counters.xla_compiles()


# ---------------------------------------------------------------------------
# serving: /healthz warming -> ready, pre-warm 503 shed
# ---------------------------------------------------------------------------

def _get_json(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestServeWarming:
    def test_healthz_warming_to_ready_and_prewarm_503(
            self, tmp_path, monkeypatch):
        from paddle_tpu.inference.engine import ContinuousBatchingEngine
        from paddle_tpu.inference.serve import PredictorServer
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=128))
        eng = ContinuousBatchingEngine(
            model, slots=2, max_len=32, cache_dtype="float32",
            tick_tokens=4, prefill_buckets=(8,))
        release = threading.Event()
        bench_store = ExecutableStore(root=str(tmp_path / "exec"))
        real_warmup = eng.warmup

        def gated_warmup(*a, **kw):
            assert release.wait(30), "test never released warmup"
            return real_warmup(store=bench_store)

        monkeypatch.setattr(eng, "warmup", gated_warmup)
        srv = PredictorServer(engine=eng, port=0, warmup=True).start()
        url = f"http://{srv.host}:{srv.port}"
        try:
            # truthful readiness: engine programs are NOT compiled yet
            code, body = _get_json(url + "/healthz")
            assert code == 503 and body["status"] == "warming"
            assert body["engine"]["warm"] is False
            # /generate sheds with the 503 contract instead of queueing
            # the request behind the compile
            req = urllib.request.Request(
                url + "/generate",
                json.dumps({"input_ids": [1, 2, 3],
                            "max_new_tokens": 4}).encode(),
                {"Content-Type": "application/json"})
            code, body = _get_json_req(req)
            assert code == 503 and body["error"] == "warming_up"

            release.set()
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                code, body = _get_json(url + "/healthz")
                if body["status"] == "ready":
                    break
                assert body["status"] == "warming"
                time.sleep(0.05)
            assert body["status"] == "ready" and code == 200
            assert body["engine"]["warm"] is True
            # warmup's compile accounting is surfaced on /healthz
            assert body["compilation"]["programs"] >= 2

            code, out = _get_json_req(req)
            assert code == 200 and out["new_tokens"] == 4
        finally:
            srv.stop()
            eng.stop()


def _get_json_req(req, timeout=60):
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# training: fit(warm_start=True) through the store
# ---------------------------------------------------------------------------

class TestFitWarmStart:
    def _model(self):
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.optimizer import AdamW

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        m = Model(net)
        m.prepare(AdamW(learning_rate=1e-3,
                        parameters=net.parameters()),
                  nn.CrossEntropyLoss())
        return m

    @staticmethod
    def _loader():
        rng = np.random.RandomState(0)
        X = rng.randn(32, 16).astype(np.float32)
        Y = rng.randint(0, 4, (32, 1))
        return [(X[i * 8:(i + 1) * 8], Y[i * 8:(i + 1) * 8])
                for i in range(4)]

    def test_second_fit_loads_train_step_from_store(
            self, tmp_path, monkeypatch):
        from paddle_tpu.compilation import store as store_mod
        monkeypatch.setattr(
            store_mod, "_default_store",
            ExecutableStore(root=str(tmp_path / "exec")))
        loader = self._loader()

        log.reset()
        self._model().fit(loader, epochs=1, num_iters=1, verbose=0,
                          warm_start=True)
        first = [r for r in log.records() if r.get("name") == "train_step"]
        assert first and first[-1]["source"] == "compiled"

        # a geometry-identical second model (a fresh process in the
        # bench; here a fresh TrainStep + jit wrapper) warms straight
        # from the store — no XLA compile for the train program
        log.reset()
        m2 = self._model()
        with counters.CompileTracker() as trk:
            m2.fit(loader, epochs=1, num_iters=1, verbose=0,
                   warm_start=True)
        second = [r for r in log.records()
                  if r.get("name") == "train_step"]
        assert second and second[-1]["source"] == "store"
        assert trk.xla_compiles == 0

    def test_warm_start_is_shape_only_training_unchanged(
            self, tmp_path, monkeypatch):
        """warm_start must not consume batches or move optimizer/RNG
        state: losses with and without it are identical."""
        from paddle_tpu.compilation import store as store_mod
        monkeypatch.setattr(
            store_mod, "_default_store",
            ExecutableStore(root=str(tmp_path / "exec")))
        loader = self._loader()
        hist_cold = self._model().fit(loader, epochs=1, verbose=0,
                                      warm_start=False)
        hist_warm = self._model().fit(loader, epochs=1, verbose=0,
                                      warm_start=True)
        p_cold = [np.asarray(t.numpy())
                  for t in hist_cold.network.parameters()]
        p_warm = [np.asarray(t.numpy())
                  for t in hist_warm.network.parameters()]
        for a, b in zip(p_cold, p_warm):
            np.testing.assert_array_equal(a, b)
