"""Resilience subsystem tests (distributed/resilience.py).

Every recovery path the subsystem promises is exercised here under
JAX_PLATFORMS=cpu via FaultInjector: retry/backoff schedules, hang
detection on a wedged (injected) collective, NaN-storm detection,
atomic checkpoint-on-failure with no partial directories, bitwise
crash-resume of step/optimizer/RNG state, elastic DataLoader worker
respawn, and TCPStore host-drop surfacing.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import resilience as resil
from paddle_tpu.distributed.resilience import (
    FaultInjected, FaultInjector, NanInfStorm, RetryPolicy, StepTimeout,
    StepWatchdog, restore_train_state, save_train_state, with_retries)
from paddle_tpu.jit import TrainStep


# ---------------------------------------------------------------------------
# RetryPolicy / with_retries
# ---------------------------------------------------------------------------

def test_retry_schedule_is_exponential_and_capped():
    p = RetryPolicy(max_attempts=6, base_delay=1.0, multiplier=2.0,
                    max_delay=8.0, jitter=0.0)
    assert p.schedule() == (1.0, 2.0, 4.0, 8.0, 8.0)
    assert p.delay(1) == 1.0 and p.delay(10) == 8.0


def test_with_retries_recovers_then_exhausts():
    calls = []

    def flaky(fail_times):
        calls.append(1)
        if len(calls) <= fail_times:
            raise OSError("transient")
        return "ok"

    p = RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0)
    assert with_retries(flaky, 2, policy=p) == "ok"
    assert len(calls) == 3

    calls.clear()
    with pytest.raises(OSError):
        with_retries(flaky, 99, policy=p)
    assert len(calls) == 3  # attempt cap respected


def test_retry_deadline_bounds_wall_clock():
    import time
    p = RetryPolicy(max_attempts=50, base_delay=0.2, multiplier=1.0,
                    jitter=0.0, deadline=0.3)
    t0 = time.monotonic()
    with pytest.raises(ValueError):
        p.run(lambda: (_ for _ in ()).throw(ValueError("x")))
    assert time.monotonic() - t0 < 2.0  # nowhere near 50 * 0.2s


def test_retry_on_filters_exceptions():
    p = RetryPolicy(max_attempts=3, base_delay=0.0, retry_on=(OSError,))
    calls = []

    def boom():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        p.run(boom)
    assert len(calls) == 1


def test_retry_policy_from_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RETRY_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("PADDLE_TPU_RETRY_BASE_DELAY", "0.125")
    monkeypatch.setenv("PADDLE_TPU_RETRY_MAX_DELAY", "nonsense")
    p = RetryPolicy.from_env(max_delay=9.0)
    assert p.max_attempts == 7
    assert p.base_delay == 0.125
    assert p.max_delay == 9.0  # malformed env falls back, never crashes


class _FakeClock:
    """Deterministic clock + sleep pair for RetryPolicy tests: sleeps
    advance the clock, nothing waits on the wall."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def monotonic(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


def test_retry_budget_gives_up_within_deadline_fake_clock():
    """The retry-time budget bounds TOTAL retry time: a storm against a
    dead tier stops within the caller's deadline, not after
    attempts x max_delay (which here would be 50 x 10 = 500 s)."""
    clk = _FakeClock()
    p = RetryPolicy(max_attempts=50, base_delay=2.0, multiplier=2.0,
                    max_delay=10.0, jitter=0.0, deadline=5.0,
                    clock=clk.monotonic, sleep_fn=clk.sleep)
    calls = []

    def dead():
        calls.append(1)
        clk.now += 0.5              # each attempt costs fake wall time
        raise OSError("tier down")

    with pytest.raises(OSError):
        p.run(dead)
    # every sleep was capped to the remaining budget, and the run gave
    # up as soon as the budget was spent — total fake time <= deadline
    # plus the one attempt that discovered the exhaustion
    assert clk.now <= 5.0 + 0.5
    assert 1 < len(calls) < 50


def test_retry_budget_per_run_override_fake_clock():
    clk = _FakeClock()
    p = RetryPolicy(max_attempts=50, base_delay=1.0, multiplier=1.0,
                    jitter=0.0, clock=clk.monotonic, sleep_fn=clk.sleep)

    def dead():
        clk.now += 0.1
        raise OSError("x")

    with pytest.raises(OSError):
        p.run(dead, deadline=2.0)   # caller's remaining budget
    assert clk.now <= 2.0 + 0.1


def test_full_jitter_draws_uniform_below_schedule():
    """Full-jitter sleeps land in [0, delay(attempt)]; the
    deterministic schedule() is unchanged."""
    import random as _random
    clk = _FakeClock()
    p = RetryPolicy(max_attempts=6, base_delay=1.0, multiplier=2.0,
                    max_delay=8.0, full_jitter=True,
                    clock=clk.monotonic, sleep_fn=clk.sleep)
    assert p.schedule() == (1.0, 2.0, 4.0, 8.0, 8.0)
    _random.seed(0)

    def dead():
        raise OSError("x")

    with pytest.raises(OSError):
        p.run(dead)
    assert len(clk.sleeps) == 5
    for slept, ceiling in zip(clk.sleeps, p.schedule()):
        assert 0.0 <= slept <= ceiling
    # across the whole run the draws are not all pinned at the ceiling
    # (the old +/-jitter mode would keep them within 10% of it)
    assert any(s < 0.9 * c for s, c in zip(clk.sleeps, p.schedule()))


def test_router_fault_sites_are_known():
    """The serving-tier sites exist (a typo'd site raises — the
    injection harness's own contract) and fire as crash-type."""
    from paddle_tpu.distributed import resilience as resil
    for site in ("router_forward", "replica_spawn", "replica_health"):
        with FaultInjector({site: 1}):
            with pytest.raises(FaultInjected):
                resil.maybe_inject(site)


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_fault_injector_context_counts():
    assert not resil.should_fire("step_nan")
    with FaultInjector({"step_nan": 2}):
        assert resil.should_fire("step_nan")
        assert resil.should_fire("step_nan")
        assert not resil.should_fire("step_nan")
    assert not resil.should_fire("step_nan")


def test_fault_injector_disarms_unfired_on_exit():
    with FaultInjector({"step_nan": 5}):
        assert resil.should_fire("step_nan")
    assert not resil.should_fire("step_nan")


def test_fault_injector_rejects_typo_site():
    with pytest.raises(ValueError, match="unknown fault-injection site"):
        FaultInjector({"step_nann": 1})
    with pytest.raises(ValueError):
        resil._parse_spec("wedged_colective")


def test_fault_injector_spec_string():
    spec = resil._parse_spec("step_hang:3, collective")
    assert spec == {"step_hang": 3, "collective": 1}


def test_maybe_inject_crash_site_raises():
    with FaultInjector({"ckpt_crash": 1}):
        with pytest.raises(FaultInjected, match="ckpt_crash"):
            resil.maybe_inject("ckpt_crash")


# ---------------------------------------------------------------------------
# StepWatchdog — hang + NaN storm detection
# ---------------------------------------------------------------------------

def test_watchdog_detects_injected_hang_within_deadline():
    import time
    failures = []
    dog = StepWatchdog(deadline=0.4,
                       on_failure=lambda kind, exc: failures.append(kind))
    with FaultInjector({"step_hang": 1}, wedge_s=3.0):
        t0 = time.monotonic()
        with pytest.raises(StepTimeout):
            dog.run(lambda: resil.maybe_inject("step_hang"))
        took = time.monotonic() - t0
    assert took < 2.5, f"detection took {took:.1f}s, wedge is 3s"
    assert failures == ["hang"]
    # the watchdog stays usable after abandoning the wedged worker
    assert dog.run(lambda: 41 + 1) == 42
    dog.close()


def test_watchdog_detects_wedged_collective():
    """The acceptance-criteria scenario: a jitted-step-shaped callable
    wedges inside a collective; the watchdog raises StepTimeout within
    the configured deadline instead of hanging the training loop."""
    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    try:
        x = paddle.to_tensor(np.ones((8, 4), np.float32))

        def step():
            return float(dist.all_reduce(x).numpy().sum())

        dog = StepWatchdog(deadline=0.5)
        assert dog.run(step) > 0  # healthy collective passes through
        with FaultInjector({"collective": 1}, wedge_s=3.0):
            with pytest.raises(StepTimeout):
                dog.run(step)
        dog.close()
    finally:
        dist.set_mesh(None)


def test_watchdog_deadline_covers_lazy_loss_fetch():
    """PR-4 regression guard: jax dispatch is async and the fused
    loop's losses are LAZY, so the supervised callable itself returns
    in microseconds — the deadline must cover the loss FETCH (the
    step's real completion point), which the watchdog runs inside the
    supervised worker. A result whose coercion wedges (= wedged device)
    must raise StepTimeout, not hang the caller."""
    import time

    class WedgedLoss:
        def __array__(self, dtype=None):
            time.sleep(3.0)
            return np.zeros(1, dtype or np.float64)

    dog = StepWatchdog(deadline=0.4)
    t0 = time.monotonic()
    with pytest.raises(StepTimeout):
        dog.run(lambda: WedgedLoss())
    assert time.monotonic() - t0 < 2.5
    # non-numeric results count as ONE finite step: the NaN streak is
    # broken, not paused (pre-fused-loop watchdog contract)
    dog2 = StepWatchdog(deadline=None, nan_limit=2)
    dog2.run(lambda: float("nan"))
    dog2.run(lambda: {"status": "ok"})
    dog2.run(lambda: float("nan"))   # streak is 1, not 2 -> no storm
    assert dog2.nonfinite_streak == 1
    dog.close()


def test_watchdog_nan_storm_and_recovery():
    failures = []
    dog = StepWatchdog(deadline=None, nan_limit=3,
                       on_failure=lambda kind, exc: failures.append(kind))
    dog.run(lambda: float("nan"))
    dog.run(lambda: float("nan"))
    dog.run(lambda: 1.0)           # a finite loss resets the streak
    dog.run(lambda: float("nan"))
    dog.run(lambda: float("inf"))  # inf counts toward the storm too
    with pytest.raises(NanInfStorm):
        dog.run(lambda: float("nan"))
    assert failures == ["nan_storm"]


def test_watchdog_env_arming(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_STEP_TIMEOUT", "12.5")
    dog = StepWatchdog()
    assert dog.deadline == 12.5
    assert StepWatchdog.enabled_by_env()
    monkeypatch.delenv("PADDLE_TPU_STEP_TIMEOUT")
    assert not StepWatchdog.enabled_by_env()


def test_watchdog_env_zero_disables(monkeypatch):
    """PADDLE_TPU_STEP_TIMEOUT=0 means OFF (DataLoader timeout=0
    convention), not an instantly-expiring deadline."""
    monkeypatch.setenv("PADDLE_TPU_STEP_TIMEOUT", "0")
    assert not StepWatchdog.enabled_by_env()
    dog = StepWatchdog()
    assert dog.deadline is None
    assert dog.run(lambda: 1.5) == 1.5  # runs inline, never times out
    monkeypatch.setenv("PADDLE_TPU_STEP_TIMEOUT", "banana")
    assert not StepWatchdog.enabled_by_env()
    assert StepWatchdog().deadline is None


def test_watchdog_propagates_step_exceptions():
    dog = StepWatchdog(deadline=5.0)
    with pytest.raises(ZeroDivisionError):
        dog.run(lambda: 1 / 0)
    dog.close()


# ---------------------------------------------------------------------------
# atomic checkpointing + corruption detection
# ---------------------------------------------------------------------------

def _tiny_step(seed=0):
    paddle.seed(seed)
    m = nn.Linear(4, 3)
    m.weight.name, m.bias.name = "lin.w", "lin.b"
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    return TrainStep(m, lambda out, y: F.mse_loss(out, y), opt)


def _batch(seed=7):
    rng = np.random.RandomState(seed)
    return (paddle.to_tensor(rng.randn(8, 4).astype("float32")),
            paddle.to_tensor(rng.randn(8, 3).astype("float32")))


def test_checkpoint_publish_is_atomic_and_survives_midsave_crash(tmp_path):
    path = str(tmp_path / "ck")
    step = _tiny_step()
    x, y = _batch()
    first = float(step(x, y))
    save_train_state(step, path)
    assert os.path.isdir(path)
    assert not os.path.exists(path + ".tmp")
    assert not os.path.exists(path + ".old")

    # a save killed between shard write and publish left a COMMITTED
    # tmp: the next load repairs the interrupted publish (WAL-style)
    # and restores that state — no committed work is ever stranded
    step(x, y)
    with FaultInjector({"ckpt_crash": 1}):
        with pytest.raises(FaultInjected):
            save_train_state(step, path)
    assert os.path.exists(path + ".tmp")  # the crash window, on disk
    fresh = _tiny_step()
    restore_train_state(fresh, path)
    assert fresh.step_count == 2  # the crashed save's state, recovered
    assert not os.path.exists(path + ".tmp")
    assert not os.path.exists(path + ".old")

    # a kill between the publish's two renames (path moved aside, tmp
    # not yet in place) must also be repaired on the next touch
    os.rename(path, path + ".old")
    fresh2 = _tiny_step()
    restore_train_state(fresh2, path)
    assert fresh2.step_count == 2
    assert not os.path.exists(path + ".old")

    save_train_state(step, path)
    assert not os.path.exists(path + ".tmp")
    assert not os.path.exists(path + ".old")
    fresh3 = _tiny_step()
    restore_train_state(fresh3, path)
    assert fresh3.step_count == 2
    assert np.isfinite(first)  # the run itself was healthy


def test_corrupt_checkpoint_shard_is_refused(tmp_path):
    path = str(tmp_path / "ck")
    step = _tiny_step()
    x, y = _batch()
    step(x, y)
    with FaultInjector({"ckpt_shard": 1}):
        save_train_state(step, path)  # save "succeeds", then corrupts
    with pytest.raises(resil.CheckpointCorrupt, match="commit marker"):
        restore_train_state(_tiny_step(), path)
    with pytest.raises(resil.CheckpointCorrupt):
        dist.verify_checkpoint(path)


def test_missing_checkpoint_names_uncommitted_tmp(tmp_path):
    path = str(tmp_path / "never")
    os.makedirs(path + ".tmp")
    with pytest.raises(resil.CheckpointCorrupt, match="killed mid-write"):
        dist.verify_checkpoint(path)


# ---------------------------------------------------------------------------
# crash-resume: bitwise step/optimizer/RNG round trip (acceptance)
# ---------------------------------------------------------------------------

def test_crash_resume_is_bitwise(tmp_path):
    import jax
    path = str(tmp_path / "ck")
    x, y = _batch()

    # uninterrupted reference trajectory
    ref = _tiny_step(seed=3)
    ref_losses = [float(ref(x, y)) for _ in range(6)]

    # run A: crash (nan storm, injected) after 3 steps under a watchdog
    # whose checkpoint-on-failure writes the atomic train state
    a = _tiny_step(seed=3)
    dog = StepWatchdog(
        deadline=None, nan_limit=2,
        on_failure=lambda kind, exc: save_train_state(a, path))

    def supervised(*batch):
        if resil.should_fire("step_nan"):
            return float("nan")
        return float(a(*batch))

    for _ in range(3):
        dog.run(supervised, x, y)   # healthy steps, no faults armed
    with FaultInjector({"step_nan": 2}):
        with pytest.raises(NanInfStorm):
            for _ in range(3):
                dog.run(supervised, x, y)

    saved_key = np.asarray(jax.random.key_data(
        paddle.framework.random.get_rng_state()))

    # run B: fresh process-equivalent, restore, resume
    b = _tiny_step(seed=99)  # deliberately different init — restore wins
    restore_train_state(b, path)

    assert b.step_count == a.step_count == 3
    assert b.update_count == a.update_count == 3
    # optimizer state bitwise identical leaf-by-leaf
    a_leaves = jax.tree_util.tree_leaves(a.opt_state)
    b_leaves = jax.tree_util.tree_leaves(b.opt_state)
    assert len(a_leaves) == len(b_leaves) > 0
    for la, lb in zip(a_leaves, b_leaves):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    # params bitwise identical
    for n in a.params:
        np.testing.assert_array_equal(np.asarray(a.params[n]),
                                      np.asarray(b.params[n]))
    # RNG key round-tripped through the checkpoint
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(
            paddle.framework.random.get_rng_state())), saved_key)

    # resumed steps reproduce the uninterrupted trajectory exactly
    resumed = [float(b(x, y)) for _ in range(3)]
    np.testing.assert_array_equal(resumed, ref_losses[3:])


# ---------------------------------------------------------------------------
# elastic DataLoader: crashing forked worker respawns, epoch completes
# ---------------------------------------------------------------------------

class _NumpyDataset(paddle.io.Dataset):
    def __init__(self, n=12):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((2,), i, dtype=np.float32)


@pytest.mark.timeout(120)
def test_crashing_worker_respawns_and_epoch_completes():
    from paddle_tpu.io.dataloader import DataLoader
    ds = _NumpyDataset(12)
    with FaultInjector({"dataloader_worker": 1}):
        dl = DataLoader(ds, batch_size=3, num_workers=1,
                        worker_mode="process", use_shared_memory=False,
                        worker_restarts=2)
        batches = [b.numpy() for b in dl]
    got = np.concatenate([b[:, 0] for b in batches])
    np.testing.assert_array_equal(got, np.arange(12, dtype=np.float32))


@pytest.mark.timeout(120)
def test_crashing_worker_without_budget_fails_fast():
    from paddle_tpu.io.dataloader import DataLoader
    ds = _NumpyDataset(8)
    with FaultInjector({"dataloader_worker": 1}):
        dl = DataLoader(ds, batch_size=2, num_workers=1,
                        worker_mode="process", use_shared_memory=False)
        with pytest.raises(RuntimeError, match="worker"):
            list(dl)


def test_thread_mode_fetch_retries_transient_failure():
    from paddle_tpu.io.dataloader import DataLoader

    class Flaky(paddle.io.Dataset):
        def __init__(self):
            self.fails = {3: 1}  # index 3 fails once, then succeeds

        def __len__(self):
            return 6

        def __getitem__(self, i):
            if self.fails.get(i, 0) > 0:
                self.fails[i] -= 1
                raise OSError("transient storage hiccup")
            return np.float32(i)

    dl = DataLoader(Flaky(), batch_size=2, num_workers=2,
                    worker_restarts=2)
    got = sorted(float(v) for b in dl for v in b.numpy())
    assert got == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


# ---------------------------------------------------------------------------
# hapi Model.fit under the watchdog (env-armed)
# ---------------------------------------------------------------------------

def test_fit_loop_runs_under_env_armed_watchdog(monkeypatch, tmp_path):
    """PADDLE_TPU_STEP_TIMEOUT arms the fit loop's StepWatchdog; healthy
    training is unaffected and a diverging run (loss storm) raises
    NanInfStorm after writing the atomic on_failure checkpoint."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.io.dataloader import TensorDataset

    monkeypatch.setenv("PADDLE_TPU_STEP_TIMEOUT", "60")
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
    y = paddle.to_tensor(rng.randn(16, 2).astype("float32"))
    ds = TensorDataset([x, y])

    net = nn.Linear(4, 2)
    model = Model(net)
    model.prepare(paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=net.parameters()),
                  loss=lambda out, y: F.mse_loss(out, y))
    model.fit(ds, batch_size=8, epochs=2, verbose=0)  # healthy: no-op

    # divergence: an absurd LR drives the loss non-finite within a few
    # steps; the watchdog aborts the run and leaves the atomic
    # on_failure snapshot under save_dir
    monkeypatch.setenv("PADDLE_TPU_NAN_LIMIT", "2")
    net2 = nn.Linear(4, 2)
    bad = Model(net2)
    bad.prepare(paddle.optimizer.SGD(learning_rate=1e30,
                                     parameters=net2.parameters()),
                loss=lambda out, y: F.mse_loss(out, y))
    save_dir = str(tmp_path / "ckpt")
    with pytest.raises(NanInfStorm):
        bad.fit(ds, batch_size=8, epochs=50, verbose=0,
                save_dir=save_dir)
    assert os.path.exists(os.path.join(save_dir, "on_failure.pdparams"))
    assert not os.path.exists(
        os.path.join(save_dir, "on_failure.pdparams.tmp"))


# ---------------------------------------------------------------------------
# TCPStore: dropped host surfaces as a rendezvous timeout
# ---------------------------------------------------------------------------

def test_store_host_drop_injection():
    # master+client in one: works on both the native store and the
    # pure-python fallback
    store = dist.TCPStore(port=0, is_master=True, world_size=1,
                          timeout=5.0)
    store.set("alive", b"1")
    assert store.get("alive") == b"1"
    with FaultInjector({"host_drop": 1}):
        with pytest.raises(TimeoutError, match="host dropped"):
            store.get("alive")
    # recovered after the injected drop
    assert store.get("alive") == b"1"


# ---------------------------------------------------------------------------
# LossSpikeDetector: windowed z-score divergence beside the NaN scan
# ---------------------------------------------------------------------------

def test_loss_spike_detector_fires_on_finite_divergence():
    from paddle_tpu.distributed.resilience import (LossSpike,
                                                   LossSpikeDetector)
    det = LossSpikeDetector(window=8, z=4.0, min_points=4)
    for v in (1.0, 1.1, 0.9, 1.0, 1.05):
        det.observe(v)
    with pytest.raises(LossSpike):
        det.observe(50.0)
    # the spiking value never entered the window: normal losses keep
    # flowing, and a COLLAPSING loss is not an incident (one-sided)
    det.observe(1.0)
    det.observe(0.0)


def test_loss_spike_detector_cold_start_and_nonfinite():
    from paddle_tpu.distributed.resilience import LossSpikeDetector
    det = LossSpikeDetector(window=8, z=4.0, min_points=4)
    det.observe(float("nan"))      # the NaN-storm scan owns these
    det.observe(float("inf"))
    det.observe(1.0)
    det.observe(1e9)               # under min_points: cold start swings
    det2 = LossSpikeDetector(window=8, z=4.0, min_points=4)
    for v in (2.0, 2.0, 2.0, 2.0):
        det2.observe(v)
    det2.reset()
    det2.observe(1e9)              # reset forgot the baseline: no fire


def test_replica_stall_site_wedges_not_raises():
    """replica_stall (ISSUE 15) is a WEDGE-type site: the engine's
    decode loop sleeps — latency injection, not death — which is the
    straggler scenario hedged decode exists for. Site-coverage: known,
    armable, sleeps the configured wedge, consumed after one fire."""
    import time as _time
    assert "replica_stall" in resil._KNOWN_SITES
    with resil.FaultInjector({"replica_stall": 1}, wedge_s=0.15):
        t0 = _time.monotonic()
        resil.maybe_inject("replica_stall")     # sleeps, never raises
        assert _time.monotonic() - t0 >= 0.14
        t0 = _time.monotonic()
        resil.maybe_inject("replica_stall")     # count consumed: no-op
        assert _time.monotonic() - t0 < 0.1


def test_arm_fault_programmatic():
    """arm_fault is the /admin/inject face: arms without a context
    manager (chaos tooling wedges LIVE replicas through it)."""
    resil.arm_fault("step_nan", 2)
    try:
        assert resil.should_fire("step_nan")
        assert resil.should_fire("step_nan")
        assert not resil.should_fire("step_nan")
    finally:
        with resil._inject_lock:                # leave no armed residue
            resil._active.pop("step_nan", None)
    with pytest.raises(ValueError, match="unknown fault-injection"):
        resil.arm_fault("replica_stal", 1)


def test_retry_policy_honors_retry_after_hint():
    """A failed attempt whose exception carries retry_after_s (the
    serving tier's relayed Retry-After) makes run() sleep exactly the
    hint — capped by the remaining deadline — instead of the
    full-jitter schedule (ISSUE 15 satellite)."""
    class _Clk:
        def __init__(self):
            self.t = 0.0
            self.sleeps = []

        def clock(self):
            return self.t

        def sleep(self, d):
            self.sleeps.append(d)
            self.t += d

    class _Shed(RuntimeError):
        retry_after_s = 1.75

    clk = _Clk()
    p = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=0.5,
                    full_jitter=True, clock=clk.clock,
                    sleep_fn=clk.sleep)
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise _Shed("shed")
        return "ok"

    assert p.run(fn) == "ok"
    # both backoffs slept the server's hint verbatim, not the
    # full-jitter draw off the 0.05s-base schedule
    assert clk.sleeps == [1.75, 1.75]
    # the hint is still capped by the remaining deadline budget
    clk2 = _Clk()
    p2 = RetryPolicy(max_attempts=3, base_delay=0.05,
                     clock=clk2.clock, sleep_fn=clk2.sleep)
    calls2 = []

    def fn2():
        calls2.append(1)
        raise _Shed("shed")

    with pytest.raises(_Shed):
        p2.run(fn2, deadline=1.0)
    assert clk2.sleeps and max(clk2.sleeps) <= 1.0
    # an unhinted exception keeps the plain schedule
    clk3 = _Clk()
    p3 = RetryPolicy(max_attempts=2, base_delay=0.25, jitter=0.0,
                     clock=clk3.clock, sleep_fn=clk3.sleep)
    with pytest.raises(ValueError):
        p3.run(lambda: (_ for _ in ()).throw(ValueError("x")))
    assert clk3.sleeps == [0.25]


def test_new_fault_sites_are_known():
    for site in ("train_step_nan", "preempt_signal", "ckpt_gc",
                 "ckpt_reshard"):
        assert site in resil._KNOWN_SITES
    assert resil._parse_spec("train_step_nan:3, preempt_signal, ckpt_gc") \
        == {"train_step_nan": 3, "preempt_signal": 1, "ckpt_gc": 1}
    # ckpt_reshard is a crash-type site: it raises, never sleeps
    with resil.FaultInjector({"ckpt_reshard": 1}):
        with pytest.raises(resil.FaultInjected):
            resil.maybe_inject("ckpt_reshard")
        resil.maybe_inject("ckpt_reshard")     # count consumed: no-op
