"""Go inference API (goapi/) — reference goapi role.

When a Go toolchain is present: build libpaddle_capi.so, save a tiny
model, `go run` the demo consumer, and check its output against the
Python predictor. Without Go (this build image), the run test records
an explicit skip — and the static checks below still keep the package
honest (files present, cgo preamble binds only symbols the C ABI
actually exports, demo stays in sync with the header).
"""
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOAPI = os.path.join(ROOT, "goapi")

_GO = shutil.which("go")


def test_goapi_package_is_complete():
    for rel in ("go.mod", "README.md", "paddle/paddle.go",
                "paddle/paddle_c.h", "demo/main.go"):
        assert os.path.exists(os.path.join(GOAPI, rel)), rel


def test_header_matches_c_library_exports():
    """Every PD_* the header declares must exist in native/c_api.cc —
    a drifted header would fail any consumer at link time."""
    with open(os.path.join(GOAPI, "paddle", "paddle_c.h")) as f:
        header_syms = set(re.findall(r"\b(PD_\w+)\s*\(", f.read()))
    with open(os.path.join(ROOT, "native", "c_api.cc")) as f:
        impl = f.read()
    missing = {s for s in header_syms if s + "(" not in impl.replace(" ", "")}
    assert not missing, f"header declares unimplemented symbols: {missing}"
    assert "PD_PredictorRun" in header_syms  # the surface is non-trivial


def test_go_binds_only_declared_symbols():
    with open(os.path.join(GOAPI, "paddle", "paddle_c.h")) as f:
        header_syms = set(re.findall(r"\b(PD_\w+)\s*\(", f.read()))
    with open(os.path.join(GOAPI, "paddle", "paddle.go")) as f:
        used = set(re.findall(r"C\.(PD_\w+)\(", f.read()))
    assert used <= header_syms, used - header_syms


def test_tensor_constructors_guard_empty_slices():
    """NewFloat32Tensor/NewInt64Tensor used to panic on empty slices via
    &data[0]; every constructor that touches &data[0] must carry the
    len-zero guard (unit-tested in paddle/paddle_test.go where a Go
    toolchain exists; this static check keeps the guard from regressing
    in images without one)."""
    with open(os.path.join(GOAPI, "paddle", "paddle.go")) as f:
        src = f.read()
    funcs = re.findall(r"func New\w+Tensor\([^)]*\) Tensor \{.*?\n\}",
                       src, re.S)
    assert len(funcs) >= 2, "tensor constructors not found"
    for fn in funcs:
        if "&data[0]" in fn:
            assert "len(data) == 0" in fn, \
                f"missing empty-slice guard in:\n{fn}"
    assert os.path.exists(os.path.join(GOAPI, "paddle", "paddle_test.go"))


@pytest.mark.skipif(_GO is None, reason="no Go toolchain in this image "
                    "(recorded skip — see goapi/README.md CI status)")
def test_goapi_unit_tests(tmp_path):
    """`go test` over the package's pure-Go surface (tensor packing,
    empty-slice guards). Needs the C library only for linking."""
    from paddle_tpu.inference.c_api import build_c_api
    so = build_c_api()
    assert so, "C API failed to build"
    env = dict(os.environ)
    lib_dir = os.path.dirname(so)
    env["CGO_LDFLAGS"] = (f"-L{lib_dir} -lpaddle_capi "
                          f"-Wl,-rpath,{lib_dir}")
    r = subprocess.run([_GO, "test", "./paddle/..."], cwd=GOAPI, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.skipif(_GO is None, reason="no Go toolchain in this image "
                    "(recorded skip — see goapi/README.md CI status)")
def test_goapi_end_to_end(tmp_path):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.inference.c_api import build_c_api

    so = build_c_api()
    assert so, "C API failed to build"

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([None, 8])])

    rows, cols = 3, 8
    x = (0.01 * np.arange(rows * cols, dtype=np.float32)).reshape(rows,
                                                                  cols)
    ref = m(paddle.to_tensor(x)).numpy()

    env = dict(os.environ)
    lib_dir = os.path.dirname(so)
    env["CGO_LDFLAGS"] = (f"-L{lib_dir} -lpaddle_capi "
                          f"-Wl,-rpath,{lib_dir}")
    r = subprocess.run(
        [_GO, "run", "./demo", path + ".pdmodel", str(rows), str(cols)],
        cwd=GOAPI, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    mline = [ln for ln in r.stdout.splitlines() if "GOAPI_OK" in ln]
    assert mline, r.stdout
    head = [float(v) for v in
            re.search(r"head=\[([^\]]*)\]", mline[0]).group(1).split()]
    np.testing.assert_allclose(head, ref.ravel()[:4], rtol=1e-4,
                               atol=1e-5)
