"""Profiler + nan/inf debugging tests (reference style:
test_profiler.py / check_nan_inf_base.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 benchmark, make_scheduler)


def test_record_event_and_summary(tmp_path):
    prof = Profiler(timer_only=True)
    prof.start()
    with RecordEvent("forward"):
        x = paddle.to_tensor(np.random.randn(64, 64).astype("float32"))
        (paddle.matmul(x, x)).numpy()
    with RecordEvent("forward"):
        paddle.matmul(x, x).numpy()
    with RecordEvent("optimizer"):
        pass
    prof.step()
    prof.step()
    table = prof.summary()
    assert "forward" in table and "optimizer" in table
    path = str(tmp_path / "trace.json")
    prof.export(path)
    prof.stop()
    data = json.load(open(path))
    names = {e["name"] for e in data["traceEvents"]}
    assert {"forward", "optimizer"} <= names


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED


def test_scheduled_profiler_cycles(tmp_path):
    out_dir = str(tmp_path / "chrome")
    from paddle_tpu.profiler import export_chrome_tracing
    prof = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=2,
                                             repeat=1),
                    on_trace_ready=export_chrome_tracing(out_dir),
                    timer_only=True)
    prof.start()
    for _ in range(4):
        with RecordEvent("step"):
            pass
        prof.step()
    prof.stop()
    assert os.path.isdir(out_dir) and os.listdir(out_dir)


def test_benchmark_timer():
    bm = benchmark()
    bm.begin()
    bm.before_reader()
    bm.after_reader()
    bm.after_step(num_samples=32)
    bm.after_step(num_samples=32)
    rep = bm.report()
    bm.end()
    assert rep["steps"] == 2 and rep["ips"] > 0


def test_check_nan_inf_raises():
    from paddle_tpu.framework.nan_inf import (disable_nan_inf_check,
                                              enable_nan_inf_check)
    x = paddle.to_tensor(np.array([1.0, 0.0], "float32"))
    enable_nan_inf_check()
    try:
        with pytest.raises(FloatingPointError, match="log"):
            paddle.log(paddle.to_tensor(np.array([-1.0], "float32")))
        # clean op passes
        paddle.add(x, x)
    finally:
        disable_nan_inf_check()
    # disabled: no error
    paddle.log(paddle.to_tensor(np.array([-1.0], "float32")))


def test_check_nan_inf_log_level():
    from paddle_tpu.framework.nan_inf import (disable_nan_inf_check,
                                              enable_nan_inf_check)
    enable_nan_inf_check(level=1)
    try:
        out = paddle.log(paddle.to_tensor(np.array([-1.0], "float32")))
        assert np.isnan(out.numpy()).all()
    finally:
        disable_nan_inf_check()
