"""Continuous-batching serving engine tests (inference/engine.py).

Key invariants:
- greedy outputs are TOKEN-IDENTICAL to sequential generate() per
  request, across staggered arrivals and mixed lengths (the bucketed
  right-padded prefill and the batched vector-pos decode are pure
  multiplexing, never a numerics change);
- a retired slot's cache rows — including the int8 quantized-cache
  scales — are reset before re-admission;
- the compiled-program count stays constant after warmup no matter how
  many distinct (prompt-len, max-new-tokens) pairs are served;
- the serving layer keeps the PR-1 degradation contract through the
  engine path: 503 `overloaded` on queue saturation, 503
  `backend_unavailable` on the injected dead backend.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.engine import (ContinuousBatchingEngine,
                                         EngineOverloaded,
                                         RequestCancelled)
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def engine(model):
    eng = ContinuousBatchingEngine(
        model, slots=4, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4)
    yield eng
    eng.stop()


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        0, 250, (n,)).astype("int64")


def test_greedy_identity_staggered_mixed_lengths(model, engine):
    """Mixed-length requests submitted at staggered times through 4
    slots come back token-identical to one-at-a-time generate()."""
    import time
    # 8 requests over 4 distinct (P, max_new) pairs: DISTINCT prompts
    # per request (the identity check is per-request content), but the
    # sequential reference compiles only 4 program pairs
    shapes = [(5, 6), (8, 9), (12, 4), (3, 12)] * 2
    prompts = [_prompt(i, p) for i, (p, _) in enumerate(shapes)]
    futs = []
    for (p, n), ids in zip(shapes, prompts):
        futs.append(engine.submit(ids, max_new_tokens=n))
        time.sleep(0.01)          # arrivals land across tick boundaries
    outs = [f.result(timeout=300) for f in futs]
    for (p, n), ids, got in zip(shapes, prompts, outs):
        want = model.generate(ids[None], max_new_tokens=n,
                              cache_dtype="float32")[0]
        np.testing.assert_array_equal(got, want)


def test_greedy_identity_with_eos(model, engine):
    """EOS retirement + eos padding matches generate()'s contract."""
    ids = _prompt(0, 6)
    # eos = whatever greedy emits first, so it fires mid-stream
    first = model.generate(ids[None], max_new_tokens=1,
                           cache_dtype="float32")[0, -1]
    eos = int(first)
    want = model.generate(ids[None], max_new_tokens=10,
                          eos_token_id=eos, cache_dtype="float32")[0]
    got = engine.generate(ids, max_new_tokens=10, eos_token_id=eos,
                          timeout=300)
    np.testing.assert_array_equal(got, want)


def test_program_count_constant_under_shape_drift(model, engine):
    """Workloads whose distinct (prompt-len, max-new-tokens) pairs
    exceed generate()'s program-cache size (16) serve with ZERO
    recompilation after warmup: the trace counters inside the engine's
    jitted bodies must not move."""
    # warmup: every bucket + the decode program
    for p in (4, 12):
        engine.generate(_prompt(p, p), max_new_tokens=3, timeout=300)
    warm = engine.compiled_program_count
    pairs = [(p, n) for p in range(3, 12) for n in (2, 3)]   # 18 > 16
    assert len(pairs) > 16
    futs = [engine.submit(_prompt(i, p), max_new_tokens=n)
            for i, (p, n) in enumerate(pairs)]
    for f in futs:
        f.result(timeout=300)
    assert engine.compiled_program_count == warm, \
        "engine recompiled under shape drift"
    # the sequential path's per-shape LRU was never involved
    assert engine.ticks > 0 and engine.completed >= len(pairs)


def test_slot_reuse_resets_cache_rows_int8(model):
    """A finished slot's cache rows (data AND int8 quantization scales)
    are fully reset before re-admission: after a long request retires
    and a short one reuses the slot, rows past the short request's
    bucket are zero again."""
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="int8",
        prefill_buckets=(8, 16), tick_tokens=4)
    try:
        # int8 identity vs sequential int8 generate (same quantizer)
        ids_long = _prompt(1, 12)
        want = model.generate(ids_long[None], max_new_tokens=8,
                              cache_dtype="int8")[0]
        got = eng.generate(ids_long, max_new_tokens=8, timeout=300)
        np.testing.assert_array_equal(got, want)
        # the long request dirtied rows well past bucket 8 on some slot;
        # drain, then admit short requests into EVERY slot
        shorts = [_prompt(2, 4), _prompt(3, 5)]
        futs = [eng.submit(s, max_new_tokens=2) for s in shorts]
        for f in futs:
            f.result(timeout=300)
        k_cache, v_cache = eng._caches[0]
        for cache in (k_cache, v_cache):
            data = np.asarray(cache["data"])      # [slots, L, nkv, hd]
            scale = np.asarray(cache["scale"])    # [slots, L, nkv]
            # rows the short requests never touched (past bucket 8 +
            # 2 new tokens + tick overshoot) must be zeroed by the
            # admission-time full-row reset — stale int8 payload OR
            # scales from the long request may not survive
            assert (data[:, 16:] == 0).all()
            assert (scale[:, 16:] == 0).all()
    finally:
        eng.stop()


def test_submit_validation(engine):
    with pytest.raises(ValueError):
        engine.submit(np.zeros((0,), np.int64), max_new_tokens=2)
    with pytest.raises(ValueError):
        engine.submit(_prompt(0, 40), max_new_tokens=2)   # > max bucket
    with pytest.raises(ValueError):
        engine.submit(_prompt(0, 4), max_new_tokens=0)
    with pytest.raises(ValueError):
        # prompt + budget + tick overshoot exceeds cache length
        engine.submit(_prompt(0, 16), max_new_tokens=60)


# ---------------------------------------------------------------------------
# cancellation + progress streaming + partial results (ISSUE 15)
# ---------------------------------------------------------------------------

def test_cancel_queued_request_resolves_immediately(model, engine):
    """A QUEUED request cancels without ever touching a slot: its
    future raises RequestCancelled with zero tokens, the partial
    record is present-but-empty, and a second cancel of the same id
    is a no-op (idempotent). Rides the warm module engine — cancel
    must add zero compiles."""
    eng = engine
    hogs = [eng.submit(_prompt(i, 6), max_new_tokens=40,
                       request_id=f"hog{i}")
            for i in range(eng.slots)]
    victim = eng.submit(_prompt(9, 6), max_new_tokens=4,
                        request_id="victim")
    assert eng.cancel("victim") is True
    with pytest.raises(RequestCancelled) as ei:
        victim.result(timeout=60)
    assert ei.value.tokens_generated == 0
    assert victim._ptpu_gen_info == {"tokens_generated": 0,
                                     "partial_tokens": []}
    # idempotent + unknown/None ids are clean no-ops
    assert eng.cancel("victim") is False
    assert eng.cancel("nope") is False
    assert eng.cancel(None) is False
    # the engine is undisturbed: the slot-holders complete
    for f in hogs:
        assert f.result(timeout=300).shape[0] == 6 + 40
    assert eng.stats()["cancelled"] >= 1


def test_cancel_mid_decode_surfaces_greedy_exact_partial(model, engine):
    """Cancelling an ADMITTED request retires it at the next tick
    boundary: the future raises RequestCancelled carrying the partial
    result, and the partial tokens are a bitwise prefix of the
    undisturbed greedy run (the property the router's journal
    reconciliation relies on). The slot frees for new work."""
    eng = engine
    ids = _prompt(2, 6)
    want = model.generate(ids[None], max_new_tokens=48,
                          cache_dtype="float32")[0]
    seen = []
    progressed = threading.Event()

    def cb(toks):
        seen.extend(toks)
        if len(seen) >= 4:
            progressed.set()

    fut = eng.submit(ids, max_new_tokens=48, request_id="mid",
                     progress_cb=cb)
    assert progressed.wait(timeout=300), "no token progress"
    assert eng.cancel("mid") is True
    with pytest.raises(RequestCancelled):
        fut.result(timeout=60)
    info = fut._ptpu_gen_info
    n = info["tokens_generated"]
    assert 4 <= n < 48 + 1
    assert info["partial_tokens"] == want[6:6 + n].tolist()
    # the slot and its future work are reclaimed: engine drains to
    # idle and serves the next request token-identically
    deadline = time.monotonic() + 60
    while eng.stats()["active"] and time.monotonic() < deadline:
        time.sleep(0.02)
    st = eng.stats()
    assert st["active"] == 0 and st["cancelled"] >= 1
    got = eng.generate(ids, max_new_tokens=5, timeout=300)
    np.testing.assert_array_equal(
        got, model.generate(ids[None], max_new_tokens=5,
                            cache_dtype="float32")[0])


def test_progress_cb_streams_exactly_the_generated_tokens(engine):
    """The per-token progress side-channel (the router journal's
    feed) delivers exactly the generated suffix, in order: first
    token at admission, then per tick — concatenated, the blocks ARE
    the new tokens of the final result."""
    ids = _prompt(3, 7)
    seen = []
    fut = engine.submit(ids, max_new_tokens=12,
                        progress_cb=seen.extend)
    out = fut.result(timeout=300)
    info = fut._ptpu_gen_info
    assert info["tokens_generated"] == 12
    assert seen == out[7:7 + 12].tolist()


def test_raising_progress_cb_is_dropped_not_fatal(model, engine):
    """A broken streaming callback is the caller's problem, never the
    engine loop's: it is dropped after the first raise and the request
    (and every other slot) still completes token-identically."""
    calls = []

    def bad(toks):
        calls.append(list(toks))
        raise RuntimeError("broken stream")

    ids = _prompt(4, 6)
    fut = engine.submit(ids, max_new_tokens=8, progress_cb=bad)
    out = fut.result(timeout=300)
    want = model.generate(ids[None], max_new_tokens=8,
                          cache_dtype="float32")[0]
    np.testing.assert_array_equal(out, want)
    assert len(calls) == 1              # dropped after the first raise


def test_engine_failure_path_surfaces_partial_results(model):
    """ISSUE 15 satellite (bugfix): a mid-decode engine fault no
    longer discards the generated tokens — the failing future carries
    ``_ptpu_gen_info`` (tokens_generated + partial_tokens, a greedy-
    exact prefix) so a router journal can reconcile against engine
    truth."""
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="float32",
        prefill_buckets=(8,), tick_tokens=2)
    try:
        ids = _prompt(5, 6)
        want = model.generate(ids[None], max_new_tokens=48,
                              cache_dtype="float32")[0]
        progressed = threading.Event()
        seen = []

        def cb(toks):
            seen.extend(toks)
            if len(seen) >= 3:
                progressed.set()

        fut = eng.submit(ids, max_new_tokens=48, progress_cb=cb)
        assert progressed.wait(timeout=300), "no token progress"

        def boom():
            raise RuntimeError("injected mid-decode fault")

        eng._tick = boom                 # the next loop pass dies
        with pytest.raises(RuntimeError, match="mid-decode fault"):
            fut.result(timeout=60)
        info = fut._ptpu_gen_info
        n = info["tokens_generated"]
        assert n >= 3
        assert info["partial_tokens"] == want[6:6 + n].tolist()
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# serving layer: /generate through the engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gen_server(engine):
    from paddle_tpu.inference.serve import PredictorServer
    srv = PredictorServer(engine=engine, port=0).start()
    yield srv
    srv.stop()


def _req(srv, path, payload=None):
    url = f"http://{srv.host}:{srv.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_generate_route_matches_generate(model, gen_server):
    srv = gen_server
    ids = _prompt(5, 9)
    code, body = _req(srv, "/generate", {"input_ids": ids.tolist(),
                                         "max_new_tokens": 6})
    assert code == 200, body
    want = model.generate(ids[None], max_new_tokens=6,
                          cache_dtype="float32")[0]
    assert body["tokens"] == want.tolist()
    assert body["prompt_len"] == 9 and body["new_tokens"] == 6
    # generation accounting (ISSUE 13 satellite): the always-present
    # field on a plain engine, with no speculative fields leaking in
    assert body["tokens_generated"] == 6
    assert "tokens_drafted" not in body
    assert "tokens_accepted" not in body


def test_healthz_reports_slot_occupancy(gen_server):
    code, body = _req(gen_server, "/healthz")
    assert code == 200, body
    eng = body["engine"]
    assert eng["slots"] == 4
    assert {"active", "free", "queued", "max_queue",
            "compiled_programs"} <= set(eng)


def test_queue_overflow_returns_503_overloaded(gen_server):
    """The PR-1 load-shedding record shape survives the engine path."""
    srv = gen_server
    eng = srv.engine
    old = eng.max_queue
    eng.max_queue = 0
    try:
        code, body = _req(srv, "/generate",
                          {"input_ids": [1, 2, 3],
                           "max_new_tokens": 4})
        assert code == 503, body
        assert body["error"] == "overloaded"
        assert "queue_depth" in body
        # direct submit sees the typed exception
        with pytest.raises(EngineOverloaded):
            eng.submit([1, 2, 3], max_new_tokens=4)
    finally:
        eng.max_queue = old


def test_dead_backend_surfaces_through_engine_path(gen_server):
    from paddle_tpu.distributed.resilience import FaultInjector
    srv = gen_server
    with FaultInjector({"serve_backend": 1}):
        code, body = _req(srv, "/generate",
                          {"input_ids": [1, 2, 3],
                           "max_new_tokens": 4})
    assert code == 503, body
    assert "backend_unavailable" in body["error"]
    # engine recovered: the next request serves normally
    code, body = _req(srv, "/generate",
                      {"input_ids": [1, 2, 3], "max_new_tokens": 4})
    assert code == 200, body


def test_config_create_predictor_surface(model):
    """Config.enable_continuous_batching -> create_predictor returns the
    engine-backed predictor (the reference's multi-stream Predictor
    usage ports to this surface, MIGRATING.md)."""
    from paddle_tpu.inference import Config, create_predictor
    cfg = Config()
    cfg.enable_continuous_batching(model=model, slots=2, max_len=64,
                                   cache_dtype="float32",
                                   prefill_buckets=(8,), tick_tokens=4)
    pred = create_predictor(cfg)
    try:
        assert pred.get_input_names() == ["input_ids"]
        ids = _prompt(8, 6)
        got = pred.generate(ids, max_new_tokens=4, timeout=300)
        want = model.generate(ids[None], max_new_tokens=4,
                              cache_dtype="float32")[0]
        np.testing.assert_array_equal(got, want)
    finally:
        pred.close()

    cfg2 = Config()
    cfg2.enable_continuous_batching(model=None)
    with pytest.raises(ValueError):
        create_predictor(cfg2)
