"""EQuARX-style quantized all-reduce tests (PAPERS.md arXiv 2506.17615;
SURVEY.md §5.8 quantized-allreduce option)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.quantized import quantized_all_reduce


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def test_two_hop_error_bound_integers():
    """Int payload: hop 1 (scale 1) is exact; hop 2 re-quantizes the sums
    (scale = sum_max/127), so the total error is bounded by sum_max/254
    per element — verify both facts."""
    dist.init_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randint(-100, 101, (8, 64)).astype(np.float32)
    x[:, 0] = 127.0   # pin block max so hop-1 scale is exactly 1
    got = quantized_all_reduce(paddle.to_tensor(x.copy()),
                               block=64).numpy()
    want = x.sum(0)
    hop2_bound = np.abs(want).max() / 254 + 1e-5
    assert np.abs(got[0] - want).max() <= hop2_bound
    # every replica row identical (all-reduce semantics)
    assert (got == got[0]).all()


def test_error_bounded_vs_exact():
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(1)
    x = rng.randn(4, 1000).astype(np.float32)
    exact = dist.all_reduce(paddle.to_tensor(x.copy())).numpy()
    approx = quantized_all_reduce(paddle.to_tensor(x.copy()),
                                  block=250).numpy()
    # hop 1: N contributions each bounded by input block_max/254;
    # hop 2: bounded by the REDUCED sum's block max / 254
    n = 4
    bound = (n * np.abs(x).max() / 254
             + np.abs(exact).max() / 254 + 1e-5)
    assert np.abs(approx - exact).max() <= bound, (
        np.abs(approx - exact).max(), bound)
    # and it is genuinely close in relative terms
    rel = np.abs(approx - exact).max() / (np.abs(exact).max() + 1e-9)
    assert rel < 0.05, rel


def test_shapes_and_padding():
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(2)
    # size 77 not divisible by ranks or block: exercises padding
    x = rng.randn(4, 7, 11).astype(np.float32)
    got = quantized_all_reduce(paddle.to_tensor(x.copy()),
                               block=32).numpy()
    assert got.shape == (4, 7, 11)
    exact = x.sum(0)
    rel = np.abs(got[0] - exact).max() / np.abs(exact).max()
    assert rel < 0.05


def test_zero_blocks_stay_zero():
    dist.init_mesh({"dp": 4})
    x = np.zeros((4, 128), np.float32)
    got = quantized_all_reduce(paddle.to_tensor(x)).numpy()
    assert (got == 0).all()
