"""EQuARX-style quantized collective tests (PAPERS.md arXiv 2506.17615;
SURVEY.md §5.8 quantized-allreduce option): all-reduce plus the
reduce-scatter / all-gather bodies the quantized ZeRO train step
(ISSUE 17) is built from."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.quantized import (_quantize, quantized_all_gather,
                                              quantized_all_reduce,
                                              quantized_reduce_scatter)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def test_two_hop_error_bound_integers():
    """Int payload: hop 1 (scale 1) is exact; hop 2 re-quantizes the sums
    (scale = sum_max/127), so the total error is bounded by sum_max/254
    per element — verify both facts."""
    dist.init_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    x = rng.randint(-100, 101, (8, 64)).astype(np.float32)
    x[:, 0] = 127.0   # pin block max so hop-1 scale is exactly 1
    got = quantized_all_reduce(paddle.to_tensor(x.copy()),
                               block=64).numpy()
    want = x.sum(0)
    hop2_bound = np.abs(want).max() / 254 + 1e-5
    assert np.abs(got[0] - want).max() <= hop2_bound
    # every replica row identical (all-reduce semantics)
    assert (got == got[0]).all()


def test_error_bounded_vs_exact():
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(1)
    x = rng.randn(4, 1000).astype(np.float32)
    exact = dist.all_reduce(paddle.to_tensor(x.copy())).numpy()
    approx = quantized_all_reduce(paddle.to_tensor(x.copy()),
                                  block=250).numpy()
    # hop 1: N contributions each bounded by input block_max/254;
    # hop 2: bounded by the REDUCED sum's block max / 254
    n = 4
    bound = (n * np.abs(x).max() / 254
             + np.abs(exact).max() / 254 + 1e-5)
    assert np.abs(approx - exact).max() <= bound, (
        np.abs(approx - exact).max(), bound)
    # and it is genuinely close in relative terms
    rel = np.abs(approx - exact).max() / (np.abs(exact).max() + 1e-9)
    assert rel < 0.05, rel


def test_shapes_and_padding():
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(2)
    # size 77 not divisible by ranks or block: exercises padding
    x = rng.randn(4, 7, 11).astype(np.float32)
    got = quantized_all_reduce(paddle.to_tensor(x.copy()),
                               block=32).numpy()
    assert got.shape == (4, 7, 11)
    exact = x.sum(0)
    rel = np.abs(got[0] - exact).max() / np.abs(exact).max()
    assert rel < 0.05


def test_zero_blocks_stay_zero():
    dist.init_mesh({"dp": 4})
    x = np.zeros((4, 128), np.float32)
    got = quantized_all_reduce(paddle.to_tensor(x)).numpy()
    assert (got == 0).all()


# ---------------------------------------------------------------------------
# reduce-scatter / all-gather (the ZeRO train-step building blocks,
# ISSUE 17): stacked [N, *S] convention like collective.all_reduce
# ---------------------------------------------------------------------------

def test_quantize_scale_shapes_and_roundtrip():
    """The wire format itself: q is int8 with one f32 scale per block,
    and integer payloads whose block max is exactly 127 round-trip
    bitwise (scale 1)."""
    import jax.numpy as jnp
    from paddle_tpu.distributed.quantized import _dequantize
    x = np.arange(-127, 385, dtype=np.float32)          # 512 elements
    x = np.clip(x, -127, 127)
    q, s = _quantize(jnp.asarray(x), 128, 127.0)
    assert q.dtype == jnp.int8 and q.shape == (512,)
    assert s.dtype == jnp.float32 and s.shape == (4,)   # 512 / 128
    back = np.asarray(_dequantize(q, s, 128))
    assert np.array_equal(back, x)                      # scale exactly 1


def test_reduce_scatter_padded_tail():
    """Chunk size 2*33=66 is not a multiple of block 64: the zero-padded
    tail blocks must not perturb the real elements (error stays within
    the single-rounding bound of the UNPADDED payload)."""
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(3)
    x = rng.randn(4, 8, 33).astype(np.float32)
    got = quantized_reduce_scatter(paddle.to_tensor(x.copy()),
                                   block=64, dim=0).numpy()
    assert got.shape == (4, 2, 33)
    want = x.sum(0)                                     # [8, 33]
    n = 4
    bound = n * np.abs(x).max() / 254 + 1e-5            # one hop, N terms
    for k in range(n):
        chunk = want[2 * k:2 * (k + 1)]
        assert np.abs(got[k] - chunk).max() <= bound


def test_reduce_scatter_integer_exact_at_block_edge():
    """Integer partials with every block max pinned at 127 and the
    per-rank chunk exactly one scale block: scale is 1, the single
    rounding is exact, and the f32 accumulate makes the scattered sums
    bitwise-equal to the true sums."""
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(4)
    x = rng.randint(-100, 101, (4, 1024)).astype(np.float32)
    x[:, ::256] = 127.0                                 # pin block scales
    got = quantized_reduce_scatter(paddle.to_tensor(x.copy()),
                                   block=256, dim=0).numpy()
    want = x.sum(0).reshape(4, 256)
    assert np.array_equal(got, want)


def test_reduce_scatter_rejects_indivisible_dim():
    dist.init_mesh({"dp": 4})
    x = np.zeros((4, 7, 8), np.float32)
    with pytest.raises(ValueError):
        quantized_reduce_scatter(paddle.to_tensor(x), dim=0)


def test_all_gather_roundtrip_rows_identical():
    """Each rank contributes a distinct shard; the gathered result must
    concatenate them along dim with one bounded rounding per element,
    and every output row must be the identical full tensor."""
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(5)
    x = rng.randn(4, 5, 7).astype(np.float32)
    got = quantized_all_gather(paddle.to_tensor(x.copy()),
                               block=32, dim=0).numpy()
    assert got.shape == (4, 20, 7)
    assert (got == got[0]).all()                        # AG semantics
    want = x.reshape(20, 7)                             # concat along dim 0
    bound = np.abs(x).max() / 254 + 1e-6                # one rounding
    assert np.abs(got[0] - want).max() <= bound


def test_all_gather_integer_exact():
    """Block-edge shard (size == block) of ints with the scale pinned to
    1: the gather must be bitwise."""
    dist.init_mesh({"dp": 4})
    rng = np.random.RandomState(6)
    x = rng.randint(-127, 128, (4, 256)).astype(np.float32)
    x[:, 0] = 127.0
    got = quantized_all_gather(paddle.to_tensor(x.copy()),
                               block=256, dim=0).numpy()
    assert np.array_equal(got[0], x.reshape(-1))
