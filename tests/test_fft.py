"""paddle.fft tests (reference: python/paddle/fft.py) — numpy parity for
every exported function + gradient flow + norm modes."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft as pfft


@pytest.fixture
def xc():
    rng = np.random.RandomState(0)
    return (rng.randn(4, 8) + 1j * rng.randn(4, 8)).astype(np.complex64)


@pytest.fixture
def xr():
    return np.random.RandomState(1).randn(4, 8).astype(np.float32)


def test_1d_family(xc, xr):
    np.testing.assert_allclose(pfft.fft(paddle.to_tensor(xc)).numpy(),
                               np.fft.fft(xc), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.ifft(paddle.to_tensor(xc)).numpy(),
                               np.fft.ifft(xc), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.rfft(paddle.to_tensor(xr)).numpy(),
                               np.fft.rfft(xr), rtol=1e-4, atol=1e-4)
    spec = np.fft.rfft(xr)
    np.testing.assert_allclose(
        pfft.irfft(paddle.to_tensor(spec.astype(np.complex64))).numpy(),
        np.fft.irfft(spec), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.hfft(paddle.to_tensor(xc)).numpy(),
                               np.fft.hfft(xc), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(pfft.ihfft(paddle.to_tensor(xr)).numpy(),
                               np.fft.ihfft(xr), rtol=1e-4, atol=1e-4)


def test_nd_family(xc, xr):
    for name in ("fft2", "ifft2", "fftn", "ifftn"):
        got = getattr(pfft, name)(paddle.to_tensor(xc)).numpy()
        want = getattr(np.fft, name)(xc)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.rfft2(paddle.to_tensor(xr)).numpy(),
                               np.fft.rfft2(xr), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.rfftn(paddle.to_tensor(xr)).numpy(),
                               np.fft.rfftn(xr), rtol=1e-4, atol=1e-4)
    spec2 = np.fft.rfft2(xr).astype(np.complex64)
    np.testing.assert_allclose(pfft.irfft2(paddle.to_tensor(spec2)).numpy(),
                               np.fft.irfft2(spec2), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(pfft.irfftn(paddle.to_tensor(spec2)).numpy(),
                               np.fft.irfftn(spec2), rtol=1e-4, atol=1e-4)


def test_hermitian_nd(xc, xr):
    # hfft2/hfftn: Hermitian on last axis, complex on the rest (numpy def)
    want = np.fft.fft(xc, axis=-2)
    want = np.fft.hfft(want, axis=-1)
    np.testing.assert_allclose(pfft.hfft2(paddle.to_tensor(xc)).numpy(),
                               want, rtol=1e-3, atol=1e-3)
    wantn = np.fft.ifft(np.fft.ihfft(xr, axis=-1), axis=-2)
    np.testing.assert_allclose(pfft.ihfftn(paddle.to_tensor(xr)).numpy(),
                               wantn, rtol=1e-4, atol=1e-4)


def test_helpers_and_norm(xr):
    np.testing.assert_allclose(pfft.fftfreq(8, d=0.5).numpy(),
                               np.fft.fftfreq(8, d=0.5), rtol=1e-6)
    np.testing.assert_allclose(pfft.rfftfreq(8).numpy(),
                               np.fft.rfftfreq(8), rtol=1e-6)
    np.testing.assert_allclose(
        pfft.fftshift(paddle.to_tensor(xr)).numpy(),
        np.fft.fftshift(xr), rtol=1e-6)
    np.testing.assert_allclose(
        pfft.ifftshift(paddle.to_tensor(xr)).numpy(),
        np.fft.ifftshift(xr), rtol=1e-6)
    np.testing.assert_allclose(
        pfft.fft(paddle.to_tensor(xr), norm="ortho").numpy(),
        np.fft.fft(xr, norm="ortho"), rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError, match="norm"):
        pfft.fft(paddle.to_tensor(xr), norm="bogus")


def test_gradient_flow(xr):
    t = paddle.to_tensor(xr)
    t.stop_gradient = False
    import paddle_tpu.tensor as T
    power = T.mean(T.abs(pfft.rfft(t)) ** 2)
    power.backward()
    g = np.asarray(t._grad)
    # Parseval: d/dx mean|rfft(x)|^2 is linear in x, nonzero
    assert g.shape == xr.shape and np.abs(g).sum() > 0
