"""LocalSGD tests (reference: meta_optimizers/localsgd_optimizer.py).

Key invariants:
- k_steps=1 equals synchronous DP averaging every step: parameter
  trajectory matches plain data-parallel SGD... not exactly (average of
  updates vs update of average differ for nonlinear opt), but for plain
  SGD on the SAME per-replica data they coincide exactly when every
  replica sees the same batch.
- replicas genuinely diverge between averaging points and re-converge at
  the averaging step.
- training reduces the loss.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.localsgd import LocalSGDStep


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _data(seed, n=32, din=8, dout=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, din).astype("float32")
    w = rng.randn(din, dout).astype("float32")
    y = (x @ w).astype("float32")
    return x, y


def test_replicas_diverge_then_average():
    dist.init_mesh({"dp": 4})
    paddle.seed(0)
    m = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
    step = LocalSGDStep(m, lambda o, y: F.mse_loss(o, y), opt, k_steps=3)
    x, y = _data(1)
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)

    losses = [float(step(xt, yt))]           # step 1: local
    w = np.asarray(step.params["weight"])
    spread1 = np.abs(w - w.mean(0, keepdims=True)).max()
    assert spread1 > 0        # different dp shards saw different batches

    losses.append(float(step(xt, yt)))       # step 2: local
    losses.append(float(step(xt, yt)))       # step 3: averaged
    w3 = np.asarray(step.params["weight"])
    spread3 = np.abs(w3 - w3.mean(0, keepdims=True)).max()
    assert spread3 < 1e-6     # replicas identical right after averaging

    for _ in range(12):
        losses.append(float(step(xt, yt)))
    assert losses[-1] < losses[0], losses


def test_k1_same_batch_matches_plain_sgd():
    """With identical per-replica batches and SGD, LocalSGD(k=1) equals
    single-replica SGD exactly (average of equal updates)."""
    x, y = _data(2, n=8)
    xrep = np.tile(x, (4, 1))       # every dp shard gets the same 8 rows
    yrep = np.tile(y, (4, 1))

    dist.init_mesh({"dp": 4})
    paddle.seed(3)
    m1 = nn.Linear(8, 4)
    o1 = paddle.optimizer.SGD(learning_rate=0.05,
                              parameters=m1.parameters())
    ls = LocalSGDStep(m1, lambda o, t: F.mse_loss(o, t), o1, k_steps=1)
    for _ in range(5):
        ls(paddle.to_tensor(xrep), paddle.to_tensor(yrep))
    ls.sync_to_model()

    dist.set_mesh(None)
    paddle.seed(3)
    m2 = nn.Linear(8, 4)
    o2 = paddle.optimizer.SGD(learning_rate=0.05,
                              parameters=m2.parameters())
    for _ in range(5):
        loss = F.mse_loss(m2(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        o2.step()
        o2.clear_grad()

    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_rejects_model_parallel_mesh():
    dist.init_mesh({"dp": 2, "mp": 4})
    paddle.seed(4)
    m = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    with pytest.raises(ValueError, match="mp"):
        LocalSGDStep(m, lambda o, y: F.mse_loss(o, y), opt)


def test_sync_to_model_writes_average():
    dist.init_mesh({"dp": 4})
    paddle.seed(5)
    m = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
    step = LocalSGDStep(m, lambda o, y: F.mse_loss(o, y), opt, k_steps=10)
    x, y = _data(6)
    step(paddle.to_tensor(x), paddle.to_tensor(y))   # replicas diverged
    want = np.asarray(step.averaged_params()["weight"])
    step.sync_to_model()
    np.testing.assert_allclose(m.weight.numpy(), want, rtol=1e-6)
