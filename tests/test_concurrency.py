"""tpurace concurrency tooling tests (ISSUE 18).

Three layers, zero device work in any of them:

* the static lock-discipline lint (paddle_tpu.analysis.concurrency) on
  tmp_path fixture snippets — guarded-attribute inference, cross-class
  typed accesses, suppression comments, the *_locked convention, the
  static lock-order cycle, check-then-act, orphan threads, and the
  lint-error path for unparseable files;
* the runtime lock sanitizer (paddle_tpu.obs.locks) — plain primitives
  when off, hold/wait histograms, the lock-order-cycle flight
  artifact, the deadlock watchdog artifact, and the resilience
  ``lock_hold`` fault site;
* a host-only smoke of the schedule-fuzzing hammers
  (tools/race_hunt.py) — the journal/QoS/metrics hammers must run
  clean with the sanitizer on.

Registered in tools/ci.py --quick.
"""
import glob
import importlib.util
import json
import os
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.analysis.concurrency import (collect_classes,
                                             lint_concurrency_file,
                                             lint_concurrency_paths)
from paddle_tpu.analysis.findings import (RACE_BLOCKING_UNDER_LOCK,
                                          RACE_CHECK_THEN_ACT,
                                          RACE_LOCK_ORDER,
                                          RACE_ORPHAN_THREAD,
                                          RACE_UNGUARDED_ATTR)
from paddle_tpu.obs import locks as L
from paddle_tpu.obs.metrics import registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture(tmp_path, src: str) -> str:
    p = tmp_path / "fix.py"
    p.write_text(textwrap.dedent(src))
    return str(p)


def _by_code(findings, code):
    return [f for f in findings if f.code == code]


# ---------------------------------------------------------------------------
# static lint: guarded-attribute inference
# ---------------------------------------------------------------------------

GUARDED_SRC = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []
            self.n = 0

        def put(self, x):
            with self._lock:
                self.items.append(x)
                self.n += 1

        def peek(self):
            return self.items[-1] if self.items else None
"""


def test_unguarded_access_flagged(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, GUARDED_SRC), str(tmp_path))
    hits = _by_code(fs, RACE_UNGUARDED_ATTR)
    assert len(hits) == 1
    f = hits[0]
    assert f.site == "C::items"               # aggregated per attr
    assert f.data["count"] == 2               # two reads in peek()
    assert f.data["methods"] == ["peek"]
    assert "written under _lock" in f.message


def test_collect_classes_inventory(tmp_path):
    p = _fixture(tmp_path, GUARDED_SRC)
    classes = collect_classes([p], str(tmp_path))
    c = classes["C"]
    assert c.lock_attrs == {"_lock"}
    assert c.guarded == {"items", "n"}        # append + += under lock
    assert c.method_locks["put"] == {"_lock"}


def test_locked_accesses_clean(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def put(self, x):
                with self._lock:
                    self.items.append(x)

            def peek(self):
                with self._lock:
                    return list(self.items)
    """), str(tmp_path))
    assert not fs


def test_sanitizer_factory_counts_as_lock(tmp_path):
    # make_lock adoption must not blind the lint to the lock attr
    fs = lint_concurrency_file(_fixture(tmp_path, """
        from paddle_tpu.obs import locks

        class C:
            def __init__(self):
                self._lock = locks.make_lock("c.lock")
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def read(self):
                return self.n
    """), str(tmp_path))
    assert [f.site for f in _by_code(fs, RACE_UNGUARDED_ATTR)] == ["C::n"]


def test_cross_class_typed_access_flagged(tmp_path):
    # j.tokens touched in ANOTHER class without j.cond: same finding,
    # attributed to the owning class (the _StreamAttempt.run shape)
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class J:
            def __init__(self):
                self.cond = threading.Condition()
                self.tokens = []

            def extend(self, t):
                with self.cond:
                    self.tokens.append(t)

        class W:
            def __init__(self, j: "J"):
                self.j = j

            def snap_bad(self):
                return list(self.j.tokens)

            def snap_good(self):
                with self.j.cond:
                    return list(self.j.tokens)
    """), str(tmp_path))
    hits = _by_code(fs, RACE_UNGUARDED_ATTR)
    assert len(hits) == 1
    assert hits[0].site == "J::tokens"
    assert hits[0].data["methods"] == ["snap_bad"]


def test_suppression_comment(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def read(self):
                return self.n  # tpurace: disable=race-unguarded-attr

            def read2(self):
                return self.n  # tpurace: disable
    """), str(tmp_path))
    assert not _by_code(fs, RACE_UNGUARDED_ATTR)


def test_locked_suffix_exempt_but_blocking_checked(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def _flush_locked(self):
                self.n = 0          # caller holds the lock: exempt
                time.sleep(0.1)     # ...but still blocking-under-lock
    """), str(tmp_path))
    assert not _by_code(fs, RACE_UNGUARDED_ATTR)
    blocks = _by_code(fs, RACE_BLOCKING_UNDER_LOCK)
    assert len(blocks) == 1
    assert blocks[0].site == "C::_flush_locked::time.sleep"
    assert "C._lock" in blocks[0].data["held"]


def test_blocking_under_lock(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def slow(self, fut):
                with self._lock:
                    time.sleep(1.0)
                    self.n = fut.result()
    """), str(tmp_path))
    sites = {f.site for f in _by_code(fs, RACE_BLOCKING_UNDER_LOCK)}
    assert sites == {"C::slow::time.sleep", "C::slow::result"}


LOCK_ORDER_CYCLE_SRC = """
    import threading

    class B:
        def __init__(self, a: "A"):
            self._lock = threading.Lock()
            self.a = a

        def hit(self):
            with self._lock:
                with self.a._lock:
                    pass

    class A:
        def __init__(self):
            self._lock = threading.Lock()
            self.b = B(self)

        def go(self):
            with self._lock:
                with self.b._lock:
                    pass
"""


def test_lock_order_cycle_detected(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, LOCK_ORDER_CYCLE_SRC),
                               str(tmp_path))
    cyc = _by_code(fs, RACE_LOCK_ORDER)
    assert len(cyc) == 1
    assert cyc[0].severity == "error"
    assert "A._lock" in cyc[0].site and "B._lock" in cyc[0].site
    # edge provenance names the method that took the second lock
    assert any("A::go" in e or "B::hit" in e for e in cyc[0].data["edges"])


def test_lock_order_acyclic_clean(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def go(self):
                with self._lock:
                    with self.b._lock:
                        pass

            def go2(self):
                with self._lock:
                    with self.b._lock:
                        pass
    """), str(tmp_path))
    assert not _by_code(fs, RACE_LOCK_ORDER)


def test_check_then_act(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.prog = None

            def ensure(self):
                if self.prog is None:
                    self.prog = object()

            def ensure_safe(self):
                with self._lock:
                    if self.prog is None:
                        self.prog = object()
    """), str(tmp_path))
    hits = _by_code(fs, RACE_CHECK_THEN_ACT)
    assert [f.site for f in hits] == ["C::ensure::prog"]


def test_orphan_thread(tmp_path):
    fs = lint_concurrency_file(_fixture(tmp_path, """
        import threading

        class Bad:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

        class Joined:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()

            def stop(self):
                self._t.join()

        class Daemonic:
            def start(self):
                self._t = threading.Thread(target=print, daemon=True)
                self._t.start()
    """), str(tmp_path))
    hits = _by_code(fs, RACE_ORPHAN_THREAD)
    assert [f.site for f in hits] == ["Bad::start"]


def test_syntax_error_is_lint_error(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    fs = lint_concurrency_paths([str(p)], str(tmp_path))
    assert [f.code for f in fs] == ["lint-error"]


def test_real_tree_engine_class_stays_clean():
    # the baseline must_stay_clean anchors in miniature: the engine
    # file alone must produce no unguarded-attr findings for the
    # ContinuousBatchingEngine class (the races fixed in this PR)
    path = os.path.join(ROOT, "paddle_tpu", "inference", "engine.py")
    fs = lint_concurrency_file(path, ROOT)
    bad = [f for f in _by_code(fs, RACE_UNGUARDED_ATTR)
           if f.site.startswith("ContinuousBatchingEngine::")]
    assert not bad, [f.key for f in bad]


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------

@pytest.fixture
def san(tmp_path, monkeypatch):
    """Sanitizer on, fresh state, artifacts into tmp_path; restored
    (env-driven off + watchdog stopped) afterwards."""
    monkeypatch.setenv("PADDLE_TPU_OBS_DIR", str(tmp_path))
    L.set_lock_san(True)
    s = L.reset_sanitizer()
    s._watchdog_interval = 0.2
    try:
        yield s
    finally:
        L.set_lock_san(None)
        s.stop_watchdog()


def test_factories_plain_when_off(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_LOCK_SAN", raising=False)
    L.set_lock_san(False)
    try:
        assert not isinstance(L.make_lock("t.off"), L.InstrumentedLock)
        assert not isinstance(L.make_rlock("t.off"), L.InstrumentedLock)
        cv = L.make_condition("t.off")
        assert isinstance(cv, threading.Condition)
        assert not isinstance(cv._lock, L.InstrumentedLock)
    finally:
        L.set_lock_san(None)


def test_env_knob_enables(monkeypatch, san):
    monkeypatch.setenv("PADDLE_TPU_LOCK_SAN", "1")
    L.set_lock_san(None)          # re-read the env
    assert L.lock_san_enabled()
    assert isinstance(L.make_lock("t.env"), L.InstrumentedLock)


def test_hold_histogram_records(san):
    lk = L.make_lock("t.hold")
    with lk:
        time.sleep(0.03)
    s = registry.get("ptpu_lock_hold_ms").snap(lock="t.hold")
    assert s.count == 1
    assert s.sum >= 20.0          # ms


def test_wait_histogram_records_contention(san):
    lk = L.make_lock("t.wait")
    released = threading.Event()

    def holder():
        with lk:
            released.set()
            time.sleep(0.05)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    released.wait(timeout=5)
    with lk:                       # contends ~50ms with the holder
        pass
    t.join(timeout=5)
    s = registry.get("ptpu_lock_wait_ms").snap(lock="t.wait")
    assert s.count >= 1
    assert s.sum >= 20.0


def test_condition_wrapping_and_reentry(san):
    cv = L.make_condition("t.cv")
    assert isinstance(cv._lock, L.InstrumentedLock)
    with cv:
        with cv:                   # reentrant (RLock-backed)
            cv.notify_all()
    hit = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=5)     # _release_save/_acquire_restore path
            hit.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert hit.is_set()


def test_order_cycle_dumps_one_artifact(san, tmp_path):
    a, b = L.make_lock("t.A"), L.make_lock("t.B")
    with a:
        with b:                    # edge A->B
            pass
    with b:
        with a:                    # edge B->A: closes the cycle
            pass
    with b:
        with a:                    # same cycle again: deduped
            pass
    arts = glob.glob(str(tmp_path / "flight_lock_order_cycle_*"))
    assert len(arts) == 1
    payload = json.load(open(arts[0]))
    assert set(payload["metadata"]["locks"]) == {"t.A", "t.B"}
    assert len(san.snapshot()["cycle_artifacts"]) == 1


def test_same_name_instances_no_cycle(san, tmp_path):
    # two journals locked in either order is NOT an order inversion
    j1, j2 = L.make_lock("journalx.cond"), L.make_lock("journalx.cond")
    with j1:
        with j2:
            pass
    with j2:
        with j1:
            pass
    assert not glob.glob(str(tmp_path / "flight_lock_order_cycle_*"))


def test_deadlock_watchdog_dumps_artifact(san, tmp_path):
    a, b = L.make_lock("t.dA"), L.make_lock("t.dB")
    got_a, got_b = threading.Event(), threading.Event()

    def t1():
        with a:
            got_a.set()
            got_b.wait(timeout=5)
            if b.acquire(timeout=4):   # blocks: t2 holds b
                b.release()

    def t2():
        with b:
            got_b.set()
            got_a.wait(timeout=5)
            if a.acquire(timeout=4):   # blocks: t1 holds a
                a.release()

    ts = [threading.Thread(target=f, daemon=True) for f in (t1, t2)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 8
    arts = []
    while time.monotonic() < deadline and not arts:
        # dump_flight writes *.tmp then renames — only the final name is
        # safe to open (the .tmp vanishes under a concurrent json.load)
        arts = [p for p in
                glob.glob(str(tmp_path / "flight_lock_deadlock_*"))
                if not p.endswith(".tmp")]
        time.sleep(0.1)
    for t in ts:
        t.join(timeout=10)
    assert len(arts) == 1, "watchdog did not dump (or dumped twice)"
    payload = json.load(open(arts[0]))
    meta = payload["metadata"]
    assert set(meta["locks"]) == {"t.dA", "t.dB"}
    assert meta["holder_stacks"]       # sys._current_frames captured
    assert len(san.snapshot()["deadlock_artifacts"]) == 1


def test_lock_hold_fault_site(san):
    from paddle_tpu.distributed.resilience import FaultInjector
    lk = L.make_lock("t.fault")
    with FaultInjector({"lock_hold": 1}, wedge_s=0.08):
        with lk:                   # the wedge fires while still held
            pass
    s = registry.get("ptpu_lock_hold_ms").snap(lock="t.fault")
    assert s.count == 1
    assert s.sum >= 60.0           # the injected 80ms dominates


# ---------------------------------------------------------------------------
# race_hunt host-only smoke
# ---------------------------------------------------------------------------

def _load_race_hunt():
    spec = importlib.util.spec_from_file_location(
        "race_hunt", os.path.join(ROOT, "tools", "race_hunt.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_race_hunt_host_hammers_clean(san):
    rh = _load_race_hunt()
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        assert rh.hammer_journal_extend_reap(1) == []
        assert rh.hammer_qos_admit_shed(1) == []
        assert rh.hammer_metrics_scrape_record(1) == []
    finally:
        sys.setswitchinterval(old)
    snap = san.snapshot()
    assert snap["cycle_artifacts"] == []
    assert snap["deadlock_artifacts"] == []


def test_race_hunt_hammer_registry():
    rh = _load_race_hunt()
    for name in rh.ALL_HAMMERS:
        assert callable(getattr(rh, f"hammer_{name}"))
    assert set(rh.HOST_HAMMERS).isdisjoint(rh.JAX_HAMMERS)
