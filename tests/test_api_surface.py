"""Top-level API surface parity: every symbol in the reference's
python/paddle/__init__.py __all__ must exist on paddle_tpu, plus
numeric checks for the parity-extras ops."""
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle

_REF_INIT = "/root/reference/python/paddle/__init__.py"


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree not mounted")
def test_top_level_all_covered():
    src = open(_REF_INIT).read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    ref = set(re.findall(r"'([^']+)'", m.group(1)))
    missing = sorted(ref - set(dir(paddle)))
    assert not missing, f"top-level symbols missing: {missing}"


_NAMESPACES = ["optimizer", "distributed", "io", "jit", "amp", "autograd",
               "metric", "static", "static.nn", "nn.functional", "nn.initializer", "nn.utils", "vision", "distribution",
               "sparse", "device", "profiler", "geometric", "text", "audio",
               "utils", "quantization", "incubate", "nn"]


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree not mounted")
@pytest.mark.parametrize("ns", _NAMESPACES)
def test_namespace_all_covered(ns):
    path = f"/root/reference/python/paddle/{ns.replace('.', '/')}/__init__.py"
    if not os.path.exists(path):
        pytest.skip(f"no reference namespace {ns}")
    m = re.search(r"__all__ = \[(.*?)\]", open(path).read(), re.S)
    if not m:
        pytest.skip(f"reference {ns} has no __all__")
    ref = set(re.findall(r"'([^']+)'", m.group(1)))
    mod = paddle
    for part in ns.split("."):
        mod = getattr(mod, part)
    mine = set(dir(mod)) | set(getattr(mod, "__all__", []))
    missing = sorted(ref - mine)
    assert not missing, f"paddle.{ns} missing: {missing}"


class TestParityExtras:
    def test_addmm_mm_t(self):
        x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
        i = paddle.to_tensor(np.ones((2, 2), np.float32))
        np.testing.assert_allclose(
            paddle.addmm(i, x, x, beta=0.5, alpha=2.0).numpy(),
            0.5 + 2 * (x.numpy() @ x.numpy()))
        np.testing.assert_allclose(paddle.mm(x, x).numpy(),
                                   x.numpy() @ x.numpy())
        assert paddle.t(x).numpy()[0, 1] == 3.0
        with pytest.raises(ValueError, match="dimension is <= 2"):
            paddle.t(paddle.to_tensor(np.zeros((2, 2, 2), np.float32)))

    def test_kron_frexp_logit(self):
        x = paddle.to_tensor(np.array([[1., 2.]], np.float32))
        assert paddle.kron(x, x).shape == [1, 4]
        m, e = paddle.frexp(paddle.to_tensor(
            np.array([4.0, 0.5], np.float32)))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(),
                                   [4.0, 0.5])
        lg = paddle.logit(paddle.to_tensor(
            np.array([0.5, 0.75], np.float32)))
        np.testing.assert_allclose(lg.numpy(), [0.0, np.log(3)],
                                   rtol=1e-5)

    def test_nan_to_num_renorm(self):
        x = paddle.to_tensor(np.array([np.nan, np.inf, 1.0], np.float32))
        out = paddle.nan_to_num(x, nan=0.0, posinf=9.0).numpy()
        np.testing.assert_allclose(out, [0.0, 9.0, 1.0])
        w = paddle.to_tensor(np.array([[3., 4.], [0.3, 0.4]], np.float32))
        r = paddle.renorm(w, p=2.0, axis=0, max_norm=1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(r[0]), 1.0, rtol=1e-5)
        np.testing.assert_allclose(r[1], w.numpy()[1])  # already small

    def test_take_modes(self):
        x = paddle.to_tensor(np.arange(6).astype(np.float32))
        idx = paddle.to_tensor(np.array([0, 7, -1], np.int64))
        wrap = paddle.take(x, idx, mode="wrap").numpy()
        np.testing.assert_allclose(wrap, [0, 1, 5])
        clip = paddle.take(x, idx, mode="clip").numpy()
        np.testing.assert_allclose(clip, [0, 5, 0])

    def test_multiplex(self):
        a = paddle.to_tensor(np.array([[1., 1.], [2., 2.]], np.float32))
        b = paddle.to_tensor(np.array([[3., 3.], [4., 4.]], np.float32))
        idx = paddle.to_tensor(np.array([[1], [0]], np.int64))
        out = paddle.multiplex([a, b], idx).numpy()
        np.testing.assert_allclose(out, [[3, 3], [2, 2]])

    def test_scatter_nd_and_inplace(self):
        idx = paddle.to_tensor(np.array([[0, 1], [1, 0]], np.int64))
        upd = paddle.to_tensor(np.array([2., 3.], np.float32))
        out = paddle.scatter_nd(idx, upd, [2, 2]).numpy()
        np.testing.assert_allclose(out, [[0, 2], [3, 0]])
        x = paddle.to_tensor(np.zeros((3, 2), np.float32))
        paddle.scatter_(x, paddle.to_tensor(np.array([1], np.int64)),
                        paddle.to_tensor(np.ones((1, 2), np.float32)))
        assert x.numpy()[1].sum() == 2.0

    def test_increment_tanh_inplace(self):
        x = paddle.to_tensor(np.zeros((1,), np.float32))
        paddle.increment(x, 2.5)
        np.testing.assert_allclose(x.numpy(), [2.5])
        y = paddle.to_tensor(np.zeros((2,), np.float32))
        paddle.tanh_(y)
        np.testing.assert_allclose(y.numpy(), 0.0)

    def test_info_and_shapes(self):
        assert paddle.finfo("bfloat16").bits == 16
        assert paddle.iinfo("int8").max == 127
        assert paddle.broadcast_shape([2, 1, 3], [4, 1]) == [2, 4, 3]
        with pytest.raises(ValueError):
            paddle.check_shape([2, -3])

    def test_flops(self):
        import paddle_tpu.nn as nn
        net = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
        assert paddle.flops(net, (1, 8)) == 8 * 4 + 4 * 2

    def test_batch_reader(self):
        def reader():
            yield from range(5)

        batches = list(paddle.batch(reader, 2)())
        assert batches == [[0, 1], [2, 3], [4]]
        batches = list(paddle.batch(reader, 2, drop_last=True)())
        assert batches == [[0, 1], [2, 3]]

    def test_places_and_misc(self):
        assert paddle.CPUPlace() == paddle.CPUPlace()
        assert paddle.CUDAPlace(0) != paddle.CUDAPlace(1)
        paddle.disable_signal_handler()
        with paddle.LazyGuard():
            pass
        p = paddle.create_parameter([2, 3])
        assert p.shape == [2, 3] and not p.stop_gradient
        assert str(paddle.dtype("float32")) == "float32"


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree not mounted")
def test_every_reference_namespace_covered():
    """Auto-discovering sweep: EVERY reference namespace with an __all__
    (outside fluid/tests) must resolve here with no missing symbols —
    the strongest form of the per-namespace checks above."""
    root = "/root/reference/python/paddle"
    gaps = []

    def _missing_from(init_path, mod):
        m = re.search(r"__all__\s*=\s*\[(.*?)\]",
                      open(init_path).read(), re.S)
        if not m:
            return None
        ref = set(re.findall(r"['\"]([^'\"]+)['\"]", m.group(1)))
        if not ref:
            return None
        if mod is None:
            return "MODULE MISSING"
        return sorted(ref - (set(dir(mod))
                             | set(getattr(mod, "__all__", [])))) or None

    for dirpath, _dirs, files in os.walk(root):
        if "__init__.py" not in files or "fluid" in dirpath \
                or "tests" in dirpath:
            continue
        rel = os.path.relpath(dirpath, root)
        if rel == ".":
            continue
        ns = rel.replace(os.sep, ".")
        mod = paddle
        try:
            for part in ns.split("."):
                mod = getattr(mod, part)
        except AttributeError:
            mod = None
        missing = _missing_from(os.path.join(dirpath, "__init__.py"), mod)
        if missing:
            gaps.append((ns, missing))
    # single-FILE namespaces (linalg.py, fft.py, callbacks via hapi, ...)
    import glob
    for path in sorted(glob.glob(root + "/*.py")):
        name = os.path.basename(path)[:-3]
        if name.startswith("_"):
            continue
        missing = _missing_from(path, getattr(paddle, name, None))
        if missing:
            gaps.append((name, missing))
    assert not gaps, f"namespace gaps vs reference: {gaps}"


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree not mounted")
def test_tensor_method_surface_covered():
    """Every name in the reference's tensor_method_func (the methods the
    eager math-op patch binds onto Tensor) must exist on our Tensor."""
    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    m = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S)
    ref = set(re.findall(r"'([^']+)'", m.group(1)))
    t = paddle.to_tensor([1.0])
    missing = sorted(ref - set(dir(t)))
    assert not missing, f"Tensor methods missing: {missing}"


def test_inplace_tail_and_lu_unpack():
    x = paddle.to_tensor(np.array([4.0, 9.0], np.float32))
    assert x.sqrt_() is x
    np.testing.assert_allclose(x.numpy(), [2.0, 3.0])
    y = paddle.to_tensor(np.array([[0.25, 0.5]], np.float32))
    y.reciprocal_()
    np.testing.assert_allclose(y.numpy(), [[4.0, 2.0]])
    A = np.random.RandomState(0).randn(5, 5).astype(np.float32)
    lu, piv = paddle.linalg.lu(paddle.to_tensor(A))
    P, L, U = paddle.lu_unpack(lu, piv)
    np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), A,
                               rtol=1e-4, atol=1e-5)
