"""Speculative decoding subsystem (ISSUE 13, inference/speculative.py).

Host-side units:
- NGramProposer: longest-suffix n-gram match, most-recent-occurrence
  wins, end-of-context truncation, no-match -> empty draft;
- resolve_speculative: env/knob normalization, loud rejections
  (sampling engines, draft mode without a model).

Engine level (the serving guarantees):
- greedy speculative output is BITWISE token-identical to sequential
  generate() — the engine's oracle — on f32 AND int8, slot AND paged
  caches, across staggered mixed-length traffic, eos mid-block
  included: the emitted block is always the TARGET's own argmax, so
  acceptance can only change how many tokens a tick consumes, never
  which tokens;
- ZERO recompiles under prompt-length / k-pattern / acceptance-pattern
  drift — proposals, draft lengths, positions and live masks ride as
  arguments (trace counters must not move after warmup);
- the draft-model proposer: a same-weights draft accepts ~everything
  (bonus-token path), a differently-seeded draft accepts ~nothing
  (rejection path) — both stay identical to the oracle, and the draft
  programs share the engine's no-recompile guarantee;
- multi-token ticks: on repetitive context the accepted-tokens-per-
  tick (per slot per verify forward) exceeds 1.0 — the whole point;
- acceptance counters surface in stats(), /healthz and the obs
  registry (ptpu_engine_spec_*), and /generate bodies carry
  tokens_generated (+ tokens_drafted/tokens_accepted) — the fields the
  router forwards unchanged (test_router.py).
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.inference.speculative import (NGramProposer,
                                              resolve_speculative)
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


# ---------------------------------------------------------------------------
# host-side units
# ---------------------------------------------------------------------------

def test_ngram_proposer_matches_suffix_continuation():
    p = NGramProposer(k=4, ngram_max=3)
    # context ends in [7, 8]; the earlier [7, 8] is followed by 9, 1, 2
    ctx = np.array([9, 7, 8, 9, 1, 2, 7, 8], np.int64)
    props, n = p.propose(ctx)
    assert n == 4
    assert props.tolist() == [9, 1, 2, 7]


def test_ngram_proposer_most_recent_full_match_wins():
    p = NGramProposer(k=2, ngram_max=2)
    # [5, 6] occurs twice with full 2-token continuations; the most
    # recent of those is followed by [3, 3]
    ctx = np.array([5, 6, 1, 1, 5, 6, 3, 3, 5, 6], np.int64)
    props, n = p.propose(ctx)
    assert n == 2 and props.tolist() == [3, 3]


def test_ngram_proposer_truncates_at_context_end():
    # the only earlier [1, 2] has a truncated continuation ([8, 1, 2]
    # then the context ends): drafted length < k, zero-padded
    p = NGramProposer(k=8, ngram_max=2)
    ctx = np.array([1, 2, 8, 1, 2], np.int64)
    props, n = p.propose(ctx)
    assert n == 3 and props[:3].tolist() == [8, 1, 2]
    assert (props[3:] == 0).all()


def test_ngram_proposer_no_match_is_empty():
    p = NGramProposer(k=4, ngram_max=3)
    props, n = p.propose(np.array([1, 2, 3, 4, 5], np.int64))
    assert n == 0 and (props == 0).all()


def test_resolve_speculative_knobs(monkeypatch):
    assert resolve_speculative(False) is None
    assert resolve_speculative(None) is None          # env unset
    cfg = resolve_speculative(True, spec_k=6, spec_ngram=2)
    assert cfg.kind == "ngram" and cfg.k == 6 and cfg.ngram_max == 2
    monkeypatch.setenv("PADDLE_TPU_SERVE_SPEC", "ngram")
    monkeypatch.setenv("PADDLE_TPU_SERVE_SPEC_K", "3")
    cfg = resolve_speculative(None)
    assert cfg.kind == "ngram" and cfg.k == 3
    with pytest.raises(ValueError):
        resolve_speculative("draft")                  # needs a model
    with pytest.raises(ValueError):
        resolve_speculative("beam")                   # unknown mode
    with pytest.raises(ValueError):
        resolve_speculative(True, spec_k=0)


# ---------------------------------------------------------------------------
# engine level
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def spec_engine(model):
    eng = ContinuousBatchingEngine(
        model, slots=4, max_len=64, cache_dtype="float32",
        prefill_buckets=(8, 16), tick_tokens=4, speculative="ngram",
        spec_k=4)
    yield eng
    eng.stop()


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        0, 250, (n,)).astype("int64")


def _rep_prompt(seed, period, reps):
    pat = _prompt(seed, period)
    return np.concatenate([pat] * reps)


def test_spec_rejects_sampling(model):
    with pytest.raises(ValueError, match="greedy-only"):
        ContinuousBatchingEngine(
            model, slots=2, max_len=64, cache_dtype="float32",
            prefill_buckets=(8,), tick_tokens=4, do_sample=True,
            speculative="ngram")


def test_spec_greedy_identity_staggered_mixed(model, spec_engine):
    """Repetitive AND non-repetitive prompts, staggered arrivals: the
    speculative engine's output is token-identical to generate() no
    matter what the drafter proposed or how much was accepted."""
    eng = spec_engine
    # oracle shapes deliberately repeat (P in {5, 9, 12, 16}, n = 8):
    # generate()'s per-(P, n) program pairs come from the model's LRU,
    # so the reference costs 4 compiles, not 6 — the ENGINE side has
    # no shape keys at all (that is the point under test)
    prompts = [_rep_prompt(0, 4, 3), _prompt(1, 5), _rep_prompt(2, 3, 4),
               _prompt(3, 9), _prompt(4, 16), _rep_prompt(5, 2, 6)]
    news = [8] * 6
    futs = []
    for ids, n in zip(prompts, news):
        futs.append(eng.submit(ids, max_new_tokens=n))
        time.sleep(0.01)          # arrivals land across tick boundaries
    outs = [f.result(timeout=300) for f in futs]
    for ids, n, got in zip(prompts, news, outs):
        want = model.generate(ids[None], max_new_tokens=n,
                              cache_dtype="float32")[0]
        np.testing.assert_array_equal(got, want)
    st = eng.stats()
    assert st["speculative"] == "ngram" and st["spec_ticks"] > 0
    assert st["tokens_drafted"] > 0


def test_spec_identity_with_eos_mid_block(model, spec_engine):
    """EOS landing INSIDE an accepted verify block truncates exactly
    like plain decode: retirement + eos padding match generate()."""
    ids = _rep_prompt(6, 3, 3)
    # eos = the first greedy token, read off a shared-shape oracle run
    # (P=9, n=8 rides the model's program-pair LRU), so it fires
    # mid-stream — inside an accepted verify block
    eos = int(model.generate(ids[None], max_new_tokens=8,
                             cache_dtype="float32")[0, ids.shape[0]])
    want = model.generate(ids[None], max_new_tokens=12,
                          eos_token_id=eos, cache_dtype="float32")[0]
    got = spec_engine.generate(ids, max_new_tokens=12, eos_token_id=eos,
                               timeout=300)
    np.testing.assert_array_equal(got, want)


def test_spec_multi_token_ticks_on_repetitive_context(model,
                                                      spec_engine):
    """The acceptance claim: on repetitive context a verify tick
    consumes MORE than one token per slot per forward."""
    eng = spec_engine
    before = (eng.spec_tokens_emitted, eng.spec_slot_ticks)
    futs = [eng.submit(_rep_prompt(50 + i, 4, 4), max_new_tokens=16)
            for i in range(4)]
    for f in futs:
        f.result(timeout=300)
    emitted = eng.spec_tokens_emitted - before[0]
    slot_ticks = eng.spec_slot_ticks - before[1]
    assert slot_ticks > 0
    assert emitted / slot_ticks > 1.0, \
        f"no multi-token ticks: {emitted} tokens / {slot_ticks} " \
        "slot-ticks"
    st = eng.stats()
    assert st["acceptance_rate"] > 0.0
    assert st["tokens_accepted"] + st["tokens_rejected"] \
        == st["tokens_drafted"]


def test_spec_zero_recompile_under_drift(model, spec_engine):
    """Prompt-length, draft-length, acceptance-pattern and k-content
    drift all ride the same compiled verify program — and the plain
    fallback tick (no proposals anywhere) its own: the trace counters
    must not move after both are warm."""
    eng = spec_engine
    # warm every path: both buckets, the verify program (repetitive
    # prompts draft immediately), the plain fallback (random prompts
    # with nothing to match)
    for p in (4, 12):
        eng.generate(_prompt(70 + p, p), max_new_tokens=3, timeout=300)
    eng.generate(_rep_prompt(71, 4, 3), max_new_tokens=6, timeout=300)
    warm = eng.compiled_program_count
    futs = []
    for i, (p, n) in enumerate([(p, n) for p in range(3, 12)
                                for n in (2, 3)]):
        futs.append(eng.submit(_prompt(100 + i, p), max_new_tokens=n))
    # acceptance-pattern drift: different periods/phases of repetition
    for i, (period, reps) in enumerate([(2, 6), (3, 4), (4, 3),
                                        (5, 3)]):
        futs.append(eng.submit(_rep_prompt(200 + i, period, reps),
                               max_new_tokens=8))
    for f in futs:
        f.result(timeout=300)
    assert eng.compiled_program_count == warm, \
        "speculative engine recompiled under drift"


def test_spec_identity_int8_slot_cache_warmed(model):
    """int8 slot-cache identity — AND warmup coverage: engine.warmup()
    AOT-covers the verify program (plus decode/admit), so the traffic
    below runs with ZERO additional traces."""
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="int8",
        prefill_buckets=(16,), tick_tokens=4, speculative="ngram",
        spec_k=4)
    try:
        eng.warmup()
        warm = eng.compiled_program_count
        for seed, (p, n) in enumerate([(12, 8), (9, 8)]):
            ids = _rep_prompt(seed, 3, p // 3) if seed % 2 \
                else _prompt(seed, p)
            want = model.generate(ids[None], max_new_tokens=n,
                                  cache_dtype="int8")[0]
            got = eng.generate(ids, max_new_tokens=n, timeout=300)
            np.testing.assert_array_equal(got, want)
        assert eng.compiled_program_count == warm
        assert eng.warm
    finally:
        eng.stop()


def test_spec_identity_paged(model):
    """Paged pools under speculative verify: block-table gathers,
    live-gated block writes and shared-prefix admissions compose with
    the verify program (int8 pools ride the churn test in
    test_paged_engine.py — one engine each keeps tier-1's compile
    budget honest)."""
    eng = ContinuousBatchingEngine(
        model, slots=4, max_len=64, cache_dtype="float32",
        prefill_buckets=(16,), tick_tokens=4, paged=True,
        page_size=8, speculative="ngram", spec_k=4)
    try:
        prompts = [_rep_prompt(20, 4, 3), _prompt(21, 9),
                   _rep_prompt(22, 2, 6), _prompt(23, 16)]
        futs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        outs = [f.result(timeout=300) for f in futs]
        for p, got in zip(prompts, outs):
            want = model.generate(p[None], max_new_tokens=8,
                                  cache_dtype="float32")[0]
            np.testing.assert_array_equal(got, want)
        # prefix reuse still composes: same prompt twice, second
        # admission skips the cached pages
        ids = _rep_prompt(24, 8, 2)          # 16 = two full pages
        want = model.generate(ids[None], max_new_tokens=8,
                              cache_dtype="float32")[0]
        for _ in range(2):
            got = eng.generate(ids, max_new_tokens=8, timeout=300)
            np.testing.assert_array_equal(got, want)
        assert eng.stats()["prefix_hits"] >= 1
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# draft-model proposer
# ---------------------------------------------------------------------------

def test_draft_model_same_weights_accepts_and_stays_identical(model):
    """A draft sharing the target's weights accepts ~every proposal
    (exercising the full-acceptance bonus-token path and the draft
    sync-block invariant at n == k) — and output stays the oracle's."""
    paddle.seed(7)
    draft = GPTForCausalLM(gpt_tiny())
    draft.eval()
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="float32",
        prefill_buckets=(16,), tick_tokens=4, speculative="draft",
        draft_model=draft, spec_k=4)
    try:
        for seed, (p, n) in enumerate([(6, 12), (11, 8), (16, 8)]):
            ids = _prompt(seed, p)
            want = model.generate(ids[None], max_new_tokens=n,
                                  cache_dtype="float32")[0]
            got = eng.generate(ids, max_new_tokens=n, timeout=300)
            np.testing.assert_array_equal(got, want)
        st = eng.stats()
        assert st["speculative"] == "draft"
        assert st["acceptance_rate"] > 0.9, st
        assert st["accepted_tokens_per_tick"] > 2.0, st
        # draft drift never retraces: k proposals per slot every tick,
        # positions/sync tokens as vectors
        warm = eng.compiled_program_count
        futs = [eng.submit(_prompt(30 + i, 3 + i), max_new_tokens=4)
                for i in range(4)]
        for f in futs:
            f.result(timeout=300)
        assert eng.compiled_program_count == warm
    finally:
        eng.stop()


def test_draft_model_disagreeing_weights_still_identical(model):
    """A differently-seeded draft proposes mostly-wrong tokens: near-
    total rejection, one guaranteed token per tick — and STILL the
    oracle's tokens (the drafter can only cost speed, never change
    output)."""
    paddle.seed(99)
    draft = GPTForCausalLM(gpt_tiny())
    draft.eval()
    eng = ContinuousBatchingEngine(
        model, slots=2, max_len=64, cache_dtype="float32",
        prefill_buckets=(16,), tick_tokens=4, speculative="draft",
        draft_model=draft, spec_k=4)
    try:
        for seed, (p, n) in enumerate([(5, 8), (9, 8)]):
            ids = _prompt(40 + seed, p)
            want = model.generate(ids[None], max_new_tokens=n,
                                  cache_dtype="float32")[0]
            got = eng.generate(ids, max_new_tokens=n, timeout=300)
            np.testing.assert_array_equal(got, want)
        st = eng.stats()
        assert st["tokens_rejected"] > 0
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# serving layer: counters, /healthz, /generate accounting fields
# ---------------------------------------------------------------------------

def _req(srv, path, payload=None):
    url = f"http://{srv.host}:{srv.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_serve_reports_spec_fields_and_healthz(model, spec_engine):
    """/generate bodies carry tokens_generated always and
    tokens_drafted/tokens_accepted on speculative engines; /healthz
    surfaces the acceptance knobs; the obs registry exports
    ptpu_engine_spec_* counters."""
    from paddle_tpu import obs
    from paddle_tpu.inference.serve import PredictorServer
    srv = PredictorServer(engine=spec_engine, port=0).start()
    try:
        ids = _rep_prompt(60, 4, 3)
        code, body = _req(srv, "/generate",
                          {"input_ids": ids.tolist(),
                           "max_new_tokens": 8})
        assert code == 200, body
        want = model.generate(ids[None], max_new_tokens=8,
                              cache_dtype="float32")[0]
        assert body["tokens"] == want.tolist()
        assert body["tokens_generated"] == 8
        assert body["tokens_drafted"] >= body["tokens_accepted"] >= 0
        # eos padding keeps new_tokens at the budget but
        # tokens_generated truthful
        eos = int(want[-1])
        code, body2 = _req(srv, "/generate",
                           {"input_ids": ids.tolist(),
                            "max_new_tokens": 8, "eos_token_id": eos})
        assert code == 200, body2
        assert body2["new_tokens"] == 8
        assert body2["tokens_generated"] <= 8

        code, h = _req(srv, "/healthz")
        assert code == 200, h
        e = h["engine"]
        assert e["speculative"] == "ngram" and e["spec_k"] == 4
        assert e["tokens_drafted"] >= e["tokens_accepted"]
        assert 0.0 <= e["acceptance_rate"] <= 1.0
        assert e["accepted_tokens_per_tick"] >= 0.0

        if obs.enabled():
            reg = obs.metrics.registry
            for name in ("ptpu_engine_spec_ticks_total",
                         "ptpu_engine_spec_drafted_total",
                         "ptpu_engine_spec_accepted_total",
                         "ptpu_engine_spec_rejected_total"):
                m = reg.get(name)
                assert m is not None and m.value() >= 0, name
            assert reg.get("ptpu_engine_spec_drafted_total").value() \
                >= reg.get("ptpu_engine_spec_accepted_total").value()
    finally:
        srv.stop()


