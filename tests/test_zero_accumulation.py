"""ZeRO stage tests + gradient-merge accumulation parity.

Reference patterns: dygraph_group_sharded_stage3.py (stage3 param sharding
+ loss parity vs lower stages), gradient_merge_optimizer tests (k micro
steps == one big batch).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import TrainStep


def _net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 16))


def _opt(m):
    return paddle.optimizer.AdamW(learning_rate=0.05,
                                  parameters=m.parameters())


def _shard_size(arr):
    return max(s.data.size for s in arr.addressable_shards)


def test_zero3_shards_params_and_matches_stage1():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 16).astype("float32"))

    losses = {}
    steps = {}
    for stage in (1, 3):
        dist.set_mesh(None)
        dist.init_mesh({"dp": 8})
        m = _net()
        step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y),
                                      _opt(m), zero_stage=stage)
        losses[stage] = [float(step(x, x)) for _ in range(5)]
        steps[stage] = step

    # same trajectory regardless of stage
    np.testing.assert_allclose(losses[1], losses[3], rtol=2e-4)

    # stage 3: parameters themselves are sharded over the zero axis —
    # per-device param bytes divided by the axis degree
    w1 = steps[1].params["0.weight"]
    w3 = steps[3].params["0.weight"]
    assert "dp" in str(w3.sharding.spec)
    assert _shard_size(w3) == _shard_size(w1) // 8

    # stage 3 optimizer slots follow the param layout
    slot = steps[3].opt_state["0.weight"]["moment1"]
    assert "dp" in str(slot.sharding.spec)


def test_zero2_constrains_grads_zero1_does_not_shard_params():
    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    m = _net()
    step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y),
                                  _opt(m), zero_stage=2)
    x = paddle.to_tensor(np.random.RandomState(1).randn(16, 16)
                         .astype("float32"))
    step(x, x)
    # stage 2 keeps params replicated but slots sharded
    assert str(step.params["0.weight"].sharding.spec) == "PartitionSpec()"
    assert "dp" in str(step.opt_state["0.weight"]["moment1"].sharding.spec)


def test_trainstep_accumulation_matches_big_batch():
    """k micro-steps of batch B must produce the same update as one step
    of batch k*B (grads averaged — reference gradient_merge avg=True)."""
    rng = np.random.RandomState(3)
    xb = rng.randn(32, 16).astype("float32")
    yb = rng.randn(32, 16).astype("float32")

    paddle.seed(11)
    m_big = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    s_big = TrainStep(m_big, lambda o, y: F.mse_loss(o, y),
                      paddle.optimizer.Momentum(
                          learning_rate=0.1, momentum=0.9,
                          parameters=m_big.parameters()))
    s_big(paddle.to_tensor(xb), paddle.to_tensor(yb))

    paddle.seed(11)
    m_acc = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    s_acc = TrainStep(m_acc, lambda o, y: F.mse_loss(o, y),
                      paddle.optimizer.Momentum(
                          learning_rate=0.1, momentum=0.9,
                          parameters=m_acc.parameters()),
                      accumulate_steps=4)
    for i in range(4):
        s_acc(paddle.to_tensor(xb[i * 8:(i + 1) * 8]),
              paddle.to_tensor(yb[i * 8:(i + 1) * 8]))

    assert s_acc.update_count == 1
    for name in s_big.params:
        np.testing.assert_allclose(np.asarray(s_big.params[name]),
                                   np.asarray(s_acc.params[name]),
                                   rtol=1e-4, atol=1e-5)


def test_parallel_step_accumulation_under_dp_and_zero():
    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    rng = np.random.RandomState(5)
    xb = rng.randn(32, 16).astype("float32")

    paddle.seed(13)
    m_big = _net()
    s_big = dist.ParallelTrainStep(m_big, lambda o, y: F.mse_loss(o, y),
                                   _opt(m_big), zero_stage=2)
    s_big(paddle.to_tensor(xb), paddle.to_tensor(xb))

    paddle.seed(13)
    m_acc = _net()
    s_acc = dist.ParallelTrainStep(m_acc, lambda o, y: F.mse_loss(o, y),
                                   _opt(m_acc), zero_stage=2,
                                   accumulate_steps=4)
    for i in range(4):
        b = paddle.to_tensor(xb[i * 8:(i + 1) * 8])
        s_acc(b, b)

    for name in s_big.params:
        np.testing.assert_allclose(np.asarray(s_big.params[name]),
                                   np.asarray(s_acc.params[name]),
                                   rtol=1e-4, atol=1e-5)


def test_hapi_accumulate_grad_batches():
    from paddle_tpu.hapi import Model
    from paddle_tpu.io.dataloader import Dataset

    class DS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(0)
            self.x = rng.randn(64, 8).astype("float32")
            self.y = rng.randn(64, 4).astype("float32")

        def __len__(self):
            return 64

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  loss=lambda o, y: F.mse_loss(o, y))
    model.fit(DS(), batch_size=8, epochs=1, verbose=0,
              accumulate_grad_batches=2)
    assert model._train_step.accumulate_steps == 2
    assert model._train_step.update_count == 4  # 8 batches / k=2


def test_flush_partial_accumulation_and_opt_state_carryover():
    """Trailing partial windows apply at fit end; switching
    accumulate_grad_batches keeps Adam moments (no silent reset)."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.io.dataloader import Dataset

    class DS(Dataset):
        def __init__(self, n):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 8).astype("float32")
            self.y = rng.randn(n, 4).astype("float32")

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  loss=lambda o, y: F.mse_loss(o, y))
    # 9 batches, k=2 -> 4 full updates + 1 trailing flush
    model.fit(DS(72), batch_size=8, epochs=1, verbose=0,
              accumulate_grad_batches=2)
    assert model._train_step.update_count == 5
    assert float(np.abs(np.asarray(
        model._train_step.acc_grads["0.weight"])).max()) == 0.0

    m1_before = np.asarray(model._train_step.opt_state["0.weight"]["moment1"])
    assert np.abs(m1_before).max() > 0
    # switching k must carry optimizer state into the rebuilt step
    model.fit(DS(32), batch_size=8, epochs=1, verbose=0,
              accumulate_grad_batches=1)
    assert model._train_step.update_count >= 6


def test_batch_splits_over_dp_and_sharding_jointly():
    """ZeRO groups are data-parallel SUB-groups (reference GroupSharded:
    world = dp x shard group, every rank trains a different batch
    shard). The default batch spec must split dim 0 over BOTH axes —
    replicating over "sharding" would redundantly compute identical
    microbatches on every group member (r5 north-star model caught 8x
    wasted FLOPs) — and the dp2 x sharding4 / zero3 trajectory must
    stay bit-equal to plain dp8."""
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(16, 16).astype("float32"))

    losses = {}
    for name, degrees, stage in (("dp8", {"dp": 8}, 0),
                                 ("dp2xsh4", {"dp": 2, "sharding": 4}, 3)):
        dist.set_mesh(None)
        dist.init_mesh(degrees)
        m = _net()
        step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y),
                                      _opt(m), zero_stage=stage)
        if name == "dp2xsh4":
            spec = step._batch_sharding([np.zeros((16, 16),
                                                  "float32")])[0].spec
            assert "sharding" in str(spec) and "dp" in str(spec), spec
            # indivisible batch falls back to the dp-only split
            spec5 = step._batch_sharding([np.zeros((2, 16),
                                                   "float32")])[0].spec
            assert "sharding" not in str(spec5), spec5
        losses[name] = [float(step(x, x)) for _ in range(4)]
    np.testing.assert_allclose(losses["dp8"], losses["dp2xsh4"],
                               rtol=2e-4)
