"""Top-level module parity: reader combinators, sysconfig, regularizer,
hub (local source), onnx guidance, dataset namespace."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_reader_combinators():
    r = paddle.reader
    base = lambda: iter(range(10))
    assert list(r.firstn(base, 3)()) == [0, 1, 2]
    assert list(r.chain(base, base)()) == list(range(10)) * 2
    assert sorted(r.shuffle(base, 4)()) == list(range(10))
    assert list(r.map_readers(lambda a, b: a + b, base, base)()) == \
        [2 * i for i in range(10)]
    assert list(r.buffered(base, 2)()) == list(range(10))
    cached = r.cache(base)
    assert list(cached()) == list(range(10)) == list(cached())
    composed = r.compose(base, base)
    assert list(composed())[0] == (0, 0)
    with pytest.raises(RuntimeError, match="lengths"):
        list(r.compose(base, lambda: iter(range(3)))())
    out = sorted(r.xmap_readers(lambda x: x * x, base, 2, 4)())
    assert out == [i * i for i in range(10)]
    assert list(r.xmap_readers(lambda x: -x, base, 2, 4, order=True)()) \
        == [-i for i in range(10)]


def test_sysconfig_and_regularizer():
    inc = paddle.sysconfig.get_include()
    assert os.path.isdir(inc) and \
        os.path.exists(os.path.join(inc, "tcp_store.cc"))
    assert isinstance(paddle.sysconfig.get_lib(), str)
    from paddle_tpu.optimizer import L2Decay
    assert paddle.regularizer.L2Decay is L2Decay
    assert paddle.regularizer.L1Decay(0.1).coeff == 0.1


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny(scale=1):\n"
        "    'build a tiny thing'\n"
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(4 * scale, 2)\n")
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "tiny" in names
    assert "tiny thing" in paddle.hub.help(str(tmp_path), "tiny",
                                           source="local")
    m = paddle.hub.load(str(tmp_path), "tiny", source="local", scale=2)
    assert m.weight.shape == [8, 2]
    with pytest.raises(RuntimeError, match="network"):
        paddle.hub.load("user/repo", "tiny")


def test_onnx_export_real(tmp_path):
    """onnx.export is a real exporter since round 4 (see tests/test_onnx.py
    for deep coverage); input_spec stays mandatory like jit.save's."""
    import paddle_tpu.nn as nn
    with pytest.raises(ValueError, match="input_spec"):
        paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "x"))
    out = paddle.onnx.export(
        nn.Linear(2, 2), str(tmp_path / "x"),
        input_spec=[paddle.static.InputSpec([1, 2], "float32")])
    assert out.endswith(".onnx")
    import os
    assert os.path.getsize(out) > 0


def test_dataset_namespace(tmp_path):
    assert paddle.dataset.common.md5file.__name__ == "md5file"
    p = tmp_path / "f.bin"
    p.write_bytes(b"hello")
    import hashlib
    assert paddle.dataset.common.md5file(str(p)) == \
        hashlib.md5(b"hello").hexdigest()
    with pytest.raises(RuntimeError, match="network"):
        paddle.dataset.common.download("http://x/y.tar", "m", "0" * 32)
    assert callable(paddle.dataset.mnist.train)


def test_version_module(capsys):
    v = paddle.version
    assert paddle.__version__ == v.full_version == "0.1.0"
    assert v.cuda() == "False" and v.cudnn() == "False"
    assert v.tpu() != ""
    v.show()
    out = capsys.readouterr().out
    assert "cuda: False" in out and "tpu:" in out
