"""Checkpoint/resume of the north-star training composition.

SURVEY §5.4 at the level that matters: the FULL ZeRO-3 training state
(sharded params + sharded optimizer slots + step counters) saved from
one topology, restored onto a DIFFERENT mesh, and the resumed run must
continue the uninterrupted loss trajectory exactly. Reference pattern:
hybrid_parallel_pp_save_load.py + auto-parallel's dist_saver/converter
re-shard; here orbax restores straight into the target shardings
(distributed/checkpoint.py), no gather step.

The RNG contract makes exactness possible: the step's dropout key is
fold_in(step_count) from the seeded default generator, so a resumed
step N draws the same key as an uninterrupted step N.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import GPTConfig, GPTForCausalLM


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _build(degrees, zero_stage):
    dist.set_mesh(None)
    dist.init_mesh(degrees)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, tie_embeddings=False)
    m = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=m.parameters())
    return dist.ParallelTrainStep(m, GPTForCausalLM.loss_fn, opt,
                                  zero_stage=zero_stage, remat=True)


def _ids():
    return paddle.to_tensor(np.random.RandomState(5).randint(
        0, 128, (8, 32)).astype("int64"))


def test_zero3_checkpoint_resumes_on_different_topology(tmp_path):
    ids = _ids()

    # uninterrupted reference: 6 steps on dp2 x sharding4 / ZeRO-3
    ref = _build({"dp": 2, "sharding": 4}, 3)
    ref_losses = [float(ref(ids, ids)) for _ in range(6)]

    # run A: same config, 3 steps, then save the full training state
    a = _build({"dp": 2, "sharding": 4}, 3)
    for _ in range(3):
        a(ids, ids)
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"params": a.params, "opt": a.opt_state}, path)
    saved_steps = a.step_count

    # run B: fresh process-equivalent on a DIFFERENT topology
    # (dp4 x sharding2) — restore re-shards into B's own layouts
    b = _build({"dp": 4, "sharding": 2}, 3)
    restored = dist.load_state_dict(
        path, target={"params": b.params, "opt": b.opt_state})
    b.params = restored["params"]
    b.opt_state = restored["opt"]
    b.step_count = b.update_count = saved_steps

    # every restored leaf landed in B's sharding (not A's)
    w = b.params["gpt.block_0.mlp.fc_in.weight"]
    assert w.sharding.mesh.shape == {"dp": 4, "sharding": 2}

    resumed = [float(b(ids, ids)) for _ in range(3)]
    np.testing.assert_allclose(resumed, ref_losses[3:], rtol=2e-4)

    # run C: the elastic-fleet case (ISSUE 12) — the SAME checkpoint
    # restored onto a 4-device SLICE via the streaming reshard path,
    # bitwise against the 8-device source state
    import jax
    c = _build({"dp": 2, "sharding": 2}, 3)
    restored_c = dist.reshard_state_dict(
        path, target={"params": c.params, "opt": c.opt_state})
    for n in a.params:
        np.testing.assert_array_equal(
            np.asarray(a.params[n]), np.asarray(restored_c["params"][n]))
    la = jax.tree_util.tree_leaves(a.opt_state)
    lc = jax.tree_util.tree_leaves(restored_c["opt"])
    assert len(la) == len(lc) > 0
    for x, y in zip(la, lc):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    wc = restored_c["params"]["gpt.block_0.mlp.fc_in.weight"]
    assert wc.sharding.mesh.shape == {"dp": 2, "sharding": 2}
    assert wc.sharding.mesh.devices.size == 4


def test_zero3_crash_resume_bitwise_via_train_state(tmp_path):
    """Acceptance: a checkpoint-on-failure written by the resilience
    layer (atomic tmp+rename, save_train_state) restores a FULL ZeRO-3
    ParallelTrainStep — params, sharded optimizer slots, step counters,
    RNG — with bitwise-identical state, and the resumed trajectory
    continues the uninterrupted one."""
    import os

    import jax

    ids = _ids()
    ref = _build({"dp": 2, "sharding": 4}, 3)
    ref_losses = [float(ref(ids, ids)) for _ in range(5)]

    a = _build({"dp": 2, "sharding": 4}, 3)
    for _ in range(3):
        a(ids, ids)
    path = str(tmp_path / "ck")
    dist.save_train_state(a, path)
    # atomic publish: no partial/intermediate directories left behind
    assert os.path.isdir(path)
    assert not os.path.exists(path + ".tmp")
    assert not os.path.exists(path + ".old")
    dist.verify_checkpoint(path)

    b = _build({"dp": 2, "sharding": 4}, 3)
    dist.restore_train_state(b, path)
    assert b.step_count == 3 and b.update_count == 3
    a_leaves = jax.tree_util.tree_leaves(a.opt_state)
    b_leaves = jax.tree_util.tree_leaves(b.opt_state)
    assert len(a_leaves) == len(b_leaves) > 0
    for la, lb in zip(a_leaves, b_leaves):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for n in a.params:
        np.testing.assert_array_equal(np.asarray(a.params[n]),
                                      np.asarray(b.params[n]))

    resumed = [float(b(ids, ids)) for _ in range(2)]
    np.testing.assert_allclose(resumed, ref_losses[3:], rtol=1e-5)


def test_zero3_restore_without_resharding_is_exact(tmp_path):
    """Same-topology restore: trajectory continues bit-comparably."""
    ids = _ids()
    a = _build({"dp": 2, "sharding": 4}, 3)
    first = [float(a(ids, ids)) for _ in range(2)]
    path = str(tmp_path / "ckpt")
    dist.save_state_dict({"params": a.params, "opt": a.opt_state}, path)
    cont = [float(a(ids, ids)) for _ in range(2)]

    b = _build({"dp": 2, "sharding": 4}, 3)
    restored = dist.load_state_dict(
        path, target={"params": b.params, "opt": b.opt_state})
    b.params, b.opt_state = restored["params"], restored["opt"]
    b.step_count = b.update_count = 2
    resumed = [float(b(ids, ids)) for _ in range(2)]
    np.testing.assert_allclose(resumed, cont, rtol=1e-5)
    assert first[0] != cont[0]  # sanity: training actually moved
