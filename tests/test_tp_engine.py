"""Tensor-parallel serving slice (ISSUE 20).

The sharded-inference contract, end to end on the 8-virtual-device
mesh: a ContinuousBatchingEngine with tp>1 runs its programs
pjit-sharded over a dedicated ("mp",) slice — attention heads and MLP
hidden dims Megatron-split, KV pools (and int8 scale planes)
head-sharded, block tables replicated — and its greedy token stream is
BITWISE identical to the single-chip engine across every cache/decode
mode, with zero recompiles under prompt-length drift.

Covered here:
- identity matrix: slot/paged x f32/int8 x plain/speculative at tp=2,
  plus one tp=4 case
- staggered admissions joining a live sharded batch mid-decode
- scan_layers + paged: the stacked pool carries its layer axis and the
  block table broadcasts onto it (the PR 9 follow-up)
- fused-kernel knobs fall back LOUDLY (warning + stats field) under a
  sharded mesh, never silently-wrong Pallas dispatch
- registry/lint completeness for the four *_tp sites
- mesh geometry in stats/snapshots + mixed-tp tier metric summing
- a LIVE 2-replica tier where each replica is a tp=2 slice
"""
import json
import urllib.request
import warnings

import numpy as np
import pytest

from paddle_tpu.framework import random as _rng
from paddle_tpu.inference.engine import ContinuousBatchingEngine
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM


def _gpt(scan_layers=False):
    _rng.seed(0)
    return GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=64,
                                    num_layers=2, num_heads=4,
                                    max_seq_len=128,
                                    scan_layers=scan_layers))


def _gpt_scan():
    """Scanned GPT with the UNROLLED model's weights: scan init consumes
    RNG in stacked order, so same-seed scan/unrolled models differ —
    parity requires the copy (same idiom as test_gpt_scan_layers)."""
    m_u = _gpt()
    m_s = _gpt(scan_layers=True)
    m_s.gpt.blocks.load_from_blocks(m_u.gpt.blocks)
    sd_u = dict(m_u.named_parameters())
    for n, p in m_s.named_parameters():
        if not n.startswith("gpt.blocks."):
            p.value = sd_u[n].value
    return m_s


def _llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    _rng.seed(0)
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=176,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128))


def _prompt(seed, n):
    return np.random.RandomState(seed).randint(
        1, 255, size=n).astype(np.int32)


PROMPTS = [(_prompt(s, n)) for s, n in ((1, 5), (2, 9), (3, 13))]


def _engine(tp=None, model=None, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("cache_dtype", "float32")
    kw.setdefault("tick_tokens", 4)
    return ContinuousBatchingEngine(model if model is not None
                                    else _gpt(), tp=tp, **kw)


def _decode_all(eng, max_new=6):
    """Warm up, decode the shared prompts, assert the zero-recompile
    contract, return the token streams."""
    eng.warmup()
    warm = eng.compiled_program_count
    outs = [eng.generate(p, max_new_tokens=max_new, timeout=300)
            for p in PROMPTS]
    assert eng.compiled_program_count == warm, \
        "recompiled under prompt-length drift"
    return outs


_BASELINES = {}


def _baseline(key, **kw):
    """tp=1 token streams for an engine config, computed once per
    module (every tp>1 case compares against the SAME single-chip
    run)."""
    if key not in _BASELINES:
        with _engine(**kw) as eng:
            _BASELINES[key] = _decode_all(eng)
    return _BASELINES[key]


# ---------------------------------------------------------------------------
# identity matrix
# ---------------------------------------------------------------------------

MATRIX = [
    ("slot_f32", {}),
    ("slot_int8", {"cache_dtype": "int8"}),
    ("paged_f32", {"paged": True, "page_size": 16, "num_pages": 24}),
    ("paged_int8", {"paged": True, "page_size": 16, "num_pages": 24,
                    "cache_dtype": "int8"}),
    ("slot_spec", {"speculative": "ngram", "spec_k": 4}),
    ("paged_spec", {"paged": True, "page_size": 16, "num_pages": 24,
                    "speculative": "ngram", "spec_k": 4}),
]


@pytest.mark.parametrize("key,kw", MATRIX,
                         ids=[k for k, _ in MATRIX])
def test_tp2_tokens_bitwise_identical(key, kw):
    """The oracle: a tp=2 slice emits EXACTLY the single-chip token
    stream — sharded partial sums reorder float math, but greedy
    argmax token IDs must not move. Slot and paged, f32 and int8
    caches, plain and speculative decode."""
    want = _baseline(key, **kw)
    with _engine(tp=2, **kw) as eng:
        got = _decode_all(eng)
        st = eng.stats()
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert st["tp"] == 2 and st["mesh_devices"] == 2
    assert st["mesh"]["mesh_axis"] == "mp"
    assert len(st["mesh"]["devices"]) == 2


def test_tp4_tokens_bitwise_identical():
    """One degree higher: the 4-way slice (one attention head per
    chip) still matches the single-chip stream."""
    want = _baseline("slot_f32")
    with _engine(tp=4) as eng:
        got = _decode_all(eng)
        assert eng.stats()["mesh_devices"] == 4
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_tp2_llama_gqa_identity():
    """GQA under TP: num_kv_heads=2 over tp=2 puts ONE kv head per
    chip while queries shard 2-per-chip — the uneven head-group split
    the GPT matrix can't exercise."""
    with _engine(model=_llama()) as eng:
        want = _decode_all(eng)
    with _engine(tp=2, model=_llama()) as eng:
        got = _decode_all(eng)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_tp2_quantized_comm_wire_runs():
    """comm_precision="int8"/"bf16" route the per-block all-reduce
    through the EQuARX wire bodies — the programs must trace, run,
    and stay recompile-free; the wire is lossy so the gate here is
    self-consistency (two identical engines produce identical
    streams), not equality with the exact-psum engine."""
    for prec in ("int8", "bf16"):
        with _engine(tp=2, comm_precision=prec) as eng:
            a = _decode_all(eng)
            st = eng.stats()
        assert st["tp_comm_precision"] == prec
        assert st["tp_tick_comm_bytes"] > 0
        with _engine(tp=2, comm_precision=prec) as eng:
            b = _decode_all(eng)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def test_staggered_admissions_join_live_batch():
    """Requests admitted MID-DECODE into a running sharded batch keep
    the identity oracle: late arrivals join slots while earlier
    requests are ticking, and every stream still matches the
    single-chip engine's for the same prompt."""
    import time
    want = _baseline("slot_f32")
    extra = _prompt(9, 7)
    with _engine() as eng:
        want_first = eng.generate(PROMPTS[0], max_new_tokens=24,
                                  timeout=300)
        want_extra = eng.generate(extra, max_new_tokens=12, timeout=300)
    with _engine(tp=2) as eng:
        eng.warmup()
        warm = eng.compiled_program_count
        first = eng.submit(PROMPTS[0], max_new_tokens=24)
        # admit the rest only once the first is live and ticking (24
        # tokens / 4 per tick leaves plenty of mid-decode window)
        deadline = time.time() + 120
        while eng.stats()["active"] == 0 and not first.done():
            assert time.time() < deadline, "first request never ran"
            time.sleep(0.01)
        rest = [eng.submit(p, max_new_tokens=6)
                for p in PROMPTS[1:]] + [eng.submit(extra,
                                                    max_new_tokens=12)]
        outs = [first.result(timeout=300)] + \
               [f.result(timeout=300) for f in rest]
        assert eng.compiled_program_count == warm
    np.testing.assert_array_equal(outs[0], want_first)
    for got, p_want in zip(outs[1:3], want[1:3]):
        np.testing.assert_array_equal(got, p_want)
    np.testing.assert_array_equal(outs[3], want_extra)


# ---------------------------------------------------------------------------
# scan_layers + paged: the stacked pool's layer axis
# ---------------------------------------------------------------------------

def test_scan_layers_paged_block_table_layer_axis():
    """The PR 9 follow-up: under scan_layers the paged pools stack
    per-layer with a leading L axis ([L, num_pages, page_size, ...])
    and the replicated block table broadcasts onto it inside
    _attach_page_meta — so scanned stacks serve paged, and identically
    to the unrolled model."""
    kw = {"paged": True, "page_size": 16, "num_pages": 24}
    with _engine(model=_gpt_scan(), **kw) as eng:
        k_stack, v_stack = eng._caches
        assert k_stack["pages"].ndim == 5          # [L, NP, PS, nkv, hd]
        assert k_stack["pages"].shape[0] == 2      # num_layers
        scan_tokens = _decode_all(eng)
    with _engine(model=_gpt(scan_layers=False), **kw) as eng:
        unrolled = _decode_all(eng)
    for a, b in zip(scan_tokens, unrolled):
        np.testing.assert_array_equal(a, b)


def test_scan_layers_paged_tp2_identity():
    """Stacked paged pools shard on the head axis (the leading L axis
    stays untouched by the one cache-sharding rule) and the tp=2
    stream matches single-chip."""
    kw = {"paged": True, "page_size": 16, "num_pages": 24}
    with _engine(model=_gpt_scan(), **kw) as eng:
        want = _decode_all(eng)
    with _engine(tp=2, model=_gpt_scan(), **kw) as eng:
        got = _decode_all(eng)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# fused-kernel knobs x TP: loud fallback, never silently-wrong Pallas
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("knob", ["PADDLE_TPU_FUSED_CACHE_WRITE",
                                  "PADDLE_TPU_MEGA_DECODE"])
def test_fused_knob_falls_back_loudly_on_tp_mesh(knob, monkeypatch):
    """A fused-kernel env knob set on a sharded engine must (a) warn
    ONCE, (b) surface in stats()["fused_knobs_disabled_tp"], and
    (c) dispatch the unfused path — token streams stay identical to
    the knob-off engine. The Pallas kernels assume whole-array block
    specs; running them under pjit sharding would be silently wrong,
    so the dispatch refuses, audibly."""
    import importlib
    # the functional package re-exports a flash_attention FUNCTION that
    # shadows the submodule attribute — import the module by name
    fa = importlib.import_module("paddle_tpu.nn.functional.flash_attention")
    monkeypatch.setenv(knob, "1")
    fa._TP_KNOB_WARNED.discard(knob)   # per-process once: rearm
    want = _baseline("slot_f32")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with _engine(tp=2) as eng:
            st = eng.stats()
            got = _decode_all(eng)
    hits = [w for w in caught if knob in str(w.message)
            and issubclass(w.category, RuntimeWarning)]
    assert len(hits) == 1, "expected exactly one loud fallback warning"
    assert knob in st["fused_knobs_disabled_tp"]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    # a single-chip engine with the same knob is NOT degraded
    fa._TP_KNOB_WARNED.discard(knob)
    with _engine() as eng:
        assert eng.stats()["fused_knobs_disabled_tp"] == []


# ---------------------------------------------------------------------------
# registry / lint completeness
# ---------------------------------------------------------------------------

TP_SITES = ("gpt_decode_tp", "gpt_decode_tp_q", "gpt_admit_tp",
            "llama_decode_tp")


def test_registry_has_tp_sites():
    """The sharded lifecycle is registry-covered by default: all four
    *_tp sites registered, gated on 2+ devices, with the collective
    inventory compiled."""
    from paddle_tpu.compilation import registry
    from paddle_tpu.compilation.sites import ensure_registered
    ensure_registered()
    names = registry.names(tag="manifest")
    for site in TP_SITES:
        assert site in names, f"{site} missing from the registry"
        prog = registry.get(site)
        assert prog.min_devices == 2
        assert prog.compile_collectives
        assert "serving" in prog.tags and "collectives" in prog.tags


def test_tpulint_baseline_anchors_tp_sites():
    """tpulint's must_stay_clean anchors pin the TP sites' hygiene —
    scatter-free cache writes, donated buffers, argument-threaded RNG,
    no host callbacks — exactly like every other engine site."""
    import os
    base = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpulint_baseline.json")
    with open(base) as f:
        clean = json.load(f)["must_stay_clean"]
    for site in TP_SITES:
        for kind in ("scatter-op", "undonated-buffer",
                     "baked-rng-key", "host-callback"):
            assert f"{kind}::{site}" in clean, \
                f"{kind}::{site} not anchored in tpulint baseline"


def test_tpucost_baseline_anchors_tp_sites():
    """tpucost pins the sharded tick: a per-chip decode_hbm anchor on
    gpt_decode_tp and the fp32-vs-int8 comm_bytes ratio floor on the
    _q twin (wire-precision wins must not silently revert)."""
    import os
    base = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpucost_baseline.json")
    with open(base) as f:
        b = json.load(f)
    assert b["anchors"]["gpt_decode_tp"]["kind"] == "decode_hbm"
    q = b["anchors"]["gpt_decode_tp_q"]
    assert q["kind"] == "comm_bytes"
    assert q["baseline_program"] == "gpt_decode_tp"
    assert q["min_ratio"] >= 1.1
    for site in TP_SITES:
        assert site in b["budgets"], f"{site} has no tpucost budget"


# ---------------------------------------------------------------------------
# obs: mesh gauge + tier summing over mixed tp
# ---------------------------------------------------------------------------

def test_mesh_gauge_and_mixed_tp_tier_summing():
    """ptpu_engine_mesh_devices reports each engine's slice width, and
    render_tier's ptpu_tier_* summation over a MIXED tier (one tp=1
    replica, one tp=2 replica) yields total serving chips = 3."""
    from paddle_tpu.obs import metrics as _metrics
    reg = _metrics.registry

    def scrape():
        return reg.render()

    def gauge_value(text):
        for name, labels, v in _metrics.parse_text(text):
            if name == "ptpu_engine_mesh_devices" and not labels:
                return v
        raise AssertionError("ptpu_engine_mesh_devices not exported")

    with _engine() as eng:
        eng.warmup()
        text_tp1 = scrape()
        assert gauge_value(text_tp1) == 1
    with _engine(tp=2) as eng:
        eng.warmup()
        text_tp2 = scrape()
        assert gauge_value(text_tp2) == 2

    tier = _metrics.render_tier("", {"r1": text_tp1, "r2": text_tp2})
    totals = {name: v for name, labels, v in _metrics.parse_text(tier)
              if name == "ptpu_tier_engine_mesh_devices"}
    assert totals and list(totals.values())[0] == 3


def test_tp_allreduce_span_recorded():
    """Every sharded tick records an engine.tp_allreduce span carrying
    the modeled per-chip wire bytes (the number tpucost anchors and
    bench_tp_decode tabulates)."""
    from paddle_tpu import obs as _obs
    with _engine(tp=2) as eng:
        eng.generate(PROMPTS[0], max_new_tokens=6, timeout=300)
        spans = [e for e in _obs.recorder.events()
                 if e["name"] == "engine.tp_allreduce"]
        modeled = eng.tp_tick_comm_bytes
    assert spans, "no engine.tp_allreduce span in the flight recorder"
    args = spans[-1]["args"]
    assert args["tp"] == 2
    assert args["modeled_comm_bytes"] == modeled > 0


# ---------------------------------------------------------------------------
# live tier: replica = tp=2 slice
# ---------------------------------------------------------------------------

def test_live_tier_of_tp2_slices(tmp_path):
    """A 2-replica tier where EACH replica is a tp=2 slice: children
    get 2 virtual devices, /healthz snapshots carry the mesh shape,
    and the tier's generate output matches a direct single-chip
    engine — the identity oracle composed through the fleet."""
    from paddle_tpu.inference.router import (ReplicaSpec, Router,
                                             single_device_child_env)
    model_spec = {"kind": "gpt", "vocab_size": 128, "hidden_size": 32,
                  "num_layers": 1, "num_heads": 2, "max_seq_len": 64}
    engine_spec = {"slots": 2, "max_len": 48, "cache_dtype": "float32",
                   "prefill_buckets": [8], "tick_tokens": 2}
    spec = ReplicaSpec(model_spec, engine_spec, warmup=True,
                       drain_s=5.0, seed=0, tp=2,
                       env=single_device_child_env(tp=2))
    router = Router(spec, replicas=2, poll_s=0.25, deadline_s=120.0,
                    workdir=str(tmp_path))
    router.start()
    try:
        assert router.wait_ready(2, timeout=240), router.replicas()
        reps = router.replicas()
        assert all(r["tp"] == 2 and r["mesh_devices"] == 2
                   for r in reps), reps
        assert all(r.get("mesh", {}).get("mesh_axis") == "mp"
                   for r in reps), reps
        req = urllib.request.Request(
            f"http://{router.host}:{router.port}/generate",
            json.dumps({"input_ids": [1, 2, 3, 4],
                        "max_new_tokens": 8}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            body = json.loads(r.read())
    finally:
        router.stop()
    _rng.seed(0)
    direct_model = GPTForCausalLM(GPTConfig(
        **{k: v for k, v in model_spec.items() if k != "kind"}))
    with ContinuousBatchingEngine(
            direct_model,
            **{**engine_spec,
               "prefill_buckets": tuple(engine_spec["prefill_buckets"])}
            ) as eng:
        direct = eng.generate([1, 2, 3, 4], max_new_tokens=8).tolist()
    assert body["tokens"] == direct
