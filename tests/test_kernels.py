"""Fused Pallas kernel library (ISSUE 19): interpret-mode unit tests.

kernels/fused_ce.py, kernels/cache_write.py, kernels/mega_decode.py run
grid-free in interpret mode on CPU — the same bodies compile gridded on
TPU. Identity targets are the UNFUSED chains they replace: jax.nn
softmax/logsumexp for cross-entropy, flash_attention.py's one-hot write
+ read + masked-softmax chain for the decode paths. The dispatch knobs
(PADDLE_TPU_FUSED_CE / _FUSED_CACHE_WRITE / _MEGA_DECODE) are exercised
through the real functionals, not by monkeypatching internals.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels import (ce_bwd, ce_fwd, fused_paged_write,
                                fused_slot_write, mega_decode_step,
                                online_lse)
from importlib import import_module

# the functional package re-exports a *function* named flash_attention,
# shadowing the submodule on attribute access — import the module itself
fa = import_module("paddle_tpu.nn.functional.flash_attention")
loss_mod = import_module("paddle_tpu.nn.functional.loss")


def _rand(*shape, seed=0, scale=1.0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype("float32") * scale)


# ---------------------------------------------------------------- fused CE

class TestFusedCE:
    N, V = 24, 384

    def _fixture(self, dtype=jnp.float32, seed=3):
        rs = np.random.RandomState(seed)
        lg = jnp.asarray(rs.randn(self.N, self.V) * 3).astype(dtype)
        labels = jnp.asarray(rs.randint(0, self.V, self.N), jnp.int32)
        return lg, labels

    def test_online_lse_matches_logsumexp(self):
        lg, _ = self._fixture()
        ref = jax.scipy.special.logsumexp(lg, axis=-1)
        np.testing.assert_allclose(online_lse(lg), ref, atol=1e-5)

    def test_online_lse_padded_tail_excluded(self):
        lg, _ = self._fixture()
        vv = self.V - 96
        junk = lg.at[:, vv:].set(1e4)   # tail junk must contribute 0
        ref = jax.scipy.special.logsumexp(lg[:, :vv], axis=-1)
        np.testing.assert_allclose(online_lse(junk, valid_vocab=vv),
                                   ref, atol=1e-5)

    def test_online_lse_inf_pairing_no_nan(self):
        # reduce order is unspecified: a tree reduction can combine two
        # -inf lanes even when the row has valid columns. Leading -inf
        # entries force the sequential CPU fold through the same
        # (-inf, -inf) monoid combine — must yield 0 weight, not nan.
        lg, _ = self._fixture()
        lg = lg.at[:, :2].set(-jnp.inf)
        ref = jax.scipy.special.logsumexp(lg, axis=-1)
        out = online_lse(lg)
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_online_lse_all_masked_row_is_neg_inf(self):
        # a fully -inf row is an empty sum: LSE is -inf, never nan
        out = online_lse(jnp.full((3, 16), -jnp.inf, jnp.float32))
        assert bool(jnp.all(out == -jnp.inf))

    def test_ce_fwd_matches_reference(self):
        lg, labels = self._fixture()
        per, lse = ce_fwd(lg, labels, interpret=True)
        ref_lse = jax.scipy.special.logsumexp(lg, axis=-1)
        ref_per = ref_lse - jnp.take_along_axis(
            lg, labels[:, None], 1)[:, 0]
        assert per.dtype == jnp.float32
        np.testing.assert_allclose(per, ref_per, atol=1e-5)
        np.testing.assert_allclose(lse, ref_lse, atol=1e-5)

    def test_ce_bwd_matches_reference(self):
        lg, labels = self._fixture()
        _, lse = ce_fwd(lg, labels, interpret=True)
        g = _rand(self.N, seed=7)
        dlg = ce_bwd(lg, labels, lse, g, interpret=True)
        ref = ((jax.nn.softmax(lg, axis=-1)
                - jax.nn.one_hot(labels, self.V)) * g[:, None])
        np.testing.assert_allclose(dlg, ref, atol=1e-5)

    def test_ce_bf16_computes_f32(self):
        lg, labels = self._fixture(dtype=jnp.bfloat16)
        per, lse = ce_fwd(lg, labels, interpret=True)
        assert per.dtype == jnp.float32
        ref = (jax.scipy.special.logsumexp(
                   lg.astype(jnp.float32), axis=-1)
               - jnp.take_along_axis(lg.astype(jnp.float32),
                                     labels[:, None], 1)[:, 0])
        # bf16 inputs, f32 accumulation: tolerance is the input grid
        np.testing.assert_allclose(per, ref, atol=5e-2)
        dlg = ce_bwd(lg, labels, lse, _rand(self.N, seed=9),
                     interpret=True)
        assert dlg.dtype == jnp.bfloat16

    def test_ce_padded_vocab_bwd_zeros_tail(self):
        lg, _ = self._fixture()
        vv = self.V - 128
        labels = jnp.asarray(
            np.random.RandomState(0).randint(0, vv, self.N), jnp.int32)
        junk = lg.at[:, vv:].set(1e4)
        per, lse = ce_fwd(junk, labels, valid_vocab=vv, interpret=True)
        ref_lse = jax.scipy.special.logsumexp(lg[:, :vv], axis=-1)
        ref_per = ref_lse - jnp.take_along_axis(
            lg, labels[:, None], 1)[:, 0]
        np.testing.assert_allclose(per, ref_per, atol=1e-5)
        dlg = ce_bwd(junk, labels, lse, _rand(self.N, seed=1),
                     valid_vocab=vv, interpret=True)
        assert bool(jnp.all(dlg[:, vv:] == 0))

    def test_ce_gridded_path_n_above_block(self):
        # the TPU kernel body: N > block_n and V > block_v, neither a
        # multiple of its block, so labels must be consumed per
        # row-block (a whole-[N] compare fails to trace here)
        N, V, bn, bv = 37, 200, 8, 64
        rs = np.random.RandomState(5)
        lg = jnp.asarray(rs.randn(N, V).astype("float32") * 3)
        labels = jnp.asarray(rs.randint(0, V, N), jnp.int32)
        per, lse = ce_fwd(lg, labels, block_n=bn, block_v=bv,
                          interpret=True, force_grid=True)
        ref_lse = jax.scipy.special.logsumexp(lg, axis=-1)
        ref_per = ref_lse - jnp.take_along_axis(
            lg, labels[:, None], 1)[:, 0]
        np.testing.assert_allclose(per, ref_per, atol=1e-5)
        np.testing.assert_allclose(lse, ref_lse, atol=1e-5)
        g = _rand(N, seed=6)
        dlg = ce_bwd(lg, labels, lse, g, block_n=bn, block_v=bv,
                     interpret=True, force_grid=True)
        ref = ((jax.nn.softmax(lg, axis=-1)
                - jax.nn.one_hot(labels, V)) * g[:, None])
        np.testing.assert_allclose(dlg, ref, atol=1e-5)

    def test_ce_gridded_path_padded_vocab(self):
        # gridded + valid_vocab: whole trailing vocab blocks are fully
        # masked, exercising the in-kernel -inf monoid guards
        N, V, vv, bn, bv = 20, 256, 100, 8, 64
        rs = np.random.RandomState(7)
        lg = jnp.asarray(rs.randn(N, V).astype("float32") * 3)
        junk = lg.at[:, vv:].set(1e4)
        labels = jnp.asarray(rs.randint(0, vv, N), jnp.int32)
        per, lse = ce_fwd(junk, labels, valid_vocab=vv, block_n=bn,
                          block_v=bv, interpret=True, force_grid=True)
        ref_lse = jax.scipy.special.logsumexp(lg[:, :vv], axis=-1)
        ref_per = ref_lse - jnp.take_along_axis(
            lg, labels[:, None], 1)[:, 0]
        np.testing.assert_allclose(per, ref_per, atol=1e-5)
        dlg = ce_bwd(junk, labels, lse, _rand(N, seed=8),
                     valid_vocab=vv, block_n=bn, block_v=bv,
                     interpret=True, force_grid=True)
        assert bool(jnp.all(dlg[:, vv:] == 0))
        assert bool(jnp.all(jnp.isfinite(dlg)))

    def test_dispatch_value_and_grad_match_unfused(self, monkeypatch):
        lg, labels = self._fixture()

        def loss_of(ce):
            return lambda x: jnp.sum(ce(x, labels) * _rand(
                self.N, seed=11))

        v0, g0 = jax.value_and_grad(
            loss_of(loss_mod._fused_softmax_ce))(lg)
        v1, g1 = jax.value_and_grad(
            loss_of(loss_mod._pallas_softmax_ce))(lg)
        np.testing.assert_allclose(v0, v1, rtol=1e-6)
        np.testing.assert_allclose(g0, g1, atol=1e-5)

    def test_cross_entropy_knob(self, monkeypatch):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        lg, labels = self._fixture()
        x = paddle.to_tensor(np.asarray(lg))
        y = paddle.to_tensor(np.asarray(labels).astype("int64"))
        base = np.asarray(F.cross_entropy(x, y).value)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "1")
        fused = np.asarray(F.cross_entropy(x, y).value)
        np.testing.assert_allclose(base, fused, rtol=1e-6)


# ------------------------------------------------------------ cache writes

class TestFusedSlotWrite:
    def test_identity_with_unfused(self, monkeypatch):
        cache = _rand(3, 16, 2, 8, seed=0)
        rows = _rand(3, 1, 2, 8, seed=1)
        pos = jnp.asarray([0, 7, 15], jnp.int32)
        base = fa._cache_write(cache, rows, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        fused = fa._cache_write(cache, rows, pos)
        assert bool(jnp.array_equal(base, fused))

    def test_int8_dict_identity(self, monkeypatch):
        cache = {"data": jnp.zeros((2, 8, 2, 4), jnp.int8),
                 "scale": jnp.zeros((2, 8, 2), jnp.float32)}
        rows = _rand(2, 1, 2, 4, seed=2)
        pos = jnp.asarray([3, 5], jnp.int32)
        base = fa._cache_write(cache, rows, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        fused = fa._cache_write(cache, rows, pos)
        assert bool(jnp.array_equal(base["data"], fused["data"]))
        assert bool(jnp.array_equal(base["scale"], fused["scale"]))

    def test_kernel_direct(self):
        cache = _rand(2, 6, 1, 4, seed=4)
        rows = _rand(2, 1, 1, 4, seed=5)
        pos = jnp.asarray([2, 5], jnp.int32)
        out = fused_slot_write(cache, rows, pos, interpret=True)
        ref = cache
        for b in range(2):
            ref = ref.at[b, int(pos[b])].set(rows[b, 0])
        assert bool(jnp.array_equal(out, ref))


class TestFusedPagedWrite:
    def _cache(self, dtype="float32"):
        pool = fa.paged_kv_cache(6, 4, 2, 8, dtype=dtype)
        bt = jnp.asarray([[2, 0], [5, 1], [3, 4]], jnp.int32)
        return {**pool, "bt": bt}

    def test_identity_with_unfused(self, monkeypatch):
        cache = self._cache()
        rows = _rand(3, 1, 2, 8, seed=6)
        pos = jnp.asarray([1, 6, 3], jnp.int32)
        base = fa._paged_cache_write(cache, rows, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        fused = fa._paged_cache_write(cache, rows, pos)
        assert bool(jnp.array_equal(base["pages"], fused["pages"]))

    def test_live_and_wlen_gating_identity(self, monkeypatch):
        cache = {**self._cache(),
                 "live": jnp.asarray([True, False, True]),
                 "wlen": jnp.asarray(2, jnp.int32)}
        rows = _rand(3, 3, 2, 8, seed=8)      # S=3, only first 2 land
        pos = jnp.asarray([0, 4, 2], jnp.int32)
        base = fa._paged_cache_write(cache, rows, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        fused = fa._paged_cache_write(cache, rows, pos)
        assert bool(jnp.array_equal(base["pages"], fused["pages"]))

    def test_int8_pool_identity(self, monkeypatch):
        cache = self._cache(dtype="int8")
        rows = _rand(3, 1, 2, 8, seed=9)
        pos = jnp.asarray([1, 6, 3], jnp.int32)
        base = fa._paged_cache_write(cache, rows, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        fused = fa._paged_cache_write(cache, rows, pos)
        assert bool(jnp.array_equal(base["pages"], fused["pages"]))
        assert bool(jnp.array_equal(base["scale"], fused["scale"]))

    def test_kernel_direct(self):
        pages = _rand(5, 3, 1, 2, seed=10)
        rows = _rand(4, 1, 2, seed=11)
        phys = jnp.asarray([4, 0, 2, 1], jnp.int32)
        off = jnp.asarray([0, 2, 1, 2], jnp.int32)
        valid = jnp.asarray([1, 0, 1, 1], jnp.int32)
        out = fused_paged_write(pages, rows, phys, off, valid,
                                interpret=True)
        ref = pages
        for i in range(4):
            if int(valid[i]):
                ref = ref.at[int(phys[i]), int(off[i])].set(rows[i])
        assert bool(jnp.array_equal(out, ref))


class TestGriddedKernelPaths:
    """The interpret dispatch runs grid-free bodies, so the gridded
    (TPU) bodies were invisible to tests — the fused-CE labels
    broadcast bug hid exactly there. These force the gridded kernels
    through the interpreter so their blocked index/broadcast logic is
    trace-covered on CPU. (fused-CE's gridded path has its own
    ``force_grid`` tests above.)"""

    @pytest.fixture
    def force_interpret(self, monkeypatch):
        from jax.experimental import pallas as pl
        orig = pl.pallas_call
        monkeypatch.setattr(
            pl, "pallas_call",
            lambda *a, **kw: orig(*a, **{**kw, "interpret": True}))

    def test_slot_write_gridded(self, force_interpret):
        cache = _rand(3, 16, 2, 8, seed=20)
        rows = _rand(3, 1, 2, 8, seed=21)
        pos = jnp.asarray([0, 7, 15], jnp.int32)
        out = fused_slot_write(cache, rows, pos, interpret=False)
        ref = cache
        for b in range(3):
            ref = ref.at[b, int(pos[b])].set(rows[b, 0])
        assert bool(jnp.array_equal(out, ref))

    def test_paged_write_gridded(self, force_interpret):
        pages = _rand(5, 3, 1, 2, seed=22)
        rows = _rand(4, 1, 2, seed=23)
        phys = jnp.asarray([4, 0, 2, 1], jnp.int32)
        off = jnp.asarray([0, 2, 1, 2], jnp.int32)
        valid = jnp.asarray([1, 0, 1, 1], jnp.int32)
        out = fused_paged_write(pages, rows, phys, off, valid,
                                interpret=False)
        ref = pages
        for i in range(4):
            if int(valid[i]):
                ref = ref.at[int(phys[i]), int(off[i])].set(rows[i])
        assert bool(jnp.array_equal(out, ref))

    def test_mega_decode_gridded(self, force_interpret):
        q, k, v, kc, vc, pos = _decode_fixture(nh=4, nkv=2, L=8)
        ctx_g, kc_g, vc_g = mega_decode_step(q, k, v, kc, vc, pos,
                                             interpret=False)
        ctx_w, kc_w, vc_w = mega_decode_step(q, k, v, kc, vc, pos,
                                             interpret=True)
        np.testing.assert_allclose(np.asarray(ctx_g), np.asarray(ctx_w),
                                   atol=1e-6)
        assert bool(jnp.array_equal(kc_g, kc_w))
        assert bool(jnp.array_equal(vc_g, vc_w))


# ------------------------------------------------- fused decode attention

def _decode_fixture(nh=4, nkv=2, B=3, L=16, hd=8, int8=False, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(B, 1, nh, hd), jnp.float32)
    k = jnp.asarray(rs.randn(B, 1, nkv, hd), jnp.float32)
    v = jnp.asarray(rs.randn(B, 1, nkv, hd), jnp.float32)
    if int8:
        kc = {"data": jnp.asarray(rs.randint(-90, 90, (B, L, nkv, hd)),
                                  jnp.int8),
              "scale": jnp.asarray(np.abs(rs.randn(B, L, nkv)) * 0.02,
                                   jnp.float32)}
        vc = {"data": jnp.asarray(rs.randint(-90, 90, (B, L, nkv, hd)),
                                  jnp.int8),
              "scale": jnp.asarray(np.abs(rs.randn(B, L, nkv)) * 0.02,
                                   jnp.float32)}
    else:
        kc = jnp.asarray(rs.randn(B, L, nkv, hd), jnp.float32)
        vc = jnp.asarray(rs.randn(B, L, nkv, hd), jnp.float32)
    # corners: empty cache (pos 0), last slot (L-1), duplicate pos —
    # the states dead/eos slots park the decode loop in
    pos = jnp.asarray([0, L - 1, 5], jnp.int32)
    return q, k, v, kc, vc, pos


def _run_cached_attention(q, k, v, kc, vc, pos):
    ctx, kc2, vc2 = fa.cached_attention(q, k, v, kc, vc, pos)
    arr = getattr(ctx, "value", ctx)
    return np.asarray(arr), kc2, vc2


class TestFusedDecodeAttention:
    @pytest.mark.parametrize("nh,nkv", [(4, 4), (4, 2)])
    def test_identity_with_unfused(self, monkeypatch, nh, nkv):
        args = _decode_fixture(nh=nh, nkv=nkv)
        ctx0, kc0, vc0 = _run_cached_attention(*args)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        ctx1, kc1, vc1 = _run_cached_attention(*args)
        # caches: bit-exact (same rows blended at the same slots);
        # ctx: softmax reassociation only (PERF.md PR 19 bound)
        assert bool(jnp.array_equal(kc0, kc1))
        assert bool(jnp.array_equal(vc0, vc1))
        np.testing.assert_allclose(ctx0, ctx1, atol=1e-5)
        assert np.argmax(ctx0[..., -1]) == np.argmax(ctx1[..., -1])

    def test_int8_dict_identity(self, monkeypatch):
        args = _decode_fixture(int8=True)
        ctx0, kc0, vc0 = _run_cached_attention(*args)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        ctx1, kc1, vc1 = _run_cached_attention(*args)
        assert bool(jnp.array_equal(kc0["data"], kc1["data"]))
        assert bool(jnp.array_equal(kc0["scale"], kc1["scale"]))
        assert bool(jnp.array_equal(vc0["data"], vc1["data"]))
        np.testing.assert_allclose(ctx0, ctx1, atol=1e-5)

    def test_multi_token_path_unaffected(self, monkeypatch):
        # S>1 (verify block) must keep the unfused chain bit-exactly:
        # the fused path is S=1-only by dispatch condition
        q, k, v, kc, vc, _ = _decode_fixture()
        q = _rand(3, 4, 4, 8, seed=13)
        k = _rand(3, 4, 2, 8, seed=14)
        v = _rand(3, 4, 2, 8, seed=15)
        pos = jnp.asarray([0, 3, 5], jnp.int32)
        ctx0, kc0, vc0 = _run_cached_attention(q, k, v, kc, vc, pos)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CACHE_WRITE", "1")
        ctx1, kc1, vc1 = _run_cached_attention(q, k, v, kc, vc, pos)
        assert bool(jnp.array_equal(ctx0, ctx1))
        assert bool(jnp.array_equal(kc0, kc1))


class TestMegaDecode:
    def test_identity_with_unfused(self, monkeypatch):
        args = _decode_fixture(nh=4, nkv=2)
        ctx0, kc0, vc0 = _run_cached_attention(*args)
        monkeypatch.setenv("PADDLE_TPU_MEGA_DECODE", "1")
        ctx1, kc1, vc1 = _run_cached_attention(*args)
        assert bool(jnp.array_equal(kc0, kc1))
        assert bool(jnp.array_equal(vc0, vc1))
        np.testing.assert_allclose(ctx0, ctx1, atol=1e-5)

    def test_kernel_direct_empty_and_full(self):
        q, k, v, kc, vc, pos = _decode_fixture(nh=2, nkv=2, L=8)
        ctx, kc2, vc2 = mega_decode_step(q, k, v, kc, vc, pos,
                                         interpret=True)
        # write landed at pos[b] exactly, everything else untouched
        for b, p in enumerate(np.asarray(pos)):
            np.testing.assert_array_equal(
                np.asarray(kc2[b, p]), np.asarray(k[b, 0]))
            rest = np.delete(np.asarray(kc2[b]), p, axis=0)
            ref = np.delete(np.asarray(kc[b]), p, axis=0)
            np.testing.assert_array_equal(rest, ref)
        # pos=0 row (empty cache): attention is ONLY the new row ->
        # ctx equals v exactly (softmax of a single logit is 1)
        np.testing.assert_allclose(np.asarray(ctx[0, 0]),
                                   np.asarray(v[0, 0]), atol=1e-6)

    def test_mega_skips_int8_and_paged(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_MEGA_DECODE", "1")
        args = _decode_fixture(int8=True)
        base = _decode_fixture(int8=True)
        ctx0, kc0, _ = _run_cached_attention(*base)
        ctx1, kc1, _ = _run_cached_attention(*args)
        # dict caches fall back to the unfused chain, bit-exactly
        assert bool(jnp.array_equal(ctx0, ctx1))
        assert bool(jnp.array_equal(kc0["data"], kc1["data"]))
