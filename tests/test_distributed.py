"""Distributed tests on the 8-device virtual CPU mesh.

Replaces the reference's subprocess-per-rank harness (test_collective_base.py
TestDistBase:144 spawning trainers) with global-array collectives — the
backend-agnostic simulated ProcessGroup SURVEY.md §4 calls for. Numeric
checks mirror the reference's collective op tests.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.meta_parallel import (ColumnParallelLinear,
                                                  RowParallelLinear,
                                                  VocabParallelEmbedding)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _stack(n, shape, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(n, *shape).astype("float32")


def test_mesh_init_degrees():
    mesh = dist.init_mesh({"dp": 2, "mp": 4})
    assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 4
    mesh = dist.init_mesh({"dp": -1, "mp": 2})
    assert mesh.shape["dp"] == 4


def test_all_reduce_sum():
    dist.init_mesh({"dp": 8})
    x = _stack(8, (4, 3))
    t = paddle.to_tensor(x)
    dist.all_reduce(t)
    expect = np.broadcast_to(x.sum(0, keepdims=True), x.shape)
    np.testing.assert_allclose(t.numpy(), expect, rtol=1e-5)


def test_all_reduce_max_on_group_axis():
    dist.init_mesh({"dp": 2, "mp": 4})
    g = dist.new_group(axis="mp")
    x = _stack(4, (5,))
    t = paddle.to_tensor(x)
    dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
    np.testing.assert_allclose(
        t.numpy(), np.broadcast_to(x.max(0, keepdims=True), x.shape),
        rtol=1e-6)


def test_new_group_ranks_axis_rows_ok():
    dist.init_mesh({"dp": 2, "mp": 4})
    g = dist.new_group(ranks=[0, 1, 2, 3], axis="mp")
    assert g.nranks == 4
    # second mp row (global ranks) is just as valid
    g2 = dist.new_group(ranks=[4, 5, 6, 7], axis="mp")
    assert g2.nranks == 4
    # dp rows are strided in global rank space
    g3 = dist.new_group(ranks=[1, 5], axis="dp")
    assert g3.nranks == 2


def test_new_group_rank_subset_rejected():
    dist.init_mesh({"dp": 2, "mp": 4})
    with pytest.raises(ValueError, match="mesh ax"):
        dist.new_group(ranks=[0, 1], axis="mp")
    with pytest.raises(ValueError, match="mesh ax"):
        dist.new_group(ranks=[1, 3, 5, 7], axis="mp")
    with pytest.raises(ValueError, match="mesh has axes"):
        dist.new_group(axis="pd")


def test_uneven_alltoall_single_controller_guidance():
    dist.init_mesh({"dp": 8})
    t = paddle.to_tensor(np.zeros((8, 8), "float32"))
    with pytest.raises(NotImplementedError, match="multi-process"):
        dist.alltoall_single(None, t, in_split_sizes=[1, 2, 1, 1, 1, 1, 1],
                             out_split_sizes=[1] * 7)
    with pytest.raises(ValueError, match="BOTH"):
        dist.alltoall_single(None, t, in_split_sizes=[1, 2, 1, 1, 1, 1, 1])


def test_p2p_raises_under_single_controller():
    dist.init_mesh({"dp": 8})
    t = paddle.to_tensor(np.zeros(4, "float32"))
    for fn in (dist.send, dist.recv, dist.isend, dist.irecv):
        with pytest.raises(NotImplementedError, match="multi-process"):
            fn(t, 1)


def test_all_gather():
    dist.init_mesh({"dp": 8})
    x = _stack(8, (2, 2))
    out = []
    dist.all_gather(out, paddle.to_tensor(x))
    assert len(out) == 8
    for i in range(8):
        np.testing.assert_allclose(out[i].numpy(), x[i], rtol=1e-6)


def test_broadcast():
    dist.init_mesh({"dp": 8})
    x = _stack(8, (3,))
    t = paddle.to_tensor(x)
    dist.broadcast(t, src=3)
    np.testing.assert_allclose(
        t.numpy(), np.broadcast_to(x[3], x.shape), rtol=1e-6)


def test_reduce_scatter():
    dist.init_mesh({"dp": 4})
    x = _stack(4, (8, 2))  # each "rank" holds [8,2]; scatter into 4 blocks
    out = paddle.to_tensor(np.zeros((4, 2, 2), "float32"))
    dist.reduce_scatter(out, paddle.to_tensor(x))
    # rank i's result = sum over ranks of block i (rows 2i..2i+2)
    blocks = x.reshape(4, 4, 2, 2).sum(0)  # [dst_block, 2, 2]
    np.testing.assert_allclose(out.numpy(), blocks, rtol=1e-5)


def test_alltoall():
    dist.init_mesh({"dp": 4})
    x = _stack(4, (4, 3))  # [src, dst, *S]
    out = []
    dist.alltoall(out, paddle.to_tensor(x))
    got = np.stack([o.numpy() for o in out])
    np.testing.assert_allclose(got, x.transpose(1, 0, 2), rtol=1e-6)


def test_dp_training_matches_single_device():
    """SPMD data parallelism must be numerically invisible (reference:
    test_parallel_dygraph_* loss-parity pattern)."""
    rng = np.random.RandomState(0)
    x_np = rng.randn(16, 8).astype("float32")
    y_np = rng.randn(16, 2).astype("float32")

    def build():
        paddle.seed(42)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=m.parameters())
        return m, opt

    # single device
    dist.init_mesh({"dp": 1})
    m1, o1 = build()
    s1 = dist.ParallelTrainStep(m1, lambda o, y: F.mse_loss(o, y), o1)
    # 8-way dp
    dist.init_mesh({"dp": 8})
    m2, o2 = build()
    s2 = dist.ParallelTrainStep(m2, lambda o, y: F.mse_loss(o, y), o2)

    for _ in range(5):
        l1 = float(s1(paddle.to_tensor(x_np), paddle.to_tensor(y_np)))
        l2 = float(s2(paddle.to_tensor(x_np), paddle.to_tensor(y_np)))
        np.testing.assert_allclose(l1, l2, rtol=2e-4)


def test_tp_layers_match_serial_and_shard():
    """Column/Row pair must equal a dense 2-layer MLP (reference:
    hybrid_parallel_mp_layers.py parity test)."""
    fleet.init(strategy=_mp_strategy(4))
    paddle.seed(0)
    col = ColumnParallelLinear(8, 16, gather_output=False)
    row = RowParallelLinear(16, 4, input_is_parallel=True)
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))

    ref = x.numpy() @ col.weight.numpy() + col.bias.numpy()
    ref = ref @ row.weight.numpy() + row.bias.numpy()

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col, self.row = col, row

        def forward(self, v):
            return self.row(self.col(v))

    blk = Block()
    dist.shard_params(blk)
    # weight physically sharded over mp
    shard_spec = col.weight.value.sharding.spec
    assert "mp" in str(shard_spec)
    out = blk(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_tp_training_step_runs_sharded():
    fleet.init(strategy=_mp_strategy(2, dp=4))

    class TPNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = VocabParallelEmbedding(32, 16)
            self.col = ColumnParallelLinear(16, 32, gather_output=False)
            self.row = RowParallelLinear(32, 16, input_is_parallel=True)
            self.head = nn.Linear(16, 32)

        def forward(self, ids):
            h = self.emb(ids)
            h = F.relu(self.col(h))
            h = self.row(h)
            return self.head(h)

    paddle.seed(1)
    m = TPNet()
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=m.parameters())
    step = dist.ParallelTrainStep(
        m, lambda o, y: paddle.mean(F.cross_entropy(
            paddle.reshape(o, [-1, 32]), paddle.reshape(y, [-1]))), opt)
    ids = paddle.to_tensor(np.random.randint(0, 32, (8, 6)).astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_zero_shards_optimizer_state():
    dist.init_mesh({"dp": 8})
    paddle.seed(0)
    m = nn.Linear(16, 16)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters())
    step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt,
                                  zero_stage=1)
    x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
    step(x, x)
    # moment slots must be laid out sharded over dp
    slot = step.opt_state["weight"]["moment1"]
    assert "dp" in str(slot.sharding.spec)


def test_data_parallel_wrapper():
    dist.init_mesh({"dp": 8})
    m = dist.DataParallel(nn.Linear(4, 4))
    x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
    y = m(x)
    assert y.shape == [8, 4]
    with m.no_sync():
        pass


def _mp_strategy(mp, dp=None):
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"mp_degree": mp,
                        "dp_degree": dp if dp else 8 // mp}
    return s


def test_all_reduce_prod_with_negatives_and_zeros():
    dist.init_mesh({"dp": 4})
    x = np.array([[-2.0, 3.0], [1.0, -1.0], [2.0, 0.0], [1.5, 2.0]],
                 dtype="float32").reshape(4, 2)
    t = paddle.to_tensor(x)
    dist.all_reduce(t, op=dist.ReduceOp.PROD)
    expect = np.broadcast_to(np.prod(x, axis=0), x.shape)
    np.testing.assert_allclose(t.numpy(), expect, rtol=1e-5)


def test_parallel_step_keeps_model_arrays_alive():
    dist.init_mesh({"dp": 8})
    m = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())
    step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt)
    x = paddle.to_tensor(np.random.randn(8, 4).astype("float32"))
    step(x, x)
    m(x).numpy()   # must not raise "Array has been deleted"


def test_alltoall_single_even_split():
    """Regression: the even-split alltoall_single path (latent shard-size
    bug — chunk j of rank i's vector must land at position i on rank j,
    i.e. a block transpose)."""
    import numpy as np
    dist.set_mesh(None)
    dist.init_mesh({"dp": 4})
    x = np.arange(16, dtype=np.float32).reshape(4, 4)
    out = dist.alltoall_single(None, paddle.to_tensor(x)).numpy()
    np.testing.assert_array_equal(out, x.T)
    # K=2 chunks
    x2 = np.arange(32, dtype=np.float32).reshape(4, 8)
    out2 = dist.alltoall_single(None, paddle.to_tensor(x2)).numpy()
    want = np.stack([np.concatenate([x2[i, 2 * j:2 * j + 2]
                                     for i in range(4)])
                     for j in range(4)])
    np.testing.assert_array_equal(out2, want)
    with pytest.raises(ValueError, match="divisible"):
        dist.alltoall_single(None, paddle.to_tensor(
            np.zeros((4, 6), np.float32)))
    dist.set_mesh(None)
