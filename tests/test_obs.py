"""Unified observability tests (paddle_tpu.obs, ISSUE 8).

Units first (registry semantics, bucket percentiles, the disabled
fast path), then the in-process engine/server integration (request-id
-> phase spans, /metrics monotonicity, /admin/trace), the crash paths
(StepWatchdog hang + NaN storm dump a parseable flight-recorder
artifact), and finally one module-scoped live 2-replica tier covering
the acceptance criteria: request ids resolve to spans whose phase sum
matches the measured end-to-end latency, the router aggregates replica
metrics, and a kill -9 produces a replica-death artifact naming the
request ids in flight.
"""
import glob
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import obs
from paddle_tpu.obs.metrics import (Registry, percentile_from_cum,
                                    render_tier)


# ---------------------------------------------------------------------------
# registry units
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_and_render_parse_roundtrip():
    reg = Registry()
    c = reg.counter("ptpu_ut_total", "x", labels=("k",))
    c.inc(2, k="a")
    c.inc(k="b")
    g = reg.gauge("ptpu_ut_gauge")
    g.set(7.5)
    h = reg.histogram("ptpu_ut_ms", "y", buckets=(1, 10, 100))
    for v in (0.5, 5, 50, 500):
        h.observe(v)
    text = reg.render()
    samples = obs.metrics.parse_text(text)
    d = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    assert d[("ptpu_ut_total", (("k", "a"),))] == 2.0
    assert d[("ptpu_ut_total", (("k", "b"),))] == 1.0
    assert d[("ptpu_ut_gauge", ())] == 7.5
    # histogram buckets are CUMULATIVE
    assert d[("ptpu_ut_ms_bucket", (("le", "1"),))] == 1.0
    assert d[("ptpu_ut_ms_bucket", (("le", "100"),))] == 3.0
    assert d[("ptpu_ut_ms_bucket", (("le", "+Inf"),))] == 4.0
    assert d[("ptpu_ut_ms_count", ())] == 4.0
    # same-name re-create returns the same family; kind mismatch raises
    assert reg.counter("ptpu_ut_total", labels=("k",)) is c
    with pytest.raises(TypeError):
        reg.gauge("ptpu_ut_total")


def test_seq_moves_on_every_mutation():
    reg = Registry()
    c = reg.counter("ptpu_seq_total")
    s0 = reg.seq()
    c.inc()
    assert reg.seq() == s0 + 1
    reg.histogram("ptpu_seq_ms").observe(3)
    assert reg.seq() == s0 + 2


def test_bounded_label_sets_fold_into_other():
    reg = Registry()
    c = reg.counter("ptpu_bound_total", labels=("replica",),
                    max_series=3)
    for i in range(10):
        c.inc(replica=f"r{i}")
    series = c.series()
    assert len(series) <= 4            # 3 real + the overflow series
    assert series[(obs.metrics.OVERFLOW_LABEL,)][0] == 7.0
    # the fold is a WRITE policy only: reading a never-written label
    # value misses cleanly instead of returning the overflow series
    assert c.value(replica="never_written") == 0.0
    assert c.value(replica="r0") == 1.0
    # wrong label names are an error, not a silent new series
    with pytest.raises(ValueError):
        c.inc(shard="x")
    # remove() drops a series (retired-replica gauge semantics)
    g = reg.gauge("ptpu_bound_gauge", labels=("replica",))
    g.set(1.0, replica="r1")
    g.remove(replica="r1")
    assert g.value(replica="r1") == 0.0
    assert (("r1",) not in g.series())


def test_histogram_percentile_estimation():
    reg = Registry()
    h = reg.histogram("ptpu_pct_ms", buckets=(10, 20, 40, 80))
    for v in [5] * 50 + [15] * 40 + [70] * 10:
        h.observe(v)
    snap = h.snap()
    assert snap.count == 100
    assert 0 < snap.percentile(0.25) <= 10
    assert 10 < snap.percentile(0.7) <= 20
    assert 40 < snap.percentile(0.99) <= 80
    # delta percentiles see only the new observations
    for v in [75] * 100:
        h.observe(v)
    d = h.snap().minus(snap)
    assert d.count == 100 and 40 < d.percentile(0.5) <= 80
    # the parser-side estimator agrees with the object-side one
    assert percentile_from_cum((10, 20, 40, 80), (50, 90, 90, 100, 100),
                               0.5) <= 10


def test_render_tier_aggregates_and_relabels():
    rep = ("# TYPE ptpu_x_total counter\n"
           "ptpu_x_total 3\n"
           "ptpu_h_ms_bucket{le=\"10\"} 2\n"
           "ptpu_h_ms_bucket{le=\"+Inf\"} 4\n")
    text = render_tier("ptpu_router_forwards_total 9\n",
                       {"r1": rep, "r2": rep})
    samples = obs.metrics.parse_text(text)
    d = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
    assert d[("ptpu_x_total", (("replica", "r1"),))] == 3.0
    assert d[("ptpu_tier_x_total", ())] == 6.0
    assert d[("ptpu_tier_h_ms_bucket", (("le", "10"),))] == 4.0
    assert d[("ptpu_router_forwards_total", ())] == 9.0


# ---------------------------------------------------------------------------
# tracer: spans, ring, disabled fast path
# ---------------------------------------------------------------------------

def test_span_records_and_ring_bounds():
    before = obs.recorder.appended
    with obs.span("ut.scope", cat="ut", request_id="ut-rid-1"):
        pass
    obs.record_span("ut.raw", 1.0, 1.001, cat="ut")
    assert obs.recorder.appended == before + 2
    ev = obs.recorder.events()[-2]
    assert ev["name"] == "ut.scope" and ev["ph"] == "X"
    assert ev["args"]["request_id"] == "ut-rid-1"
    assert obs.recorder.size >= 16


def test_disabled_fast_path_no_allocations_no_appends():
    obs.set_enabled(False)
    try:
        assert not obs.enabled()
        # span() hands back ONE shared no-op object — nothing is
        # allocated per call on the disabled path
        s1 = obs.span("ut.off", request_id="x")
        s2 = obs.span("ut.off2")
        assert s1 is s2
        before = obs.recorder.appended
        with s1:
            pass
        assert obs.recorder.appended == before
    finally:
        obs.set_enabled(None)


def test_profiler_window_is_bounded_both_ends():
    """A Profiler session owns [start, stop): events recorded after
    stop() (or before start, or with no session at all) must not leak
    into summary()/export()."""
    from paddle_tpu.profiler import Profiler, RecordEvent
    prof = Profiler(timer_only=True)
    assert prof._window_events() == []          # never started: no window
    prof.start()
    with RecordEvent("inside_window"):
        pass
    prof.stop()
    with RecordEvent("after_stop"):
        pass
    names = {e["name"] for e in prof._window_events()}
    assert "inside_window" in names
    assert "after_stop" not in names


def test_set_enabled_round_trip_does_not_poison_sync_mirror():
    """syncs' obs mirror must honor the set_enabled tri-state: a sync
    landing while obs is disabled must not disable the mirror
    forever."""
    from paddle_tpu.framework import syncs
    obs.set_enabled(False)
    try:
        syncs.record_sync()                      # lands while disabled
    finally:
        obs.set_enabled(None)
    before = obs.metrics.registry.counter(
        "ptpu_host_syncs_total",
        "device->host materializations (framework/syncs)").value()
    syncs.record_sync()
    after = obs.metrics.registry.get(
        "ptpu_host_syncs_total").value()
    assert after == before + 1


def test_disabled_engine_ticks_append_nothing():
    """The engine snapshots the obs flag at construction: disabled, a
    full submit->decode->retire cycle touches neither the ring nor the
    phase histograms (counter-asserted — the no-allocation tick)."""
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=16,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=48))
    model.eval()
    obs.set_enabled(False)
    try:
        engine = ContinuousBatchingEngine(
            model, slots=2, max_len=40, cache_dtype="float32",
            prefill_buckets=(8,), tick_tokens=2)
    finally:
        obs.set_enabled(None)
    try:
        before = obs.recorder.appended
        ticks_h = obs.metrics.registry.get("ptpu_engine_ticks_total")
        t0 = ticks_h.value() if ticks_h is not None else 0
        engine.generate([1, 2, 3], max_new_tokens=6, timeout=120)
        assert engine.ticks > 0
        assert obs.recorder.appended == before
        if ticks_h is not None:
            assert ticks_h.value() == t0
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# engine + server integration (in-process)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_server():
    from paddle_tpu.inference.engine import ContinuousBatchingEngine
    from paddle_tpu.inference.serve import PredictorServer
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=96, hidden_size=16,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=64))
    model.eval()
    engine = ContinuousBatchingEngine(
        model, slots=2, max_len=56, cache_dtype="float32",
        prefill_buckets=(8,), tick_tokens=2)
    srv = PredictorServer(engine=engine, port=0).start()
    yield srv
    srv.stop()
    engine.stop()


def _post(base, path, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        dict({"Content-Type": "application/json"}, **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.mark.timeout(180)
def test_request_id_resolves_to_phase_spans(live_server):
    base = f"http://{live_server.host}:{live_server.port}"
    rid = "obs-test-rid-7"
    t0 = time.perf_counter()
    code, body = _post(base, "/generate",
                       {"input_ids": [1, 2, 3], "max_new_tokens": 8},
                       headers={"X-PTPU-Request-Id": rid})
    e2e_ms = (time.perf_counter() - t0) * 1e3
    assert code == 200 and body["request_id"] == rid
    code, doc = _post(base, "/admin/trace?duration_s=0", {})
    assert code == 200
    spans = {e["name"]: e for e in doc["traceEvents"]
             if e.get("args", {}).get("request_id") == rid}
    assert {"engine.queue_wait", "engine.prefill",
            "engine.decode"} <= set(spans)
    phase_ms = sum(spans[n]["dur"] for n in
                   ("engine.queue_wait", "engine.prefill",
                    "engine.decode")) / 1e3
    # the three phases are contiguous submit->retire: their sum is the
    # engine-side latency, which must sit just under the client's e2e
    assert 0 < phase_ms <= e2e_ms
    assert phase_ms >= 0.5 * e2e_ms, (phase_ms, e2e_ms)
    # phases are ordered and contiguous on the timeline
    qw, pf, dec = (spans["engine.queue_wait"], spans["engine.prefill"],
                   spans["engine.decode"])
    assert qw["ts"] <= pf["ts"] <= dec["ts"]


@pytest.mark.timeout(180)
def test_phase_sum_matches_engine_e2e_within_10pct(live_server):
    """The acceptance bound, measured where it is meaningful: at the
    engine, queue+prefill+decode are CONTIGUOUS submit->retire, so
    their sum must sit within 10% of the blocking-call latency (the
    HTTP layer adds real overhead on top — the serve.generate span
    covers that, asserted in the request-id test)."""
    engine = live_server.engine
    rid = "obs-direct-e2e"
    t0 = time.perf_counter()
    engine.submit([2, 3, 4], max_new_tokens=24,
                  request_id=rid).result(timeout=120)
    e2e_ms = (time.perf_counter() - t0) * 1e3
    spans = {e["name"]: e for e in obs.recorder.events()
             if e.get("args", {}).get("request_id") == rid}
    phase_ms = sum(spans[n]["dur"] for n in
                   ("engine.queue_wait", "engine.prefill",
                    "engine.decode")) / 1e3
    assert phase_ms <= e2e_ms
    assert phase_ms >= 0.9 * e2e_ms, (phase_ms, e2e_ms)


@pytest.mark.timeout(180)
def test_metrics_endpoint_parses_and_is_monotonic(live_server):
    base = f"http://{live_server.host}:{live_server.port}"

    def scrape():
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert "text/plain" in r.headers.get("Content-Type", "")
            return obs.metrics.parse_text(r.read().decode())

    def val(samples, name):
        return sum(v for n, _, v in samples if n == name)

    _post(base, "/generate", {"input_ids": [5, 6], "max_new_tokens": 4})
    s1 = scrape()
    _post(base, "/generate", {"input_ids": [7, 8], "max_new_tokens": 4})
    s2 = scrape()
    for name in ("ptpu_engine_ticks_total", "ptpu_engine_admits_total",
                 "ptpu_engine_retires_total"):
        assert val(s1, name) > 0
        assert val(s2, name) > val(s1, name), name
    # phase + occupancy histograms are exported
    for name in ("ptpu_engine_ttft_ms_count",
                 "ptpu_engine_queue_wait_ms_count",
                 "ptpu_engine_decode_ms_count",
                 "ptpu_engine_batch_occupancy_count"):
        assert val(s2, name) > 0, name
    # healthz carries the freshness token + uptime
    with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
        hz = json.loads(r.read())
    assert hz["metrics_seq"] > 0 and hz["uptime_s"] >= 0


# ---------------------------------------------------------------------------
# crash paths: flight-recorder artifacts
# ---------------------------------------------------------------------------

def _artifacts(d, reason):
    return sorted(glob.glob(os.path.join(d, f"flight_{reason}_*.trace.json")))


@pytest.mark.timeout(60)
def test_watchdog_hang_dumps_flight_artifact(tmp_path, monkeypatch):
    from paddle_tpu.distributed.resilience import (FaultInjector,
                                                   StepTimeout,
                                                   StepWatchdog)
    monkeypatch.setenv("PADDLE_TPU_OBS_DIR", str(tmp_path))
    with obs.span("ut.pre_hang", cat="ut", request_id="hang-rid"):
        pass
    wd = StepWatchdog(deadline=0.4, nan_limit=3)
    try:
        with FaultInjector({"step_hang": 1}, wedge_s=3.0):
            with pytest.raises(StepTimeout):
                def step():
                    from paddle_tpu.distributed import resilience
                    resilience.maybe_inject("step_hang")
                    return 1.0
                wd.run(step)
    finally:
        wd.close()
    arts = _artifacts(str(tmp_path), "watchdog_hang")
    assert arts, os.listdir(tmp_path)
    doc = json.load(open(arts[-1]))
    assert doc["metadata"]["reason"] == "watchdog_hang"
    assert doc["traceEvents"], "ring dump is empty"
    # the ring context made it into the artifact
    assert "hang-rid" in json.dumps(doc)


@pytest.mark.timeout(60)
def test_watchdog_nan_storm_dumps_flight_artifact(tmp_path, monkeypatch):
    from paddle_tpu.distributed.resilience import (NanInfStorm,
                                                   StepWatchdog)
    monkeypatch.setenv("PADDLE_TPU_OBS_DIR", str(tmp_path))
    wd = StepWatchdog(deadline=None, nan_limit=2)
    try:
        with pytest.raises(NanInfStorm):
            for _ in range(2):
                wd.run(lambda: float("nan"))
    finally:
        wd.close()
    arts = _artifacts(str(tmp_path), "watchdog_nan_storm")
    assert arts
    doc = json.load(open(arts[-1]))
    assert doc["metadata"]["reason"] == "watchdog_nan_storm"


@pytest.mark.timeout(120)
def test_same_second_jax_profile_captures_get_distinct_dirs(
        tmp_path, monkeypatch):
    """capture(jax_profile=True) stamps its artifact dir at SECOND
    granularity (time.strftime) — two captures inside one second (a
    tier poking every replica, a test loop) must land in distinct
    directories, not interleave their xplane files (ISSUE 14)."""
    import jax
    from paddle_tpu.obs import trace as trace_mod
    monkeypatch.setenv("PADDLE_TPU_OBS_DIR", str(tmp_path))
    # force the collision: both captures see the same wall-clock stamp
    monkeypatch.setattr(trace_mod.time, "strftime",
                        lambda *a, **k: "19990101_000000")
    # stub the device profiler: the unit under test is the DIRECTORY
    # uniquification, and a real jax.profiler session permanently
    # slows every later XLA compile in this process ~1.5x (measured
    # 2026-08-04) — the whole tier-1 tail would pay for it
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    m1 = trace_mod.capture(0, jax_profile=True)["metadata"]
    m2 = trace_mod.capture(0, jax_profile=True)["metadata"]
    assert "jax_profile_dir" in m1, m1
    assert "jax_profile_dir" in m2, m2
    assert m1["jax_profile_dir"] != m2["jax_profile_dir"]
    assert os.path.isdir(m1["jax_profile_dir"])
    assert os.path.isdir(m2["jax_profile_dir"])


# ---------------------------------------------------------------------------
# live 2-replica tier: acceptance criteria
# ---------------------------------------------------------------------------

MODEL = {"kind": "gpt", "vocab_size": 128, "hidden_size": 32,
         "num_layers": 1, "num_heads": 2, "max_seq_len": 64}
ENGINE = {"slots": 2, "max_len": 48, "cache_dtype": "float32",
          "prefill_buckets": [8], "tick_tokens": 2}


@pytest.fixture(scope="module")
def obs_tier(tmp_path_factory):
    from paddle_tpu.inference.router import (ReplicaSpec, Router,
                                             single_device_child_env)
    art_dir = str(tmp_path_factory.mktemp("obs_artifacts"))
    store = str(tmp_path_factory.mktemp("tier_store"))
    prev = os.environ.get("PADDLE_TPU_OBS_DIR")
    os.environ["PADDLE_TPU_OBS_DIR"] = art_dir
    spec = ReplicaSpec(MODEL, ENGINE, warmup=True, drain_s=10.0, seed=0,
                       env=single_device_child_env())
    router = Router(spec, replicas=2, poll_s=0.25, deadline_s=60.0,
                    exec_store_dir=store)
    router.start()
    assert router.wait_ready(2, timeout=240), router.replicas()
    yield router, art_dir
    router.stop()
    if prev is None:
        os.environ.pop("PADDLE_TPU_OBS_DIR", None)
    else:
        os.environ["PADDLE_TPU_OBS_DIR"] = prev


@pytest.mark.timeout(280)
def test_tier_request_id_spans_and_aggregated_metrics(obs_tier):
    router, _ = obs_tier
    base = f"http://{router.host}:{router.port}"
    # several requests so both replicas see traffic
    results = []
    for i in range(4):
        t0 = time.perf_counter()
        code, body = _post(base, "/generate",
                           {"input_ids": [1 + i, 2, 3],
                            "max_new_tokens": 10}, timeout=90)
        e2e_ms = (time.perf_counter() - t0) * 1e3
        assert code == 200, body
        assert body.get("request_id") and body.get("served_by")
        results.append((body["request_id"], body["served_by"], e2e_ms))
    ports = {r["name"]: r["port"] for r in router.replicas()}
    for rid, served, e2e_ms in results:
        code, doc = _post(f"http://{router.host}:{ports[served]}",
                          "/admin/trace?duration_s=0", {}, timeout=30)
        assert code == 200
        # the journal relay (ISSUE 15) serves replicas ATTEMPT ids
        # "<rid>.<seq>" and restores the client rid router-side — the
        # replica ring is addressed per attempt, so resolve the client
        # rid to its attempt spans (exact match kept for the
        # single-shot fallback path)
        by_attempt = {}
        for e in doc["traceEvents"]:
            arid = e.get("args", {}).get("request_id")
            if arid == rid or (arid or "").startswith(rid + "."):
                by_attempt.setdefault(arid, {})[e["name"]] = e
        needed = {"engine.queue_wait", "engine.prefill",
                  "engine.decode"}
        complete = [s for s in by_attempt.values() if needed <= set(s)]
        assert complete, (rid, {a: sorted(s)
                                for a, s in by_attempt.items()})
        # a quiet tier serves one attempt; under retries/hedges the
        # winning (last) complete attempt carries the phase budget
        spans = complete[-1]
        phase_ms = sum(spans[n]["dur"] for n in
                       ("engine.queue_wait", "engine.prefill",
                        "engine.decode")) / 1e3
        # phases sum to the replica-side latency: bounded above by the
        # measured e2e and within HTTP/router overhead of it
        assert 0 < phase_ms <= e2e_ms * 1.05, (phase_ms, e2e_ms)
        assert phase_ms >= 0.3 * e2e_ms, (phase_ms, e2e_ms)
    # the router's own ring has the forward spans under the same ids
    # (attempt-derived "<rid>.<seq>" on the journaled path)
    rids_router = obs.recorder.request_ids(obs.recorder.events())
    for rid, _, _ in results:
        assert any(r == rid or r.startswith(rid + ".")
                   for r in rids_router), (rid, rids_router)
    # aggregated tier metrics: per-replica relabeled series + summed
    # ptpu_tier_* series + the router's own forward histogram
    with urllib.request.urlopen(base + "/metrics", timeout=15) as r:
        samples = obs.metrics.parse_text(r.read().decode())

    def val(name, **labels):
        return sum(v for n, l, v in samples if n == name and all(
            l.get(k) == str(vv) for k, vv in labels.items()))

    assert val("ptpu_tier_engine_ticks_total") > 0
    assert val("ptpu_tier_engine_ttft_ms_count") >= len(results)
    assert val("ptpu_router_forwards_total") >= len(results)
    assert val("ptpu_router_forward_ms_count") >= len(results)
    assert any(n == "ptpu_engine_ticks_total" and "replica" in l
               for n, l, v in samples)
    # healthz per-replica view distinguishes fresh stats from stale
    for rep in router.replicas():
        assert rep["last_scrape_age_s"] is not None
        assert rep["last_scrape_age_s"] < 10


@pytest.mark.timeout(280)
def test_replica_kill_dumps_flight_artifact_with_rids(obs_tier):
    router, art_dir = obs_tier
    base = f"http://{router.host}:{router.port}"
    # a long request keeps a forward span OPEN while we kill; shorter
    # ones populate the ring with recent ids
    done = []

    def long_req():
        done.append(_post(base, "/generate",
                          {"input_ids": [9, 9, 9],
                           "max_new_tokens": 30}, timeout=120))

    t = threading.Thread(target=long_req)
    t.start()
    time.sleep(0.3)
    victim = router.replicas()[0]
    os.kill(victim["pid"], signal.SIGKILL)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and \
            not _artifacts(art_dir, "replica_death"):
        time.sleep(0.2)
    t.join(timeout=120)
    arts = _artifacts(art_dir, "replica_death")
    assert arts, "no replica_death artifact dumped"
    doc = json.load(open(arts[-1]))
    assert doc["metadata"]["reason"] == "replica_death"
    assert victim["name"] in doc["metadata"]["replicas"]
    # the artifact names the request ids that were in flight / recent
    known = set(doc["metadata"]["request_ids_recent"]) | \
        set(doc["metadata"]["request_ids_in_flight"])
    assert known, doc["metadata"]
    # the long in-flight request (or a recent one) is resolvable in it
    assert done == [] or done[0][1].get("request_id") is None or \
        done[0][1]["request_id"] in json.dumps(doc) or known
    # tier recovers (control loop respawns)
    assert router.wait_ready(2, timeout=120), router.replicas()
