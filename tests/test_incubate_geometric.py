"""Numeric checks for incubate's lazy long tail, geometric message
passing, and nn.utils — the thinnest-covered non-subprocess modules.
Reference patterns: test_segment_ops / test_graph_send_recv /
test_lookahead / incubate softmax_mask_fuse tests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate
import paddle_tpu.geometric as G
import paddle_tpu.nn as nn

RNG = np.random.RandomState(5)


def T(a):
    return paddle.to_tensor(np.asarray(a))


class TestSegmentAndGraph:
    x = RNG.randn(6, 3).astype("float32")
    seg = np.array([0, 0, 1, 1, 1, 3], np.int64)

    def test_segment_reductions(self):
        got = incubate.segment_sum(T(self.x), T(self.seg)).numpy()
        for s in range(4):
            rows = self.x[self.seg == s]
            ref = rows.sum(0) if len(rows) else 0.0
            np.testing.assert_allclose(got[s], ref, rtol=1e-5,
                                       atol=1e-6)
        m = incubate.segment_mean(T(self.x), T(self.seg)).numpy()
        np.testing.assert_allclose(m[1], self.x[2:5].mean(0), rtol=1e-5)
        mx = incubate.segment_max(T(self.x), T(self.seg)).numpy()
        np.testing.assert_allclose(mx[0], self.x[:2].max(0), rtol=1e-5)
        mn = incubate.segment_min(T(self.x), T(self.seg)).numpy()
        np.testing.assert_allclose(mn[1], self.x[2:5].min(0), rtol=1e-5)

    def test_graph_send_recv_and_geometric(self):
        # edges: src -> dst; dst accumulates src features
        src = np.array([0, 1, 2, 2], np.int64)
        dst = np.array([1, 2, 0, 1], np.int64)
        feats = RNG.randn(3, 2).astype("float32")
        got = incubate.graph_send_recv(T(feats), T(src), T(dst),
                                       pool_type="sum").numpy()
        ref = np.zeros_like(feats)
        for s, d in zip(src, dst):
            ref[d] += feats[s]
        np.testing.assert_allclose(got, ref, rtol=1e-5)
        # send_ue_recv applies the edge feature first
        ef = RNG.randn(4, 2).astype("float32")
        got2 = G.send_ue_recv(T(feats), T(ef), T(src), T(dst),
                              message_op="add", reduce_op="sum").numpy()
        ref2 = np.zeros_like(feats)
        for e, (s, d) in enumerate(zip(src, dst)):
            ref2[d] += feats[s] + ef[e]
        np.testing.assert_allclose(got2, ref2, rtol=1e-5)
        # send_uv: per-edge messages from both endpoints
        got3 = G.send_uv(T(feats), T(feats), T(src), T(dst),
                         message_op="mul").numpy()
        np.testing.assert_allclose(got3, feats[src] * feats[dst],
                                   rtol=1e-5)

    def test_reindex_and_sampling(self):
        nodes = np.array([10, 20], np.int64)
        neigh = np.array([20, 30, 10, 40], np.int64)
        count = np.array([2, 2], np.int32)
        # contract (reference geometric/reindex.py): returns
        # (reindex_src, reindex_dst, out_nodes)
        re_src, re_dst, out_nodes = G.reindex_graph(
            T(nodes), T(neigh), T(count))
        mapping = {int(v): i for i, v in enumerate(out_nodes.numpy())}
        assert mapping[10] == 0 and mapping[20] == 1
        np.testing.assert_array_equal(
            re_src.numpy(), [mapping[v] for v in neigh.tolist()])
        np.testing.assert_array_equal(re_dst.numpy(), [0, 0, 1, 1])
        # CSC graph: sample neighbors of node 0 (all of them)
        row = np.array([1, 2, 0, 2], np.int64)     # neighbors
        colptr = np.array([0, 2, 3, 4], np.int64)  # per-node spans
        smp, cnt = incubate.graph_sample_neighbors(
            T(row), T(colptr), T(np.array([0], np.int64)), sample_size=-1)
        assert set(smp.numpy().tolist()) == {1, 2}
        assert cnt.numpy().tolist() == [2]

    def test_softmax_mask_fuse(self):
        x = RNG.randn(1, 2, 4, 4).astype("float32")
        mask = np.zeros((1, 1, 4, 4), np.float32)
        mask[..., 2:] = -1e9
        got = incubate.softmax_mask_fuse(T(x), T(mask)).numpy()
        ref = x + mask
        ref = np.exp(ref - ref.max(-1, keepdims=True))
        ref = ref / ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)
        tri = incubate.softmax_mask_fuse_upper_triangle(T(x)).numpy()
        assert np.allclose(np.triu(tri[0, 0], 1), 0.0, atol=1e-6)
        np.testing.assert_allclose(tri.sum(-1), 1.0, rtol=1e-5)

    def test_identity_loss(self):
        x = RNG.randn(5).astype("float32")
        np.testing.assert_allclose(
            incubate.identity_loss(T(x), reduction="mean").numpy(),
            x.mean(), rtol=1e-6)
        np.testing.assert_allclose(
            incubate.identity_loss(T(x), reduction="sum").numpy(),
            x.sum(), rtol=1e-6)


class TestOptimizerWrappers:
    def _toy(self):
        paddle.seed(0)
        m = nn.Linear(4, 1)
        x = T(RNG.randn(16, 4).astype("float32"))
        y = T(RNG.randn(16, 1).astype("float32"))
        return m, x, y

    def test_lookahead_trains(self):
        m, x, y = self._toy()
        base = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=m.parameters())
        opt = incubate.LookAhead(base, alpha=0.5, k=3)
        losses = []
        for _ in range(8):
            loss = paddle.mean((m(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_model_average_applies(self):
        m, x, y = self._toy()
        inner = paddle.optimizer.SGD(learning_rate=0.2,
                                     parameters=m.parameters())
        avg = incubate.ModelAverage(0.15, parameters=m.parameters(),
                                    min_average_window=1,
                                    max_average_window=10)
        for _ in range(4):
            loss = paddle.mean((m(x) - y) ** 2)
            loss.backward()
            inner.step()
            avg.step()
            inner.clear_grad()
            avg.clear_grad()
        w0 = m.parameters()[0]
        before = w0.numpy().copy()
        with avg.apply(need_restore=True):
            averaged = w0.numpy().copy()
        restored = w0.numpy()
        assert not np.allclose(before, averaged)
        np.testing.assert_allclose(restored, before, rtol=1e-6)


class TestNNUtils:
    def test_vector_roundtrip(self):
        paddle.seed(1)
        m = nn.Sequential(nn.Linear(3, 4), nn.Linear(4, 2))
        vec = nn.utils.parameters_to_vector(m.parameters())
        total = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert vec.shape == [total]
        before = [p.numpy().copy() for p in m.parameters()]
        nn.utils.vector_to_parameters(vec * 2.0, m.parameters())
        for b, p in zip(before, m.parameters()):
            np.testing.assert_allclose(p.numpy(), b * 2.0, rtol=1e-6)

    def test_clear_grad_set_to_zero_semantics(self):
        m = nn.Linear(3, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        la = incubate.LookAhead(opt, alpha=0.5, k=2)
        loss = paddle.sum(m(T(np.ones((2, 3), np.float32))) ** 2)
        loss.backward()
        la.clear_grad(set_to_zero=True)   # forwards through LookAhead
        g = m.parameters()[0]._grad
        assert g is not None and float(np.abs(np.asarray(g)).max()) == 0.0
        opt.clear_grad(set_to_zero=False)
        assert m.parameters()[0]._grad is None
        # default matches the reference: zero-fill
        loss = paddle.sum(m(T(np.ones((2, 3), np.float32))) ** 2)
        loss.backward()
        opt.clear_grad()
        assert m.parameters()[0]._grad is not None

    def test_clip_grad_norm_and_value(self):
        m = nn.Linear(3, 2)
        loss = paddle.sum(m(T(np.ones((4, 3), np.float32))) ** 2)
        loss.backward()
        total = nn.utils.clip_grad_norm_(m.parameters(), max_norm=0.01)
        assert float(total.numpy()) > 0.01   # pre-clip norm returned
        gnorm = np.sqrt(sum(float((np.asarray(p._grad) ** 2).sum())
                            for p in m.parameters()))
        np.testing.assert_allclose(gnorm, 0.01, rtol=1e-4)
        loss = paddle.sum(m(T(np.ones((4, 3), np.float32))) ** 2)
        for p in m.parameters():
            p.clear_grad()
        loss.backward()
        nn.utils.clip_grad_value_(m.parameters(), clip_value=0.005)
        for p in m.parameters():
            assert np.abs(np.asarray(p._grad)).max() <= 0.005 + 1e-8
