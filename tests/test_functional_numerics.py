"""Numeric checks for thin-coverage nn.functional modules (common,
activation, loss) against torch (CPU, baked into the image) or numpy
references — the reference's OpTest convention for the functional tail.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(11)


def T(a):
    return paddle.to_tensor(np.asarray(a))


def tt(a):
    return torch.from_numpy(np.asarray(a))


class TestCommon:
    def test_pad_modes(self):
        x = RNG.randn(1, 2, 4, 5).astype("float32")
        for mode in ("constant", "reflect", "replicate", "circular"):
            got = F.pad(T(x), [1, 2, 2, 1], mode=mode, value=3.0).numpy()
            ref = tF.pad(tt(x), (1, 2, 2, 1), mode=mode,
                         value=3.0 if mode == "constant" else 0.0).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-6,
                                       err_msg=mode)

    def test_interpolate_modes(self):
        x = RNG.randn(1, 3, 6, 6).astype("float32")
        for mode, kw in (("nearest", {}), ("bilinear", {}),
                         ("bilinear", {"align_corners": True})):
            got = F.interpolate(T(x), size=[9, 11], mode=mode,
                                **kw).numpy()
            ref = tF.interpolate(tt(x), size=(9, 11), mode=mode,
                                 **kw).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{mode} {kw}")

    def test_unfold_fold_roundtrip(self):
        x = RNG.randn(2, 3, 8, 8).astype("float32")
        u = F.unfold(T(x), kernel_sizes=3, strides=1, paddings=1)
        ref = tF.unfold(tt(x), 3, padding=1).numpy()
        np.testing.assert_allclose(u.numpy(), ref, rtol=1e-6)
        folded = F.fold(u, output_sizes=[8, 8], kernel_sizes=3,
                        strides=1, paddings=1)
        ref_f = tF.fold(tt(ref), (8, 8), 3, padding=1).numpy()
        np.testing.assert_allclose(folded.numpy(), ref_f, rtol=1e-5)

    def test_pixel_shuffle_channel_shuffle(self):
        x = RNG.randn(1, 8, 3, 3).astype("float32")
        np.testing.assert_allclose(
            F.pixel_shuffle(T(x), 2).numpy(),
            tF.pixel_shuffle(tt(x), 2).numpy(), rtol=1e-6)
        y = F.pixel_unshuffle(F.pixel_shuffle(T(x), 2), 2)
        np.testing.assert_allclose(y.numpy(), x, rtol=1e-6)
        cs = F.channel_shuffle(T(x), 4).numpy()
        ref = x.reshape(1, 4, 2, 3, 3).transpose(0, 2, 1, 3, 4).reshape(
            1, 8, 3, 3)
        np.testing.assert_allclose(cs, ref, rtol=1e-6)

    def test_cosine_similarity_pairwise_distance_normalize(self):
        a = RNG.randn(4, 6).astype("float32")
        b = RNG.randn(4, 6).astype("float32")
        np.testing.assert_allclose(
            F.cosine_similarity(T(a), T(b), axis=1).numpy(),
            tF.cosine_similarity(tt(a), tt(b), dim=1).numpy(),
            rtol=1e-5)
        np.testing.assert_allclose(
            F.pairwise_distance(T(a), T(b)).numpy(),
            tF.pairwise_distance(tt(a), tt(b)).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            F.normalize(T(a), p=2, axis=1).numpy(),
            tF.normalize(tt(a), p=2.0, dim=1).numpy(), rtol=1e-5)

    def test_bilinear_label_smooth_one_hot(self):
        x1 = RNG.randn(3, 4).astype("float32")
        x2 = RNG.randn(3, 5).astype("float32")
        w = RNG.randn(6, 4, 5).astype("float32")
        bias = RNG.randn(1, 6).astype("float32")
        got = F.bilinear(T(x1), T(x2), T(w), T(bias)).numpy()
        ref = tF.bilinear(tt(x1), tt(x2), tt(w),
                          tt(bias[0])).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        lab = np.eye(4, dtype="float32")[[0, 2]]
        np.testing.assert_allclose(
            F.label_smooth(T(lab), epsilon=0.2).numpy(),
            lab * 0.8 + 0.2 / 4, rtol=1e-6)
        oh = F.one_hot(T(np.array([1, 3], np.int64)), 5).numpy()
        assert oh.shape == (2, 5) and oh[0, 1] == 1 and oh[1, 3] == 1


class TestActivation:
    x = RNG.randn(3, 7).astype("float32")

    @pytest.mark.parametrize("ours,theirs", [
        (lambda x: F.relu6(x), lambda x: tF.relu6(x)),
        (lambda x: F.gelu(x), lambda x: tF.gelu(x)),
        (lambda x: F.gelu(x, approximate=True),
         lambda x: tF.gelu(x, approximate="tanh")),
        (lambda x: F.silu(x), lambda x: tF.silu(x)),
        (lambda x: F.elu(x, alpha=0.7), lambda x: tF.elu(x, 0.7)),
        (lambda x: F.selu(x), lambda x: tF.selu(x)),
        (lambda x: F.celu(x, alpha=1.3), lambda x: tF.celu(x, 1.3)),
        (lambda x: F.hardswish(x), lambda x: tF.hardswish(x)),
        (lambda x: F.hardtanh(x, -0.5, 0.4),
         lambda x: tF.hardtanh(x, -0.5, 0.4)),
        (lambda x: F.hardshrink(x, 0.3),
         lambda x: tF.hardshrink(x, 0.3)),
        (lambda x: F.softshrink(x, 0.3),
         lambda x: tF.softshrink(x, 0.3)),
        (lambda x: F.tanhshrink(x), lambda x: tF.tanhshrink(x)),
        (lambda x: F.softplus(x, beta=2.0),
         lambda x: tF.softplus(x, beta=2.0)),
        (lambda x: F.softsign(x), lambda x: tF.softsign(x)),
        (lambda x: F.mish(x), lambda x: tF.mish(x)),
        (lambda x: F.log_sigmoid(x), lambda x: tF.logsigmoid(x)),
        (lambda x: F.leaky_relu(x, 0.2),
         lambda x: tF.leaky_relu(x, 0.2)),
    ])
    def test_matches_torch(self, ours, theirs):
        got = ours(T(self.x)).numpy()
        ref = theirs(tt(self.x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_glu_maxout_prelu_thresholded(self):
        x = RNG.randn(2, 6).astype("float32")
        np.testing.assert_allclose(F.glu(T(x), axis=1).numpy(),
                                   tF.glu(tt(x), dim=1).numpy(),
                                   rtol=1e-5)
        x4 = RNG.randn(1, 4, 2, 2).astype("float32")
        mo = F.maxout(T(x4), groups=2, axis=1).numpy()
        ref = x4.reshape(1, 2, 2, 2, 2).max(2)
        np.testing.assert_allclose(mo, ref, rtol=1e-6)
        w = np.array([0.15], np.float32)
        np.testing.assert_allclose(
            F.prelu(T(x4), T(w)).numpy(),
            tF.prelu(tt(x4), tt(w)).numpy(), rtol=1e-5)
        thr = F.thresholded_relu(T(x), threshold=0.3).numpy()
        np.testing.assert_allclose(thr, np.where(x > 0.3, x, 0.0),
                                   rtol=1e-6)


class TestLoss:
    def test_smooth_l1_huber_kl(self):
        a = RNG.randn(4, 3).astype("float32")
        b = RNG.randn(4, 3).astype("float32")
        np.testing.assert_allclose(
            F.smooth_l1_loss(T(a), T(b)).numpy(),
            tF.smooth_l1_loss(tt(a), tt(b)).numpy(), rtol=1e-5)
        logp = tF.log_softmax(tt(a), dim=1).numpy()
        q = tF.softmax(tt(b), dim=1).numpy()
        np.testing.assert_allclose(
            F.kl_div(T(logp), T(q), reduction="batchmean").numpy(),
            tF.kl_div(tt(logp), tt(q), reduction="batchmean").numpy(),
            rtol=1e-5)

    def test_margin_and_cosine_losses(self):
        a = RNG.randn(5, 4).astype("float32")
        b = RNG.randn(5, 4).astype("float32")
        y = np.sign(RNG.randn(5)).astype("float32")
        np.testing.assert_allclose(
            F.cosine_embedding_loss(T(a), T(b), T(y)).numpy(),
            tF.cosine_embedding_loss(tt(a), tt(b), tt(y)).numpy(),
            rtol=1e-5)
        x1 = RNG.randn(5).astype("float32")
        x2 = RNG.randn(5).astype("float32")
        yy = np.ones(5, np.float32)
        np.testing.assert_allclose(
            F.margin_ranking_loss(T(x1), T(x2), T(yy)).numpy(),
            tF.margin_ranking_loss(tt(x1), tt(x2), tt(yy)).numpy(),
            rtol=1e-5)
        anchor = RNG.randn(4, 8).astype("float32")
        pos = RNG.randn(4, 8).astype("float32")
        neg = RNG.randn(4, 8).astype("float32")
        np.testing.assert_allclose(
            F.triplet_margin_loss(T(anchor), T(pos), T(neg)).numpy(),
            tF.triplet_margin_loss(tt(anchor), tt(pos),
                                   tt(neg)).numpy(), rtol=1e-5)

    def test_nll_poisson_soft_margin(self):
        logits = RNG.randn(6, 5).astype("float32")
        labels = RNG.randint(0, 5, 6).astype("int64")
        logp = tF.log_softmax(tt(logits), dim=1).numpy()
        np.testing.assert_allclose(
            F.nll_loss(T(logp), T(labels)).numpy(),
            tF.nll_loss(tt(logp), tt(labels)).numpy(), rtol=1e-5)
        lam = np.abs(RNG.randn(8).astype("float32")) + 0.1
        tgt = RNG.poisson(2.0, 8).astype("float32")
        np.testing.assert_allclose(
            F.poisson_nll_loss(T(lam), T(tgt), log_input=False).numpy(),
            tF.poisson_nll_loss(tt(lam), tt(tgt),
                                log_input=False).numpy(), rtol=1e-4)
        x = RNG.randn(7).astype("float32")
        yy = np.sign(RNG.randn(7)).astype("float32")
        np.testing.assert_allclose(
            F.soft_margin_loss(T(x), T(yy)).numpy(),
            tF.soft_margin_loss(tt(x), tt(yy)).numpy(), rtol=1e-5)
