"""KV-cache generation tests.

Key invariant (OpTest-style numeric check): greedy decode WITH the cache
must produce exactly the tokens that full-recompute greedy decode (no
cache) produces — the cached incremental attention is a pure optimization.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (GPTForCausalLM, LlamaForCausalLM, gpt_tiny,
                               llama_tiny)


def _greedy_nocache(model, ids, n):
    """Reference decode: full forward each step, argmax last logits."""
    cur = ids.copy()
    for _ in range(n):
        logits = model(paddle.to_tensor(cur)).numpy()
        nxt = logits[:, -1, :].argmax(-1).astype(np.int64)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    return cur


@pytest.mark.parametrize("family", [
    "gpt",
    # llama repeats the same cache-vs-recompute contract on the second
    # family; one core in CI — full profile only
    pytest.param("llama", marks=pytest.mark.slow),
])
def test_cached_greedy_matches_full_recompute(family):
    paddle.seed(41)
    if family == "gpt":
        model = GPTForCausalLM(gpt_tiny())
    else:
        model = LlamaForCausalLM(llama_tiny())
    model.eval()
    ids = np.random.RandomState(0).randint(0, 250, (2, 12)).astype("int64")
    n = 8
    want = _greedy_nocache(model, ids, n)
    got = model.generate(ids, max_new_tokens=n, cache_dtype="float32")
    np.testing.assert_array_equal(got, want)


def test_generate_eos_padding():
    paddle.seed(42)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    ids = np.random.RandomState(1).randint(0, 250, (2, 6)).astype("int64")
    # force eos immediately: eos id = whatever greedy emits first for row 0
    first = model.generate(ids, max_new_tokens=1,
                           cache_dtype="float32")[:, -1]
    eos = int(first[0])
    out = model.generate(ids, max_new_tokens=6, eos_token_id=eos,
                         cache_dtype="float32")
    assert out.shape == (2, 12)
    row = out[0, 6:]
    k = np.argmax(row == eos)
    assert (row[k:] == eos).all()   # once finished, padded with eos


def test_sampling_reproducible_and_diverse():
    paddle.seed(43)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    ids = np.random.RandomState(2).randint(0, 250, (1, 8)).astype("int64")
    a = model.generate(ids, max_new_tokens=10, do_sample=True,
                       temperature=1.0, top_k=50, seed=7,
                       cache_dtype="float32")
    b = model.generate(ids, max_new_tokens=10, do_sample=True,
                       temperature=1.0, top_k=50, seed=7,
                       cache_dtype="float32")
    c = model.generate(ids, max_new_tokens=10, do_sample=True,
                       temperature=1.0, top_k=50, seed=8,
                       cache_dtype="float32")
    np.testing.assert_array_equal(a, b)      # same seed -> same tokens
    assert not np.array_equal(a, c)          # different seed -> differs


def test_generate_under_mesh_bf16():
    """Serving under an active tp x dp mesh with bf16 params."""
    import paddle_tpu.distributed as dist
    dist.set_mesh(None)
    try:
        dist.init_mesh({"mp": 4, "dp": 2})
        paddle.seed(3)
        model = LlamaForCausalLM(llama_tiny())
        model.bfloat16()
        model.eval()
        ids = np.random.RandomState(0).randint(
            0, 250, (2, 8)).astype("int64")
        out = model.generate(ids, max_new_tokens=6)
        assert out.shape == (2, 14)
        assert (out[:, :8] == ids).all()
    finally:
        dist.set_mesh(None)


def test_gen_prog_cache_thread_safety(monkeypatch):
    """Regression: the per-model compiled-program LRU is mutated by
    concurrent server threads (get/move_to_end/popitem). Unlocked
    OrderedDict mutation corrupts or KeyErrors under this hammer; the
    lock in models/generation.py must keep every call correct. The
    cache bound is shrunk below the working set so eviction + reinsert
    churn concurrently with lookups."""
    import threading

    paddle.seed(44)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    lens = [4, 5, 6, 7]
    prompts = {p: np.random.RandomState(p).randint(
        0, 250, (1, p)).astype("int64") for p in lens}
    # warm every program first (compiles serialize on jax internals and
    # would hide the race behind compile walls)
    for p in lens:
        model.generate(prompts[p], max_new_tokens=1,
                       cache_dtype="float32")
    # shrink the LRU below the working set: every miss now evicts and
    # reinserts while other threads move_to_end — the reported race
    monkeypatch.setenv("PADDLE_TPU_GEN_PROG_CACHE", "3")

    errors = []

    def hammer(seed):
        rng = np.random.RandomState(seed)
        try:
            for _ in range(12):
                p = lens[rng.randint(len(lens))]
                out = model.generate(prompts[p], max_new_tokens=1,
                                     cache_dtype="float32")
                assert out.shape == (1, p + 1)
                assert (out[:, :p] == prompts[p]).all()
        except Exception as e:   # noqa: BLE001 — surface to main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_gqa_cache_shape():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    caches = model.new_cache(2, 16, "float32")
    assert len(caches) == cfg.num_layers
    k, v = caches[0]
    assert k.shape == (2, 16, cfg.kv_heads, cfg.hidden_size // cfg.num_heads)
