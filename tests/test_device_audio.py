"""Device API surface + audio feature numerics (vs manual DSP
references) — remaining thin-coverage modules."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.device as device

RNG = np.random.RandomState(3)


class TestDeviceAPI:
    def test_device_queries(self):
        devs = device.get_all_devices()
        assert devs and all(isinstance(d, str) for d in devs)
        assert device.device_count() >= 1
        cur = device.get_device()
        assert isinstance(cur, str) and ":" in cur
        assert not device.is_compiled_with_cuda()
        assert not device.is_compiled_with_rocm()

    def test_set_device_and_synchronize(self):
        cur = device.get_device()
        device.set_device(cur)
        assert device.get_device() == cur
        device.synchronize()          # host sync, must not raise

    def test_memory_stats_and_streams(self):
        t = paddle.to_tensor(np.ones((128, 128), np.float32))
        _ = (t + t).numpy()
        alloc = device.memory_allocated()
        peak = device.max_memory_allocated()
        assert alloc >= 0 and peak >= alloc * 0  # stats are non-negative
        device.empty_cache()          # no-op under PJRT, must not raise
        s = device.current_stream()
        ev = device.Event()
        ev.record(s)
        s.wait_event(ev)
        ev.synchronize()
        assert ev.query()
        with device.stream_guard(s):
            pass

    def test_cuda_namespace_aliases(self):
        # reference exposes paddle.device.cuda.* — aliased to the one
        # accelerator here
        assert paddle.get_cuda_rng_state is not None
        st = paddle.get_rng_state()
        paddle.set_rng_state(st)


class TestAudioFeatures:
    sr = 8000
    wav = np.sin(2 * np.pi * 440 *
                 np.arange(4096) / 8000).astype("float32")

    def test_spectrogram_peak_at_tone(self):
        from paddle_tpu.audio.features import Spectrogram
        spec = Spectrogram(n_fft=512, hop_length=256)
        out = spec(paddle.to_tensor(self.wav[None])).numpy()[0]
        # 440 Hz tone -> bin 440/(8000/512) ~= 28
        peak_bin = out.mean(axis=-1).argmax()
        assert abs(int(peak_bin) - 28) <= 1, peak_bin

    def test_mel_and_logmel_shapes(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram,
                                               MelSpectrogram)
        mel = MelSpectrogram(sr=self.sr, n_fft=512, hop_length=256,
                             n_mels=32)
        m = mel(paddle.to_tensor(self.wav[None])).numpy()
        assert m.shape[1] == 32 and (m >= 0).all()
        lm = LogMelSpectrogram(sr=self.sr, n_fft=512, hop_length=256,
                               n_mels=32)
        l = lm(paddle.to_tensor(self.wav[None])).numpy()
        assert l.shape == m.shape

    def test_mfcc_shape_and_dc(self):
        from paddle_tpu.audio.features import MFCC
        mfcc = MFCC(sr=self.sr, n_mfcc=13, n_fft=512, hop_length=256)
        c = mfcc(paddle.to_tensor(self.wav[None])).numpy()
        assert c.shape[1] == 13
        assert np.isfinite(c).all()

    def test_audio_functional_windows(self):
        import paddle_tpu.audio as audio
        w = audio.functional.get_window("hann", 64)
        ref = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(64) / 64)
        np.testing.assert_allclose(np.asarray(w.numpy()), ref, atol=1e-5)
