"""Int8 inference lowering: PTQ calibration -> true int8-dot programs.

Reference role: the TRT int8 path (inference/tensorrt/convert/,
tensorrt_subgraph_pass.cc) + static PTQ
(post_training_quantization.py). Validates (VERDICT r3 item 9):
 * convert_to_int8 replaces calibrated Linears with int8-dot layers,
 * int8 outputs track the fake-quant reference on a BERT encoder,
 * the saved artifact contains int8 dots and serves through
   Config.enable_int8() -> create_predictor,
 * enable_int8 on an f32 artifact refuses loudly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.models import BertForSequenceClassification, bert_tiny
from paddle_tpu.quantization import (PTQ, Int8Linear, QuantConfig,
                                     convert_to_int8)
from paddle_tpu.quantization.observers import AbsmaxObserver
from paddle_tpu.static import InputSpec


def _ptq_pipeline(model, calib_batches):
    q = QuantConfig(activation=AbsmaxObserver(), weight=None)
    ptq = PTQ(q)
    observed = ptq.quantize(model)
    for b in calib_batches:
        observed(*b)
    fakeq, scales = ptq.convert(observed)
    int8 = convert_to_int8(fakeq)
    return fakeq, int8, scales


class TestInt8Linear:
    def test_mlp_tracks_fake_quant(self):
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 8))
        model.eval()
        calib = [(paddle.to_tensor(
            rng.randn(4, 16).astype(np.float32)),) for _ in range(4)]
        fakeq, int8, scales = _ptq_pipeline(model, calib)
        assert any(isinstance(l, Int8Linear) for l in int8.sublayers())
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        ref = fakeq(x).numpy()
        got = int8(x).numpy()
        # int8 dot vs f32 fake-quant: same quant grid on activations,
        # per-channel (finer) grid on weights; small residual expected
        delta = np.abs(ref - got).max()
        assert delta < 0.05 * (np.abs(ref).max() + 1e-6), delta

    def test_requires_calibration(self):
        with pytest.raises(ValueError, match="PTQ"):
            convert_to_int8(nn.Sequential(nn.Linear(4, 4)))


class TestInt8Bert:
    def _bert_pipeline(self):
        paddle.seed(1)
        rng = np.random.RandomState(1)
        model = BertForSequenceClassification(bert_tiny(), num_classes=4)
        model.eval()
        calib = [(paddle.to_tensor(
            rng.randint(0, model.bert.cfg.vocab_size, (2, 32))
            .astype(np.int64)),) for _ in range(3)]
        fakeq, int8, _ = _ptq_pipeline(model, calib)
        ids = paddle.to_tensor(
            rng.randint(0, model.bert.cfg.vocab_size, (4, 32))
            .astype(np.int64))
        return fakeq, int8, ids

    def test_encoder_accuracy_delta(self):
        fakeq, int8, ids = self._bert_pipeline()
        ref = fakeq(ids).numpy()
        got = int8(ids).numpy()
        # record the delta the way the reference PTQ docs do: quantized
        # logits must preserve ranking on the classification head
        assert np.argmax(ref, -1).tolist() == np.argmax(got, -1).tolist()
        rel = np.abs(ref - got).max() / (np.abs(ref).max() + 1e-6)
        assert rel < 0.15, f"int8 BERT diverged from fake-quant: {rel:.3f}"

    def test_serves_through_predictor(self, tmp_path):
        fakeq, int8, ids = self._bert_pipeline()
        prefix = str(tmp_path / "bert")
        spec = [InputSpec([4, 32], "int64", "ids")]
        paddle.jit.save(int8, prefix + "_int8", input_spec=spec)

        cfg = Config(prefix)
        cfg.enable_int8()
        pred = create_predictor(cfg)
        [out] = pred.run([ids.numpy()])
        np.testing.assert_allclose(out, int8(ids).numpy(),
                                   rtol=2e-3, atol=2e-3)

    def test_enable_int8_refuses_f32_artifact(self, tmp_path):
        paddle.seed(2)
        model = nn.Sequential(nn.Linear(8, 8))
        model.eval()
        prefix = str(tmp_path / "f32model")
        paddle.jit.save(model, prefix,
                        input_spec=[InputSpec([2, 8], "float32", "x")])
        cfg = Config(prefix)
        cfg.enable_int8()
        with pytest.raises(RuntimeError, match="convert_to_int8"):
            create_predictor(cfg)

    def test_artifact_contains_int8_dots(self, tmp_path):
        import jax
        fakeq, int8, ids = self._bert_pipeline()
        prefix = str(tmp_path / "bert_int8")
        paddle.jit.save(int8, prefix,
                        input_spec=[InputSpec([4, 32], "int64", "ids")])
        with open(prefix + ".pdmodel", "rb") as f:
            exported = jax.export.deserialize(f.read())
        mlir = exported.mlir_module()
        assert "i8" in mlir and "dot_general" in mlir
