"""Model zoo forward-shape tests (small inputs) + the LeNet-on-MNIST
Model.fit e2e smoke (the reference's test/book/test_recognize_digits.py
pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(n=1, c=3, hw=64):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(rng.randn(n, c, hw, hw).astype(np.float32))


class TestModelZoo:
    # ONE cpu core in CI: the zoo's big-CNN compiles dominate the fast
    # profile, so two light archs stay default and the rest run under
    # --runslow (tools/ci.py --full)
    @pytest.mark.parametrize("ctor,kw,hw", [
        pytest.param(M.alexnet, {}, 224, marks=pytest.mark.slow),
        pytest.param(M.vgg11, {}, 64, marks=pytest.mark.slow),
        pytest.param(M.squeezenet1_0, {}, 64, marks=pytest.mark.slow),
        (M.squeezenet1_1, {}, 64),
        (M.mobilenet_v1, {"scale": 0.25}, 64),
        pytest.param(M.mobilenet_v2, {"scale": 0.25}, 64,
                     marks=pytest.mark.slow),
        pytest.param(M.mobilenet_v3_small, {"scale": 1.0}, 64,
                     marks=pytest.mark.slow),
        pytest.param(M.mobilenet_v3_large, {"scale": 1.0}, 64,
                     marks=pytest.mark.slow),
        pytest.param(M.shufflenet_v2_x0_25, {}, 64,
                     marks=pytest.mark.slow),
        pytest.param(M.shufflenet_v2_swish, {}, 64,
                     marks=pytest.mark.slow),
        pytest.param(M.densenet121, {}, 64, marks=pytest.mark.slow),
        pytest.param(M.resnext50_32x4d, {}, 64, marks=pytest.mark.slow),
        pytest.param(M.wide_resnet101_2, {}, 64, marks=pytest.mark.slow),
    ])
    def test_forward_shape(self, ctor, kw, hw):
        model = ctor(num_classes=7, **kw)
        model.eval()
        out = model(_img(2, 3, hw))
        assert out.shape == [2, 7]

    @pytest.mark.slow
    def test_vgg_batch_norm(self):
        model = M.vgg11(batch_norm=True, num_classes=5)
        model.eval()
        assert model(_img(1, 3, 64)).shape == [1, 5]

    @pytest.mark.slow
    def test_googlenet_aux_heads(self):
        model = M.googlenet(num_classes=6)
        model.eval()
        out, aux1, aux2 = model(_img(1, 3, 64))
        assert out.shape == [1, 6]
        assert aux1.shape == [1, 6] and aux2.shape == [1, 6]

    @pytest.mark.slow
    def test_inception_v3(self):
        model = M.inception_v3(num_classes=4)
        model.eval()
        out = model(_img(1, 3, 299))
        assert out.shape == [1, 4]

    def test_lenet_shape(self):
        model = M.LeNet()
        out = model(paddle.to_tensor(
            np.random.RandomState(1).randn(3, 1, 28, 28).astype(
                np.float32)))
        assert out.shape == [3, 10]

    @pytest.mark.slow
    def test_with_pool_false_num_classes_0(self):
        model = M.mobilenet_v2(scale=0.25, num_classes=0, with_pool=False)
        model.eval()
        out = model(_img(1, 3, 64))
        assert len(out.shape) == 4  # raw feature map

    def test_pretrained_raises(self):
        with pytest.raises(AssertionError):
            M.alexnet(pretrained=True)

    def test_conv_norm_activation_disable(self):
        from paddle_tpu.vision.ops import ConvNormActivation
        import paddle_tpu.nn as nn
        blk = ConvNormActivation(3, 4, norm_layer=None,
                                 activation_layer=None)
        subs = list(blk.children())
        assert len(subs) == 1  # conv only
        assert subs[0].bias is not None  # no norm → biased conv
        blk2 = ConvNormActivation(3, 4)
        kinds = [type(m).__name__.lower() for m in blk2.children()]
        assert kinds == ["conv2d", "batchnorm2d", "relu"]


class TestLeNetBook:
    """Reference book-test pattern: train a few iters, assert the loss
    drops and accuracy beats chance (test/book/test_recognize_digits.py)."""

    def test_lenet_mnist_fit(self, tmp_path):
        import gzip
        import struct

        from paddle_tpu.hapi import Model
        from paddle_tpu.metric import Accuracy
        from paddle_tpu.vision.datasets import MNIST

        # synthetic MNIST whose label is recoverable from the image: digit
        # k gets a bright kxk top-left block plus noise
        rng = np.random.RandomState(0)
        n = 256
        lbls = rng.randint(0, 10, (n,)).astype(np.uint8)
        imgs = (rng.rand(n, 28, 28) * 40).astype(np.uint8)
        for i, k in enumerate(lbls):
            imgs[i, :k + 2, :k + 2] = 250
        ip = str(tmp_path / "img.gz")
        lp = str(tmp_path / "lbl.gz")
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(lbls.tobytes())

        def normalize(x):
            return ((x / 255.0) - 0.5).astype(np.float32).transpose(2, 0, 1)

        ds = MNIST(image_path=ip, label_path=lp, transform=normalize)
        paddle.seed(1)
        net = M.LeNet()
        model = Model(net)
        opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                    parameters=net.parameters())
        model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy())
        h0 = model.evaluate(ds, batch_size=64, verbose=0)
        model.fit(ds, epochs=4, batch_size=64, verbose=0)
        h1 = model.evaluate(ds, batch_size=64, verbose=0)
        assert h1["loss"] < h0["loss"]
        assert h1["acc"] > 0.3  # well above 0.1 chance
