"""Measurement-suite mechanics (tools/_suite_lib.sh) — the skip/landed
protocol the hardware recovery loop depends on.

Three load-bearing properties, each of which has silently broken once:
  1. a landed record is SKIPPED on re-run (never re-spent, never
     truncated — round-4 suites used truncating redirects);
  2. a failed/error record is retried on the next fire;
  3. output goes through .part-then-rename, so a crash mid-write
     leaves no half-written file that looks landed.
No jax, no tunnel — pure harness logic against a temp results dir.
"""
import json
import os
import subprocess

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _mini_suite(results_dir, body):
    script = f"""#!/bin/bash
set -u
R={results_dir}
mkdir -p "$R"
SUITE_LOG_TAG=minisuite
. {TOOLS}/_suite_lib.sh
{body}
"""
    path = os.path.join(results_dir, "mini.sh")
    with open(path, "w") as f:
        f.write(script)
    return subprocess.run(["bash", path], capture_output=True, text=True,
                          timeout=60)


def test_landed_record_skipped_and_never_truncated(tmp_path):
    d = str(tmp_path)
    body = 'run ok ok.json echo \'{"metric": "m", "value": 42}\'\n'
    r = _mini_suite(d, body)
    assert r.returncode == 0, r.stderr
    out = os.path.join(d, "ok.json")
    assert json.load(open(out))["value"] == 42
    mtime = os.path.getmtime(out)

    # re-fire: must skip (log says so), not rewrite
    r = _mini_suite(d, body)
    assert os.path.getmtime(out) == mtime
    log = open(os.path.join(d, "minisuite.log")).read()
    assert "already have result, skip" in log


def test_error_record_is_retried(tmp_path):
    d = str(tmp_path)
    flag = os.path.join(d, "second_try")
    # first run emits an error record; once the flag exists it succeeds
    body = (f'run flaky flaky.json sh -c '
            f'\'if [ -f {flag} ]; then echo "{{\\"value\\": 7}}"; '
            f'else echo "{{\\"error\\": \\"wedged\\"}}"; exit 1; fi\'\n')
    _mini_suite(d, body)
    assert "error" in json.load(open(os.path.join(d, "flaky.json")))
    open(flag, "w").close()
    _mini_suite(d, body)             # retried, not skipped
    assert json.load(open(os.path.join(d, "flaky.json")))["value"] == 7


def test_crash_mid_write_leaves_no_landed_looking_file(tmp_path):
    d = str(tmp_path)
    # tool writes half a JSON object then dies
    body = ('run crash crash.json sh -c '
            '\'printf "{\\"value\\": 4"; kill -9 $$\'\n')
    _mini_suite(d, body)
    # the .part was renamed over by run() after the crash, but the
    # half-written payload must NOT satisfy the landed predicate
    r = subprocess.run(["python", os.path.join(TOOLS, "_have_result.py"),
                        os.path.join(d, "crash.json")],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert not os.path.exists(os.path.join(d, "crash.json.part"))


def test_txt_artifact_requires_terminal_json_record(tmp_path):
    """A .txt artifact lands only when its LAST non-empty line is a
    good JSON record — raw size must not qualify (a mid-print kill
    leaves >100 bytes of prose but no terminal record)."""
    def rc(path):
        return subprocess.run(
            ["python", os.path.join(TOOLS, "_have_result.py"), path],
            capture_output=True).returncode

    filler = "== top ops ==\n" + ("  fusion.1   12.3 ms\n" * 20)

    good = os.path.join(str(tmp_path), "good.txt")
    with open(good, "w") as f:
        f.write(filler + json.dumps({"metric": "gpt_step_profile",
                                     "ms_per_step_wall": 1.0}) + "\n")
    assert rc(good) == 0

    # mid-print kill: plenty of bytes, record truncated mid-JSON
    cut = os.path.join(str(tmp_path), "cut.txt")
    with open(cut, "w") as f:
        f.write(filler + '{"metric": "gpt_step_profile", "ms_per')
    assert os.path.getsize(cut) > 100 and rc(cut) == 1

    # error-record tail (probe's backend_unavailable line) is not landed
    err = os.path.join(str(tmp_path), "err.txt")
    with open(err, "w") as f:
        f.write(filler + json.dumps({"error": "backend_unavailable"})
                + "\n")
    assert rc(err) == 1

    # no terminal record at all
    prose = os.path.join(str(tmp_path), "prose.txt")
    with open(prose, "w") as f:
        f.write(filler)
    assert os.path.getsize(prose) > 100 and rc(prose) == 1


def test_watcher_landed_list_tracks_suite_outputs():
    """tpu_watch2.sh exits only when its landed-file list is all good;
    that list must contain exactly tpu_suite2.sh's step outputs, or the
    loop either exits early (missing entry) or never exits (stale
    entry for a step the suite no longer runs)."""
    import re
    with open(os.path.join(TOOLS, "tpu_suite2.sh")) as f:
        suite_outs = set(re.findall(r"^run\s+\S+\s+(\S+)", f.read(),
                                    re.M))
    with open(os.path.join(TOOLS, "tpu_watch2.sh")) as f:
        src = f.read()
    # anchor to the _have_result.py invocation block so comments
    # elsewhere can't leak in, and accept any non-slash filename chars
    # (the suite-side \S+ accepts hyphens etc. — classes must agree)
    block = re.search(r"_have_result\.py(.*?)(?:>>|\n\s*then)", src,
                      re.S).group(1)
    watch_outs = set(re.findall(r"tpu_results/([^/\s\\]+)", block))
    assert suite_outs == watch_outs, (
        f"suite-only: {suite_outs - watch_outs}; "
        f"watcher-only: {watch_outs - suite_outs}")
