"""nn layer long-tail closure + sparse.nn + incubate ASP (task: close
the SURVEY §2.8 nn/sparse/incubate gaps)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestMaxUnpool:
    def test_pool_mask_roundtrip_2d(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
        out, mask = F.max_pool2d(x, 2, 2, return_mask=True)
        assert out.shape == [2, 3, 4, 4]
        assert mask.shape == [2, 3, 4, 4]
        # unpool restores max values at their argmax locations
        up = F.max_unpool2d(out, mask, 2, 2)
        assert up.shape == [2, 3, 8, 8]
        u = up.numpy()
        np.testing.assert_allclose(np.sort(u[u != 0]),
                                   np.sort(out.numpy().ravel()))
        # layer form
        up2 = nn.MaxUnPool2D(2, 2)(out, mask)
        np.testing.assert_allclose(up2.numpy(), u)

    def test_mask_matches_argmax(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 1, 2] = 5.0  # max of the top-right 2x2 window
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                 return_mask=True)
        assert int(mask.numpy()[0, 0, 0, 1]) == 1 * 4 + 2
        assert float(out.numpy()[0, 0, 0, 1]) == 5.0

    def test_unpool_1d_3d(self):
        rng = np.random.RandomState(1)
        x1 = paddle.to_tensor(rng.randn(1, 2, 8).astype(np.float32))
        o, m = F.max_pool1d(x1, 2, 2, return_mask=True)
        assert F.max_unpool1d(o, m, 2, 2).shape == [1, 2, 8]
        x3 = paddle.to_tensor(rng.randn(1, 2, 4, 4, 4).astype(np.float32))
        o, m = F.max_pool3d(x3, 2, 2, return_mask=True)
        assert F.max_unpool3d(o, m, 2, 2).shape == [1, 2, 4, 4, 4]

    def test_grad_flows_through_unpool(self):
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(1, 1, 4, 4).astype(np.float32))
        x.stop_gradient = False
        o, m = F.max_pool2d(x, 2, 2, return_mask=True)
        F.max_unpool2d(o, m, 2, 2).sum().backward()
        g = x.grad.numpy()
        assert (g.sum() == 4.0) and ((g == 0) | (g == 1)).all()


class TestNewLosses:
    def test_multi_margin(self):
        x = paddle.to_tensor(np.array([[0.1, 0.9, 0.3]], np.float32))
        y = paddle.to_tensor(np.array([1], np.int64))
        loss = F.multi_margin_loss(x, y, reduction="none").numpy()
        want = (max(0, 1 - 0.9 + 0.1) + max(0, 1 - 0.9 + 0.3)) / 3
        np.testing.assert_allclose(loss[0], want, rtol=1e-5)
        l2 = nn.MultiMarginLoss()(x, y)
        np.testing.assert_allclose(float(l2), want, rtol=1e-5)

    def test_pairwise_distance(self):
        a = paddle.to_tensor(np.array([[3.0, 0.0]], np.float32))
        b = paddle.to_tensor(np.array([[0.0, 4.0]], np.float32))
        d = nn.PairwiseDistance()(a, b)
        np.testing.assert_allclose(d.numpy(), [5.0], rtol=1e-4)

    def test_triplet_with_distance(self):
        rng = np.random.RandomState(3)
        a = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        pos = paddle.to_tensor(
            (a.numpy() + 0.01 * rng.randn(4, 8)).astype(np.float32))
        neg = paddle.to_tensor(rng.randn(4, 8).astype(np.float32) * 5)
        loss = nn.TripletMarginWithDistanceLoss(margin=0.5)(a, pos, neg)
        assert float(loss) >= 0

    def test_softmax2d(self):
        rng = np.random.RandomState(4)
        x = paddle.to_tensor(rng.randn(2, 3, 4, 4).astype(np.float32))
        out = nn.Softmax2D()(x).numpy()
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    def test_hsigmoid(self):
        paddle.seed(0)
        rng = np.random.RandomState(5)
        layer = nn.HSigmoidLoss(feature_size=8, num_classes=6)
        x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 2, 4, 5], np.int64))
        loss = layer(x, y)
        assert loss.shape == [4, 1]
        assert np.isfinite(loss.numpy()).all()
        loss.sum().backward()
        assert layer.weight.grad is not None

    def test_rnnt_loss_trivial(self):
        # single-label, T=2: brute-force the two alignments
        V = 3
        logits = np.random.RandomState(6).randn(1, 2, 2, V).astype(
            np.float32)
        label = np.array([[2]], np.int64)
        loss = F.rnnt_loss(paddle.to_tensor(logits),
                           paddle.to_tensor(label),
                           paddle.to_tensor(np.array([2], np.int64)),
                           paddle.to_tensor(np.array([1], np.int64)),
                           reduction="none")
        import scipy.special as sp
        lp = sp.log_softmax(logits[0], -1)
        # alignments: (emit@t0,blank,blank) path structure over (T=2,U=2)
        a1 = lp[0, 0, 2] + lp[0, 1, 0] + lp[1, 1, 0]   # emit then blanks
        a2 = lp[0, 0, 0] + lp[1, 0, 2] + lp[1, 1, 0]   # blank emit blank
        want = -np.logaddexp(a1, a2)
        np.testing.assert_allclose(loss.numpy(), [want], rtol=1e-4)

    def test_rnnt_layer_batch(self):
        rng = np.random.RandomState(7)
        B, T, U, V = 3, 5, 4, 6
        logits = paddle.to_tensor(rng.randn(B, T, U, V).astype(np.float32))
        labels = paddle.to_tensor(
            rng.randint(1, V, (B, U - 1)).astype(np.int64))
        tl = paddle.to_tensor(np.array([5, 4, 3], np.int64))
        ul = paddle.to_tensor(np.array([3, 2, 1], np.int64))
        loss = nn.RNNTLoss(reduction="none")(logits, labels, tl, ul)
        assert loss.shape == [B]
        assert (loss.numpy() > 0).all() and np.isfinite(loss.numpy()).all()


class TestSparseNN:
    def _point_cloud(self, n=12, spatial=(6, 6, 6), c=4, seed=0):
        rng = np.random.RandomState(seed)
        # unique coordinates
        coords = set()
        while len(coords) < n:
            coords.add((0, rng.randint(spatial[0]),
                        rng.randint(spatial[1]), rng.randint(spatial[2])))
        coords = np.asarray(sorted(coords), np.int64)
        vals = rng.randn(n, c).astype(np.float32)
        import paddle_tpu.sparse as sparse
        st = sparse.sparse_coo_tensor(
            paddle.to_tensor(coords.T), paddle.to_tensor(vals),
            (1, *spatial, c))
        return st, coords, vals

    def test_subm_conv_identity_kernel(self):
        import paddle_tpu.sparse.nn as snn
        st, coords, vals = self._point_cloud()
        conv = snn.SubmConv3D(4, 4, 3, padding=1, bias_attr=False)
        # identity kernel: center tap = I, rest 0
        w = np.zeros((3, 3, 3, 4, 4), np.float32)
        w[1, 1, 1] = np.eye(4)
        conv.weight.value = paddle.to_tensor(w).value
        out = conv(st)
        assert out.nnz() == st.nnz()
        np.testing.assert_allclose(out.values().numpy(), vals, rtol=1e-5)

    def test_subm_conv_matches_dense(self):
        import paddle_tpu.sparse.nn as snn
        st, coords, vals = self._point_cloud()
        conv = snn.SubmConv3D(4, 6, 3, padding=1)
        out = conv(st)
        # dense reference: conv3d then evaluate at input coords only
        dense = np.zeros((1, 6, 6, 6, 4), np.float32)
        for co, v in zip(coords, vals):
            dense[0, co[1], co[2], co[3]] = v
        w = conv.weight.numpy()
        b = conv.bias.numpy()
        padded = np.pad(dense, ((0, 0), (1, 1), (1, 1), (1, 1), (0, 0)))
        got = out.values().numpy()
        for row, co in enumerate(out.value.indices):
            z, y, x = int(co[1]), int(co[2]), int(co[3])
            patch = padded[0, z:z + 3, y:y + 3, x:x + 3]     # (3,3,3,C)
            # submanifold: only taps landing on occupied inputs count
            occ = (np.abs(patch).sum(-1, keepdims=True) > 0)
            want = np.einsum("zyxc,zyxco->o", patch * occ, w) + b
            np.testing.assert_allclose(got[row], want, rtol=1e-4,
                                       atol=1e-5)

    def test_strided_conv_downsamples(self):
        import paddle_tpu.sparse.nn as snn
        st, coords, vals = self._point_cloud()
        conv = snn.Conv3D(4, 5, kernel_size=2, stride=2)
        out = conv(st)
        assert out.shape == [1, 3, 3, 3, 5]
        assert out.nnz() >= 1

    def test_batchnorm_relu(self):
        import paddle_tpu.sparse.nn as snn
        st, _, vals = self._point_cloud(seed=1)
        bn = snn.BatchNorm(4)
        out = bn(st)
        v = out.values().numpy()
        np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)
        r = snn.ReLU()(out)
        assert (r.values().numpy() >= 0).all()


class TestASP:
    def test_mask_1d(self):
        from paddle_tpu.incubate import asp
        rng = np.random.RandomState(0)
        w = rng.randn(8, 16).astype(np.float32)
        mask = asp.get_mask_1d(w, 2, 4)
        assert asp.check_mask_1d(w * mask, 2, 4)
        assert abs(asp.calculate_density(w * mask) - 0.5) < 1e-6
        # kept entries are the two largest per group
        g = np.abs(w.reshape(8, 4, 4))
        kept = (mask.reshape(8, 4, 4) > 0)
        for i in range(8):
            for j in range(4):
                top2 = set(np.argsort(-g[i, j])[:2])
                assert set(np.nonzero(kept[i, j])[0]) == top2

    def test_mask_2d(self):
        from paddle_tpu.incubate import asp
        rng = np.random.RandomState(1)
        w = rng.randn(8, 8).astype(np.float32)
        mask = asp.get_mask_2d_greedy(w, 2, 4)
        assert asp.check_mask_2d(w * mask, 2, 4)

    def test_prune_and_decorate(self):
        from paddle_tpu.incubate import asp
        paddle.seed(2)
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                              nn.Linear(16, 4))
        masks = asp.prune_model(model, mask_algo="mask_1d")
        assert len(masks) == 2
        for name, p in model.named_parameters():
            if name in masks:
                assert asp.check_sparsity(p, asp.CheckMethod.CHECK_1D)
        opt = asp.decorate(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=model.parameters()))
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype(np.int64))
        lf = nn.CrossEntropyLoss()
        for _ in range(3):
            loss = lf(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        # sparsity preserved through training
        for name, p in model.named_parameters():
            if name in masks:
                assert asp.check_sparsity(p, asp.CheckMethod.CHECK_1D)

    def test_autotune_config(self):
        from paddle_tpu.incubate import autotune
        autotune.set_config({"kernel": {"enable": True},
                             "dataloader": {"enable": True}})
        cfg = autotune.get_config()
        assert cfg["dataloader"]["enable"]


class TestReparameterizations:
    """nn.utils weight_norm / remove_weight_norm / spectral_norm
    (reference: nn/utils/weight_norm_hook.py, spectral_norm_hook.py)."""

    def test_weight_norm_semantics_and_grads(self):
        import paddle_tpu.nn.utils as U
        paddle.seed(0)
        lin = nn.Linear(4, 6)
        w0 = lin.weight.numpy().copy()
        U.weight_norm(lin, dim=0)
        names = dict(lin.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4)
                             .astype(np.float32))
        out = lin(x)
        # initial reparam is exact: g*v/||v|| == original w
        np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                                   atol=1e-6)
        out.sum().backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None
        # remove folds back to a single trainable weight
        U.remove_weight_norm(lin)
        names = dict(lin.named_parameters())
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                                   atol=1e-6)

    def test_spectral_norm_divides_by_sigma(self):
        import paddle_tpu.nn.utils as U
        paddle.seed(1)
        lin = nn.Linear(8, 8)
        w0 = lin.weight.numpy().copy()
        U.spectral_norm(lin, n_power_iterations=20)
        x = paddle.to_tensor(np.eye(8, dtype=np.float32))
        lin(x)  # hook recomputes
        sigma = np.linalg.svd(w0, compute_uv=False)[0]
        np.testing.assert_allclose(lin.weight.numpy(), w0 / sigma,
                                   rtol=1e-3, atol=1e-4)
        out = lin(x)
        out.sum().backward()
        assert lin.weight_orig.grad is not None


class TestInitializerExtras:
    def test_bilinear_kernel(self):
        from paddle_tpu.nn.initializer import Bilinear
        w = np.asarray(Bilinear()([2, 2, 4, 4]))
        assert w.shape == (2, 2, 4, 4)
        # separable triangle kernel, symmetric, peak at the center block
        k = w[0, 0]
        np.testing.assert_allclose(k, k[::-1, ::-1])
        assert k[1:3, 1:3].min() == k.max() or k.max() == k[1, 1]
        # deconv with this kernel interpolates a constant exactly
        conv = nn.Conv2DTranspose(1, 1, 4, stride=2, padding=1,
                                  weight_attr=Bilinear(), bias_attr=False)
        x = paddle.to_tensor(np.ones((1, 1, 3, 3), np.float32))
        y = conv(x).numpy()
        np.testing.assert_allclose(y[0, 0, 1:-1, 1:-1], 1.0, rtol=1e-5)

    def test_set_global_initializer(self):
        from paddle_tpu.nn import initializer as I
        I.set_global_initializer(I.Constant(0.25), I.Constant(-1.0))
        try:
            lin = nn.Linear(3, 3)
            np.testing.assert_allclose(lin.weight.numpy(), 0.25)
            np.testing.assert_allclose(lin.bias.numpy(), -1.0)
            # explicit ParamAttr initializer still wins
            lin2 = nn.Linear(3, 3, weight_attr=I.Constant(2.0))
            np.testing.assert_allclose(lin2.weight.numpy(), 2.0)
        finally:
            I.set_global_initializer(None, None)
        lin3 = nn.Linear(3, 3)
        assert not np.allclose(lin3.weight.numpy(), 0.25)
