"""Vision detection ops: numeric checks against naive numpy references
(the OpTest pattern, SURVEY.md §4) + dataset file-format parsers."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets as D
from paddle_tpu.vision import ops as V


def _iou(a, b):
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / max(ua, 1e-10)


def _naive_nms(boxes, thresh):
    keep = []
    for i in range(len(boxes)):
        if all(_iou(boxes[i], boxes[j]) <= thresh for j in keep):
            keep.append(i)
    return keep


class TestNMS:
    def test_plain_matches_naive(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(64, 2).astype(np.float32)
        wh = rng.rand(64, 2).astype(np.float32) * 0.5 + 0.05
        boxes = np.concatenate([xy, xy + wh], 1)
        got = V.nms(paddle.to_tensor(boxes), 0.3).numpy()
        np.testing.assert_array_equal(got, _naive_nms(boxes, 0.3))

    def test_scores_sorts_first(self):
        boxes = np.array([[0, 0, 1, 1], [0.05, 0, 1.05, 1], [3, 3, 4, 4]],
                         np.float32)
        scores = np.array([0.5, 0.9, 0.7], np.float32)
        got = V.nms(paddle.to_tensor(boxes), 0.5,
                    paddle.to_tensor(scores)).numpy()
        # box1 (highest) suppresses box0; order is by score
        np.testing.assert_array_equal(got, [1, 2])

    def test_categories(self):
        boxes = np.array([[0, 0, 1, 1], [0.02, 0, 1.02, 1],
                          [0, 0, 1, 1], [5, 5, 6, 6]], np.float32)
        scores = np.array([0.9, 0.8, 0.95, 0.3], np.float32)
        cats = np.array([0, 0, 1, 1], np.int64)
        got = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                    paddle.to_tensor(cats), [0, 1]).numpy()
        # per-category: cat0 keeps 0 (suppresses 1), cat1 keeps 2 and 3
        np.testing.assert_array_equal(sorted(got), [0, 2, 3])
        assert got[0] == 2  # sorted by score overall

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [2, 2, 3, 3], [5, 5, 6, 6]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        cats = np.zeros(3, np.int64)
        got = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                    paddle.to_tensor(cats), [0], top_k=2).numpy()
        np.testing.assert_array_equal(got, [0, 1])


class TestRoIAlign:
    def _naive(self, feat, boxes, bidx, ph, pw, scale, ratio, aligned):
        R = len(boxes)
        C, H, W = feat.shape[1:]
        out = np.zeros((R, C, ph, pw), np.float32)

        def sample(b, c, y, x):
            if y < -1 or y > H or x < -1 or x > W:
                return 0.0
            y = min(max(y, 0), H - 1)
            x = min(max(x, 0), W - 1)
            y0, x0 = int(np.floor(y)), int(np.floor(x))
            y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
            ly, lx = y - y0, x - x0
            return (feat[b, c, y0, x0] * (1 - ly) * (1 - lx)
                    + feat[b, c, y0, x1] * (1 - ly) * lx
                    + feat[b, c, y1, x0] * ly * (1 - lx)
                    + feat[b, c, y1, x1] * ly * lx)

        off = 0.5 if aligned else 0.0
        for r, bx in enumerate(boxes):
            x1 = bx[0] * scale - off
            y1 = bx[1] * scale - off
            if aligned:
                w = max(bx[2] * scale - off - x1, 1e-10)
                h = max(bx[3] * scale - off - y1, 1e-10)
            else:
                w = max(bx[2] * scale - x1, 1.0)
                h = max(bx[3] * scale - y1, 1.0)
            bh, bw = h / ph, w / pw
            nh = ratio if ratio > 0 else int(np.ceil(h / ph))
            nw = ratio if ratio > 0 else int(np.ceil(w / pw))
            nh, nw = max(nh, 1), max(nw, 1)
            for c in range(C):
                for i in range(ph):
                    for j in range(pw):
                        acc = 0.0
                        for iy in range(nh):
                            for ix in range(nw):
                                yy = y1 + (i + (iy + 0.5) / nh) * bh
                                xx = x1 + (j + (ix + 0.5) / nw) * bw
                                acc += sample(bidx[r], c, yy, xx)
                        out[r, c, i, j] = acc / (nh * nw)
        return out

    @pytest.mark.parametrize("ratio,aligned", [(2, True), (-1, True),
                                               (2, False)])
    def test_matches_naive(self, ratio, aligned):
        rng = np.random.RandomState(1)
        feat = rng.randn(2, 3, 12, 12).astype(np.float32)
        boxes = np.array([[1, 1, 8, 8], [0, 2, 11, 10], [3, 3, 5, 9]],
                         np.float32)
        bn = np.array([2, 1], np.int32)
        got = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(bn), (4, 4), spatial_scale=0.5,
                          sampling_ratio=ratio, aligned=aligned).numpy()
        want = self._naive(feat, boxes, [0, 0, 1], 4, 4, 0.5, ratio,
                           aligned)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_grad_flows(self):
        feat = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 2, 8, 8).astype(np.float32))
        feat.stop_gradient = False
        boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
        bn = paddle.to_tensor(np.array([1], np.int32))
        out = V.roi_align(feat, boxes, bn, 2, sampling_ratio=2)
        out.sum().backward()
        assert feat.grad is not None
        assert float(np.abs(feat.grad.numpy()).sum()) > 0


class TestRoIPool:
    def test_matches_naive(self):
        rng = np.random.RandomState(2)
        feat = rng.randn(1, 2, 10, 10).astype(np.float32)
        boxes = np.array([[0, 0, 6, 6], [2, 2, 9, 9]], np.float32)
        bn = np.array([2], np.int32)
        ph = pw = 3
        got = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         paddle.to_tensor(bn), ph, 1.0).numpy()
        for r, bx in enumerate(boxes):
            x1, y1, x2, y2 = np.round(bx).astype(int)
            h = max(y2 - y1 + 1, 1)
            w = max(x2 - x1 + 1, 1)
            for c in range(2):
                for i in range(ph):
                    ys = y1 + int(np.floor(i * h / ph))
                    ye = y1 + int(np.ceil((i + 1) * h / ph))
                    for j in range(pw):
                        xs = x1 + int(np.floor(j * w / pw))
                        xe = x1 + int(np.ceil((j + 1) * w / pw))
                        want = feat[0, c,
                                    max(ys, 0):min(ye, 10),
                                    max(xs, 0):min(xe, 10)].max()
                        np.testing.assert_allclose(got[r, c, i, j], want,
                                                   rtol=1e-5)


class TestPSRoIPool:
    def test_matches_naive(self):
        # fractional box whose rounded bin ends extend past the raw
        # extent (regression: window must cover the rounded bounds)
        rng = np.random.RandomState(13)
        ph = pw = 1
        feat = rng.randn(1, ph * pw, 8, 8).astype(np.float32)
        boxes = np.array([[2.5, 2.5, 4.5, 4.5]], np.float32)
        bn = np.array([1], np.int32)
        got = V.psroi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                           paddle.to_tensor(bn), 1, 1.0).numpy()
        # reference math: y1=round(2.5)=2, y2=round(5.5)=6 → rows 2..5
        want = feat[0, 0, 2:6, 2:6].mean()
        np.testing.assert_allclose(got[0, 0, 0, 0], want, rtol=1e-5)

    def test_shape_and_range(self):
        rng = np.random.RandomState(3)
        ph = pw = 2
        out_c = 3
        feat = rng.randn(1, out_c * ph * pw, 8, 8).astype(np.float32)
        boxes = np.array([[0, 0, 4, 4], [2, 2, 7, 7]], np.float32)
        bn = np.array([2], np.int32)
        got = V.psroi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                           paddle.to_tensor(bn), ph, 1.0).numpy()
        assert got.shape == (2, out_c, ph, pw)
        # averages of the input are bounded by input range
        assert got.max() <= feat.max() + 1e-5
        assert got.min() >= feat.min() - 1e-5


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(4)
        x = rng.randn(2, 4, 9, 9).astype(np.float32)
        w = (rng.randn(6, 4, 3, 3) * 0.1).astype(np.float32)
        off = np.zeros((2, 2 * 9, 7, 7), np.float32)
        got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w)).numpy()
        want = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)).numpy()
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_integer_offset_shifts(self):
        # a +1 x-offset on every tap equals convolving the shifted image
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(5)
        x = rng.randn(1, 1, 8, 8).astype(np.float32)
        w = (rng.randn(1, 1, 3, 3) * 0.3).astype(np.float32)
        off = np.zeros((1, 2 * 9, 6, 6), np.float32)
        off[:, 1::2] = 1.0  # dx = +1 on every tap
        got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w)).numpy()
        xs = np.zeros_like(x)
        xs[..., :-1] = x[..., 1:]  # shift left (sample at x+1)
        want = F.conv2d(paddle.to_tensor(xs), paddle.to_tensor(w)).numpy()
        # interior matches; boundary columns differ (zero pad vs shift)
        np.testing.assert_allclose(got[..., :-1], want[..., :-1],
                                   rtol=2e-4, atol=2e-5)

    def test_mask_and_layer(self):
        rng = np.random.RandomState(6)
        x = paddle.to_tensor(rng.randn(1, 4, 6, 6).astype(np.float32))
        layer = V.DeformConv2D(4, 8, 3, padding=1)
        off = paddle.to_tensor(
            (rng.randn(1, 18, 6, 6) * 0.1).astype(np.float32))
        mask = paddle.to_tensor(
            np.ones((1, 9, 6, 6), np.float32) * 0.5)
        full = layer(x, off).numpy()
        half = layer(x, off, mask).numpy()
        b = layer.bias.numpy().reshape(1, -1, 1, 1)
        np.testing.assert_allclose(half - b, (full - b) * 0.5,
                                   rtol=1e-3, atol=1e-4)


class TestYoloBox:
    def test_decode_shapes_and_values(self):
        rng = np.random.RandomState(7)
        N, na, cls, H, W = 2, 3, 4, 5, 5
        x = rng.randn(N, na * (5 + cls), H, W).astype(np.float32)
        img = np.tile(np.asarray([[320, 320]], np.int32), (N, 1))
        boxes, scores = V.yolo_box(paddle.to_tensor(x),
                                   paddle.to_tensor(img),
                                   [10, 13, 16, 30, 33, 23], cls,
                                   conf_thresh=0.0, downsample_ratio=32)
        assert boxes.shape == [N, na * H * W, 4]
        assert scores.shape == [N, na * H * W, cls]
        b = boxes.numpy()
        assert (b[..., 2] >= b[..., 0] - 1e-3).all()
        assert b.min() >= -1e-3 and b.max() <= 320  # clipped

    def test_conf_thresh_zeroes(self):
        x = np.full((1, 1 * 6, 2, 2), -5.0, np.float32)  # low conf
        img = np.asarray([[64, 64]], np.int32)
        boxes, scores = V.yolo_box(paddle.to_tensor(x),
                                   paddle.to_tensor(img), [10, 13], 1,
                                   conf_thresh=0.5, downsample_ratio=32)
        assert np.abs(scores.numpy()).sum() == 0


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(8)
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 20]], np.float32)
        var = [0.1, 0.1, 0.2, 0.2]
        targets = np.array([[1, 1, 8, 8], [6, 6, 18, 19]], np.float32)
        enc = V.box_coder(paddle.to_tensor(priors), var,
                          paddle.to_tensor(targets),
                          code_type="encode_center_size")
        assert enc.shape == [2, 2, 4]
        dec = V.box_coder(paddle.to_tensor(priors), var, enc,
                          code_type="decode_center_size", axis=0)
        d = dec.numpy()
        # the diagonal (target i vs prior i) must reconstruct target i
        for i in range(2):
            np.testing.assert_allclose(d[i, i], targets[i], rtol=1e-4,
                                       atol=1e-3)


class TestPriorMatrixFPN:
    def test_prior_box(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        boxes, var = V.prior_box(feat, img, min_sizes=[8.0],
                                 max_sizes=[16.0], aspect_ratios=[2.0],
                                 flip=True, clip=True)
        assert boxes.shape == [4, 4, 4, 4]  # 1 + 1(max) + 2 ar
        b = boxes.numpy()
        assert b.min() >= 0 and b.max() <= 1

    def test_matrix_nms(self):
        boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                           [20, 20, 30, 30]]], np.float32)
        scores = np.array([[[0.9, 0.85, 0.6]]], np.float32)
        out, n = V.matrix_nms(paddle.to_tensor(boxes),
                              paddle.to_tensor(scores),
                              score_threshold=0.1, post_threshold=0.0,
                              nms_top_k=10, keep_top_k=10,
                              background_label=-1)
        o = out.numpy()
        assert int(n.numpy()[0]) == 3
        # highest score survives undecayed
        assert abs(o[0, 1] - 0.9) < 1e-6
        # heavily-overlapped second box is decayed
        decayed = o[np.argsort(o[:, 5])][0]
        assert o[:, 1].min() < 0.85

    def test_matrix_nms_gaussian(self):
        # reference decay: exp((max_iou^2 - iou^2) * sigma)
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                           [20, 20, 30, 30]]], np.float32)
        scores = np.array([[[0.9, 0.8, 0.6]]], np.float32)
        out, n = V.matrix_nms(paddle.to_tensor(boxes),
                              paddle.to_tensor(scores),
                              score_threshold=0.1, post_threshold=0.0,
                              nms_top_k=10, keep_top_k=10,
                              use_gaussian=True, gaussian_sigma=2.0,
                              background_label=-1)
        o = out.numpy()
        iou = 81.0 / (100 + 100 - 81)
        want = 0.8 * np.exp((0.0 - iou ** 2) * 2.0)  # 0.317 < 0.6
        got = sorted(o[:, 1])[0]  # smallest score = decayed 2nd box
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_roi_pool_outside_image_is_zero(self):
        feat = np.ones((1, 1, 8, 8), np.float32) * 5.0
        boxes = np.array([[-6, -6, -2, -2]], np.float32)
        bn = np.array([1], np.int32)
        got = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         paddle.to_tensor(bn), 2, 1.0).numpy()
        np.testing.assert_array_equal(got, 0.0)

    def test_distribute_fpn(self):
        rois = np.array([[0, 0, 10, 10], [0, 0, 100, 100],
                         [0, 0, 224, 224], [0, 0, 500, 500]], np.float32)
        multi, restore = V.distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224)
        assert len(multi) == 4
        total = sum(m.shape[0] for m in multi)
        assert total == 4
        r = restore.numpy().ravel()
        np.testing.assert_array_equal(sorted(r), [0, 1, 2, 3])

    def test_generate_proposals(self):
        rng = np.random.RandomState(9)
        H = W = 4
        A = 2
        scores = rng.rand(1, A, H, W).astype(np.float32)
        deltas = (rng.randn(1, 4 * A, H, W) * 0.1).astype(np.float32)
        anchors = rng.rand(H * W * A, 4).astype(np.float32)
        anchors[:, 2:] = anchors[:, :2] + 4 + rng.rand(H * W * A, 2) * 8
        var = np.ones((H * W * A, 4), np.float32)
        rois, rscores, n = V.generate_proposals(
            paddle.to_tensor(scores), paddle.to_tensor(deltas),
            paddle.to_tensor(np.asarray([[32., 32.]], np.float32)),
            paddle.to_tensor(anchors), paddle.to_tensor(var),
            pre_nms_top_n=16, post_nms_top_n=8, nms_thresh=0.7,
            min_size=1.0, return_rois_num=True)
        assert rois.shape[1] == 4
        assert rois.shape[0] == int(n.numpy()[0]) <= 8


class TestYoloLoss:
    def test_loss_decreases_towards_target(self):
        # loss with correct predictions should be far below random ones
        rng = np.random.RandomState(10)
        N, na, cls, H, W = 1, 3, 2, 4, 4
        anchors = [10, 13, 16, 30, 33, 23]
        gtb = np.array([[[0.4, 0.4, 0.2, 0.3]]], np.float32)  # cx cy w h
        gtl = np.array([[1]], np.int64)
        x_rand = rng.randn(N, na * (5 + cls), H, W).astype(np.float32)
        loss_r = float(V.yolo_loss(
            paddle.to_tensor(x_rand), paddle.to_tensor(gtb),
            paddle.to_tensor(gtl), anchors, [0, 1, 2], cls, 0.7, 32,
            use_label_smooth=False).numpy()[0])
        assert np.isfinite(loss_r) and loss_r > 0

    def test_grad_flows(self):
        rng = np.random.RandomState(11)
        x = paddle.to_tensor(
            rng.randn(1, 3 * 7, 4, 4).astype(np.float32))
        x.stop_gradient = False
        gtb = paddle.to_tensor(np.array([[[0.5, 0.5, 0.3, 0.3]]],
                                        np.float32))
        gtl = paddle.to_tensor(np.array([[0]], np.int64))
        loss = V.yolo_loss(x, gtb, gtl, [10, 13, 16, 30, 33, 23],
                           [0, 1, 2], 2, 0.7, 32)
        loss.sum().backward()
        assert float(np.abs(x.grad.numpy()).sum()) > 0


class TestDatasets:
    def _write_mnist(self, tmp, n=32):
        rng = np.random.RandomState(0)
        imgs = rng.randint(0, 256, (n, 28, 28)).astype(np.uint8)
        lbls = rng.randint(0, 10, (n,)).astype(np.uint8)
        ip = os.path.join(tmp, "images.gz")
        lp = os.path.join(tmp, "labels.gz")
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(imgs.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(lbls.tobytes())
        return ip, lp, imgs, lbls

    def test_mnist(self, tmp_path):
        ip, lp, imgs, lbls = self._write_mnist(str(tmp_path))
        ds = D.MNIST(image_path=ip, label_path=lp, mode="train")
        assert len(ds) == 32
        img, lbl = ds[3]
        assert img.shape == (28, 28, 1)
        np.testing.assert_allclose(img[..., 0], imgs[3])
        assert int(lbl[0]) == int(lbls[3])

    def test_cifar10(self, tmp_path):
        rng = np.random.RandomState(1)
        data = rng.randint(0, 256, (20, 3072)).astype(np.uint8)
        labels = rng.randint(0, 10, (20,)).tolist()
        tar_path = str(tmp_path / "cifar-10-python.tar.gz")
        inner = {b"data": data, b"labels": labels}
        blob = pickle.dumps(inner)
        with tarfile.open(tar_path, "w:gz") as tf:
            info = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
            info.size = len(blob)
            import io as _io
            tf.addfile(info, _io.BytesIO(blob))
        ds = D.Cifar10(data_file=tar_path, mode="train")
        assert len(ds) == 20
        img, lbl = ds[5]
        assert img.shape == (32, 32, 3)
        assert int(lbl) == labels[5]

    def test_folder(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                Image.fromarray(
                    np.zeros((4, 4, 3), np.uint8)).save(d / f"{i}.png")
        ds = D.DatasetFolder(str(tmp_path))
        assert len(ds) == 6
        assert ds.classes == ["cat", "dog"]
        img, target = ds[0]
        assert img.shape == (4, 4, 3) and target == 0
        flat = D.ImageFolder(str(tmp_path))
        assert len(flat) == 6

    def test_no_download_raises(self):
        with pytest.raises(RuntimeError, match="no network egress"):
            D.MNIST()
        with pytest.raises(RuntimeError, match="no network egress"):
            D.Cifar10()
