"""North-star-scale AOT validation (BASELINE.md configs 3-4).

GPT-6.7B (dp x sharding, ZeRO-3, remat, bf16+master) and LLaMA-13B
(tp x pp x dp) training steps are lowered and compiled on the 8-device
virtual mesh with LazyGuard-abstract parameters — zero bytes allocated —
and their per-device memory demands are asserted against the v5p HBM
budget and a recorded watermark (>10% regression fails, VERDICT r3
item 5). Reference-scale counterpart: the fleet hybrid suites
(unittests/collective/fleet/hybrid_parallel_pp_transformer.py), which
need a real cluster; XLA's compiler validates the same compositions here.

These are the slowest tests in the suite (~40-90s each: full-scale HLO).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                               LlamaForCausalLM, LlamaPipelineForCausalLM,
                               llama_13b)

V5P_HBM = 95 * 2 ** 30          # public v5p HBM per chip
# Recorded round-3 per-device ARGUMENT watermarks (bytes); >10%
# regression fails. Arguments (sharded params + optimizer slots + master
# weights) are the backend-independent memory floor — XLA:CPU's
# temp/activation accounting does not transfer to the TPU backend
# (its CPU buffer assignment neither fuses nor schedules like TPU), so
# temps are informational only.
GPT67_ARGS_RECORDED = 24_026_312_712      # dp2 x sharding4, ZeRO-3, bf16
LLAMA13_ARGS_RECORDED = 27_350_000_000    # mp2 x pp2 x dp2, ZeRO-2, f32
LLAMA13_SCAN_ARGS_RECORDED = 45_555_590_664  # dp2 x sharding4, ZeRO-3, bf16+master


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def test_lazy_guard_abstract_params():
    with paddle.LazyGuard():
        m = GPTForCausalLM(GPTConfig(vocab_size=128, hidden_size=32,
                                     num_layers=2, num_heads=4,
                                     max_seq_len=32))
        m.bfloat16()
    p = next(iter(m.parameters()))
    assert isinstance(p.value, jax.ShapeDtypeStruct)
    assert p.dtype == jnp.bfloat16
    # a step built from an abstract model must refuse to train
    dist.init_mesh({"dp": 8})
    opt = paddle.optimizer.AdamW(parameters=m.parameters())
    step = dist.ParallelTrainStep(m, GPTForCausalLM.loss_fn, opt)
    with pytest.raises(RuntimeError, match="LazyGuard"):
        step(paddle.to_tensor(np.zeros((8, 32), "int64")))


def _gpt67_aot_argument_bytes(scan_layers: bool,
                              check_no_activation_gather=False) -> int:
    """BASELINE config 3: GPT-6.7B, dp2 x sharding4, ZeRO-3, remat,
    bf16 params + fp32 master — AOT-compile and return per-device
    argument bytes."""
    dist.init_mesh({"dp": 2, "sharding": 4})
    with paddle.LazyGuard():
        model = GPTForCausalLM(GPTConfig(
            hidden_size=4096, num_layers=32, num_heads=32,
            max_seq_len=2048, tie_embeddings=False,
            scan_layers=scan_layers))
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                                 parameters=model.parameters())
    step = dist.ParallelTrainStep(model, GPTForCausalLM.loss_fn, opt,
                                  zero_stage=3, remat=True)
    ids = jax.ShapeDtypeStruct((8, 2048), jnp.int64)
    compiled = step.aot_compile(ids, ids)      # raises if lowering breaks
    if check_no_activation_gather:
        _assert_no_activation_sized_gathers(compiled.as_text())
    return compiled.memory_analysis().argument_size_in_bytes


def _assert_no_activation_sized_gathers(hlo: str) -> None:
    """Regression gate for the r5 ZeRO-3 pathology: with the zero axis
    on both matmul operands, the SPMD partitioner can resolve the
    conflict by un-sharding ACTIVATIONS instead of weights (measured
    2.7 TiB/step before the use-site gather fix). Discriminator: every
    activation tensor carries the sequence dim (2048) and is large;
    no weight at this geometry has a 2048 dim except the [2048, H]
    position table (33 MB f32 — under the size floor). Flag any
    all-gather whose result has a 2048 dim AND exceeds 64 MB."""
    import re
    width = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "s64": 8}
    matched = 0
    for m in re.finditer(
            r"^\s*(?:ROOT\s+)?(?:[%\w.\-]+|\([^)]*\)) = "
            r"(\([^)]*\)|[\w\[\],{}\s/]+?) "
            r"all-gather(?:-start|-done)?\(", hlo, re.M):
        matched += 1
        # judge each tensor in the signature on its own (an async
        # -start result is an (operand, result) tuple — summing would
        # double-count; the full gathered tensor judges itself)
        for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", m.group(1)):
            if dt not in width:
                continue
            n = 1
            has_seq_dim = False
            for d in dims.split(","):
                if d:
                    n *= int(d)
                    if int(d) == 2048:
                        has_seq_dim = True
            nbytes = n * width[dt]
            assert not (has_seq_dim and nbytes > 64 * 2**20), (
                f"activation-sized all-gather ({nbytes/2**20:.0f} MiB) "
                f"in the ZeRO-3 step — the use-site weight gather "
                f"regressed: {m.group(1).strip()[:90]}")
    # the gate must never be vacuous: ZeRO-3 always gathers weights
    assert matched > 0, "no all-gather matched — gate regex is broken"


def _assert_gpt67_memory(args: int) -> None:
    assert args < 0.9 * V5P_HBM, f"6.7B step needs {args/2**30:.1f}GiB"
    assert args < 1.1 * GPT67_ARGS_RECORDED, (
        f"per-device argument memory regressed: {args} vs recorded "
        f"{GPT67_ARGS_RECORDED}")


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_gpt_6_7b_zero3_remat_aot_fits_v5p():
    """Unrolled variant: must compile and fit v5p HBM."""
    _assert_gpt67_memory(_gpt67_aot_argument_bytes(scan_layers=False))


@pytest.mark.timeout(300)
def test_gpt_6_7b_scan_layers_aot_fast():
    """Same BASELINE config 3 with cfg.scan_layers: the 32-block stack
    compiles as ONE lax.scan body, so the full 6.7B ZeRO-3+remat step
    AOT-compiles in seconds (measured 7.4s vs 209s unrolled on this
    host, 28x) with IDENTICAL per-device argument memory. Fast enough
    to run in every CI profile — depth-independent compile is the
    feature; this guards it at north-star scale, plus the r5
    no-activation-sized-gathers pathology gate."""
    _assert_gpt67_memory(_gpt67_aot_argument_bytes(
        scan_layers=True, check_no_activation_gather=True))


@pytest.mark.timeout(300)
def test_llama_13b_scan_zero3_aot_fast():
    """BASELINE config 4 through the non-pipeline lens: LLaMA-13B
    (40 layers), ZeRO-3 + remat + scan_layers + fused CE, bf16 params.
    Depth-independent compile makes the full 13B step AOT-compile in
    seconds, so the config is guarded in every CI profile (the pipeline
    variant remains the slow-marked test below)."""
    dist.init_mesh({"dp": 2, "sharding": 4})
    with paddle.LazyGuard():
        # step-level remat only (like the GPT counterpart); cfg.recompute
        # would nest a second jax.checkpoint inside the scan body
        model = LlamaForCausalLM(llama_13b(scan_layers=True,
                                           fused_loss_chunk=2048))
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                                 parameters=model.parameters())
    step = dist.ParallelTrainStep(model, model.make_loss_fn(), opt,
                                  zero_stage=3, remat=True)
    ids = jax.ShapeDtypeStruct((8, 2048), jnp.int64)
    compiled = step.aot_compile(ids, ids)
    args = compiled.memory_analysis().argument_size_in_bytes
    assert args < 0.9 * V5P_HBM, f"13B scan step needs {args/2**30:.1f}GiB"
    assert args < 1.1 * LLAMA13_SCAN_ARGS_RECORDED, (
        f"per-device argument memory regressed: {args} vs recorded "
        f"{LLAMA13_SCAN_ARGS_RECORDED}")


def test_bf16_pipeline_lowers_for_tpu():
    """The bf16 ppermute pipeline pattern (the config that actually runs
    on v5p) must LOWER for the TPU backend even though XLA:CPU cannot
    compile it ("Invalid binary instruction opcode copy", a CPU-backend
    bug). jax.export cross-lowers the full hybrid step for platform
    "tpu" on this TPU-less host; the resulting StableHLO must carry the
    bf16 collective_permute ring. Replaces the f32-only evidence from
    round 3 (VERDICT r3 item 6); backend codegen is exercised on real
    hardware by the driver's dryrun/bench."""
    from paddle_tpu.models import LlamaConfig
    dist.init_mesh({"pp": 2, "mp": 2, "dp": 2})
    with paddle.LazyGuard():
        model = LlamaPipelineForCausalLM(
            LlamaConfig(vocab_size=256, hidden_size=64, num_layers=4,
                        num_heads=4, intermediate_size=128,
                        max_seq_len=128),
            num_stages=2, num_micro=4)
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                                 parameters=model.parameters())
    step = dist.ParallelTrainStep(model, LlamaForCausalLM.loss_fn, opt,
                                  zero_stage=2)
    ids = jax.ShapeDtypeStruct((8, 128), jnp.int64)
    exported = step.aot_compile(ids, ids, platform="tpu")
    assert exported.platforms == ("tpu",)
    mlir = exported.mlir_module()
    assert "collective_permute" in mlir          # the pipeline ring
    # the f32-workaround pattern must not silently return: the ring
    # must move bf16 activations
    ring_ops = [l for l in mlir.splitlines() if "collective_permute" in l]
    assert any("bf16" in l for l in ring_ops), ring_ops[:3]


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_llama_13b_tp_pp_aot_fits_v5p():
    """BASELINE config 4: LLaMA-13B, mp2 x pp2 x dp2 hybrid, ZeRO-2.

    f32 on the XLA:CPU compile path only — the bf16 variant of the same
    ppermute pipeline pattern is validated for the TPU backend by
    test_bf16_pipeline_lowers_for_tpu above (XLA:CPU crashes with
    "Invalid binary instruction opcode copy" on bf16 ppermute, a
    CPU-backend-only bug). f32 numbers are the conservative (2x) bound.
    """
    dist.init_mesh({"pp": 2, "mp": 2, "dp": 2})
    with paddle.LazyGuard():
        model = LlamaPipelineForCausalLM(llama_13b(), num_stages=2,
                                         num_micro=4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.ParallelTrainStep(model, LlamaForCausalLM.loss_fn, opt,
                                  zero_stage=2)
    ids = jax.ShapeDtypeStruct((8, 2048), jnp.int64)
    compiled = step.aot_compile(ids, ids)
    args = compiled.memory_analysis().argument_size_in_bytes
    assert args < 0.9 * V5P_HBM, f"13B step needs {args/2**30:.1f}GiB"
    assert args < 1.1 * LLAMA13_ARGS_RECORDED, (
        f"per-device argument memory regressed: {args} vs recorded "
        f"{LLAMA13_ARGS_RECORDED}")
