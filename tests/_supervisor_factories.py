"""Trainer factories for the supervisor tests (and loadable by child
processes through the supervisor's ``file.py:fn`` factory spec).

The trainer is deliberately tiny and DETERMINISTIC: fixed seed, fixed
data, ``shuffle=False`` — the contract that makes crash/preempt resume
bitwise-comparable against an unfaulted run.
"""
import os
import time

import numpy as np


class _Rows:
    """Minimal deterministic map-style dataset."""

    def __init__(self, xs, ys):
        self.xs, self.ys = xs, ys

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]


def make_trainer():
    """(model, loader, fit_kwargs): 8 steps/epoch x 3 epochs of SGD on
    a Linear(4,4) MSE problem. PTPU_TEST_STEP_SLEEP (seconds) slows
    each step so tests can land signals mid-run."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import Callback
    from paddle_tpu.io.dataloader import DataLoader

    paddle.seed(7)
    net = nn.Linear(4, 4)
    model = Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    model.prepare(optimizer=opt, loss=lambda o, y: F.mse_loss(o, y))
    rng = np.random.RandomState(3)
    xs = rng.randn(32, 4).astype("float32")
    ys = rng.randn(32, 4).astype("float32")
    loader = DataLoader(_Rows(xs, ys), batch_size=4, shuffle=False)

    sleep_s = float(os.environ.get("PTPU_TEST_STEP_SLEEP", "0") or 0)

    class SlowStep(Callback):
        def on_train_batch_end(self, step, logs=None):
            if sleep_s:
                time.sleep(sleep_s)

    return model, loader, {"epochs": 3, "verbose": 0,
                           "callbacks": [SlowStep()]}


def make_crashing_trainer():
    """A trainer that cannot even build — the crash-loop fixture."""
    raise RuntimeError("injected: trainer factory crashes at build")
