"""Round-4 namespace long tail: distributed compat, sharding entry
points, L-BFGS optimizers, sparse.nn additions, incubate.nn.functional
fused ops, cost_model, device.cuda (references cited per module)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


class TestDistributedCompat:
    def test_object_collectives_single_process(self):
        objs = [{"a": 1}, "hello", np.arange(3)]
        dist.broadcast_object_list(objs, src=0)
        assert objs[0] == {"a": 1} and objs[1] == "hello"
        out = []
        dist.scatter_object_list(out, [["r0"], ["r1"]], src=0)
        assert out == [["r0"]]

    def test_lifecycle_and_misc(self):
        assert dist.is_available()
        assert dist.get_backend() == "XLA"
        assert dist.ParallelMode.PIPELINE_PARALLEL == 2
        t = paddle.to_tensor(np.ones(2, np.float32))
        assert dist.wait(t) is t
        dist.init_mesh({"dp": 8})
        dist.destroy_process_group()
        from paddle_tpu.distributed.mesh import get_mesh
        assert get_mesh(create_default=False) is None
        with pytest.raises(NotImplementedError, match="ColumnParallel"):
            dist.split(t, (2, 2), "linear")
        with pytest.raises(NotImplementedError, match="parameter-server"):
            dist.InMemoryDataset()

    def test_group_sharded_parallel_sets_zero_stage(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        dist.init_mesh({"dp": 2, "sharding": 4})
        model = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=16))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        with pytest.raises(ValueError):
            dist.sharding.group_sharded_parallel(model, opt, "bogus")
        model, opt, _ = dist.sharding.group_sharded_parallel(
            model, opt, "p_g_os")
        step = dist.ParallelTrainStep(model, GPTForCausalLM.loss_fn, opt)
        assert step.zero_stage == 3

    def test_passes_rewrite_the_step_plan(self):
        # the pass pipeline REALLY mutates the training-step plan
        # (reference PassManager.apply rewrites Programs; the plan is
        # this design's program surface — see passes.py docstring)
        plan = dist.passes.new_step_plan()
        pm = dist.passes.PassManager([
            dist.passes.new_pass("auto_parallel_recompute",
                                 {"policy": "dots"}),
            dist.passes.new_pass("auto_parallel_sharding", {"stage": 3}),
            dist.passes.new_pass("auto_parallel_gradient_merge",
                                 {"k_steps": 4}),
            dist.passes.new_pass("auto_parallel_amp", {"level": "O2"}),
        ])
        plan, _ = pm.apply(plan)
        assert plan["remat"] and plan["remat_policy"] == "dots"
        assert plan["zero_stage"] == 3
        assert plan["accumulate_steps"] == 4
        assert plan["amp_level"] == "O2"
        assert len(pm.context.applied_passes) == 4
        assert pm.names[0] == "auto_parallel_recompute"
        # unknown passes construct (ported configs) but refuse to no-op
        bogus = dist.passes.new_pass("fuse_all_reduce_ops")
        import pytest as _pytest
        with _pytest.raises(NotImplementedError):
            bogus.apply(plan)
        assert dist.communication.stream.all_reduce is dist.all_reduce


class TestOptimizerLongTail:
    def test_lbfgs_optimizer(self):
        from paddle_tpu.incubate.optimizer import LBFGS
        p = paddle.create_parameter([4], "float32")
        target = paddle.to_tensor(np.array([1., -2., 3., .5], np.float32))
        opt = LBFGS(max_iter=30, parameters=[p], line_search_fn="armijo")

        def closure():
            opt.clear_grad()
            loss = ((p - target) ** 2).sum()
            loss.backward()
            return loss

        opt.step(closure)
        np.testing.assert_allclose(p.numpy(), target.numpy(), atol=1e-3)

    def test_functional_minimizers(self):
        from paddle_tpu.incubate.optimizer.functional import (minimize_bfgs,
                                                              minimize_lbfgs)
        A = np.array([[3., 1.], [1., 2.]], np.float32)
        b = np.array([1., -2.], np.float32)

        def quad(x):
            return 0.5 * (x @ paddle.to_tensor(A) @ x) - \
                (x * paddle.to_tensor(b)).sum()

        want = np.linalg.solve(A, b)
        for fn in (minimize_bfgs, minimize_lbfgs):
            ok, nfev, x, fx, g = fn(quad, np.zeros(2, np.float32))
            assert bool(ok.numpy())
            np.testing.assert_allclose(x.numpy(), want, atol=1e-4)

        # Rosenbrock in f32: the Armijo BFGS must still solve it
        def rosen(x):
            return (1 - x[0]) ** 2 + 100 * (x[1] - x[0] ** 2) ** 2
        ok, _, x, fx, _ = minimize_bfgs(
            rosen, np.array([-1.2, 1.0], np.float32), max_iters=200)
        assert float(fx.numpy()) < 1e-4


class TestSparseAdditions:
    def test_softmax_per_row_over_nnz(self):
        import paddle_tpu.sparse as sp
        import paddle_tpu.sparse.nn as snn
        idx = np.array([[0, 0, 1, 1, 1], [0, 2, 0, 1, 3]], np.int64)
        vals = np.array([1.0, 2.0, 0.5, -1.0, 3.0], np.float32)
        x = sp.sparse_coo_tensor(idx, vals, (2, 4))
        dense = snn.functional.softmax(x).to_dense().numpy()
        e = np.exp(np.array([1.0, 2.0]) - 2.0)
        np.testing.assert_allclose(dense[0, [0, 2]], e / e.sum(), rtol=1e-5)
        assert dense[0, 1] == 0

    def test_activations_and_pool(self):
        import paddle_tpu.sparse as sp
        import paddle_tpu.sparse.nn as snn
        idx = np.array([[0, 1], [0, 1]], np.int64)
        vals = np.array([-2.0, 8.0], np.float32)
        x = sp.sparse_coo_tensor(idx, vals, (2, 2))
        np.testing.assert_allclose(
            snn.LeakyReLU(0.1)(x).values().numpy(), [-0.2, 8.0])
        np.testing.assert_allclose(
            snn.ReLU6()(x).values().numpy(), [0.0, 6.0])
        coords = np.array([[0, 0, 0, 0], [0, 1, 1, 1]], np.int64).T
        vol = sp.sparse_coo_tensor(
            coords, np.array([[1.0], [5.0]], np.float32), (1, 4, 4, 4, 1))
        pd = snn.MaxPool3D(2, 2)(vol).to_dense().numpy()
        assert pd.shape == (1, 2, 2, 2, 1) and pd[0, 0, 0, 0, 0] == 5.0

    def test_masked_attention(self):
        import paddle_tpu.sparse as sp
        import paddle_tpu.sparse.nn as snn
        B, H, S, D = 1, 1, 4, 8
        rng = np.random.RandomState(0)
        q = paddle.to_tensor(rng.randn(B, H, S, D).astype(np.float32))
        mask_dense = np.tril(np.ones((S, S), np.float32))
        mask = sp.to_sparse_coo(paddle.to_tensor(mask_dense[None]),
                                sparse_dim=3)
        out = snn.functional.attention(q, q, q, mask)
        s = (q.numpy()[0, 0] @ q.numpy()[0, 0].T) / np.sqrt(D)
        s = np.where(mask_dense > 0, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        np.testing.assert_allclose(out.numpy()[0, 0], p @ q.numpy()[0, 0],
                                   rtol=1e-4, atol=1e-5)

    def test_sync_batchnorm_convert(self):
        import paddle_tpu.sparse.nn as snn
        net = paddle.nn.Sequential()
        net.add_sublayer("bn", snn.BatchNorm(4))
        snn.SyncBatchNorm.convert_sync_batchnorm(net)
        assert type(net._sub_layers["bn"]).__name__ == "SyncBatchNorm"


class TestFusedFunctional:
    def test_fused_mha_matches_manual(self):
        import paddle_tpu.incubate.nn.functional as IF
        rng = np.random.RandomState(0)
        B, S, E, nh = 2, 4, 16, 2
        hd = E // nh
        x = paddle.to_tensor(rng.randn(B, S, E).astype(np.float32))
        qkvw = paddle.to_tensor(
            rng.randn(3, nh, hd, E).astype(np.float32) * 0.1)
        lw = paddle.to_tensor(rng.randn(E, E).astype(np.float32) * 0.1)
        ones = paddle.to_tensor(np.ones(E, np.float32))
        zeros = paddle.to_tensor(np.zeros(E, np.float32))
        out = IF.fused_multi_head_attention(
            x, qkvw, lw, dropout_rate=0.0, attn_dropout_rate=0.0,
            ln_scale=ones, ln_bias=zeros, training=False)
        xe = x.numpy()
        qkv = np.einsum("bse,tnde->tbnsd", xe, qkvw.numpy())
        q, k, v = qkv
        s = np.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ctx = np.einsum("bnqk,bnkd->bqnd", p, v).reshape(B, S, E)
        ref = xe + ctx @ lw.numpy()
        mu = ref.mean(-1, keepdims=True)
        ref = (ref - mu) / np.sqrt(ref.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_ffn_grads_and_ec_moe(self):
        import paddle_tpu.incubate.nn.functional as IF
        rng = np.random.RandomState(1)
        B, S, E = 2, 3, 8
        x = paddle.to_tensor(rng.randn(B, S, E).astype(np.float32))
        ones = paddle.to_tensor(np.ones(E, np.float32))
        zeros = paddle.to_tensor(np.zeros(E, np.float32))
        w1 = paddle.to_tensor(rng.randn(E, 16).astype(np.float32) * 0.1,
                              stop_gradient=False)
        w2 = paddle.to_tensor(rng.randn(16, E).astype(np.float32) * 0.1,
                              stop_gradient=False)
        y = IF.fused_feedforward(x, w1, w2, ln2_scale=ones, ln2_bias=zeros,
                                 dropout1_rate=0.0, dropout2_rate=0.0,
                                 training=False)
        y.sum().backward()
        assert w1.grad is not None and np.isfinite(w1.grad.numpy()).all()

        e, dff = 3, 4
        gate = paddle.to_tensor(rng.randn(B, S, e).astype(np.float32))
        w0 = paddle.to_tensor(rng.randn(e, E, dff).astype(np.float32) * .1)
        b0 = paddle.to_tensor(np.zeros((e, dff), np.float32))
        w1m = paddle.to_tensor(rng.randn(e, dff, E).astype(np.float32) * .1)
        b1m = paddle.to_tensor(np.zeros((e, E), np.float32))
        moe = IF.fused_ec_moe(x, gate, w0, b0, w1m, b1m, "relu")
        pg = np.exp(gate.numpy() - gate.numpy().max(-1, keepdims=True))
        pg /= pg.sum(-1, keepdims=True)
        h = np.maximum(np.einsum("bsd,edf->besf", x.numpy(), w0.numpy()), 0)
        ym = np.einsum("besf,efd->besd", h, w1m.numpy())
        np.testing.assert_allclose(
            moe.numpy(), np.einsum("besd,bse->bsd", ym, pg),
            rtol=1e-4, atol=1e-5)


class TestMiscSurfaces:
    def test_cost_model(self):
        import jax.numpy as jnp
        cm = paddle.cost_model.CostModel()
        cost = cm.profile_measure(lambda x: (x @ x.T).sum(),
                                  (jnp.ones((32, 32), jnp.float32),))
        assert cost["flops"] > 0 and cost["measured_seconds"] > 0

    def test_device_cuda_surface(self):
        import paddle_tpu.device.cuda as cuda
        assert cuda.device_count() >= 1
        props = cuda.get_device_properties()
        assert props.name and cuda.get_device_capability() == (0, 0)
        cuda.synchronize()

    def test_inference_enums_and_pool(self, tmp_path):
        from paddle_tpu.inference import (Config, DataType, PredictorPool,
                                          get_num_bytes_of_data_type,
                                          get_version)
        assert get_num_bytes_of_data_type(DataType.BFLOAT16) == 2
        assert "paddle_tpu" in get_version()
        import paddle_tpu.nn as nn
        prefix = str(tmp_path / "m")
        paddle.jit.save(nn.Linear(4, 2), prefix,
                        input_spec=[paddle.jit.InputSpec([1, 4])])
        pool = PredictorPool(Config(prefix), 2)
        assert len(pool) == 2
        [out] = pool.retrive(1).run([np.ones((1, 4), np.float32)])
        assert out.shape == (1, 2)

    def test_quanter_decorator_and_stub(self):
        from paddle_tpu.quantization import quanter
        from paddle_tpu.quantization.base import BaseQuanter
        import paddle_tpu.quantization.factory as fac

        @quanter("TestQF")
        class _TQ(BaseQuanter):
            def forward(self, x):
                return x

            def scales(self):
                return 1.0

            def zero_points(self):
                return 0

        assert hasattr(fac, "TestQF")
        import paddle_tpu.nn.quant as q
        t = paddle.to_tensor(np.float32(3))
        assert float(q.Stub()(t).numpy()) == 3.0

    def test_incubate_autograd(self):
        import paddle_tpu.incubate.autograd as ia
        x = paddle.to_tensor(np.array([1., 2.], np.float32))
        J = ia.Jacobian(lambda v: (v * v).sum(), x)
        np.testing.assert_allclose(np.asarray(J[:].numpy()), [2., 4.])
        ia.enable_prim()
        assert ia.prim_enabled()
        ia.disable_prim()
        with pytest.raises(NotImplementedError, match="jvp"):
            ia.forward_grad(x, x)
