"""Core tensor op tests, OpTest style (reference op_test.py pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle

from op_test import OpTest

rng = np.random.default_rng(0)


class TestMatmul(OpTest):
    def setup_method(self, m):
        self.op = paddle.matmul
        self.inputs = {"x": rng.standard_normal((3, 4), dtype=np.float32),
                       "y": rng.standard_normal((4, 5), dtype=np.float32)}
        self.ref = lambda x, y: x @ y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestAddBroadcast(OpTest):
    def setup_method(self, m):
        self.op = paddle.add
        self.inputs = {"x": rng.standard_normal((2, 3, 4), dtype=np.float32),
                       "y": rng.standard_normal((4,), dtype=np.float32)}
        self.ref = lambda x, y: x + y

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestSoftmaxLike(OpTest):
    def setup_method(self, m):
        self.op = lambda x: paddle.exp(x) / paddle.exp(x).sum(axis=-1, keepdim=True)
        self.inputs = {"x": rng.standard_normal((5, 7), dtype=np.float32)}
        self.ref = lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


class TestReduce(OpTest):
    def setup_method(self, m):
        self.op = paddle.mean
        self.attrs = {"axis": 1, "keepdim": True}
        self.inputs = {"x": rng.standard_normal((3, 5, 2), dtype=np.float32)}
        self.ref = lambda x, axis, keepdim: np.mean(x, axis=axis, keepdims=keepdim)

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad()


def test_creation():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2], 7).numpy().tolist() == [7, 7]
    assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
    assert paddle.eye(3).numpy().trace() == 3
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert str(t.dtype) == "float32"


def test_manipulation():
    x = paddle.to_tensor(rng.standard_normal((2, 3, 4), dtype=np.float32))
    assert paddle.reshape(x, [6, 4]).shape == [6, 4]
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.concat([x, x], axis=0).shape == [4, 3, 4]
    assert paddle.stack([x, x], axis=0).shape == [2, 2, 3, 4]
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    assert paddle.squeeze(paddle.unsqueeze(x, 0), 0).shape == x.shape
    assert paddle.flatten(x, 1, 2).shape == [2, 12]
    assert x.T.shape == [4, 3, 2]


def test_indexing_and_setitem():
    x = paddle.zeros([4, 4])
    x[1, 2] = 5.0
    assert x.numpy()[1, 2] == 5.0
    y = x[1]
    assert y.shape == [4]
    x[0] = paddle.ones([4])
    assert x.numpy()[0].sum() == 4


def test_logic_search():
    x = paddle.to_tensor([3.0, 1.0, 2.0])
    assert paddle.argmax(x).item() == 0
    assert paddle.argsort(x).numpy().tolist() == [1, 2, 0]
    v, i = paddle.topk(x, 2)
    assert v.numpy().tolist() == [3.0, 2.0]
    assert i.numpy().tolist() == [0, 2]
    assert bool(paddle.allclose(x, x).item())
    w = paddle.where(x > 1.5, x, paddle.zeros_like(x))
    assert w.numpy().tolist() == [3.0, 0.0, 2.0]


def test_einsum():
    a = rng.standard_normal((3, 4), dtype=np.float32)
    b = rng.standard_normal((4, 5), dtype=np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_linalg():
    a = rng.standard_normal((4, 4), dtype=np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    t = paddle.to_tensor(spd)
    c = paddle.linalg.cholesky(t)
    np.testing.assert_allclose((c @ c.T).numpy(), spd, rtol=1e-4, atol=1e-4)
    inv = paddle.linalg.inverse(t)
    np.testing.assert_allclose((t @ inv).numpy(), np.eye(4), atol=1e-4)


def test_inplace_ops():
    x = paddle.ones([3])
    x.add_(paddle.ones([3]))
    assert x.numpy().tolist() == [2.0, 2.0, 2.0]
    x.scale_(2.0)
    assert x.numpy().tolist() == [4.0, 4.0, 4.0]


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.rand([3, 3])
    paddle.seed(42)
    b = paddle.rand([3, 3])
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    assert paddle.randint(0, 10, [20]).numpy().max() < 10
    p = paddle.randperm(16)
    assert sorted(p.numpy().tolist()) == list(range(16))


def test_dtype_cast():
    x = paddle.to_tensor([1.5, 2.5])
    assert str(x.astype("int32").dtype) == "int32"
    assert str(x.astype(paddle.bfloat16).dtype) == "bfloat16"
