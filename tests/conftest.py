"""Test env bootstrap.

Tests run on 8 virtual CPU devices so sharding/collective tests exercise the
same XLA code path as real chips without hardware (SURVEY.md §4: the
reference spawns real processes per card; virtual host devices replace that).

jax is pre-imported at interpreter startup in this image, so setting
JAX_PLATFORMS/XLA_FLAGS via os.environ in conftest is too late — if the env
is not already correct, re-run pytest in a child process with the right env
(after releasing pytest's fd capture so output flows through).
"""
import os
import subprocess
import sys

_WANT = "--xla_force_host_platform_device_count=8"


def _env_ok():
    return (os.environ.get("_PADDLE_TPU_TEST_REEXEC") == "1"
            or (os.environ.get("JAX_PLATFORMS") == "cpu"
                and _WANT in os.environ.get("XLA_FLAGS", "")
                and not os.environ.get("PALLAS_AXON_POOL_IPS")))


def pytest_configure(config):
    if _env_ok():
        return
    env = dict(os.environ)
    env["_PADDLE_TPU_TEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + _WANT).strip()
    # the axon sitecustomize registers the TPU backend whenever this var is
    # set, overriding JAX_PLATFORMS=cpu — tests must run on the virtual
    # 8-device CPU mesh
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # Exact fp32 matmuls for numeric checks (prod keeps fast MXU default).
    env.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    ret = subprocess.call([sys.executable, "-m", "pytest"] + sys.argv[1:],
                          env=env)
    os._exit(ret)
