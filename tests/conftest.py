"""Test env bootstrap.

Tests run on 8 virtual CPU devices so sharding/collective tests exercise the
same XLA code path as real chips without hardware (SURVEY.md §4: the
reference spawns real processes per card; virtual host devices replace that).

jax is pre-imported at interpreter startup in this image, so setting
JAX_PLATFORMS/XLA_FLAGS via os.environ in conftest is too late — if the env
is not already correct, re-run pytest in a child process with the right env
(after releasing pytest's fd capture so output flows through).
"""
import os
import signal
import subprocess
import sys

import pytest

_WANT = "--xla_force_host_platform_device_count=8"


def _env_ok():
    return (os.environ.get("_PADDLE_TPU_TEST_REEXEC") == "1"
            or (os.environ.get("JAX_PLATFORMS") == "cpu"
                and _WANT in os.environ.get("XLA_FLAGS", "")
                and not os.environ.get("PALLAS_AXON_POOL_IPS")))


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="include tests marked slow (north-star AOT compiles, "
             "benchmark smokes) — tools/ci.py --full sets this")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow") or os.environ.get(
            "PADDLE_TPU_RUN_SLOW"):
        return
    skip = pytest.mark.skip(
        reason="marked slow: run with --runslow (tools/ci.py --full)")
    for it in items:
        if "slow" in it.keywords:
            it.add_marker(skip)


def _test_limit(item) -> int:
    m = item.get_closest_marker("timeout")
    if m is None:
        return 300
    if m.args:
        return int(m.args[0])
    return int(m.kwargs.get("seconds", 300))


def _alarm_guard(item, phase):
    limit = _test_limit(item)

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"{item.nodeid} {phase} exceeded the {limit}s per-test limit")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(limit)
    return old


def _alarm_clear(old):
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_setup(item):
    """Per-test wall-clock limits cover setup, call, AND teardown
    (reference: per-case TIMEOUT properties in the CMake test driver) —
    one hung test or fixture must not eat the CI budget. Override with
    @pytest.mark.timeout(seconds). SIGALRM-based, so a hang inside a
    non-yielding C call can still block — subprocess-heavy tests also
    carry their own communicate() timeouts."""
    old = _alarm_guard(item, "setup")
    try:
        return (yield)
    finally:
        _alarm_clear(old)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    old = _alarm_guard(item, "call")
    try:
        return (yield)
    finally:
        _alarm_clear(old)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_teardown(item):
    old = _alarm_guard(item, "teardown")
    try:
        return (yield)
    finally:
        _alarm_clear(old)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight test, deselected unless --runslow")
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test wall-clock limit "
                   "(default 300)")
    if _env_ok():
        return
    env = dict(os.environ)
    env["_PADDLE_TPU_TEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + _WANT).strip()
    # the axon sitecustomize registers the TPU backend whenever this var is
    # set, overriding JAX_PLATFORMS=cpu — tests must run on the virtual
    # 8-device CPU mesh
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # Exact fp32 matmuls for numeric checks (prod keeps fast MXU default).
    env.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")
    # Deliberately NO persistent XLA compile cache here: reloading a
    # cached MULTI-DEVICE CPU program segfaulted the ZeRO-3 resume test
    # (measured 2026-08-03 — exactly the cpu_aot_loader hazard
    # paddle_tpu/__init__.py documents). tools/ci.py opts in for its
    # own runs; the raw pytest path stays cache-free and crash-free.
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    ret = subprocess.call([sys.executable, "-m", "pytest"] + sys.argv[1:],
                          env=env)
    os._exit(ret)


# ---------------------------------------------------------------------------
# optional line coverage (tools/ci.py --coverage): stdlib sys.monitoring,
# restricted to paddle_tpu/ — the reference's tools/coverage/ role without
# external packages.
# ---------------------------------------------------------------------------

_COV_TOOL = 3          # sys.monitoring tool id reserved for coverage
_cov_hits = {}


def _cov_enabled():
    return os.environ.get("PADDLE_TPU_COVERAGE") and _env_ok()


def pytest_sessionstart(session):
    if not _cov_enabled():
        return
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu")
    mon = sys.monitoring
    mon.use_tool_id(_COV_TOOL, "paddle_tpu_cov")

    def on_line(code, line):
        fn = code.co_filename
        if fn.startswith(pkg):
            _cov_hits.setdefault(fn, set()).add(line)
            return None
        return mon.DISABLE  # stop monitoring this location

    mon.register_callback(_COV_TOOL, mon.events.LINE, on_line)
    mon.set_events(_COV_TOOL, mon.events.LINE)


def pytest_sessionfinish(session, exitstatus):
    if not _cov_enabled() or not _cov_hits:
        return
    import ast
    sys.monitoring.set_events(_COV_TOOL, 0)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows = []
    tot_hit = tot_all = 0
    for fn in sorted(_cov_hits):
        try:
            tree = ast.parse(open(fn).read())
        except (OSError, SyntaxError):
            continue
        execable = {n.lineno for n in ast.walk(tree)
                    if isinstance(n, ast.stmt)}
        hit = len(_cov_hits[fn] & execable) or len(_cov_hits[fn])
        total = max(len(execable), hit)
        tot_hit += hit
        tot_all += total
        rel = os.path.relpath(fn, root)
        rows.append(f"{rel:60s} {hit:5d}/{total:<5d} "
                    f"{100.0 * hit / total:5.1f}%")
    report = os.path.join(root, "tools", "coverage_report.txt")
    with open(report, "w") as f:
        f.write("\n".join(rows))
        if tot_all:
            f.write(f"\n\nTOTAL {tot_hit}/{tot_all} "
                    f"({100.0 * tot_hit / tot_all:.1f}%)\n")
    print(f"\ncoverage report: {report} "
          f"({100.0 * tot_hit / max(tot_all, 1):.1f}% of touched files)")
