"""paddle.distributed.rpc tests — reference pattern: rpc unittests spawn
real processes (test_rpc_base.py style; no mock agent)."""
import os
import subprocess
import sys
import textwrap

import numpy as np

_WORKER = textwrap.dedent("""
    import sys
    import numpy as np
    import paddle_tpu.distributed.rpc as rpc

    rank = int(sys.argv[1])
    port = sys.argv[2]

    def add(a, b):
        return a + b

    def matmul_np(x, y):
        return np.asarray(x) @ np.asarray(y)

    def whoami():
        return rpc.get_current_worker_info().name

    def boom():
        raise ValueError("boom from callee")

    # all remotely-invoked functions are defined before init_rpc: its
    # barrier guarantees every worker has them before any call arrives
    rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2,
                 master_endpoint=f"127.0.0.1:{port}")

    infos = rpc.get_all_worker_infos()
    assert [i.name for i in infos] == ["worker0", "worker1"], infos

    peer = f"worker{1 - rank}"
    assert rpc.rpc_sync(peer, add, args=(2, 3)) == 5
    fut = rpc.rpc_async(peer, matmul_np,
                        args=(np.eye(4), np.arange(16.).reshape(4, 4)))
    np.testing.assert_allclose(fut.wait(), np.arange(16.).reshape(4, 4))
    assert rpc.rpc_sync(peer, whoami) == peer
    # error propagation
    try:
        rpc.rpc_sync(peer, boom)
    except ValueError as e:
        assert "boom" in str(e)
    else:
        raise AssertionError("exception did not propagate")
    # self-call
    assert rpc.rpc_sync(f"worker{rank}", add, args=(1, 1)) == 2
    rpc.shutdown()
    print("RPC_WORKER_OK", rank)
""")


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_rpc_two_process():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)))
        for r in range(2)]
    try:
        outs = [p.communicate(timeout=180)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"RPC_WORKER_OK {r}" in out


def test_rpc_requires_init():
    import paddle_tpu.distributed.rpc as rpc
    import pytest
    with pytest.raises(RuntimeError, match="not initialized"):
        rpc.rpc_sync("worker0", lambda: None)
