"""First-touch quickstart flows a switching reference user runs.

tests/test_api_surface.py proves the NAMES exist; this file proves the
first code a migrating user writes BEHAVES: the canonical tensor ops,
the define-a-Layer-and-train loop, save/load round-trips, the dataset/
dataloader/hapi path, AMP decorator use, and the deploy hop (jit.save
-> inference predictor). Each block is written the way the reference's
own docs teach the API (guide-level idioms, not this repo's internals).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_tensor_quickstart():
    # the canonical first lines of any reference tutorial
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    y = paddle.ones([2, 2])
    z = paddle.matmul(x, y) + x * 2 - paddle.full([2, 2], 0.5)
    assert z.shape == [2, 2]
    assert float(paddle.sum(z).numpy()) == pytest.approx(
        float((x.numpy() @ y.numpy() + x.numpy() * 2 - 0.5).sum()))
    # reshape/transpose/slice chain
    a = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    b = paddle.transpose(a, [1, 0, 2])[:, :, 1:3]
    assert b.shape == [3, 2, 2]
    # autograd one-liner
    t = paddle.to_tensor(2.0, stop_gradient=False)
    (t * t * 3).backward()
    assert float(t.grad.numpy()) == pytest.approx(12.0)


def test_subclass_layer_train_eval_save_load(tmp_path):
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.drop = nn.Dropout(0.5)
            self.fc2 = nn.Linear(16, 2)

        def forward(self, x):
            return self.fc2(self.drop(F.relu(self.fc1(x))))

    paddle.seed(0)
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = (X.sum(1) > 0).astype("int64")
    model = MLP()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    first = last = None
    for _ in range(30):
        loss = loss_fn(model(paddle.to_tensor(X)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first - 0.1

    # eval() makes dropout deterministic
    model.eval()
    o1 = model(paddle.to_tensor(X)).numpy()
    o2 = model(paddle.to_tensor(X)).numpy()
    np.testing.assert_array_equal(o1, o2)

    # the reference's save/load idiom
    path = str(tmp_path / "mlp.pdparams")
    paddle.save(model.state_dict(), path)
    model2 = MLP()
    model2.set_state_dict(paddle.load(path))
    model2.eval()
    np.testing.assert_allclose(model2(paddle.to_tensor(X)).numpy(), o1,
                               rtol=1e-6)


def test_dataset_dataloader_hapi_fit():
    from paddle_tpu.io import DataLoader, Dataset

    class Spiral(Dataset):
        def __init__(self, n=64):
            rng = np.random.RandomState(1)
            self.x = rng.randn(n, 4).astype("float32")
            self.y = (self.x[:, 0] > 0).astype("int64")

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=net.parameters()),
                  nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy())
    model.fit(Spiral(), epochs=3, batch_size=16, verbose=0)
    ev = model.evaluate(Spiral(), batch_size=16, verbose=0)
    assert ev["acc"] > 0.8
    loader = DataLoader(Spiral(), batch_size=16, shuffle=False)
    xb, yb = next(iter(loader))
    assert list(xb.shape) == [16, 4] and list(yb.shape) == [16]


def test_amp_auto_cast_idiom():
    paddle.seed(2)
    net = nn.Linear(8, 8)
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, 8)
                         .astype("float32"))
    with paddle.amp.auto_cast():
        out = net(x)
    loss = paddle.mean(out)
    loss.backward()
    assert net.weight.grad is not None


def test_deploy_hop_jit_save_to_predictor(tmp_path):
    from paddle_tpu.inference import Config, create_predictor

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(6, 12), nn.GELU(), nn.Linear(12, 3))
    net.eval()
    prefix = str(tmp_path / "deploy")
    paddle.jit.save(net, prefix,
                    input_spec=[paddle.jit.InputSpec([None, 6],
                                                     dtype="float32")])
    assert os.path.exists(prefix + ".pdmodel")
    pred = create_predictor(Config(prefix + ".pdmodel"))
    x = np.random.RandomState(3).randn(2, 6).astype("float32")
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)
