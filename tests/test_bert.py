"""BERT/ERNIE family tests — BASELINE.json config 2 (fine-tune e2e).

Reference patterns: numeric forward check (OpTest style), fine-tune
convergence through TrainStep and hapi Model.fit (book-test style),
attention-mask semantics, MLM loss masking, tp x dp hybrid parity.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import (BertConfig, BertForMaskedLM,
                               BertForSequenceClassification, BertModel,
                               ErnieModel, bert_tiny, ernie_3_tiny)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _np_forward(model, ids, mask=None):
    """Re-derive BertModel's math in numpy (eval mode, no dropout)."""
    cfg = model.cfg
    sd = {k: v.numpy().astype(np.float64) for k, v in
          model.state_dict().items()}
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh

    def ln(x, w, b, eps=cfg.layer_norm_eps):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + eps) * w + b

    B, S = ids.shape
    x = (sd["embeddings.word_embeddings.weight"][ids]
         + sd["embeddings.position_embeddings.weight"][np.arange(S)][None]
         + sd["embeddings.token_type_embeddings.weight"][0][None, None])
    x = ln(x, sd["embeddings.layer_norm.weight"],
           sd["embeddings.layer_norm.bias"])
    for i in range(cfg.num_layers):
        p = f"layer_{i}."
        qkv = x @ sd[p + "attn.qkv.weight"] + sd[p + "attn.qkv.bias"]
        H = cfg.hidden_size
        q = qkv[..., :H].reshape(B, S, nh, hd)
        k = qkv[..., H:2 * H].reshape(B, S, nh, hd)
        v = qkv[..., 2 * H:].reshape(B, S, nh, hd)
        logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        if mask is not None:
            logits = logits + ((mask[:, None, None, :] - 1.0) * 1e30)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        ctx = np.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, H)
        att = ctx @ sd[p + "attn.out_proj.weight"] \
            + sd[p + "attn.out_proj.bias"]
        x = ln(x + att, sd[p + "ln_1.weight"], sd[p + "ln_1.bias"])
        h = x @ sd[p + "fc_in.weight"] + sd[p + "fc_in.bias"]
        if cfg.hidden_act == "relu":
            h = np.maximum(h, 0)
        else:
            from scipy.stats import norm as _n  # pragma: no cover
            h = h * _n.cdf(h)
        y = h @ sd[p + "fc_out.weight"] + sd[p + "fc_out.bias"]
        x = ln(x + y, sd[p + "ln_2.weight"], sd[p + "ln_2.bias"])
    pooled = np.tanh(x[:, 0] @ sd["pooler.dense.weight"]
                     + sd["pooler.dense.bias"])
    return x, pooled


def test_forward_matches_numpy():
    paddle.seed(21)
    cfg = ernie_3_tiny()          # relu FFN: exact numpy re-derivation
    model = ErnieModel(cfg)
    model.eval()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    seq, pooled = model(paddle.to_tensor(ids))
    want_seq, want_pooled = _np_forward(model, ids)
    np.testing.assert_allclose(seq.numpy(), want_seq, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(pooled.numpy(), want_pooled,
                               rtol=2e-4, atol=2e-4)


def test_attention_mask_ignores_padding():
    paddle.seed(22)
    cfg = bert_tiny()
    model = BertModel(cfg)
    model.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (1, 12)).astype("int64")
    mask = np.ones((1, 12), np.int64)
    mask[0, 8:] = 0
    seq1, _ = model(paddle.to_tensor(ids),
                    attention_mask=paddle.to_tensor(mask))
    ids2 = ids.copy()
    ids2[0, 8:] = rng.randint(0, cfg.vocab_size, 4)  # scramble padding
    seq2, _ = model(paddle.to_tensor(ids2),
                    attention_mask=paddle.to_tensor(mask))
    # non-pad positions must not see the scrambled pad tokens
    np.testing.assert_allclose(seq1.numpy()[0, :8], seq2.numpy()[0, :8],
                               rtol=1e-5, atol=1e-5)


def test_finetune_convergence():
    paddle.seed(23)
    cfg = bert_tiny()
    model = BertForSequenceClassification(cfg, num_classes=2)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    from paddle_tpu.jit import TrainStep
    step = TrainStep(model, BertForSequenceClassification.loss_fn, opt)
    rng = np.random.RandomState(2)
    ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype("int64")
    labels = (ids[:, 0] % 2).astype("int64")   # learnable from input
    x, y = paddle.to_tensor(ids), paddle.to_tensor(labels)
    losses = [float(step(x, y)) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.2, losses


def test_mlm_loss_masks_ignore_index():
    paddle.seed(24)
    cfg = bert_tiny()
    model = BertForMaskedLM(cfg)
    model.eval()
    ids = np.random.RandomState(3).randint(
        0, cfg.vocab_size, (2, 8)).astype("int64")
    logits = model(paddle.to_tensor(ids))
    labels = np.full((2, 8), -100, np.int64)
    labels[0, 2] = ids[0, 2]
    loss = BertForMaskedLM.loss_fn(logits, paddle.to_tensor(labels))
    # loss over exactly one position == CE at that position
    lg = logits.numpy()[0, 2].astype(np.float64)
    p = np.exp(lg - lg.max())
    p /= p.sum()
    want = -np.log(p[ids[0, 2]])
    np.testing.assert_allclose(float(loss), want, rtol=1e-4)


def test_tp_dp_hybrid_matches_single():
    ids = np.random.RandomState(4).randint(0, 512, (4, 16)).astype("int64")
    labels = (ids[:, 0] % 2).astype("int64")

    def run(degrees):
        dist.set_mesh(None)
        if degrees:
            dist.init_mesh(degrees)
        paddle.seed(25)
        model = BertForSequenceClassification(bert_tiny(), num_classes=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        if degrees:
            step = dist.ParallelTrainStep(
                model, BertForSequenceClassification.loss_fn, opt)
        else:
            from paddle_tpu.jit import TrainStep
            step = TrainStep(model,
                             BertForSequenceClassification.loss_fn, opt)
        x, y = paddle.to_tensor(ids), paddle.to_tensor(labels)
        return [float(step(x, y)) for _ in range(3)]

    single = run(None)
    hybrid = run({"dp": 2, "mp": 2})
    np.testing.assert_allclose(single, hybrid, rtol=2e-4, atol=2e-4)


def test_hapi_model_fit_bert():
    """Config 2's e2e shape: fine-tune through the high-level API."""
    paddle.seed(26)
    cfg = bert_tiny()
    net = BertForSequenceClassification(cfg, num_classes=2)
    model = paddle.Model(net)
    rng = np.random.RandomState(5)
    ids = rng.randint(0, cfg.vocab_size, (32, 16)).astype("int64")
    labels = (ids[:, 0] % 2).astype("int64")

    import paddle_tpu.nn as nn
    model.prepare(
        optimizer=paddle.optimizer.AdamW(
            learning_rate=1e-3, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())

    class DS(paddle.io.Dataset):
        def __len__(self):
            return len(ids)

        def __getitem__(self, i):
            return ids[i], labels[i]

    hist = model.fit(DS(), epochs=2, batch_size=8, verbose=0)
    res = model.evaluate(DS(), batch_size=8, verbose=0)
    assert res["acc"] >= 0.5
