"""Autograd tape tests (parity: eager backward semantics,
paddle/fluid/eager/backward.cc + test patterns from unittests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_backward_simple():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_backward_chain_and_accumulate():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    a = x * 2
    b = a + x          # x used twice -> grads accumulate
    loss = b.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])
    # second backward accumulates into .grad (paddle semantics)
    loss2 = (x * x).sum()
    loss2.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 7.0])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    loss = (x * y).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = y.detach() * x
    z.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only direct path


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5
    assert y.stop_gradient
    assert y._node is None


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_grad_unused_input():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        paddle.grad(y, [z])
    gx, gz = paddle.grad(y, [x, z], allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    loss = parts[0].sum() * 3 + parts[2].sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[3, 0, 1], [3, 0, 1]])


def test_backward_nonscalar_requires_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    x.clear_grad()
    assert x.grad is None


def test_functional_jacobian_hessian():
    def f(x):
        return (x * x).sum()

    x = paddle.to_tensor([1.0, 2.0, 3.0])
    jac = paddle.autograd.jacobian(f, x)
    np.testing.assert_allclose(jac.numpy(), [2.0, 4.0, 6.0])
    hes = paddle.autograd.hessian(f, x)
    np.testing.assert_allclose(hes.numpy(), 2 * np.eye(3), atol=1e-6)


def test_vjp_jvp():
    def f(x):
        return x * x

    x = paddle.to_tensor([3.0])
    out, g = paddle.autograd.vjp(f, x)
    np.testing.assert_allclose(g.numpy(), [6.0])
    out, tang = paddle.autograd.jvp(f, x)
    np.testing.assert_allclose(tang.numpy(), [6.0])


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, gy):
            return gy * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(y.numpy(), [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_higher_path_through_graph():
    # diamond dependency
    x = paddle.to_tensor([1.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    loss = (a * b).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_inplace_ops_chain_gradients():
    """Inplace variants must keep the autograd chain: x._replace_(f(x))
    was a self-referential edge that silently dropped upstream grads
    (round-4 fix: snapshot semantics in Tensor._inplace_)."""
    x = paddle.to_tensor(np.array([4.0], np.float32), stop_gradient=False)
    y = x * 3.0
    y.sqrt_()                      # y = sqrt(3x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               3.0 / (2 * np.sqrt(12.0)), rtol=1e-6)

    z = paddle.to_tensor(np.array([-1.0, 2.0], np.float32),
                         stop_gradient=False)
    h = z * 2.0
    import paddle_tpu.nn.functional as F
    F.relu_(h)
    h.sum().backward()
    np.testing.assert_allclose(z.grad.numpy(), [0.0, 2.0])
