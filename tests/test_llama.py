"""LLaMA family tests — BASELINE.json config 4 (TP+PP hybrid).

Patterns from the reference suite: forward numerics vs a numpy re-derivation
(OpTest style), single-device convergence (book-test style), and hybrid
tp x dp / pp parallel steps on the virtual mesh
(hybrid_parallel_mp_layers.py / hybrid_parallel_pp_transformer.py roles).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                               LlamaPipelineForCausalLM, llama_tiny)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _np_rope(x, theta=10000.0):
    B, S, H, D = x.shape
    half = D // 2
    freqs = theta ** (-np.arange(half, dtype=np.float32) / half)
    ang = np.arange(S, dtype=np.float32)[:, None] * freqs[None, :]
    cos, sin = np.cos(ang)[None, :, None, :], np.sin(ang)[None, :, None, :]
    x0, x1 = x[..., 0::2], x[..., 1::2]
    out = np.empty_like(x)
    out[..., 0::2] = x0 * cos - x1 * sin
    out[..., 1::2] = x1 * cos + x0 * sin
    return out


def _np_forward(model, ids):
    """Re-derive LlamaForCausalLM's math in numpy."""
    cfg = model.cfg
    sd = {k: v.numpy().astype(np.float64) for k, v in
          model.state_dict().items()}
    nh, nkv = cfg.num_heads, cfg.kv_heads
    hd = cfg.hidden_size // nh

    def rms(x, w, eps=cfg.rms_eps):
        var = np.mean(x * x, axis=-1, keepdims=True)
        return x / np.sqrt(var + eps) * w

    x = sd["llama.embed_tokens.weight"][ids]
    B, S, _ = x.shape
    for i in range(cfg.num_layers):
        p = f"llama.block_{i}."
        h = rms(x, sd[p + "input_layernorm.weight"])
        q = (h @ sd[p + "self_attn.q_proj.weight"]).reshape(B, S, nh, hd)
        k = (h @ sd[p + "self_attn.k_proj.weight"]).reshape(B, S, nkv, hd)
        v = (h @ sd[p + "self_attn.v_proj.weight"]).reshape(B, S, nkv, hd)
        q = _np_rope(q.astype(np.float32)).astype(np.float64)
        k = _np_rope(k.astype(np.float32)).astype(np.float64)
        rep = nh // nkv
        k = np.repeat(k, rep, axis=2)
        v = np.repeat(v, rep, axis=2)
        logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        mask = np.tril(np.ones((S, S), bool))
        logits = np.where(mask[None, None], logits, -1e30)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        ctx = np.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, nh * hd)
        x = x + ctx @ sd[p + "self_attn.o_proj.weight"]
        h = rms(x, sd[p + "post_attention_layernorm.weight"])
        g = h @ sd[p + "mlp.gate_proj.weight"]
        u = h @ sd[p + "mlp.up_proj.weight"]
        silu = g / (1.0 + np.exp(-g))
        x = x + (silu * u) @ sd[p + "mlp.down_proj.weight"]
    x = rms(x, sd["llama.norm.weight"])
    return x @ sd["lm_head.weight"]


def test_forward_matches_numpy():
    paddle.seed(11)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    got = model(paddle.to_tensor(ids)).numpy()
    want = _np_forward(model, ids)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gqa_head_counts():
    cfg = llama_tiny()
    assert cfg.kv_heads == 2 and cfg.num_heads == 4
    model = LlamaForCausalLM(cfg)
    kw = model.llama.blocks[0].self_attn.k_proj.weight
    qw = model.llama.blocks[0].self_attn.q_proj.weight
    assert kw.shape[1] * 2 == qw.shape[1]


def test_single_device_convergence():
    paddle.seed(3)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    from paddle_tpu.jit import TrainStep
    step = TrainStep(model, LlamaForCausalLM.loss_fn, opt)
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (4, 32))
        .astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert losses[-1] < losses[0] - 0.5, losses


def test_tp_dp_parallel_step_matches_single():
    ids = np.random.RandomState(2).randint(0, 256, (4, 32)).astype("int64")

    def run(degrees):
        dist.set_mesh(None)
        if degrees:
            dist.init_mesh(degrees)
        paddle.seed(5)
        model = LlamaForCausalLM(llama_tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        if degrees:
            step = dist.ParallelTrainStep(
                model, LlamaForCausalLM.loss_fn, opt, zero_stage=1)
        else:
            from paddle_tpu.jit import TrainStep
            step = TrainStep(model, LlamaForCausalLM.loss_fn, opt)
        x = paddle.to_tensor(ids)
        return [float(step(x, x)) for _ in range(3)]

    single = run(None)
    hybrid = run({"dp": 2, "mp": 2})
    np.testing.assert_allclose(single, hybrid, rtol=2e-4, atol=2e-4)


def test_pipeline_llama_runs():
    dist.init_mesh({"pp": 4})
    paddle.seed(9)
    cfg = llama_tiny()
    model = LlamaPipelineForCausalLM(cfg, num_stages=4, num_micro=8)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = dist.ParallelTrainStep(model, LlamaForCausalLM.loss_fn, opt)
    ids = paddle.to_tensor(
        np.random.RandomState(4).randint(0, cfg.vocab_size, (8, 32))
        .astype("int64"))
    losses = [float(step(ids, ids)) for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
