"""Long-tail parity batch (VERDICT item 9): geometric message passing,
Auc metric, viterbi decode, audio features, text datasets, spawn."""
import gzip
import os
import struct
import wave

import numpy as np
import pytest

import paddle_tpu as paddle


class TestGeometric:
    def test_segment_ops(self):
        import paddle_tpu.geometric as G
        data = paddle.to_tensor(
            np.array([[1., 2., 3.], [3., 2., 1.], [4., 5., 6.]],
                     np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1], np.int64))
        np.testing.assert_allclose(
            G.segment_sum(data, ids).numpy(),
            [[4, 4, 4], [4, 5, 6]])
        np.testing.assert_allclose(
            G.segment_mean(data, ids).numpy(),
            [[2, 2, 2], [4, 5, 6]])
        np.testing.assert_allclose(
            G.segment_min(data, ids).numpy(),
            [[1, 2, 1], [4, 5, 6]])
        np.testing.assert_allclose(
            G.segment_max(data, ids).numpy(),
            [[3, 2, 3], [4, 5, 6]])

    def test_segment_empty_segment_zero(self):
        import paddle_tpu.geometric as G
        data = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
        ids = paddle.to_tensor(np.array([0, 2], np.int64))
        out = G.segment_max(data, ids).numpy()
        assert out[1, 0] == 0  # empty segment -> 0, not -inf

    def test_send_u_recv_reference_example(self):
        import paddle_tpu.geometric as G
        x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                      np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int64))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int64))
        out = G.send_u_recv(x, src, dst, "sum").numpy()
        np.testing.assert_allclose(out, [[0, 2, 3], [2, 8, 10],
                                         [1, 4, 5]])

    def test_send_ue_recv_and_uv(self):
        import paddle_tpu.geometric as G
        x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                      np.float32))
        y = paddle.to_tensor(np.array([1., 1., 1., 1.], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int64))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int64))
        out = G.send_ue_recv(x, y, src, dst, "add", "sum").numpy()
        np.testing.assert_allclose(out, [[1, 3, 4], [4, 10, 12],
                                         [2, 5, 6]])
        uv = G.send_uv(x, x, src, dst, "add").numpy()
        np.testing.assert_allclose(uv[0], x.numpy()[0] + x.numpy()[1])

    def test_send_u_recv_grad(self):
        import paddle_tpu.geometric as G
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        x.stop_gradient = False
        src = paddle.to_tensor(np.array([0, 1], np.int64))
        dst = paddle.to_tensor(np.array([1, 1], np.int64))
        G.send_u_recv(x, src, dst).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1, 1], [1, 1], [0, 0]])

    def test_out_size(self):
        import paddle_tpu.geometric as G
        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        src = paddle.to_tensor(np.array([0], np.int64))
        dst = paddle.to_tensor(np.array([0], np.int64))
        assert G.send_u_recv(x, src, dst, out_size=5).shape == [5, 2]


class TestAuc:
    def test_matches_manual(self):
        from paddle_tpu.metric import Auc
        rng = np.random.RandomState(0)
        y = rng.randint(0, 2, 1000)
        s = np.clip(rng.rand(1000) * 0.6 + y * 0.3, 0, 0.999)
        m = Auc()
        # feed in two batches (streaming)
        for sl in (slice(0, 500), slice(500, 1000)):
            m.update(np.stack([1 - s[sl], s[sl]], 1), y[sl].reshape(-1, 1))
        # exact AUC via rank statistic
        order = np.argsort(s)
        ranks = np.empty(len(s))
        ranks[order] = np.arange(1, len(s) + 1)
        n_pos, n_neg = y.sum(), (1 - y).sum()
        exact = (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) \
            / (n_pos * n_neg)
        assert abs(m.accumulate() - exact) < 2e-3

    def test_all_one_class_is_zero(self):
        from paddle_tpu.metric import Auc
        m = Auc()
        m.update(np.array([[0.3, 0.7]]), np.array([[1]]))
        assert m.accumulate() == 0.0

    def test_reset(self):
        from paddle_tpu.metric import Auc
        m = Auc()
        m.update(np.array([[0.3, 0.7], [0.6, 0.4]]),
                 np.array([[1], [0]]))
        assert m.accumulate() > 0
        m.reset()
        assert m.accumulate() == 0.0

    def test_distributed_auc_single_process(self):
        from paddle_tpu.distributed import DistributedAuc
        m = DistributedAuc()
        m.update(np.array([[0.2, 0.8], [0.9, 0.1]]), np.array([[1], [0]]))
        assert m.accumulate() == 1.0


class TestViterbi:
    def test_layer(self):
        from paddle_tpu.text import ViterbiDecoder
        rng = np.random.RandomState(3)
        trans = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
        dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
        pot = paddle.to_tensor(rng.randn(2, 6, 4).astype(np.float32))
        lens = paddle.to_tensor(np.array([6, 4], np.int64))
        scores, path = dec(pot, lens)
        assert scores.shape == [2]
        assert path.shape == [2, 6]
        assert (path.numpy()[1, 4:] == 0).all()  # masked beyond length

    def test_greedy_on_diag_dominant(self):
        # with zero transitions the viterbi path is the per-step argmax
        from paddle_tpu.text import viterbi_decode
        rng = np.random.RandomState(4)
        pot = rng.randn(2, 5, 3).astype(np.float32)
        scores, path = viterbi_decode(
            paddle.to_tensor(pot),
            paddle.to_tensor(np.zeros((3, 3), np.float32)),
            paddle.to_tensor(np.array([5, 5], np.int64)), False)
        np.testing.assert_array_equal(path.numpy(), pot.argmax(-1))
        np.testing.assert_allclose(scores.numpy(), pot.max(-1).sum(-1),
                                   rtol=1e-5)


class TestAudio:
    def test_mel_roundtrip(self):
        from paddle_tpu.audio import functional as AF
        for htk in (False, True):
            hz = AF.mel_to_hz(AF.hz_to_mel(440.0, htk), htk)
            assert abs(hz - 440.0) < 1e-3

    def test_fbank_shape_and_norm(self):
        from paddle_tpu.audio import functional as AF
        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert fb.min() >= 0
        assert (fb.sum(1) > 0).all()

    def test_spectrogram_parseval(self):
        from paddle_tpu.audio.features import Spectrogram
        rng = np.random.RandomState(5)
        wav = paddle.to_tensor(rng.randn(1, 4000).astype(np.float32))
        spec = Spectrogram(n_fft=256, hop_length=128)(wav)
        assert spec.shape == [1, 129, 4000 // 128 + 1]
        assert float(spec.numpy().min()) >= 0

    def test_mfcc_shapes(self):
        from paddle_tpu.audio.features import (LogMelSpectrogram,
                                               MelSpectrogram, MFCC)
        rng = np.random.RandomState(6)
        wav = paddle.to_tensor(rng.randn(2, 8000).astype(np.float32))
        mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=32)(wav)
        assert mel.shape[0:2] == [2, 32]
        logmel = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=32)(wav)
        assert logmel.shape == mel.shape
        mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=32)(wav)
        assert mfcc.shape[0:2] == [2, 13]

    def test_wav_io_roundtrip(self, tmp_path):
        import paddle_tpu.audio as A
        rng = np.random.RandomState(7)
        wav = (rng.rand(1, 1600).astype(np.float32) - 0.5) * 0.9
        path = str(tmp_path / "t.wav")
        A.save(path, paddle.to_tensor(wav), 16000)
        meta = A.info(path)
        assert meta.sample_rate == 16000
        assert meta.num_samples == 1600
        back, sr = A.load(path)
        assert sr == 16000
        np.testing.assert_allclose(back.numpy(), wav, atol=1e-3)


class TestTextDatasets:
    def test_uci_housing(self, tmp_path):
        rng = np.random.RandomState(8)
        raw = rng.rand(50, 14).astype(np.float32)
        path = str(tmp_path / "housing.data")
        np.savetxt(path, raw)
        from paddle_tpu.text.datasets import UCIHousing
        tr = UCIHousing(data_file=path, mode="train")
        te = UCIHousing(data_file=path, mode="test")
        assert len(tr) == 40 and len(te) == 10
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_no_download_raises(self):
        from paddle_tpu.text.datasets import Imdb, UCIHousing
        with pytest.raises(RuntimeError, match="no network egress"):
            UCIHousing()
        with pytest.raises(RuntimeError, match="no network egress"):
            Imdb()


class TestSpawn:
    def test_spawn_runs_and_sets_env(self, tmp_path):
        from paddle_tpu.distributed import spawn
        out = str(tmp_path)
        ctx = spawn(_spawn_probe, args=(out,), nprocs=2, join=True)
        assert all(p.exitcode == 0 for p in ctx.processes)
        got = sorted(os.listdir(out))
        assert got == ["rank0.txt", "rank1.txt"]
        for i, fn in enumerate(got):
            rank, world = open(os.path.join(out, fn)).read().split(",")
            assert int(rank) == i and int(world) == 2

    def test_spawn_propagates_failure(self):
        from paddle_tpu.distributed import spawn
        with pytest.raises(RuntimeError, match="failed"):
            spawn(_spawn_fail, nprocs=2, join=True)


def _spawn_probe(out_dir):
    # runs in a fresh interpreter: no jax import needed
    import os
    rank = os.environ["PADDLE_TRAINER_ID"]
    world = os.environ["PADDLE_TRAINERS_NUM"]
    with open(os.path.join(out_dir, f"rank{rank}.txt"), "w") as f:
        f.write(f"{rank},{world}")


def _spawn_fail():
    raise SystemExit(3)
