"""Quantized + overlapped ZeRO collectives in ParallelTrainStep
(ISSUE 17): the fp32 knob stays bitwise with the implicit-GSPMD
baseline (per-step AND scan_steps), bf16/int8 trajectories stay inside
the documented drift bounds, knob flips never recompile an
already-built program, the stage-3 chunked weight-gather leaves its
optimization_barrier chain in the lowered text (and an interleaved —
not front-loaded — compiled schedule), optimizer math stays sharded
(no replicated update, arXiv 2004.13336), and the ctor rejects the
geometries the quantized path cannot serve.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

# same bounds tools/bench_collectives.py gates the 64-device A/B on
DRIFT_BOUNDS = {"bf16": 5e-3, "int8": 2e-2}


@pytest.fixture(autouse=True)
def fresh_mesh(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_COMM_PRECISION", raising=False)
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _mesh22():
    import jax
    dist.init_mesh({"dp": 2, "sharding": 2}, devices=jax.devices()[:4])


def _net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 16))


def _opt(m):
    return paddle.optimizer.AdamW(learning_rate=0.05,
                                  parameters=m.parameters())


def _loss(o, y):
    return F.mse_loss(o, y)


def _batch():
    rng = np.random.RandomState(0)
    return rng.randn(8, 16).astype("float32")


def _make_step(prec, stage=3):
    paddle.seed(5)
    m = _net()
    kw = {} if prec is None else {"comm_precision": prec}
    return dist.ParallelTrainStep(m, _loss, _opt(m), zero_stage=stage,
                                  **kw)


def _run(prec, steps=4):
    step = _make_step(prec)
    x = _batch()
    return [float(step(x, x)) for _ in range(steps)], step


def _params_bitwise(a, b):
    return all(np.array_equal(np.asarray(a.params[n]),
                              np.asarray(b.params[n])) for n in a.params)


def _maxrel(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return float(np.max(np.abs(a - b) / np.maximum(np.abs(a), 1e-8)))


# ---------------------------------------------------------------------------
# fp32 knob: bitwise with the implicit-GSPMD baseline
# ---------------------------------------------------------------------------

def test_fp32_knob_bitwise_per_step():
    """comm_precision='fp32' must keep the implicit GSPMD collectives:
    identical losses AND identical final params, to the last ulp."""
    _mesh22()
    base_losses, base = _run(None)
    knob_losses, knob = _run("fp32")
    assert np.array_equal(np.asarray(base_losses),
                          np.asarray(knob_losses))
    assert _params_bitwise(base, knob)


def test_fp32_knob_bitwise_scan():
    """The fused K-step window at comm_precision='fp32' reproduces the
    default per-step trajectory bitwise (the scan path threads the knob
    through _scan_progs)."""
    _mesh22()
    seq_losses, seq = _run(None, steps=4)
    scan_step = _make_step("fp32")
    x = _batch()
    stacked = np.stack([x] * 4)
    scan_losses = np.asarray(
        scan_step.scan_steps(4, stacked, stacked).value).tolist()
    assert np.array_equal(np.asarray(seq_losses),
                          np.asarray(scan_losses))
    assert _params_bitwise(seq, scan_step)


# ---------------------------------------------------------------------------
# bf16 / int8: bounded trajectory drift
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prec", ["bf16", "int8"])
def test_quantized_trajectory_drift_bounded(prec):
    _mesh22()
    ref_losses, _ = _run(None)
    q_losses, _ = _run(prec)
    drift = _maxrel(ref_losses, q_losses)
    assert drift <= DRIFT_BOUNDS[prec], (prec, drift, ref_losses,
                                         q_losses)
    # and the run is actually training, not collapsing to noise
    assert q_losses[-1] < q_losses[0]


# ---------------------------------------------------------------------------
# knob flips: programs cached per precision, zero recompiles
# ---------------------------------------------------------------------------

def test_zero_recompile_knob_flips():
    _mesh22()
    step = _make_step("int8")
    x = _batch()
    step(x, x)
    assert step._trace_count == 1
    step.set_comm_precision("bf16")
    step(x, x)
    assert step._trace_count == 2          # first bf16 step compiles
    step.set_comm_precision("int8")
    step(x, x)
    assert step._trace_count == 2          # cached: NO retrace
    step.set_comm_precision("bf16")
    step(x, x)
    assert step._trace_count == 2          # cached both ways


# ---------------------------------------------------------------------------
# stage-3 chunked gather/compute overlap: lowered chain + schedule
# ---------------------------------------------------------------------------

def test_stage3_gather_chain_and_schedule():
    """GPT-tiny (real per-layer structure) at int8: the lowered text
    carries the optimization_barrier gather chain (one link per
    gathered leaf group), the compiled schedule interleaves gathers
    with compute rather than front-loading them, and the differ
    refuses unscheduled text. The fp32 lowering of the same step has
    no chain (lower only — no second compile)."""
    import jax.numpy as jnp
    from paddle_tpu.analysis.collective_schedule import (
        gather_chain_links, gather_overlap_report, schedule_events)
    from paddle_tpu.compilation.sites import (_gpt_tiny_model,
                                              _train_step_parts)

    def lower(prec):
        dist.set_mesh(None)
        _mesh22()
        model = _gpt_tiny_model()
        loss_fn, opt, _rng = _train_step_parts(model)
        step = dist.ParallelTrainStep(model, loss_fn, opt, zero_stage=3,
                                      comm_precision=prec)
        ids = np.zeros((4, 32), np.int64)
        step._build((ids, ids))
        args = (step.params, step.buffers, step.opt_state,
                jnp.asarray(1e-3, jnp.float32),
                jnp.asarray(1, jnp.float32),
                _rng.default_generator().fold_in(1), ids, ids)
        return step._jitted.lower(*args)

    lowered = lower("int8")
    links = gather_chain_links(lowered.as_text())
    assert links > 0, "no gather chain in the int8 stage-3 lowering"
    # the differ must refuse pre-scheduling text outright
    with pytest.raises(ValueError):
        schedule_events(lowered.as_text())
    rep = gather_overlap_report(lowered.compile().as_text())
    assert rep["n_gathers"] >= 1 and rep["n_compute"] >= 1
    assert not rep["front_loaded"], rep
    assert rep["interleaved_gaps"] >= 1, rep
    # fp32 keeps the implicit GSPMD gathers: no explicit chain
    assert gather_chain_links(lower("fp32").as_text()) == 0


# ---------------------------------------------------------------------------
# no replicated optimizer math (arXiv 2004.13336)
# ---------------------------------------------------------------------------

def test_optimizer_state_stays_sharded():
    """Every non-scalar optimizer slot (and every stage-3 param) lives
    1/G-sharded over the zero axis — a device holding a full copy would
    mean the update math was replicated."""
    import jax
    _mesh22()
    step = _make_step("int8")
    x = _batch()
    step(x, x)                              # one real update
    G = 2                                   # zero axis: sharding=2
    for name, arr in step.params.items():
        assert arr.addressable_shards[0].data.size * G == arr.size, name
    checked = 0
    for pname, slots in step.opt_state.items():
        for leaf in jax.tree_util.tree_leaves(slots):
            if leaf.ndim >= 1 and leaf.size > 1 \
                    and leaf.shape[0] % G == 0:
                assert (leaf.addressable_shards[0].data.size * G
                        == leaf.size), pname
                checked += 1
    assert checked >= 4                     # both weights + both biases


# ---------------------------------------------------------------------------
# geometry validation
# ---------------------------------------------------------------------------

def test_ctor_and_knob_validation():
    import jax
    _mesh22()
    with pytest.raises(ValueError):
        _make_step("fp8")                   # unknown precision
    with pytest.raises(ValueError):
        _make_step("int8", stage=1)         # no grad RS to quantize
    step = _make_step("fp32")
    with pytest.raises(ValueError):
        step.set_comm_precision("fp16")
    # hybrid mesh: quantized fwd/bwd cannot carry mp collectives
    dist.set_mesh(None)
    dist.init_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    with pytest.raises(ValueError):
        _make_step("int8", stage=2)
