"""TPU (Mosaic) lowering checks for the Pallas kernels — no hardware.

VERDICT r3 weak #3: interpret mode proves numerics, not lowering —
Mosaic rejects layouts the interpreter accepts (this caught a real one:
a rank-3 [.., bq] LSE block spec violates the (8,128) tiling rule; the
kernel now lane-broadcasts residuals to [.., bq, 128] like the library
TPU flash kernel's l/m). These tests cross-lower the kernels for
platform "tpu" via jax.export on the CPU host, which runs the full
Pallas->Mosaic MLIR pipeline and embeds the serialized Mosaic payload
as a tpu_custom_call; backend codegen happens on the real chip.
"""
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.kernels.flash_block import flash_block_attention


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def _tpu_mlir(fn, *args):
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(
        *args).mlir_module()


def test_flash_fwd_lowers_for_tpu():
    q = jnp.zeros((1, 4, 256, 64), jnp.bfloat16)

    def f(q, k, v):
        return flash_block_attention(q, k, v, 0, 0, causal=True,
                                     sm_scale=0.125)

    mlir = _tpu_mlir(f, q, q, q)
    assert mlir.count("tpu_custom_call") == 1


def test_flash_bwd_lowers_for_tpu():
    q = jnp.zeros((1, 4, 256, 64), jnp.bfloat16)

    def loss(q, k, v):
        o, lse = flash_block_attention(q, k, v, 0, 0, True, 0.125,
                                       128, 128, False)
        return o.astype(jnp.float32).sum() + lse.sum()

    mlir = _tpu_mlir(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    # fwd + dkv + dq kernels
    assert mlir.count("tpu_custom_call") == 3


def test_fused_ring_lowers_for_tpu():
    import paddle_tpu.distributed.sequence_parallel as sp
    dist.init_mesh({"sp": 8})
    mesh = dist.get_mesh()
    q = jnp.zeros((1, 1024, 8, 64), jnp.bfloat16)
    # the exact program the TPU dispatch builds: fused=True,
    # interpret=False (what backend in ("tpu","axon") selects)
    prog = sp._ring_program(mesh, 8, 0.125, True, 128, True, False)
    mlir = _tpu_mlir(prog, q, q, q)
    assert mlir.count("tpu_custom_call") >= 1      # Pallas kernel fires
    assert mlir.count("collective_permute") >= 2   # the k/v rotation ring


def _export_train_step_for_tpu(step, batch=(2, 256)):
    """Cross-lower a built TrainStep's whole donated program for the TPU
    target (the one export recipe both bench-shaped gates share)."""
    import paddle_tpu.framework.random as _rng
    step._build()
    aval = lambda t: jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
    scalar = jax.ShapeDtypeStruct((), jnp.float32)
    key = jax.eval_shape(lambda: _rng.default_generator().fold_in(1))
    ids = jax.ShapeDtypeStruct(batch, jnp.int64)
    return jax.export.export(step._jitted, platforms=["tpu"])(
        aval(step.params), aval(step.buffers), aval(step.opt_state),
        scalar, scalar, key, ids, ids)


def test_gpt_train_step_with_pallas_attention_lowers_for_tpu(monkeypatch):
    """The exact bench path: full donated GPT train step with the library
    pallas flash attention (dispatch forced as on a real TPU backend),
    cross-lowered for the TPU target — fwd + dq + dkv Mosaic payloads."""
    import importlib
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    fa = importlib.import_module("paddle_tpu.nn.functional.flash_attention")
    monkeypatch.setattr(fa, "_on_tpu", lambda: True)

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=2,
                    num_heads=4, max_seq_len=256)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                                 parameters=model.parameters())
    step = TrainStep(model, GPTForCausalLM.loss_fn, opt)
    exp = _export_train_step_for_tpu(step)
    assert exp.mlir_module().count("tpu_custom_call") == 3
    assert fa.last_attention_dispatch()["backend"] == "pallas"


@pytest.mark.parametrize("policy", ["full", "dots"])
def test_gpt_1p3b_shaped_step_lowers_for_tpu(monkeypatch, policy):
    """The exact gpt1.3b bench composition (bench.py PADDLE_TPU_BENCH_
    MODEL=gpt1.3b) at tiny geometry: scan-over-layers + per-block remat
    (both recompute_policy values) + fused linear-CE + pure-bf16 Adam,
    with pallas attention dispatch forced — cross-lowered for the TPU
    target so a Mosaic/lowering blocker is caught HERE, not an hour
    into the remote-compile slot (r4 lost its 1.3B run to compile)."""
    import importlib
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    fa = importlib.import_module("paddle_tpu.nn.functional.flash_attention")
    monkeypatch.setattr(fa, "_on_tpu", lambda: True)

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=3,
                    num_heads=4, max_seq_len=256, scan_layers=True,
                    recompute=True, recompute_policy=policy,
                    fused_loss_chunk=64)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 multi_precision=False,  # 1.3b bench mode
                                 parameters=model.parameters())
    step = TrainStep(model, model.make_loss_fn(), opt)
    exp = _export_train_step_for_tpu(step)
    # scan body compiles ONCE (depth-independent): fwd + dq + dkv, plus
    # the remat'd bwd replaying the fwd kernel = 4 Mosaic payloads
    assert exp.mlir_module().count("tpu_custom_call") == 4
    assert fa.last_attention_dispatch()["backend"] == "pallas"
