"""Pipeline-parallel tests (reference: hybrid_parallel_pp_transformer.py /
test_parallel_dygraph_pipeline_parallel.py — forward parity + convergence)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.meta_parallel import (LayerDesc, PipelineLayer,
                                                  PipelineParallel)


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return x + F.tanh(self.fc(x))


def _build_pipeline(d=8, nblocks=4, num_stages=1):
    paddle.seed(7)
    return PipelineLayer(
        layers=[LayerDesc(nn.Linear, d, d)]
        + [LayerDesc(Block, d) for _ in range(nblocks)]
        + [LayerDesc(nn.Linear, d, d)],
        num_stages=num_stages,
        loss_fn=lambda o, y: F.mse_loss(o, y))


def _sequential_ref(model, x_np):
    """Recompute the pipeline model's math with plain numpy."""
    h = x_np @ model.pre_0.weight.numpy() + model.pre_0.bias.numpy()
    sd = model.state_dict()
    w = sd["blocks__fc__weight"].numpy()   # [L, d, d]
    b = sd["blocks__fc__bias"].numpy()     # [L, d]
    for i in range(w.shape[0]):
        h = h + np.tanh(h @ w[i] + b[i])
    return h @ model.post_0.weight.numpy() + model.post_0.bias.numpy()


class MoEBlock(nn.Layer):
    """Transformer-ish block with an MoE FFN — the MoE+PP composition
    (reference: moe_layer.py:261 under hybrid topology)."""

    def __init__(self, d):
        super().__init__()
        self.moe = dist.MoELayer(d, 2 * d, num_experts=4, gate="switch",
                                 capacity_factor=4.0)

    def forward(self, x):
        return x + self.moe(x)


def test_moe_inside_pipeline_aux_loss_trains():
    """MoE blocks pipelined over pp=4: the load-balancing aux loss must be
    collected from inside the schedule (not dropped — r2 limitation) and
    move under training."""
    dist.init_mesh({"pp": 4})
    paddle.seed(3)
    d = 8
    model = PipelineLayer(
        layers=[LayerDesc(nn.Linear, d, d)]
        + [LayerDesc(MoEBlock, d) for _ in range(4)]
        + [LayerDesc(nn.Linear, d, d)],
        num_stages=4, num_micro=4,
        loss_fn=lambda o, y: F.mse_loss(o, y))
    pp = PipelineParallel(model)
    opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16, d).astype("float32"))
    y = paddle.to_tensor(rng.randn(8, 16, d).astype("float32"))

    auxes, losses = [], []
    for _ in range(6):
        loss = pp.train_batch((x, y), opt)
        aux = model._template._last_pipeline_aux
        assert isinstance(aux, paddle.Tensor)
        auxes.append(float(aux))
        losses.append(float(loss))
    # aux loss is real (positive — switch balance loss >= 1/E * weight)
    assert auxes[0] > 0.0
    # and it MOVES: training with the balance term changes the router
    assert any(abs(a - auxes[0]) > 1e-7 for a in auxes[1:]), auxes
    assert losses[-1] < losses[0], losses


def test_moe_pipeline_aux_matches_unpipelined():
    """The pipelined aux total equals the same blocks applied sequentially
    (validity masking must exclude ramp-up/drain filler ticks)."""
    d = 8
    rng = np.random.RandomState(1)
    x_np = rng.randn(8, 16, d).astype("float32")

    def build(num_stages):
        paddle.seed(11)
        return PipelineLayer(
            layers=[LayerDesc(MoEBlock, d) for _ in range(4)],
            num_stages=num_stages, num_micro=4,
            loss_fn=lambda o, y: F.mse_loss(o, y))

    dist.init_mesh({"pp": 4})
    m_pp = build(4)
    out_pp = m_pp(paddle.to_tensor(x_np))
    aux_pp = float(m_pp._template._last_pipeline_aux)

    dist.set_mesh(None)
    dist.init_mesh({"dp": 8})
    m_seq = build(1)
    out_seq = m_seq(paddle.to_tensor(x_np))
    aux_seq = float(m_seq._template._last_pipeline_aux)

    np.testing.assert_allclose(out_pp.numpy(), out_seq.numpy(), rtol=2e-4,
                               atol=1e-5)
    # pipelined aux averages per-microbatch totals; sequential computes
    # the full batch at once — same blocks, same statistic up to the
    # microbatch-vs-batch mean difference (tight here: iid tokens)
    np.testing.assert_allclose(aux_pp, aux_seq, rtol=0.2)


def test_default_num_micro_shrinks_bubble():
    """Default microbatch count follows the GPipe M≈4·pp guidance (the
    fill-drain bubble is (pp-1)/(M+pp-1)): with batch 16 on pp=4 the
    default must pick M=16, not M=pp=4 (43% bubble -> 16%)."""
    dist.init_mesh({"pp": 4})
    m = _build_pipeline(num_stages=4)
    x = np.random.RandomState(0).randn(16, 8).astype("float32")
    out = m(paddle.to_tensor(x))           # builds the default schedule
    keys = list(m._template._pp_prog_cache)
    assert any(k[3] == 16 for k in keys), keys  # M slot of the cache key
    np.testing.assert_allclose(out.numpy(), _sequential_ref(m, x),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_layer_structure():
    dist.init_mesh({"pp": 4})
    m = _build_pipeline(num_stages=4)
    desc = m.parameters_desc
    assert desc == {"prologue": 1, "body": 4, "epilogue": 1, "stages": 4}
    names = {n for n, _ in m.named_parameters()}
    assert "blocks__fc__weight" in names


def test_pipeline_forward_matches_sequential_pp1():
    dist.init_mesh({"pp": 1})
    m = _build_pipeline(num_stages=1)
    x = np.random.randn(8, 8).astype("float32")
    out = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, _sequential_ref(m, x), rtol=1e-4,
                               atol=1e-5)


def test_pipeline_forward_matches_sequential_pp4():
    dist.init_mesh({"pp": 4})
    m = _build_pipeline(num_stages=4)
    x = np.random.randn(8, 8).astype("float32")
    out = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, _sequential_ref(m, x), rtol=1e-4,
                               atol=1e-5)


def test_pipeline_backward_grads_flow():
    dist.init_mesh({"pp": 4})
    m = _build_pipeline(num_stages=4)
    x = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
    loss = paddle.mean(m(x))
    loss.backward()
    for n, p in m.named_parameters():
        assert p.grad is not None, n
        assert float(paddle.abs(p.grad).sum()) > 0 or "bias" in n, n


def test_pipeline_training_converges_vs_single():
    rng = np.random.RandomState(0)
    x_np = rng.randn(16, 8).astype("float32")
    y_np = rng.randn(16, 8).astype("float32")

    def run(pp):
        dist.set_mesh(None)
        dist.init_mesh({"pp": pp})
        m = _build_pipeline(num_stages=pp)
        opt = paddle.optimizer.Adam(learning_rate=0.02,
                                    parameters=m.parameters())
        step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt)
        return [float(step(paddle.to_tensor(x_np), paddle.to_tensor(y_np)))
                for _ in range(6)]

    l1 = run(1)
    l4 = run(4)
    np.testing.assert_allclose(l1, l4, rtol=3e-3)
    assert l4[-1] < l4[0]


def test_pipeline_parallel_train_batch():
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"pp_degree": 4, "dp_degree": 2}
    fleet.init(strategy=s)
    m = _build_pipeline(num_stages=4)
    model = fleet.distributed_model(m)
    assert isinstance(model, PipelineParallel)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
    x = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
    y = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
    losses = [float(model.train_batch((x, y), opt)) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_pipeline_num_micro_gt_pp_matches_sequential():
    """M > pp (the reference's accumulate_steps > pp regime)."""
    dist.init_mesh({"pp": 4})
    paddle.seed(7)
    m = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 8)]
        + [LayerDesc(Block, 8) for _ in range(4)]
        + [LayerDesc(nn.Linear, 8, 8)],
        num_stages=4, num_micro=8,
        loss_fn=lambda o, y: F.mse_loss(o, y))
    x = np.random.randn(16, 8).astype("float32")  # 8 microbatches of 2
    out = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, _sequential_ref(m, x), rtol=1e-4,
                               atol=1e-5)


def test_pipeline_interleaved_matches_sequential():
    """Interleaved virtual stages (reference
    PipelineParallelWithInterleave, pipeline_parallel.py:461): chunk c on
    stage c % pp; numerics must equal the sequential model."""
    dist.init_mesh({"pp": 2})
    paddle.seed(7)
    m = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 8)]
        + [LayerDesc(Block, 8) for _ in range(8)]
        + [LayerDesc(nn.Linear, 8, 8)],
        num_stages=2, interleave=2, num_micro=4,
        loss_fn=lambda o, y: F.mse_loss(o, y))
    x = np.random.randn(8, 8).astype("float32")

    # stacked rows are in placement order; rebuild the logical order for
    # the numpy reference
    from paddle_tpu.distributed.meta_parallel.pipeline_parallel import (
        interleave_perm)
    perm = interleave_perm(8, 2, 2)
    sd = m.state_dict()
    w = sd["blocks__fc__weight"].numpy()
    b = sd["blocks__fc__bias"].numpy()
    h = x @ m.pre_0.weight.numpy() + m.pre_0.bias.numpy()
    wl = np.empty_like(w); bl = np.empty_like(b)
    for pos, logical in enumerate(perm):
        wl[logical] = w[pos]; bl[logical] = b[pos]
    for i in range(8):
        h = h + np.tanh(h @ wl[i] + bl[i])
    ref = h @ m.post_0.weight.numpy() + m.post_0.bias.numpy()

    out = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_pipeline_interleaved_pp1_and_stage_map():
    dist.init_mesh({"pp": 1})
    paddle.seed(7)
    m = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 8, 8)]
        + [LayerDesc(Block, 8) for _ in range(8)]
        + [LayerDesc(nn.Linear, 8, 8)],
        num_stages=2, interleave=2,
        loss_fn=lambda o, y: F.mse_loss(o, y))
    # placement map: chunks of 2 blocks round-robin over 2 stages
    assert [m.get_stage_from_index(i) for i in range(8)] == \
        [0, 0, 1, 1, 0, 0, 1, 1]


def test_pipeline_interleaved_training_matches_plain():
    rng = np.random.RandomState(1)
    x_np = rng.randn(8, 8).astype("float32")
    y_np = rng.randn(8, 8).astype("float32")

    def run(interleave):
        dist.set_mesh(None)
        dist.init_mesh({"pp": 2})
        paddle.seed(7)
        m = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8)]
            + [LayerDesc(Block, 8) for _ in range(8)]
            + [LayerDesc(nn.Linear, 8, 8)],
            num_stages=2, interleave=interleave, num_micro=4,
            loss_fn=lambda o, y: F.mse_loss(o, y))
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt)
        return [float(step(paddle.to_tensor(x_np), paddle.to_tensor(y_np)))
                for _ in range(5)]

    np.testing.assert_allclose(run(1), run(2), rtol=2e-4)


def test_pipeline_memory_shape():
    """The schedule's live-activation bound: per-tick ys collection +
    recompute must need less temp memory than the same schedule without
    recompute (1F1B-equivalent memory discipline, reference
    pipeline_parallel.py:117)."""
    import jax

    def temp_bytes(recompute):
        dist.set_mesh(None)
        dist.init_mesh({"pp": 4})
        paddle.seed(7)
        m = PipelineLayer(
            layers=[LayerDesc(Block, 64) for _ in range(4)],
            num_stages=4, num_micro=8,
            recompute_interval=1 if recompute else 0,
            loss_fn=lambda o, y: F.mse_loss(o, y))
        x = np.random.randn(32, 64).astype("float32")

        def loss(params, xv):
            from paddle_tpu.jit.functional import functional_call
            out, _ = functional_call(m, params, {}, paddle.to_tensor(xv))
            return jax.numpy.mean((out.value if hasattr(out, "value")
                                   else out) ** 2)

        from paddle_tpu.jit.functional import raw_state
        params, _ = raw_state(m)
        lowered = jax.jit(jax.grad(loss)).lower(params, x)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    assert temp_bytes(True) < temp_bytes(False)
