"""Independent-oracle checks: paddle.distribution and paddle.fft vs
torch. log_prob/entropy/KL formulas are easy to get subtly wrong
(Jacobian terms, parameterization conventions); torch.distributions is
the oracle nobody here wrote. Parity target: the reference's
python/paddle/distribution/ formulas, which match torch's."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.distribution import (Beta, Categorical, Dirichlet, Gamma,
                                     Geometric, Gumbel, Laplace, LogNormal,
                                     Normal, Uniform, kl_divergence)


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestLogProbEntropy:
    def test_normal(self):
        loc, scale = np.float32(0.3), np.float32(1.7)
        x = np.linspace(-3, 3, 7).astype(np.float32)
        ours = Normal(loc, scale)
        ref = torch.distributions.Normal(torch.tensor(loc),
                                         torch.tensor(scale))
        np.testing.assert_allclose(
            _np(ours.log_prob(paddle.to_tensor(x))),
            ref.log_prob(torch.from_numpy(x)).numpy(), rtol=1e-5)
        np.testing.assert_allclose(_np(ours.entropy()),
                                   ref.entropy().numpy(), rtol=1e-5)

    def test_laplace_lognormal_gumbel(self):
        x = np.array([0.2, 1.5, 2.7], np.float32)
        pairs = [
            (Laplace(0.5, 1.2),
             torch.distributions.Laplace(0.5, 1.2)),
            (LogNormal(0.1, 0.8),
             torch.distributions.LogNormal(0.1, 0.8)),
            (Gumbel(0.3, 1.1),
             torch.distributions.Gumbel(0.3, 1.1)),
        ]
        for ours, ref in pairs:
            np.testing.assert_allclose(
                _np(ours.log_prob(paddle.to_tensor(x))),
                ref.log_prob(torch.from_numpy(x)).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_beta_gamma_dirichlet(self):
        x01 = np.array([0.2, 0.5, 0.9], np.float32)
        b_ours, b_ref = Beta(2.0, 3.0), torch.distributions.Beta(2.0, 3.0)
        np.testing.assert_allclose(
            _np(b_ours.log_prob(paddle.to_tensor(x01))),
            b_ref.log_prob(torch.from_numpy(x01)).numpy(), rtol=1e-5)
        g_ours = Gamma(paddle.to_tensor(np.float32(2.5)),
                       paddle.to_tensor(np.float32(1.5)))
        g_ref = torch.distributions.Gamma(2.5, 1.5)
        xp = np.array([0.5, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            _np(g_ours.log_prob(paddle.to_tensor(xp))),
            g_ref.log_prob(torch.from_numpy(xp)).numpy(), rtol=1e-5)
        conc = np.array([1.5, 2.0, 3.0], np.float32)
        d_ours = Dirichlet(paddle.to_tensor(conc))
        d_ref = torch.distributions.Dirichlet(torch.from_numpy(conc))
        p = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            _np(d_ours.log_prob(paddle.to_tensor(p))),
            d_ref.log_prob(torch.from_numpy(p)).numpy(), rtol=1e-5)
        np.testing.assert_allclose(_np(d_ours.entropy()),
                                   d_ref.entropy().numpy(), rtol=1e-5)

    def test_uniform_geometric(self):
        u_ours = Uniform(-1.0, 3.0)
        u_ref = torch.distributions.Uniform(-1.0, 3.0)
        x = np.array([-0.5, 0.0, 2.9], np.float32)
        np.testing.assert_allclose(
            _np(u_ours.log_prob(paddle.to_tensor(x))),
            u_ref.log_prob(torch.from_numpy(x)).numpy(), rtol=1e-6)
        g_ours = Geometric(0.3)
        g_ref = torch.distributions.Geometric(0.3)
        k = np.array([0.0, 1.0, 5.0], np.float32)
        np.testing.assert_allclose(
            _np(g_ours.log_prob(paddle.to_tensor(k))),
            g_ref.log_prob(torch.from_numpy(k)).numpy(), rtol=1e-5)


class TestKL:
    def test_normal_kl(self):
        ours = kl_divergence(Normal(0.0, 1.0), Normal(1.0, 2.0))
        ref = torch.distributions.kl_divergence(
            torch.distributions.Normal(0.0, 1.0),
            torch.distributions.Normal(1.0, 2.0))
        np.testing.assert_allclose(float(_np(ours)), float(ref), rtol=1e-5)

    def test_beta_dirichlet_kl(self):
        ours = kl_divergence(Beta(2.0, 3.0), Beta(4.0, 1.5))
        ref = torch.distributions.kl_divergence(
            torch.distributions.Beta(2.0, 3.0),
            torch.distributions.Beta(4.0, 1.5))
        np.testing.assert_allclose(float(_np(ours)), float(ref), rtol=1e-5)
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([2.0, 2.0, 2.0], np.float32)
        ours = kl_divergence(Dirichlet(paddle.to_tensor(a)),
                             Dirichlet(paddle.to_tensor(b)))
        ref = torch.distributions.kl_divergence(
            torch.distributions.Dirichlet(torch.from_numpy(a)),
            torch.distributions.Dirichlet(torch.from_numpy(b)))
        np.testing.assert_allclose(float(_np(ours)), float(ref), rtol=1e-5)

    def test_categorical_split_semantics(self):
        """The reference Categorical is internally inconsistent:
        probs/log_prob sum-normalize (categorical.py:116) while
        entropy/KL/sample softmax (:165, :214, :258). Pin both halves."""
        w1 = np.array([1.0, 2.0, 3.0], np.float32)
        w2 = np.array([3.0, 2.0, 1.0], np.float32)
        c1 = Categorical(paddle.to_tensor(w1))
        c2 = Categorical(paddle.to_tensor(w2))
        # probs/log_prob: sum-normalized == torch probs=w/sum(w)
        t_probs = torch.distributions.Categorical(
            probs=torch.from_numpy(w1 / w1.sum()))
        idx = np.array([0, 1, 2], np.int64)
        np.testing.assert_allclose(
            _np(c1.log_prob(paddle.to_tensor(idx))),
            t_probs.log_prob(torch.from_numpy(idx)).numpy(), rtol=1e-5)
        # entropy/KL: softmax == torch logits=w
        t1 = torch.distributions.Categorical(logits=torch.from_numpy(w1))
        t2 = torch.distributions.Categorical(logits=torch.from_numpy(w2))
        np.testing.assert_allclose(float(_np(c1.entropy())),
                                   float(t1.entropy()), rtol=1e-5)
        np.testing.assert_allclose(
            float(_np(kl_divergence(c1, c2))),
            float(torch.distributions.kl_divergence(t1, t2)), rtol=1e-5)


class TestFFT:
    def test_fft_family(self):
        import paddle_tpu.fft as pfft
        v = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        np.testing.assert_allclose(
            _np(pfft.fft(paddle.to_tensor(v))),
            torch.fft.fft(torch.from_numpy(v)).numpy(), rtol=1e-4,
            atol=1e-5)
        np.testing.assert_allclose(
            _np(pfft.rfft(paddle.to_tensor(v))),
            torch.fft.rfft(torch.from_numpy(v)).numpy(), rtol=1e-4,
            atol=1e-5)
        r = np.random.RandomState(1).randn(4, 9).astype(np.complex64)
        np.testing.assert_allclose(
            _np(pfft.irfft(paddle.to_tensor(r))),
            torch.fft.irfft(torch.from_numpy(r)).numpy(), rtol=1e-4,
            atol=1e-5)
        m = np.random.RandomState(2).randn(6, 8).astype(np.float32)
        np.testing.assert_allclose(
            _np(pfft.fft2(paddle.to_tensor(m))),
            torch.fft.fft2(torch.from_numpy(m)).numpy(), rtol=1e-4,
            atol=1e-4)
        np.testing.assert_allclose(
            _np(pfft.fftshift(paddle.to_tensor(m))),
            torch.fft.fftshift(torch.from_numpy(m)).numpy())
