"""Native shm-ring DataLoader transport tests (native/shm_ring.cc role:
the reference's shared-memory tensors + buffered_reader.cc)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io.shm_ring import ShmRing, ring_available

pytestmark = pytest.mark.skipif(not ring_available(),
                                reason="native shm ring not built")


def test_ring_roundtrip_and_wraparound():
    name = f"/ptpu_t_{os.getpid()}"
    prod = ShmRing(name, capacity=1 << 14)
    cons = ShmRing(name, create=False)
    try:
        for i in range(64):
            msg = bytes([i % 256]) * (500 + i * 7)
            prod.write(msg, timeout=2.0)
            assert cons.read(timeout=2.0) == msg
        # full ring -> write timeout
        prod.write(b"a" * 12000, timeout=2.0)
        with pytest.raises(TimeoutError):
            prod.write(b"b" * 8000, timeout=0.2)
        # oversized message -> ValueError
        with pytest.raises(ValueError):
            prod.write(b"c" * (1 << 15), timeout=0.2)
        # closed + drained -> EOF
        prod.mark_closed()
        assert cons.read(timeout=2.0) == b"a" * 12000
        with pytest.raises(EOFError):
            cons.read(timeout=2.0)
    finally:
        cons.close()
        prod.close()


class _NpDataset(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((16, 16), i, np.float32), np.int64(i))


def _collect(dl):
    xs, ys = [], []
    for x, y in dl:
        xs.append(x.numpy())
        ys.append(y.numpy())
    return np.concatenate(xs), np.concatenate(ys)


def test_process_loader_ring_matches_queue():
    ds = _NpDataset()
    dl_ring = DataLoader(ds, batch_size=8, num_workers=2,
                         worker_mode="process", use_shared_memory=True)
    dl_q = DataLoader(ds, batch_size=8, num_workers=2,
                      worker_mode="process", use_shared_memory=False)
    xr, yr = _collect(dl_ring)
    xq, yq = _collect(dl_q)
    np.testing.assert_array_equal(xr, xq)
    np.testing.assert_array_equal(yr, yq)
    np.testing.assert_array_equal(np.sort(yr), np.arange(64))


def test_process_loader_ring_error_propagates():
    class Bad(_NpDataset):
        def __getitem__(self, i):
            if i == 10:
                raise ValueError("bad sample 10")
            return super().__getitem__(i)

    dl = DataLoader(Bad(), batch_size=4, num_workers=2,
                    worker_mode="process", use_shared_memory=True)
    with pytest.raises(RuntimeError, match="bad sample 10"):
        _collect(dl)


def test_process_loader_large_batches():
    """Batches bigger than the queue pipe would like; several ring laps."""
    class Big(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return np.full((256, 1024), i, np.float32)  # 1 MB

    dl = DataLoader(Big(), batch_size=2, num_workers=2,
                    worker_mode="process", use_shared_memory=True)
    seen = []
    for b in dl:
        assert b.shape == [2, 256, 1024]
        seen.extend(np.asarray(b.numpy()[:, 0, 0]).tolist())
    assert sorted(seen) == list(range(12))
