"""vision.transforms tests (reference: test_transforms.py patterns —
identity checks, involutions, numeric formulas, surface parity)."""
import os
import re

import numpy as np
import pytest

import paddle_tpu.vision.transforms as T


@pytest.fixture
def img():
    return np.random.RandomState(0).randint(
        0, 255, (24, 32, 3)).astype(np.uint8)


_REF_TRANSFORMS = ("/root/reference/python/paddle/vision/transforms/"
                   "__init__.py")


@pytest.mark.skipif(not os.path.exists(_REF_TRANSFORMS),
                    reason="reference tree not mounted")
def test_surface_matches_reference():
    ref = open(_REF_TRANSFORMS).read()
    names = {a or b for a, b in re.findall(
        r"'(\w+)'|\"(\w+)\"",
        re.search(r"__all__ = \[(.*?)\]", ref, re.S).group(1))}
    missing = sorted(n for n in names if not hasattr(T, n))
    assert not missing, missing


def test_identity_geometry(img):
    np.testing.assert_array_equal(T.rotate(img, 0.0), img)
    np.testing.assert_array_equal(T.affine(img, 0.0), img)
    corners = [(0, 0), (31, 0), (31, 23), (0, 23)]
    np.testing.assert_array_equal(
        T.perspective(img, corners, corners), img)


def test_flips_are_involutions(img):
    np.testing.assert_array_equal(T.hflip(T.hflip(img)), img)
    np.testing.assert_array_equal(T.vflip(T.vflip(img)), img)
    np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])


def test_rotate_90_exact(img):
    sq = img[:24, :24]
    got = T.rotate(sq, 90.0)
    want = np.rot90(sq, 1)   # CCW, matching positive-angle convention
    # interior agrees exactly (boundary interpolation may differ by 1px)
    np.testing.assert_allclose(got[2:-2, 2:-2].astype(int),
                               want[2:-2, 2:-2].astype(int), atol=1)


def test_crop_pad_roundtrip(img):
    padded = T.pad(img, 4, fill=7)
    assert padded.shape == (32, 40, 3)
    assert (padded[:4] == 7).all()
    np.testing.assert_array_equal(T.crop(padded, 4, 4, 24, 32), img)
    cc = T.center_crop(img, (10, 12))
    assert cc.shape == (10, 12, 3)
    np.testing.assert_array_equal(cc, img[7:17, 10:22])


def test_adjustments(img):
    np.testing.assert_array_equal(T.adjust_brightness(img, 1.0), img)
    np.testing.assert_array_equal(T.adjust_hue(img, 0.0), img)
    doubled = T.adjust_brightness(img, 2.0)
    assert doubled.max() == 255 and doubled.dtype == np.uint8
    gray = T.to_grayscale(img)
    want = (img.astype(np.float32) @
            np.array([0.299, 0.587, 0.114], np.float32))
    np.testing.assert_allclose(gray[..., 0].astype(float), np.round(want),
                               atol=1)
    # saturation 0 == grayscale in all channels
    desat = T.adjust_saturation(img, 0.0)
    assert np.abs(desat[..., 0].astype(int)
                  - desat[..., 1].astype(int)).max() <= 1


def test_erase_and_random_erasing(img):
    out = T.erase(img, 2, 3, 5, 6, 0)
    assert (out[2:7, 3:9] == 0).all()
    assert (out[:2] == img[:2]).all()
    out2 = T.RandomErasing(prob=1.0, value=9)(img)
    assert (out2 == 9).any()


def test_to_tensor_and_normalize(img):
    t = T.to_tensor(img)
    assert t.shape == [3, 24, 32]
    assert float(t.numpy().max()) <= 1.0
    n = T.normalize(np.ones((3, 4, 4), np.float32), mean=[0.5] * 3,
                    std=[0.5] * 3)
    np.testing.assert_allclose(n, np.ones((3, 4, 4)) * 1.0)


def test_normalize_to_rgb_flips_channels():
    bgr = np.stack([np.full((2, 2), c, np.float32) for c in (1.0, 2.0, 3.0)])
    out = T.normalize(bgr, mean=[0.0] * 3, std=[1.0] * 3, to_rgb=True)
    np.testing.assert_allclose(out[0], 3.0)  # R came from BGR channel 2
    np.testing.assert_allclose(out[2], 1.0)
    hwc = bgr.transpose(1, 2, 0)
    out2 = T.normalize(hwc, mean=[0.0] * 3, std=[1.0] * 3,
                       data_format="HWC", to_rgb=True)
    np.testing.assert_allclose(out2[..., 0], 3.0)


def test_random_transforms_shapes(img):
    assert T.RandomRotation(30)(img).shape == img.shape
    assert T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.9, 1.1),
                          shear=5)(img).shape == img.shape
    assert T.RandomPerspective(prob=1.0)(img).shape == img.shape
    assert T.RandomResizedCrop(16)(img).shape == (16, 16, 3)
    assert T.RandomVerticalFlip(prob=1.0)(img).shape == img.shape
    assert T.Grayscale(3)(img).shape == img.shape
    assert T.ColorJitter(0.4, 0.4, 0.4, 0.2)(img).shape == img.shape
    assert T.Pad(2)(img).shape == (28, 36, 3)


def test_jitter_ranges_and_validation(img):
    # (min, max) range form accepted, like the reference _check_input
    assert T.BrightnessTransform((0.8, 1.2))(img).shape == img.shape
    assert T.ColorJitter(brightness=(0.9, 1.1), hue=(-0.1, 0.1))(
        img).shape == img.shape
    with pytest.raises(ValueError):
        T.BrightnessTransform(-0.5)
    with pytest.raises(ValueError):
        T.HueTransform(0.7)
    with pytest.raises(ValueError):
        T.SaturationTransform((1.2, 0.8))   # min > max
    # value=0 == identity
    np.testing.assert_array_equal(T.ContrastTransform(0)(img), img)


def test_random_erasing_array_value(img):
    out = T.RandomErasing(prob=1.0,
                          value=np.array([1, 2, 3], np.uint8))(img)
    assert out.shape == img.shape


def test_paired_keys():
    tr = T.Grayscale(keys=["image", "label"])
    img = np.zeros((4, 4, 3), np.uint8)
    out_img, out_label = tr((img, 7))
    assert out_img.shape == (4, 4, 1) and out_label == 7
