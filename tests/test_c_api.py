"""C inference API (native/c_api.cc) — reference capi_exp role.

Two consumers are driven: (a) this process via ctypes (the library
detects the already-initialized interpreter), and (b) a REAL standalone
C program, compiled here and run in a subprocess, which embeds Python
itself — the actual C-deployment story.
"""
import ctypes
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference.c_api import build_c_api

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path_factory.mktemp("capi") / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([None, 8])])
    x = np.random.RandomState(0).randn(3, 8).astype("float32")
    return path + ".pdmodel", x, m(paddle.to_tensor(x)).numpy()


def test_c_api_via_ctypes(saved):
    so = build_c_api()
    assert so, "C API failed to build"
    model, x, ref = saved
    lib = ctypes.CDLL(so)
    lib.PD_PredictorCreate.restype = ctypes.c_void_p
    lib.PD_PredictorCreate.argtypes = [ctypes.c_char_p]
    lib.PD_GetLastError.restype = ctypes.c_char_p
    p = lib.PD_PredictorCreate(model.encode())
    assert p, lib.PD_GetLastError()
    try:
        assert lib.PD_PredictorGetInputNum(ctypes.c_void_p(p)) == 1
        assert lib.PD_PredictorGetOutputNum(ctypes.c_void_p(p)) == 1

        data = np.ascontiguousarray(x)
        shape = (ctypes.c_int64 * 2)(*x.shape)
        ins = (ctypes.c_void_p * 1)(data.ctypes.data)
        shapes = (ctypes.POINTER(ctypes.c_int64) * 1)(shape)
        ndims = (ctypes.c_int * 1)(2)
        dts = (ctypes.c_int * 1)(0)  # PD_DTYPE_FLOAT32
        rc = lib.PD_PredictorRun(ctypes.c_void_p(p), ins, shapes, ndims,
                                 dts, 1)
        assert rc == 0, lib.PD_GetLastError()

        oshape = (ctypes.c_int64 * 8)()
        ondim = ctypes.c_int()
        rc = lib.PD_PredictorGetOutputShape(
            ctypes.c_void_p(p), 0, oshape, ctypes.byref(ondim), 8)
        assert rc == 0, lib.PD_GetLastError()
        got_shape = tuple(oshape[i] for i in range(ondim.value))
        assert got_shape == ref.shape

        buf = np.zeros(ref.size, np.float32)
        lib.PD_PredictorGetOutputData.restype = ctypes.c_int64
        n = lib.PD_PredictorGetOutputData(
            ctypes.c_void_p(p), 0,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(buf.size))
        assert n == ref.size, lib.PD_GetLastError()
        np.testing.assert_allclose(buf.reshape(ref.shape), ref,
                                   rtol=1e-5, atol=1e-6)
    finally:
        lib.PD_PredictorDestroy(ctypes.c_void_p(p))


_C_DRIVER = textwrap.dedent("""
    #include <stdio.h>
    #include <stdint.h>
    typedef struct PD_Predictor PD_Predictor;
    extern PD_Predictor* PD_PredictorCreate(const char*);
    extern void PD_PredictorDestroy(PD_Predictor*);
    extern int PD_PredictorRun(PD_Predictor*, const void**,
                               const int64_t**, const int*, const int*,
                               int);
    extern int64_t PD_PredictorGetOutputData(PD_Predictor*, int, float*,
                                             int64_t);
    extern const char* PD_GetLastError(void);

    int main(int argc, char** argv) {
        PD_Predictor* p = PD_PredictorCreate(argv[1]);
        if (!p) { fprintf(stderr, "create: %s\\n", PD_GetLastError());
                  return 1; }
        float x[16];
        for (int i = 0; i < 16; i++) x[i] = (float)i * 0.1f - 0.8f;
        int64_t shape[2] = {2, 8};
        const void* ins[1] = {x};
        const int64_t* shapes[1] = {shape};
        int ndims[1] = {2}; int dts[1] = {0};
        if (PD_PredictorRun(p, ins, shapes, ndims, dts, 1)) {
            fprintf(stderr, "run: %s\\n", PD_GetLastError()); return 2;
        }
        float out[8];
        int64_t n = PD_PredictorGetOutputData(p, 0, out, 8);
        if (n < 0) { fprintf(stderr, "out: %s\\n", PD_GetLastError());
                     return 3; }
        for (int64_t i = 0; i < n; i++) printf("%.6f\\n", out[i]);
        PD_PredictorDestroy(p);
        return 0;
    }
""")


@pytest.mark.slow
def test_c_api_from_standalone_c_program(saved, tmp_path):
    """Compile and run an actual C consumer: it embeds Python, loads the
    model, runs inference, prints the outputs."""
    so = build_c_api()
    assert so, "C API failed to build"
    model, _, _ = saved
    src = tmp_path / "driver.c"
    src.write_text(_C_DRIVER)
    exe = str(tmp_path / "driver")
    subprocess.run(["gcc", str(src), so, "-o", exe,
                    f"-Wl,-rpath,{os.path.dirname(so)}"], check=True,
                   capture_output=True)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([exe, model], capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode == 0, r.stderr
    vals = [float(v) for v in r.stdout.strip().splitlines()]
    assert len(vals) == 8

    # reference from the Python path
    x = (np.arange(16, dtype=np.float32) * 0.1 - 0.8).reshape(2, 8)
    from paddle_tpu.inference import Config, create_predictor
    ref = create_predictor(Config(model)).run([x])[0]
    np.testing.assert_allclose(np.asarray(vals).reshape(2, 4), ref,
                               rtol=1e-5, atol=1e-6)
