"""Semi-auto parallel API: ProcessMesh, shard_tensor, Engine on the
virtual 8-device CPU mesh (conftest bootstraps it). VERDICT item 8:
dp x mp training without touching Parameter.sharding_axes directly."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import Engine, ProcessMesh, shard_tensor
from paddle_tpu.distributed.auto_parallel.process_mesh import (
    get_current_process_mesh)


class TestProcessMesh:
    def test_construct(self):
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["x", "y"])
        assert pm.shape == [2, 4]
        assert pm.ndim == 2
        assert pm.process_ids == list(range(8))
        assert pm.dim_names == ["x", "y"]

    def test_context_manager(self):
        pm = ProcessMesh([0, 1], dim_names=["x"])
        assert get_current_process_mesh() is None
        with pm:
            assert get_current_process_mesh() is pm
        assert get_current_process_mesh() is None

    def test_getitem(self):
        pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        row = pm[0]
        assert row.process_ids == [0, 1]
        assert row.shape == [2]

    def test_eq(self):
        a = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        b = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        c = ProcessMesh([0, 1, 2, 3], dim_names=["x"])
        assert a == b and a != c

    def test_to_jax_mesh(self):
        import jax
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["dp", "mp"])
        mesh = pm.to_jax_mesh()
        assert mesh.shape == {"dp": 2, "mp": 4}
        assert mesh.devices.shape == (2, 4)

    def test_validation(self):
        with pytest.raises(AssertionError):
            ProcessMesh([[0, 1]], dim_names=["x"])  # ndim mismatch
        with pytest.raises(AssertionError):
            ProcessMesh([0, 0], dim_names=["x"])  # dup ids

    def test_getitem_keeps_surviving_dim_names(self):
        pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        col = pm[slice(None), 0]
        assert col.process_ids == [0, 2]
        assert col.dim_names == ["x"]
        row = pm[1]
        assert row.dim_names == ["y"]

    def test_hashable(self):
        a = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        b = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        assert len({a, b}) == 1

    def test_out_of_range_process_id(self):
        pm = ProcessMesh([0, 99], dim_names=["x"])
        with pytest.raises(ValueError, match="out of range"):
            pm.to_jax_mesh()


class TestShardTensor:
    def test_places_on_mesh(self):
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["x", "y"])
        t = paddle.to_tensor(np.zeros((8, 12), np.float32))
        out = shard_tensor(t, pm, ["x", "y"])
        shard_shape = out.value.sharding.shard_shape(out.value.shape)
        assert shard_shape == (4, 3)

    def test_parameter_records_axes(self):
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["dp", "mp"])
        lin = nn.Linear(8, 8)
        shard_tensor(lin.weight, pm, [None, "mp"])
        assert lin.weight.sharding_axes == (None, "mp")

    def test_replicated_when_spec_none(self):
        pm = ProcessMesh([0, 1], dim_names=["x"])
        t = paddle.to_tensor(np.zeros((4, 4), np.float32))
        out = shard_tensor(t, pm)
        assert out.value.sharding.shard_shape(out.value.shape) == (4, 4)

    def test_current_mesh_used(self):
        t = paddle.to_tensor(np.zeros((8,), np.float32))
        with ProcessMesh([0, 1, 2, 3], dim_names=["x"]):
            out = shard_tensor(t, shard_spec=["x"])
        assert out.value.sharding.shard_shape(out.value.shape) == (2,)

    def test_requires_mesh(self):
        t = paddle.to_tensor(np.zeros((4,), np.float32))
        with pytest.raises(AssertionError):
            shard_tensor(t, shard_spec=[None])

    def test_bad_axis_name(self):
        pm = ProcessMesh([0, 1], dim_names=["x"])
        t = paddle.to_tensor(np.zeros((4,), np.float32))
        with pytest.raises(AssertionError):
            shard_tensor(t, pm, ["nope"])


class TestEngine:
    def _data(self, n=64, d=16):
        rng = np.random.RandomState(0)
        X = rng.randn(n, d).astype(np.float32)
        w = rng.randn(d).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        return X, y

    def test_engine_dp_mp_fit(self):
        """dp x mp training through Engine: user annotates weights with
        shard_tensor only (no Parameter.sharding_axes)."""
        from paddle_tpu.io.dataloader import Dataset

        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["dp", "mp"])
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 2))
        # column-parallel first weight, row-parallel second (Megatron
        # pattern) via the user-facing annotation only
        shard_tensor(model[0].weight, pm, [None, "mp"])
        shard_tensor(model[2].weight, pm, ["mp", None])

        X, y = self._data()

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return len(X)

        engine = Engine(model=model, loss=nn.CrossEntropyLoss(),
                        optimizer=paddle.optimizer.Adam(
                            learning_rate=5e-3,
                            parameters=model.parameters()),
                        process_mesh=pm)
        hist = engine.fit(DS(), epochs=3, batch_size=16, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        # parameters kept their mp sharding through training
        p0 = engine._train_step.params["0.weight"]
        assert p0.sharding.shard_shape(p0.shape)[1] == 32 // 4

    def test_engine_evaluate_predict(self):
        from paddle_tpu.io.dataloader import Dataset
        from paddle_tpu.metric import Accuracy

        pm = ProcessMesh([[i] for i in range(8)], dim_names=["dp", "mp"])
        paddle.seed(1)
        model = nn.Sequential(nn.Linear(16, 8), nn.ReLU(),
                              nn.Linear(8, 2))
        X, y = self._data(n=32)

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return len(X)

        engine = Engine(model=model, loss=nn.CrossEntropyLoss(),
                        optimizer=paddle.optimizer.SGD(
                            learning_rate=1e-2,
                            parameters=model.parameters()),
                        metrics=Accuracy(), process_mesh=pm)
        engine.fit(DS(), epochs=1, batch_size=8, verbose=0)
        logs = engine.evaluate(DS(), batch_size=8, verbose=0)
        assert "loss" in logs and "acc" in logs
        preds = engine.predict(DS(), batch_size=8)
        assert preds[0].shape == (8, 2)

    def test_engine_save_load(self, tmp_path):
        pm = ProcessMesh(list(range(8)), dim_names=["dp"])
        paddle.seed(2)
        model = nn.Sequential(nn.Linear(4, 4))
        engine = Engine(model=model, loss=nn.MSELoss(),
                        optimizer=paddle.optimizer.SGD(
                            learning_rate=0.1,
                            parameters=model.parameters()),
                        process_mesh=pm)
        engine.prepare(mode="train")
        path = str(tmp_path / "ckpt")
        engine.save(path)
        w_before = model[0].weight.numpy().copy()
        # perturb then load back
        model[0].weight.value = model[0].weight.value + 1.0
        engine.load(path)
        np.testing.assert_allclose(model[0].weight.numpy(), w_before,
                                   rtol=1e-6)

    def test_eval_only_engine(self):
        """Reference supports inference-only Engines (no optimizer)."""
        from paddle_tpu.io.dataloader import Dataset

        pm = ProcessMesh(list(range(8)), dim_names=["dp"])
        paddle.seed(4)
        model = nn.Sequential(nn.Linear(16, 2))
        X, y = self._data(n=16)

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return len(X)

        engine = Engine(model=model, loss=nn.CrossEntropyLoss(),
                        process_mesh=pm)
        logs = engine.evaluate(DS(), batch_size=8, verbose=0)
        assert np.isfinite(logs["loss"])
        preds = engine.predict(DS(), batch_size=8)
        assert preds[0].shape == (8, 2)

    def test_zero3_via_strategy(self):
        from paddle_tpu.distributed import DistributedStrategy
        from paddle_tpu.io.dataloader import Dataset

        pm = ProcessMesh(list(range(8)), dim_names=["sharding"])
        paddle.seed(3)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 2))
        st = DistributedStrategy()
        st.sharding = True
        st.sharding_configs.stage = 3
        X, y = self._data(n=32)

        class DS(Dataset):
            def __getitem__(self, i):
                return X[i], y[i]

            def __len__(self):
                return len(X)

        engine = Engine(model=model, loss=nn.CrossEntropyLoss(),
                        optimizer=paddle.optimizer.Adam(
                            learning_rate=5e-3,
                            parameters=model.parameters()),
                        strategy=st, process_mesh=pm)
        hist = engine.fit(DS(), epochs=2, batch_size=32, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0] + 1e-6
        # ZeRO-3: params sharded over the axis
        p = engine._train_step.params["0.weight"]
        assert p.sharding.shard_shape(p.shape) != tuple(p.shape)


class TestPassPipeline:
    """distributed.passes really rewrites the Engine's step plan
    (reference: pass_base.py PassManager over Programs; here the plan
    IS the program surface — see passes.py docstring)."""

    def test_passes_change_the_built_step(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

        pm_mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 16), nn.GELU(),
                              nn.Linear(16, 16))
        engine = Engine(model=model, loss=nn.MSELoss(),
                        optimizer=paddle.optimizer.AdamW(
                            learning_rate=1e-3,
                            parameters=model.parameters()),
                        process_mesh=pm_mesh)
        pipeline = dist.passes.PassManager([
            dist.passes.new_pass("auto_parallel_sharding", {"stage": 2}),
            dist.passes.new_pass("auto_parallel_recompute"),
            dist.passes.new_pass("auto_parallel_gradient_merge",
                                 {"k_steps": 2}),
        ])
        pipeline.apply(engine)
        engine.prepare(mode="train")
        step = engine._train_step
        assert step.zero_stage == 2          # sharding pass took effect
        assert step.remat                    # recompute pass took effect
        assert step.accumulate_steps == 2    # gradient merge took effect
        # and the step still trains
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 16).astype("float32"))
        for _ in range(2):                   # k=2 -> one full update
            loss = step(x, x)
        assert np.isfinite(float(loss))
        assert step.update_count == 1

    def test_pass_survives_default_strategy_fold(self):
        """A default-constructed DistributedStrategy must not silently
        reset plan values set by passes (its pipeline_configs exists by
        default_factory; only a non-default cadence may override)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

        pm_mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        paddle.seed(3)
        model = nn.Sequential(nn.Linear(8, 8))
        engine = Engine(model=model, loss=nn.MSELoss(),
                        optimizer=paddle.optimizer.SGD(
                            learning_rate=0.1,
                            parameters=model.parameters()),
                        strategy=dist.fleet.DistributedStrategy(),
                        process_mesh=pm_mesh)
        dist.passes.PassManager([
            dist.passes.new_pass("auto_parallel_gradient_merge",
                                 {"k_steps": 4}),
        ]).apply(engine)
        engine.prepare(mode="train")
        assert engine._train_step.accumulate_steps == 4

    def test_pass_apply_rejects_non_plan_targets(self):
        import paddle_tpu.distributed as dist
        import pytest as _pytest
        with _pytest.raises(TypeError, match="new_step_plan"):
            dist.passes.new_pass("auto_parallel_recompute").apply(["prog"])

    def test_amp_o2_keeps_norm_fp32_and_engages_master_weights(self):
        """ISSUE 16 satellite: the O2 amp pass must NOT blanket-cast —
        normalization params/stats stay fp32 (a bf16 running-variance
        drifts) while compute params go bfloat16, and the optimizer's
        multi_precision master-weight path engages so updates
        accumulate in fp32 slots."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.auto_parallel import (Engine,
                                                          ProcessMesh)

        pm_mesh = ProcessMesh(list(range(8)), dim_names=["dp"])
        paddle.seed(5)
        model = nn.Sequential(nn.Linear(16, 16), nn.LayerNorm(16),
                              nn.GELU(), nn.Linear(16, 16))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        assert not opt._multi_precision
        engine = Engine(model=model, loss=nn.MSELoss(), optimizer=opt,
                        process_mesh=pm_mesh)
        dist.passes.PassManager([
            dist.passes.new_pass("auto_parallel_amp", {"level": "O2"}),
        ]).apply(engine)
        engine.prepare(mode="train")
        # compute params cast, norm params untouched
        assert str(model[0].weight.value.dtype) == "bfloat16"
        assert str(model[3].weight.value.dtype) == "bfloat16"
        assert str(model[1].weight.value.dtype) == "float32"
        assert str(model[1].bias.value.dtype) == "float32"
        # master weights: the multi_precision path is armed
        assert opt._multi_precision
        # and the step still trains in bf16 without NaNs
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(8, 16).astype("float32"))
        loss = engine._train_step(x, x)
        assert np.isfinite(float(loss))
