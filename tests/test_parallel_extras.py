"""MoE, recompute, and sequence-parallel tests.

Reference models: moe tests (incubate moe_layer), recompute tests
(test_dygraph_recompute.py: grads with/without recompute must match),
and — beyond the reference (SURVEY.md §5.7) — ring/Ulysses attention
checked exactly against plain softmax attention.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


@pytest.fixture(autouse=True)
def fresh_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


# ---------------------------------------------------------------------------
# recompute
# ---------------------------------------------------------------------------

class TwoLayer(nn.Layer):
    def __init__(self):
        super().__init__()
        self.a = nn.Linear(8, 32)
        self.b = nn.Linear(32, 8)

    def forward(self, x):
        return self.b(F.gelu(self.a(x)))


def test_recompute_grads_match_plain():
    paddle.seed(0)
    m = TwoLayer()
    x_np = np.random.randn(4, 8).astype("float32")

    x1 = paddle.to_tensor(x_np)
    paddle.mean(m(x1)).backward()
    ref = {n: p.grad.numpy().copy() for n, p in m.named_parameters()}
    m.clear_gradients()

    x2 = paddle.to_tensor(x_np)
    out = dist.recompute(m, x2)
    paddle.mean(out).backward()
    for n, p in m.named_parameters():
        np.testing.assert_allclose(p.grad.numpy(), ref[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_recompute_in_parallel_step():
    dist.init_mesh({"dp": 8})
    paddle.seed(0)
    m = TwoLayer()
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())
    step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt,
                                  remat=True)
    x = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
    losses = [float(step(x, x)) for _ in range(5)]
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------

def test_moe_forward_and_balance_loss():
    dist.init_mesh({"ep": 4})
    paddle.seed(0)
    moe = dist.MoELayer(d_model=16, d_hidden=32, num_experts=4,
                        gate="switch", capacity_factor=2.0)
    x = paddle.to_tensor(np.random.randn(2, 12, 16).astype("float32"))
    out = moe(x)
    assert out.shape == [2, 12, 16]
    aux = moe.l_aux
    assert aux is not None and float(aux) > 0
    # expert weights annotated for the ep axis
    assert moe.w_in.sharding_axes[0] == "ep"


def test_moe_trains():
    dist.init_mesh({"ep": 4, "dp": 2})

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.inp = nn.Linear(8, 16)
            self.moe = dist.MoELayer(16, 32, 4, gate="gshard",
                                     capacity_factor=2.0)
            self.out = nn.Linear(16, 8)

        def forward(self, x):
            return self.out(self.moe(self.inp(x)))

    paddle.seed(1)
    m = Net()
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters())
    step = dist.ParallelTrainStep(m, lambda o, y: F.mse_loss(o, y), opt)
    x = paddle.to_tensor(np.random.randn(8, 4, 8).astype("float32"))
    losses = [float(step(x, x)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_moe_routes_all_tokens_with_capacity():
    dist.init_mesh({"ep": 1})
    paddle.seed(0)
    # capacity ample -> output should differ from zero for every token
    moe = dist.MoELayer(8, 16, 2, gate="switch", capacity_factor=4.0)
    x = paddle.to_tensor(np.random.randn(1, 16, 8).astype("float32"))
    out = moe(x).numpy()
    assert (np.abs(out).sum(-1) > 0).all()


# ---------------------------------------------------------------------------
# sequence parallel (exceeds reference)
# ---------------------------------------------------------------------------

def _np_attention(q, k, v, causal):
    d = q.shape[-1]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        S = q.shape[1]
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    dist.init_mesh({"sp": 4})
    rng = np.random.RandomState(0)
    q = rng.randn(2, 16, 4, 8).astype("float32")
    k = rng.randn(2, 16, 4, 8).astype("float32")
    v = rng.randn(2, 16, 4, 8).astype("float32")
    out = dist.ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                              paddle.to_tensor(v), causal=causal)
    np.testing.assert_allclose(out.numpy(), _np_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_exact(causal):
    dist.init_mesh({"sp": 4})
    rng = np.random.RandomState(1)
    q = rng.randn(2, 16, 8, 4).astype("float32")
    k = rng.randn(2, 16, 8, 4).astype("float32")
    v = rng.randn(2, 16, 8, 4).astype("float32")
    out = dist.ulysses_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), causal=causal)
    np.testing.assert_allclose(out.numpy(), _np_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_backward():
    dist.init_mesh({"sp": 4})
    q = paddle.to_tensor(np.random.randn(1, 8, 2, 4).astype("float32"),
                         stop_gradient=False)
    k = paddle.to_tensor(np.random.randn(1, 8, 2, 4).astype("float32"),
                         stop_gradient=False)
    v = paddle.to_tensor(np.random.randn(1, 8, 2, 4).astype("float32"),
                         stop_gradient=False)
    out = dist.ring_attention(q, k, v, causal=True)
    paddle.mean(out).backward()
    for t in (q, k, v):
        assert t.grad is not None
        assert np.isfinite(t.grad.numpy()).all()


def test_moe_gating_no_slot_collisions_and_router_grad():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.moe import _gating

    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(16, 4).astype("float32"))
    dispatch, combine, _ = _gating(logits, top_k=2, capacity=16)
    # no (expert, slot) may hold more than one token
    occupancy = np.asarray(dispatch.sum(axis=0))
    assert occupancy.max() <= 1.0, occupancy.max()

    # router must receive task gradient through combine, also for top-1
    def combine_sum(lg):
        _, c, _ = _gating(lg, top_k=1, capacity=16)
        return (c * jnp.arange(c.size).reshape(c.shape)).sum()

    g = np.asarray(jax.grad(combine_sum)(logits))
    assert np.abs(g).sum() > 0


def test_recompute_kwarg_tensor_gets_grad():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x, bias=None):
            out = self.fc(x)
            return out + bias if bias is not None else out

    m = Net()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    b = paddle.to_tensor(np.random.randn(4, 8).astype("float32"),
                         stop_gradient=False)
    out = dist.recompute(m, x, bias=b)
    paddle.mean(out).backward()
    assert b.grad is not None
    np.testing.assert_allclose(b.grad.numpy(), np.full((4, 8), 1 / 32),
                               rtol=1e-5)


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_ring_attention_long_context_16k():
    """Long-context first-class (brief/SURVEY §5.7): a 16384-token causal
    ring over sp=8 runs in shard-sized memory — each device only ever
    holds S/sp=2048-long q and one rotating k/v block (the unfused XLA
    body; the fused Pallas path is hardware-gated). Statistical check
    against the closed form for constant v."""
    import paddle_tpu.distributed.sequence_parallel as sp_mod
    dist.init_mesh({"sp": 8})
    mesh = dist.get_mesh()
    B, S, H, D = 1, 16384, 2, 64
    prog = sp_mod._ring_program(mesh, 8, 1.0 / D ** 0.5, True, S // 8,
                                False, True)
    import jax.numpy as jnp
    q = jnp.zeros((B, S, H, D), jnp.float32)
    # constant v: causal attention output is exactly v regardless of scores
    v = jnp.full((B, S, H, D), 0.731, jnp.float32)
    out = np.asarray(prog(q, q, v))
    assert out.shape == (B, S, H, D)
    np.testing.assert_allclose(out, 0.731, rtol=1e-5)
