"""Numeric op checks for the thinnest-covered tensor modules (linalg,
stat, search, manipulation, random) against numpy references — the
reference's OpTest pattern (test/legacy_test/op_test.py: compare against
a python/numpy model) applied to the long tail.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

RNG = np.random.RandomState(7)


def T(a):
    return paddle.to_tensor(np.asarray(a))


# ---------------------------------------------------------------- linalg

class TestLinalg:
    A = RNG.randn(4, 4).astype("float32")
    B = RNG.randn(4, 4).astype("float32")
    SPD = (A @ A.T + 4 * np.eye(4)).astype("float32")

    def test_solve_and_inverse(self):
        x = paddle.linalg.solve(T(self.A), T(self.B))
        np.testing.assert_allclose(self.A @ x.numpy(), self.B, atol=1e-4)
        inv = paddle.linalg.inverse(T(self.A))
        np.testing.assert_allclose(inv.numpy() @ self.A, np.eye(4),
                                   atol=1e-4)

    def test_cholesky_and_cholesky_solve(self):
        L = paddle.linalg.cholesky(T(self.SPD)).numpy()
        np.testing.assert_allclose(L @ L.T, self.SPD, atol=1e-4)
        U = paddle.linalg.cholesky(T(self.SPD), upper=True).numpy()
        np.testing.assert_allclose(U.T @ U, self.SPD, atol=1e-4)
        y = RNG.randn(4, 2).astype("float32")
        x = paddle.linalg.cholesky_solve(T(y), T(L)).numpy()
        np.testing.assert_allclose(self.SPD @ x, y, atol=2e-3)

    def test_qr_svd_pinv(self):
        q, r = paddle.linalg.qr(T(self.A))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), self.A,
                                   atol=1e-4)
        u, s, vh = paddle.linalg.svd(T(self.A))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vh.numpy(), self.A, atol=1e-4)
        p = paddle.linalg.pinv(T(self.A)).numpy()
        np.testing.assert_allclose(self.A @ p @ self.A, self.A, atol=1e-3)

    def test_eigh_det_slogdet(self):
        w, v = paddle.linalg.eigh(T(self.SPD))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, self.SPD,
            atol=1e-3)
        det = float(paddle.linalg.det(T(self.A)).numpy())
        np.testing.assert_allclose(det, np.linalg.det(self.A), rtol=1e-4)
        sign, logd = paddle.linalg.slogdet(T(self.A))
        ref_sign, ref_log = np.linalg.slogdet(self.A)
        np.testing.assert_allclose(float(sign.numpy()), ref_sign)
        np.testing.assert_allclose(float(logd.numpy()), ref_log,
                                   rtol=1e-4)

    def test_norms_and_cond(self):
        x = RNG.randn(3, 5).astype("float32")
        np.testing.assert_allclose(
            paddle.linalg.norm(T(x)).numpy(), np.linalg.norm(x),
            rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.norm(T(x), p=1, axis=1).numpy(),
            np.abs(x).sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.matrix_norm(T(x), p="fro").numpy(),
            np.linalg.norm(x, "fro"), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.cond(T(self.SPD)).numpy(),
            np.linalg.cond(self.SPD), rtol=1e-3)

    def test_matrix_power_rank_multi_dot(self):
        np.testing.assert_allclose(
            paddle.linalg.matrix_power(T(self.A), 3).numpy(),
            np.linalg.matrix_power(self.A, 3), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            paddle.linalg.matrix_power(T(self.A), -1).numpy(),
            np.linalg.matrix_power(self.A, -1), rtol=1e-3, atol=1e-3)
        low = np.outer(RNG.randn(4), RNG.randn(4)).astype("float32")
        assert int(paddle.linalg.matrix_rank(T(low)).numpy()) == 1
        mats = [RNG.randn(2, 3).astype("float32"),
                RNG.randn(3, 4).astype("float32"),
                RNG.randn(4, 2).astype("float32")]
        np.testing.assert_allclose(
            paddle.linalg.multi_dot([T(m) for m in mats]).numpy(),
            mats[0] @ mats[1] @ mats[2], rtol=1e-4, atol=1e-4)

    def test_triangular_solve_cross_cov(self):
        up = np.triu(self.A) + 4 * np.eye(4, dtype=np.float32)
        y = RNG.randn(4, 2).astype("float32")
        x = paddle.linalg.triangular_solve(T(up), T(y)).numpy()
        np.testing.assert_allclose(up @ x, y, atol=1e-3)
        a = RNG.randn(3).astype("float32")
        b = RNG.randn(3).astype("float32")
        np.testing.assert_allclose(
            paddle.cross(T(a), T(b)).numpy(), np.cross(a, b), rtol=1e-5)
        d = RNG.randn(3, 50).astype("float32")
        np.testing.assert_allclose(paddle.linalg.cov(T(d)).numpy(),
                                   np.cov(d), rtol=1e-3, atol=1e-4)


# ------------------------------------------------------------------ stat

class TestStat:
    x = RNG.randn(5, 7).astype("float32")

    def test_std_var(self):
        np.testing.assert_allclose(paddle.std(T(self.x)).numpy(),
                                   self.x.std(ddof=1), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.var(T(self.x), axis=1, unbiased=False).numpy(),
            self.x.var(1), rtol=1e-5)

    def test_median_quantile(self):
        np.testing.assert_allclose(paddle.median(T(self.x)).numpy(),
                                   np.median(self.x), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.median(T(self.x), axis=1).numpy(),
            np.median(self.x, axis=1), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.quantile(T(self.x), 0.3, axis=0).numpy(),
            np.quantile(self.x, 0.3, axis=0), rtol=1e-5)

    def test_nan_variants(self):
        xn = self.x.copy()
        xn[0, 0] = np.nan
        np.testing.assert_allclose(paddle.nanmedian(T(xn)).numpy(),
                                   np.nanmedian(xn), rtol=1e-6)
        np.testing.assert_allclose(
            paddle.nanquantile(T(xn), 0.5).numpy(),
            np.nanquantile(xn, 0.5), rtol=1e-5)

    def test_histogram_bincount(self):
        v = (RNG.rand(100) * 10).astype("float32")
        got = paddle.histogram(T(v), bins=10, min=0, max=10).numpy()
        ref, _ = np.histogram(v, bins=10, range=(0, 10))
        np.testing.assert_array_equal(got, ref)
        iv = RNG.randint(0, 6, 50)
        np.testing.assert_array_equal(
            paddle.bincount(T(iv.astype("int64"))).numpy(),
            np.bincount(iv))


# --------------------------------------------------------------- search

class TestSearch:
    def test_sort_argsort_topk(self):
        x = RNG.randn(4, 6).astype("float32")
        np.testing.assert_allclose(
            paddle.sort(T(x), axis=1, descending=True).numpy(),
            -np.sort(-x, axis=1), rtol=1e-6)
        np.testing.assert_array_equal(
            paddle.argsort(T(x), axis=0).numpy(), np.argsort(x, axis=0))
        vals, idx = paddle.topk(T(x), k=3, axis=1)
        ref = -np.sort(-x, axis=1)[:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_searchsorted_kthvalue_mode(self):
        edges = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
        q = np.array([0.5, 3.0, 8.0], np.float32)
        np.testing.assert_array_equal(
            paddle.searchsorted(T(edges), T(q)).numpy(),
            np.searchsorted(edges, q))
        x = RNG.randn(3, 8).astype("float32")
        v, i = paddle.kthvalue(T(x), k=2, axis=1)
        np.testing.assert_allclose(v.numpy(), np.sort(x, 1)[:, 1],
                                   rtol=1e-6)
        m = np.array([[1, 2, 2, 3], [4, 4, 5, 4]], np.int64)
        mv, _ = paddle.mode(T(m), axis=1)
        np.testing.assert_array_equal(mv.numpy(), [2, 4])

    def test_masked_select_index_sample(self):
        x = RNG.randn(3, 4).astype("float32")
        mask = x > 0
        np.testing.assert_allclose(
            paddle.masked_select(T(x), T(mask)).numpy(), x[mask],
            rtol=1e-6)
        idx = np.array([[0, 2], [1, 3], [0, 0]], np.int64)
        got = paddle.index_sample(T(x), T(idx)).numpy()
        np.testing.assert_allclose(
            got, np.take_along_axis(x, idx, axis=1), rtol=1e-6)


# ---------------------------------------------------------- manipulation

class TestManipulation:
    def test_roll_rot90_flip(self):
        x = RNG.randn(3, 4).astype("float32")
        np.testing.assert_allclose(
            paddle.roll(T(x), shifts=2, axis=1).numpy(),
            np.roll(x, 2, axis=1), rtol=1e-6)
        np.testing.assert_allclose(paddle.rot90(T(x)).numpy(),
                                   np.rot90(x), rtol=1e-6)
        np.testing.assert_allclose(paddle.flip(T(x), axis=[0]).numpy(),
                                   np.flip(x, 0), rtol=1e-6)

    def test_take_along_put_along(self):
        x = RNG.randn(3, 5).astype("float32")
        idx = RNG.randint(0, 5, (3, 2)).astype("int64")
        np.testing.assert_allclose(
            paddle.take_along_axis(T(x), T(idx), axis=1).numpy(),
            np.take_along_axis(x, idx, 1), rtol=1e-6)
        vals = np.full((3, 2), 9.0, np.float32)
        ref = x.copy()
        np.put_along_axis(ref, idx, vals, axis=1)
        got = paddle.put_along_axis(T(x), T(idx), T(vals), axis=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        # reduce="add" accumulates; duplicate indices accumulate too
        idx2 = np.array([[1, 1], [0, 2], [4, 4]], np.int64)
        ref2 = x.copy()
        for r in range(3):
            for c in range(2):
                ref2[r, idx2[r, c]] += 9.0
        got2 = paddle.put_along_axis(T(x), T(idx2), T(vals), axis=1,
                                     reduce="add").numpy()
        np.testing.assert_allclose(got2, ref2, rtol=1e-6)
        # broadcastable size-1 non-axis dim (np.put_along_axis semantics)
        idx3 = np.array([[0, 3]], np.int64)
        ref3 = x.copy()
        np.put_along_axis(ref3, idx3, np.float32(7.0), axis=1)
        got3 = paddle.put_along_axis(T(x), T(idx3), T(np.full((1, 2), 7.0,
                                     np.float32)), axis=1).numpy()
        np.testing.assert_allclose(got3, ref3, rtol=1e-6)

    def test_repeat_interleave_tile_unique(self):
        x = np.array([[1, 2], [3, 4]], np.float32)
        np.testing.assert_allclose(
            paddle.repeat_interleave(T(x), 2, axis=0).numpy(),
            np.repeat(x, 2, axis=0), rtol=1e-6)
        np.testing.assert_allclose(paddle.tile(T(x), [2, 3]).numpy(),
                                   np.tile(x, (2, 3)), rtol=1e-6)
        v = np.array([3, 1, 2, 1, 3], np.int64)
        np.testing.assert_array_equal(paddle.unique(T(v)).numpy(),
                                      np.unique(v))

    def test_chunk_unbind_stack_splits(self):
        x = RNG.randn(6, 4).astype("float32")
        parts = paddle.chunk(T(x), 3, axis=0)
        assert len(parts) == 3
        np.testing.assert_allclose(parts[1].numpy(), x[2:4], rtol=1e-6)
        cols = paddle.unbind(T(x), axis=1)
        assert len(cols) == 4
        np.testing.assert_allclose(cols[2].numpy(), x[:, 2], rtol=1e-6)
        np.testing.assert_allclose(
            paddle.concat([T(x[:2]), T(x[2:])], axis=0).numpy(), x,
            rtol=1e-6)

    def test_gather_scatter_nd(self):
        x = RNG.randn(5, 3).astype("float32")
        idx = np.array([[1], [3]], np.int64)
        np.testing.assert_allclose(paddle.gather_nd(T(x), T(idx)).numpy(),
                                   x[[1, 3]], rtol=1e-6)
        upd = np.ones((2, 3), np.float32)
        got = paddle.scatter_nd_add(T(x), T(idx), T(upd)).numpy()
        ref = x.copy()
        ref[[1, 3]] += 1.0
        np.testing.assert_allclose(got, ref, rtol=1e-6)


# --------------------------------------------------------------- random

class TestRandom:
    def test_distribution_shapes_and_ranges(self):
        paddle.seed(3)
        u = paddle.uniform([200], min=-2.0, max=3.0).numpy()
        assert u.min() >= -2.0 and u.max() <= 3.0
        r = paddle.randint(0, 7, [300]).numpy()
        # int32 under jax's default no-x64 config (paddle spells int64)
        assert r.min() >= 0 and r.max() < 7
        assert np.issubdtype(r.dtype, np.integer)
        n = paddle.normal(mean=1.0, std=2.0, shape=[2000]).numpy()
        assert abs(n.mean() - 1.0) < 0.2 and abs(n.std() - 2.0) < 0.2
        p = paddle.randperm(50).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(50))

    def test_multinomial_bernoulli_poisson(self):
        paddle.seed(4)
        probs = paddle.to_tensor(np.array([0.0, 0.7, 0.3], np.float32))
        draws = paddle.multinomial(probs, 200, replacement=True).numpy()
        assert 0 not in draws
        b = paddle.bernoulli(paddle.to_tensor(
            np.full((1000,), 0.25, np.float32))).numpy()
        assert abs(b.mean() - 0.25) < 0.08
        lam = paddle.to_tensor(np.full((2000,), 3.0, np.float32))
        pois = paddle.poisson(lam).numpy()
        assert abs(pois.mean() - 3.0) < 0.3

    def test_seed_reproducibility(self):
        paddle.seed(11)
        a = paddle.randn([16]).numpy()
        paddle.seed(11)
        b = paddle.randn([16]).numpy()
        np.testing.assert_array_equal(a, b)
