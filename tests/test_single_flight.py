"""Single-flight tunnel lock (tools/_single_flight.py) — including the
wedge drill VERDICT r4 item 6 asked for.

The hazard being guarded: two processes touching the one-chip axon
tunnel at once (or a watchdog killing a holder mid-remote-compile)
wedges the backend for hours. The lock serializes tunnel access; these
tests prove the three properties that make it safe to rely on:

  1. mutual exclusion — a second acquirer waits, never proceeds;
  2. a LIVE holder is never broken, no matter how long it holds
     (long compiles are legitimate);
  3. the drill: a SIGKILLed holder (the round-4 failure shape) is
     reclaimed automatically by the next acquirer — zero human action,
     no queued measurement lost.

All tests run against a tmpdir lock (PADDLE_TPU_LOCK_DIR); nothing here
touches jax or the tunnel.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
sys.path.insert(0, TOOLS)

from _single_flight import (BusyTimeout, SingleFlight,  # noqa: E402
                            holder_alive, read_owner)


@pytest.fixture()
def lockdir(tmp_path, monkeypatch):
    d = str(tmp_path / "inflight")
    monkeypatch.setenv("PADDLE_TPU_LOCK_DIR", d)
    return d


def test_acquire_release_roundtrip(lockdir):
    assert not holder_alive()
    with SingleFlight("t1") as lk:
        assert holder_alive()
        o = read_owner()
        assert o["tool"] == "t1" and o["pid"] == os.getpid()
        lk.stage("compile")
        assert read_owner()["stage"] == "compile"
    assert not holder_alive()
    assert read_owner() is None  # advisory record cleaned on release


def test_live_holder_is_never_broken(lockdir):
    with SingleFlight("holder"):
        t0 = time.time()
        with pytest.raises(BusyTimeout) as ei:
            SingleFlight("intruder", wait=3).__enter__()
        assert time.time() - t0 >= 3      # actually waited, didn't barge
        assert "holder" in str(ei.value)  # names who held it
        assert read_owner()["tool"] == "holder"  # untouched


def test_second_acquirer_proceeds_after_release(lockdir):
    lk1 = SingleFlight("first").__enter__()
    lk2 = SingleFlight("second", wait=30)
    import threading
    acquired = []
    th = threading.Thread(
        target=lambda: (lk2.__enter__(), acquired.append(time.time())))
    th.start()
    time.sleep(3)
    assert not acquired            # still excluded
    lk1.__exit__(None, None, None)
    th.join(timeout=30)
    assert acquired                # took over promptly after release
    assert read_owner()["tool"] == "second"
    lk2.__exit__(None, None, None)


_HOLDER_SRC = """
import sys, time
sys.path.insert(0, %r)
from _single_flight import SingleFlight
lk = SingleFlight("drill-victim").__enter__()
lk.stage("compile")           # pretend a remote compile is in flight
print("HELD", flush=True)
time.sleep(120)               # would hold for 2 min if not killed
"""


def test_wedge_drill_sigkill_holder_is_reclaimed(lockdir):
    """The drill: deliberately kill a lock holder (SIGKILL — no cleanup
    handler runs, same shape as the round-4 watchdog kill) and show the
    next measurement recovers the lock automatically."""
    p = subprocess.Popen(
        [sys.executable, "-c", _HOLDER_SRC % TOOLS],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "PADDLE_TPU_LOCK_DIR": lockdir})
    assert p.stdout.readline().strip() == "HELD"
    assert holder_alive()
    assert read_owner()["stage"] == "compile"

    p.send_signal(signal.SIGKILL)          # the wedge event
    p.wait()
    assert not holder_alive()              # dead pid detected, no timer

    # the next queued measurement just... runs. Zero human action.
    t0 = time.time()
    with SingleFlight("next-measurement", wait=30):
        assert read_owner()["tool"] == "next-measurement"
    assert time.time() - t0 < 10           # reclaim was immediate


_CONTENDER_SRC = """
import os, sys, time
sys.path.insert(0, %r)
from _single_flight import SingleFlight
with SingleFlight(sys.argv[1], wait=60):
    with open(sys.argv[2], "a") as f:
        f.write("enter %%s %%.6f\\n" %% (sys.argv[1], time.time()))
    time.sleep(0.25)
    with open(sys.argv[2], "a") as f:
        f.write("exit %%s %%.6f\\n" %% (sys.argv[1], time.time()))
"""


def test_no_overlapping_holders_under_contention(lockdir, tmp_path):
    """Mutual exclusion under racing acquirers — including one starting
    right as another's dead lock is being recovered. Hold intervals
    recorded by each process must never overlap."""
    trace = str(tmp_path / "trace.txt")
    env = {**os.environ, "PADDLE_TPU_LOCK_DIR": lockdir}
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CONTENDER_SRC % TOOLS, "c%d" % i, trace],
        env=env) for i in range(5)]
    for p in procs:
        assert p.wait(timeout=60) == 0
    intervals = {}
    with open(trace) as f:
        for line in f:
            ev, tool, t = line.split()
            intervals.setdefault(tool, []).append(float(t))
    spans = sorted(tuple(v) for v in intervals.values())
    assert len(spans) == 5
    for (_, aexit), (benter, _) in zip(spans, spans[1:]):
        assert benter >= aexit  # next holder entered after prior left


def test_owner_record_is_json_debuggable(lockdir):
    """A postmortem must be able to cat the owner file: stable keys."""
    with SingleFlight("bench:gpt1.3b") as lk:
        lk.stage("measuring")
        with open(os.path.join(lockdir, "owner.json")) as f:
            o = json.load(f)
        assert set(o) == {"pid", "tool", "stage", "t"}
