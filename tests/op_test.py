"""OpTest harness.

Parity: the reference's OpTest pattern (python/paddle/fluid/tests/unittests/
op_test.py:325): data-driven per-op tests — check_output compares the real
kernel against a numpy reference; check_grad compares tape gradients against
jax numeric/autodiff gradients. Multi-backend sweep is XLA's job here; the
numeric-vs-analytic grad check is kept.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


class OpTest:
    """Subclass sets: self.op (callable on Tensors), self.inputs (dict of
    np arrays), self.attrs (kwargs), self.ref (numpy reference callable)."""

    attrs: dict = {}

    def check_output(self, rtol=1e-5, atol=1e-6):
        tensors = {k: paddle.to_tensor(v) for k, v in self.inputs.items()}
        out = self.op(**tensors, **self.attrs)
        ref = self.ref(**self.inputs, **self.attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        refs = ref if isinstance(ref, (list, tuple)) else [ref]
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(np.asarray(o.numpy(), dtype=np.float64),
                                       np.asarray(r, dtype=np.float64),
                                       rtol=rtol, atol=atol)

    def _weighted_loss(self, outs):
        """sum(w * out) with fixed pseudo-random w — avoids degenerate
        constant losses (e.g. sum of softmax) where finite-difference noise
        dominates. Mirrors OpTest user_defined_grad_outputs."""
        loss = None
        for j, o in enumerate(outs):
            v = o.numpy()
            if not np.issubdtype(v.dtype, np.floating):
                continue
            w = np.random.default_rng(1234 + j).standard_normal(
                v.shape).astype(np.float32)
            s = (o * paddle.to_tensor(w)).sum()
            loss = s if loss is None else loss + s
        return loss

    def check_grad(self, wrt=None, rtol=5e-3, atol=1e-3, eps=5e-3):
        """Finite-difference vs tape-backward gradient (reference
        op_test.py:2251 check_grad / :132 get_numeric_gradient pattern)."""
        wrt = wrt or [k for k, v in self.inputs.items()
                      if np.issubdtype(np.asarray(v).dtype, np.floating)]
        tensors = {k: paddle.to_tensor(np.asarray(v, dtype=np.float32),
                                       stop_gradient=k not in wrt)
                   for k, v in self.inputs.items()}
        out = self.op(**tensors, **self.attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = self._weighted_loss(outs)
        loss.backward()
        for k in wrt:
            analytic = tensors[k].grad.numpy()
            numeric = self._numeric_grad(k, eps)
            np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                       err_msg=f"grad mismatch for input {k!r}")

    def _numeric_grad(self, key, eps):
        base = {k: np.asarray(v, dtype=np.float32)
                for k, v in self.inputs.items()}
        x = base[key]
        g = np.zeros_like(x, dtype=np.float64)

        def f(arr):
            ins = dict(base)
            ins[key] = arr
            tensors = {k: paddle.to_tensor(v) for k, v in ins.items()}
            out = self.op(**tensors, **self.attrs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            tot = 0.0
            for j, o in enumerate(outs):
                v = o.numpy()
                if np.issubdtype(v.dtype, np.floating):
                    w = np.random.default_rng(1234 + j).standard_normal(
                        v.shape).astype(np.float32)
                    tot += float(np.sum(np.asarray(v, dtype=np.float64) * w))
            return tot

        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for i in range(flat.size):
            xp = x.copy().reshape(-1)
            xm = x.copy().reshape(-1)
            xp[i] += eps
            xm[i] -= eps
            gf[i] = (f(xp.reshape(x.shape)) - f(xm.reshape(x.shape))) / (2 * eps)
        return g.reshape(x.shape)
