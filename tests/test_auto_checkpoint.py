"""Auto-checkpoint tests (reference: auto_checkpoint.py TrainEpochRange —
kill mid-training, relaunch, resume from last completed epoch)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.checkpoint import TrainEpochRange


def _setup(seed=0):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    # a real relaunch restarts the auto-name counter; in-process we pin
    # names so optimizer-slot restore matches across "runs"
    m.weight.name = "linear.w"
    m.bias.name = "linear.b"
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=m.parameters())
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
    y = paddle.to_tensor(rng.randn(8, 2).astype("float32"))
    return m, opt, x, y


def _one_epoch(m, opt, x, y):
    loss = F.mse_loss(m(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_resume_skips_completed_epochs(tmp_path):
    ck = str(tmp_path)
    # first run: "crashes" after 3 of 6 epochs
    m, opt, x, y = _setup(1)
    r = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m, optimizer=opt)
    seen = []
    w_after_epoch1 = None
    for epoch in r:
        _one_epoch(m, opt, x, y)
        seen.append(epoch)
        if epoch == 1:
            w_after_epoch1 = m.weight.numpy().copy()
        if epoch == 2:
            break   # simulated kill: epoch 2's snapshot never commits
    assert seen == [0, 1, 2]

    # relaunch: fresh objects, same dir/name
    m2, opt2, x2, y2 = _setup(1)
    r2 = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m2, optimizer=opt2)
    resumed = []
    for epoch in r2:
        if not resumed:
            # restored state = last COMMITTED snapshot (end of epoch 1);
            # epoch 2's work is lost, exactly crash semantics
            np.testing.assert_allclose(m2.weight.numpy(), w_after_epoch1,
                                       rtol=1e-6)
            # optimizer velocity restored too
            vel = opt2._accumulators["velocity"]
            assert any(float(np.abs(np.asarray(v)).sum()) > 0
                       for v in vel.values())
        _one_epoch(m2, opt2, x2, y2)
        resumed.append(epoch)
    assert resumed == [2, 3, 4, 5]

    # a third run finds everything done
    m3, opt3, _, _ = _setup(1)
    r3 = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m3, optimizer=opt3)
    assert list(r3) == []


def test_disabled_without_dir():
    m, opt, x, y = _setup(2)
    r = TrainEpochRange(3).attach(model=m)
    assert list(r) == [0, 1, 2]
    assert list(TrainEpochRange(3)) == [0, 1, 2]   # stateless re-iteration


def test_crash_mid_epoch_roundtrips_optimizer_and_rng(tmp_path):
    """Satellite: kill the training loop mid-epoch via FaultInjector,
    relaunch, and assert the epoch counter, optimizer state, AND the
    global RNG key round-trip through the committed snapshot."""
    import jax
    import pytest

    from paddle_tpu.distributed import resilience as resil
    from paddle_tpu.distributed.resilience import (FaultInjected,
                                                   FaultInjector, RngState)
    from paddle_tpu.framework.random import get_rng_state, next_key

    ck = str(tmp_path)
    m, opt, x, y = _setup(5)
    r = TrainEpochRange(4, checkpoint_dir=ck, name="jobF").attach(
        model=m, optimizer=opt, rng=RngState())
    key_after_epoch1 = None
    vel_after_epoch1 = None
    with FaultInjector({"train_crash": 1}):
        with pytest.raises(FaultInjected):
            for epoch in r:
                _one_epoch(m, opt, x, y)
                next_key()   # the epoch consumed randomness
                if epoch == 1:
                    key_after_epoch1 = np.asarray(
                        jax.random.key_data(get_rng_state()))
                    vel_after_epoch1 = {
                        k: np.asarray(v).copy() for k, v in
                        opt._accumulators["velocity"].items()}
                if epoch == 2:
                    # mid-epoch kill: epoch 2's snapshot never commits
                    resil.maybe_inject("train_crash")

    # relaunch: fresh objects, same dir/name — resumes AT epoch 2
    m2, opt2, x2, y2 = _setup(5)
    next_key()   # perturb the fresh process's RNG; restore must win
    r2 = TrainEpochRange(4, checkpoint_dir=ck, name="jobF").attach(
        model=m2, optimizer=opt2, rng=RngState())
    it = iter(r2)
    assert next(it) == 2
    # RNG key restored to the end-of-epoch-1 commit, bitwise
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(get_rng_state())),
        key_after_epoch1)
    # optimizer velocity restored bitwise (same accumulator names —
    # parameter names are pinned in _setup)
    vel2 = opt2._accumulators["velocity"]
    assert set(vel2) == set(vel_after_epoch1)
    for k in vel2:
        np.testing.assert_array_equal(np.asarray(vel2[k]),
                                      vel_after_epoch1[k])


def test_save_interval(tmp_path):
    ck = str(tmp_path)
    m, opt, x, y = _setup(3)
    r = TrainEpochRange(5, checkpoint_dir=ck, name="j2",
                        save_checkpoint_inter=2).attach(model=m)
    for epoch in r:
        _one_epoch(m, opt, x, y)
        if epoch == 2:
            break
    # epochs 0..2 ran; snapshots at epoch 1 (2 % 2 == 0) only -> resume at 2
    m2, opt2, _, _ = _setup(3)
    r2 = TrainEpochRange(5, checkpoint_dir=ck, name="j2",
                         save_checkpoint_inter=2).attach(model=m2)
    assert next(iter(r2)) == 2
