"""Auto-checkpoint tests (reference: auto_checkpoint.py TrainEpochRange —
kill mid-training, relaunch, resume from last completed epoch)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.checkpoint import TrainEpochRange


def _setup(seed=0):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    # a real relaunch restarts the auto-name counter; in-process we pin
    # names so optimizer-slot restore matches across "runs"
    m.weight.name = "linear.w"
    m.bias.name = "linear.b"
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=m.parameters())
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
    y = paddle.to_tensor(rng.randn(8, 2).astype("float32"))
    return m, opt, x, y


def _one_epoch(m, opt, x, y):
    loss = F.mse_loss(m(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_resume_skips_completed_epochs(tmp_path):
    ck = str(tmp_path)
    # first run: "crashes" after 3 of 6 epochs
    m, opt, x, y = _setup(1)
    r = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m, optimizer=opt)
    seen = []
    w_after_epoch1 = None
    for epoch in r:
        _one_epoch(m, opt, x, y)
        seen.append(epoch)
        if epoch == 1:
            w_after_epoch1 = m.weight.numpy().copy()
        if epoch == 2:
            break   # simulated kill: epoch 2's snapshot never commits
    assert seen == [0, 1, 2]

    # relaunch: fresh objects, same dir/name
    m2, opt2, x2, y2 = _setup(1)
    r2 = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m2, optimizer=opt2)
    resumed = []
    for epoch in r2:
        if not resumed:
            # restored state = last COMMITTED snapshot (end of epoch 1);
            # epoch 2's work is lost, exactly crash semantics
            np.testing.assert_allclose(m2.weight.numpy(), w_after_epoch1,
                                       rtol=1e-6)
            # optimizer velocity restored too
            vel = opt2._accumulators["velocity"]
            assert any(float(np.abs(np.asarray(v)).sum()) > 0
                       for v in vel.values())
        _one_epoch(m2, opt2, x2, y2)
        resumed.append(epoch)
    assert resumed == [2, 3, 4, 5]

    # a third run finds everything done
    m3, opt3, _, _ = _setup(1)
    r3 = TrainEpochRange(6, checkpoint_dir=ck, name="job1").attach(
        model=m3, optimizer=opt3)
    assert list(r3) == []


def test_disabled_without_dir():
    m, opt, x, y = _setup(2)
    r = TrainEpochRange(3).attach(model=m)
    assert list(r) == [0, 1, 2]
    assert list(TrainEpochRange(3)) == [0, 1, 2]   # stateless re-iteration


def test_save_interval(tmp_path):
    ck = str(tmp_path)
    m, opt, x, y = _setup(3)
    r = TrainEpochRange(5, checkpoint_dir=ck, name="j2",
                        save_checkpoint_inter=2).attach(model=m)
    for epoch in r:
        _one_epoch(m, opt, x, y)
        if epoch == 2:
            break
    # epochs 0..2 ran; snapshots at epoch 1 (2 % 2 == 0) only -> resume at 2
    m2, opt2, _, _ = _setup(3)
    r2 = TrainEpochRange(5, checkpoint_dir=ck, name="j2",
                         save_checkpoint_inter=2).attach(model=m2)
    assert next(iter(r2)) == 2
