"""jit module tests: functional_call, to_static, TrainStep, jit.save/load.

Mirrors the reference's dy2static test style (test/legacy_test
test_jit_save_load.py etc.): train/eval parity between eager and compiled
paths, save->load->same outputs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import TrainStep, functional_call, raw_state, to_static


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


class BNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 8)
        self.bn = nn.BatchNorm1D(8)

    def forward(self, x):
        return self.bn(self.fc(x))


def test_functional_call_matches_eager():
    m = MLP()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    eager = m(x).numpy()
    params, buffers = raw_state(m)
    out, new_bufs = functional_call(m, params, buffers, x)
    np.testing.assert_allclose(np.asarray(out), eager, rtol=1e-6)


def test_functional_call_returned_parameter_is_traced():
    """A forward that RETURNS a Parameter (e.g. a tied LM weight handed
    to a fused loss) must yield the swapped-in value, not the stale
    concrete array — regression for the unwrap-after-restore bug that
    silently froze such leaves in compiled programs (grads through the
    returned leaf were zero)."""
    import jax

    class ReturnsWeight(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return self.fc(x), self.fc.weight

    m = ReturnsWeight()
    params, buffers = raw_state(m)
    x = np.random.randn(2, 4).astype("float32")

    def loss(p):
        (out, w), _ = functional_call(m, p, buffers, paddle.to_tensor(x))
        return jax.numpy.sum(out * 0.0) + jax.numpy.sum(w ** 2)

    g = jax.grad(loss)(params)["fc.weight"]
    expect = 2 * params["fc.weight"]
    np.testing.assert_allclose(np.asarray(g), np.asarray(expect),
                               rtol=1e-6)


def test_to_static_forward_and_backward():
    m = MLP()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    eager = m(x).numpy()
    ms = to_static(m)
    out = ms(x)
    np.testing.assert_allclose(out.numpy(), eager, rtol=1e-5, atol=1e-6)
    # backward through the compiled program reaches leaf params
    loss = paddle.mean(out)
    loss.backward()
    for p in m.parameters():
        assert p.grad is not None, p.name
    # compile cache: second call with same shape reuses the entry
    ms(x)
    assert len(m._static_function._jit_cache) == 1
    # new shape -> same entry list (jax.jit recompiles internally)
    ms(paddle.to_tensor(np.random.randn(6, 8).astype("float32")))


def test_to_static_function():
    @to_static
    def f(a, b):
        return paddle.matmul(a, b) + 1.0

    a = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
    b = paddle.to_tensor(np.random.randn(4, 5).astype("float32"))
    ref = (np.asarray(a.numpy()) @ np.asarray(b.numpy())) + 1.0
    np.testing.assert_allclose(f(a, b).numpy(), ref, rtol=1e-5, atol=1e-5)


def test_to_static_batchnorm_updates_buffers():
    m = BNNet()
    ms = to_static(m)
    x = paddle.to_tensor(np.random.randn(16, 8).astype("float32") * 3 + 1)
    before = m.bn._mean.numpy().copy()
    ms(x)
    after = m.bn._mean.numpy()
    assert not np.allclose(before, after)


def test_train_step_converges():
    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 4).astype("float32")
    x_np = rng.randn(64, 8).astype("float32")
    y_np = x_np @ w_true

    m = MLP()
    opt = paddle.optimizer.Adam(learning_rate=0.03, parameters=m.parameters())
    step = TrainStep(m, lambda out, y: F.mse_loss(out, y), opt)
    x, y = paddle.to_tensor(x_np), paddle.to_tensor(y_np)
    losses = [float(step(x, y)) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.2, losses[::10]
    # sync back and check eager forward agrees with trained state
    step.sync_to_model()
    out = m(x)
    eager_loss = float(F.mse_loss(out, y))
    np.testing.assert_allclose(eager_loss, losses[-1], rtol=0.3)


def test_jit_save_load(tmp_path):
    m = MLP()
    m.eval()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([4, 8])])
    loaded = paddle.jit.load(path)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_to_static_grad_flows_to_inputs():
    # gradients must flow through a compiled sublayer into upstream tensors
    up = nn.Linear(8, 8)
    sub = to_static(MLP())
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    h = up(x)
    out = sub(h)
    paddle.mean(out).backward()
    assert up.weight.grad is not None
    for p in sub.parameters():
        assert p.grad is not None


def test_to_static_method_decorator_sees_param_updates():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        @to_static
        def forward(self, x):
            return self.fc(x)

    m = Net()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    out1 = m(x).numpy()
    with paddle.no_grad():
        m.fc.weight.value = m.fc.weight.value + 1.0
    out2 = m(x).numpy()
    # params are traced arguments, not baked constants
    assert not np.allclose(out1, out2)


def test_jit_save_dynamic_batch(tmp_path):
    m = MLP()
    m.eval()
    path = str(tmp_path / "dyn")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([None, 8])])
    loaded = paddle.jit.load(path)
    for bs in (1, 4, 9):
        x = paddle.to_tensor(np.random.randn(bs, 8).astype("float32"))
        ref = m(x).numpy()
        np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5,
                                   atol=1e-6)


def test_train_step_keeps_model_usable():
    m = MLP()
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    step = TrainStep(m, lambda out, y: F.mse_loss(out, y), opt)
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
    step(x, y)
    m(x).numpy()           # model arrays not donated away
    step.sync_to_model()
    step(x, y)             # donation after sync must not kill model state
    m(x).numpy()


def test_to_static_function_single_tuple_output():
    @to_static
    def f(x):
        return (x * 2,)

    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    out = f(x)
    assert isinstance(out, tuple) and len(out) == 1
    np.testing.assert_allclose(out[0].numpy(), 2 * np.ones((2, 2)))


def test_gpt_recompute_matches_plain():
    """cfg.recompute=True (per-block jax.checkpoint, fleet recompute
    parity) must change memory, not math: identical loss trajectory."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    ids = np.random.RandomState(3).randint(0, 64, (2, 16)).astype("int64")
    losses = []
    for rc in (False, True):
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16, recompute=rc)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        step = TrainStep(model, GPTForCausalLM.loss_fn, opt)
        t = paddle.to_tensor(ids)
        losses.append([float(step(t, t)) for _ in range(3)])
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


def test_jit_save_is_platform_portable(tmp_path):
    """An artifact saved on the CPU host must serve on the TPU fleet:
    jit.save lowers for both platforms (reference's __model__ is
    backend-portable the same way)."""
    import jax
    m = MLP()
    path = str(tmp_path / "portable")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([2, 8])])
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    assert set(exported.platforms) == {"cpu", "tpu"}


def test_jit_save_plain_and_decorated_function(tmp_path):
    """jit.save accepts plain functions and @to_static functions, like
    the reference (python/paddle/jit/api.py save of StaticFunction)."""
    from paddle_tpu.static import InputSpec

    def f(x, y):
        return paddle.tanh(x) + y * 2

    prefix = str(tmp_path / "fn")
    paddle.jit.save(f, prefix, input_spec=[InputSpec([2, 3], "float32"),
                                           InputSpec([2, 3], "float32")])
    loaded = paddle.jit.load(prefix)
    a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    b = np.random.RandomState(1).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(
        loaded(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        np.tanh(a) + b * 2, rtol=1e-6)

    @to_static
    def g(x):
        return x * x + 1

    prefix2 = str(tmp_path / "fn2")
    paddle.jit.save(g, prefix2, input_spec=[InputSpec([4], "float32")])
    out = paddle.jit.load(prefix2)(
        paddle.to_tensor(np.ones(4, np.float32)))
    np.testing.assert_allclose(out.numpy(), 2.0)
