import sys, time
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM

batch = int(sys.argv[1]); seq = 1024; iters = 12
cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                num_heads=12, max_seq_len=seq)
paddle.seed(0)
model = GPTForCausalLM(cfg); model.bfloat16()
opt = paddle.optimizer.AdamW(learning_rate=1e-4, multi_precision=True,
                             parameters=model.parameters())
step = TrainStep(model, GPTForCausalLM.loss_fn, opt)
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype("int64"))
for _ in range(3): loss = step(ids, ids)
l0 = float(loss)
t0 = time.perf_counter()
for _ in range(iters): loss = step(ids, ids)
float(loss)
dt = time.perf_counter() - t0
print(f"RESULT batch={batch}: {batch*seq*iters/dt:,.0f} tok/s ({dt/iters*1e3:.1f} ms/step) loss@3={l0:.3f}")
