"""THE efficiency formula: model FLOPs / modeled bytes over measured
wall time, as a fraction of one chip's peak.

Before this module every surface that wanted an efficiency number
derived its own — ``tools/northstar_model.py`` analytically,
``bench.py`` with its own FLOPs-per-token accounting, and the live
loops not at all. This is the ONE implementation the live gauges and
the bench records share (ISSUE 14's "no third formula" rule):

* training: ``mfu(train_step_flops(params, tokens), seconds)`` — the
  standard nominal-MFU accounting (6 * params * tokens; remat recompute
  excluded, attention's O(L*H*S) term excluded when layer geometry is
  unknown — the same convention northstar_model.py documents). hapi's
  fit loop exports it per dispatch as the ``ptpu_train_mfu`` gauge
  (plus ``ptpu_train_step_seconds``), and tools/bench_train_loop.py
  puts the identical arithmetic in its JSON record.
* serving: the decode tick is bandwidth-bound (tpucost's anchor), so
  its efficiency is modeled HBM bytes moved per measured second as a
  fraction of the chip's bandwidth — ``model_bandwidth_eff(
  modeled_tick_bytes(kind, geometry), seconds)``. The engine exports
  it per tick as ``ptpu_engine_tick_model_eff`` (surfaced in
  ``stats()`` / ``/healthz``), and tools/bench_serving.py reports the
  same gauge's value.

Numbers are chip-RELATIVE: the default chip is analysis/chips.py's
``DEFAULT_CHIP`` (v5lite — the measured 33.6%-MFU anchor's chip),
overridable via ``PADDLE_TPU_EFF_CHIP``. On a CPU backend the gauges
still move (the arithmetic is honest) but read as tiny fractions of a
TPU's peak — they become meaningful when the TPU suite runs.

Module import is stdlib-only (the obs package contract);
analysis/chips.py is itself dependency-free, and the pytree helpers
import jax lazily at call time (callers are jax-land by definition).
"""
from __future__ import annotations

import os

__all__ = [
    "MFU_GAUGE", "STEP_SECONDS_GAUGE", "TICK_EFF_GAUGE",
    "chip_spec", "train_step_flops", "mfu", "model_bandwidth_eff",
    "modeled_tick_bytes", "tree_nbytes", "tree_nelems",
]

# the gauge names, importable so benches/docs/northstar cross-reference
# the exact exported series instead of retyping strings
MFU_GAUGE = "ptpu_train_mfu"
STEP_SECONDS_GAUGE = "ptpu_train_step_seconds"
TICK_EFF_GAUGE = "ptpu_engine_tick_model_eff"


def chip_spec(chip=None):
    """Resolve a chip for the efficiency denominator: a ChipSpec passes
    through untouched (the per-tick hot path — the engine resolves once
    at init and hands the spec back in), a name looks up
    analysis/chips.py's table, None reads ``PADDLE_TPU_EFF_CHIP``
    (default: the table's DEFAULT_CHIP)."""
    if chip is not None and not isinstance(chip, str):
        return chip
    from ..analysis.chips import CHIP_SPECS, DEFAULT_CHIP
    if chip is None:
        chip = os.environ.get("PADDLE_TPU_EFF_CHIP") or DEFAULT_CHIP
    return CHIP_SPECS[chip]


def train_step_flops(param_count: int, tokens: int) -> float:
    """Nominal model FLOPs for training ``tokens`` tokens: the standard
    6 * N * T (fwd 2NT + bwd 4NT) MFU accounting. Remat recompute is
    deliberately EXCLUDED (standard MFU counts useful math, not
    re-execution) and so is the attention O(L*H*S^2) term — callers
    that know their layer geometry (bench.py's 125M/1.3B configs) add
    it themselves; the live gauge stays the comparable lower bound."""
    return 6.0 * float(param_count) * float(tokens)


def mfu(model_flops: float, seconds: float, chip=None) -> float:
    """Model-FLOPs-utilization: useful FLOPs over what the chip could
    have done in the measured wall time."""
    if seconds <= 0:
        return 0.0
    return float(model_flops) / (float(seconds)
                                 * chip_spec(chip).peak_flops)


def model_bandwidth_eff(modeled_bytes: float, seconds: float,
                        chip=None) -> float:
    """Modeled HBM bytes moved per measured second, as a fraction of
    the chip's bandwidth — the efficiency notion for bandwidth-bound
    programs (the decode tick)."""
    if seconds <= 0:
        return 0.0
    return float(modeled_bytes) / (float(seconds)
                                   * chip_spec(chip).hbm_bandwidth)


def modeled_tick_bytes(kind: str, geometry: dict) -> int:
    """Analytic HBM bytes for one engine dispatch, by program kind —
    delegating to the ONE set of formulas in analysis/hlo_cost.py (the
    same bounds the tpucost anchors price):

      "decode"        dense slot tick   (tick_tokens, param, kv bytes)
      "decode_paged"  paged tick        (+ kv_view_bytes)
      "verify"        speculative verify-k dispatch (single pass)
    """
    from ..analysis import hlo_cost
    fn = {"decode": hlo_cost.analytic_decode_hbm_bytes,
          "decode_paged": hlo_cost.analytic_paged_decode_hbm_bytes,
          "verify": hlo_cost.analytic_verify_hbm_bytes}.get(kind)
    if fn is None:
        raise ValueError(f"unknown tick kind {kind!r} "
                         "(valid: decode, decode_paged, verify)")
    return fn(geometry)


def tree_nbytes(tree) -> int:
    """Total leaf bytes of a pytree (params/caches) — the geometry
    input every analytic bound consumes. Lazy jax import: callers
    (engine init, registry builders, benches) are jax-land already."""
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        n = 1
        for d in shape:
            n *= int(d)
        dt = getattr(leaf, "dtype", None)
        total += n * (np.dtype(dt).itemsize if dt is not None else 4)
    return total


def tree_nelems(tree) -> int:
    """Total leaf element count of a pytree (the parameter count the
    train MFU formula takes)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = 1
        for d in tuple(getattr(leaf, "shape", ()) or ()):
            n *= int(d)
        total += n
    return total
