"""Span tracer + ring-buffer flight recorder (the obs timeline half).

Every instrumented phase — a request's queue-wait/prefill/decode in the
engine, a router forward attempt, a training window's
prefetch-wait/dispatch/fetch, a profiler RecordEvent scope — lands as
ONE event format: a Chrome-trace complete event (``ph: "X"``, ts/dur in
microseconds on the ``time.perf_counter`` clock) carrying its
``request_id`` and category in ``args``. They all buffer in one
fixed-size ring (`FlightRecorder`) — always on, bounded memory, no
per-event I/O — so the answer to "what was this process doing in the
5 seconds before it died?" is a dump away:

* `export_chrome` is the ONE Chrome/Perfetto-JSON exporter (the legacy
  ``paddle_tpu.profiler`` export and ``tools/trace_tool.py`` both call
  it);
* `dump_flight` writes the ring + still-open spans to a timestamped
  artifact — wired into ``StepWatchdog`` hang/NaN-storm and the
  router's replica-death path, and exposed as ``POST /admin/trace`` on
  live servers (`capture`).

Layering: the primitives here (``record_span``/``begin``/``end``)
ALWAYS record — an explicit call is its own opt-in (profiler
RecordEvent must work with ambient telemetry off). The ``span()``
helper is the gated face for ambient instrumentation: with
``PADDLE_TPU_OBS=0`` it returns one shared no-op singleton — zero
allocations on the disabled hot path (counter-asserted in
tests/test_obs.py). Heavier sites (the engine tick) gate themselves
once at init instead of per call.

Env knobs (COMPONENTS.md "Observability"):
  PADDLE_TPU_OBS        ambient instrumentation on/off (default on)
  PADDLE_TPU_OBS_RING   ring capacity in events (default 4096)
  PADDLE_TPU_OBS_DIR    artifact/trace directory (default obs_artifacts)
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "recorder", "span", "record_span",
           "begin_span", "end_span", "export_chrome", "dump_flight",
           "capture", "artifact_dir"]

_PID = os.getpid()


def _enabled() -> bool:
    from . import enabled
    return enabled()


def artifact_dir() -> str:
    """Where flight-recorder dumps and trace captures land."""
    return os.environ.get("PADDLE_TPU_OBS_DIR") or "obs_artifacts"


class FlightRecorder:
    """Fixed-size ring of completed span events + the set of spans
    currently open. Appends are O(1) under one lock; the ring never
    grows (old events fall off the back) so it is safe to leave on in
    production forever."""

    def __init__(self, size: int):
        self._ring: deque = deque(maxlen=max(16, int(size)))
        self._lock = threading.Lock()
        self._open: Dict[int, dict] = {}
        self._tokens = itertools.count(1)
        self.appended = 0          # monotonic; tests assert deltas

    @property
    def size(self) -> int:
        # maxlen is immutable — no lock needed for this read
        return self._ring.maxlen  # tpurace: disable=race-unguarded-attr

    # -- writing ---------------------------------------------------------
    def record(self, name: str, t0_s: float, t1_s: float,
               cat: str = "app", tid: Optional[int] = None,
               args: Optional[dict] = None) -> None:
        """One complete span; ``t0_s``/``t1_s`` are
        ``time.perf_counter()`` readings."""
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": t0_s * 1e6, "dur": max(0.0, (t1_s - t0_s) * 1e6),
              "pid": _PID,
              "tid": tid if tid is not None else threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._ring.append(ev)
            self.appended += 1

    def begin(self, name: str, cat: str = "app",
              args: Optional[dict] = None) -> int:
        token = next(self._tokens)
        ev = {"name": name, "cat": cat, "t0": time.perf_counter(),
              "tid": threading.get_ident(),
              "args": dict(args) if args else None}
        with self._lock:
            self._open[token] = ev
        return token

    def end(self, token: int) -> None:
        with self._lock:
            ev = self._open.pop(token, None)
        if ev is not None:
            self.record(ev["name"], ev["t0"], time.perf_counter(),
                        cat=ev["cat"], tid=ev["tid"], args=ev["args"])

    # -- reading ---------------------------------------------------------
    def events(self, since_s: Optional[float] = None) -> List[dict]:
        """Completed events (oldest first); ``since_s`` filters to
        spans that STARTED at or after that perf_counter reading."""
        with self._lock:
            evs = list(self._ring)
        if since_s is not None:
            cutoff = since_s * 1e6
            evs = [e for e in evs if e["ts"] >= cutoff]
        return evs

    def open_events(self) -> List[dict]:
        """Spans in flight right now, synthesized as complete events
        with duration-so-far and ``args.open = true`` — what a crash
        dump needs most (the request that was mid-forward when the
        replica died)."""
        now = time.perf_counter()
        with self._lock:
            opens = list(self._open.values())
        out = []
        for ev in opens:
            args = dict(ev["args"] or {})
            args["open"] = True
            out.append({"name": ev["name"], "cat": ev["cat"], "ph": "X",
                        "ts": ev["t0"] * 1e6,
                        "dur": max(0.0, (now - ev["t0"]) * 1e6),
                        "pid": _PID, "tid": ev["tid"], "args": args})
        return out

    def request_ids(self, events: Optional[List[dict]] = None
                    ) -> List[str]:
        evs = self.open_events() if events is None else events
        return sorted({str(e["args"]["request_id"]) for e in evs
                       if e.get("args", {}).get("request_id")})

    def clear(self) -> None:                     # tests only
        with self._lock:
            self._ring.clear()
            self._open.clear()


def _unique_dir(parent: str, base: str) -> str:
    """Create and return a fresh directory ``parent/base`` — with a
    ``.N`` suffix when the name is taken. The jax-profile capture dir
    is stamped at SECOND granularity (time.strftime); two captures in
    the same second (a tier poking every replica, a test loop) must
    not interleave their xplane files in one directory."""
    os.makedirs(parent, exist_ok=True)
    path = os.path.join(parent, base)
    for i in range(10000):
        try:
            os.makedirs(path if i == 0 else f"{path}.{i}",
                        exist_ok=False)
            return path if i == 0 else f"{path}.{i}"
        except FileExistsError:
            continue
    raise OSError(f"could not create a unique capture dir under "
                  f"{parent!r} (base {base!r})")


def _ring_size() -> int:
    try:
        return int(os.environ.get("PADDLE_TPU_OBS_RING", 4096))
    except ValueError:
        return 4096


#: the ONE process-wide flight recorder
recorder = FlightRecorder(_ring_size())


# ---------------------------------------------------------------------------
# span API
# ---------------------------------------------------------------------------

class _Span:
    __slots__ = ("_name", "_cat", "_args", "_token")

    def __init__(self, name, cat, args):
        self._name = name
        self._cat = cat
        self._args = args or None
        self._token = None

    def __enter__(self):
        self._token = recorder.begin(self._name, self._cat, self._args)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            recorder.end(self._token)
            self._token = None
        return False


class _NoopSpan:
    """The disabled fast path: one shared instance, no state, no
    allocations per use."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, cat: str = "app", **args):
    """Context-manager span, gated on ``PADDLE_TPU_OBS``. Disabled ->
    the shared no-op singleton (identity-testable)."""
    if not _enabled():
        return _NOOP
    return _Span(name, cat, args)


def record_span(name: str, t0_s: float, t1_s: float, cat: str = "app",
                tid: Optional[int] = None, **args) -> None:
    """Record a completed span from explicit perf_counter timestamps.
    Ungated — callers that need the ambient on/off gate check
    ``obs.enabled()`` themselves (the engine does, once, at init)."""
    recorder.record(name, t0_s, t1_s, cat=cat, tid=tid,
                    args=args or None)


def begin_span(name: str, cat: str = "app", **args) -> int:
    return recorder.begin(name, cat, args or None)


def end_span(token: int) -> None:
    recorder.end(token)


# ---------------------------------------------------------------------------
# export / dump / capture
# ---------------------------------------------------------------------------

def export_chrome(path: str, since_s: Optional[float] = None,
                  metadata: Optional[dict] = None,
                  include_open: bool = False,
                  events: Optional[List[dict]] = None) -> str:
    """THE Chrome/Perfetto trace writer: ``{"traceEvents": [...]}``
    JSON, atomically published. ``events`` overrides the ring read
    (trace_tool re-exports fetched captures through the same path)."""
    if events is None:
        events = recorder.events(since_s)
        if include_open:
            events = events + recorder.open_events()
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": dict(metadata or {})}
    doc["metadata"].setdefault("clock", "perf_counter_us")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def dump_flight(reason: str, extra: Optional[dict] = None,
                dir_path: Optional[str] = None) -> str:
    """Crash/postmortem dump: ring + open spans to a timestamped
    artifact. Returns the path. Callers on failure paths wrap this in
    try/except — forensics must never mask the original error."""
    d = dir_path or artifact_dir()
    os.makedirs(d, exist_ok=True)
    stamp = time.strftime("%Y%m%d_%H%M%S") + f"_{int(time.time_ns() % 1_000_000):06d}"
    path = os.path.join(d, f"flight_{reason}_{stamp}.trace.json")
    opens = recorder.open_events()
    ring = recorder.events()
    meta = {"reason": reason, "pid": _PID,
            "dumped_at_unix": time.time(),
            "ring_events": len(ring), "open_spans": len(opens),
            "request_ids_in_flight": recorder.request_ids(opens),
            "request_ids_recent": recorder.request_ids(ring)}
    if extra:
        meta.update(extra)
    return export_chrome(path, metadata=meta, events=ring + opens)


def capture(duration_s: float = 0.0, jax_profile: bool = False) -> dict:
    """The ``POST /admin/trace?duration_s=`` body (serve + router):
    record for ``duration_s`` (0 -> snapshot the whole ring now) and
    return the Chrome-trace dict. ``jax_profile=True`` additionally
    runs a programmatic ``jax.profiler`` capture over the window into
    the artifact dir (xplane for TensorBoard/XProf); its directory
    rides in the metadata. jax failures degrade to the host-span-only
    capture — a trace endpoint must not 500 because the device
    profiler is busy."""
    meta: dict = {"duration_s": float(duration_s)}
    since = time.perf_counter() if duration_s and duration_s > 0 else None
    prof_dir = None
    if jax_profile:
        try:
            import jax
            prof_dir = _unique_dir(
                artifact_dir(),
                "jax_profile_" + time.strftime("%Y%m%d_%H%M%S"))
            jax.profiler.start_trace(prof_dir)
        except Exception as e:   # noqa: BLE001 — degrade, don't 500
            meta["jax_profile_error"] = f"{type(e).__name__}: {e}"
            prof_dir = None
    if since is not None:
        time.sleep(float(duration_s))
    if prof_dir is not None:
        try:
            import jax
            jax.profiler.stop_trace()
            meta["jax_profile_dir"] = prof_dir
        except Exception as e:   # noqa: BLE001
            meta["jax_profile_error"] = f"{type(e).__name__}: {e}"
    events = recorder.events(since) + recorder.open_events()
    meta["request_ids"] = recorder.request_ids(events)
    meta.setdefault("clock", "perf_counter_us")
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}
