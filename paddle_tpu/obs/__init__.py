"""Unified observability: metrics registry, span tracer, flight recorder.

The stack spans a fused training loop, a continuous-batching engine,
and a multi-replica router; before this package their telemetry was
fragmented one-off counters (framework/syncs.py, compilation/
counters.py, the engine's private ints, the router's stats dict) and
point-in-time ``/healthz`` snapshots. ``paddle_tpu.obs`` is the ONE
measurement layer they all feed:

* :mod:`.metrics` — process-wide registry of counters/gauges/
  histograms (bounded label sets, lock-guarded, ~zero-cost when
  untouched), exported as Prometheus-style text on ``/metrics``
  (PredictorServer and the router; the router additionally scrapes and
  aggregates replica metrics into ``ptpu_tier_*`` series).
* :mod:`.efficiency` — the ONE MFU / model-efficiency formula: model
  FLOPs (training) or modeled HBM bytes (the bandwidth-bound decode
  tick) over measured wall time, relative to one chip's peak —
  exported live as ``ptpu_train_mfu`` / ``ptpu_engine_tick_model_eff``
  and reused verbatim by the bench JSON records.
* :mod:`.trace` — request-scoped span tracer (request ids propagate
  router -> replica -> engine via the ``X-PTPU-Request-Id`` header)
  buffering into a fixed-size ring-buffer **flight recorder**, with
  Chrome/Perfetto JSON export (``tools/trace_tool.py``), a
  ``POST /admin/trace?duration_s=`` capture endpoint, and crash dumps
  wired into ``StepWatchdog`` and the router's replica-death path.

Env knobs (COMPONENTS.md "Observability" has the full table):
  PADDLE_TPU_OBS        ambient instrumentation on/off (default on)
  PADDLE_TPU_OBS_RING   flight-recorder capacity in events (4096)
  PADDLE_TPU_OBS_DIR    artifact/trace directory (obs_artifacts)
  PADDLE_TPU_LOCK_SAN   lock sanitizer on/off (default off) — the
                        :mod:`.locks` factories return instrumented
                        locks feeding ``ptpu_lock_{hold,wait}_ms``
                        and the deadlock watchdog

This package imports ONLY the stdlib (the analysis/chips.py rule):
crash-path consumers (distributed/resilience.py keeps its stdlib-only
module contract) and tools must be able to reach the recorder without
pulling jax — so the env parsing below mirrors framework/env.py
instead of importing it.
"""
from __future__ import annotations

import os

__all__ = ["enabled", "set_enabled", "metrics", "trace", "efficiency",
           "locks", "registry", "recorder", "span", "record_span",
           "dump_flight", "lock_san_enabled", "set_lock_san",
           "make_lock", "make_rlock", "make_condition"]

_enabled_override = None     # set_enabled() tri-state; None -> env
_enabled_env = None          # cached env read


def enabled() -> bool:
    """Is ambient instrumentation on? One env read
    (``PADDLE_TPU_OBS``, default on — mirrors framework/env.bool_env's
    truthiness rule), cached; ``set_enabled`` overrides for tests and
    the overhead bench."""
    global _enabled_env
    if _enabled_override is not None:
        return _enabled_override
    if _enabled_env is None:
        raw = os.environ.get("PADDLE_TPU_OBS")
        _enabled_env = (True if raw is None else
                        raw.strip().lower() not in ("0", "false", "off",
                                                    ""))
    return _enabled_env


def set_enabled(on) -> None:
    """Force instrumentation on/off (``None`` re-reads the env).
    Affects gated sites built AFTER the call (the engine snapshots the
    flag at construction)."""
    global _enabled_override, _enabled_env
    _enabled_override = None if on is None else bool(on)
    _enabled_env = None


from . import efficiency, locks, metrics, trace           # noqa: E402
from .locks import (lock_san_enabled, make_condition, make_lock,  # noqa: E402
                    make_rlock, set_lock_san)
from .metrics import registry                             # noqa: E402
from .trace import dump_flight, record_span, recorder, span  # noqa: E402
